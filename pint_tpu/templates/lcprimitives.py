"""Light-curve primitive components: wrapped Gaussian and von Mises
peaks on the phase circle.

(reference: src/pint/templates/lcprimitives.py — LCGaussian,
LCVonMises, LCPrimitive base with loc/width params, get_location.)

Each primitive is a normalized density on [0,1); parameters are
stored as a small array [width_param, location] so templates vmap and
differentiate (the reference stores .p arrays the same way —
width-like first, location last).
"""

from __future__ import annotations

import math

import numpy as np


class LCPrimitive:
    """Base: density f(phi) normalized over the unit circle."""

    n_params = 2
    energy_dependent = False

    def __init__(self, p):
        self.p = np.asarray(p, float)

    @property
    def loc(self):
        return self.p[-1]

    def __call__(self, phases, p=None):
        raise NotImplementedError

    def integrate(self, lo=0.0, hi=1.0):
        """Fraction of the density in [lo, hi); default 1."""
        import jax.numpy as jnp

        # 1024-point trapezoid on device; exact enough for norms
        x = jnp.linspace(lo, hi, 1025)
        y = self(x)
        return jnp.trapezoid(y, x)

    def project_params(self, q):
        """Constrain one optimizer step's slice of this primitive's
        params (LCFitter calls this after each update): widths stay
        positive, the trailing location wraps to [0, 1)."""
        import jax.numpy as jnp

        q = q.at[:-1].set(jnp.maximum(q[:-1], 1e-4))
        return q.at[-1].set(q[-1] % 1.0)


class LCGaussian(LCPrimitive):
    """Wrapped Gaussian (reference: lcprimitives.py::LCGaussian):
    p = [sigma, loc]."""

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        sigma, loc = p[0], p[1]
        ph = jnp.asarray(phases)
        # sum over wraps k = -2..2 (sigma << 1 in practice); the
        # (ph - loc) form broadcasts per-photon params (lceprimitives)
        k = jnp.arange(-2, 3, dtype=jnp.float64)
        z = ((ph - loc)[..., None] + k) / jnp.asarray(sigma)[..., None]
        return jnp.sum(jnp.exp(-0.5 * z**2), axis=-1) / (
            sigma * math.sqrt(2 * math.pi))


class LCLorentzian(LCPrimitive):
    """Wrapped Lorentzian (reference: lcprimitives.py::LCLorentzian):
    p = [gamma (HWHM), loc]. The infinite wrap sum has the closed form
    sum_k gamma/pi/((x+k)^2+gamma^2) = sinh(2 pi gamma) /
    (cosh(2 pi gamma) - cos(2 pi x))  (normalized on [0,1))."""

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        gamma, loc = p[0], p[1]
        x = 2 * jnp.pi * (jnp.asarray(phases) - loc)
        g = 2 * jnp.pi * gamma
        return jnp.sinh(g) / (jnp.cosh(g) - jnp.cos(x))


class LCSkewGaussian(LCPrimitive):
    """Two-sided (skew) wrapped Gaussian
    (reference: lcprimitives.py::LCGaussian2): p = [sigma1, sigma2,
    loc] — width sigma1 leading (phi < loc), sigma2 trailing;
    normalized density with continuous peak."""

    n_params = 3

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        s1, s2, loc = p[0], p[1], p[2]
        ph = jnp.asarray(phases)
        k = jnp.arange(-2, 3, dtype=jnp.float64)
        d = (ph - loc)[..., None] + k
        sig = jnp.where(d < 0, jnp.asarray(s1)[..., None],
                        jnp.asarray(s2)[..., None])
        dens = jnp.exp(-0.5 * (d / sig) ** 2)
        # normalization: integral = sqrt(pi/2)(s1+s2)
        return jnp.sum(dens, axis=-1) / (
            math.sqrt(math.pi / 2.0) * (s1 + s2))


class LCLorentzian2(LCPrimitive):
    """Two-sided wrapped Lorentzian (reference: lcprimitives.py::
    LCLorentzian2 — the asymmetric-peak workhorse alongside
    LCGaussian2): p = [gamma1, gamma2, loc], HWHM gamma1 leading
    (phi < loc), gamma2 trailing, continuous at the peak.

    The wrap sum is truncated at ±K turns, but the normalization is
    EXACT for the truncated kernel: integrating the k-sum over one
    cycle telescopes to F(K+1-loc) - F(-K-loc) with F the two-sided
    Lorentzian CDF (closed form in arctan), so the density integrates
    to exactly 1 on [0,1) and stays differentiable in all params —
    no slowly-converging tail approximation.
    """

    n_params = 3
    _K = 5  # wrap truncation (normalization exact regardless; see above)

    @staticmethod
    def _cdf(d, g1, g2):
        import jax.numpy as jnp

        w1 = g1 / (g1 + g2)
        w2 = 1.0 - w1
        lead = w1 * (1.0 + (2.0 / jnp.pi) * jnp.arctan(d / g1))
        trail = w1 + w2 * (2.0 / jnp.pi) * jnp.arctan(d / g2)
        return jnp.where(d < 0, lead, trail)

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        g1, g2, loc = p[0], p[1], p[2]
        ph = jnp.asarray(phases)
        K = self._K
        k = jnp.arange(-K, K + 1, dtype=jnp.float64)
        d = (ph - loc)[..., None] + k
        g = jnp.where(d < 0, jnp.asarray(g1)[..., None],
                      jnp.asarray(g2)[..., None])
        # unnormalized two-sided kernel: 1/(1+(d/g)^2), continuous at 0
        dens = jnp.sum(1.0 / (1.0 + (d / g) ** 2), axis=-1)
        # peak height of the unit kernel is 1; line-integral of the
        # kernel is (pi/2)(g1+g2) * (covered mass fraction)
        mass = self._cdf(K + 1.0 - loc, g1, g2) - self._cdf(-K - loc, g1, g2)
        return dens / ((jnp.pi / 2.0) * (g1 + g2) * mass)


# Upstream-parity alias: LCSkewGaussian's p = [sigma1, sigma2, loc]
# (leading/trailing widths, continuous peak) IS the reference
# LCGaussian2 parameterization (reference: lcprimitives.py::LCGaussian2).
LCGaussian2 = LCSkewGaussian


class LCVonMises(LCPrimitive):
    """von Mises peak (reference: lcprimitives.py::LCVonMises):
    p = [kappa_inv, loc]; density ~ exp(kappa cos(2pi(phi-loc)))."""

    def __call__(self, phases, p=None):
        import jax.numpy as jnp
        from jax.scipy.special import i0e

        p = self.p if p is None else p
        kappa = 1.0 / p[0]
        loc = p[1]
        ph = jnp.asarray(phases)
        # density on [0,1): exp(k cos)/I0(k); i0e(k) = exp(-k) I0(k)
        # keeps the ratio finite for large kappa
        return jnp.exp(kappa * (jnp.cos(2 * jnp.pi * (ph - loc)) - 1.0)) / i0e(kappa)


class LCTopHat(LCPrimitive):
    """Top-hat (boxcar) component (reference: lcprimitives.py::LCTopHat):
    p = [width, loc]; uniform density 1/width on the wrapped interval
    centered at loc. A steep-but-smooth logistic edge (scale width/50)
    keeps it differentiable for the gradient fitters."""

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        width, loc = p[0], p[1]
        ph = jnp.asarray(phases)
        # wrapped distance from center in [-0.5, 0.5)
        d = (ph - loc + 0.5) % 1.0 - 0.5
        edge = jnp.asarray(width) / 50.0
        inside = (jax_sigmoid((width / 2.0 - d) / edge)
                  * jax_sigmoid((width / 2.0 + d) / edge))
        return inside / width


def jax_sigmoid(x):
    import jax.nn

    return jax.nn.sigmoid(x)


class LCHarmonic(LCPrimitive):
    """Single-harmonic density (reference: lcprimitives.py::LCHarmonic):
    p = [order, loc]; density 1 + cos(2 pi m (phi - loc)) — the lowest
    nonnegative density containing only harmonic m. ``order`` is a
    structural (integer, non-fitted) parameter."""

    def __init__(self, p):
        super().__init__(p)
        self.order = int(round(float(self.p[0])))

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        loc = p[1]
        ph = jnp.asarray(phases)
        return 1.0 + jnp.cos(2 * jnp.pi * self.order * (ph - loc))

    def project_params(self, q):
        # the harmonic order is structural, not a fit parameter
        q = q.at[0].set(float(self.order))
        return q.at[1].set(q[1] % 1.0)


class LCKernelDensity(LCPrimitive):
    """Non-parametric wrapped-Gaussian KDE of a photon-phase sample
    (reference: lcprimitives.py::LCKernelDensity — upstream's
    bootstrap-a-template-from-the-photons-themselves primitive).

    Construction evaluates a binned KDE once on a phase grid (the
    N-photon sum never re-runs per call): photons are histogrammed on
    ``nbins`` and circularly smoothed with a wrapped Gaussian kernel
    via FFT, which IS the exact binned KDE on the circle. ``__call__``
    then linearly interpolates the grid — cheap, jittable, and with a
    fixed shape regardless of photon count.

    ``bandwidth=None`` uses the circular Silverman rule
    h = 1.06 * sigma_c * n^(-1/5) with sigma_c the circular standard
    deviation. p = [loc]: the single fit parameter is a phase SHIFT of
    the frozen empirical shape (matching upstream, where the KDE shape
    is data and only alignment is fit).
    """

    n_params = 1

    def __init__(self, phases, weights=None, bandwidth=None, nbins=512,
                 loc=0.0):
        ph = np.asarray(phases, np.float64) % 1.0
        w = (np.ones_like(ph) if weights is None
             else np.asarray(weights, np.float64))
        if bandwidth is None:
            # circular Silverman: resultant-based sigma
            C = np.sum(w * np.cos(2 * np.pi * ph))
            S = np.sum(w * np.sin(2 * np.pi * ph))
            R = np.sqrt(C * C + S * S) / max(np.sum(w), 1e-300)
            R = min(max(R, 1e-12), 1.0 - 1e-12)
            sigma_c = np.sqrt(-2.0 * np.log(R)) / (2 * np.pi)
            n_eff = float(np.sum(w)) ** 2 / float(np.sum(w * w))
            bandwidth = 1.06 * max(sigma_c, 1.0 / nbins) * n_eff ** (-0.2)
        self.bandwidth = float(bandwidth)
        hist, _ = np.histogram(ph, bins=nbins, range=(0.0, 1.0), weights=w)
        # wrapped-Gaussian smoothing on the circle == multiply the
        # histogram's Fourier coefficients by exp(-2 (pi k h)^2)
        k = np.fft.rfftfreq(nbins, d=1.0 / nbins)
        F = np.fft.rfft(hist) * np.exp(-2.0 * (np.pi * k * self.bandwidth) ** 2)
        dens = np.fft.irfft(F, nbins) * nbins / max(np.sum(w), 1e-300)
        self.grid = np.maximum(dens, 1e-12)  # density, mean exactly 1
        self.nbins = nbins
        super().__init__([loc])

    @property
    def loc(self):
        return float(self.p[0]) % 1.0

    def __call__(self, phases, p=None):
        import jax.numpy as jnp

        p = self.p if p is None else p
        # histogram mass for bin i sits at the bin CENTER (i+0.5)/nbins
        # — interpolate on center coordinates or every evaluation (and
        # the fitted loc) inherits a -0.5/nbins (~1 milliphase) bias
        x = (jnp.asarray(phases) - p[0]) % 1.0 * self.nbins - 0.5
        i0 = jnp.floor(x).astype(jnp.int32) % self.nbins
        i1 = (i0 + 1) % self.nbins
        frac = x - jnp.floor(x)
        g = jnp.asarray(self.grid)
        return g[i0] * (1.0 - frac) + g[i1] * frac
