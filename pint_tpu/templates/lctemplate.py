"""LCTemplate: normalized mixture of light-curve primitives + DC.

(reference: src/pint/templates/lctemplate.py — LCTemplate holds
primitives + NormAngles norms; __call__(phases) returns the density
1 + sum_i n_i (f_i(phi) - 1); integrates to 1 with DC fraction
1 - sum n_i.)
"""

from __future__ import annotations

import numpy as np


class LCTemplate:
    """Mixture template: density(phi) = (1-sum n) + sum n_i f_i(phi)."""

    def __init__(self, primitives, norms):
        self.primitives = list(primitives)
        self.norms = np.asarray(norms, float)
        if self.norms.sum() > 1.0 + 1e-9:
            raise ValueError("norms must sum to <= 1 (rest is DC)")
        if len(self.norms) != len(self.primitives):
            raise ValueError("one norm per primitive")

    # ---- parameter packing (for gradient fits) ----

    def get_parameters(self):
        """Flat vector [norms..., prim0.p..., prim1.p...]."""
        return np.concatenate([self.norms] + [pr.p for pr in self.primitives])

    def set_parameters(self, vec):
        vec = np.asarray(vec, float)
        n = len(self.primitives)
        self.norms = vec[:n].copy()
        i = n
        for pr in self.primitives:
            pr.p = vec[i:i + pr.n_params].copy()
            i += pr.n_params

    def __call__(self, phases, vec=None):
        """Density at phases; with vec given, a pure function of
        (vec, phases) usable under jit/grad."""
        import jax.numpy as jnp

        ph = jnp.asarray(phases)
        n = len(self.primitives)
        if vec is None:
            norms = jnp.asarray(self.norms)
            out = 1.0 - jnp.sum(norms)
            for nm, pr in zip(self.norms, self.primitives):
                out = out + nm * pr(ph)
            return out
        norms = vec[:n]
        out = (1.0 - jnp.sum(norms)) * jnp.ones_like(ph)
        i = n
        # index norms by primitive number, NOT by offset into vec:
        # norms[i - n] walked past the end for the 2nd+ primitive, and
        # jax's clipped out-of-bounds gather silently DROPPED that
        # norm's gradient (multi-peak fits collapsed their later peaks)
        for j, pr in enumerate(self.primitives):
            out = out + norms[j] * pr(ph, p=vec[i:i + pr.n_params])
            i += pr.n_params
        return out

    def gradient_ready(self):
        """(density_fn(vec, phases), initial vec) for LCFitter."""
        vec0 = self.get_parameters()

        def fn(vec, phases):
            return self(phases, vec=vec)

        return fn, vec0

    def integrate(self, lo=0.0, hi=1.0):
        import jax.numpy as jnp

        x = jnp.linspace(lo, hi, 2049)
        return jnp.trapezoid(self(x), x)

    def max_location(self, resolution=4096):
        """Phase of the template peak."""
        import jax.numpy as jnp

        x = jnp.linspace(0.0, 1.0, resolution, endpoint=False)
        return float(x[jnp.argmax(self(x))])

    def as_binned(self, nbins=256):
        """Bin-averaged template (for MCMCFitterBinnedTemplate)."""
        import jax.numpy as jnp

        x = jnp.linspace(0.0, 1.0, nbins * 8, endpoint=False)
        return np.asarray(self(x)).reshape(nbins, 8).mean(axis=1)
