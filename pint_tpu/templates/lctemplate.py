"""LCTemplate: normalized mixture of light-curve primitives + DC.

(reference: src/pint/templates/lctemplate.py — LCTemplate holds
primitives + NormAngles norms; __call__(phases) returns the density
1 + sum_i n_i (f_i(phi) - 1); integrates to 1 with DC fraction
1 - sum n_i.)
"""

from __future__ import annotations

import numpy as np


class LCTemplate:
    """Mixture template: density(phi) = (1-sum n) + sum n_i f_i(phi)."""

    def __init__(self, primitives, norms):
        self.primitives = list(primitives)
        self.norms = np.asarray(norms, float)
        if self.norms.sum() > 1.0 + 1e-9:
            raise ValueError("norms must sum to <= 1 (rest is DC)")
        if len(self.norms) != len(self.primitives):
            raise ValueError("one norm per primitive")

    # ---- parameter packing (for gradient fits) ----

    def get_parameters(self):
        """Flat vector [norms..., prim0.p..., prim1.p...]."""
        return np.concatenate([self.norms] + [pr.p for pr in self.primitives])

    def set_parameters(self, vec):
        vec = np.asarray(vec, float)
        n = len(self.primitives)
        self.norms = vec[:n].copy()
        i = n
        for pr in self.primitives:
            pr.p = vec[i:i + pr.n_params].copy()
            i += pr.n_params

    def __call__(self, phases, vec=None, log10_ens=None):
        """Density at phases; with vec given, a pure function of
        (vec, phases) usable under jit/grad. ``log10_ens`` (log10 MeV,
        per photon) feeds any energy-dependent primitives
        (reference: lctemplate.py::LCTemplate.__call__(phases, log10_ens))."""
        import jax.numpy as jnp

        def evaluate(pr, ph, p):
            if pr.energy_dependent:
                return pr(ph, p=p, log10_ens=log10_ens)
            return pr(ph, p=p)

        ph = jnp.asarray(phases)
        n = len(self.primitives)
        if vec is None:
            norms = jnp.asarray(self.norms)
            out = 1.0 - jnp.sum(norms)
            for nm, pr in zip(self.norms, self.primitives):
                out = out + nm * evaluate(pr, ph, pr.p)
            return out
        norms = vec[:n]
        out = (1.0 - jnp.sum(norms)) * jnp.ones_like(ph)
        i = n
        # index norms by primitive number, NOT by offset into vec:
        # norms[i - n] walked past the end for the 2nd+ primitive, and
        # jax's clipped out-of-bounds gather silently DROPPED that
        # norm's gradient (multi-peak fits collapsed their later peaks)
        for j, pr in enumerate(self.primitives):
            out = out + norms[j] * evaluate(pr, ph, vec[i:i + pr.n_params])
            i += pr.n_params
        return out

    def gradient_ready(self):
        """(density_fn(vec, phases[, log10_ens]), initial vec) for
        LCFitter; the energy argument reaches any energy-dependent
        primitives in the mixture."""
        vec0 = self.get_parameters()

        def fn(vec, phases, log10_ens=None):
            return self(phases, vec=vec, log10_ens=log10_ens)

        return fn, vec0

    def integrate(self, lo=0.0, hi=1.0):
        import jax.numpy as jnp

        x = jnp.linspace(lo, hi, 2049)
        return jnp.trapezoid(self(x), x)

    def max_location(self, resolution=4096):
        """Phase of the template peak."""
        import jax.numpy as jnp

        x = jnp.linspace(0.0, 1.0, resolution, endpoint=False)
        return float(x[jnp.argmax(self(x))])

    def as_binned(self, nbins=256):
        """Bin-averaged template (for MCMCFitterBinnedTemplate)."""
        import jax.numpy as jnp

        x = jnp.linspace(0.0, 1.0, nbins * 8, endpoint=False)
        return np.asarray(self(x)).reshape(nbins, 8).mean(axis=1)


class LCEmpiricalFourier:
    """Template built nonparametrically from a binned profile via its
    Fourier series (reference: lctemplate.py::LCEmpiricalFourier).

    Keeps ``nharm`` harmonics of the (background-subtracted, normalized)
    profile; the density is 1 + sum_k [a_k cos(2 pi k phi) +
    b_k sin(2 pi k phi)], clipped to be nonnegative and renormalized.
    Exposes the same __call__/max_location surface as LCTemplate so
    fitters and phaseogram tools can take either.
    """

    def __init__(self, profile=None, phases=None, nharm=8, alpha=None,
                 beta=None):
        import numpy as np

        self.nharm = int(nharm)
        if alpha is not None:
            self.alpha = np.asarray(alpha, float)
            self.beta = np.asarray(beta, float)
            return
        if profile is not None:
            prof = np.asarray(profile, float)
            n = len(prof)
            spec = np.fft.rfft(prof)
            mean = spec[0].real / n
            kmax = min(self.nharm, len(spec) - 1)
            # density normalized to integrate to 1 on [0,1)
            self.alpha = 2.0 * spec[1:kmax + 1].real / (n * mean)
            self.beta = -2.0 * spec[1:kmax + 1].imag / (n * mean)
        elif phases is not None:
            ph = np.asarray(phases, float) % 1.0
            k = np.arange(1, self.nharm + 1)
            ang = 2 * np.pi * k[:, None] * ph[None, :]
            self.alpha = 2.0 * np.cos(ang).mean(axis=1)
            self.beta = 2.0 * np.sin(ang).mean(axis=1)
        else:
            raise ValueError("need profile=, phases=, or alpha=/beta=")

    def __call__(self, phases, vec=None, log10_ens=None):
        import jax.numpy as jnp

        ph = jnp.asarray(phases)
        k = jnp.arange(1, len(self.alpha) + 1, dtype=jnp.float64)
        ang = 2 * jnp.pi * k * ph[..., None]
        dens = (1.0 + jnp.sum(jnp.asarray(self.alpha) * jnp.cos(ang), axis=-1)
                + jnp.sum(jnp.asarray(self.beta) * jnp.sin(ang), axis=-1))
        # Fourier truncation can ring below zero on sharp pulses; the
        # density floor keeps log-likelihoods finite
        return jnp.maximum(dens, 1e-6)

    def max_location(self, resolution=4096):
        import jax.numpy as jnp

        x = jnp.linspace(0.0, 1.0, resolution, endpoint=False)
        return float(x[jnp.argmax(self(x))])


# ---------------------------------------------------------------------------
# template file I/O (reference: lctemplate.py::gauss_template_from_file and
# the pygaussfit.py "# gauss" itemized format it reads; also prim_io writing)
# ---------------------------------------------------------------------------

_FWHM_TO_SIGMA = 1.0 / (2.0 * np.sqrt(2.0 * np.log(2.0)))


def gauss_template_from_file(path) -> LCTemplate:
    """Read an itemized gaussian-template file into an LCTemplate.

    Accepts the presto/pygaussfit style used by the reference::

        const  = 0.30
        phas1  = 0.10 +/- 0.001
        fwhm1  = 0.03 +/- 0.001
        ampl1  = 0.50 +/- 0.01

    ``ampl`` entries are the pulsed norms (renormalized against const
    when they sum above 1-const); ``fwhm`` converts to the wrapped-
    Gaussian sigma.
    """
    from .lcprimitives import LCGaussian

    items = {}
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, val = line.split("=", 1)
            val = val.split("+/-")[0].strip()
            try:
                items[key.strip().lower()] = float(val)
            except ValueError:
                continue
    idx = sorted({int(k[4:]) for k in items
                  if k.startswith(("phas", "fwhm", "ampl")) and k[4:].isdigit()})
    if not idx:
        raise ValueError(f"{path}: no gaussian components found")
    prims, norms = [], []
    for i in idx:
        loc = items.get(f"phas{i}", 0.0) % 1.0
        sigma = items.get(f"fwhm{i}", 0.05) * _FWHM_TO_SIGMA
        prims.append(LCGaussian([max(sigma, 1e-4), loc]))
        norms.append(items.get(f"ampl{i}", 0.1))
    norms = np.asarray(norms, float)
    const = items.get("const", None)
    pulsed_cap = 1.0 if const is None else max(1.0 - const, 0.0)
    total = norms.sum()
    if total > pulsed_cap > 0:
        norms *= pulsed_cap / total
    elif total > 1.0:
        norms /= total
    return LCTemplate(prims, norms)


def write_gauss_template(template: LCTemplate, path):
    """Write an LCTemplate of plain Gaussians to the itemized file
    format; round-trips with gauss_template_from_file. Other primitive
    types have no representation in this format, so they are rejected
    rather than silently flattened to Gaussians."""
    from .lcprimitives import LCGaussian

    for pr in template.primitives:
        if type(pr) is not LCGaussian:
            raise ValueError(
                f"gauss template format only holds LCGaussian components; "
                f"got {type(pr).__name__}")
    lines = [f"const  = {1.0 - template.norms.sum():.6f}"]
    for i, (pr, nm) in enumerate(zip(template.primitives, template.norms),
                                 start=1):
        sigma = float(pr.p[0])
        lines.append(f"phas{i}  = {float(pr.loc) % 1.0:.6f}")
        lines.append(f"fwhm{i}  = {sigma / _FWHM_TO_SIGMA:.6f}")
        lines.append(f"ampl{i}  = {float(nm):.6f}")
    with open(path, "w") as f:
        f.write("# gauss\n" + "\n".join(lines) + "\n")
