"""Energy-dependent light-curve primitives.

(reference: src/pint/templates/lceprimitives.py — LCEGaussian /
LCEVonMises etc.: each base parameter gains a linear slope in
log10(E/1 GeV), so pulse peaks may drift and sharpen with photon
energy, as Fermi pulsars do.)

Parameter layout of an energy-dependent primitive with base
``n_base = base.n_params``:

    p = [base params (at the 1 GeV pivot)..., slopes...]

so ``n_params = 2 * n_base``; at the pivot energy the slopes drop out
and the primitive equals its base. Energies enter as ``log10_ens`` in
log10(MeV) (upstream convention; the pivot is 3.0 = 1 GeV).
"""

from __future__ import annotations

import numpy as np

from .lcprimitives import LCGaussian, LCLorentzian, LCPrimitive, LCVonMises

PIVOT_LOG10_MEV = 3.0  # 1 GeV


class LCEPrimitive(LCPrimitive):
    """Generic energy-dependence wrapper around a base primitive class.

    Evaluation broadcasts per-photon effective parameters through the
    base density (the base primitives accept array-valued params), so
    a million-photon evaluation is still one fused device expression.
    """

    energy_dependent = True
    base_cls: type[LCPrimitive] = LCGaussian

    def __init__(self, p, slopes=None):
        base_n = self.base_cls.n_params
        p = np.asarray(p, float)
        if len(p) == base_n:
            p = np.concatenate([p, np.zeros(base_n) if slopes is None
                                else np.asarray(slopes, float)])
        if len(p) != 2 * base_n:
            raise ValueError(
                f"{type(self).__name__} expects {base_n} base params "
                f"(+{base_n} optional slopes); got {len(p)}")
        super().__init__(p)
        self.n_params = 2 * base_n
        self._base = self.base_cls(p[:base_n])

    @property
    def loc(self):
        return self.p[self.base_cls.n_params - 1]

    def effective_params(self, log10_ens, p=None):
        """Per-photon base parameters at the given energies."""
        import jax.numpy as jnp

        p = self.p if p is None else p
        nb = self.base_cls.n_params
        base = jnp.asarray(p[:nb])
        slope = jnp.asarray(p[nb:2 * nb])
        if log10_ens is None:
            return base
        de = jnp.asarray(log10_ens) - PIVOT_LOG10_MEV
        return base[:, None] + slope[:, None] * de

    def project_params(self, q):
        import jax.numpy as jnp

        nb = self.base_cls.n_params
        if nb > 1:
            q = q.at[:nb - 1].set(jnp.maximum(q[:nb - 1], 1e-4))
        return q.at[nb - 1].set(q[nb - 1] % 1.0)  # slopes stay free

    def __call__(self, phases, p=None, log10_ens=None):
        import jax.numpy as jnp

        peff = self.effective_params(log10_ens, p=p)
        # widths must stay positive whatever the slope extrapolates to
        nb = self.base_cls.n_params
        if nb > 1:
            peff = jnp.concatenate(
                [jnp.maximum(peff[:nb - 1], 1e-4), peff[nb - 1:]], axis=0)
        return self._base(phases, p=peff)


class LCEGaussian(LCEPrimitive):
    """(reference: lceprimitives.py::LCEGaussian) wrapped Gaussian with
    sigma(E), loc(E) linear in log10 E."""

    base_cls = LCGaussian
    n_params = 4


class LCEVonMises(LCEPrimitive):
    """(reference: lceprimitives.py::LCEVonMises)."""

    base_cls = LCVonMises
    n_params = 4


class LCELorentzian(LCEPrimitive):
    """(reference: lceprimitives.py::LCELorentzian)."""

    base_cls = LCLorentzian
    n_params = 4
