"""Frequency-dependent (profile evolution) delay.

(reference: src/pint/models/frequency_dependent.py::FD — FD1..FDn;
delay = sum_i FDi * log(freq/1 GHz)^i, FDi in seconds; and
src/pint/models/fdjump.py::FDJump — system-dependent FD<n>JUMP mask
parameters with the FDJUMPLOG basis convention.)
"""

from __future__ import annotations

import numpy as np

from .parameter import (boolParameter, maskParameter, pack_mask_values,
                        prefixParameter)
from .timing_model import DelayComponent


class FD(DelayComponent):
    category = "frequency_dependent"
    order = 40

    def __init__(self):
        super().__init__()
        self.fd_ids: list[int] = []

    def add_fd(self, index=None):
        index = index if index is not None else len(self.fd_ids) + 1
        p = prefixParameter(f"FD{index}", "FD", index, units="s",
                            description=f"FD delay term, log(GHz)^{index}")
        p.value = 0.0
        self.add_param(p)
        self.fd_ids.append(index)
        return index

    def device_slot(self, pname):
        return "FD", self.fd_ids.index(int(pname[2:]))

    def pack(self, model, toas, prep, params0):
        params0["FD"] = np.array([getattr(self, f"FD{i}").value or 0.0
                                  for i in self.fd_ids], dtype=np.float64)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        logf = jnp.log(batch.freq_mhz / 1000.0)  # log(freq/GHz)
        logf = jnp.where(jnp.isfinite(logf), logf, 0.0)
        out = jnp.zeros_like(logf)
        lp = logf
        for i in range(params["FD"].shape[0]):
            out = out + params["FD"][i] * lp
            lp = lp * logf
        return jnp.where(jnp.isfinite(batch.freq_mhz), out, 0.0)


class FDJump(DelayComponent):
    """System-dependent profile-frequency-evolution jumps
    (reference: src/pint/models/fdjump.py::FDJump).

    ``FD<n>JUMP <mask> <value>`` adds ``value * b(nu)^n`` seconds of
    delay to mask-selected TOAs, where the basis is
    ``b = log(nu / 1 GHz)`` when ``FDJUMPLOG`` is true (PINT's FD
    convention, the default) or ``b = nu / 1 GHz`` when false
    (tempo2's linear convention). Multiple systems repeat the same
    order with different masks, exactly like EFAC/EQUAD repetition.
    """

    category = "fdjump"
    order = 41

    def __init__(self):
        super().__init__()
        # parallel lists over mask-parameter slots
        self.fdjump_names: list[str] = []
        self.fdjump_orders: list[int] = []
        p = boolParameter("FDJUMPLOG",
                          description="log-frequency FDJUMP basis (Y) "
                                      "vs linear tempo2 basis (N)")
        p.value = True
        self.add_param(p)

    def add_fdjump(self, n, key="", key_value=(), value=0.0, frozen=False):
        """Add one FD<n>JUMP mask parameter (order n >= 1)."""
        n = int(n)
        if n < 1:
            raise ValueError(f"FDJUMP order must be >= 1, got {n}")
        seq = sum(1 for o in self.fdjump_orders if o == n) + 1
        name = f"FD{n}JUMP{seq}"
        p = maskParameter(name, f"FD{n}JUMP", seq, units="s", frozen=frozen)
        p.key = key
        p.key_value = list(key_value)
        p.value = value
        self.add_param(p)
        self.fdjump_names.append(name)
        self.fdjump_orders.append(int(n))
        return p

    def device_slot(self, pname):
        return "FDJUMP", self.fdjump_names.index(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        vals, masks = pack_mask_values(self, self.fdjump_names, toas)
        params0["FDJUMP"] = vals
        prep["fdjump_masks"] = jnp.asarray(masks)
        prep["fdjump_orders"] = np.asarray(self.fdjump_orders, dtype=np.int64)
        prep["fdjump_log"] = bool(self.FDJUMPLOG.value)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        vals = params["FDJUMP"]
        if vals.shape[0] == 0:
            return jnp.zeros_like(batch.freq_mhz)
        nu = batch.freq_mhz / 1000.0  # GHz
        if prep["fdjump_log"]:
            b = jnp.log(nu)
            b = jnp.where(jnp.isfinite(b), b, 0.0)
        else:
            b = jnp.where(jnp.isfinite(nu), nu, 0.0)
        orders = prep["fdjump_orders"]  # static host ints
        basis = jnp.stack([b ** int(n) for n in orders])  # (P, N)
        out = (vals[:, None] * prep["fdjump_masks"] * basis).sum(axis=0)
        return jnp.where(jnp.isfinite(batch.freq_mhz), out, 0.0)
