"""Frequency-dependent (profile evolution) delay.

(reference: src/pint/models/frequency_dependent.py::FD — FD1..FDn;
delay = sum_i FDi * log(freq/1 GHz)^i, FDi in seconds.)
"""

from __future__ import annotations

import numpy as np

from .parameter import prefixParameter
from .timing_model import DelayComponent


class FD(DelayComponent):
    category = "frequency_dependent"
    order = 40

    def __init__(self):
        super().__init__()
        self.fd_ids: list[int] = []

    def add_fd(self, index=None):
        index = index if index is not None else len(self.fd_ids) + 1
        p = prefixParameter(f"FD{index}", "FD", index, units="s",
                            description=f"FD delay term, log(GHz)^{index}")
        p.value = 0.0
        self.add_param(p)
        self.fd_ids.append(index)
        return index

    def device_slot(self, pname):
        return "FD", self.fd_ids.index(int(pname[2:]))

    def pack(self, model, toas, prep, params0):
        params0["FD"] = np.array([getattr(self, f"FD{i}").value or 0.0
                                  for i in self.fd_ids], dtype=np.float64)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        logf = jnp.log(batch.freq_mhz / 1000.0)  # log(freq/GHz)
        logf = jnp.where(jnp.isfinite(logf), logf, 0.0)
        out = jnp.zeros_like(logf)
        lp = logf
        for i in range(params["FD"].shape[0]):
            out = out + params["FD"][i] * lp
            lp = lp * logf
        return jnp.where(jnp.isfinite(batch.freq_mhz), out, 0.0)
