"""White/correlated noise components.

(reference: src/pint/models/noise_model.py — ScaleToaError (EFAC/EQUAD
maskParameters), EcorrNoise (epoch-correlated, quantization basis),
PLRedNoise (power-law Fourier basis), ScaleDmError for wideband.)

Device representation: masks resolved at pack time; EFAC/EQUAD scale
sigma inside jit; ECORR and red noise export (basis, weight) pairs the
GLS fitter appends to the design matrix (Woodbury form), mirroring the
reference's noise_model_designmatrix/noise_model_basis_weight API.
"""

from __future__ import annotations

import numpy as np

from ..constants import C_M_S, DMconst, SECS_PER_DAY
from .parameter import maskParameter, floatParameter
from .timing_model import Component


class NoiseComponent(Component):
    kind = "noise"

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        return jnp.zeros_like(batch.tdb_sec)


class ScaleToaError(NoiseComponent):
    """EFAC/EQUAD sigma scaling (reference: noise_model.py::ScaleToaError).

    scaled_sigma = sqrt((EFAC * sigma)^2 + EQUAD^2) [EQUAD in us].
    """

    category = "scale_toa_error"
    order = 90

    def __init__(self):
        super().__init__()
        self.efac_ids: list[int] = []
        self.equad_ids: list[int] = []
        self.dmefac_ids: list[int] = []
        self.dmequad_ids: list[int] = []

    def add_mask_param(self, kind: str, fields):
        ids = getattr(self, f"{kind.lower()}_ids")
        index = len(ids) + 1
        name = f"{kind}{index}"
        p = maskParameter(name, kind, index, units="" if "FAC" in kind else "us")
        p.from_parfile_fields(fields)
        self.add_param(p)
        ids.append(index)
        return p

    def device_slot(self, pname):
        for kind, key in (("EFAC", "EFAC"), ("EQUAD", "EQUAD"),
                          ("DMEFAC", "DMEFAC"), ("DMEQUAD", "DMEQUAD")):
            if pname.startswith(kind) and pname[len(kind):].isdigit():
                ids = getattr(self, f"{kind.lower()}_ids")
                return key, ids.index(int(pname[len(kind):]))
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        for kind in ("EFAC", "EQUAD", "DMEFAC", "DMEQUAD"):
            ids = getattr(self, f"{kind.lower()}_ids")
            default = 1.0 if "FAC" in kind else 0.0
            vals = np.array([getattr(self, f"{kind}{i}").value or default
                             for i in ids])
            params0[kind] = vals
            masks = (np.stack([getattr(self, f"{kind}{i}").resolve_mask(toas)
                               for i in ids]).astype(np.float64)
                     if ids else np.zeros((0, len(toas))))
            prep[f"{kind.lower()}_masks"] = jnp.asarray(masks)

    def scale_sigma(self, params, batch, prep, sigma_us):
        import jax.numpy as jnp

        efac = 1.0 + (params["EFAC"] - 1.0) @ prep["efac_masks"]
        equad = params["EQUAD"] @ prep["equad_masks"]
        return jnp.sqrt(jnp.square(efac * sigma_us) + jnp.square(equad))

    def scale_dm_sigma(self, params, prep, sigma_dm):
        """Scaled wideband DM uncertainties [pc cm^-3]:
        sqrt((DMEFAC * sigma)^2 + DMEQUAD^2) per mask (reference:
        noise_model.py::ScaleDmError.scale_dm_sigma — the DM-domain
        twin of scale_sigma, consumed by WidebandDMResiduals and the
        wideband fitters)."""
        import jax.numpy as jnp

        dmefac = 1.0 + (params["DMEFAC"] - 1.0) @ prep["dmefac_masks"]
        dmequad = params["DMEQUAD"] @ prep["dmequad_masks"]
        return jnp.sqrt(jnp.square(dmefac * sigma_dm) + jnp.square(dmequad))


class EcorrNoise(NoiseComponent):
    """Epoch-correlated white noise (reference: noise_model.py::EcorrNoise).

    Host pack quantizes TOAs of each ECORR mask into epochs (default
    2 s window, matching the reference's create_quantization_matrix)
    producing basis U (n_toa x n_epoch) with weights w = ECORR^2 us^2.
    """

    category = "ecorr_noise"
    order = 91

    def __init__(self):
        super().__init__()
        self.ecorr_ids: list[int] = []

    def add_mask_param(self, fields):
        index = len(self.ecorr_ids) + 1
        p = maskParameter(f"ECORR{index}", "ECORR", index, units="us")
        p.from_parfile_fields(fields)
        self.add_param(p)
        self.ecorr_ids.append(index)
        return p

    def device_slot(self, pname):
        return "ECORR", self.ecorr_ids.index(int(pname[5:]))

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        vals = np.array([getattr(self, f"ECORR{i}").value or 0.0
                         for i in self.ecorr_ids])
        params0["ECORR"] = vals
        mjds = toas.get_mjds()
        groups = []  # member-index arrays, one per epoch
        owner = []  # which ECORR param each epoch belongs to
        for k, i in enumerate(self.ecorr_ids):
            mask = getattr(self, f"ECORR{i}").resolve_mask(toas)
            idx = np.flatnonzero(mask)
            if len(idx) == 0:
                continue
            order = idx[np.argsort(mjds[idx])]
            t = mjds[order]
            # quantize: new epoch when gap > 2 seconds
            bucket = np.concatenate([[0], np.cumsum(np.diff(t) > 2.0 / SECS_PER_DAY)])
            for b in range(bucket[-1] + 1):
                members = order[bucket == b]
                if len(members) < 2:
                    continue  # singleton epochs carry no correlated info
                groups.append(members)
                owner.append(k)
        prep["ecorr_owner"] = jnp.asarray(np.array(owner, dtype=np.int64))
        counts = np.zeros(len(toas), dtype=np.int64)
        for g in groups:
            counts[g] += 1
        if groups and counts.max() > 1:
            # overlapping ECORR masks (a TOA in two epochs): only the
            # dense basis can represent this; the GLS auto path falls
            # back to the dense solve for such models anyway
            U = np.zeros((len(toas), len(groups)))
            for j, g in enumerate(groups):
                U[g, j] = 1.0
            prep["ecorr_U"] = jnp.asarray(U)
        else:
            # disjoint epochs — the universal real-data case: store the
            # O(n) epoch index instead of the O(n*k) dense basis. At
            # NANOGrav scale (30k TOAs, ~10^3 epochs/pulsar) the dense
            # U is ~0.25 GB/pulsar of pure redundancy; the index packs
            # the identical information in 120 kB and the marginalized
            # GLS path (parallel/pta.py::one_step_marg) consumes it
            # directly via segment sums.
            eidx = np.full(len(toas), -1, dtype=np.int32)
            for j, g in enumerate(groups):
                eidx[g] = j
            prep["ecorr_eidx"] = jnp.asarray(eidx)

    @staticmethod
    def dense_U(prep):
        """The (n_toa, k) 0/1 quantization basis, reconstructed from
        the epoch index when only the sparse form is packed."""
        import jax.numpy as jnp

        if "ecorr_U" in prep:
            return prep["ecorr_U"]
        k = prep["ecorr_owner"].shape[-1]
        eidx = prep["ecorr_eidx"]
        return (eidx[:, None] == jnp.arange(k)[None, :]).astype(jnp.float64)

    def basis_weight(self, params, prep):
        """(U, w): covariance contribution U diag(w) U^T, w in us^2.

        owner < 0 marks batch-padding columns (parallel/pta.py pads
        ragged epoch counts with owner=-1): those get w=0 so the padded
        zero column is exactly degenerate and dropped by the solver's
        threshold instead of carrying pulsar-0's ECORR prior."""
        import jax.numpy as jnp

        U = self.dense_U(prep)
        if not U.shape[1]:
            return U, jnp.zeros(0)
        owner = prep["ecorr_owner"]
        w = jnp.square(params["ECORR"])[jnp.clip(owner, 0, None)]
        return U, jnp.where(owner >= 0, w, 0.0)

    def epoch_index_weight(self, params, prep):
        """Sparse form for the analytically-marginalized GLS path:
        (eidx (n_toa,) int, w_us2 (k,)) with eidx in [0,k) or any
        out-of-range value (-1 / padded) meaning 'not in an epoch'.
        None when only the overlapping dense form exists."""
        import jax.numpy as jnp

        if "ecorr_eidx" not in prep:
            return None
        owner = prep["ecorr_owner"]
        w = jnp.square(params["ECORR"])[jnp.clip(owner, 0, None)]
        return prep["ecorr_eidx"], jnp.where(owner >= 0, w, 0.0)


def fourier_basis(toas, n_harm):
    """(F (n_toa, 2*n_harm), freqs_Hz repeated sin/cos, tspan_s) —
    the shared red/DM-noise Fourier machinery (one home so the basis
    convention can't diverge between the chromatic and achromatic
    components)."""
    mjds = toas.get_mjds()
    tspan_s = (mjds.max() - mjds.min() + 1.0) * SECS_PER_DAY
    t_s = (mjds - mjds.min()) * SECS_PER_DAY
    k = np.arange(1, n_harm + 1)
    freqs = k / tspan_s
    arg = 2 * np.pi * np.outer(t_s, freqs)
    F = np.empty((len(toas), 2 * n_harm))
    F[:, 0::2] = np.sin(arg)
    F[:, 1::2] = np.cos(arg)
    return F, np.repeat(freqs, 2), tspan_s


def powerlaw_phi(A, gamma, f, tspan_s):
    """Per-column prior variances [us^2] of the enterprise-convention
    power law P(f) = A^2/(12 pi^2) (f/f_yr)^(-gamma) yr^3."""
    import jax.numpy as jnp

    fyr = 1.0 / (365.25 * SECS_PER_DAY)
    psd = (A**2 / (12.0 * jnp.pi**2) * (f / fyr) ** (-gamma)) / fyr**3
    return psd / tspan_s * 1e12  # s^2 -> us^2


class PLRedNoise(NoiseComponent):
    """Power-law red noise Fourier basis (reference: noise_model.py::PLRedNoise).

    Params RNAMP/RNIDX (or TNRedAmp/TNRedGam/TNRedC aliases resolved by
    the builder). Basis: sin/cos at k/T_span, k=1..n_harm; weights are
    the power-law PSD integrated per bin.
    """

    category = "pl_red_noise"
    order = 92

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("RNAMP", units="us*yr^0.5",
                                      description="Red noise amplitude"))
        self.add_param(floatParameter("RNIDX", units="",
                                      description="Red noise spectral index (negative)"))
        self.add_param(floatParameter("TNREDAMP", units="log10",
                                      description="log10 TN red amplitude"))
        self.add_param(floatParameter("TNREDGAM", units="",
                                      description="TN red spectral index (positive)"))
        p = floatParameter("TNREDC", units="", description="Number of harmonics")
        p.value = 30
        self.add_param(p)

    def device_slot(self, pname):
        return pname, None

    def n_harmonics(self):
        return int(self.TNREDC.value or 30)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        F, freqs, tspan_s = fourier_basis(toas, self.n_harmonics())
        prep["rn_F"] = jnp.asarray(F)
        prep["rn_freqs"] = jnp.asarray(freqs)
        prep["rn_tspan_s"] = jnp.asarray(tspan_s, jnp.float64)
        for pname in ("RNAMP", "RNIDX", "TNREDAMP", "TNREDGAM"):
            params0[pname] = getattr(self, pname).value or 0.0

    def basis_weight(self, params, prep):
        """(F, phi): weights [us^2] of the power-law PSD per basis column.

        Convention matches the reference/enterprise: P(f) = A^2/(12 pi^2)
        (f/f_yr)^(-gamma) yr^3 with A in TN units, or RNAMP/RNIDX
        tempo-style converted equivalently.
        """
        import jax.numpy as jnp

        f = prep["rn_freqs"]
        tspan = prep["rn_tspan_s"]
        fyr = 1.0 / (365.25 * SECS_PER_DAY)
        use_tn = self.TNREDAMP.value is not None
        if use_tn:
            A = 10.0 ** params["TNREDAMP"]
            gamma = params["TNREDGAM"]
        else:
            # tempo RNAMP [us yr^0.5] -> dimensionless strain-like TN amplitude
            # (reference: noise_model.py RNAMP conversion: A = RNAMP*2*pi*sqrt(3)/ (1e6 * yr_s * f_yr^... )
            # kept equivalent: validated in tests/test_gls.py against direct PSD)
            A = params["RNAMP"] * (2.0 * jnp.pi * jnp.sqrt(3.0)) / (1e6 * 365.25 * 86400.0)
            gamma = -params["RNIDX"]
        return prep["rn_F"], powerlaw_phi(A, gamma, f, tspan)


class _PLScaledNoise(NoiseComponent):
    """Shared machinery for power-law noise whose Fourier basis is
    row-scaled per TOA by (f_ref/nu)^alpha: PLDMNoise (alpha = 2) and
    PLChromNoise (alpha = the model's TNCHROMIDX). Subclasses set the
    parameter names and the prep-key prefix; the basis/weight math has
    exactly one home so the two cannot diverge."""

    F_REF_MHZ = 1400.0
    AMP = GAM = NHARM = PREP = None  # subclass config
    PHI_SCALE = 1.0  # basis-weight unit conversion (see PLSWNoise)

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(self.AMP, units="log10",
                                      description="log10 noise amplitude"))
        self.add_param(floatParameter(self.GAM, units="",
                                      description="Noise spectral index"))
        p = floatParameter(self.NHARM, units="",
                           description="Number of harmonics")
        p.value = 30
        self.add_param(p)

    def device_slot(self, pname):
        return pname, None

    def _alpha(self, model):
        raise NotImplementedError

    def _row_scale(self, model, toas, prep, params0):
        """Per-TOA multiplier on the Fourier basis rows. Default: the
        chromatic factor (f_ref/nu)^alpha; infinite-frequency TOAs see
        none of this noise."""
        alpha = self._alpha(model)
        with np.errstate(divide="ignore"):
            return np.where(np.isfinite(toas.freq_mhz),
                            (self.F_REF_MHZ / toas.freq_mhz) ** alpha, 0.0)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        F, freqs, tspan_s = fourier_basis(
            toas, int(getattr(self, self.NHARM).value or 30))
        scale = self._row_scale(model, toas, prep, params0)
        prep[f"{self.PREP}_F"] = jnp.asarray(F * scale[:, None])
        prep[f"{self.PREP}_freqs"] = jnp.asarray(freqs)
        prep[f"{self.PREP}_tspan_s"] = jnp.asarray(tspan_s, jnp.float64)
        for pname in (self.AMP, self.GAM):
            params0[pname] = getattr(self, pname).value or 0.0

    def basis_weight(self, params, prep):
        A = 10.0 ** params[self.AMP]
        gamma = params[self.GAM]
        return prep[f"{self.PREP}_F"], self.PHI_SCALE * powerlaw_phi(
            A, gamma, prep[f"{self.PREP}_freqs"],
            prep[f"{self.PREP}_tspan_s"])


class PLDMNoise(_PLScaledNoise):
    """Power-law DM (chromatic) noise (reference: noise_model.py::
    PLDMNoise): same Fourier machinery as PLRedNoise, but the basis is
    scaled per TOA by (f_ref/nu)^2, f_ref = 1400 MHz — achromatic in
    DM units, chromatic in time delay. Params TNDMAMP (log10),
    TNDMGAM, TNDMC.
    """

    category = "pl_dm_noise"
    order = 93
    AMP, GAM, NHARM, PREP = "TNDMAMP", "TNDMGAM", "TNDMC", "dmrn"

    def _alpha(self, model):
        return 2.0


class PLChromNoise(_PLScaledNoise):
    """Power-law chromatic noise with a variable spectral index in
    frequency (reference: noise_model.py::PLChromNoise): the PLDMNoise
    machinery with the per-TOA basis scaling (f_ref/nu)^alpha, where
    alpha is the model's chromatic index TNCHROMIDX (owned by
    ChromaticCM, default 4 — the thin-screen scattering expectation).
    Params TNCHROMAMP (log10), TNCHROMGAM, TNCHROMC.
    """

    category = "pl_chrom_noise"
    order = 94
    AMP, GAM, NHARM, PREP = "TNCHROMAMP", "TNCHROMGAM", "TNCHROMC", "chromrn"

    def _alpha(self, model):
        # static at pack time (like the basis span); default matches
        # ChromaticCM.DEFAULT_CHROM_IDX
        cm = model.components.get("ChromaticCM")
        if cm is not None and cm.TNCHROMIDX.value is not None:
            return float(cm.TNCHROMIDX.value)
        return 4.0


class PLSWNoise(_PLScaledNoise):
    """Power-law solar-wind (NE_SW) noise (reference:
    noise_model.py::PLSWNoise *(version-dependent; Susarla et al.
    2024 stochastic solar-wind model)*).

    A Gaussian process on the solar-wind electron density NE_SW(t):
    Fourier basis rows are scaled per TOA by the time-delay signature
    of a unit NE_SW change,

        d(delay)/d(NE_SW) = DMconst * geom_pc(t) / nu^2   [s / cm^-3]

    (geometry from SolarWindDispersion's line-of-sight integral, so
    the noise peaks at solar conjunction and scales as 1/nu^2).
    TNSWAMP is the log10 amplitude of the NE_SW power law in the
    enterprise convention with NE_SW in cm^-3 (PHI_SCALE removes the
    s^2 -> us^2 factor powerlaw_phi applies for dimensionless bases:
    here the basis itself carries us per cm^-3, so the weights stay in
    (cm^-3)^2 and the covariance comes out in us^2).
    Params TNSWAMP (log10), TNSWGAM, TNSWC.
    """

    category = "pl_sw_noise"
    order = 95
    AMP, GAM, NHARM, PREP = "TNSWAMP", "TNSWGAM", "TNSWC", "swrn"
    PHI_SCALE = 1e-12

    def _row_scale(self, model, toas, prep, params0):
        astrom = next((c for c in model.delay_components()
                       if c.category == "astrometry"), None)
        has_sw = ("SolarWindDispersion" in model.components
                  or "SolarWindDispersionX" in model.components)
        if not has_sw or astrom is None:
            raise ValueError(
                "PLSWNoise needs a solar-wind component (NE_SW or SWX) "
                "and an astrometry component to evaluate the "
                "line-of-sight geometry")
        # geometry per unit NE_SW at the start-of-fit position (static
        # during a fit, like the basis span): DM_sw/NE_SW in pc cm^-3
        # per cm^-3, times DMconst/nu^2 -> seconds, times 1e6 -> us.
        # The geometry formula's one home is solar_wind.py (p=2 reduces
        # exactly to the (pi - theta)/(r sin theta) factor).
        from .solar_wind import solar_wind_geometry_p

        n_hat = np.asarray(astrom.ssb_to_psb_xyz(params0, prep))
        sun_ls = toas.obs_sun.pos / C_M_S
        # the EFFECTIVE wind profile index, not hardcoded 2: under
        # SWM 1 the deterministic d(delay)/d(NE_SW) is the r^-SWP
        # geometry, and the GP basis must match it or conjunction
        # epochs are mis-weighted relative to the wind being fit
        sw = model.components.get("SolarWindDispersionX",
                                  model.components.get(
                                      "SolarWindDispersion"))
        p_base = 2.0
        if int(sw.SWM.value or 0) == 1 and sw.SWP.value is not None:
            p_base = float(sw.SWP.value)
        swx_ids = getattr(sw, "swx_ids", ())
        if swx_ids:
            # under SWX the wind index is per-window (SWXP_####): give
            # each TOA the index of the window it falls in (base index
            # outside all windows), else conjunction epochs inside a
            # p != 2 window would be mis-weighted exactly as the
            # comment above warns (ADVICE r4)
            mjd = toas.get_mjds()
            p_eff = np.full(len(toas), p_base, dtype=np.float64)
            for i in swx_ids:
                lo = getattr(sw, f"SWXR1_{i:04d}").value
                hi = getattr(sw, f"SWXR2_{i:04d}").value
                pv = getattr(sw, f"SWXP_{i:04d}").value
                m = (mjd >= lo) & (mjd < hi)
                p_eff[m] = 2.0 if pv is None else float(pv)
        else:
            p_eff = p_base
        geom_pc = np.asarray(solar_wind_geometry_p(sun_ls, n_hat, p_eff))
        with np.errstate(divide="ignore"):
            per_f2 = np.where(np.isfinite(toas.freq_mhz),
                              1.0 / np.square(toas.freq_mhz), 0.0)
        return 1e6 * DMconst * geom_pc * per_f2
