"""Harmonic whitening terms: Wave (Tempo-style) and WaveX.

(reference: src/pint/models/wave.py::Wave — WAVEEPOCH, WAVE_OM
[rad/day], WAVEn pair parameters (sin, cos amplitudes in seconds);
phase += F0 * sum_k [A_k sin(k w t) + B_k cos(k w t)].
reference: src/pint/models/wavex.py::WaveX — WXEPOCH, explicit
per-term frequencies WXFREQ_#### [1/day] with WXSIN_####/WXCOS_####
delay amplitudes in seconds.)
"""

from __future__ import annotations

import numpy as np

from ..constants import SECS_PER_DAY
from .parameter import MJDParameter, floatParameter, pairParameter, prefixParameter
from .timing_model import PhaseComponent, DelayComponent, MissingParameter


class Wave(PhaseComponent):
    category = "wave"
    order = 35

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("WAVE_OM", units="rad/day",
                                      description="Fundamental wave frequency"))
        self.add_param(MJDParameter("WAVEEPOCH", units="MJD",
                                    description="Reference epoch of wave terms"))
        self.wave_ids: list[int] = []

    def add_wave(self, index=None):
        index = index if index is not None else len(self.wave_ids) + 1
        p = pairParameter(f"WAVE{index}", "WAVE", index, units="s",
                          description=f"Wave harmonic {index} (sin, cos) [s]")
        p.value = (0.0, 0.0)
        self.add_param(p)
        self.wave_ids.append(index)
        return index

    def validate(self):
        if self.wave_ids and self.WAVE_OM.value is None:
            raise MissingParameter("Wave", "WAVE_OM")

    def device_slot(self, pname):
        if pname == "WAVE_OM":
            return "WAVE_OM", None
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        params0["WAVE_OM"] = self.WAVE_OM.value or 0.0
        a = np.array([getattr(self, f"WAVE{i}").value[0] for i in self.wave_ids])
        b = np.array([getattr(self, f"WAVE{i}").value[1] for i in self.wave_ids])
        params0["WAVEA"] = a
        params0["WAVEB"] = b
        we = self.WAVEEPOCH
        if we is not None and we.day is not None:
            day, sec = we.day, we.sec
        else:
            day, sec = prep["pepoch_day"], prep["pepoch_sec"]
        dt_day = ((toas.tdb.day - day).astype(np.float64)
                  + (toas.tdb.sec - sec) / SECS_PER_DAY)
        prep["wave_dt_day"] = jnp.asarray(dt_day)

    def phase(self, params, batch, prep, delay_total):
        import jax.numpy as jnp

        t = prep["wave_dt_day"] - delay_total / SECS_PER_DAY
        k = jnp.arange(1, params["WAVEA"].shape[0] + 1, dtype=t.dtype)
        arg = params["WAVE_OM"] * t[:, None] * k[None, :]
        wave_s = jnp.sum(params["WAVEA"] * jnp.sin(arg)
                         + params["WAVEB"] * jnp.cos(arg), axis=-1)
        return params["F"][0] * wave_s


class WaveX(DelayComponent):
    """Explicit-frequency harmonic delays (reference: wavex.py::WaveX)."""

    category = "wavex"
    order = 36

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("WXEPOCH", units="MJD",
                                    description="Reference epoch of WaveX terms"))
        self.wx_ids: list[int] = []

    def add_wavex(self, index=None, freq_per_day=None):
        index = index if index is not None else len(self.wx_ids) + 1
        f = prefixParameter(f"WXFREQ_{index:04d}", "WXFREQ_", index, units="1/d")
        f.value = freq_per_day if freq_per_day is not None else 0.0
        self.add_param(f)
        for stem in ("WXSIN", "WXCOS"):
            p = prefixParameter(f"{stem}_{index:04d}", f"{stem}_", index, units="s")
            p.value = 0.0
            self.add_param(p)
        self.wx_ids.append(index)
        return index

    def device_slot(self, pname):
        stem, idx = pname.rsplit("_", 1)
        if stem in ("WXSIN", "WXCOS", "WXFREQ"):
            return stem, self.wx_ids.index(int(idx))
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        for stem in ("WXFREQ", "WXSIN", "WXCOS"):
            params0[stem] = np.array(
                [getattr(self, f"{stem}_{i:04d}").value or 0.0
                 for i in self.wx_ids], dtype=np.float64)
        we = self.WXEPOCH
        if we is not None and we.day is not None:
            day, sec = we.day, we.sec
        else:
            day, sec = prep["pepoch_day"], prep["pepoch_sec"]
        dt_day = ((toas.tdb.day - day).astype(np.float64)
                  + (toas.tdb.sec - sec) / SECS_PER_DAY)
        prep["wavex_dt_day"] = jnp.asarray(dt_day)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        t = prep["wavex_dt_day"]
        arg = 2.0 * jnp.pi * params["WXFREQ"] * t[:, None]
        return jnp.sum(params["WXSIN"] * jnp.sin(arg)
                       + params["WXCOS"] * jnp.cos(arg), axis=-1)


class DMWaveX(DelayComponent):
    """WaveX in DM space (reference: dmwavex.py::DMWaveX): explicit
    frequencies DMWXFREQ_#### with DMWXSIN/DMWXCOS amplitudes in
    pc cm^-3; delay = DMconst * DM_wave / nu^2."""

    category = "dmwavex"
    order = 37

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("DMWXEPOCH", units="MJD",
                                    description="Reference epoch of DMWaveX terms"))
        self.wx_ids: list[int] = []

    def add_dmwavex(self, index=None, freq_per_day=None):
        index = index if index is not None else len(self.wx_ids) + 1
        f = prefixParameter(f"DMWXFREQ_{index:04d}", "DMWXFREQ_", index,
                            units="1/d")
        f.value = freq_per_day if freq_per_day is not None else 0.0
        self.add_param(f)
        for stem in ("DMWXSIN", "DMWXCOS"):
            p = prefixParameter(f"{stem}_{index:04d}", f"{stem}_", index,
                                units="pc/cm^3")
            p.value = 0.0
            self.add_param(p)
        self.wx_ids.append(index)
        return index

    def device_slot(self, pname):
        stem, idx = pname.rsplit("_", 1)
        if stem in ("DMWXSIN", "DMWXCOS", "DMWXFREQ"):
            return stem, self.wx_ids.index(int(idx))
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        for stem in ("DMWXFREQ", "DMWXSIN", "DMWXCOS"):
            params0[stem] = np.array(
                [getattr(self, f"{stem}_{i:04d}").value or 0.0
                 for i in self.wx_ids], dtype=np.float64)
        we = self.DMWXEPOCH
        if we is not None and we.day is not None:
            day, sec = we.day, we.sec
        else:
            day, sec = prep["pepoch_day"], prep["pepoch_sec"]
        dt_day = ((toas.tdb.day - day).astype(np.float64)
                  + (toas.tdb.sec - sec) / SECS_PER_DAY)
        prep["dmwavex_dt_day"] = jnp.asarray(dt_day)

    def dm_value(self, params, prep):
        """Fourier DM contribution [pc cm^-3] (shared by delay and
        TimingModel.total_dm / the wideband DM model)."""
        import jax.numpy as jnp

        t = prep["dmwavex_dt_day"]
        arg = 2.0 * jnp.pi * params["DMWXFREQ"] * t[:, None]
        return jnp.sum(params["DMWXSIN"] * jnp.sin(arg)
                       + params["DMWXCOS"] * jnp.cos(arg), axis=-1)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        from ..constants import DMconst

        dm = self.dm_value(params, prep)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm / f2, 0.0)
