"""Chromatic (variable-index) dispersion-like delays.

(reference: src/pint/models/chromatic_model.py — ChromaticCM with
Taylor-series CM/CM1/... at CMEPOCH and chromatic index TNCHROMIDX,
ChromaticCMX piecewise windows CMX_####/CMXR1_####/CMXR2_####;
src/pint/models/cmwavex.py::CMWaveX — explicit-frequency Fourier
amplitudes in CM units.)

Convention: delay = DMconst * CM(t) / nu_MHz^alpha with
alpha = TNCHROMIDX (default 4, the expected scattering index).
DMconst carries s MHz^2 / (pc cm^-3), so CM is in
pc cm^-3 MHz^(alpha-2); at alpha = 2 every formula reduces exactly to
the corresponding DM component (pinned by tests/test_chromatic.py).
"""

from __future__ import annotations

import numpy as np

from ..constants import DMconst, SECS_PER_DAY
from .parameter import MJDParameter, floatParameter, prefixParameter
from .timing_model import DelayComponent, MissingParameter

DEFAULT_CHROM_IDX = 4.0


class ChromaticCM(DelayComponent):
    """Taylor-series chromatic measure (reference: ChromaticCM)."""

    category = "chromatic"
    order = 32

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter(
            "CM", "CM", 0, units="pc cm^-3 MHz^(alpha-2)",
            description="Chromatic measure"))
        self.add_param(MJDParameter("CMEPOCH", units="MJD",
                                    description="Epoch of CM measurement"))
        p = floatParameter("TNCHROMIDX", units="",
                           description="Chromatic index alpha (delay ~ nu^-alpha)")
        p.value = DEFAULT_CHROM_IDX
        self.add_param(p)

    def validate(self):
        if self.CM.value is None:
            raise MissingParameter("ChromaticCM", "CM")

    def n_terms(self):
        n = 0
        while f"CM{n + 1}" in self.params:
            n += 1
        return n + 1

    def add_cmterm(self, index, value=0.0, frozen=True):
        p = prefixParameter(f"CM{index}", "CM", index,
                            units=f"pc cm^-3 MHz^(alpha-2)/yr^{index}",
                            frozen=frozen)
        p.value = value
        self.add_param(p)

    def device_slot(self, pname):
        if pname == "CM":
            return "CM", 0
        if pname == "TNCHROMIDX":
            return "TNCHROMIDX", None
        return "CM", int(pname[2:])

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        vals = np.array([getattr(self, f"CM{i}" if i else "CM").value or 0.0
                         for i in range(self.n_terms())], dtype=np.float64)
        params0["CM"] = vals
        params0["TNCHROMIDX"] = self.TNCHROMIDX.value or DEFAULT_CHROM_IDX
        ce = self.CMEPOCH
        if ce is not None and ce.day is not None:
            day, sec = ce.day, ce.sec
        else:
            day, sec = prep["pepoch_day"], prep["pepoch_sec"]
        dt = ((toas.tdb.day - day).astype(np.float64) * SECS_PER_DAY
              + (toas.tdb.sec - sec))
        prep["cmepoch_dt"] = jnp.asarray(dt)

    def cm_value(self, params, prep):
        """CM(t) Taylor series; CM1, CM2, ... per Julian year like the
        DM derivatives (reference: chromatic_model.py CM units)."""
        from ..constants import SECS_PER_JULIAN_YEAR

        cm = params["CM"]
        dt = prep["cmepoch_dt"] / SECS_PER_JULIAN_YEAR
        out = 0.0 * dt
        fact = 1.0
        tp = 1.0
        for i in range(cm.shape[0]):
            if i > 0:
                fact *= i
            out = out + cm[i] * tp / fact
            tp = tp * dt
        return out

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        cm = self.cm_value(params, prep)
        falpha = jnp.power(batch.freq_mhz, params["TNCHROMIDX"])
        return jnp.where(jnp.isfinite(falpha), DMconst * cm / falpha, 0.0)


class ChromaticCMX(DelayComponent):
    """Piecewise-constant CM offsets in MJD windows (reference:
    ChromaticCMX — CMX_#### with CMXR1_####/CMXR2_#### ranges).

    Uses the chromatic index of the model's ChromaticCM component
    (the builder always adds ChromaticCM with CM=0 when only CMX lines
    are present, so TNCHROMIDX has exactly one home).
    """

    category = "chromatic_cmx"
    order = 33

    def __init__(self):
        super().__init__()
        self.cmx_ids: list[int] = []

    def add_cmx_range(self, index, mjd_start, mjd_end, value=0.0, frozen=True):
        p = prefixParameter(f"CMX_{index:04d}", "CMX_", index,
                            units="pc cm^-3 MHz^(alpha-2)", frozen=frozen)
        p.value = value
        self.add_param(p)
        r1 = MJDParameter(f"CMXR1_{index:04d}", units="MJD")
        r1.set_mjd(int(mjd_start), (mjd_start % 1) * SECS_PER_DAY)
        self.add_param(r1)
        r2 = MJDParameter(f"CMXR2_{index:04d}", units="MJD")
        r2.set_mjd(int(mjd_end), (mjd_end % 1) * SECS_PER_DAY)
        self.add_param(r2)
        self.cmx_ids.append(index)

    def device_slot(self, pname):
        if pname.startswith("CMX_"):
            return "CMX", self.cmx_ids.index(int(pname[4:]))
        raise KeyError(pname)

    def validate(self):
        super().validate()
        # a missing CMXR1/CMXR2 pair parses as the empty window [0, 0],
        # whose design column is identically zero — a silently
        # degenerate fit (reference behavior: MissingParameter)
        for i in self.cmx_ids:
            r1 = getattr(self, f"CMXR1_{i:04d}").value
            r2 = getattr(self, f"CMXR2_{i:04d}").value
            if r1 is None or r2 is None or not r1 < r2:
                raise MissingParameter(
                    "ChromaticCMX", f"CMXR1_{i:04d}/CMXR2_{i:04d}",
                    f"CMX_{i:04d} needs a non-empty MJD window "
                    f"(got [{r1}, {r2}])")

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        vals = np.array([getattr(self, f"CMX_{i:04d}").value or 0.0
                         for i in self.cmx_ids], dtype=np.float64)
        params0["CMX"] = vals
        mjds = toas.get_mjds()
        masks = np.zeros((len(self.cmx_ids), len(toas)))
        for k, i in enumerate(self.cmx_ids):
            lo = getattr(self, f"CMXR1_{i:04d}").value
            hi = getattr(self, f"CMXR2_{i:04d}").value
            masks[k] = (mjds >= lo) & (mjds <= hi)
        prep["cmx_masks"] = jnp.asarray(masks)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        cm_per_toa = params["CMX"] @ prep["cmx_masks"]
        alpha = params.get("TNCHROMIDX", DEFAULT_CHROM_IDX)
        falpha = jnp.power(batch.freq_mhz, alpha)
        return jnp.where(jnp.isfinite(falpha), DMconst * cm_per_toa / falpha,
                         0.0)


class CMWaveX(DelayComponent):
    """WaveX in CM space (reference: cmwavex.py::CMWaveX): explicit
    frequencies CMWXFREQ_#### with CMWXSIN_####/CMWXCOS_#### amplitudes
    in CM units; delay = DMconst * CM_wave / nu^alpha."""

    category = "cmwavex"
    order = 38

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("CMWXEPOCH", units="MJD",
                                    description="Reference epoch of CMWaveX terms"))
        self.wx_ids: list[int] = []

    def add_cmwavex(self, index=None, freq_per_day=None):
        index = index if index is not None else len(self.wx_ids) + 1
        f = prefixParameter(f"CMWXFREQ_{index:04d}", "CMWXFREQ_", index,
                            units="1/d")
        f.value = freq_per_day if freq_per_day is not None else 0.0
        self.add_param(f)
        for stem in ("CMWXSIN", "CMWXCOS"):
            p = prefixParameter(f"{stem}_{index:04d}", f"{stem}_", index,
                                units="pc cm^-3 MHz^(alpha-2)")
            p.value = 0.0
            self.add_param(p)
        self.wx_ids.append(index)
        return index

    def device_slot(self, pname):
        stem, idx = pname.rsplit("_", 1)
        if stem in ("CMWXSIN", "CMWXCOS", "CMWXFREQ"):
            return stem, self.wx_ids.index(int(idx))
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        for stem in ("CMWXFREQ", "CMWXSIN", "CMWXCOS"):
            params0[stem] = np.array(
                [getattr(self, f"{stem}_{i:04d}").value or 0.0
                 for i in self.wx_ids], dtype=np.float64)
        we = self.CMWXEPOCH
        if we is not None and we.day is not None:
            day, sec = we.day, we.sec
        else:
            day, sec = prep["pepoch_day"], prep["pepoch_sec"]
        dt_day = ((toas.tdb.day - day).astype(np.float64)
                  + (toas.tdb.sec - sec) / SECS_PER_DAY)
        prep["cmwavex_dt_day"] = jnp.asarray(dt_day)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        t = prep["cmwavex_dt_day"]
        arg = 2.0 * jnp.pi * params["CMWXFREQ"] * t[:, None]
        cm = jnp.sum(params["CMWXSIN"] * jnp.sin(arg)
                     + params["CMWXCOS"] * jnp.cos(arg), axis=-1)
        alpha = params.get("TNCHROMIDX", DEFAULT_CHROM_IDX)
        falpha = jnp.power(batch.freq_mhz, alpha)
        return jnp.where(jnp.isfinite(falpha), DMconst * cm / falpha, 0.0)
