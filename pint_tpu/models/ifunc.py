"""Tabulated phase offsets (IFUNC).

(reference: src/pint/models/ifunc.py::IFunc — SIFUNC selects the
interpolation mode (0 = constant/nearest, 2 = linear), IFUNC1..n are
(MJD, value_s[, error]) tuples; phase += F0 * interp(t).)

The table MJDs are packed static; values are device parameters so they
are fittable (each IFUNCn is a free/frozen amplitude).
"""

from __future__ import annotations

import numpy as np

from ..constants import SECS_PER_DAY
from .parameter import intParameter, pairParameter
from .timing_model import PhaseComponent


class IFunc(PhaseComponent):
    category = "ifunc"
    order = 37

    def __init__(self):
        super().__init__()
        p = intParameter("SIFUNC", description="IFUNC interpolation mode (0|2)")
        p.value = 2
        self.add_param(p)
        self.if_ids: list[int] = []

    def add_ifunc(self, index=None, mjd=0.0, value=0.0):
        index = index if index is not None else len(self.if_ids) + 1
        p = pairParameter(f"IFUNC{index}", "IFUNC", index, units="(MJD, s)",
                          description=f"IFUNC node {index}")
        p.value = (mjd, value)
        self.add_param(p)
        self.if_ids.append(index)
        return index

    def validate(self):
        if self.if_ids and self.SIFUNC.value not in (0, 2):
            raise ValueError(f"unsupported SIFUNC {self.SIFUNC.value} (0|2)")

    def device_slot(self, pname):
        if pname.startswith("IFUNC"):
            return "IFUNC", self.if_ids.index(int(pname[5:]))
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        nodes = np.array([getattr(self, f"IFUNC{i}").value for i in self.if_ids],
                         dtype=np.float64)
        # params stay in if_ids order (device_slot indexes that order);
        # a static sort permutation orders nodes by MJD on device
        params0["IFUNC"] = nodes[:, 1] if len(nodes) else np.zeros(0)
        order = np.argsort(nodes[:, 0]) if len(nodes) else np.arange(0)
        prep["ifunc_sortidx"] = jnp.asarray(order, dtype=jnp.int32)
        mjds = nodes[order, 0] if len(nodes) else np.zeros(0)
        prep["ifunc_mjd"] = jnp.asarray(mjds)
        t = toas.tdb.day.astype(np.float64) + toas.tdb.sec / SECS_PER_DAY
        prep["ifunc_t"] = jnp.asarray(t)
        prep["ifunc_mode"] = int(self.SIFUNC.value or 2)

    def phase(self, params, batch, prep, delay_total):
        import jax.numpy as jnp

        if params["IFUNC"].shape[0] == 0:
            return jnp.zeros_like(prep["ifunc_t"])
        vals = params["IFUNC"][prep["ifunc_sortidx"]]
        x = prep["ifunc_mjd"]
        t = prep["ifunc_t"]
        if prep["ifunc_mode"] == 0:
            idx = jnp.clip(jnp.searchsorted(x, t) - 1, 0, vals.shape[0] - 1)
            off_s = vals[idx]
        else:
            # linear interpolation, clamped at the ends
            j = jnp.clip(jnp.searchsorted(x, t), 1, vals.shape[0] - 1)
            x0, x1 = x[j - 1], x[j]
            w = jnp.clip((t - x0) / jnp.where(x1 > x0, x1 - x0, 1.0), 0.0, 1.0)
            off_s = (1.0 - w) * vals[j - 1] + w * vals[j]
        return params["F"][0] * off_s
