"""Dispersion delay components.

(reference: src/pint/models/dispersion_model.py — Dispersion base with
dispersion_time_delay = DMconst*DM/freq^2, DispersionDM (DM Taylor
series at DMEPOCH), DispersionDMX (piecewise-constant windows
DMX_####/DMXR1_####/DMXR2_####).)
"""

from __future__ import annotations

import numpy as np

from ..constants import DMconst, SECS_PER_DAY
from .parameter import MJDParameter, prefixParameter
from .timing_model import DelayComponent, MissingParameter


class DispersionDM(DelayComponent):
    category = "dispersion"
    order = 30

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("DM", "DM", 0, units="pc cm^-3",
                                       description="Dispersion measure"))
        self.add_param(MJDParameter("DMEPOCH", units="MJD",
                                    description="Epoch of DM measurement"))

    def validate(self):
        if self.DM.value is None:
            raise MissingParameter("DispersionDM", "DM")

    def n_terms(self):
        n = 0
        while f"DM{n + 1}" in self.params:
            n += 1
        return n + 1

    def add_dmterm(self, index, value=0.0, frozen=True):
        p = prefixParameter(f"DM{index}", "DM", index,
                            units=f"pc cm^-3/yr^{index}", frozen=frozen)
        p.value = value
        self.add_param(p)

    def device_slot(self, pname):
        if pname == "DM":
            return "DM", 0
        return "DM", int(pname[2:])

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        vals = np.array([getattr(self, f"DM{i}" if i else "DM").value or 0.0
                         for i in range(self.n_terms())], dtype=np.float64)
        params0["DM"] = vals
        de = self.DMEPOCH
        if de is not None and de.day is not None:
            day, sec = de.day, de.sec
        else:
            day, sec = prep["pepoch_day"], prep["pepoch_sec"]
        dt = ((toas.tdb.day - day).astype(np.float64) * SECS_PER_DAY
              + (toas.tdb.sec - sec))
        prep["dmepoch_dt"] = jnp.asarray(dt)

    def dm_value(self, params, prep):
        """DM(t) Taylor series [pc/cm^3].

        DM1, DM2, ... follow the par-file convention pc cm^-3 / yr^i
        (reference: dispersion_model.py DM derivative units), so the
        Taylor expansion runs in Julian years since DMEPOCH.
        """
        from ..constants import SECS_PER_JULIAN_YEAR

        dm = params["DM"]
        dt = prep["dmepoch_dt"] / SECS_PER_JULIAN_YEAR
        out = 0.0 * dt
        fact = 1.0
        tp = 1.0
        for i in range(dm.shape[0]):
            if i > 0:
                fact *= i
            out = out + dm[i] * tp / fact
            tp = tp * dt
        return out

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm = self.dm_value(params, prep)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm / f2, 0.0)


class DispersionDMX(DelayComponent):
    """Piecewise-constant DM offsets (reference: DispersionDMX)."""

    category = "dispersion_dmx"
    order = 31

    def __init__(self):
        super().__init__()
        from .parameter import floatParameter

        # bare "DMX <days>" par line: legacy tempo DMX epoch-bin width;
        # carried for round-trip fidelity, not used in the delay
        # (reference: dispersion_model.py DMX parameter)
        self.add_param(floatParameter(
            "DMX", units="d",
            description="legacy DMX bin width marker (unused in delay)"))
        self.dmx_ids: list[int] = []

    def validate(self):
        super().validate()
        # DMX (the bare bin-width marker) has no device slot; a fit
        # flag on it would crash prepare() with a KeyError — freeze it
        # loudly instead
        if not self.DMX.frozen:
            import warnings

            warnings.warn("bare DMX is a legacy bin-width marker, not a "
                          "fittable parameter; freezing it")
            self.DMX.frozen = True
        # a missing DMXR1/DMXR2 pair parses as the empty window [0, 0]
        # -> identically-zero design column, silently degenerate fit
        # (reference behavior: MissingParameter)
        windows = []
        for i in self.dmx_ids:
            r1 = getattr(self, f"DMXR1_{i:04d}").value
            r2 = getattr(self, f"DMXR2_{i:04d}").value
            if r1 is None or r2 is None or not r1 < r2:
                raise MissingParameter(
                    "DispersionDMX", f"DMXR1_{i:04d}/DMXR2_{i:04d}",
                    f"DMX_{i:04d} needs a non-empty MJD window "
                    f"(got [{r1}, {r2}])")
            windows.append((r1, r2, i))
        # overlapping windows apply ADDITIVELY to shared TOAs (the
        # delay sums the per-window offsets) — usually a par-file
        # mistake (upstream tempo convention is disjoint bins), so say
        # so once instead of fitting a silently-degenerate pair
        windows.sort()
        for (a1, a2, ia), (b1, b2, ib) in zip(windows, windows[1:]):
            if b1 < a2:
                import warnings

                warnings.warn(
                    f"DMX windows DMX_{ia:04d} [{a1}, {a2}] and "
                    f"DMX_{ib:04d} [{b1}, {b2}] overlap; both offsets "
                    "apply additively to TOAs in the overlap")
                break

    def add_dmx_range(self, index, mjd_start, mjd_end, value=0.0, frozen=True):
        from .parameter import floatParameter

        p = prefixParameter(f"DMX_{index:04d}", "DMX_", index,
                            units="pc cm^-3", frozen=frozen)
        p.value = value
        self.add_param(p)
        r1 = MJDParameter(f"DMXR1_{index:04d}", units="MJD")
        r1.set_mjd(int(mjd_start), (mjd_start % 1) * SECS_PER_DAY)
        self.add_param(r1)
        r2 = MJDParameter(f"DMXR2_{index:04d}", units="MJD")
        r2.set_mjd(int(mjd_end), (mjd_end % 1) * SECS_PER_DAY)
        self.add_param(r2)
        self.dmx_ids.append(index)

    def device_slot(self, pname):
        if pname.startswith("DMX_"):
            return "DMX", self.dmx_ids.index(int(pname[4:]))
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        vals = np.array([getattr(self, f"DMX_{i:04d}").value or 0.0
                         for i in self.dmx_ids], dtype=np.float64)
        params0["DMX"] = vals
        mjds = toas.get_mjds()
        masks = np.zeros((len(self.dmx_ids), len(toas)))
        for k, i in enumerate(self.dmx_ids):
            lo = getattr(self, f"DMXR1_{i:04d}").value
            hi = getattr(self, f"DMXR2_{i:04d}").value
            masks[k] = (mjds >= lo) & (mjds <= hi)
        # windows are inclusive on BOTH ends (upstream convention), so
        # a TOA at the exact shared boundary of abutting bins lands in
        # two masks and gets both offsets — validate()'s strict-overlap
        # warning can't see that (it has no TOAs); report it exactly here
        multi = masks.sum(axis=0) > 1
        if multi.any():
            import warnings

            warnings.warn(
                f"{int(multi.sum())} TOA(s) fall inside more than one "
                "DMX window (inclusive boundaries); the window offsets "
                "apply additively to them")
        prep["dmx_masks"] = jnp.asarray(masks)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm_per_toa = params["DMX"] @ prep["dmx_masks"]
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm_per_toa / f2, 0.0)


class DispersionJump(DelayComponent):
    """DMJUMP: per-subset DM offsets applied to wideband DM
    measurements ONLY — no TOA delay contribution (reference:
    src/pint/models/dispersion_model.py::DispersionJump, the wideband
    analog of JUMP: receiver-dependent offsets in the measured DMs).

    Sign matches the reference's jump_dm: the jump enters the model DM
    negated (dm_model - DMJUMP over each mask), so fitted DMJUMP values
    interchange with reference par files (see
    residuals.py::wideband_dm_model). A FREE DMJUMP is meaningful only
    to wideband fitters; narrowband fitters reject it loudly rather
    than reporting a zero-uncertainty no-op fit.
    """

    category = "dispersion_jump"
    order = 31

    def __init__(self):
        super().__init__()
        self.dmjump_ids: list[int] = []

    def add_dmjump(self, key="", key_value=(), value=0.0, frozen=False,
                   index=None):
        from .parameter import maskParameter

        index = index if index is not None else len(self.dmjump_ids) + 1
        p = maskParameter(f"DMJUMP{index}", "DMJUMP", index,
                          units="pc cm^-3", frozen=frozen)
        p.key = key
        p.key_value = list(key_value)
        p.value = value
        self.add_param(p)
        self.dmjump_ids.append(index)
        return p

    def device_slot(self, pname):
        return "DMJUMP", self.dmjump_ids.index(int(pname[6:]))

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        if not self.dmjump_ids:
            params0["DMJUMP"] = np.zeros(0)
            prep["dmjump_masks"] = jnp.zeros((0, len(toas)))
            return
        vals = np.array([getattr(self, f"DMJUMP{i}").value or 0.0
                         for i in self.dmjump_ids])
        params0["DMJUMP"] = vals
        masks = np.stack([getattr(self, f"DMJUMP{i}").resolve_mask(toas)
                          for i in self.dmjump_ids]).astype(np.float64)
        prep["dmjump_masks"] = jnp.asarray(masks)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        return jnp.zeros_like(batch.tdb_sec)
