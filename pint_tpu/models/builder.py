"""Par file -> TimingModel construction.

(reference: src/pint/models/model_builder.py — ModelBuilder /
AllComponents: parse par lines, resolve aliases, choose components from
content (BINARY line, DMX_* -> DispersionDMX, GLEP_* -> Glitch, ...),
report unrecognized lines.)
"""

from __future__ import annotations

import io
import os
import re
import warnings

from ..utils import interesting_lines, split_prefixed_name
from .parameter import strParameter, floatParameter, MJDParameter, maskParameter
from .timing_model import TimingModel
from .spindown import Spindown
from .astrometry import AstrometryEquatorial, AstrometryEcliptic
from .dispersion import DispersionDM, DispersionDMX
from .solar_system_shapiro import SolarSystemShapiro
from .jump import PhaseJump

# par-file key aliases -> canonical names (reference: each Parameter's aliases)
ALIASES = {
    "E": "ECC", "PSRJ": "PSR", "PSRB": "PSR", "DEC": "DECJ", "RA": "RAJ",
    "LAMBDA": "ELONG", "BETA": "ELAT", "PMLAMBDA": "PMELONG", "PMBETA": "PMELAT",
    "CLK": "CLOCK", "T2EFAC": "EFAC", "T2EQUAD": "EQUAD", "NE1AU": "NE_SW",
    "SOLARN0": "NE_SW",
    # temponest spellings (reference: noise_model.py aliases; TNEQ and
    # TNGlobalEQ carry log10-second values, converted on read below;
    # TNGlobalEF is a plain all-TOA EFAC — the selector-less mask line
    # parses to the all-TOA mask already, so an alias suffices)
    "TNEF": "EFAC", "TNECORR": "ECORR", "TNGLOBALEF": "EFAC",
}

# FD1JUMP (canonical, reference: fdjump.py) or FDJUMP1 (tempo2 alias);
# order 0 (a constant jump) is not a valid FD term and falls through to
# the unrecognized-line report
_FDJUMP_RE = re.compile(r"^FD([1-9]\d*)JUMP$|^FDJUMP([1-9]\d*)$")

TOP_LEVEL_STR = ("PSR", "EPHEM", "CLOCK", "UNITS", "TIMEEPH", "T2CMETHOD",
                 "TZRSITE", "INFO", "DCOVFILE", "TRACK", "MODE", "EPHVER",
                 "DMDATA", "NITS", "IBOOT", "DILATEFREQ")
TOP_LEVEL_FLOAT = ("NTOA", "TRES", "TZRFRQ", "DMRES", "CHI2", "CHI2R")
TOP_LEVEL_MJD = ("START", "FINISH", "TZRMJD")


def parse_parfile(parfile) -> list[tuple[str, list[str]]]:
    """par file path or content string -> [(KEY, fields)] preserving order."""
    if isinstance(parfile, str) and ("\n" in parfile or not os.path.exists(parfile)):
        if "\n" not in parfile and not os.path.exists(parfile):
            raise FileNotFoundError(parfile)
        fh = io.StringIO(parfile)
    else:
        fh = open(parfile)
    out = []
    with fh:
        for line in interesting_lines(fh, comments=("#", "C ", "c ")):
            parts = line.split()
            out.append((parts[0].upper(), parts[1:]))
    return out


def get_model(parfile, allow_name_mixing=False, allow_tcb=False) -> TimingModel:
    """(reference: model_builder.py::get_model)

    ``allow_tcb``: a par file with UNITS TCB raises by default (the
    framework computes in TDB); ``True`` converts it to TDB on load
    with a warning; ``"raw"`` keeps the TCB values untouched
    (reference: model_builder.py allow_tcb semantics).
    """
    entries = parse_parfile(parfile)
    keys = {}
    repeats = []
    for k, fields in entries:
        canon = ALIASES.get(k, k)
        # FDJUMP3 (tempo2 spelling) -> FD3JUMP (canonical)
        m_fdj = _FDJUMP_RE.match(canon)
        if m_fdj:
            canon = f"FD{m_fdj.group(1) or m_fdj.group(2)}JUMP"
        if canon in ("JUMP", "EFAC", "EQUAD", "ECORR", "DMEFAC", "DMEQUAD",
                     "DMJUMP", "TNEQ", "TNGLOBALEQ") or m_fdj:
            repeats.append((canon, fields))
        else:
            keys[canon] = fields

    # tempo1-style P0/P1 spin parameterization -> F0/F1 (with
    # uncertainty propagation; reference analog: utils.py::p_to_f —
    # upstream requires F0, but P0 par files are common in old archives)
    if "P0" in keys and "F0" not in keys:
        def _vfu(fields):
            val = float(fields[0])
            fit, unc = "0", None
            rest = list(fields[1:])
            if rest and rest[0] in ("0", "1"):
                fit = rest.pop(0)
            if rest:
                unc = float(rest[0])
            return val, fit, unc

        from ..utils import p_to_f, pferrs

        p0, fit0, u0 = _vfu(keys.pop("P0"))
        keys["F0"] = [repr(1.0 / p0), fit0] + (
            [repr(u0 / p0**2)] if u0 is not None else [])
        had_p1 = "P1" in keys
        p1, fit1, u1 = (_vfu(keys.pop("P1")) if had_p1
                        else (0.0, "0", None))
        if had_p1 or "P2" in keys:
            keys["F1"] = [repr(-p1 / p0**2), fit1]
            if u0 is not None or u1 is not None:
                _, _, _, f1err = pferrs(p0, u0 or 0.0, p1, u1 or 0.0)
                keys["F1"].append(repr(f1err))
        if "P2" in keys:
            p2, fit2, u2 = _vfu(keys.pop("P2"))
            f2 = p_to_f(p0, p1, p2)[2]
            keys["F2"] = [repr(f2), fit2] + (
                [repr(u2 / p0**2)] if u2 is not None else [])
        warnings.warn("converted P0/P1/P2 spin parameters to F0/F1/F2")

    model = TimingModel(name=str(parfile) if isinstance(parfile, (str, os.PathLike)) else "")
    unrecognized = {}

    # --- component selection ---
    model.add_component(Spindown())
    if "RAJ" in keys or "DECJ" in keys:
        model.add_component(AstrometryEquatorial())
    elif "ELONG" in keys or "ELAT" in keys:
        model.add_component(AstrometryEcliptic())
    if "DM" in keys or "DM1" in keys:
        model.add_component(DispersionDM())
    if "DMX" in keys or any(k.startswith("DMX_") for k in keys):
        model.add_component(DispersionDMX())
    model.add_component(SolarSystemShapiro())
    has_tnsw = any(k.startswith("TNSW") for k in keys)
    if "NE_SW" in keys or "SWM" in keys or has_tnsw:
        from .solar_wind import SolarWindDispersion

        model.add_component(SolarWindDispersion())
    if has_tnsw:
        from .noise import PLSWNoise

        model.add_component(PLSWNoise())
    if "CORRECT_TROPOSPHERE" in keys:
        from .troposphere import TroposphereDelay

        model.add_component(TroposphereDelay())
    if any(k.startswith("GLEP_") for k in keys):
        from .glitch import Glitch

        model.add_component(Glitch())
    if "WAVE_OM" in keys or any(k.startswith("WAVE") and k[4:].isdigit() for k in keys):
        from .wave import Wave

        model.add_component(Wave())
    if any(k.startswith("WXFREQ_") for k in keys):
        from .wave import WaveX

        model.add_component(WaveX())
    if any(k.startswith("FD") and k[2:].isdigit() for k in keys):
        from .frequency_dependent import FD

        model.add_component(FD())
    if "SIFUNC" in keys or any(k.startswith("IFUNC") and k[5:].isdigit() for k in keys):
        from .ifunc import IFunc

        model.add_component(IFunc())
    if "PHOFF" in keys:
        from .phase_offset import PhaseOffset

        model.add_component(PhaseOffset())
    if any(c == "JUMP" for c, _ in repeats):
        model.add_component(PhaseJump())
    if any(_FDJUMP_RE.match(c) for c, _ in repeats):
        from .frequency_dependent import FDJump

        model.add_component(FDJump())
    if any(c == "DMJUMP" for c, _ in repeats):
        from .dispersion import DispersionJump

        model.add_component(DispersionJump())
    if "BINARY" in keys:
        if keys["BINARY"][0].upper() == "T2":
            # tempo2's universal container: pick the concrete model
            # from the PAR keys present (valid only here, where keys
            # really are par-file keys — programmatic convert_binary
            # targets still reject 'T2')
            from .binary import choose_t2_model

            chosen = choose_t2_model(set(keys))
            warnings.warn(
                f"BINARY T2 is a tempo2 container model; selected "
                f"BINARY {chosen} from the parameters present (persist "
                f"the choice with scripts/t2binary2pint.py)")
            keys["BINARY"] = [chosen]
        from .binary import add_binary_component

        add_binary_component(model, keys["BINARY"][0], keys)
    if "TZRMJD" in keys:
        from .absolute_phase import AbsPhase

        model.add_component(AbsPhase())
    if any(c in ("EFAC", "EQUAD", "ECORR", "DMEFAC", "DMEQUAD", "TNEQ",
                 "TNGLOBALEQ") for c, _ in repeats) or any(
            k.startswith(("RNAMP", "RNIDX", "TNRED", "TNDM")) for k in keys):
        from .noise import ScaleToaError, EcorrNoise, PLRedNoise, PLDMNoise

        if any(c in ("EFAC", "EQUAD", "DMEFAC", "DMEQUAD", "TNEQ",
                     "TNGLOBALEQ") for c, _ in repeats):
            model.add_component(ScaleToaError())
        if any(c == "ECORR" for c, _ in repeats):
            model.add_component(EcorrNoise())
        if any(k.startswith(("RNAMP", "RNIDX", "TNRED")) for k in keys):
            model.add_component(PLRedNoise())
        if any(k.startswith("TNDM") for k in keys):
            model.add_component(PLDMNoise())
    if any(k.startswith("DMWXFREQ_") for k in keys):
        from .wave import DMWaveX

        model.add_component(DMWaveX())
    if ("CM" in keys or "CM1" in keys or "TNCHROMIDX" in keys
            or any(k.startswith(("CMX_", "CMWXFREQ_")) for k in keys)
            or any(k in ("TNCHROMAMP", "TNCHROMGAM", "TNCHROMC")
                   for k in keys)):
        from .chromatic import ChromaticCM, ChromaticCMX, CMWaveX

        # ChromaticCM always rides along: it owns TNCHROMIDX, the one
        # home of the chromatic index that CMX/CMWaveX/PLChromNoise read
        cm_comp = ChromaticCM()
        if "CM" not in keys:
            cm_comp.CM.value = 0.0
        model.add_component(cm_comp)
        if any(k.startswith("CMX_") for k in keys):
            model.add_component(ChromaticCMX())
        if any(k.startswith("CMWXFREQ_") for k in keys):
            model.add_component(CMWaveX())
    if any(k in ("TNCHROMAMP", "TNCHROMGAM", "TNCHROMC") for k in keys):
        from .noise import PLChromNoise

        model.add_component(PLChromNoise())
    if any(k.startswith(("SWXDM_", "SWX_")) for k in keys):
        # SWX_#### is the tempo2 value spelling for SWXDM_#### (the
        # "SWX_" test requires the underscore right after SWX, so
        # SWXP_/SWXR1_/SWXR2_ never match it)
        from .solar_wind import SolarWindDispersionX

        # replaces the plain solar-wind component when both would match
        if "SolarWindDispersion" in model.components:
            model.remove_component("SolarWindDispersion")
        model.add_component(SolarWindDispersionX())
    if any(k.startswith("PWEP_") for k in keys):
        from .piecewise import PiecewiseSpindown

        model.add_component(PiecewiseSpindown())

    # dynamic prefix families before value assignment
    sd = model.components["Spindown"]
    i = 1
    while f"F{i}" in keys:
        sd.add_fterm(i)
        i += 1
    if "DispersionDM" in model.components:
        dd = model.components["DispersionDM"]
        i = 1
        while f"DM{i}" in keys:
            dd.add_dmterm(i)
            i += 1
    if "Glitch" in model.components:
        gl = model.components["Glitch"]
        ids = sorted({int(k.split("_")[1]) for k in keys if k.startswith("GLEP_")})
        for idx in ids:
            gl.add_glitch(idx)
    if "Wave" in model.components:
        wv = model.components["Wave"]
        i = 1
        while f"WAVE{i}" in keys:
            wv.add_wave(i)
            i += 1
    if "WaveX" in model.components:
        wx = model.components["WaveX"]
        ids = sorted({int(k.split("_")[1]) for k in keys if k.startswith("WXFREQ_")})
        for idx in ids:
            wx.add_wavex(idx)
    if "DMWaveX" in model.components:
        dwx = model.components["DMWaveX"]
        ids = sorted({int(k.split("_")[1]) for k in keys
                      if k.startswith("DMWXFREQ_")})
        for idx in ids:
            dwx.add_dmwavex(idx)
    if "ChromaticCM" in model.components:
        cmc = model.components["ChromaticCM"]
        i = 1
        while f"CM{i}" in keys:
            cmc.add_cmterm(i)
            i += 1
    if "ChromaticCMX" in model.components:
        cx = model.components["ChromaticCMX"]
        ids = sorted({int(k.split("_")[1]) for k in keys
                      if k.startswith("CMX_")})
        for idx in ids:
            lo = float(keys.get(f"CMXR1_{idx:04d}", ["0"])[0])
            hi = float(keys.get(f"CMXR2_{idx:04d}", ["0"])[0])
            cx.add_cmx_range(idx, lo, hi)
    if "CMWaveX" in model.components:
        cwx = model.components["CMWaveX"]
        ids = sorted({int(k.split("_")[1]) for k in keys
                      if k.startswith("CMWXFREQ_")})
        for idx in ids:
            cwx.add_cmwavex(idx)
    if "SolarWindDispersionX" in model.components:
        swx = model.components["SolarWindDispersionX"]
        # SWX_#### (tempo2 value spelling, aliased to SWXDM_####) must
        # create the window too, or its R1/R2 companions fall through
        # to `unrecognized` (found by the fuzz, VERDICT r3 weak 5)
        ids = sorted({int(k.split("_")[1]) for k in keys
                      if k.startswith(("SWXDM_", "SWX_"))})
        for idx in ids:
            lo = float(keys.get(f"SWXR1_{idx:04d}", ["0"])[0])
            hi = float(keys.get(f"SWXR2_{idx:04d}", ["0"])[0])
            p = float(keys.get(f"SWXP_{idx:04d}", ["2"])[0])
            swx.add_swx_range(idx, lo, hi, p=p)
    if "PiecewiseSpindown" in model.components:
        pw = model.components["PiecewiseSpindown"]
        ids = sorted({int(k.split("_")[1]) for k in keys
                      if k.startswith("PWEP_")})
        for idx in ids:
            pw.add_segment(idx)
    if "FD" in model.components:
        fd = model.components["FD"]
        i = 1
        while f"FD{i}" in keys:
            fd.add_fd(i)
            i += 1
    if "IFunc" in model.components:
        ifc = model.components["IFunc"]
        i = 1
        while f"IFUNC{i}" in keys:
            ifc.add_ifunc(i)
            i += 1
    if "DispersionDMX" in model.components:
        dx = model.components["DispersionDMX"]
        ids = sorted({split_prefixed_name(k)[1] for k in keys if k.startswith("DMX_")})
        for idx in ids:
            lo = float(keys.get(f"DMXR1_{idx:04d}", ["0"])[0])
            hi = float(keys.get(f"DMXR2_{idx:04d}", ["0"])[0])
            dx.add_dmx_range(idx, lo, hi)

    # --- assign values ---
    param_index = {}
    for comp in model.components.values():
        for pname in comp.params:
            par = getattr(comp, pname)
            param_index[pname.upper()] = par
            for a in par.aliases:
                param_index[a.upper()] = par

    for key, fields in keys.items():
        if key in ("BINARY",):
            continue
        if key in TOP_LEVEL_STR:
            p = strParameter(key)
            p.value = fields[0] if fields else ""
            model.add_top_param(p)
        elif key in TOP_LEVEL_FLOAT:
            p = floatParameter(key)
            if fields:
                p.from_parfile_fields(fields)
            model.add_top_param(p)
        elif key in TOP_LEVEL_MJD:
            p = MJDParameter(key)
            if fields:
                p.from_parfile_fields(fields)
            model.add_top_param(p)
        elif key == "PLANET_SHAPIRO":
            model.PLANET_SHAPIRO.from_parfile_fields(fields)
        elif key in param_index:
            try:
                param_index[key].from_parfile_fields(fields)
            except (ValueError, IndexError) as e:
                warnings.warn(f"bad par line {key} {fields}: {e}")
        else:
            unrecognized[key] = fields

    # --- repeated mask parameters ---
    jump_comp = model.components.get("PhaseJump")
    dmjump_comp = model.components.get("DispersionJump")
    noise_comp = model.components.get("ScaleToaError")
    ecorr_comp = model.components.get("EcorrNoise")
    fdjump_comp = model.components.get("FDJump")
    for canon, fields in repeats:
        # canon is already canonical FD<n>JUMP here (first loop rewrites
        # the FDJUMP<n> spelling), so only group(1) can match
        m_fdj = _FDJUMP_RE.match(canon)
        if m_fdj and fdjump_comp is not None:
            p = fdjump_comp.add_fdjump(int(m_fdj.group(1)))
            p.from_parfile_fields(fields)
        elif canon == "JUMP" and jump_comp is not None:
            p = jump_comp.add_jump()
            p.from_parfile_fields(fields)
        elif canon == "DMJUMP" and dmjump_comp is not None:
            p = dmjump_comp.add_dmjump()
            p.from_parfile_fields(fields)
        elif canon in ("EFAC", "EQUAD", "DMEFAC", "DMEQUAD") and noise_comp is not None:
            noise_comp.add_mask_param(canon, fields)
        elif canon in ("TNEQ", "TNGLOBALEQ") and noise_comp is not None:
            # temponest EQUAD: log10(equad / s) -> us
            import math

            p = noise_comp.add_mask_param("EQUAD", fields)
            if p.value is not None:
                v = p.value
                p.value = 10.0**v * 1e6
                if p.uncertainty is not None:
                    p.uncertainty = math.log(10.0) * p.value * p.uncertainty
        elif canon == "ECORR" and ecorr_comp is not None:
            ecorr_comp.add_mask_param(fields)

    model.unrecognized = unrecognized
    if unrecognized:
        warnings.warn(f"unrecognized par lines: {sorted(unrecognized)}")
    model.setup()
    model.validate()
    units = ((model.UNITS.value or "").upper()
             if "UNITS" in model.params else "")
    if units in ("TCB", "SI"):  # tempo2 'UNITS SI' = TCB timescale
        if allow_tcb == "raw":
            pass
        elif allow_tcb:
            warnings.warn("par file is in TCB units; converting to TDB "
                          "on load (reference: model_builder.py allow_tcb)")
            from .tcb_conversion import convert_tcb_tdb

            convert_tcb_tdb(model)
        else:
            raise ValueError(
                "par file has UNITS TCB but the framework computes in "
                "TDB. Pass allow_tcb=True to convert on load, "
                "allow_tcb='raw' to keep TCB values, or convert the "
                "file with the tcb2tdb script.")
    elif units not in ("", "TDB"):
        raise ValueError(f"unrecognized UNITS {units!r} in par file "
                         "(expected TDB, TCB, or SI)")
    return model


def get_model_and_toas(parfile, timfile, allow_tcb=False, **kw):
    """(reference: model_builder.py::get_model_and_toas)"""
    from ..toa import get_TOAs

    model = get_model(parfile, allow_tcb=allow_tcb)
    ephem = "de440s"
    if "EPHEM" in model.params and model.EPHEM.value:
        ephem = model.EPHEM.value.lower()
    planets = bool(model.PLANET_SHAPIRO.value) if "PLANET_SHAPIRO" in model.params else False
    toas = get_TOAs(timfile, ephem=ephem, planets=planets, **kw)
    return model, toas
