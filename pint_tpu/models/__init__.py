from .builder import get_model, get_model_and_toas  # noqa: F401
from .timing_model import TimingModel, Component  # noqa: F401
