"""Phase jumps over TOA subsets.

(reference: src/pint/models/jump.py::PhaseJump — JUMP maskParameters;
jump_phase = -F0 * JUMP over the selected TOAs.)
"""

from __future__ import annotations

from .parameter import maskParameter, pack_mask_values
from .timing_model import DelayComponent, PhaseComponent


class PhaseJump(PhaseComponent):
    category = "phase_jump"
    order = 40

    def __init__(self):
        super().__init__()
        self.jump_ids: list[int] = []

    def add_jump(self, key="", key_value=(), value=0.0, frozen=False, index=None):
        index = index if index is not None else len(self.jump_ids) + 1
        p = maskParameter(f"JUMP{index}", "JUMP", index, units="s", frozen=frozen)
        p.key = key
        p.key_value = list(key_value)
        p.value = value
        self.add_param(p)
        self.jump_ids.append(index)
        return p

    def device_slot(self, pname):
        return "JUMP", self.jump_ids.index(int(pname[4:]))

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        vals, masks = pack_mask_values(
            self, [f"JUMP{i}" for i in self.jump_ids], toas)
        params0["JUMP"] = vals
        prep["jump_masks"] = jnp.asarray(masks)

    def phase(self, params, batch, prep, delay_total):
        # jump in seconds of time; phase shift = -F0 * jump on masked TOAs
        jump_per_toa = params["JUMP"] @ prep["jump_masks"]
        return -params["F"][0] * jump_per_toa


class DelayJump(DelayComponent):
    """Per-subset constant time offsets applied as delays
    (reference: jump.py::DelayJump — rare; tempo2 'JUMP' semantics)."""

    category = "delay_jump"
    order = 45

    def __init__(self):
        super().__init__()
        self.jump_ids: list[int] = []

    def add_jump(self, key="", key_value=(), value=0.0, frozen=False, index=None):
        index = index if index is not None else len(self.jump_ids) + 1
        p = maskParameter(f"DJUMP{index}", "DJUMP", index, units="s", frozen=frozen)
        p.key = key
        p.key_value = list(key_value)
        p.value = value
        self.add_param(p)
        self.jump_ids.append(index)
        return p

    def device_slot(self, pname):
        return "DJUMP", self.jump_ids.index(int(pname[5:]))

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        vals, masks = pack_mask_values(
            self, [f"DJUMP{i}" for i in self.jump_ids], toas)
        params0["DJUMP"] = vals
        prep["djump_masks"] = jnp.asarray(masks)

    def delay(self, params, batch, prep, delay_accum):
        return params["DJUMP"] @ prep["djump_masks"]


def jump_flags_to_params(toas, model) -> list[str]:
    """Create one free JUMP parameter per distinct tim-file JUMP block
    (reference: jump.py::PhaseJump tim-jump handling — tim JUMP
    commands mark TOAs with -tim_jump N flags; this turns each group
    into a fittable JUMP maskParameter). Returns the new param names;
    groups that already have a matching JUMP are skipped.
    """
    values = sorted({f["tim_jump"] for f in toas.flags if "tim_jump" in f},
                    key=lambda v: (len(v), v))
    if not values:
        return []
    if "PhaseJump" not in model.components:
        model.add_component(PhaseJump())
    comp = model.components["PhaseJump"]
    existing = {tuple(getattr(comp, p).key_value)
                for p in comp.params
                if getattr(comp, p).key == "-tim_jump"}
    created = []
    for v in values:
        if (v,) in existing:
            continue
        p = comp.add_jump(key="-tim_jump", key_value=[v], value=0.0,
                          frozen=False)
        created.append(p.name)
    return created
