"""Parameter system: par-file metadata on host, flat pytree on device.

TPU-native re-design of the reference's parameter layer
(reference: src/pint/models/parameter.py — Parameter, floatParameter,
MJDParameter, AngleParameter, prefixParameter, maskParameter, and
toa_select.py::TOASelect).

Key architectural difference from the reference: Parameter objects are
*host-only metadata* (name, units, free/frozen, aliases, par-file
formatting). The device never sees them — ``TimingModel.prepare``
flattens free/frozen values into a ``{name: f64}`` pytree and resolves
every maskParameter into a static boolean mask over the TOABatch, so
one jitted kernel serves any parameter values without retracing.
"""

from __future__ import annotations

import numpy as np

from ..mjd import LD, parse_mjd_string, format_mjd
from ..constants import SECS_PER_DAY

_D2R = np.pi / 180.0


def _parse_fit_and_unc(fields):
    """Par-file line tail: [fit-flag] [uncertainty]."""
    frozen = True
    unc = None
    if len(fields) >= 1:
        if fields[0] in ("1", "2"):
            frozen = False
            if len(fields) >= 2:
                unc = fields[1]
        elif fields[0] == "0":
            if len(fields) >= 2:
                unc = fields[1]
        else:
            unc = fields[0]
    return frozen, unc


def _float(s):
    return float(str(s).replace("D", "e").replace("d", "e"))


class Parameter:
    """Base parameter (reference: parameter.py::Parameter).

    value       — float in natural par-file units (device-facing)
    uncertainty — same units, or None
    frozen      — True = not fit
    """

    kind = "float"

    def __init__(self, name, value=None, units="", description="", aliases=(),
                 frozen=True, uncertainty=None, continuous=True):
        self.name = name
        self.value = value
        self.units = units
        self.description = description
        self.aliases = tuple(aliases)
        self.frozen = frozen
        self.uncertainty = uncertainty
        self.continuous = continuous
        self._component = None

    @property
    def quantity(self):
        return self.value

    def from_parfile_fields(self, fields):
        self.value = _float(fields[0])
        self.frozen, unc = _parse_fit_and_unc(fields[1:])
        if unc is not None:
            self.uncertainty = _float(unc)

    def as_parfile_line(self):
        if self.value is None:
            return ""
        fit = "0" if self.frozen else "1"
        line = f"{self.name:<15} {self._format_value()}"
        line += f" {fit}"
        if self.uncertainty is not None:
            line += f" {self._format_unc()}"
        return line + "\n"

    def _format_value(self):
        return repr(float(self.value))

    def _format_unc(self):
        return f"{float(self.uncertainty):.5g}"

    def set_fitted_value(self, v):
        """Write a fitted device-vector entry back (same units as
        ``.value``; overridden where the device layout differs)."""
        self.value = v

    def __repr__(self):
        state = "frozen" if self.frozen else "free"
        return f"<{type(self).__name__} {self.name}={self.value} ({state})>"


class floatParameter(Parameter):
    pass


class intParameter(Parameter):
    kind = "int"

    def from_parfile_fields(self, fields):
        self.value = int(float(fields[0]))

    def _format_value(self):
        return str(int(self.value))


class boolParameter(Parameter):
    kind = "bool"

    def from_parfile_fields(self, fields):
        s = str(fields[0]).upper()
        self.value = s in ("1", "Y", "YES", "T", "TRUE")

    def _format_value(self):
        return "Y" if self.value else "N"


class strParameter(Parameter):
    kind = "str"

    def from_parfile_fields(self, fields):
        self.value = fields[0]

    def as_parfile_line(self):
        if self.value is None:
            return ""
        return f"{self.name:<15} {self.value}\n"


class MJDParameter(Parameter):
    """Epoch parameter held as exact (day, sec) (reference: MJDParameter).

    ``.value`` is float MJD; assigning it (e.g. from a fitter update)
    re-derives ``.day``/``.sec``, which keep full precision when set
    via ``from_parfile_fields``/``set_mjd``.
    """

    kind = "mjd"

    def __init__(self, *a, **kw):
        self.day = None
        self.sec = None
        super().__init__(*a, **kw)

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, v):
        self._value = v
        if v is not None:
            day = int(np.floor(v))
            self.day = day
            self.sec = (v - day) * SECS_PER_DAY

    def from_parfile_fields(self, fields):
        self.day, self.sec = parse_mjd_string(fields[0])
        self._value = self.day + self.sec / SECS_PER_DAY
        self.frozen, unc = _parse_fit_and_unc(fields[1:])
        if unc is not None:
            self.uncertainty = _float(unc)

    def set_mjd(self, day, sec):
        self.day, self.sec = int(day), float(sec)
        self._value = self.day + self.sec / SECS_PER_DAY

    def _format_value(self):
        return format_mjd(self.day, self.sec, 11)


class AngleParameter(Parameter):
    """RA/Dec-style angle (reference: AngleParameter). ``.value`` is radians.

    Par-file representation: 'h:m:s' (units=hourangle) or 'd:m:s'.
    """

    kind = "angle"

    def __init__(self, *a, angle_unit="deg", **kw):
        super().__init__(*a, **kw)
        self.angle_unit = angle_unit

    def from_parfile_fields(self, fields):
        self.value = self._parse_angle(fields[0])
        self.frozen, unc = _parse_fit_and_unc(fields[1:])
        if unc is not None:
            # uncertainty given in seconds (of time or arc)
            scale = 15.0 if self.angle_unit == "hourangle" else 1.0
            self.uncertainty = _float(unc) * scale / 3600.0 * _D2R

    def _parse_angle(self, s):
        s = str(s)
        scale = 15.0 if self.angle_unit == "hourangle" else 1.0
        if ":" in s:
            sign = -1.0 if s.strip().startswith("-") else 1.0
            parts = s.replace("-", "").split(":")
            deg = float(parts[0])
            if len(parts) > 1:
                deg += float(parts[1]) / 60.0
            if len(parts) > 2:
                deg += float(parts[2]) / 3600.0
            return sign * deg * scale * _D2R
        return _float(s) * _D2R  # bare degrees

    def _format_value(self):
        rad = float(self.value)
        scale = 15.0 if self.angle_unit == "hourangle" else 1.0
        total = rad / _D2R / scale
        sign = "-" if total < 0 else ""
        # integer tick arithmetic at the printed resolution so seconds
        # can never print as 60.0 ("1:0:0" used to format as 00:59:60
        # through float truncation)
        ndec = 10
        unit = 10**ndec
        ticks = round(abs(total) * 3600 * unit)
        d, rem = divmod(ticks, 3600 * unit)
        m, s_ticks = divmod(rem, 60 * unit)
        s_int, s_frac = divmod(s_ticks, unit)
        return (f"{sign}{int(d):02d}:{int(m):02d}:"
                f"{int(s_int):02d}.{int(s_frac):0{ndec}d}")


class prefixParameter(floatParameter):
    """One member of a numbered family F0..Fn, DMX_0001.. (reference: prefixParameter)."""

    kind = "prefix"

    def __init__(self, name, prefix, index, **kw):
        super().__init__(name, **kw)
        self.prefix = prefix
        self.index = index


class pairParameter(Parameter):
    """Two-component parameter, e.g. WAVEn 'A B' sin/cos amplitudes
    (reference: parameter.py::pairParameter). ``.value`` is (a, b)."""

    kind = "pair"

    def __init__(self, name, prefix="", index=0, **kw):
        super().__init__(name, **kw)
        self.prefix = prefix
        self.index = index

    def from_parfile_fields(self, fields):
        self.value = (_float(fields[0]), _float(fields[1]))
        if len(fields) > 2:
            self.frozen, unc = _parse_fit_and_unc(fields[2:])
            if unc is not None:
                self.uncertainty = _float(unc)

    def _format_value(self):
        a, b = self.value
        return f"{float(a)!r} {float(b)!r}"

    def set_fitted_value(self, v):
        # device exposes only the amplitude (second element)
        self.value = (self.value[0] if self.value else 0.0, v)

    def as_parfile_line(self):
        if self.value is None:
            return ""
        return f"{self.name:<15} {self._format_value()}\n"


class maskParameter(floatParameter):
    """Parameter applying to a TOA subset (reference: maskParameter).

    Selection spec: (key, key_value) where key is 'flag <name>',
    'mjd', 'freq', 'tel', or '' (all TOAs). ``resolve_mask(toas)``
    evaluates it host-side into a static boolean array — the TPU-native
    stand-in for the reference's TOASelect cache
    (reference: src/pint/toa_select.py::TOASelect).
    """

    kind = "mask"

    def __init__(self, name, prefix, index, **kw):
        super().__init__(name, **kw)
        self.prefix = prefix
        self.index = index
        self.key = ""
        self.key_value: list[str] = []

    @staticmethod
    def _is_flag_token(tok) -> bool:
        # "-f"/"-fe" are flag selectors; "-6.0"/"-1e-5" are negative
        # values (e.g. a selector-less global TNGlobalEQ line)
        t = str(tok)
        return (t.startswith("-") and len(t) > 1
                and not (t[1].isdigit() or t[1] == "."))

    def from_parfile_fields(self, fields):
        # e.g. "EFAC -f L-wide 1.1" parsed from fields after name:
        # [-f, L-wide, 1.1, [fit], [unc]] or "JUMP MJD 55000 55100 1e-6 1"
        if fields and self._is_flag_token(fields[0]):
            self.key = str(fields[0])
            self.key_value = [str(fields[1])]
            rest = fields[2:]
        elif fields and str(fields[0]).lower() in ("mjd", "freq"):
            self.key = str(fields[0]).lower()
            self.key_value = [str(fields[1]), str(fields[2])]
            rest = fields[3:]
        elif fields and str(fields[0]).lower() in ("tel", "obs"):
            self.key = "tel"
            self.key_value = [str(fields[1])]
            rest = fields[2:]
        else:
            self.key = ""
            self.key_value = []
            rest = fields
        if rest:
            self.value = _float(rest[0])
            self.frozen, unc = _parse_fit_and_unc(rest[1:])
            if unc is not None:
                self.uncertainty = _float(unc)

    def resolve_mask(self, toas) -> np.ndarray:
        n = len(toas)
        if self.key == "":
            return np.ones(n, dtype=bool)
        if self.key.startswith("-"):
            flag = self.key[1:]
            vals = toas.get_flag_value(flag)
            return np.array([str(v) == self.key_value[0] for v in vals])
        if self.key == "mjd":
            mjds = toas.get_mjds()
            lo, hi = float(self.key_value[0]), float(self.key_value[1])
            return (mjds >= lo) & (mjds <= hi)
        if self.key == "freq":
            lo, hi = float(self.key_value[0]), float(self.key_value[1])
            return (toas.freq_mhz >= lo) & (toas.freq_mhz <= hi)
        if self.key == "tel":
            return toas.obs.astype(str) == self.key_value[0].lower()
        raise ValueError(f"unsupported mask key {self.key!r}")

    def as_parfile_line(self):
        if self.value is None:
            return ""
        sel = f"{self.key} {' '.join(self.key_value)}".strip()
        fit = "0" if self.frozen else "1"
        line = f"{self.prefix:<8} {sel} {self._format_value()} {fit}"
        if self.uncertainty is not None:
            line += f" {self._format_unc()}"
        return line + "\n"


class funcParameter(floatParameter):
    """Read-only derived parameter computed from other parameters
    (reference: parameter.py::funcParameter *(version-dependent)* —
    e.g. total mass from PB/A1/SINI/M2). Not fittable; ``value``
    evaluates the function on each access."""

    kind = "func"

    def __init__(self, name, func, params, units="", description=""):
        super().__init__(name, units=units, description=description,
                         frozen=True)
        self._func = func
        self._src_params = tuple(params)

    @property
    def value(self):
        if self._component is None or self._component._parent is None:
            return None
        model = self._component._parent
        args = []
        for p in self._src_params:
            par = getattr(model, p, None)
            if par is None or par.value is None:
                return None
            args.append(par.value)
        return self._func(*args)

    @value.setter
    def value(self, v):
        if v is not None:
            raise AttributeError(f"{self.name} is a derived parameter")

    def as_parfile_line(self):
        return ""  # derived values never round-trip into par files


def pack_mask_values(component, names, toas):
    """Shared pack-time evaluation for a component's maskParameter
    slots: returns (values, masks) as float64 arrays of shapes (P,)
    and (P, n_toa). Empty name list gives ((0,), (0, n_toa)) so device
    code can contract unconditionally. Used by PhaseJump/DelayJump/
    FDJump and any future mask-family component."""
    if not names:
        return (np.zeros(0), np.zeros((0, len(toas))))
    vals = np.array([getattr(component, nm).value or 0.0 for nm in names],
                    dtype=np.float64)
    masks = np.stack([getattr(component, nm).resolve_mask(toas)
                      for nm in names]).astype(np.float64)
    return vals, masks
