"""Pulsar glitches: step changes in phase / spin with exponential recovery.

(reference: src/pint/models/glitch.py::Glitch — prefix families
GLEP_n (epoch), GLPH_n (phase step), GLF0_n/GLF1_n/GLF2_n (permanent
frequency/derivative steps), GLF0D_n + GLTD_n (decaying frequency step
with timescale)).

Phase contribution for each glitch, for t after GLEP (dt in seconds):

    dphi = GLPH + GLF0*dt + GLF1*dt^2/2 + GLF2*dt^3/6
         + GLF0D * tau * (1 - exp(-dt/tau)),  tau = GLTD [days -> s]

All glitch parameters live in flat device arrays indexed by glitch, so
any of them (including GLEP, away from the step) is differentiable for
the design matrix.
"""

from __future__ import annotations

import numpy as np

from ..constants import SECS_PER_DAY
from .parameter import MJDParameter, prefixParameter
from .timing_model import PhaseComponent, MissingParameter

_FIELDS = ("GLPH", "GLF0", "GLF1", "GLF2", "GLF0D", "GLTD")
_UNITS = {"GLPH": "pulse phase", "GLF0": "Hz", "GLF1": "Hz/s",
          "GLF2": "Hz/s^2", "GLF0D": "Hz", "GLTD": "d"}


class Glitch(PhaseComponent):
    category = "glitch"
    order = 30

    def __init__(self):
        super().__init__()
        self.glitch_ids: list[int] = []

    def add_glitch(self, index=None):
        index = index if index is not None else len(self.glitch_ids) + 1
        ep = MJDParameter(f"GLEP_{index}", units="MJD",
                          description=f"Epoch of glitch {index}")
        self.add_param(ep)
        for f in _FIELDS:
            p = prefixParameter(f"{f}_{index}", f, index, units=_UNITS[f],
                                description=f"{f} of glitch {index}")
            p.value = 0.0
            self.add_param(p)
        self.glitch_ids.append(index)
        return index

    def validate(self):
        for i in self.glitch_ids:
            if getattr(self, f"GLEP_{i}").value is None:
                raise MissingParameter("Glitch", f"GLEP_{i}")

    def device_slot(self, pname):
        stem, idx = pname.rsplit("_", 1)
        if stem == "GLEP":
            return "GLEP", self.glitch_ids.index(int(idx))
        if stem in _FIELDS:
            return stem, self.glitch_ids.index(int(idx))
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        ids = self.glitch_ids
        # GLEP stays in MJD on device (so fit sync round-trips); the
        # conversion to seconds-since-PEPOCH happens in phase() against
        # this packed static epoch
        params0["GLEP"] = np.array([getattr(self, f"GLEP_{i}").value
                                    for i in ids], dtype=np.float64)
        prep["glitch_pepoch_mjd"] = (float(prep["pepoch_day"])
                                     + prep["pepoch_sec"] / SECS_PER_DAY)
        for f in _FIELDS:
            params0[f] = np.array([getattr(self, f"{f}_{i}").value or 0.0
                                   for i in ids], dtype=np.float64)

    def phase(self, params, batch, prep, delay_total):
        import jax.numpy as jnp

        T = prep["T_hi"] + prep["T_lo"] - delay_total  # (n,)
        ep_s = (params["GLEP"] - prep["glitch_pepoch_mjd"]) * SECS_PER_DAY
        dt = T[:, None] - ep_s[None, :]                # (n, nglitch)
        on = (dt > 0).astype(dt.dtype)
        dtp = jnp.where(dt > 0, dt, 0.0)
        tau = params["GLTD"] * SECS_PER_DAY
        # guard tau=0 (no decaying term): exp factor forced to 0 contribution
        safe_tau = jnp.where(tau > 0, tau, 1.0)
        decay = jnp.where(tau > 0,
                          params["GLF0D"] * safe_tau
                          * (1.0 - jnp.exp(-dtp / safe_tau)), 0.0)
        dphi = (params["GLPH"] + params["GLF0"] * dtp
                + params["GLF1"] * dtp**2 / 2.0
                + params["GLF2"] * dtp**3 / 6.0 + decay)
        return jnp.sum(on * dphi, axis=-1)
