"""Solar-system Shapiro delay.

(reference: src/pint/models/solar_system_shapiro.py::SolarSystemShapiro
— ln-term delay from the Sun, plus planets when PLANET_SHAPIRO is set
and planet posvels were computed.)
"""

from __future__ import annotations

from ..constants import AU_LS, GM_C3_S, TSUN_S
from .parameter import boolParameter
from .timing_model import DelayComponent

_PLANET_ORDER = ("venus", "mars", "jupiter", "saturn", "uranus", "neptune")


class SolarSystemShapiro(DelayComponent):
    category = "solar_system_shapiro"
    order = 20

    def __init__(self):
        super().__init__()
        p = boolParameter("PLANET_SHAPIRO", description="Include planetary Shapiro delays")
        p.value = False
        self.add_param(p)

    def device_slot(self, pname):
        raise KeyError(pname)  # no fittable params

    def pack(self, model, toas, prep, params0):
        prep["planet_shapiro"] = bool(self.PLANET_SHAPIRO.value) and bool(toas.planet_pos)

    @staticmethod
    def _body_delay(body_pos_ls, psr_dir, gm_c3):
        """-2 GM/c^3 * ln((r - r.n)/AU): standard log Shapiro term.

        body_pos_ls: body wrt observatory [ls]. Constant offsets from
        the log normalization are absorbed by the phase offset.
        """
        import jax.numpy as jnp

        r = jnp.linalg.norm(body_pos_ls, axis=-1)
        rcos = jnp.sum(body_pos_ls * psr_dir, axis=-1)
        return -2.0 * gm_c3 * jnp.log((r - rcos) / AU_LS)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        # pulsar direction from whichever astrometry component is present;
        # without one (barycentric toy models) there is no geometry to apply
        astrom = next((c for c in self._parent.delay_components()
                       if c.category == "astrometry"), None)
        if astrom is None:
            return jnp.zeros_like(batch.tdb_sec)
        n = astrom.ssb_to_psb_xyz(params, prep)
        d = self._body_delay(batch.obs_sun_ls, n, TSUN_S)
        if prep.get("planet_shapiro"):
            for k, name in enumerate(_PLANET_ORDER):
                d = d + self._body_delay(batch.planet_pos_ls[k], n, GM_C3_S[name])
        return d
