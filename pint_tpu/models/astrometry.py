"""Astrometry delay components (Roemer + parallax).

(reference: src/pint/models/astrometry.py — Astrometry base,
AstrometryEquatorial (RAJ/DECJ/PMRA/PMDEC/PX),
AstrometryEcliptic (ELONG/ELAT/PMELONG/PMELAT/OBL);
solar_system_geometric_delay including the parallax curvature term.)

Device code computes the pulsar unit vector from the *current* params
(so RAJ/DECJ/PM/PX are all differentiable for the design matrix via
jacfwd) and dots it with the packed observatory SSB position in
light-seconds. f64 suffices: 500 ls x 8e-15 (TPU 47-bit) ~ 4 ps.
"""

from __future__ import annotations

import numpy as np

from ..constants import (MASYR_TO_RADS, MAS_TO_RAD, OBLIQUITY_ARCSEC,
                         ARCSEC_TO_RAD, PC_M, C_M_S, SECS_PER_DAY)
from .parameter import AngleParameter, MJDParameter, floatParameter, strParameter
from .timing_model import DelayComponent, MissingParameter

_LS_PER_PC = PC_M / C_M_S  # light-seconds per parsec


class Astrometry(DelayComponent):
    category = "astrometry"
    order = 10

    def pack(self, model, toas, prep, params0):
        # seconds since POSEPOCH for proper motion (f64 is ample)
        pe = getattr(self, "POSEPOCH", None)
        if pe is not None and pe.day is not None:
            day, sec = pe.day, pe.sec
        else:
            day, sec = prep["pepoch_day"], prep["pepoch_sec"]
        import jax.numpy as jnp

        dt = ((toas.tdb.day - day).astype(np.float64) * SECS_PER_DAY
              + (toas.tdb.sec - sec))
        prep["posepoch_dt"] = jnp.asarray(dt)
        for pname in self.params:
            par = getattr(self, pname)
            if par.kind in ("float", "angle", "prefix"):
                params0[pname] = par.value if par.value is not None else 0.0

    def device_slot(self, pname):
        return pname, None

    def ssb_to_psb_xyz(self, params, prep):
        """Unit vector SSB->pulsar (ICRS) at each TOA; differentiable."""
        raise NotImplementedError

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        n = self.ssb_to_psb_xyz(params, prep)  # (ntoa, 3)
        r = batch.obs_pos_ls
        rdotn = jnp.sum(r * n, axis=-1)
        d = -rdotn
        px_mas = params.get("PX", 0.0)
        r2 = jnp.sum(r * r, axis=-1)
        # parallax curvature: PX [mas] -> distance 1000/PX pc, so
        # 1/d_ls = PX/(1000*ls_per_pc); delay += |r_perp|^2/(2 d)
        inv_d_ls = px_mas / (1000.0 * _LS_PER_PC)
        d = d + 0.5 * (r2 - rdotn**2) * inv_d_ls
        return d


class AstrometryEquatorial(Astrometry):
    """(reference: astrometry.py::AstrometryEquatorial)"""

    def __init__(self):
        super().__init__()
        self.add_param(AngleParameter("RAJ", units="rad", angle_unit="hourangle",
                                      description="Right ascension (J2000)"))
        self.add_param(AngleParameter("DECJ", units="rad", angle_unit="deg",
                                      description="Declination (J2000)",
                                      aliases=("DEC",)))
        self.add_param(floatParameter("PMRA", units="mas/yr", description="Proper motion in RA*cos(DEC)"))
        self.add_param(floatParameter("PMDEC", units="mas/yr", description="Proper motion in DEC"))
        self.add_param(floatParameter("PX", units="mas", description="Parallax"))
        self.add_param(MJDParameter("POSEPOCH", units="MJD", description="Position epoch"))

    def validate(self):
        if self.RAJ.value is None or self.DECJ.value is None:
            raise MissingParameter("AstrometryEquatorial", "RAJ/DECJ")

    def ssb_to_psb_xyz(self, params, prep):
        import jax.numpy as jnp

        dt = prep["posepoch_dt"]
        ra0 = params["RAJ"]
        dec0 = params["DECJ"]
        pmra = params.get("PMRA", 0.0) * MASYR_TO_RADS
        pmdec = params.get("PMDEC", 0.0) * MASYR_TO_RADS
        dec = dec0 + pmdec * dt
        ra = ra0 + pmra * dt / jnp.cos(dec0)
        cd = jnp.cos(dec)
        return jnp.stack([cd * jnp.cos(ra), cd * jnp.sin(ra), jnp.sin(dec)], axis=-1)


class AstrometryEcliptic(Astrometry):
    """(reference: astrometry.py::AstrometryEcliptic — ELONG/ELAT frame)."""

    def __init__(self):
        super().__init__()
        self.add_param(AngleParameter("ELONG", units="rad", angle_unit="deg",
                                      description="Ecliptic longitude",
                                      aliases=("LAMBDA",)))
        self.add_param(AngleParameter("ELAT", units="rad", angle_unit="deg",
                                      description="Ecliptic latitude", aliases=("BETA",)))
        self.add_param(floatParameter("PMELONG", units="mas/yr", aliases=("PMLAMBDA",),
                                      description="PM in ecliptic longitude"))
        self.add_param(floatParameter("PMELAT", units="mas/yr", aliases=("PMBETA",),
                                      description="PM in ecliptic latitude"))
        self.add_param(floatParameter("PX", units="mas", description="Parallax"))
        self.add_param(MJDParameter("POSEPOCH", units="MJD", description="Position epoch"))
        self.add_param(strParameter("ECL", description="Obliquity convention"))
        self.ECL.value = "IERS2010"

    def validate(self):
        if self.ELONG.value is None or self.ELAT.value is None:
            raise MissingParameter("AstrometryEcliptic", "ELONG/ELAT")

    def obliquity_rad(self):
        name = (self.ECL.value or "IERS2010").upper()
        return OBLIQUITY_ARCSEC.get(name, OBLIQUITY_ARCSEC["DEFAULT"]) * ARCSEC_TO_RAD

    def pack(self, model, toas, prep, params0):
        super().pack(model, toas, prep, params0)
        prep["obliquity"] = self.obliquity_rad()

    def ssb_to_psb_xyz(self, params, prep):
        import jax.numpy as jnp

        dt = prep["posepoch_dt"]
        eps = prep["obliquity"]
        lon0 = params["ELONG"]
        lat0 = params["ELAT"]
        pml = params.get("PMELONG", 0.0) * MASYR_TO_RADS
        pmb = params.get("PMELAT", 0.0) * MASYR_TO_RADS
        lat = lat0 + pmb * dt
        lon = lon0 + pml * dt / jnp.cos(lat0)
        cb = jnp.cos(lat)
        x = cb * jnp.cos(lon)
        y = cb * jnp.sin(lon)
        z = jnp.sin(lat)
        # rotate ecliptic -> equatorial ICRS
        ce, se = jnp.cos(eps), jnp.sin(eps)
        return jnp.stack([x, ce * y - se * z, se * y + ce * z], axis=-1)
