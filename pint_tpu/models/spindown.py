"""Spindown phase component.

(reference: src/pint/models/spindown.py::Spindown — params F0..Fn via
prefixParameter, PEPOCH; phase = taylor_horner(dt, [0, F0, F1, ...])).

Device strategy (see timing_model.py module docstring): the host packs
phi_ref = taylor(F_ref, T) in longdouble as (int, frac); the device
adds only exact small deltas — the dF Taylor terms and the
-delay * instantaneous-frequency divided-difference term — all f64-safe
on TPU's ~47-bit emulated doubles.
"""

from __future__ import annotations

import numpy as np

from ..mjd import LD
from .parameter import MJDParameter, prefixParameter
from .timing_model import PhaseComponent, MissingParameter


class Spindown(PhaseComponent):
    category = "spindown"
    order = 10

    def __init__(self):
        super().__init__()
        self.add_param(prefixParameter("F0", "F", 0, units="Hz",
                                       description="Spin frequency"))
        self.add_param(MJDParameter("PEPOCH", units="MJD",
                                    description="Epoch of spin parameters"))

    def setup(self):
        pass

    def validate(self):
        if self.F0.value is None:
            raise MissingParameter("Spindown", "F0")

    def n_fterms(self):
        n = 0
        while f"F{n + 1}" in self.params:
            n += 1
        return n + 1

    def add_fterm(self, index, value=0.0, frozen=True):
        p = prefixParameter(f"F{index}", "F", index, units=f"Hz/s^{index}",
                            frozen=frozen)
        p.value = value
        self.add_param(p)

    def fvalues(self):
        return np.array([getattr(self, f"F{i}").value or 0.0
                         for i in range(self.n_fterms())], dtype=np.float64)

    def device_slot(self, pname):
        if pname.startswith("F"):
            return "F", int(pname[1:])
        raise KeyError(pname)

    # ---- host pack ----

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        F_ref = self.fvalues()
        params0["F"] = F_ref.copy()
        prep["F_ref"] = jnp.asarray(F_ref)  # traced arg: values change per refit
        T = prep["T_ld"]  # longdouble seconds since PEPOCH
        phi = np.zeros_like(T)
        fact = LD(1.0)
        for i, f in enumerate(F_ref):
            fact = fact * LD(i + 1)
            phi = phi + LD(f) * T ** (i + 1) / fact
        phi_int = np.floor(phi + LD(0.5))
        prep["phi_ref_int"] = jnp.asarray(phi_int.astype(np.float64))
        prep["phi_ref_frac"] = jnp.asarray((phi - phi_int).astype(np.float64))

    # ---- device phase ----

    def phase(self, params, batch, prep, delay_total):
        import jax.numpy as jnp

        F = params["F"]
        F_ref = prep["F_ref"]
        T = prep["T_hi"] + prep["T_lo"]
        d = delay_total
        n = F_ref.shape[0]
        ph = prep["phi_ref_frac"]
        # delta-F Taylor terms: sum_i (F_i - F_ref_i) T^(i+1)/(i+1)!
        fact = 1.0
        Tp = T  # T^(i+1)
        for i in range(n):
            fact *= i + 1
            ph = ph + (F[i] - F_ref[i]) * Tp / fact
            Tp = Tp * T
        # exact delay term: phi(T-d) - phi(T)
        #   = -d * sum_i F_i/(i+1)! * sum_{j<=i} T^(i-j) (T-d)^j
        Tm = T - d
        fact = 1.0
        B = jnp.zeros_like(T)
        for i in range(n):
            fact *= i + 1
            s = jnp.zeros_like(T)
            Tmj = jnp.ones_like(T)  # (T-d)^j
            for j in range(i + 1):
                # T^(i-j) * (T-d)^j
                s = s + T ** (i - j) * Tmj
                Tmj = Tmj * Tm
            B = B + F[i] / fact * s
        return ph - d * B

    def d_phase_d_toa_freq(self, params, batch, prep, delay_total):
        """Instantaneous spin frequency at emission [Hz] (for resid->time)."""
        F = params["F"]
        T = prep["T_hi"] + prep["T_lo"] - delay_total
        freq = 0.0 * T
        fact = 1.0
        Tp = 1.0
        for i in range(prep["F_ref"].shape[0]):
            if i > 0:
                fact *= i
            freq = freq + F[i] * Tp / fact
            Tp = Tp * T
        return freq
