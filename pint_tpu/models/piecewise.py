"""Piecewise-constant spindown segments.

(reference: src/pint/models/piecewise.py::PiecewiseSpindown
*(version-dependent)* — per-window (PWEP_####, PWSTART_####,
PWSTOP_####) extra spin solutions PWF0_####/PWF1_####/PWF2_#### added
to the phase inside the window.)
"""

from __future__ import annotations

import numpy as np

from ..constants import SECS_PER_DAY
from .parameter import MJDParameter, prefixParameter
from .timing_model import PhaseComponent


class PiecewiseSpindown(PhaseComponent):
    category = "piecewise_spindown"
    order = 55

    def __init__(self):
        super().__init__()
        self.pw_ids: list[int] = []

    def add_segment(self, index, start_mjd=None, stop_mjd=None,
                    epoch_mjd=None):
        ep = MJDParameter(f"PWEP_{index:04d}", units="MJD",
                          description="Segment phase epoch")
        if epoch_mjd is not None:
            ep.value = epoch_mjd
        self.add_param(ep)
        r1 = MJDParameter(f"PWSTART_{index:04d}", units="MJD")
        if start_mjd is not None:
            r1.value = start_mjd
        self.add_param(r1)
        r2 = MJDParameter(f"PWSTOP_{index:04d}", units="MJD")
        if stop_mjd is not None:
            r2.value = stop_mjd
        self.add_param(r2)
        for stem, unit in (("PWPH", ""), ("PWF0", "1/s"), ("PWF1", "1/s^2"),
                           ("PWF2", "1/s^3")):
            p = prefixParameter(f"{stem}_{index:04d}", f"{stem}_", index,
                                units=unit)
            p.value = 0.0
            self.add_param(p)
        self.pw_ids.append(index)

    def validate(self):
        from .timing_model import MissingParameter

        for i in self.pw_ids:
            for stem in ("PWSTART", "PWSTOP"):
                if getattr(self, f"{stem}_{i:04d}").value is None:
                    raise MissingParameter(
                        "PiecewiseSpindown", f"{stem}_{i:04d}",
                        "(segment window bounds are required)")

    def device_slot(self, pname):
        stem = pname.split("_")[0]
        if stem in ("PWPH", "PWF0", "PWF1", "PWF2"):
            return stem, self.pw_ids.index(int(pname.split("_")[1]))
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        n_seg = len(self.pw_ids)
        for stem in ("PWPH", "PWF0", "PWF1", "PWF2"):
            params0[stem] = np.array(
                [getattr(self, f"{stem}_{i:04d}").value or 0.0
                 for i in self.pw_ids], dtype=np.float64)
        mjd_f = toas.tdb.day + toas.tdb.sec / SECS_PER_DAY
        masks = np.zeros((n_seg, len(toas)))
        dts = np.zeros((n_seg, len(toas)))
        for j, i in enumerate(self.pw_ids):
            lo = getattr(self, f"PWSTART_{i:04d}").value
            hi = getattr(self, f"PWSTOP_{i:04d}").value
            ep = getattr(self, f"PWEP_{i:04d}")
            masks[j] = (mjd_f >= lo) & (mjd_f < hi)
            dts[j] = ((toas.tdb.day - ep.day).astype(np.float64) * SECS_PER_DAY
                      + (toas.tdb.sec - ep.sec))
        prep["pw_masks"] = jnp.asarray(masks)
        prep["pw_dts"] = jnp.asarray(dts)

    def phase(self, params, batch, prep, delay_total):
        import jax.numpy as jnp

        dt = prep["pw_dts"] - delay_total[None, :]
        ph = (params["PWPH"][:, None]
              + params["PWF0"][:, None] * dt
              + 0.5 * params["PWF1"][:, None] * dt**2
              + params["PWF2"][:, None] * dt**3 / 6.0)
        return jnp.sum(ph * prep["pw_masks"], axis=0)
