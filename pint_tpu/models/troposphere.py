"""Tropospheric propagation delay.

(reference: src/pint/models/troposphere_delay.py::TroposphereDelay —
CORRECT_TROPOSPHERE flag; zenith hydrostatic delay (Davis et al. 1985)
from a standard-atmosphere pressure at the site, a nominal zenith wet
delay, and Niell (1996) mapping functions vs elevation.)

Host pack: per-TOA zenith unit vector in GCRS (observatory geodetic
up-vector rotated by the erfa_lite ITRF->GCRS chain), site latitude /
height, and day-of-year for the seasonal Niell term. Device: elevation
from the differentiable pulsar direction, continued-fraction mapping
functions, delay in seconds. TOAs from non-topocentric observatories
(barycenter/geocenter/satellites) get zero delay via a packed mask.
"""

from __future__ import annotations

import numpy as np

from ..constants import C_M_S, SECS_PER_DAY
from .parameter import boolParameter
from .timing_model import DelayComponent

# Niell (1996) hydrostatic mapping coefficients at latitudes 15..75 deg:
# time-average (a, b, c) and seasonal amplitude (a, b, c); public
# geodesy constants (JGR 101, B2, 3227).
_NMF_LAT = np.array([15.0, 30.0, 45.0, 60.0, 75.0])
_NMF_H_AVG = np.array([
    [1.2769934e-3, 2.9153695e-3, 62.610505e-3],
    [1.2683230e-3, 2.9152299e-3, 62.837393e-3],
    [1.2465397e-3, 2.9288445e-3, 63.721774e-3],
    [1.2196049e-3, 2.9022565e-3, 63.824265e-3],
    [1.2045996e-3, 2.9024912e-3, 64.258455e-3],
])
_NMF_H_AMP = np.array([
    [0.0, 0.0, 0.0],
    [1.2709626e-5, 2.1414979e-5, 9.0128400e-5],
    [2.6523662e-5, 3.0160779e-5, 4.3497037e-5],
    [3.4000452e-5, 7.2562722e-5, 84.795348e-5],
    [4.1202191e-5, 11.723375e-5, 170.37206e-5],
])
# height correction coefficients (Niell 1996, per km)
_NMF_HT = (2.53e-5, 5.49e-3, 1.14e-3)
# wet mapping coefficients (no seasonal term)
_NMF_W = np.array([
    [5.8021897e-4, 1.4275268e-3, 4.3472961e-2],
    [5.6794847e-4, 1.5138625e-3, 4.6729510e-2],
    [5.8118019e-4, 1.4572752e-3, 4.3908931e-2],
    [5.9727542e-4, 1.5007428e-3, 4.4626982e-2],
    [6.1641693e-4, 1.7599082e-3, 5.4736038e-2],
])


def _interp_coeffs(table, abs_lat_deg):
    """Piecewise-linear latitude interpolation of Niell coefficient rows."""
    out = [np.interp(abs_lat_deg, _NMF_LAT, table[:, k]) for k in range(3)]
    return np.array(out)


def zenith_hydrostatic_delay_m(lat_rad, height_m):
    """Davis et al. (1985) ZHD [m] with standard-atmosphere pressure."""
    p_hpa = 1013.25 * (1.0 - 2.25577e-5 * height_m) ** 5.25588
    return 0.0022768 * p_hpa / (
        1.0 - 0.00266 * np.cos(2.0 * lat_rad) - 0.00028 * height_m / 1000.0)


class TroposphereDelay(DelayComponent):
    category = "troposphere"
    order = 21

    # nominal zenith wet delay [m]; the reference likewise has no met
    # data and uses a fixed nominal wet term
    ZWD_M = 0.1

    def __init__(self):
        super().__init__()
        p = boolParameter("CORRECT_TROPOSPHERE",
                          description="Enable tropospheric delay correction")
        p.value = True
        self.add_param(p)

    def device_slot(self, pname):
        raise KeyError(pname)  # nothing fittable

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        from ..earth.erfa_lite import (itrf_to_gcrs_matrix, itrf_to_geodetic)
        from ..mjd import Epochs
        from ..observatory import get_observatory

        n = len(toas)
        zenith = np.zeros((n, 3))
        lat = np.zeros(n)
        height = np.zeros(n)
        topo = np.zeros(n, dtype=bool)
        utc = Epochs(toas.day, toas.sec + toas.clock_corr_s, "utc").normalized()
        for obs_name in np.unique(toas.obs.astype(str)):
            ob = get_observatory(obs_name)
            xyz = getattr(ob, "earth_location_itrf", lambda: None)()
            mask = toas.obs.astype(str) == obs_name
            if xyz is None:
                continue
            lat_d, lon_d, h = itrf_to_geodetic(xyz)
            lat_r, lon_r = np.deg2rad(lat_d), np.deg2rad(lon_d)
            up_itrf = np.array([np.cos(lat_r) * np.cos(lon_r),
                                np.cos(lat_r) * np.sin(lon_r),
                                np.sin(lat_r)])
            sub = Epochs(utc.day[mask], utc.sec[mask], "utc")
            M = itrf_to_gcrs_matrix(sub)
            zenith[mask] = (M @ up_itrf).reshape(-1, 3)
            lat[mask] = lat_r
            height[mask] = h
            topo[mask] = True
        # day of year for the seasonal Niell term (southern hemisphere
        # shifted by half a year, per Niell 1996)
        doy = (toas.get_mjds() - 44239.0) % 365.25  # MJD 44239 = 1980-01-01
        doy = np.where(lat < 0, doy + 365.25 / 2.0, doy)
        season = np.cos(2.0 * np.pi * (doy - 28.0) / 365.25)
        abs_lat_deg = np.abs(np.rad2deg(lat))
        h_avg = np.stack([np.interp(abs_lat_deg, _NMF_LAT, _NMF_H_AVG[:, k])
                          for k in range(3)], axis=-1)
        h_amp = np.stack([np.interp(abs_lat_deg, _NMF_LAT, _NMF_H_AMP[:, k])
                          for k in range(3)], axis=-1)
        w_abc = np.stack([np.interp(abs_lat_deg, _NMF_LAT, _NMF_W[:, k])
                          for k in range(3)], axis=-1)
        habc = h_avg - h_amp * season[:, None]
        prep["tropo_zenith"] = jnp.asarray(zenith)
        prep["tropo_mask"] = jnp.asarray(topo.astype(np.float64))
        prep["tropo_zhd_m"] = jnp.asarray(
            np.where(topo, zenith_hydrostatic_delay_m(lat, height), 0.0))
        prep["tropo_habc"] = jnp.asarray(habc)
        prep["tropo_wabc"] = jnp.asarray(w_abc)
        prep["tropo_height_km"] = jnp.asarray(height / 1000.0)
        prep["tropo_on"] = bool(self.CORRECT_TROPOSPHERE.value)

    @staticmethod
    def _cfrac(sin_e, a, b, c):
        """Niell continued-fraction mapping, normalized to 1 at zenith."""
        top = 1.0 + a / (1.0 + b / (1.0 + c))
        bot = sin_e + a / (sin_e + b / (sin_e + c))
        return top / bot

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        if not prep.get("tropo_on", False):
            return jnp.zeros_like(batch.tdb_sec)
        astrom = next((c for c in self._parent.delay_components()
                       if c.category == "astrometry"), None)
        if astrom is None:
            return jnp.zeros_like(batch.tdb_sec)
        n = astrom.ssb_to_psb_xyz(params, prep)
        sin_e = jnp.sum(prep["tropo_zenith"] * n, axis=-1)
        # floor at 5 deg elevation: mapping functions diverge at horizon
        sin_e = jnp.clip(sin_e, np.sin(np.deg2rad(5.0)), 1.0)
        ha, hb, hc = (prep["tropo_habc"][:, 0], prep["tropo_habc"][:, 1],
                      prep["tropo_habc"][:, 2])
        m_h = self._cfrac(sin_e, ha, hb, hc)
        # Niell height correction
        aht, bht, cht = _NMF_HT
        dm = (1.0 / sin_e - self._cfrac(sin_e, aht, bht, cht)) * prep["tropo_height_km"]
        m_h = m_h + dm
        wa, wb, wc = (prep["tropo_wabc"][:, 0], prep["tropo_wabc"][:, 1],
                      prep["tropo_wabc"][:, 2])
        m_w = self._cfrac(sin_e, wa, wb, wc)
        path_m = prep["tropo_zhd_m"] * m_h + self.ZWD_M * m_w
        return prep["tropo_mask"] * path_m / C_M_S
