"""Explicit overall phase offset.

(reference: src/pint/models/phase_offset.py::PhaseOffset — PHOFF; the
explicit alternative to the implicit 'Offset' design-matrix column.
When PHOFF is free, fitters drop the implicit offset column.)
"""

from __future__ import annotations

from .parameter import floatParameter
from .timing_model import PhaseComponent


class PhaseOffset(PhaseComponent):
    category = "phase_offset"
    order = 45

    def __init__(self):
        super().__init__()
        p = floatParameter("PHOFF", units="pulse phase",
                           description="Overall phase offset")
        p.value = 0.0
        self.add_param(p)

    def device_slot(self, pname):
        return "PHOFF", None

    def pack(self, model, toas, prep, params0):
        params0["PHOFF"] = self.PHOFF.value or 0.0

    def phase(self, params, batch, prep, delay_total):
        import jax.numpy as jnp

        return -params["PHOFF"] * jnp.ones_like(prep["T_hi"])
