"""Solar-wind dispersion delay.

(reference: src/pint/models/solar_wind_dispersion.py::SolarWindDispersion
— NE_SW electron density at 1 AU, spherically-symmetric n ~ r^-2 wind,
delay = DMconst * DM_sw / freq^2 with the (pi - theta)/(r sin theta)
line-of-sight geometry factor.)

Geometry: for n(d) = NE_SW (AU/d)^2 integrated from the observatory to
infinity along the line of sight,

    DM_sw = NE_SW * AU^2 * (pi - theta) / (r * sin(theta))

where r = |observatory -> Sun| and theta is the angle between the
observatory->Sun vector and the pulsar direction (elongation). All on
device and differentiable in NE_SW and the pulsar position.
"""

from __future__ import annotations

import numpy as np

from ..constants import AU_LS, DMconst, ONE_AU_PC
from .parameter import floatParameter
from .timing_model import DelayComponent


class SolarWindDispersion(DelayComponent):
    category = "solar_wind"
    order = 32

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(
            "NE_SW", units="cm^-3", aliases=("NE1AU", "SOLARN0"),
            description="Solar wind electron density at 1 AU"))
        self.add_param(floatParameter(
            "SWM", units="", description="Solar wind model index (0 supported)"))
        self.NE_SW.value = 0.0
        self.SWM.value = 0.0

    def validate(self):
        if self.SWM.value not in (None, 0, 0.0):
            raise ValueError("only SWM 0 (spherical r^-2 wind) is supported")

    def device_slot(self, pname):
        if pname == "NE_SW":
            return "NE_SW", None
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        params0["NE_SW"] = self.NE_SW.value or 0.0

    def solar_wind_dm(self, params, batch, prep):
        """DM_sw per TOA [pc cm^-3]; differentiable."""
        import jax.numpy as jnp

        astrom = next((c for c in self._parent.delay_components()
                       if c.category == "astrometry"), None)
        if astrom is None:
            return jnp.zeros_like(batch.tdb_sec)
        n = astrom.ssb_to_psb_xyz(params, prep)
        sun = batch.obs_sun_ls
        r_ls = jnp.linalg.norm(sun, axis=-1)
        cos_t = jnp.clip(jnp.sum(sun * n, axis=-1) / r_ls, -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        sin_t = jnp.clip(jnp.sin(theta), 1e-6, None)
        r_au = r_ls / AU_LS
        geom_pc = ONE_AU_PC * (jnp.pi - theta) / (r_au * sin_t)
        return params["NE_SW"] * geom_pc

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm = self.solar_wind_dm(params, batch, prep)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm / f2, 0.0)


# Fixed Gauss-Legendre rule on [0, 1] for the general-p line-of-sight
# integral: static nodes keep the quadrature jit-safe and differentiable.
_GL_U, _GL_W = np.polynomial.legendre.leggauss(48)
_GL_U = 0.5 * (_GL_U + 1.0)
_GL_W = 0.5 * _GL_W


def _cospow_integral(phi_hi, p):
    """F(phi_hi; p) = integral_0^phi_hi cos^(p-2)(phi) dphi, vectorized
    over phi_hi (any shape) with scalar-or-matching p. Exact for p=2
    (reduces to phi_hi); analytic integrand -> 48-node Gauss-Legendre
    is ~machine precision for the p in solar-wind use (1 < p <~ 6)."""
    import jax.numpy as jnp

    u = jnp.asarray(_GL_U)
    w = jnp.asarray(_GL_W)
    phi = phi_hi[..., None] * u
    vals = jnp.cos(phi) ** (jnp.asarray(p)[..., None] - 2.0)
    return phi_hi * jnp.sum(w * vals, axis=-1)


def solar_wind_geometry_p(sun_ls, n_hat, p):
    """DM per unit electron density at 1 AU [pc cm^-3 per cm^-3] for an
    n ~ r^-p wind, along the observatory->pulsar line of sight.

    I = AU^p * integral_0^inf d(s)^-p ds with d^2 = b^2 + (s - z0)^2,
    b = r sin(theta) the impact parameter, z0 = r cos(theta);
    substituting u = tan(phi): I = AU^p/b^(p-1) * [F(pi/2;p) + F(atan(z0/b);p)]
    with F the cos-power integral above. p=2 reduces exactly to the
    classic (pi - theta)/(r sin theta) factor
    (reference: solar_wind_dispersion.py::_dm_p_int / _solar_wind_geometry).
    """
    import jax.numpy as jnp

    r_ls = jnp.linalg.norm(sun_ls, axis=-1)
    cos_t = jnp.clip(jnp.sum(sun_ls * n_hat, axis=-1) / r_ls, -1.0, 1.0)
    theta = jnp.arccos(cos_t)
    sin_t = jnp.clip(jnp.sin(theta), 1e-6, None)
    b_ls = r_ls * sin_t
    z0_ls = r_ls * cos_t
    # p and b_ls broadcast naturally (e.g. per-window p (k,1) against
    # per-TOA b_ls (1,n) -> (k,n)); do NOT force p to b_ls's shape —
    # that is an invalid broadcast for k >= 2 windows
    p = jnp.asarray(p)
    ones = jnp.ones(jnp.broadcast_shapes(jnp.shape(p), jnp.shape(b_ls)))
    F_inf = _cospow_integral(ones * (0.5 * jnp.pi), p * ones)
    F_z = _cospow_integral(jnp.arctan(z0_ls / b_ls) * jnp.ones_like(ones),
                           p * ones)
    I_ls = AU_LS**p / b_ls ** (p - 1.0) * (F_inf + F_z)
    return I_ls * (ONE_AU_PC / AU_LS)  # ls -> pc


class SolarWindDispersionX(SolarWindDispersion):
    """Piecewise solar wind (reference: solar_wind_dispersion.py::
    SolarWindDispersionX *(version-dependent)*).

    Upstream convention (matching tempo2/PINT par files): SWXDM_#### is
    the window's MAXIMUM solar-wind DM [pc cm^-3] over
    [SWXR1_####, SWXR2_####); the per-TOA contribution is
    SWXDM * g_p(t) / max_window(g_p) with g_p the r^-p geometry factor
    and p = SWXP_#### (default 2). Outside all windows the base NE_SW
    density applies.
    """

    category = "solar_windx"

    def __init__(self):
        super().__init__()
        self.swx_ids: list[int] = []

    def add_swx_range(self, index, mjd_lo, mjd_hi, dm=0.0, p=2.0):
        from .parameter import MJDParameter, prefixParameter

        pdm = prefixParameter(f"SWXDM_{index:04d}", "SWXDM_", index,
                              units="pc cm^-3",
                              aliases=(f"SWX_{index:04d}",))
        pdm.value = dm
        self.add_param(pdm)
        pp = prefixParameter(f"SWXP_{index:04d}", "SWXP_", index, units="")
        pp.value = p
        self.add_param(pp)
        r1 = MJDParameter(f"SWXR1_{index:04d}", units="MJD")
        r1.value = mjd_lo
        self.add_param(r1)
        r2 = MJDParameter(f"SWXR2_{index:04d}", units="MJD")
        r2.value = mjd_hi
        self.add_param(r2)
        self.swx_ids.append(index)

    def validate(self):
        super().validate()
        for i in self.swx_ids:
            pp = getattr(self, f"SWXP_{i:04d}")
            if not pp.frozen:
                raise ValueError(
                    f"SWXP_{i:04d}: fitting the solar-wind power index is "
                    "not supported (static per-window quadrature)")

    def device_slot(self, pname):
        if pname.startswith("SWXDM_"):
            return "SWXDM", self.swx_ids.index(int(pname[6:]))
        return super().device_slot(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        super().pack(model, toas, prep, params0)
        params0["SWXDM"] = np.array(
            [getattr(self, f"SWXDM_{i:04d}").value or 0.0
             for i in self.swx_ids], dtype=np.float64)
        mjd = toas.get_mjds()
        masks = np.stack([
            ((mjd >= getattr(self, f"SWXR1_{i:04d}").value)
             & (mjd < getattr(self, f"SWXR2_{i:04d}").value)).astype(np.float64)
            for i in self.swx_ids]) if self.swx_ids else np.zeros((0, len(toas)))
        prep["swx_masks"] = jnp.asarray(masks)
        prep["swx_p"] = jnp.asarray(np.array(
            [getattr(self, f"SWXP_{i:04d}").value or 2.0
             for i in self.swx_ids], dtype=np.float64))

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm = self.swx_dm(params, batch, prep)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm / f2, 0.0)

    def swx_dm(self, params, batch, prep):
        """Per-TOA solar-wind DM [pc cm^-3]: SWX windows (max-DM
        convention) + NE_SW base outside every window. Shared by
        delay() and TimingModel.total_dm."""
        import jax.numpy as jnp

        astrom = next((c for c in self._parent.delay_components()
                       if c.category == "astrometry"), None)
        masks = prep["swx_masks"]
        base_dm = self.solar_wind_dm(params, batch, prep)
        if masks.shape[0] == 0 or astrom is None:
            return base_dm
        n_hat = astrom.ssb_to_psb_xyz(params, prep)
        # per-window geometry (k, n): window j uses its own power index
        G = solar_wind_geometry_p(batch.obs_sun_ls[None, :, :],
                                  n_hat[None, :, :] if n_hat.ndim == 2
                                  else n_hat[None, :],
                                  prep["swx_p"][:, None])
        # normalize each window by its in-window maximum (upstream's
        # "SWXDM is the max DM over the window" convention)
        gmax = jnp.max(G * masks, axis=1)
        gmax = jnp.where(gmax > 0, gmax, 1.0)
        dm_x = jnp.sum((params["SWXDM"] / gmax)[:, None] * G * masks, axis=0)
        in_any = jnp.clip(jnp.sum(masks, axis=0), 0.0, 1.0)
        return dm_x + base_dm * (1.0 - in_any)
