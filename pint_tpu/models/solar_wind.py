"""Solar-wind dispersion delay.

(reference: src/pint/models/solar_wind_dispersion.py::SolarWindDispersion
— NE_SW electron density at 1 AU, spherically-symmetric n ~ r^-2 wind,
delay = DMconst * DM_sw / freq^2 with the (pi - theta)/(r sin theta)
line-of-sight geometry factor.)

Geometry: for n(d) = NE_SW (AU/d)^2 integrated from the observatory to
infinity along the line of sight,

    DM_sw = NE_SW * AU^2 * (pi - theta) / (r * sin(theta))

where r = |observatory -> Sun| and theta is the angle between the
observatory->Sun vector and the pulsar direction (elongation). All on
device and differentiable in NE_SW and the pulsar position.
"""

from __future__ import annotations

import numpy as np

from ..constants import AU_LS, DMconst, ONE_AU_PC
from .parameter import floatParameter
from .timing_model import DelayComponent


class SolarWindDispersion(DelayComponent):
    category = "solar_wind"
    order = 32

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(
            "NE_SW", units="cm^-3", aliases=("NE1AU", "SOLARN0"),
            description="Solar wind electron density at 1 AU"))
        self.add_param(floatParameter(
            "SWM", units="",
            description="Solar wind model index (0: spherical r^-2; "
                        "1: r^-SWP power law)"))
        self.add_param(floatParameter(
            "SWP", units="",
            description="Solar wind density power-law index (SWM 1; "
                        "density ~ r^-SWP, SWP=2 recovers SWM 0)"))
        self.NE_SW.value = 0.0
        self.SWM.value = 0.0
        self.SWP.value = 2.0

    def validate(self):
        if self.SWM.value not in (None, 0, 0.0, 1, 1.0):
            raise ValueError(
                "only SWM 0 (spherical r^-2 wind) and SWM 1 (r^-SWP "
                "power-law wind) are supported")
        swm = int(self.SWM.value or 0)
        # no falsy-zero fallback: SWP 0.0 is a real (and invalid) value
        swp = 2.0 if self.SWP.value is None else float(self.SWP.value)
        if swm == 1 and not swp > 1.0:
            raise ValueError("SWM 1 needs SWP > 1 (the line-of-sight "
                             "integral diverges otherwise)")
        if swm != 1 and not self.SWP.frozen:
            raise ValueError(
                "SWP is only used with SWM 1; freeing it under SWM 0 "
                "would put an identically-zero column in the design "
                "matrix (rank-deficient fit)")

    def device_slot(self, pname):
        if pname in ("NE_SW", "SWP"):
            return pname, None
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        params0["NE_SW"] = self.NE_SW.value or 0.0
        params0["SWP"] = (2.0 if self.SWP.value is None
                          else float(self.SWP.value))

    def solar_wind_dm(self, params, batch, prep):
        """DM_sw per TOA [pc cm^-3]; differentiable (including in SWP
        under SWM 1 — the cos-power quadrature is smooth in p).
        (reference: solar_wind_dispersion.py — SWM 0 spherical and
        SWM 1 general power-law models.)"""
        import jax.numpy as jnp

        astrom = next((c for c in self._parent.delay_components()
                       if c.category == "astrometry"), None)
        if astrom is None:
            return jnp.zeros_like(batch.tdb_sec)
        n = astrom.ssb_to_psb_xyz(params, prep)
        sun = batch.obs_sun_ls
        if int(self.SWM.value or 0) == 1:
            # general r^-SWP wind: same geometry kernel the SWX
            # windows use, with the base (fittable) index
            return params["NE_SW"] * solar_wind_geometry_p(
                sun, n, params["SWP"])
        r_ls = jnp.linalg.norm(sun, axis=-1)
        cos_t = jnp.clip(jnp.sum(sun * n, axis=-1) / r_ls, -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        sin_t = jnp.clip(jnp.sin(theta), 1e-6, None)
        r_au = r_ls / AU_LS
        geom_pc = ONE_AU_PC * (jnp.pi - theta) / (r_au * sin_t)
        return params["NE_SW"] * geom_pc

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm = self.solar_wind_dm(params, batch, prep)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm / f2, 0.0)


# Fixed tanh-sinh (double-exponential) rule on [0, 1]: one static node
# set integrates t^(p-2) * smooth(t) to ~1e-12 for EVERY p > ~1.1 —
# endpoint algebraic singularities (1 < p < 2) and non-integer-power
# endpoint derivatives (2 < p < 3) alike — because node t_j and weight
# both decay doubly-exponentially while the singularity grows only
# algebraically. a-range 4.5 keeps the truncated tail below 1e-12 down
# to p ~ 1.1 (tail ~ exp(-(p-1) pi sinh a_max)).
_TS_A = np.linspace(-4.5, 4.5, 81)
_TS_U = 0.5 * np.pi * np.sinh(_TS_A)
# t = 0.5*(1+tanh u) computed as a stable sigmoid: naive tanh
# SATURATES to exactly -1 for u < ~-19 in f64 and the node becomes
# exactly 0, so t^(p-2) for p < 2 would be inf at the deep-left nodes
_TS_T = np.where(_TS_U < 0,
                 np.exp(2 * np.minimum(_TS_U, 0))
                 / (1.0 + np.exp(2 * np.minimum(_TS_U, 0))),
                 1.0 / (1.0 + np.exp(-2 * np.maximum(_TS_U, 0))))
_TS_W = (_TS_A[1] - _TS_A[0]) * 0.25 * np.pi * np.cosh(_TS_A) \
    / np.cosh(_TS_U) ** 2


def _cospow_half(p):
    """Closed form F(pi/2; p) = integral_0^(pi/2) cos^(p-2) =
    sqrt(pi)/2 * Gamma((p-1)/2) / Gamma(p/2), differentiable in p."""
    import jax.numpy as jnp
    from jax.scipy.special import gammaln

    p = jnp.asarray(p)
    return (0.5 * jnp.sqrt(jnp.pi)
            * jnp.exp(gammaln((p - 1.0) / 2.0) - gammaln(p / 2.0)))


def _cospow_integral(phi_hi, p):
    """F(phi_hi; p) = integral_0^phi_hi cos^(p-2)(psi) dpsi for
    phi_hi <= pi/2 (either sign), vectorized with matching-shape p.

    Accurate for ALL p > ~1.2, including 1 < p < 2 where the
    integrand is endpoint-singular at pi/2 (a naive fixed-node
    Gauss-Legendre rule is percent-level wrong there — r4 review
    finding): evaluated as F_half(p) - G(eps; p) with
    eps = pi/2 - phi_hi and G(eps; p) = integral_0^eps sin^(p-2) =
    eps^(p-1) * integral_0^1 t^(p-2) (sin(eps t)/(eps t))^(p-2) dt,
    integrated with the fixed 81-node tanh-sinh rule above — one
    static node set handles the t^(p-2) endpoint behavior for every
    p. Measured vs dense reference integration (pinned in
    tests/test_components2.py): <= 2.4e-12 ABSOLUTE for
    p in [1.2, 6] over elongations away from exact anti-solar
    alignment (phi_hi >= -1.5); in the last ~0.07 rad toward the
    anti-solar pole the sinc^(p-2) factor develops a t=1 near-
    singularity and small p degrades to ~3e-4 absolute (~6e-5
    relative of |F|~5) at the clipped phi_hi = -(pi/2 - 1e-6)
    extreme — sub-1e-7 pc cm^-3 of far-side DM, far below timing
    relevance, and pinned by the same test. Exact for
    p = 2. Differentiable in p (gammaln + smooth quadrature; the
    truncated tail grows as exp(-(p-1) pi sinh 4.5) toward p -> 1,
    ~1e-6 by p = 1.1).
    """
    import jax.numpy as jnp

    p = jnp.asarray(p)
    eps = 0.5 * jnp.pi - phi_hi  # in (0, pi); callers clip theta
    t = jnp.asarray(_TS_T)
    w = jnp.asarray(_TS_W)
    x = eps[..., None] * t
    # (sin x / x)^(p-2) without 0/0 at x = 0
    sinc = jnp.where(jnp.abs(x) > 1e-300,
                     jnp.sin(x) / jnp.where(jnp.abs(x) > 1e-300, x, 1.0),
                     1.0)
    f = t ** (p[..., None] - 2.0) * sinc ** (p[..., None] - 2.0)
    G = eps ** (p - 1.0) * jnp.sum(w * f, axis=-1)
    return _cospow_half(p) - G


def solar_wind_geometry_p(sun_ls, n_hat, p):
    """DM per unit electron density at 1 AU [pc cm^-3 per cm^-3] for an
    n ~ r^-p wind, along the observatory->pulsar line of sight.

    I = AU^p * integral_0^inf d(s)^-p ds with d^2 = b^2 + (s - z0)^2,
    b = r sin(theta) the impact parameter, z0 = r cos(theta);
    substituting u = tan(phi): I = AU^p/b^(p-1) * [F(pi/2;p) + F(atan(z0/b);p)]
    with F the cos-power integral above. p=2 reduces exactly to the
    classic (pi - theta)/(r sin theta) factor
    (reference: solar_wind_dispersion.py::_dm_p_int / _solar_wind_geometry).
    """
    import jax.numpy as jnp

    r_ls = jnp.linalg.norm(sun_ls, axis=-1)
    cos_t = jnp.clip(jnp.sum(sun_ls * n_hat, axis=-1) / r_ls, -1.0, 1.0)
    theta = jnp.arccos(cos_t)
    sin_t = jnp.clip(jnp.sin(theta), 1e-6, None)
    b_ls = r_ls * sin_t
    z0_ls = r_ls * cos_t
    # p and b_ls broadcast naturally (e.g. per-window p (k,1) against
    # per-TOA b_ls (1,n) -> (k,n)); do NOT force p to b_ls's shape —
    # that is an invalid broadcast for k >= 2 windows
    p = jnp.asarray(p)
    ones = jnp.ones(jnp.broadcast_shapes(jnp.shape(p), jnp.shape(b_ls)))
    # closed form for the half-range piece: _cospow_integral(pi/2)
    # would hit eps=0 where the eps^(p-1) factor has a NaN p-gradient.
    # p alone (not p * ones): F_half depends only on p, and the sum
    # below broadcasts against F_z's full shape — no per-TOA gammaln
    F_inf = _cospow_half(p)
    F_z = _cospow_integral(jnp.arctan(z0_ls / b_ls) * jnp.ones_like(ones),
                           p * ones)
    I_ls = AU_LS**p / b_ls ** (p - 1.0) * (F_inf + F_z)
    return I_ls * (ONE_AU_PC / AU_LS)  # ls -> pc


class SolarWindDispersionX(SolarWindDispersion):
    """Piecewise solar wind (reference: solar_wind_dispersion.py::
    SolarWindDispersionX *(version-dependent)*).

    Upstream convention (matching tempo2/PINT par files): SWXDM_#### is
    the window's MAXIMUM solar-wind DM [pc cm^-3] over
    [SWXR1_####, SWXR2_####); the per-TOA contribution is
    SWXDM * g_p(t) / max_window(g_p) with g_p the r^-p geometry factor
    and p = SWXP_#### (default 2). Outside all windows the base NE_SW
    density applies.
    """

    category = "solar_windx"

    def __init__(self):
        super().__init__()
        self.swx_ids: list[int] = []

    def add_swx_range(self, index, mjd_lo, mjd_hi, dm=0.0, p=2.0):
        from .parameter import MJDParameter, prefixParameter

        pdm = prefixParameter(f"SWXDM_{index:04d}", "SWXDM_", index,
                              units="pc cm^-3",
                              aliases=(f"SWX_{index:04d}",))
        pdm.value = dm
        self.add_param(pdm)
        pp = prefixParameter(f"SWXP_{index:04d}", "SWXP_", index, units="")
        pp.value = p
        self.add_param(pp)
        r1 = MJDParameter(f"SWXR1_{index:04d}", units="MJD")
        r1.value = mjd_lo
        self.add_param(r1)
        r2 = MJDParameter(f"SWXR2_{index:04d}", units="MJD")
        r2.value = mjd_hi
        self.add_param(r2)
        self.swx_ids.append(index)

    def validate(self):
        super().validate()
        for i in self.swx_ids:
            pp = getattr(self, f"SWXP_{i:04d}")
            if not pp.frozen:
                raise ValueError(
                    f"SWXP_{i:04d}: fitting the solar-wind power index is "
                    "not supported (static per-window quadrature)")
            # same divergence guard as the base SWP (no falsy-zero
            # fallback): p <= 1 makes _cospow_half(p) = inf and every
            # in-window delay inf/NaN with no diagnostic
            pv = 2.0 if pp.value is None else float(pp.value)
            if not pv > 1.0:
                raise ValueError(
                    f"SWXP_{i:04d} must be > 1 (the line-of-sight "
                    f"integral diverges otherwise), got {pv}")

    def device_slot(self, pname):
        if pname.startswith("SWXDM_"):
            return "SWXDM", self.swx_ids.index(int(pname[6:]))
        return super().device_slot(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        super().pack(model, toas, prep, params0)
        params0["SWXDM"] = np.array(
            [getattr(self, f"SWXDM_{i:04d}").value or 0.0
             for i in self.swx_ids], dtype=np.float64)
        mjd = toas.get_mjds()
        masks = np.stack([
            ((mjd >= getattr(self, f"SWXR1_{i:04d}").value)
             & (mjd < getattr(self, f"SWXR2_{i:04d}").value)).astype(np.float64)
            for i in self.swx_ids]) if self.swx_ids else np.zeros((0, len(toas)))
        prep["swx_masks"] = jnp.asarray(masks)
        prep["swx_p"] = jnp.asarray(np.array(
            [2.0 if getattr(self, f"SWXP_{i:04d}").value is None
             else float(getattr(self, f"SWXP_{i:04d}").value)
             for i in self.swx_ids], dtype=np.float64))

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm = self.swx_dm(params, batch, prep)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm / f2, 0.0)

    def swx_dm(self, params, batch, prep):
        """Per-TOA solar-wind DM [pc cm^-3]: SWX windows (max-DM
        convention) + NE_SW base outside every window. Shared by
        delay() and TimingModel.total_dm."""
        import jax.numpy as jnp

        astrom = next((c for c in self._parent.delay_components()
                       if c.category == "astrometry"), None)
        masks = prep["swx_masks"]
        base_dm = self.solar_wind_dm(params, batch, prep)
        if masks.shape[0] == 0 or astrom is None:
            return base_dm
        n_hat = astrom.ssb_to_psb_xyz(params, prep)
        # per-window geometry (k, n): window j uses its own power index
        G = solar_wind_geometry_p(batch.obs_sun_ls[None, :, :],
                                  n_hat[None, :, :] if n_hat.ndim == 2
                                  else n_hat[None, :],
                                  prep["swx_p"][:, None])
        # normalize each window by its in-window maximum (upstream's
        # "SWXDM is the max DM over the window" convention)
        gmax = jnp.max(G * masks, axis=1)
        gmax = jnp.where(gmax > 0, gmax, 1.0)
        dm_x = jnp.sum((params["SWXDM"] / gmax)[:, None] * G * masks, axis=0)
        in_any = jnp.clip(jnp.sum(masks, axis=0), 0.0, 1.0)
        return dm_x + base_dm * (1.0 - in_any)
