"""Solar-wind dispersion delay.

(reference: src/pint/models/solar_wind_dispersion.py::SolarWindDispersion
— NE_SW electron density at 1 AU, spherically-symmetric n ~ r^-2 wind,
delay = DMconst * DM_sw / freq^2 with the (pi - theta)/(r sin theta)
line-of-sight geometry factor.)

Geometry: for n(d) = NE_SW (AU/d)^2 integrated from the observatory to
infinity along the line of sight,

    DM_sw = NE_SW * AU^2 * (pi - theta) / (r * sin(theta))

where r = |observatory -> Sun| and theta is the angle between the
observatory->Sun vector and the pulsar direction (elongation). All on
device and differentiable in NE_SW and the pulsar position.
"""

from __future__ import annotations

from ..constants import AU_LS, DMconst, ONE_AU_PC
from .parameter import floatParameter
from .timing_model import DelayComponent


class SolarWindDispersion(DelayComponent):
    category = "solar_wind"
    order = 32

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(
            "NE_SW", units="cm^-3", aliases=("NE1AU", "SOLARN0"),
            description="Solar wind electron density at 1 AU"))
        self.add_param(floatParameter(
            "SWM", units="", description="Solar wind model index (0 supported)"))
        self.NE_SW.value = 0.0
        self.SWM.value = 0.0

    def validate(self):
        if self.SWM.value not in (None, 0, 0.0):
            raise ValueError("only SWM 0 (spherical r^-2 wind) is supported")

    def device_slot(self, pname):
        if pname == "NE_SW":
            return "NE_SW", None
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        params0["NE_SW"] = self.NE_SW.value or 0.0

    def solar_wind_dm(self, params, batch, prep):
        """DM_sw per TOA [pc cm^-3]; differentiable."""
        import jax.numpy as jnp

        astrom = next((c for c in self._parent.delay_components()
                       if c.category == "astrometry"), None)
        if astrom is None:
            return jnp.zeros_like(batch.tdb_sec)
        n = astrom.ssb_to_psb_xyz(params, prep)
        sun = batch.obs_sun_ls
        r_ls = jnp.linalg.norm(sun, axis=-1)
        cos_t = jnp.clip(jnp.sum(sun * n, axis=-1) / r_ls, -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        sin_t = jnp.clip(jnp.sin(theta), 1e-6, None)
        r_au = r_ls / AU_LS
        geom_pc = ONE_AU_PC * (jnp.pi - theta) / (r_au * sin_t)
        return params["NE_SW"] * geom_pc

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm = self.solar_wind_dm(params, batch, prep)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm / f2, 0.0)
