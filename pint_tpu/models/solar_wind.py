"""Solar-wind dispersion delay.

(reference: src/pint/models/solar_wind_dispersion.py::SolarWindDispersion
— NE_SW electron density at 1 AU, spherically-symmetric n ~ r^-2 wind,
delay = DMconst * DM_sw / freq^2 with the (pi - theta)/(r sin theta)
line-of-sight geometry factor.)

Geometry: for n(d) = NE_SW (AU/d)^2 integrated from the observatory to
infinity along the line of sight,

    DM_sw = NE_SW * AU^2 * (pi - theta) / (r * sin(theta))

where r = |observatory -> Sun| and theta is the angle between the
observatory->Sun vector and the pulsar direction (elongation). All on
device and differentiable in NE_SW and the pulsar position.
"""

from __future__ import annotations

import numpy as np

from ..constants import AU_LS, DMconst, ONE_AU_PC
from .parameter import floatParameter
from .timing_model import DelayComponent


class SolarWindDispersion(DelayComponent):
    category = "solar_wind"
    order = 32

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter(
            "NE_SW", units="cm^-3", aliases=("NE1AU", "SOLARN0"),
            description="Solar wind electron density at 1 AU"))
        self.add_param(floatParameter(
            "SWM", units="", description="Solar wind model index (0 supported)"))
        self.NE_SW.value = 0.0
        self.SWM.value = 0.0

    def validate(self):
        if self.SWM.value not in (None, 0, 0.0):
            raise ValueError("only SWM 0 (spherical r^-2 wind) is supported")

    def device_slot(self, pname):
        if pname == "NE_SW":
            return "NE_SW", None
        raise KeyError(pname)

    def pack(self, model, toas, prep, params0):
        params0["NE_SW"] = self.NE_SW.value or 0.0

    def solar_wind_dm(self, params, batch, prep):
        """DM_sw per TOA [pc cm^-3]; differentiable."""
        import jax.numpy as jnp

        astrom = next((c for c in self._parent.delay_components()
                       if c.category == "astrometry"), None)
        if astrom is None:
            return jnp.zeros_like(batch.tdb_sec)
        n = astrom.ssb_to_psb_xyz(params, prep)
        sun = batch.obs_sun_ls
        r_ls = jnp.linalg.norm(sun, axis=-1)
        cos_t = jnp.clip(jnp.sum(sun * n, axis=-1) / r_ls, -1.0, 1.0)
        theta = jnp.arccos(cos_t)
        sin_t = jnp.clip(jnp.sin(theta), 1e-6, None)
        r_au = r_ls / AU_LS
        geom_pc = ONE_AU_PC * (jnp.pi - theta) / (r_au * sin_t)
        return params["NE_SW"] * geom_pc

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm = self.solar_wind_dm(params, batch, prep)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * dm / f2, 0.0)


class SolarWindDispersionX(SolarWindDispersion):
    """Piecewise solar wind (reference: solar_wind_dispersion.py::
    SolarWindDispersionX *(version-dependent)*): per-window electron
    densities SWXDM_#### active in [SWXR1_####, SWXR2_####] MJD,
    replacing the single NE_SW over those spans. Windows use the same
    spherical r^-2 geometry; outside all windows NE_SW applies.
    """

    category = "solar_windx"

    def __init__(self):
        super().__init__()
        self.swx_ids: list[int] = []

    def add_swx_range(self, index, mjd_lo, mjd_hi, ne=0.0):
        from .parameter import MJDParameter, prefixParameter

        p = prefixParameter(f"SWXDM_{index:04d}", "SWXDM_", index,
                            units="cm^-3")
        p.value = ne
        self.add_param(p)
        r1 = MJDParameter(f"SWXR1_{index:04d}", units="MJD")
        r1.value = mjd_lo
        self.add_param(r1)
        r2 = MJDParameter(f"SWXR2_{index:04d}", units="MJD")
        r2.value = mjd_hi
        self.add_param(r2)
        self.swx_ids.append(index)

    def device_slot(self, pname):
        if pname.startswith("SWXDM_"):
            return "SWXDM", self.swx_ids.index(int(pname[6:]))
        return super().device_slot(pname)

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        super().pack(model, toas, prep, params0)
        params0["SWXDM"] = np.array(
            [getattr(self, f"SWXDM_{i:04d}").value or 0.0
             for i in self.swx_ids], dtype=np.float64)
        mjd = toas.get_mjds()
        masks = np.stack([
            ((mjd >= getattr(self, f"SWXR1_{i:04d}").value)
             & (mjd < getattr(self, f"SWXR2_{i:04d}").value)).astype(np.float64)
            for i in self.swx_ids]) if self.swx_ids else np.zeros((0, len(toas)))
        prep["swx_masks"] = jnp.asarray(masks)

    def delay(self, params, batch, prep, delay_accum):
        import jax.numpy as jnp

        dm_geom = self.solar_wind_dm(
            {**params, "NE_SW": 1.0}, batch, prep)  # geometry for unit density
        masks = prep["swx_masks"]
        in_any = jnp.clip(jnp.sum(masks, axis=0), 0.0, 1.0)
        ne = (params["SWXDM"] @ masks if masks.shape[0]
              else jnp.zeros_like(dm_geom))
        ne = ne + params["NE_SW"] * (1.0 - in_any)
        f2 = jnp.square(batch.freq_mhz)
        return jnp.where(jnp.isfinite(f2), DMconst * ne * dm_geom / f2, 0.0)
