"""Absolute phase reference (TZRMJD/TZRSITE/TZRFRQ).

(reference: src/pint/models/absolute_phase.py::AbsPhase —
get_TZR_toa builds a single TOA at the reference epoch/site/frequency
and pushes it through the full pipeline; model phase is then quoted
relative to that TOA, so absolute pulse numbers agree across
observatories and with external ephemerides.)
"""

from __future__ import annotations

import numpy as np

from .timing_model import PhaseComponent


class AbsPhase(PhaseComponent):
    category = "absolute_phase"
    order = 90

    # TZRMJD/TZRSITE/TZRFRQ live as top-level model parameters
    # (builder.py TOP_LEVEL_*); this component consumes them.

    def __init__(self):
        super().__init__()
        self._tzr_cache: tuple[str, float, float] | None = None

    def get_TZR_toa(self, model):
        """The 1-TOA TOAs object at the reference point
        (reference: absolute_phase.py::AbsPhase.get_TZR_toa)."""
        from ..toa import TOA, TOAs

        tzr = model.TZRMJD
        site = (model.TZRSITE.value or "barycenter") if "TZRSITE" in model.params else "barycenter"
        freq = (model.TZRFRQ.value if "TZRFRQ" in model.params
                and model.TZRFRQ.value is not None else np.inf)
        if freq == 0.0:
            # tempo convention: TZRFRQ 0 means infinite frequency
            freq = np.inf
        ephem = (model.EPHEM.value if "EPHEM" in model.params
                 and model.EPHEM.value else "de440s")
        planets = ("PLANET_SHAPIRO" in model.params
                   and bool(model.PLANET_SHAPIRO.value))
        t = TOAs([TOA(int(tzr.day), float(tzr.sec), error_us=0.0,
                      freq_mhz=freq, obs=site)], ephem=ephem, planets=planets)
        t.apply_clock_corrections()
        t.compute_TDBs()
        t.compute_posvels()
        return t

    def pack(self, model, toas, prep, params0):
        import copy

        import jax.numpy as jnp

        if "TZRMJD" not in model.params or model.TZRMJD.value is None:
            prep["tzr_frac"] = jnp.float64(0.0)
            return
        # the TZR phase depends only on the model, not the data TOAs;
        # cache it across prepare() calls keyed on full model state
        from ..utils import compute_hash

        key = compute_hash(model.as_parfile())
        if self._tzr_cache is not None and self._tzr_cache[0] == key:
            _, tzr_int, tzr_frac = self._tzr_cache
        else:
            tzr_toas = self.get_TZR_toa(model)
            # evaluate the model's own phase at the TZR point (without
            # this component, to avoid recursion) at reference params
            m2 = copy.deepcopy(model)
            m2.remove_component("AbsPhase")
            ph = m2.prepare(tzr_toas, subtract_mean=False).phase()
            tzr_frac = float(np.asarray(ph.frac)[0])
            tzr_int = float(np.asarray(ph.int_)[0])
            self._tzr_cache = (key, tzr_int, tzr_frac)
        prep["tzr_frac"] = jnp.float64(tzr_frac)
        # fold the integer reference into the packed integer phase so
        # Phase.int_ counts pulses since the TZR TOA
        prep["phi_ref_int"] = prep["phi_ref_int"] - jnp.float64(tzr_int)

    def phase(self, params, batch, prep, delay_total):
        import jax.numpy as jnp

        return -prep["tzr_frac"] * jnp.ones_like(batch.tdb_sec)
