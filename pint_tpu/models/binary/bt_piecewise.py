"""BT_piecewise: BT binary with piecewise-constant T0 and A1.

(reference: src/pint/models/binary_piecewise.py::BinaryBTPiecewise +
stand_alone_psr_binaries/BT_piecewise.py — prefix groups T0X_####/
A1X_#### with MJD boundaries XR1_####/XR2_####; TOAs inside a group's
window use that group's T0/A1, TOAs outside every window use the
global values.)

TPU mapping: group membership is resolved at pack time into a static
per-TOA segment index (pieces are defined by MJD windows, which never
change during a fit), while the piece values T0X/A1X live in flat
device vectors indexed by piece — so every piece parameter is
differentiable and fittable, and the delay is a single gather away
from the plain BT path (no per-piece python loop on device).
"""

from __future__ import annotations

import numpy as np

from ..parameter import MJDParameter, prefixParameter
from ..timing_model import MissingParameter
from .bt import BinaryBT


class BinaryBTPiecewise(BinaryBT):
    binary_model_name = "BT_piecewise"

    def __init__(self):
        super().__init__()
        self.piece_ids: list[int] = []

    # ---- piece management (reference: BinaryBTPiecewise.add_group_range
    # + add_piecewise_param) ----

    def add_piece(self, index=None, mjd_start=None, mjd_end=None,
                  t0x=None, a1x=None, frozen=True):
        """Create piece ``index`` with window [mjd_start, mjd_end].

        Either of ``t0x``/``a1x`` may stay None: the piece then keeps
        the global value for that element (matching the reference,
        where a group may carry only a T0X or only an A1X).
        """
        index = index if index is not None else (
            max(self.piece_ids, default=-1) + 1)
        from ...constants import SECS_PER_DAY

        t0p = MJDParameter(f"T0X_{index:04d}", units="MJD", frozen=frozen,
                           description=f"piecewise T0, group {index}")
        if t0x is not None:
            t0p.set_mjd(int(t0x), (t0x % 1) * SECS_PER_DAY)
        self.add_param(t0p)
        a1p = prefixParameter(f"A1X_{index:04d}", "A1X_", index, units="ls",
                              frozen=frozen,
                              description=f"piecewise A1, group {index}")
        if a1x is not None:
            a1p.value = a1x
        self.add_param(a1p)
        r1 = MJDParameter(f"XR1_{index:04d}", units="MJD")
        if mjd_start is not None:
            r1.set_mjd(int(mjd_start), (mjd_start % 1) * SECS_PER_DAY)
        self.add_param(r1)
        r2 = MJDParameter(f"XR2_{index:04d}", units="MJD")
        if mjd_end is not None:
            r2.set_mjd(int(mjd_end), (mjd_end % 1) * SECS_PER_DAY)
        self.add_param(r2)
        self.piece_ids.append(index)
        return index

    def add_prefix_members(self, keys):
        super().add_prefix_members(keys)
        ids = sorted({int(k.split("_")[1]) for k in keys
                      if k.split("_")[0] in ("T0X", "A1X", "XR1", "XR2")
                      and k.split("_")[-1].isdigit()})
        for i in ids:
            self.add_piece(i)

    def validate(self):
        super().validate()
        for i in self.piece_ids:
            r1 = getattr(self, f"XR1_{i:04d}").value
            r2 = getattr(self, f"XR2_{i:04d}").value
            if r1 is None or r2 is None or not r1 < r2:
                raise MissingParameter(
                    "BinaryBTPiecewise", f"XR1_{i:04d}/XR2_{i:04d}",
                    f"piece {i} needs a non-empty MJD window "
                    f"(got [{r1}, {r2}])")
        # overlapping windows make the piece assignment order-dependent
        wins = sorted((getattr(self, f"XR1_{i:04d}").value,
                       getattr(self, f"XR2_{i:04d}").value, i)
                      for i in self.piece_ids)
        for (lo1, hi1, i1), (lo2, hi2, i2) in zip(wins, wins[1:]):
            if lo2 < hi1:
                raise ValueError(
                    f"BT_piecewise windows {i1} [{lo1},{hi1}] and "
                    f"{i2} [{lo2},{hi2}] overlap")

    def device_slot(self, pname):
        stem = pname.split("_")[0]
        if stem in ("T0X", "A1X") and pname.split("_")[-1].isdigit():
            return stem, self.piece_ids.index(int(pname.split("_")[1]))
        return super().device_slot(pname)

    # ---- host pack ----

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        super().pack(model, toas, prep, params0)
        ids = self.piece_ids
        t0_global = self.T0.value
        a1_global = self.A1.value
        n = max(len(ids), 1)
        t0x = np.full(n, t0_global, dtype=np.float64)
        a1x = np.full(n, a1_global, dtype=np.float64)
        has_t0 = np.zeros(n, dtype=bool)
        has_a1 = np.zeros(n, dtype=bool)
        mjds = toas.get_mjds()
        seg = np.full(len(toas), -1, dtype=np.int32)
        for k, i in enumerate(ids):
            tp = getattr(self, f"T0X_{i:04d}")
            ap = getattr(self, f"A1X_{i:04d}")
            has_t0[k] = tp.value is not None
            has_a1[k] = ap.value is not None
            t0x[k] = tp.value if has_t0[k] else t0_global
            a1x[k] = ap.value if has_a1[k] else a1_global
            lo = getattr(self, f"XR1_{i:04d}").value
            hi = getattr(self, f"XR2_{i:04d}").value
            # half-open [lo, hi) like models/piecewise.py: a TOA on a
            # shared boundary of touching windows belongs to one piece
            seg[(mjds >= lo) & (mjds < hi)] = k
        params0["T0X"] = t0x
        params0["A1X"] = a1x
        # base pack published each member as a scalar leaf; the device
        # reads only the packed vectors (same convention as FB members)
        for i in ids:
            for stem in ("T0X", "A1X", "XR1", "XR2"):
                params0.pop(f"{stem}_{i:04d}", None)
        prep["btpw_seg"] = jnp.asarray(seg)
        prep["btpw_has_t0"] = jnp.asarray(has_t0)
        prep["btpw_has_a1"] = jnp.asarray(has_a1)

    # ---- device delay ----

    def delay(self, params, batch, prep, delay_accum):
        if not self.piece_ids:
            return super().delay(params, batch, prep, delay_accum)
        import jax.numpy as jnp

        seg = prep["btpw_seg"]
        safe = jnp.clip(seg, 0, None)
        in_piece = seg >= 0
        # per-TOA effective elements: a piece that never set T0X/A1X
        # follows the (possibly fitted) global parameter instead of the
        # stale pack-time copy
        eff = dict(params)
        eff["T0"] = jnp.where(in_piece & prep["btpw_has_t0"][safe],
                              params["T0X"][safe], params["T0"])
        eff["A1"] = jnp.where(in_piece & prep["btpw_has_a1"][safe],
                              params["A1X"][safe], params["A1"])
        return super().delay(eff, batch, prep, delay_accum)
