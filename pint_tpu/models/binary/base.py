"""Binary component base: orbital phase with host-reference precision.

(reference: src/pint/models/pulsar_binary.py::PulsarBinary +
stand_alone_psr_binaries/binary_generic.py::PSR_BINARY and
binary_orbits.py::OrbitPB/OrbitFBX.)

The reference strips astropy units and calls a standalone numpy model;
here the analogous split is host/device: the host packs the orbit
count n_orb(t) at reference parameters in longdouble (exact int+frac,
like the spindown phi_ref), and the device evaluates only exact small
deltas — parameter shifts (Sterbenz-exact near-equal subtractions) and
the accumulated delay shift — so mean anomaly survives TPU's ~47-bit
f64 for arbitrarily wide orbits and decade spans.
"""

from __future__ import annotations

import numpy as np

from ...constants import SECS_PER_DAY, SECS_PER_JULIAN_YEAR
from ...mjd import LD
from ..parameter import MJDParameter, floatParameter, prefixParameter
from ..timing_model import DelayComponent, MissingParameter

_DEG2RAD = np.pi / 180.0
_TWO_PI = 2.0 * np.pi


class PulsarBinary(DelayComponent):
    category = "pulsar_system"
    order = 40
    binary_model_name = "base"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("PB", units="d", description="Orbital period"))
        self.add_param(floatParameter("PBDOT", units="s/s", description="Orbital period derivative"))
        self.add_param(floatParameter("A1", units="ls", description="Projected semi-major axis"))
        self.add_param(floatParameter("A1DOT", units="ls/s", aliases=("XDOT",),
                                      description="Rate of change of A1"))
        self.add_param(MJDParameter("T0", units="MJD", description="Epoch of periastron"))
        self.fb_ids: list[int] = []

    def add_prefix_members(self, keys):
        """Add FBn orbital-frequency terms found in the par file."""
        i = 0
        while f"FB{i}" in keys:
            p = prefixParameter(f"FB{i}", "FB", i, units=f"1/s^{i+1}")
            self.add_param(p)
            self.fb_ids.append(i)
            i += 1

    def device_slot(self, pname):
        if pname.startswith("FB") and pname[2:].isdigit():
            return "FB", self.fb_ids.index(int(pname[2:]))
        return pname, None

    # ---- epoch helpers ----

    def _epoch_param(self):
        """The orbital reference epoch parameter (T0 or TASC)."""
        return self.T0

    def validate(self):
        if self.A1.value is None:
            raise MissingParameter(type(self).__name__, "A1")
        if not self.fb_ids and self.PB.value is None:
            raise MissingParameter(type(self).__name__, "PB (or FB0)")
        if self._epoch_param().value is None:
            raise MissingParameter(type(self).__name__,
                                   self._epoch_param().name)

    # ---- host pack ----

    def pack(self, model, toas, prep, params0):
        import jax.numpy as jnp

        ep = self._epoch_param()
        t0_day, t0_sec = ep.day, ep.sec
        dt_hi = (toas.tdb.day - t0_day).astype(np.float64) * SECS_PER_DAY
        dt_lo = toas.tdb.sec - t0_sec
        prep["orb_dt_hi"] = jnp.asarray(dt_hi)
        prep["orb_dt_lo"] = jnp.asarray(dt_lo)
        dt_ld = LD(dt_hi) + LD(dt_lo)
        pbdot = self.PBDOT.value or 0.0
        if self.fb_ids:
            fb = np.array([getattr(self, f"FB{i}").value or 0.0 for i in self.fb_ids])
            params0["FB"] = fb
            prep["FB_ref"] = jnp.asarray(fb)
            norb = np.zeros_like(dt_ld)
            fact = LD(1.0)
            for i, f in enumerate(fb):
                fact = fact * LD(i + 1)
                norb = norb + LD(f) * dt_ld ** (i + 1) / fact
            prep["orb_mode_fb"] = True
        else:
            pb_s = LD(self.PB.value) * LD(SECS_PER_DAY)
            phi = dt_ld / pb_s
            norb = phi - LD(0.5) * LD(pbdot) * phi * phi
            prep["orb_mode_fb"] = False
        n_int = np.floor(norb + LD(0.5))
        prep["norb_ref_frac"] = jnp.asarray((norb - n_int).astype(np.float64))
        prep["norb_ref_int"] = jnp.asarray(n_int.astype(np.float64))
        prep["PB_ref"] = jnp.asarray(self.PB.value or 0.0, jnp.float64)
        prep["PBDOT_ref"] = jnp.asarray(pbdot, jnp.float64)
        prep["T0_ref"] = jnp.asarray(ep.value, jnp.float64)
        for pname in self.params:
            par = getattr(self, pname)
            if pname.startswith("FB"):
                continue
            params0[pname] = par.value if par.value is not None else 0.0

    # ---- device orbital phase ----

    def orbital_phase(self, params, prep, delay_accum):
        """Mean orbital phase [rad], exact modulo 2*pi.

        (reference: binary_orbits.py::OrbitPB.orbit_phase / OrbitFBX)
        """
        import jax.numpy as jnp

        dt = prep["orb_dt_hi"] + prep["orb_dt_lo"]  # f64 collapse, ~8e-6 s err
        frac = prep["norb_ref_frac"]
        ep_name = self._epoch_param().name
        d_epoch_s = (params[ep_name] - prep["T0_ref"]) * SECS_PER_DAY
        teff_shift = -(delay_accum + d_epoch_s)  # binary time minus ref time
        if prep["orb_mode_fb"]:
            FB = params["FB"]
            FB_ref = prep["FB_ref"]
            f_orb = jnp.zeros_like(dt)
            dnorb = jnp.zeros_like(dt)
            fact = 1.0
            tp = dt
            for i in range(FB.shape[0]):
                fact *= i + 1
                dnorb = dnorb + (FB[i] - FB_ref[i]) * tp / fact
                tp = tp * dt
            # instantaneous orbital frequency for the time-shift term
            fact = 1.0
            tp = jnp.ones_like(dt)
            for i in range(FB.shape[0]):
                if i > 0:
                    fact *= i
                f_orb = f_orb + FB[i] * tp / fact
                tp = tp * dt
            dnorb = dnorb + f_orb * teff_shift
        else:
            pb_ref_s = prep["PB_ref"] * SECS_PER_DAY
            pb_s = params["PB"] * SECS_PER_DAY
            # (1/PB - 1/PB_ref), exact for near-equal values
            dinv = (prep["PB_ref"] - params["PB"]) / (params["PB"] * prep["PB_ref"] * SECS_PER_DAY)
            phi_ref = dt / pb_ref_s
            dnorb = dt * dinv + teff_shift / pb_s
            # PBDOT delta + cross terms (all small)
            dnorb = dnorb - 0.5 * (params["PBDOT"] - prep["PBDOT_ref"]) * phi_ref**2
            dnorb = dnorb - prep["PBDOT_ref"] * phi_ref * (dt * dinv + teff_shift / pb_s)
        total_frac = frac + dnorb
        return _TWO_PI * (total_frac - jnp.floor(total_frac + 0.5))

    # ---- shared element helpers (device) ----

    def x_ls(self, params, prep, delay_accum):
        """Projected semimajor axis x(t) [ls] with A1DOT."""
        dt = prep["orb_dt_hi"] + prep["orb_dt_lo"] - delay_accum
        return params["A1"] + params.get("A1DOT", 0.0) * dt

    def omega_rad(self, params, prep, delay_accum, nu=None):
        """Longitude of periastron [rad]; OMDOT applied linearly in time
        (or via true anomaly when nu is given, DD-style)."""
        om = params.get("OM", 0.0) * _DEG2RAD
        omdot = params.get("OMDOT", 0.0) * _DEG2RAD / SECS_PER_JULIAN_YEAR
        if nu is not None:
            # mean orbital angular frequency, from FB0 in FBn mode
            # (PB is packed as 0.0 there) else from PB
            if prep["orb_mode_fb"]:
                n_orb = _TWO_PI * params["FB"][0]
            else:
                n_orb = _TWO_PI / (params["PB"] * SECS_PER_DAY)
            return om + (omdot / n_orb) * nu
        dt = prep["orb_dt_hi"] + prep["orb_dt_lo"] - delay_accum
        return om + omdot * dt

    def ecc(self, params, prep, delay_accum):
        dt = prep["orb_dt_hi"] + prep["orb_dt_lo"] - delay_accum
        return params.get("ECC", 0.0) + params.get("EDOT", 0.0) * dt


def kepler_solve(M, e, iters=8):
    """Eccentric anomaly from mean anomaly, fixed-iteration Newton.

    Fixed count (no data-dependent control flow) so the solve is
    jit/vmap-safe and differentiable (reference: BT_model.py Newton
    loop; SURVEY.md 7.3 item 6).
    """
    import jax.numpy as jnp

    E = M + e * jnp.sin(M)
    for _ in range(iters):
        E = E - (E - e * jnp.sin(E) - M) / (1.0 - e * jnp.cos(E))
    return E


def orthometric_shapiro_rs(h3, sigma):
    """(range r [s], shape sini) from the orthometric Shapiro
    parameters (Freire & Wex 2010: sini = 2 sigma/(1+sigma^2),
    r = h3/sigma^3). Single home for the mapping shared by BinaryELL1H
    and BinaryDDH; sigma = 0 (unset) degrades to r = h3, sini = 0
    rather than dividing by zero."""
    import jax.numpy as jnp

    sini = 2.0 * sigma / (1.0 + sigma**2)
    r = h3 / jnp.where(sigma == 0.0, 1.0, sigma**3)
    return r, sini
