"""DD (Damour & Deruelle 1986) binary model family: DD, DDS, DDK.

(reference: src/pint/models/stand_alone_psr_binaries/DD_model.py::DDmodel,
DDS_model.py, DDK_model.py; wrappers binary_dd.py, binary_ddk.py.)

Full relativistic timing model: Roemer with e_r/e_theta, Einstein
(GAMMA sin u), Shapiro (M2/SINI log term), aberration (A0/B0), with
periastron advance applied via true anomaly.

DDS: SINI reparameterized as 1 - exp(-SHAPMAX).
DDK: Kopeikin (1995/1996) corrections — annual-orbital parallax and
proper-motion-induced secular changes of x and omega, from KIN/KOM and
the packed observatory positions.
"""

from __future__ import annotations

import numpy as np

from ...constants import (TSUN_S, MASYR_TO_RADS, MAS_TO_RAD, PC_M, C_M_S,
                          SECS_PER_DAY, SECS_PER_JULIAN_YEAR)
from ..parameter import floatParameter
from ..timing_model import MissingParameter
from .base import PulsarBinary, kepler_solve, _TWO_PI

_DEG2RAD = np.pi / 180.0


class BinaryDD(PulsarBinary):
    binary_model_name = "DD"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("ECC", units="", aliases=("E",)))
        self.add_param(floatParameter("EDOT", units="1/s"))
        self.add_param(floatParameter("OM", units="deg"))
        self.add_param(floatParameter("OMDOT", units="deg/yr"))
        self.add_param(floatParameter("GAMMA", units="s"))
        self.add_param(floatParameter("M2", units="Msun"))
        self.add_param(floatParameter("SINI", units=""))
        self.add_param(floatParameter("DR", units=""))
        self.add_param(floatParameter("DTH", units=""))
        self.add_param(floatParameter("A0", units="s", description="Aberration A0"))
        self.add_param(floatParameter("B0", units="s", description="Aberration B0"))

    def sini(self, params):
        return params.get("SINI", 0.0)

    def shapiro_rs(self, params):
        """(range r [s], shape s) of the Shapiro delay — the hook DDH
        overrides with the orthometric parameterization."""
        return TSUN_S * params.get("M2", 0.0), self.sini(params)

    def _dd_delay_at(self, params, prep, delay_accum):
        import jax.numpy as jnp

        M = self.orbital_phase(params, prep, delay_accum)
        e = self.ecc(params, prep, delay_accum)
        u = kepler_solve(M, e)
        su, cu = jnp.sin(u), jnp.cos(u)
        # true anomaly
        nu = 2.0 * jnp.arctan2(jnp.sqrt(1.0 + e) * jnp.sin(u / 2.0),
                               jnp.sqrt(1.0 - e) * jnp.cos(u / 2.0))
        om = self.omega_rad(params, prep, delay_accum, nu=nu)
        so, co = jnp.sin(om), jnp.cos(om)
        x = self.x_ls(params, prep, delay_accum)
        er = e * (1.0 + params.get("DR", 0.0))
        eth = e * (1.0 + params.get("DTH", 0.0))
        # Roemer + Einstein (DD86 eq. 46-52)
        alpha = x * so
        beta = x * jnp.sqrt(1.0 - eth**2) * co
        roemer = alpha * (cu - er) + beta * su
        einstein = params.get("GAMMA", 0.0) * su
        # Shapiro (DD86 eq. 26)
        r, s = self.shapiro_rs(params)
        shapiro = -2.0 * r * jnp.log(1.0 - e * cu
                                     - s * (so * (cu - e)
                                            + jnp.sqrt(1.0 - e**2) * co * su))
        # aberration (DD86 eq. 27)
        a0 = params.get("A0", 0.0)
        b0 = params.get("B0", 0.0)
        aberr = (a0 * (jnp.sin(om + nu) + e * so)
                 + b0 * (jnp.cos(om + nu) + e * co))
        return roemer + einstein + shapiro + aberr

    def delay(self, params, batch, prep, delay_accum):
        d = self._dd_delay_at(params, prep, delay_accum)
        d = self._dd_delay_at(params, prep, delay_accum + d)
        return self._dd_delay_at(params, prep, delay_accum + d)


class BinaryDDGR(BinaryDD):
    """DDGR: GR-constrained DD (reference: DDGR_model.py::DDGRmodel).

    The post-Keplerian parameters (OMDOT, GAMMA, PBDOT, SINI, DR, DTH)
    are not free: they are derived from the total mass MTOT and the
    companion mass M2 via the GR relations (Damour & Deruelle 1986;
    Taylor & Weisberg 1989). XOMDOT/XPBDOT are additive non-GR excess
    terms. Because the whole delay is jax-differentiable, the design
    matrix w.r.t. MTOT/M2 flows through these relations via jacfwd.
    """

    binary_model_name = "DDGR"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("MTOT", units="Msun", aliases=("M",),
                                      description="Total system mass"))
        self.add_param(floatParameter("XOMDOT", units="deg/yr",
                                      description="Excess periastron advance"))
        self.add_param(floatParameter("XPBDOT", units="s/s",
                                      description="Excess orbital period decay"))

    def validate(self):
        super().validate()
        if self.MTOT.value is None:
            raise MissingParameter("BinaryDDGR", "MTOT")
        if self.M2.value is None:
            raise MissingParameter("BinaryDDGR", "M2")

    def _gr_params(self, params, prep):
        """Derived PK parameters from (MTOT, M2) — all dimensionless or
        in seconds; masses in Msun via TSUN_S."""
        import jax.numpy as jnp

        M = params["MTOT"]
        m2 = params["M2"]
        m1 = M - m2
        e = params.get("ECC", 0.0)
        if prep["orb_mode_fb"]:
            n = _TWO_PI * params["FB"][0]
        else:
            n = _TWO_PI / (params["PB"] * SECS_PER_DAY)
        u2 = (TSUN_S * M * n) ** (2.0 / 3.0)  # (GM n / c^3)^(2/3), dimensionless
        k = 3.0 * u2 / (1.0 - e**2)  # periastron advance per radian of nu
        gamma = (e * (TSUN_S ** (2.0 / 3.0)) * n ** (-1.0 / 3.0)
                 * m2 * (m1 + 2.0 * m2) * M ** (-4.0 / 3.0))
        pbdot = (-(192.0 * jnp.pi / 5.0) * (TSUN_S * n) ** (5.0 / 3.0)
                 * m1 * m2 * M ** (-1.0 / 3.0)
                 * (1.0 + (73.0 / 24.0) * e**2 + (37.0 / 96.0) * e**4)
                 * (1.0 - e**2) ** (-3.5))
        sini = (params["A1"] * n ** (2.0 / 3.0) * M ** (2.0 / 3.0)
                / (TSUN_S ** (1.0 / 3.0) * m2))
        dr = (3.0 * m1**2 + 6.0 * m1 * m2 + 2.0 * m2**2) / M**2 * u2
        dth = (3.5 * m1**2 + 6.0 * m1 * m2 + 2.0 * m2**2) / M**2 * u2
        return {"k": k, "GAMMA": gamma, "PBDOT": pbdot, "SINI": sini,
                "DR": dr, "DTH": dth, "n": n}

    def _merged(self, params, prep):
        if "_GR_MERGED" in params:
            return params
        gr = self._gr_params(params, prep)
        # OMDOT equivalent: omega advances by k per radian of true
        # anomaly; omega_rad applies OMDOT/n_orb * nu, so the
        # effective OMDOT [rad/s] is k*n (+ excess XOMDOT).
        omdot = (gr["k"] * gr["n"] * SECS_PER_JULIAN_YEAR / _DEG2RAD
                 + params.get("XOMDOT", 0.0))
        out = dict(params)
        out.update(GAMMA=gr["GAMMA"], SINI=gr["SINI"], DR=gr["DR"],
                   DTH=gr["DTH"], OMDOT=omdot,
                   PBDOT=params.get("PBDOT", 0.0) + gr["PBDOT"]
                   + params.get("XPBDOT", 0.0))
        out["_GR_MERGED"] = True
        return out

    def orbital_phase(self, params, prep, delay_accum):
        return super().orbital_phase(self._merged(params, prep), prep,
                                     delay_accum)

    def _dd_delay_at(self, params, prep, delay_accum):
        return super()._dd_delay_at(self._merged(params, prep), prep,
                                    delay_accum)


class BinaryDDS(BinaryDD):
    """DDS: high-inclination reparameterization SHAPMAX = -ln(1-SINI)
    (reference: DDS_model.py::DDSmodel)."""

    binary_model_name = "DDS"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("SHAPMAX", units=""))

    def sini(self, params):
        import jax.numpy as jnp

        return 1.0 - jnp.exp(-params.get("SHAPMAX", 0.0))


class BinaryDDH(BinaryDD):
    """DDH: DD with the orthometric Shapiro parameterization
    (H3 + STIGMA, or H3 + H4 with sigma = H4/H3) of Freire & Wex 2010
    in place of (M2, SINI) — better-conditioned for intermediate
    inclinations (reference: binary_dd.py::BinaryDDH / DDH_model.py).
    M2/SINI are REMOVED: they would be silent no-ops here (the delay
    never reads them), exactly why the reference's DDH drops them.
    """

    binary_model_name = "DDH"

    def __init__(self):
        super().__init__()
        self.remove_param("M2")
        self.remove_param("SINI")
        self.add_param(floatParameter(
            "H3", units="s", description="Orthometric amplitude h3"))
        self.add_param(floatParameter(
            "H4", units="s", description="Orthometric amplitude h4"))
        self.add_param(floatParameter(
            "STIGMA", units="", aliases=("VARSIGMA", "STIG"),
            description="Orthometric ratio"))

    def validate(self):
        super().validate()
        if self.H3.value is None:
            raise MissingParameter("BinaryDDH", "H3")
        if self.STIGMA.value is None and self.H4.value is None:
            raise MissingParameter(
                "BinaryDDH", "STIGMA",
                "DDH needs STIGMA (or H4, for sigma = H4/H3) with H3")

    def _stigma(self, params):
        import jax.numpy as jnp

        if self.STIGMA.value is not None:
            return params.get("STIGMA", 0.0)
        h3 = params.get("H3", 0.0)
        return params.get("H4", 0.0) / jnp.where(h3 == 0.0, 1.0, h3)

    def shapiro_rs(self, params):
        from .base import orthometric_shapiro_rs

        return orthometric_shapiro_rs(params.get("H3", 0.0),
                                      self._stigma(params))

    def sini(self, params):
        return self.shapiro_rs(params)[1]


class BinaryDDK(BinaryDD):
    """DDK: Kopeikin annual-orbital parallax + proper-motion terms
    (reference: DDK_model.py::DDKmodel; params KIN, KOM).

    x and omega acquire (a) secular drifts from proper motion and
    (b) annual terms from the observatory's SSB orbit projected on the
    sky basis (I0, J0) — both require KIN/KOM and PX from astrometry.
    """

    binary_model_name = "DDK"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("KIN", units="deg", description="Inclination"))
        self.add_param(floatParameter("KOM", units="deg",
                                      description="Long. of ascending node"))
        self.add_param(floatParameter("K96", units="", description="Apply K96 PM terms"))

    def sini(self, params):
        import jax.numpy as jnp

        return jnp.sin(params.get("KIN", 0.0) * _DEG2RAD)

    def pack(self, model, toas, prep, params0):
        super().pack(model, toas, prep, params0)
        # sky basis for Kopeikin terms: unit vectors east (I0) and
        # north (J0) at the reference position
        astrom = next(c for c in model.delay_components()
                      if c.category == "astrometry")
        import jax.numpy as jnp

        n = np.asarray(astrom.ssb_to_psb_xyz(
            {k: np.asarray(v) for k, v in params0.items()}, prep))[0]
        zhat = np.array([0.0, 0.0, 1.0])
        east = np.cross(zhat, n)
        east /= np.linalg.norm(east)
        north = np.cross(n, east)
        prep["ddk_east"] = jnp.asarray(east)
        prep["ddk_north"] = jnp.asarray(north)
        # proper motion [rad/s] in (east, north)
        pm_e = (model.PMRA.value or 0.0) if "PMRA" in model.params else (
            model.PMELONG.value or 0.0)
        pm_n = (model.PMDEC.value or 0.0) if "PMDEC" in model.params else (
            model.PMELAT.value or 0.0)
        prep["ddk_pm_e"] = pm_e * MASYR_TO_RADS
        prep["ddk_pm_n"] = pm_n * MASYR_TO_RADS
        px = model.PX.value if "PX" in model.params and model.PX.value else 0.0
        prep["ddk_dist_ls"] = (1000.0 / px * PC_M / C_M_S) if px else np.inf
        # observatory SSB positions [ls], packed so the Kopeikin terms
        # never need the TOABatch threaded through x_ls/omega_rad
        if toas.ssb_obs is None:
            toas.compute_posvels()
        prep["ddk_obs_ls"] = jnp.asarray(toas.ssb_obs.pos / C_M_S)

    def _kopeikin_xom(self, params, prep, delay_accum):
        """(delta_x, delta_omega) from proper motion + annual parallax."""
        import jax.numpy as jnp

        kin = params.get("KIN", 0.0) * _DEG2RAD
        kom = params.get("KOM", 0.0) * _DEG2RAD
        sk, ck = jnp.sin(kom), jnp.cos(kom)
        x = params["A1"]
        dt = prep["orb_dt_hi"] + prep["orb_dt_lo"] - delay_accum
        mu_e, mu_n = prep["ddk_pm_e"], prep["ddk_pm_n"]
        cot_i = jnp.cos(kin) / jnp.sin(kin)
        csc_i = 1.0 / jnp.sin(kin)
        # K96 proper-motion secular terms (Kopeikin 1996 eq. 10-11)
        dx_pm = x * cot_i * (-mu_e * sk + mu_n * ck) * dt
        dom_pm = csc_i * (mu_e * ck + mu_n * sk) * dt
        # annual-orbital parallax (Kopeikin 1995 eq. 15-16)
        robs = prep["ddk_obs_ls"]  # [ls]
        d_ls = prep["ddk_dist_ls"]
        de = jnp.sum(robs * prep["ddk_east"], axis=-1) / d_ls
        dn = jnp.sum(robs * prep["ddk_north"], axis=-1) / d_ls
        dx_px = x * cot_i * (de * sk - dn * ck)
        dom_px = -csc_i * (de * ck + dn * sk)
        return dx_pm + dx_px, dom_pm + dom_px

    def x_ls(self, params, prep, delay_accum):
        dx, _ = self._kopeikin_xom(params, prep, delay_accum)
        return super().x_ls(params, prep, delay_accum) + dx

    def omega_rad(self, params, prep, delay_accum, nu=None):
        _, dom = self._kopeikin_xom(params, prep, delay_accum)
        return super().omega_rad(params, prep, delay_accum, nu=nu) + dom
