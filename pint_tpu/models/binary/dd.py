"""DD (Damour & Deruelle 1986) binary model family: DD, DDS, DDK.

(reference: src/pint/models/stand_alone_psr_binaries/DD_model.py::DDmodel,
DDS_model.py, DDK_model.py; wrappers binary_dd.py, binary_ddk.py.)

Full relativistic timing model: Roemer with e_r/e_theta, Einstein
(GAMMA sin u), Shapiro (M2/SINI log term), aberration (A0/B0), with
periastron advance applied via true anomaly.

DDS: SINI reparameterized as 1 - exp(-SHAPMAX).
DDK: Kopeikin (1995/1996) corrections — annual-orbital parallax and
proper-motion-induced secular changes of x and omega, from KIN/KOM and
the packed observatory positions.
"""

from __future__ import annotations

import numpy as np

from ...constants import TSUN_S, MASYR_TO_RADS, MAS_TO_RAD, PC_M, C_M_S
from ..parameter import floatParameter
from .base import PulsarBinary, kepler_solve

_DEG2RAD = np.pi / 180.0


class BinaryDD(PulsarBinary):
    binary_model_name = "DD"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("ECC", units="", aliases=("E",)))
        self.add_param(floatParameter("EDOT", units="1/s"))
        self.add_param(floatParameter("OM", units="deg"))
        self.add_param(floatParameter("OMDOT", units="deg/yr"))
        self.add_param(floatParameter("GAMMA", units="s"))
        self.add_param(floatParameter("M2", units="Msun"))
        self.add_param(floatParameter("SINI", units=""))
        self.add_param(floatParameter("DR", units=""))
        self.add_param(floatParameter("DTH", units=""))
        self.add_param(floatParameter("A0", units="s", description="Aberration A0"))
        self.add_param(floatParameter("B0", units="s", description="Aberration B0"))

    def sini(self, params):
        return params.get("SINI", 0.0)

    def _dd_delay_at(self, params, prep, delay_accum):
        import jax.numpy as jnp

        M = self.orbital_phase(params, prep, delay_accum)
        e = self.ecc(params, prep, delay_accum)
        u = kepler_solve(M, e)
        su, cu = jnp.sin(u), jnp.cos(u)
        # true anomaly
        nu = 2.0 * jnp.arctan2(jnp.sqrt(1.0 + e) * jnp.sin(u / 2.0),
                               jnp.sqrt(1.0 - e) * jnp.cos(u / 2.0))
        om = self.omega_rad(params, prep, delay_accum, nu=nu)
        so, co = jnp.sin(om), jnp.cos(om)
        x = self.x_ls(params, prep, delay_accum)
        er = e * (1.0 + params.get("DR", 0.0))
        eth = e * (1.0 + params.get("DTH", 0.0))
        # Roemer + Einstein (DD86 eq. 46-52)
        alpha = x * so
        beta = x * jnp.sqrt(1.0 - eth**2) * co
        roemer = alpha * (cu - er) + beta * su
        einstein = params.get("GAMMA", 0.0) * su
        # Shapiro (DD86 eq. 26)
        r = TSUN_S * params.get("M2", 0.0)
        s = self.sini(params)
        shapiro = -2.0 * r * jnp.log(1.0 - e * cu
                                     - s * (so * (cu - e)
                                            + jnp.sqrt(1.0 - e**2) * co * su))
        # aberration (DD86 eq. 27)
        a0 = params.get("A0", 0.0)
        b0 = params.get("B0", 0.0)
        aberr = (a0 * (jnp.sin(om + nu) + e * so)
                 + b0 * (jnp.cos(om + nu) + e * co))
        return roemer + einstein + shapiro + aberr

    def delay(self, params, batch, prep, delay_accum):
        d = self._dd_delay_at(params, prep, delay_accum)
        d = self._dd_delay_at(params, prep, delay_accum + d)
        return self._dd_delay_at(params, prep, delay_accum + d)


class BinaryDDS(BinaryDD):
    """DDS: high-inclination reparameterization SHAPMAX = -ln(1-SINI)
    (reference: DDS_model.py::DDSmodel)."""

    binary_model_name = "DDS"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("SHAPMAX", units=""))

    def sini(self, params):
        import jax.numpy as jnp

        return 1.0 - jnp.exp(-params.get("SHAPMAX", 0.0))


class BinaryDDK(BinaryDD):
    """DDK: Kopeikin annual-orbital parallax + proper-motion terms
    (reference: DDK_model.py::DDKmodel; params KIN, KOM).

    x and omega acquire (a) secular drifts from proper motion and
    (b) annual terms from the observatory's SSB orbit projected on the
    sky basis (I0, J0) — both require KIN/KOM and PX from astrometry.
    """

    binary_model_name = "DDK"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("KIN", units="deg", description="Inclination"))
        self.add_param(floatParameter("KOM", units="deg",
                                      description="Long. of ascending node"))
        self.add_param(floatParameter("K96", units="", description="Apply K96 PM terms"))

    def sini(self, params):
        import jax.numpy as jnp

        return jnp.sin(params.get("KIN", 0.0) * _DEG2RAD)

    def pack(self, model, toas, prep, params0):
        super().pack(model, toas, prep, params0)
        # sky basis for Kopeikin terms: unit vectors east (I0) and
        # north (J0) at the reference position
        astrom = next(c for c in model.delay_components()
                      if c.category == "astrometry")
        import jax.numpy as jnp

        n = np.asarray(astrom.ssb_to_psb_xyz(
            {k: np.asarray(v) for k, v in params0.items()}, prep))[0]
        zhat = np.array([0.0, 0.0, 1.0])
        east = np.cross(zhat, n)
        east /= np.linalg.norm(east)
        north = np.cross(n, east)
        prep["ddk_east"] = jnp.asarray(east)
        prep["ddk_north"] = jnp.asarray(north)
        # proper motion [rad/s] in (east, north)
        pm_e = (model.PMRA.value or 0.0) if "PMRA" in model.params else (
            model.PMELONG.value or 0.0)
        pm_n = (model.PMDEC.value or 0.0) if "PMDEC" in model.params else (
            model.PMELAT.value or 0.0)
        prep["ddk_pm_e"] = pm_e * MASYR_TO_RADS
        prep["ddk_pm_n"] = pm_n * MASYR_TO_RADS
        px = model.PX.value if "PX" in model.params and model.PX.value else 0.0
        prep["ddk_dist_ls"] = (1000.0 / px * PC_M / C_M_S) if px else np.inf
        # observatory SSB positions [ls], packed so the Kopeikin terms
        # never need the TOABatch threaded through x_ls/omega_rad
        if toas.ssb_obs is None:
            toas.compute_posvels()
        prep["ddk_obs_ls"] = jnp.asarray(toas.ssb_obs.pos / C_M_S)

    def _kopeikin_xom(self, params, prep, delay_accum):
        """(delta_x, delta_omega) from proper motion + annual parallax."""
        import jax.numpy as jnp

        kin = params.get("KIN", 0.0) * _DEG2RAD
        kom = params.get("KOM", 0.0) * _DEG2RAD
        sk, ck = jnp.sin(kom), jnp.cos(kom)
        x = params["A1"]
        dt = prep["orb_dt_hi"] + prep["orb_dt_lo"] - delay_accum
        mu_e, mu_n = prep["ddk_pm_e"], prep["ddk_pm_n"]
        cot_i = jnp.cos(kin) / jnp.sin(kin)
        csc_i = 1.0 / jnp.sin(kin)
        # K96 proper-motion secular terms (Kopeikin 1996 eq. 10-11)
        dx_pm = x * cot_i * (-mu_e * sk + mu_n * ck) * dt
        dom_pm = csc_i * (mu_e * ck + mu_n * sk) * dt
        # annual-orbital parallax (Kopeikin 1995 eq. 15-16)
        robs = prep["ddk_obs_ls"]  # [ls]
        d_ls = prep["ddk_dist_ls"]
        de = jnp.sum(robs * prep["ddk_east"], axis=-1) / d_ls
        dn = jnp.sum(robs * prep["ddk_north"], axis=-1) / d_ls
        dx_px = x * cot_i * (de * sk - dn * ck)
        dom_px = -csc_i * (de * ck + dn * sk)
        return dx_pm + dx_px, dom_pm + dom_px

    def x_ls(self, params, prep, delay_accum):
        dx, _ = self._kopeikin_xom(params, prep, delay_accum)
        return super().x_ls(params, prep, delay_accum) + dx

    def omega_rad(self, params, prep, delay_accum, nu=None):
        _, dom = self._kopeikin_xom(params, prep, delay_accum)
        return super().omega_rad(params, prep, delay_accum, nu=nu) + dom
