"""Binary pulsar components (reference: src/pint/models/pulsar_binary.py
+ stand_alone_psr_binaries/). Populated by model family: ELL1 first
(closed form), BT/DD (Kepler iteration under jit), extensions after.
"""

from __future__ import annotations


def add_binary_component(model, binary_name: str, keys: dict):
    import importlib

    name = binary_name.upper()
    if importlib.util.find_spec(f"{__name__}.ell1") is None:
        raise NotImplementedError(
            f"BINARY {name}: binary components not yet built in this tree")
    if name in ("ELL1", "ELL1H", "ELL1K"):
        from .ell1 import BinaryELL1, BinaryELL1H, BinaryELL1k

        comp = {"ELL1": BinaryELL1, "ELL1H": BinaryELL1H,
                "ELL1K": BinaryELL1k}[name]()
    elif name in ("BT", "BTX"):
        from .bt import BinaryBT, BinaryBTX

        comp = BinaryBTX() if name == "BTX" else BinaryBT()
    elif name == "BT_PIECEWISE":
        from .bt_piecewise import BinaryBTPiecewise

        comp = BinaryBTPiecewise()
    elif name in ("DD", "DDS", "DDGR", "DDK", "DDH"):
        from .dd import (BinaryDD, BinaryDDGR, BinaryDDH, BinaryDDK,
                         BinaryDDS)

        comp = {"DD": BinaryDD, "DDS": BinaryDDS, "DDK": BinaryDDK,
                "DDGR": BinaryDDGR, "DDH": BinaryDDH}[name]()
    else:
        raise ValueError(f"unsupported BINARY model {binary_name!r}")
    model.add_component(comp)
    comp.add_prefix_members(keys)
    return comp
