"""Binary pulsar components (reference: src/pint/models/pulsar_binary.py
+ stand_alone_psr_binaries/). Populated by model family: ELL1 first
(closed form), BT/DD (Kepler iteration under jit), extensions after.
"""

from __future__ import annotations


def choose_t2_model(keys: set) -> str:
    """Pick the concrete binary model for a tempo2 "BINARY T2"
    parameter set (T2 is a universal container; what's present decides):
    KIN/KOM -> DDK, EPS1/EPS2 (+H3/H4/STIG) -> ELL1/ELL1H,
    H3/STIG alone -> DDH, SHAPMAX -> DDS, ECC/OM + M2/SINI -> DD,
    else BT.
    Single home for the rule — scripts/t2binary2pint.py imports it.
    Expects UPPERCASE par keys; only meaningful for PAR-FILE key sets
    (the par loader applies it; add_binary_component deliberately
    still rejects 'T2' so programmatic converts can't silently pick a
    wrong model from non-par keys)."""
    if "KIN" in keys or "KOM" in keys:
        return "DDK"
    if "EPS1" in keys or "EPS2" in keys:
        if "H3" in keys or "H4" in keys or "STIGMA" in keys or "STIG" in keys:
            return "ELL1H"
        return "ELL1"
    if "H3" in keys or "STIGMA" in keys or "STIG" in keys:
        return "DDH"  # eccentric orbit with orthometric Shapiro
    if "SHAPMAX" in keys:
        return "DDS"  # SHAPMAX is DDS's defining parameter — mapping
        # it to DD would silently drop the Shapiro shape (r4 review)
    if "M2" in keys or "SINI" in keys:
        return "DD"
    return "BT"


def add_binary_component(model, binary_name: str, keys: dict):
    import importlib

    name = binary_name.upper()
    if importlib.util.find_spec(f"{__name__}.ell1") is None:
        raise NotImplementedError(
            f"BINARY {name}: binary components not yet built in this tree")
    if name in ("ELL1", "ELL1H", "ELL1K"):
        from .ell1 import BinaryELL1, BinaryELL1H, BinaryELL1k

        comp = {"ELL1": BinaryELL1, "ELL1H": BinaryELL1H,
                "ELL1K": BinaryELL1k}[name]()
    elif name in ("BT", "BTX"):
        from .bt import BinaryBT, BinaryBTX

        comp = BinaryBTX() if name == "BTX" else BinaryBT()
    elif name == "BT_PIECEWISE":
        from .bt_piecewise import BinaryBTPiecewise

        comp = BinaryBTPiecewise()
    elif name in ("DD", "DDS", "DDGR", "DDK", "DDH"):
        from .dd import (BinaryDD, BinaryDDGR, BinaryDDH, BinaryDDK,
                         BinaryDDS)

        comp = {"DD": BinaryDD, "DDS": BinaryDDS, "DDK": BinaryDDK,
                "DDGR": BinaryDDGR, "DDH": BinaryDDH}[name]()
    else:
        raise ValueError(f"unsupported BINARY model {binary_name!r}")
    model.add_component(comp)
    comp.add_prefix_members(keys)
    return comp
