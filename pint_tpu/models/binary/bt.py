"""BT (Blandford & Teukolsky 1976) binary model.

(reference: src/pint/models/stand_alone_psr_binaries/BT_model.py::BTmodel,
wrapper src/pint/models/binary_bt.py::BinaryBT.)

  delay = x sin(om) (cos E - e) + [x cos(om) sqrt(1-e^2) + GAMMA] sin E

with E from Kepler's equation; applied via 2 fixed-point iterations of
the inverse timing formula (delay evaluated at t - delay).
"""

from __future__ import annotations

from ..parameter import floatParameter
from .base import PulsarBinary, kepler_solve


class BinaryBT(PulsarBinary):
    binary_model_name = "BT"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("ECC", units="", aliases=("E",),
                                      description="Eccentricity"))
        self.add_param(floatParameter("EDOT", units="1/s"))
        self.add_param(floatParameter("OM", units="deg",
                                      description="Longitude of periastron"))
        self.add_param(floatParameter("OMDOT", units="deg/yr"))
        self.add_param(floatParameter("GAMMA", units="s",
                                      description="Einstein delay amplitude"))

    def _bt_delay_at(self, params, prep, delay_accum):
        import jax.numpy as jnp

        M = self.orbital_phase(params, prep, delay_accum)
        e = self.ecc(params, prep, delay_accum)
        E = kepler_solve(M, e)
        om = self.omega_rad(params, prep, delay_accum)
        x = self.x_ls(params, prep, delay_accum)
        gamma = params.get("GAMMA", 0.0)
        return (x * jnp.sin(om) * (jnp.cos(E) - e)
                + (x * jnp.cos(om) * jnp.sqrt(1.0 - e**2) + gamma) * jnp.sin(E))

    def delay(self, params, batch, prep, delay_accum):
        # inverse timing formula: evaluate at binary time t - delay
        d = self._bt_delay_at(params, prep, delay_accum)
        d = self._bt_delay_at(params, prep, delay_accum + d)
        return self._bt_delay_at(params, prep, delay_accum + d)


class BinaryBTX(BinaryBT):
    """BTX (reference: BT_model.py BTX mode): BT orbit parameterized by
    orbital-frequency harmonics FB0, FB1, ... instead of PB/PBDOT.
    The FBn Taylor orbit itself lives in PulsarBinary.orbital_phase
    (base.py, OrbitFBX equivalent); this subclass only fixes the name
    so par files with BINARY BTX round-trip."""

    binary_model_name = "BTX"
