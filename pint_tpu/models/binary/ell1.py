"""ELL1 / ELL1H low-eccentricity binary models.

(reference: src/pint/models/stand_alone_psr_binaries/ELL1_model.py::ELL1model,
ELL1H_model.py::ELL1Hmodel, wrapper src/pint/models/binary_ell1.py.)

Lange et al. (2001) expansion in eccentricity around TASC with
EPS1 = e sin(omega), EPS2 = e cos(omega):

  Roemer = x [ sin(Phi) - (EPS1/2) cos(2 Phi) + (EPS2/2) sin(2 Phi) ]
  Shapiro = -2 r ln(1 - SINI sin Phi)

ELL1H replaces (M2, SINI) by orthometric (H3, H4 | STIGMA)
(Freire & Wex 2010): sigma = H4/H3, SINI = 2 sigma/(1+sigma^2),
r = H3/sigma^3.
"""

from __future__ import annotations

import numpy as np

from ...constants import TSUN_S, SECS_PER_DAY, SECS_PER_JULIAN_YEAR
from ..parameter import MJDParameter, floatParameter
from ..timing_model import MissingParameter
from .base import PulsarBinary, _TWO_PI

_DEG2RAD = np.pi / 180.0


class BinaryELL1(PulsarBinary):
    binary_model_name = "ELL1"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("TASC", units="MJD",
                                    description="Epoch of ascending node"))
        self.add_param(floatParameter("EPS1", units="", description="e*sin(omega)"))
        self.add_param(floatParameter("EPS2", units="", description="e*cos(omega)"))
        self.add_param(floatParameter("EPS1DOT", units="1/s"))
        self.add_param(floatParameter("EPS2DOT", units="1/s"))
        self.add_param(floatParameter("M2", units="Msun", description="Companion mass"))
        self.add_param(floatParameter("SINI", units="", description="Sine of inclination"))

    def _epoch_param(self):
        return self.TASC if self.TASC.value is not None else self.T0

    def validate(self):
        if self.TASC.value is None and self.T0.value is None:
            raise MissingParameter("BinaryELL1", "TASC")
        super().validate()

    def eps(self, params, prep, delay_accum):
        dt = prep["orb_dt_hi"] + prep["orb_dt_lo"] - delay_accum
        e1 = params.get("EPS1", 0.0) + params.get("EPS1DOT", 0.0) * dt
        e2 = params.get("EPS2", 0.0) + params.get("EPS2DOT", 0.0) * dt
        return e1, e2

    def shapiro_rs(self, params):
        """(range r [s], shape s) of the Shapiro delay."""
        return TSUN_S * params.get("M2", 0.0), params.get("SINI", 0.0)

    def _ell1_delay_at(self, params, prep, delay_accum):
        import jax.numpy as jnp

        phi = self.orbital_phase(params, prep, delay_accum)
        x = self.x_ls(params, prep, delay_accum)
        e1, e2 = self.eps(params, prep, delay_accum)
        roemer = x * (jnp.sin(phi)
                      - 0.5 * (e1 * jnp.cos(2 * phi) - e2 * jnp.sin(2 * phi)))
        r, s = self.shapiro_rs(params)
        shapiro = -2.0 * r * jnp.log(1.0 - s * jnp.sin(phi))
        return roemer + shapiro

    def delay(self, params, batch, prep, delay_accum):
        # inverse timing formula via fixed point: the reference expands
        # Dre*(1 - nhat*Drep + ...) (ELL1_model.py::delayI); the fixed-point
        # iteration sums the same series to all orders
        d = self._ell1_delay_at(params, prep, delay_accum)
        d = self._ell1_delay_at(params, prep, delay_accum + d)
        return self._ell1_delay_at(params, prep, delay_accum + d)


class BinaryELL1k(BinaryELL1):
    """ELL1k (reference: ELL1k_model.py): variant for rapid periastron
    advance. Instead of EPS1DOT/EPS2DOT linearization, the eccentricity
    vector rotates rigidly with OMDOT and its magnitude evolves as
    e(t) = e0 * (1 + LNEDOT * dt):

      eps1(t) = (1 + LNEDOT dt) [ eps1 cos(w) + eps2 sin(w) ]
      eps2(t) = (1 + LNEDOT dt) [ eps2 cos(w) - eps1 sin(w) ],
      w = OMDOT * dt.
    """

    binary_model_name = "ELL1K"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("OMDOT", units="deg/yr",
                                      description="Periastron advance rate"))
        self.add_param(floatParameter("LNEDOT", units="1/s",
                                      description="d(ln e)/dt"))
        # the rotation model replaces the linearized eccentricity-vector
        # rates; keeping them would create silently-dead (zero-column)
        # fit parameters (reference: ELL1k removes EPS1DOT/EPS2DOT)
        self.remove_param("EPS1DOT")
        self.remove_param("EPS2DOT")

    def eps(self, params, prep, delay_accum):
        import jax.numpy as jnp

        dt = prep["orb_dt_hi"] + prep["orb_dt_lo"] - delay_accum
        w = (params.get("OMDOT", 0.0) * _DEG2RAD / SECS_PER_JULIAN_YEAR) * dt
        scale = 1.0 + params.get("LNEDOT", 0.0) * dt
        e1, e2 = params.get("EPS1", 0.0), params.get("EPS2", 0.0)
        cw, sw = jnp.cos(w), jnp.sin(w)
        return scale * (e1 * cw + e2 * sw), scale * (e2 * cw - e1 * sw)


class BinaryELL1H(BinaryELL1):
    binary_model_name = "ELL1H"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("H3", units="s", description="Orthometric amplitude h3"))
        self.add_param(floatParameter("H4", units="s", description="Orthometric amplitude h4"))
        self.add_param(floatParameter("STIGMA", units="", aliases=("VARSIGMA", "STIG"),
                                      description="Orthometric ratio"))

    def shapiro_rs(self, params):
        import jax.numpy as jnp

        from .base import orthometric_shapiro_rs

        h3 = params.get("H3", 0.0)
        if self.STIGMA.value is not None:
            sig = params.get("STIGMA", 0.0)
        else:
            # sigma = H4/H3 (Freire & Wex 2010 eq. 25)
            sig = params.get("H4", 0.0) / jnp.where(h3 == 0.0, 1.0, h3)
        return orthometric_shapiro_rs(h3, sig)
