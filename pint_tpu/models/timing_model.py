"""TimingModel: component container + device compilation.

TPU-native re-design of the reference's model layer
(reference: src/pint/models/timing_model.py — TimingModel, Component,
ModelMeta, DelayComponent, PhaseComponent).

Architecture (differs deliberately from the reference):

- **Host**: ``TimingModel`` holds Parameter metadata and Component
  instances, handles par-file round-trips, validation, and attribute
  delegation — same public surface as the reference.
- **Device**: ``model.prepare(toas)`` compiles model+TOAs into a
  ``PreparedTiming``: every maskParameter becomes a static boolean
  mask, every epoch difference a precomputed (hi, lo) f64 pair, and
  the spindown reference phase is evaluated on host in longdouble
  (pint_tpu/mjd.py LD). The jitted device functions then evaluate only
  *exact small-delta* terms in f64 — this is how sub-ns phase
  precision survives TPU hardware whose emulated f64 is ~47-bit and
  not correctly rounded (measured; see dd.py docstring).

Phase identity used on device (exact algebra, f64-safe term by term)::

    phi(T - d) = phi_ref(T)                      # host longdouble, (int, frac)
             + sum_i dF_i T^(i+1)/(i+1)!         # dF_i = F_i - F_ref_i, small
             - d * sum_i F_i/(i+1)! * sum_{j<=i} T^(i-j) (T-d)^j
             + small phase components (glitch, wave, jump, ...)

where T = tdb - PEPOCH (packed as exact (hi, lo) seconds) and d is the
total delay (<~3000 s, f64).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from ..constants import SECS_PER_DAY
from ..mjd import LD
from .parameter import Parameter, maskParameter, prefixParameter


class TimingModelError(Exception):
    pass


import threading as _threading

# staging depth is PER THREAD: the pipelined PTAFleet builds bucket
# batches in a worker pool, and a process-global depth would let one
# worker's active staging scope silently no-op another worker's final
# device_put_staged transfer (jax.default_device is already
# thread-local config, so the placement side matches)
_STAGING_STATE = _threading.local()


def _staging_depth():
    return getattr(_STAGING_STATE, "depth", 0)


class _cpu_staging:
    """Context manager placing new jax arrays on the host CPU backend
    (no-op when the default backend already is cpu or no cpu backend
    exists). Used to stage packing before one batched transfer to the
    accelerator. Nesting-aware: device_put_staged is inert while any
    staging context is active ON THIS THREAD, so an outer batcher
    (PTABatch) can wrap many PreparedTiming constructions and do ONE
    transfer at the end — and concurrent batchers on other threads
    stage independently."""

    def __enter__(self):
        import contextlib

        import jax

        self._ctx = contextlib.nullcontext()
        try:
            if jax.default_backend() != "cpu":
                self._ctx = jax.default_device(
                    jax.local_devices(backend="cpu")[0])
        except RuntimeError:
            pass
        self._ctx.__enter__()
        _STAGING_STATE.depth = _staging_depth() + 1
        return self

    def __exit__(self, *exc):
        _STAGING_STATE.depth = _staging_depth() - 1
        return self._ctx.__exit__(*exc)


def _numpy_transferable(x):
    """numpy leaves safe to move to the device as-is: plain numeric
    dtypes of <= 8 bytes. float128/longdouble (itemsize 16) and object
    arrays must stay on host — jnp would silently downcast them."""
    return (isinstance(x, np.ndarray) and x.dtype.kind in "biufc"
            and x.dtype.itemsize <= 8)


def device_put_staged(tree, include_numpy=False):
    """Move every jax-array leaf of a pytree to the default backend's
    device 0 in a single batched device_put; non-array leaves (python
    scalars, longdouble arrays) pass through untouched.

    ``include_numpy=True`` additionally moves plain-numeric numpy
    leaves in the same batched transfer (skipping the intermediate
    host jnp.asarray copy a caller would otherwise make); longdouble
    and object arrays still pass through untouched.

    The target device must be explicit: device_put with device=None is
    the identity for arrays already committed to ANY device (including
    the CPU staging device), which would defer the transfer to every
    jit dispatch — re-paying tunnel latency per fit iteration.

    Inside an active _cpu_staging context (on this thread) this is a
    no-op: the outermost staging scope owns the single batched
    transfer."""
    import jax

    if _staging_depth() > 0:
        return tree
    # local_devices, not devices: in a multi-process fleet
    # (jax.distributed) devices()[0] belongs to process 0 and is
    # non-addressable elsewhere
    target = jax.local_devices()[0]
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    is_arr = [isinstance(x, jax.Array)
              or (include_numpy and _numpy_transferable(x))
              for x in leaves]
    arrs = [x for x, a in zip(leaves, is_arr) if a]
    if arrs:
        moved = iter(jax.device_put(arrs, target))
        leaves = [next(moved) if a else x for x, a in zip(leaves, is_arr)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class MissingParameter(TimingModelError):
    def __init__(self, component, param, msg=""):
        super().__init__(f"{component} requires {param} {msg}")
        self.component = component
        self.param = param


class Component:
    """Base component; subclasses auto-register
    (reference: timing_model.py::Component + ModelMeta metaclass)."""

    component_types: dict[str, type] = {}
    register = True
    category = ""
    order = 50  # delay evaluation order; lower = earlier

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.__dict__.get("register", True) and not cls.__name__.startswith("_"):
            Component.component_types[cls.__name__] = cls

    def __init__(self):
        self.params: list[str] = []
        self._parent: TimingModel | None = None

    def add_param(self, par: Parameter):
        setattr(self, par.name, par)
        par._component = self
        self.params.append(par.name)

    def remove_param(self, name: str):
        """Drop a parameter inherited from a superclass that this
        variant does not support (reference: Component.remove_param)."""
        self.params.remove(name)
        delattr(self, name)

    def setup(self):
        pass

    def validate(self):
        pass

    @property
    def free_params_component(self):
        return [p for p in self.params if not getattr(self, p).frozen]

    # --- device hooks ---
    def pack(self, model: "TimingModel", toas, prep: dict, params0: dict):
        """Host-side: add static arrays to prep, values to params0."""

    def delay(self, params, batch, prep, delay_accum):
        """Device: delay seconds added by this component (f64 array)."""
        raise NotImplementedError

    def phase(self, params, batch, prep, delay_total):
        """Device: small phase contribution in cycles (f64 array)."""
        raise NotImplementedError


class DelayComponent(Component):
    kind = "delay"


class PhaseComponent(Component):
    kind = "phase"


class TimingModel:
    """(reference: timing_model.py::TimingModel — same public surface)."""

    def __init__(self, components=(), name=""):
        self.name = name
        self.components: dict[str, Component] = {}
        self.top_params: list[str] = []  # model-level params (PSR, EPHEM, ...)
        self._top: dict[str, Parameter] = {}
        for c in components:
            self.add_component(c)

    # ---- structure ----

    def add_component(self, comp: Component):
        comp._parent = self
        self.components[type(comp).__name__] = comp

    def remove_component(self, name: str):
        del self.components[name]

    def add_top_param(self, par: Parameter):
        self._top[par.name] = par
        self.top_params.append(par.name)

    def __getattr__(self, name):
        # delegate parameter lookup to owning component
        # (reference: TimingModel.__getattr__)
        if name.startswith("_") or name in ("components", "top_params"):
            raise AttributeError(name)
        top = self.__dict__.get("_top", {})
        if name in top:
            return top[name]
        for comp in self.__dict__.get("components", {}).values():
            if name in comp.params:
                return getattr(comp, name)
        raise AttributeError(f"TimingModel has no parameter or attribute {name!r}")

    @property
    def params(self) -> list[str]:
        out = list(self.top_params)
        for comp in self.components.values():
            out.extend(comp.params)
        return out

    @property
    def free_params(self) -> list[str]:
        return [p for p in self.params if p not in self.top_params
                and not getattr(self, p).frozen]

    @free_params.setter
    def free_params(self, names):
        # validate BEFORE touching any frozen flag: a typo must not
        # leave the model with a half-rewritten free-parameter set
        missing = set(names) - set(self.params)
        if missing:
            raise KeyError(f"unknown params {missing}")
        for p in self.params:
            if p in self.top_params:
                continue
            getattr(self, p).frozen = p not in names

    def get_params_dict(self):
        return {p: getattr(self, p).value for p in self.params}

    def delay_components(self):
        return sorted([c for c in self.components.values() if c.kind == "delay"],
                      key=lambda c: c.order)

    def phase_components(self):
        return sorted([c for c in self.components.values() if c.kind == "phase"],
                      key=lambda c: c.order)

    def setup(self):
        for c in self.components.values():
            c.setup()

    def validate(self):
        for c in self.components.values():
            c.validate()

    # ---- par file round trip (reference: TimingModel.as_parfile) ----

    # parameter spellings tempo/tempo2 expect on output; our reader
    # accepts both spellings, so format="pint" files stay canonical
    # (reference: parameter.py tempo2-alias handling in as_parfile)
    _TEMPO2_RENAMES = {
        "EFAC": "T2EFAC", "EQUAD": "T2EQUAD", "STIGMA": "VARSIGMA",
        "A1DOT": "XDOT", "NE_SW": "NE1AU", "ELONG": "LAMBDA",
        "ELAT": "BETA", "PMELONG": "PMLAMBDA", "PMELAT": "PMBETA",
    }
    _TEMPO_RENAMES = {
        "ELONG": "LAMBDA", "ELAT": "BETA",
        "PMELONG": "PMLAMBDA", "PMELAT": "PMBETA", "NE_SW": "SOLARN0",
        "A1DOT": "XDOT", "STIGMA": "VARSIGMA",
    }

    def as_parfile(self, format="pint") -> str:
        """Serialize the model (reference: TimingModel.as_parfile with
        format in {"pint", "tempo", "tempo2"}: same parameter values,
        target-program spellings — T2EFAC/VARSIGMA/LAMBDA..., and a
        MODE 1 header for tempo)."""
        if format not in ("pint", "tempo", "tempo2"):
            raise ValueError(f"unknown par file format {format!r} "
                             "(expected pint, tempo, or tempo2)")
        renames = (self._TEMPO2_RENAMES if format == "tempo2"
                   else self._TEMPO_RENAMES if format == "tempo" else {})
        lines = []
        if format == "tempo":
            lines.append(f"{'MODE':<15} 1\n")
        for p in self.top_params:
            if format == "tempo" and p == "MODE":
                continue
            lines.append(self._top[p].as_parfile_line())
        if format in ("tempo", "tempo2") and "UNITS" not in self.top_params:
            lines.append(f"{'UNITS':<15} TDB\n")
        ordered = list(self.delay_components()) + list(self.phase_components())
        # noise components are neither delay nor phase but their
        # EFAC/EQUAD/ECORR/red-noise params are model state too — a par
        # file that silently drops them is not a checkpoint
        ordered += [c for c in self.components.values() if c not in ordered]
        for comp in ordered:
            name = getattr(comp, "binary_model_name", None)
            if name is not None:
                # the BINARY line is the model selector, not a
                # parameter — without it the par file can't rebuild
                # the model (par-file-as-checkpoint invariant)
                lines.append(f"{'BINARY':<15} {name}\n")
            for pname in comp.params:
                lines.append(getattr(comp, pname).as_parfile_line())
        if renames:
            out = []
            for l in lines:
                head = l.split(" ", 1)[0] if l else ""
                if head in renames:
                    body = l.rstrip("\n")
                    rest = body[len(head):].lstrip()
                    # keep the original name-field width (15 for plain
                    # params, 8 for mask prefixes) so columns stay aligned
                    field_w = len(body) - len(rest) - 1
                    new = renames[head]
                    l = f"{new:<{max(field_w, len(new))}} {rest}\n"
                out.append(l)
            lines = out
        return "".join(l for l in lines if l)

    def write_parfile(self, path, format="pint"):
        with open(path, "w") as f:
            f.write(self.as_parfile(format=format))

    def compare(self, other: "TimingModel", sigma=None) -> str:
        """Pre/post-fit comparison table (reference: TimingModel.compare).

        ``sigma``: only list parameters whose difference exceeds this
        many combined uncertainties (parameters with no uncertainty on
        either side always shown when their values differ)."""
        rows = [f"{'PARAM':<12} {'SELF':>20} {'OTHER':>20} {'DIFF/UNC':>10}"]
        for p in self.params:
            a = getattr(self, p)
            b = getattr(other, p, None) if p in other.params else None
            if a.kind in ("str",) or a.value is None or b is None or b.value is None:
                continue
            try:
                diff = float(b.value) - float(a.value)
            except (TypeError, ValueError):
                continue
            # combined (quadrature) uncertainty when both sides have one
            ua, ub = a.uncertainty or 0.0, b.uncertainty or 0.0
            unc = float(np.hypot(ua, ub)) or None
            if sigma is not None:
                if unc:
                    if abs(diff) < sigma * unc:
                        continue
                elif diff == 0.0:
                    continue
            rel = f"{diff / unc:.2f}" if unc else "-"
            rows.append(f"{p:<12} {float(a.value):>20.12g} {float(b.value):>20.12g} {rel:>10}")
        return "\n".join(rows)

    # ---- device compilation ----

    def prepare(self, toas, subtract_mean=True) -> "PreparedTiming":
        return PreparedTiming(self, toas, subtract_mean=subtract_mean)

    # ---- reference-style conveniences (host entry points) ----

    def phase(self, toas, abs_phase=False):
        return self.prepare(toas).phase()

    def delay(self, toas):
        return self.prepare(toas).delay()

    def designmatrix(self, toas, incoffset=True):
        return self.prepare(toas).designmatrix(incoffset=incoffset)

    def scaled_toa_uncertainty(self, toas):
        """EFAC/EQUAD-scaled sigma [us] (reference: noise_model scaled sigma)."""
        prep = self.prepare(toas)
        return prep.scaled_sigma_us()

    def total_dm(self, toas):
        """Model DM at each TOA [pc/cm^3]: every nu^-2 dispersion
        contribution — Taylor DM series, DMX windows, DMWaveX, solar
        wind (reference: TimingModel.total_dm). DMJUMP offsets are
        excluded — they apply to wideband DM measurements, not the
        model DM."""
        from ..residuals import wideband_dm_model

        prepared = self.prepare(toas)
        return np.asarray(wideband_dm_model(
            self, prepared.params0, prepared.prep, batch=prepared.batch,
            include_jumps=False))

    def d_phase_d_toa(self, toas, sample_step_s=1.0):
        """Instantaneous topocentric spin frequency [Hz] at each TOA
        (reference: TimingModel.d_phase_d_toa — a finite-difference
        sample window through the full pipeline, so every delay's time
        dependence, including Doppler from observatory motion, is in
        the derivative)."""
        h = float(sample_step_s)
        # mask(all-True) is the cheap structural copy: fresh day/sec/
        # clock arrays, no duplication of cached posvel/ephemeris data
        keep = np.ones(len(toas), dtype=bool)
        tp = toas.mask(keep)
        tp.adjust_times(+h)
        tm = toas.mask(keep)
        tm.adjust_times(-h)
        php = self.prepare(tp).phase()
        phm = self.prepare(tm).phase()
        dint = np.asarray(php.int_) - np.asarray(phm.int_)
        dfrac = np.asarray(php.frac) - np.asarray(phm.frac)
        return (dint + dfrac) / (2.0 * h)

    def _delay_contributions(self, prepared):
        """Yield (component, contribution) over delay_components() with
        the chain's accumulation convention — the one home of the
        partial-delay accumulator (same convention as
        PreparedTiming._delay_fn); _delay_until and delay_breakdown
        both consume it."""
        import jax.numpy as jnp

        d = jnp.zeros_like(prepared.batch.tdb_sec)
        for comp in self.delay_components():
            di = comp.delay(prepared.params0, prepared.batch,
                            prepared.prep, d)
            d = d + di
            yield comp, di

    def _delay_until(self, prepared, stop_comp):
        """Accumulated delay up to but excluding ``stop_comp``
        (None = all components)."""
        import jax.numpy as jnp

        d = jnp.zeros_like(prepared.batch.tdb_sec)
        for comp, di in self._delay_contributions(prepared):
            if comp is stop_comp:
                break
            d = d + di
        return d

    def delay_breakdown(self, toas):
        """{component name: per-TOA delay contribution [s]} in
        evaluation order, each evaluated with the accumulated upstream
        delay exactly as in the full chain, so the values sum to
        ``delay(toas)`` (the reference exposes the same decomposition
        via per-component cutoff delays; this is the diagnostic form
        for delay-budget plots)."""
        prepared = self.prepare(toas)
        return {type(comp).__name__: np.asarray(di)
                for comp, di in self._delay_contributions(prepared)}

    def get_barycentric_toas(self, toas, cutoff_component=None):
        """Barycentric arrival times [TDB MJD, float64] — the TDB TOA
        times minus every delay up to but excluding
        ``cutoff_component`` (default: the binary component, so
        binary pulsars get infinite-frequency barycentric orbital
        times; isolated pulsars get all delays removed)
        (reference: timing_model.py::TimingModel.get_barycentric_toas).
        """
        prepared = self.prepare(toas)
        delays = self.delay_components()
        if cutoff_component is None:
            stop = next((c for c in delays
                         if c.category == "pulsar_system"), None)
        else:
            stop = next((c for c in delays
                         if c.__class__.__name__ == cutoff_component), None)
            if stop is None:
                raise KeyError(f"no delay component named "
                               f"{cutoff_component!r} (have "
                               f"{[c.__class__.__name__ for c in delays]})")
        d = np.asarray(self._delay_until(prepared, stop))
        return (np.asarray(prepared.batch.tdb_day)
                + (np.asarray(prepared.batch.tdb_sec) - d) / SECS_PER_DAY)

    def orbital_phase(self, toas, radians=False):
        """Mean orbital phase at each TOA — cycles in [0, 1) by
        default, radians in [0, 2 pi) with ``radians=True`` — measured
        from the binary epoch (T0, or TASC for ELL1 models)
        (reference: timing_model.py::TimingModel.orbital_phase).
        """
        binary = next((c for c in self.delay_components()
                       if c.category == "pulsar_system"), None)
        if binary is None:
            raise AttributeError("model has no binary component")
        prepared = self.prepare(toas)
        d = self._delay_until(prepared, binary)
        phi = np.asarray(binary.orbital_phase(prepared.params0,
                                              prepared.prep, d))
        cycles = (phi / (2.0 * np.pi)) % 1.0
        return cycles * (2.0 * np.pi) if radians else cycles

    def map_component(self, name: str):
        for comp in self.components.values():
            if name in comp.params:
                return comp
        raise KeyError(name)


# ---- process-global compiled-function cache ----
#
# Keyed by ((variant...), structure_key): fresh PreparedTiming
# instances over the same model structure + static prep share XLA
# executables. Entries hold closures over host objects only.
_GLOBAL_FNS: dict = {}
_GLOBAL_FNS_MAX = 512  # FIFO bound; see _global_fn


def _static_key_value(v):
    """Hashable, value-faithful key form of a static prep entry."""
    if isinstance(v, np.ndarray):
        return ("nd", str(v.dtype), v.shape, v.tobytes())
    if isinstance(v, (list, tuple)):
        return tuple(_static_key_value(x) for x in v)
    if isinstance(v, (bool, int, float, str, bytes, type(None))):
        return v
    tb = getattr(v, "tobytes", None)
    return (type(v).__name__, tb() if callable(tb) else repr(v))


def _merge_prep(static, arrays):
    out = dict(static)
    out.update(arrays)
    return out


def _delay_impl(model, params, batch, prep):
    import jax.numpy as jnp

    d = jnp.zeros_like(batch.tdb_sec)
    for comp in model.delay_components():
        d = d + comp.delay(params, batch, prep, d)
    return d


def _phase_impl(model, params, batch, prep):
    import jax.numpy as jnp

    d = _delay_impl(model, params, batch, prep)
    ph = jnp.zeros_like(d)
    for comp in model.phase_components():
        ph = ph + comp.phase(params, batch, prep, d)
    return ph  # cycles; includes phi_ref_frac via spindown component


def _sigma_impl(model, params, batch, prep):
    sigma = batch.error_us
    for comp in model.components.values():
        scale = getattr(comp, "scale_sigma", None)
        if scale is not None:
            sigma = scale(params, batch, prep, sigma)
    return sigma


def _register_barrier_batching():
    """jax 0.4.x ships optimization_barrier without a vmap batching
    rule, so any barrier emitted inside a later-vmapped overlay dies
    with NotImplementedError AFTER tracing (outside any try/except at
    the call site). The barrier is the identity on values, so the
    batching rule is the canonical identity batcher: bind the batched
    operands, keep their batch dims. Registered idempotently on first
    overlay; newer jax versions that already have the rule are left
    alone."""
    try:
        from jax._src.lax import lax as _lax_internal
        from jax.interpreters import batching as _batching

        prim = _lax_internal.optimization_barrier_p
        if prim not in _batching.primitive_batchers:
            def _identity_batcher(args, dims):
                return prim.bind(*args), dims

            _batching.primitive_batchers[prim] = _identity_batcher
    except Exception:
        pass  # private-module move in a future jax: barrier under
        # vmap then fails as before, nothing new breaks


def _overlay_params(x, params0, free_map):
    """Overlay flat free-param vector x onto the params0 pytree.

    Under a trace, every value in the returned pytree is routed
    through ``lax.optimization_barrier``: without it, the frozen
    params0 entries become compile-time CONSTANTS inside whatever
    jit wraps this call, and on the axon TPU backend XLA's
    simplifier then folds parts of the emulated-float64 phase
    pipeline at single-f32 precision (measured: 3.6e-3 cycles =
    f32-eps-level phase error in residual_vector_fn, while the
    identical math with params as traced INPUTS is accurate to
    1e-9 cycles). The barrier makes the constants opaque, matching
    the traced-input graph. It is the identity on values and has a
    transparent JVP, so jacfwd design matrices are unaffected.
    """
    import jax

    _register_barrier_batching()
    p = dict(params0)
    for i, (_, key, idx) in enumerate(free_map):
        if idx is None:
            p[key] = x[i]
        else:
            p = {**p, key: p[key].at[idx].set(x[i])}
    if any(isinstance(v, jax.core.Tracer) for v in jax.tree.leaves(p)):
        try:
            p = jax.lax.optimization_barrier(p)
        except NotImplementedError:
            # jax 0.4.x has no differentiation rule for the barrier.
            # This only triggers when the overlay runs INSIDE a
            # jacfwd/jvp closure (e.g. toa_shard's per-shard design
            # matrix): there the params are differentiation inputs,
            # not foldable constants, so skipping the barrier loses
            # nothing
            pass
    return p


class PreparedTiming:
    """Model x TOAs compiled for device execution.

    Holds the TOABatch, the static prep dict, the initial params
    pytree, and lazily-jitted phase/residual/design functions. This is
    the TPU-era analog of the reference's implicit (model, toas)
    pairing inside Residuals/Fitter — made explicit because jit needs
    static structure separated from traced values.
    """

    def __init__(self, model: TimingModel, toas, subtract_mean=True):
        import jax
        import jax.numpy as jnp

        self.model = model
        self.toas = toas
        self.subtract_mean = subtract_mean
        # Pack on the host CPU backend, then ship everything to the
        # accelerator in ONE batched device_put: component pack()
        # methods emit dozens of small arrays, and issuing a separate
        # host->device transfer for each dominates wall-clock when the
        # chip sits behind a network tunnel (measured: ~100 s of
        # per-array latency for a 68-pulsar pack vs <1 s batched).
        with _cpu_staging():
            self.batch = toas.to_batch()
            self.prep: dict = {}
            self.params0: dict = {}
            # exact T = tdb - PEPOCH split, shared by spindown/binary/etc.
            pepoch = model.PEPOCH if "PEPOCH" in model.params else None
            if pepoch is not None and pepoch.day is not None:
                pd, psec = pepoch.day, pepoch.sec
            else:
                pd, psec = int(np.median(toas.tdb.day)), 0.0
            t_hi = (toas.tdb.day - pd).astype(np.float64) * SECS_PER_DAY
            t_lo = toas.tdb.sec - psec
            self.prep["pepoch_day"] = pd
            self.prep["pepoch_sec"] = psec
            self.prep["T_hi"] = jnp.asarray(t_hi)
            self.prep["T_lo"] = jnp.asarray(t_lo)
            self.prep["T_ld"] = LD(t_hi) + LD(t_lo)  # host-side longdouble copy
            for comp in model.components.values():
                comp.pack(model, toas, self.prep, self.params0)
            if "phi_ref_int" not in self.prep:
                self.prep["phi_ref_int"] = jnp.zeros_like(self.prep["T_hi"])
            self.params0 = {k: jnp.asarray(v, jnp.float64)
                            for k, v in self.params0.items()}
        self.prep, self.params0, self.batch = device_put_staged(
            (self.prep, self.params0, self.batch))
        self._fns: dict[str, Callable] = {}
        # split prep for the global compile cache: jax arrays become
        # jit arguments; everything else is static structure
        self._prep_arrays = {k: v for k, v in self.prep.items()
                             if isinstance(v, jax.Array)}
        self._prep_static = {k: v for k, v in self.prep.items()
                             if k not in self._prep_arrays}
        self._skey = None

    # -- parameter vector mapping (free params <-> flat vector) --

    def free_param_map(self):
        """[(par_name, pytree_key, index)] for free params."""
        out = []
        for pname in self.model.free_params:
            comp = self.model.map_component(pname)
            key, idx = comp.device_slot(pname)
            out.append((pname, key, idx))
        return out

    def params_with_vector(self, x):
        """Overlay flat free-param vector x onto params0 pytree (see
        _overlay_params for the optimization-barrier rationale)."""
        return _overlay_params(x, self.params0,
                               tuple(self.free_param_map()))

    def vector_from_params(self, params=None):
        import jax.numpy as jnp

        p = self.params0 if params is None else params
        vals = []
        for (_, key, idx) in self.free_param_map():
            vals.append(p[key] if idx is None else p[key][idx])
        return jnp.array(vals, jnp.float64)

    # -- device functions --
    #
    # COMPILE-CACHE DESIGN: the traced computations are module-level
    # functions of (model, params, batch, prep) with every device
    # array passed as a jit ARGUMENT, and the jitted callables live in
    # a process-global cache keyed by the model's structure (component
    # classes + static prep values + free-param map). A fresh
    # WLSFitter/Residuals/PreparedTiming on the same par+tim therefore
    # reuses the existing XLA executable instead of recompiling
    # (measured: 62-TOA refit 1.5 s -> sub-0.1 s steady state). The
    # cached closures capture only HOST objects (model, static dict,
    # free map) — never device buffers — so the cache cannot pin
    # accelerator memory.

    def _delay_fn(self, params):
        return _delay_impl(self.model, params, self.batch, self.prep)

    def _phase_continuous(self, params):
        """Differentiable phase minus the (constant) host reference ints."""
        return _phase_impl(self.model, params, self.batch, self.prep)

    # prep entries consumed ONLY at pack time on the host — they never
    # enter traced code, so they must not poison the compile-cache key
    # (T_ld is an object array of LD scalars whose tobytes() would be
    # pointer-unique per prepare)
    _HOST_ONLY_PREP = frozenset({"T_ld"})

    def _structure_key(self):
        if self._skey is None:
            # per-component signature: class, order, AND which params
            # are set — components pick parameterization branches at
            # trace time on value PRESENCE (e.g. BinaryDDH H4 vs
            # STIGMA, ELL1H orthometric modes), and params0 stores
            # None as 0.0, so presence is structure the key must carry
            comps = tuple(
                (c.__class__.__name__, c.order,
                 tuple((pn, getattr(c, pn).value is None)
                       for pn in c.params))
                for c in self.model.components.values())
            statics = tuple((k, _static_key_value(self._prep_static[k]))
                            for k in sorted(self._prep_static)
                            if k not in self._HOST_ONLY_PREP)
            shapes = tuple(sorted((k, np.shape(v))
                                  for k, v in self.params0.items()))
            self._skey = (comps, statics, shapes)
        # the free-param map is recomputed EVERY call: freezing or
        # freeing a parameter after prepare() must change the key, or
        # a cached fn built for the old map would silently mis-overlay
        # the shorter/longer x vector
        return self._skey + (tuple(self.free_param_map()),)

    def _global_fn(self, variant, builder):
        """Fetch (or jit-and-store) the compiled fn for this model
        structure; `builder()` must return f(arg, params0, batch,
        prep_arrays) closing over host state only."""
        import jax

        key = (variant, self._structure_key())
        fn = _GLOBAL_FNS.get(key)
        if fn is None:
            # FIFO bound: each closure keeps its creating model (host
            # object) alive, so an unbounded cache would grow host
            # memory monotonically across many distinct structures
            while len(_GLOBAL_FNS) >= _GLOBAL_FNS_MAX:
                _GLOBAL_FNS.pop(next(iter(_GLOBAL_FNS)))
            fn = jax.jit(builder())
            _GLOBAL_FNS[key] = fn
        return fn

    def delay(self, params=None):
        model, static = self.model, self._prep_static
        fn = self._global_fn(("delay",), lambda: (
            lambda p, batch, pa:
                _delay_impl(model, p, batch, _merge_prep(static, pa))))
        return fn(self.params0 if params is None else params,
                  self.batch, self._prep_arrays)

    def phase_frac_and_int(self, params=None):
        import jax.numpy as jnp

        model, static = self.model, self._prep_static
        fn = self._global_fn(("phasec",), lambda: (
            lambda p, batch, pa:
                _phase_impl(model, p, batch, _merge_prep(static, pa))))
        frac = fn(self.params0 if params is None else params,
                  self.batch, self._prep_arrays)
        n = jnp.floor(frac + 0.5)
        return frac - n, self.prep["phi_ref_int"] + n

    def phase(self, params=None):
        """Full Phase (int, frac) (reference: TimingModel.phase)."""
        from ..phase import Phase

        frac, pint_ = self.phase_frac_and_int(params)
        return Phase(pint_, frac)

    def scaled_sigma_us(self, params=None):
        return _sigma_impl(self.model,
                           self.params0 if params is None else params,
                           self.batch, self.prep)

    def _jit(self, name, fn):
        """Instance-local jit cache for AD-HOC functions (numeric
        cross-check helpers in tests); the production forward/derivative
        paths go through _global_fn's structure-keyed cache instead."""
        import jax

        if name not in self._fns:
            self._fns[name] = jax.jit(fn)
        return self._fns[name]

    def residual_vector_fn(self, subtract_mean=True, use_weighted_mean=True,
                           track_mode="nearest"):
        """Jitted x -> whitened-ready time residuals [s] as a function of
        the free-param vector. The exact-delta phase formulation makes
        this valid for any x without re-preparing (the host reference
        terms are constants, not an approximation), so fit loops run
        entirely on device.

        track_mode 'use_pulse_numbers' honors tim-file pn flags /
        TRACK -2 (reference: residuals.py track_mode) instead of
        wrapping to the nearest turn.
        """
        import jax
        import jax.numpy as jnp

        from ..utils import weighted_mean

        model, static = self.model, self._prep_static
        free_map = tuple(self.free_param_map())

        def build():
            def f(x, params0, batch, pa):
                prep = _merge_prep(static, pa)
                p = _overlay_params(x, params0, free_map)
                frac = _phase_impl(model, p, batch, prep)
                if track_mode == "use_pulse_numbers":
                    # full phase minus assigned pulse number; untracked
                    # TOAs fall back to nearest-turn wrapping
                    pn = batch.pulse_number
                    tracked = (prep["phi_ref_int"] - pn) + frac
                    wrapped = frac - jnp.floor(frac + 0.5)
                    resid = jnp.where(jnp.isnan(pn), wrapped, tracked)
                else:
                    resid = frac - jnp.floor(frac + 0.5)
                if subtract_mean:
                    if use_weighted_mean:
                        sigma = _sigma_impl(model, p, batch, prep)
                        resid = resid - weighted_mean(resid, sigma)
                    else:
                        resid = resid - jnp.mean(resid)
                return resid / p["F"][0]
            return f

        fn = self._global_fn(
            ("residfn", subtract_mean, use_weighted_mean, track_mode), build)
        return lambda x: fn(x, self.params0, self.batch, self._prep_arrays)

    def designmatrix_fn(self, incoffset=True):
        """Jitted x -> (n_toa, n_free[+1]) phase-derivative matrix."""
        import jax
        import jax.numpy as jnp

        labels = [n for n, _, _ in self.free_param_map()]
        # PHOFF free -> it IS the offset column; drop the implicit one
        # (reference: phase_offset.py PhaseOffset vs 'Offset' column)
        if incoffset and "PHOFF" in labels:
            incoffset = False
        model, static = self.model, self._prep_static
        free_map = tuple(self.free_param_map())

        def build():
            def dm(x, params0, batch, pa):
                prep = _merge_prep(static, pa)

                def f(xx):
                    return _phase_impl(
                        model, _overlay_params(xx, params0, free_map),
                        batch, prep)

                M = jax.jacfwd(f)(x)
                if incoffset:
                    M = jnp.concatenate([jnp.ones((M.shape[0], 1)), M], axis=1)
                return M
            return dm

        fn = self._global_fn(("dmfn", incoffset), build)
        labels_out = (["Offset"] + labels) if incoffset else labels
        return (lambda x: fn(x, self.params0, self.batch, self._prep_arrays),
                labels_out)

    def designmatrix(self, params=None, incoffset=True):
        """M[i,j] = d(phase_i)/d(param_j) in cycles/par-unit, via jacfwd.

        The reference chains hand-written analytic derivatives
        (reference: timing_model.py::designmatrix + d_phase_d_param);
        here the jitted phase graph is differentiated directly — same
        columns, no 50-function registry. Column 0 is the implicit
        phase offset (reference: 'Offset' column).
        """
        p = self.params0 if params is None else params
        fn, labels = self.designmatrix_fn(incoffset=incoffset)
        return fn(self.vector_from_params(p)), labels
