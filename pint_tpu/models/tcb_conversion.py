"""TCB <-> TDB timing-model conversion.

(reference: src/pint/models/tcb_conversion.py — convert_tcb_tdb,
scale_parameter, transform_mjd_parameter; CLI script tcb2tdb.)

TCB ticks faster than TDB by 1/(1-L_B), L_B = 1.550519768e-8 (IAU
2006 resolution B3). A par file fitted in TCB units converts to TDB
by scaling every parameter with net time dimension d by K^d
(K = 1/(1-L_B)) and mapping epochs through the linear relation pinned
at the IFTE epoch MJD 43144.0003725 (TAI 1977-01-01.0).

This is the same "multiply by K^d" rule tempo2's TRANSFORM and the
reference implement; like them, it does not re-fit — second-order
effects (e.g. DM vs frequency-scale coupling) are below the fit
uncertainties they are compared against.
"""

from __future__ import annotations

L_B = 1.550519768e-8
IFTE_MJD0 = 43144.0003725
IFTE_K = 1.0 / (1.0 - L_B)

# net time-dimension of each convertible parameter family:
# value_tdb = value_tcb * K**dim   (K = 1/(1-L_B) > 1)
# A frequency (s^-1) gets dim=+1; an interval (s) gets dim=-1.
_DIMS = {
    "F": lambda idx: idx + 1,     # F0 s^-1, F1 s^-2, ...
    "FB": lambda idx: idx + 1,    # FB0 s^-1, ...
    "PB": lambda idx: -1,
    "A1": lambda idx: -1,         # light-seconds
    "GAMMA": lambda idx: -1,
    "M2": lambda idx: -1,         # enters timing as TSUN*M2 seconds
    "MTOT": lambda idx: -1,
    "DM": lambda idx: 1 + idx,    # DMconst*DM has units of s*MHz^2 => +1;
                                  # DM1 (per-time derivative) one more
    "DMX_": lambda idx: 1,
    "NE_SW": lambda idx: +1,
    "PX": lambda idx: 0,
}

_EPOCHS = ("PEPOCH", "POSEPOCH", "DMEPOCH", "T0", "TASC", "TZRMJD",
           "WAVEEPOCH", "GLEP")


def scale_parameter(model, pname, dim, backwards=False):
    par = getattr(model, pname, None)
    if par is None or par.value is None:
        return
    k = IFTE_K ** (-dim if backwards else dim)
    par.value = par.value * k
    if par.uncertainty is not None:
        par.uncertainty = par.uncertainty * k


def transform_mjd_parameter(model, pname, backwards=False):
    par = getattr(model, pname, None)
    if par is None or par.value is None:
        return
    # MJD(TDB) = MJD0 + (MJD(TCB) - MJD0) / K
    f = IFTE_K if backwards else 1.0 / IFTE_K
    par.value = IFTE_MJD0 + (par.value - IFTE_MJD0) * f
    if par.uncertainty is not None:
        par.uncertainty = par.uncertainty * f


def convert_tcb_tdb(model, backwards=False):
    """In-place convert a TimingModel between TCB and TDB units
    (reference: tcb_conversion.py::convert_tcb_tdb). backwards=True
    goes TDB -> TCB."""
    from ..utils import split_prefixed_name

    for pname in list(model.params):
        if pname in _EPOCHS or (pname[:4] == "GLEP"):
            transform_mjd_parameter(model, pname, backwards)
            continue
        # exact name first: A1/M2 would otherwise be split into
        # ("A", 1)/("M", 2) and silently skipped
        if pname in _DIMS:
            prefix, idx = pname, 0
        else:
            try:
                prefix, idx = split_prefixed_name(pname)
            except ValueError:
                prefix, idx = pname, 0
        if prefix in _DIMS:
            scale_parameter(model, pname, _DIMS[prefix](idx), backwards)
    units = getattr(model, "UNITS", None)
    if units is not None:
        units.value = "TCB" if backwards else "TDB"
    return model
