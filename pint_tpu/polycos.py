"""Polycos: piecewise-polynomial phase predictors, TEMPO format.

(reference: src/pint/polycos.py — Polycos.generate_polycos,
read_polyco_file, eval_abs_phase, eval_spin_freq, write_polyco_file.)

TEMPO polyco.dat convention (per segment)::

    phase(t) = RPHASE + 60 * F0 * DT + sum_k COEFF[k] * DT^k
    freq(t)  = F0 + (1/60) * sum_k k * COEFF[k] * DT^(k-1)

with DT = (t - TMID) [minutes]. Generation fits the coefficients to
the full timing-model phase at Chebyshev nodes inside each segment —
one vmapped least-squares per segment batch instead of the reference's
per-segment numpy loop.
"""

from __future__ import annotations

import math

import numpy as np

from .toa import TOA, TOAs


class PolycoEntry:
    """One polyco segment (reference: polycos.py::PolycoEntry)."""

    def __init__(self, tmid_mjd, mjdspan_min, rphase_int, rphase_frac,
                 f0, ncoeff, coeffs, obs="gbt", obsfreq=1400.0, psrname="PSR"):
        self.tmid = float(tmid_mjd)
        self.mjdspan = float(mjdspan_min)
        self.rphase_int = int(rphase_int)
        self.rphase_frac = float(rphase_frac)
        self.f0 = float(f0)
        self.ncoeff = int(ncoeff)
        self.coeffs = np.asarray(coeffs, dtype=np.float64)
        self.obs = obs
        self.obsfreq = float(obsfreq)
        self.psrname = psrname

    @property
    def start(self):
        return self.tmid - self.mjdspan / 2880.0

    @property
    def stop(self):
        return self.tmid + self.mjdspan / 2880.0

    def covers(self, mjd):
        return (mjd >= self.start) & (mjd <= self.stop)

    def abs_phase(self, mjd):
        """Absolute phase (int, frac) at topocentric MJD(s)."""
        dt_min = (np.asarray(mjd, np.float64) - self.tmid) * 1440.0
        poly = np.polynomial.polynomial.polyval(dt_min, self.coeffs)
        ph = self.rphase_frac + 60.0 * self.f0 * dt_min + poly
        n = np.floor(ph)
        return self.rphase_int + n.astype(np.int64), ph - n

    def spin_freq(self, mjd):
        """Apparent spin frequency [Hz] (reference: evalfreq)."""
        dt_min = (np.asarray(mjd, np.float64) - self.tmid) * 1440.0
        k = np.arange(1, self.ncoeff)
        dpoly = np.polynomial.polynomial.polyval(dt_min, k * self.coeffs[1:])
        return self.f0 + dpoly / 60.0


class Polycos:
    """Set of polyco segments (reference: polycos.py::Polycos)."""

    def __init__(self, entries=()):
        self.entries: list[PolycoEntry] = list(entries)

    # ---------------- generation ----------------

    @classmethod
    def generate_polycos(cls, model, mjd_start, mjd_end, obs="gbt",
                         segLength=60, ncoeff=12, obsFreq=1400.0,
                         nodes_per_seg=None):
        """Fit polyco segments to the model phase.

        segLength in minutes (reference: generate_polycos signature).
        The model phase is evaluated through the full topocentric
        pipeline at Chebyshev nodes, then each segment's coefficients
        come from one well-conditioned Chebyshev-Vandermonde lstsq.
        """
        nodes = nodes_per_seg or max(2 * ncoeff, 24)
        seg_days = segLength / 1440.0
        n_seg = max(1, int(math.ceil((mjd_end - mjd_start) / seg_days - 1e-9)))
        psrname = model.PSR.value if "PSR" in model.params else "PSR"
        entries = []
        # Chebyshev nodes in [-1, 1] shared by all segments
        xk = np.cos(np.pi * (2 * np.arange(nodes) + 1) / (2.0 * nodes))[::-1]
        # quantize tmids to their file representation so the written
        # polyco reproduces the generation-time phases exactly
        tmids = np.array([
            float(f"{mjd_start + (i + 0.5) * seg_days:.15f}")
            for i in range(n_seg)])
        # ONE pipeline + jit pass over all segments' nodes (the
        # per-segment loop below only does tiny host lstsq work)
        all_mjds = (tmids[:, None] + xk[None, :] * seg_days / 2.0).ravel()
        all_int, all_frac = _model_abs_phase(model, all_mjds, obs, obsFreq)
        all_int = all_int.reshape(n_seg, nodes)
        all_frac = all_frac.reshape(n_seg, nodes)
        for i in range(n_seg):
            tmid = tmids[i]
            ph_int, ph_frac = all_int[i], all_frac[i]
            # reference phase at tmid: nearest node's int part anchors;
            # work in exact (int - int0) + frac space in longdouble
            mid_idx = nodes // 2
            rph_int = int(ph_int[mid_idx])
            dphi = (ph_int - rph_int).astype(np.float64) + ph_frac
            # dt from the f64-rounded node MJDs actually evaluated, so
            # the fit is consistent with eval-time (mjd - tmid) math
            dt_min = (all_mjds.reshape(n_seg, nodes)[i] - tmid) * 1440.0
            f0 = float(model.F0.value)
            resid_ph = dphi - 60.0 * f0 * dt_min
            # Chebyshev-basis lstsq, then convert to power basis for the
            # TEMPO file convention
            T = np.polynomial.chebyshev.chebvander(xk, ncoeff - 1)
            c_cheb, *_ = np.linalg.lstsq(T, resid_ph, rcond=None)
            c_pow = np.polynomial.chebyshev.cheb2poly(c_cheb)
            # rescale from x in [-1,1] to dt_min: x = dt_min / half_min
            half_min = seg_days / 2.0 * 1440.0
            c_dt = c_pow / half_min ** np.arange(len(c_pow))
            c_dt = np.pad(c_dt, (0, ncoeff - len(c_dt)))
            rphase_frac = float(np.polynomial.polynomial.polyval(0.0, c_dt))
            c_dt[0] -= rphase_frac  # fold the constant into RPHASE
            # renormalize so RPHASE = int.frac with frac in [0, 1)
            carry = math.floor(rphase_frac)
            rph_int += carry
            rphase_frac -= carry
            entries.append(PolycoEntry(
                tmid, segLength, rph_int, rphase_frac, f0, ncoeff, c_dt,
                obs=obs, obsfreq=obsFreq, psrname=psrname))
        return cls(entries)

    # ---------------- evaluation ----------------

    def _find(self, mjds):
        mjds = np.atleast_1d(np.asarray(mjds, np.float64))
        idx = np.full(mjds.shape, -1, dtype=int)
        for i, e in enumerate(self.entries):
            m = e.covers(mjds) & (idx < 0)
            idx[m] = i
        if (idx < 0).any():
            bad = mjds[idx < 0]
            raise ValueError(f"MJDs outside polyco span: {bad[:3]}...")
        return mjds, idx

    def eval_abs_phase(self, mjds):
        """(int, frac) absolute phase (reference: eval_abs_phase)."""
        mjds, idx = self._find(mjds)
        pi_ = np.empty(mjds.shape, np.int64)
        pf = np.empty(mjds.shape, np.float64)
        for i, e in enumerate(self.entries):
            m = idx == i
            if m.any():
                pi_[m], pf[m] = e.abs_phase(mjds[m])
        return pi_, pf

    def eval_phase(self, mjds):
        """Fractional phase in [-0.5, 0.5) (reference: eval_phase)."""
        _, pf = self.eval_abs_phase(mjds)
        return pf - np.round(pf)

    def eval_spin_freq(self, mjds):
        """(reference: eval_spin_freq)"""
        mjds, idx = self._find(mjds)
        out = np.empty(mjds.shape, np.float64)
        for i, e in enumerate(self.entries):
            m = idx == i
            if m.any():
                out[m] = e.spin_freq(mjds[m])
        return out

    # ---------------- TEMPO format I/O ----------------

    def write_polyco_file(self, path):
        """(reference: polycos.py format writer; TEMPO polyco.dat)"""
        with open(path, "w") as f:
            for e in self.entries:
                date = _mjd_to_datestr(e.tmid)
                utc = _mjd_to_utcstr(e.tmid)
                f.write(f"{e.psrname:<10s} {date:>9s}{utc:>11s}"
                        f"{e.tmid:24.15f}{0.0:21.6f} 0.000 0.000\n")
                # sign-magnitude decimal: external readers parse the
                # whole field as one signed number, so a negative
                # absolute phase must print as -(|int|.|frac|)
                total_neg = e.rphase_int < 0 or (e.rphase_int == 0
                                                 and e.rphase_frac < 0)
                if total_neg:
                    if e.rphase_frac == 0.0:
                        ip, fr = -e.rphase_int, 0.0
                    else:
                        ip, fr = -(e.rphase_int + 1), 1.0 - e.rphase_frac
                    rph = f"-{ip}.{min(int(round(fr * 1e6)), 999999):06d}"
                else:
                    rph = f"{e.rphase_int}.{min(int(round(e.rphase_frac * 1e6)), 999999):06d}"
                f.write(f"{rph:>20s}{e.f0:18.12f}{_obs_code(e.obs):>5s}"
                        f"{e.mjdspan:10.0f}{e.ncoeff:5d}{e.obsfreq:10.3f}\n")
                for j in range(0, e.ncoeff, 3):
                    f.write("".join(f"{c:25.17e}" for c in e.coeffs[j:j + 3]) + "\n")

    @classmethod
    def read_polyco_file(cls, path):
        """(reference: polycos.py::Polycos.read_polyco_file)"""
        entries = []
        with open(path) as f:
            lines = [l for l in f.read().splitlines() if l.strip()]
        i = 0
        while i < len(lines):
            hdr1 = lines[i].split()
            psrname = hdr1[0]
            tmid = float(hdr1[3])
            hdr2 = lines[i + 1].split()
            rph = hdr2[0]
            # signed decimal: value = sign * (|int|.|frac|); renormalize
            # to rphase_int + frac with frac in [0, 1)
            neg = rph.lstrip().startswith("-")
            body = rph.lstrip().lstrip("-")
            if "." in body:
                ip, fp = body.split(".")
                rphase_int, rphase_frac = int(ip or 0), float("0." + fp)
            else:
                rphase_int, rphase_frac = int(body), 0.0
            if neg:
                if rphase_frac:
                    rphase_int = -rphase_int - 1
                    rphase_frac = 1.0 - rphase_frac
                else:
                    rphase_int = -rphase_int
            f0 = float(hdr2[1])
            obs = hdr2[2]
            span = float(hdr2[3])
            ncoeff = int(hdr2[4])
            obsfreq = float(hdr2[5])
            ncl = (ncoeff + 2) // 3
            coeffs = []
            for l in lines[i + 2: i + 2 + ncl]:
                coeffs.extend(float(x.replace("D", "e")) for x in l.split())
            entries.append(PolycoEntry(tmid, span, rphase_int, rphase_frac,
                                       f0, ncoeff, coeffs, obs=obs,
                                       obsfreq=obsfreq, psrname=psrname))
            i += 2 + ncl
        return cls(entries)


def _model_abs_phase(model, mjds, obs, freq_mhz):
    """Absolute model phase at topocentric UTC MJDs via the full pipeline."""
    toalist = [TOA(int(m), (m - int(m)) * 86400.0, error_us=1.0,
                   freq_mhz=freq_mhz, obs=obs) for m in mjds]
    ephem = "de440s"
    if "EPHEM" in model.params and model.EPHEM.value:
        ephem = model.EPHEM.value.lower()
    toas = TOAs(toalist, ephem=ephem)
    toas.apply_clock_corrections()
    toas.compute_TDBs()
    toas.compute_posvels()
    ph = model.prepare(toas, subtract_mean=False).phase()
    return (np.asarray(ph.int_, np.int64), np.asarray(ph.frac, np.float64))


_MONTHS = ("Jan", "Feb", "Mar", "Apr", "May", "Jun",
           "Jul", "Aug", "Sep", "Oct", "Nov", "Dec")


def _mjd_to_datestr(mjd):
    """MJD -> TEMPO polyco DD-Mon-YY date field."""
    from .mjd import mjd_to_caldate

    y, mo, d = mjd_to_caldate(int(mjd))
    return f"{d:2d}-{_MONTHS[mo - 1]}-{y % 100:02d}"


def _mjd_to_utcstr(mjd):
    frac = mjd - int(mjd)
    s = frac * 86400.0
    h = int(s // 3600)
    m = int((s - 3600 * h) // 60)
    sec = s - 3600 * h - 60 * m
    return f"{h:02d}{m:02d}{sec:05.2f}"


_OBS_CODES = {"gbt": "1", "arecibo": "3", "ao": "3", "parkes": "7",
              "jodrell": "8", "jbo": "8", "vla": "6", "effelsberg": "g",
              "meerkat": "m", "@": "@", "bat": "@", "geocenter": "0"}


def _obs_code(obs):
    return _OBS_CODES.get(str(obs).lower(), str(obs)[:1])
