"""Earth-orientation parameters (UT1-UTC, polar motion).

The reference gets these from astropy's auto-downloaded IERS-A tables
(reference: src/pint/erfautils.py + astropy.utils.iers). This build
environment has no network and no bundled EOP data, so:

- ``EOPTable.from_finals2000a(path)`` parses a standard IERS
  ``finals2000A.all``-format file if the user supplies one;
- otherwise the rotation chain runs with UT1=UTC and zero polar motion
  (documented error: up to ~1.4 us Roemer from |UT1-UTC|<=0.9 s, and
  ~30 ns from ~0.3 arcsec polar motion).
"""

from __future__ import annotations

import numpy as np

from ..constants import ARCSEC_TO_RAD
from ..mjd import Epochs


class EOPTable:
    """Linear-interpolated EOP series keyed on UTC MJD."""

    def __init__(self, mjd, ut1_utc, pm_x_arcsec, pm_y_arcsec):
        self.mjd = np.asarray(mjd, dtype=np.float64)
        self.ut1_utc = np.asarray(ut1_utc, dtype=np.float64)
        self.pm_x = np.asarray(pm_x_arcsec, dtype=np.float64)
        self.pm_y = np.asarray(pm_y_arcsec, dtype=np.float64)

    @classmethod
    def from_finals2000a(cls, path: str) -> "EOPTable":
        """Parse IERS finals2000A fixed-width format (Bulletin A columns)."""
        mjd, dut, px, py = [], [], [], []
        with open(path) as f:
            for line in f:
                if len(line) < 68:
                    continue
                try:
                    m = float(line[7:15])
                    x = float(line[18:27])
                    y = float(line[37:46])
                    d = float(line[58:68])
                except ValueError:
                    continue
                mjd.append(m)
                px.append(x)
                py.append(y)
                dut.append(d)
        if not mjd:
            raise ValueError(f"no EOP rows parsed from {path}")
        return cls(mjd, dut, px, py)

    def _interp(self, series, t: Epochs):
        x = t.mjd_float()
        return np.interp(x, self.mjd, series)

    def ut1_minus_utc(self, t: Epochs) -> np.ndarray:
        return self._interp(self.ut1_utc, t)

    def polar_motion(self, t: Epochs):
        """(xp, yp) in radians."""
        return (self._interp(self.pm_x, t) * ARCSEC_TO_RAD,
                self._interp(self.pm_y, t) * ARCSEC_TO_RAD)


# --- global table: the transparent data-upgrade path -------------------
# Drop a finals2000A.all into pint_tpu/data/ (or point $PINT_TPU_EOP_FILE
# at one) and every site->GCRS conversion picks it up; no code changes.
_GLOBAL: EOPTable | None = None
_SEARCHED = False


def set_eop_table(table: EOPTable | None) -> None:
    """Install the process-wide EOP table; None DISABLES EOP (the
    UT1=UTC / zero-polar-motion tier) until reset_eop_discovery() or a
    new table. Disabling sticks — it does not re-trigger file
    discovery, so "how much does EOP data contribute" comparisons are
    expressible."""
    global _GLOBAL, _SEARCHED
    _GLOBAL = table
    _SEARCHED = True


def reset_eop_discovery() -> None:
    """Forget any installed/disabled state and re-run the file
    auto-discovery on next use (e.g. after changing
    $PINT_TPU_EOP_FILE)."""
    global _GLOBAL, _SEARCHED
    _GLOBAL = None
    _SEARCHED = False


def get_eop_table() -> EOPTable | None:
    """The process-wide EOP table, auto-discovered on first use from
    $PINT_TPU_EOP_FILE or pint_tpu/data/finals2000A.all; None when no
    data is available (rotation chain then runs UT1=UTC, zero polar
    motion — the documented ~1.4 us fallback tier)."""
    global _GLOBAL, _SEARCHED
    if _SEARCHED:
        return _GLOBAL
    _SEARCHED = True
    import os
    import warnings

    env_file = os.environ.get("PINT_TPU_EOP_FILE", "")
    candidates = [
        env_file,
        os.path.join(os.path.dirname(__file__), "..", "data",
                     "finals2000A.all"),
    ]
    for p in candidates:
        if not p:
            continue
        try:
            _GLOBAL = EOPTable.from_finals2000a(p)
            break
        except FileNotFoundError:
            continue  # candidate simply absent — the normal case
        except (OSError, ValueError) as e:
            # a file that EXISTS but fails to load deserves a
            # diagnostic — silently ignoring it would let the user
            # believe their data is applied while the chain runs the
            # degraded UT1=UTC tier
            which = (f"PINT_TPU_EOP_FILE={p!r}" if p == env_file
                     else f"bundled EOP file {p!r}")
            warnings.warn(f"{which} could not be loaded ({e}); "
                          "continuing without it")
            continue
    return _GLOBAL
