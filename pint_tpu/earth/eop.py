"""Earth-orientation parameters (UT1-UTC, polar motion).

The reference gets these from astropy's auto-downloaded IERS-A tables
(reference: src/pint/erfautils.py + astropy.utils.iers). This build
environment has no network and no bundled EOP data, so:

- ``EOPTable.from_finals2000a(path)`` parses a standard IERS
  ``finals2000A.all``-format file if the user supplies one;
- otherwise the rotation chain runs with UT1=UTC and zero polar motion
  (documented error: up to ~1.4 us Roemer from |UT1-UTC|<=0.9 s, and
  ~30 ns from ~0.3 arcsec polar motion).
"""

from __future__ import annotations

import numpy as np

from ..constants import ARCSEC_TO_RAD
from ..mjd import Epochs


class EOPTable:
    """Linear-interpolated EOP series keyed on UTC MJD."""

    def __init__(self, mjd, ut1_utc, pm_x_arcsec, pm_y_arcsec):
        self.mjd = np.asarray(mjd, dtype=np.float64)
        self.ut1_utc = np.asarray(ut1_utc, dtype=np.float64)
        self.pm_x = np.asarray(pm_x_arcsec, dtype=np.float64)
        self.pm_y = np.asarray(pm_y_arcsec, dtype=np.float64)

    @classmethod
    def from_finals2000a(cls, path: str) -> "EOPTable":
        """Parse IERS finals2000A fixed-width format (Bulletin A columns)."""
        mjd, dut, px, py = [], [], [], []
        with open(path) as f:
            for line in f:
                if len(line) < 68:
                    continue
                try:
                    m = float(line[7:15])
                    x = float(line[18:27])
                    y = float(line[37:46])
                    d = float(line[58:68])
                except ValueError:
                    continue
                mjd.append(m)
                px.append(x)
                py.append(y)
                dut.append(d)
        if not mjd:
            raise ValueError(f"no EOP rows parsed from {path}")
        return cls(mjd, dut, px, py)

    def _interp(self, series, t: Epochs):
        x = t.mjd_float()
        return np.interp(x, self.mjd, series)

    def ut1_minus_utc(self, t: Epochs) -> np.ndarray:
        return self._interp(self.ut1_utc, t)

    def polar_motion(self, t: Epochs):
        """(xp, yp) in radians."""
        return (self._interp(self.pm_x, t) * ARCSEC_TO_RAD,
                self._interp(self.pm_y, t) * ARCSEC_TO_RAD)
