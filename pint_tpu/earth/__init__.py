from .erfa_lite import gcrs_posvel_from_itrf, itrf_to_gcrs_matrix  # noqa: F401
