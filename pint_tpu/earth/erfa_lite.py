"""Earth rotation: ITRF -> GCRS observatory position/velocity.

TPU-native replacement for the reference's ERFA chain
(reference: src/pint/erfautils.py::gcrs_posvel_from_itrf, which calls
astropy/ERFA pnm06a+era00+polar motion). ERFA (C) is not available in
the build environment, so this module implements the needed subset
directly:

- Earth Rotation Angle (ERA, IAU 2000)
- GMST/GAST via IAU 2006 polynomial + equation of the equinoxes
- Frame bias + IAU 1976/2000-style precession angles
- FULL 77-term IAU 2000B nutation (6-coefficient form + planetary
  bias; reproduces the published SOFA nut00b test values to ~1e-19
  rad — tests/test_precision_budget.py::test_nutation_sofa_nut00b_anchor)
- Polar motion hook (EOP table optional; zero fallback)

Accuracy budget (documented, honest): nutation = exact IAU2000B, so
the remaining nutation tier is the 2000B-vs-2000A model difference
~1 mas (~3 cm at Earth radius, ~0.1 ns Roemer); precession model
drift ~0.1 arcsec/century vs IAU2006 (~3 m, ~10 ns at 50 yr from
J2000); UT1=UTC fallback when no EOP table is provided (up to ±0.9 s
→ up to ~1.4 us Roemer; supply an IERS finals file to remove). All
host-side numpy f64; results feed the device TOABatch; the C++ mirror
receives this module's tables at load (native/__init__.py::get_lib).
"""

from __future__ import annotations

import numpy as np

from ..constants import ARCSEC_TO_RAD, SECS_PER_DAY
from ..mjd import Epochs
from .. import timescales as ts
from .eop import EOPTable  # noqa: F401  (re-export: callers pass EOPTable in)

TWO_PI = 2.0 * np.pi
OMEGA_EARTH = 7.292115855306589e-5  # rad/s, Earth rotation rate (IERS)

# WGS84 / GRS80 ellipsoid for geodetic -> ITRF conversion
_WGS84_A = 6378137.0
_WGS84_F = 1.0 / 298.257223563


def geodetic_to_itrf(lat_deg, lon_deg, height_m):
    """Geodetic (lat, lon, h) -> ITRF XYZ [m] (reference: erfa gd2gc)."""
    lat = np.deg2rad(lat_deg)
    lon = np.deg2rad(lon_deg)
    e2 = _WGS84_F * (2 - _WGS84_F)
    n = _WGS84_A / np.sqrt(1 - e2 * np.sin(lat) ** 2)
    x = (n + height_m) * np.cos(lat) * np.cos(lon)
    y = (n + height_m) * np.cos(lat) * np.sin(lon)
    z = (n * (1 - e2) + height_m) * np.sin(lat)
    return np.array([x, y, z])


def itrf_to_geodetic(xyz_m):
    """ITRF XYZ [m] -> geodetic (lat_deg, lon_deg, height_m)
    (reference: erfa gc2gd; Bowring's iterative method, WGS84)."""
    x, y, z = np.asarray(xyz_m, dtype=np.float64)
    e2 = _WGS84_F * (2 - _WGS84_F)
    lon = np.arctan2(y, x)
    p = np.hypot(x, y)
    lat = np.arctan2(z, p * (1 - e2))
    for _ in range(4):
        n = _WGS84_A / np.sqrt(1 - e2 * np.sin(lat) ** 2)
        h = p / np.cos(lat) - n
        lat = np.arctan2(z, p * (1 - e2 * n / (n + h)))
    n = _WGS84_A / np.sqrt(1 - e2 * np.sin(lat) ** 2)
    h = p / np.cos(lat) - n
    return np.rad2deg(lat), np.rad2deg(lon), h


def _jc_tt(tt: Epochs) -> np.ndarray:
    """Julian centuries of TT since J2000.0."""
    return ((tt.day - 51544) - 0.5 + tt.sec / SECS_PER_DAY) / 36525.0


def era(ut1: Epochs) -> np.ndarray:
    """Earth Rotation Angle [rad] (reference: erfa era00)."""
    # Tu = JD(UT1) - 2451545.0 ; MJD 51544.5 == J2000.0
    du = (ut1.day - 51544).astype(np.float64) - 0.5 + ut1.sec / SECS_PER_DAY
    # Fractional-cycle carrier: Tu mod 1. Tu = (int days) - 0.5 + sec/day,
    # so the +0.5 is required (erfa era00 uses fmod(jd1,1)+fmod(jd2,1) = 0.5
    # + sec/day for MJD-split epochs); omitting it puts ERA off by exactly pi.
    frac = ut1.sec / SECS_PER_DAY + 0.5
    theta = TWO_PI * (0.7790572732640 + 0.00273781191135448 * du + frac)
    return np.mod(theta, TWO_PI)


# FULL IAU2000B luni-solar nutation series (McCarthy & Luzum 2003):
# 77 rows of (l, lp, F, D, Om multipliers, ps, pst, pc, ec, ect, es)
# with coefficients in 0.1 uas units —
#   dpsi = sum (ps + pst*T) sin(arg) + pc cos(arg)
#   deps = sum (ec + ect*T) cos(arg) + es sin(arg)
# plus fixed planetary-bias offsets (below) in lieu of the 2000A
# planetary terms. Validated against the published SOFA/ERFA nut00b
# test values (tests/test_precision_budget.py) — any wrong
# coefficient anywhere in the table shows at the 1e-13 rad level
# there. (reference: erfa nut00b)
_NUT_TERMS = np.array([
    # l lp  F  D Om      ps        pst      pc        ec       ect    es
    [0, 0, 0, 0, 1, -172064161.0, -174666.0, 33386.0, 92052331.0, 9086.0, 15377.0],
    [0, 0, 2, -2, 2, -13170906.0, -1675.0, -13696.0, 5730336.0, -3015.0, -4587.0],
    [0, 0, 2, 0, 2, -2276413.0, -234.0, 2796.0, 978459.0, -485.0, 1374.0],
    [0, 0, 0, 0, 2, 2074554.0, 207.0, -698.0, -897492.0, 470.0, -291.0],
    [0, 1, 0, 0, 0, 1475877.0, -3633.0, 11817.0, 73871.0, -184.0, -1924.0],
    [0, 1, 2, -2, 2, -516821.0, 1226.0, -524.0, 224386.0, -677.0, -174.0],
    [1, 0, 0, 0, 0, 711159.0, 73.0, -872.0, -6750.0, 0.0, 358.0],
    [0, 0, 2, 0, 1, -387298.0, -367.0, 380.0, 200728.0, 18.0, 318.0],
    [1, 0, 2, 0, 2, -301461.0, -36.0, 816.0, 129025.0, -63.0, 367.0],
    [0, -1, 2, -2, 2, 215829.0, -494.0, 111.0, -95929.0, 299.0, 132.0],
    [0, 0, 2, -2, 1, 128227.0, 137.0, 181.0, -68982.0, -9.0, 39.0],
    [-1, 0, 2, 0, 2, 123457.0, 11.0, 19.0, -53311.0, 32.0, -4.0],
    [-1, 0, 0, 2, 0, 156994.0, 10.0, -168.0, -1235.0, 0.0, 82.0],
    [1, 0, 0, 0, 1, 63110.0, 63.0, 27.0, -33228.0, 0.0, -9.0],
    [-1, 0, 0, 0, 1, -57976.0, -63.0, -189.0, 31429.0, 0.0, -75.0],
    [-1, 0, 2, 2, 2, -59641.0, -11.0, 149.0, 25543.0, -11.0, 66.0],
    [1, 0, 2, 0, 1, -51613.0, -42.0, 129.0, 26366.0, 0.0, 78.0],
    [-2, 0, 2, 0, 1, 45893.0, 50.0, 31.0, -24236.0, -10.0, 20.0],
    [0, 0, 0, 2, 0, 63384.0, 11.0, -150.0, -1220.0, 0.0, 29.0],
    [0, 0, 2, 2, 2, -38571.0, -1.0, 158.0, 16452.0, -11.0, 68.0],
    [0, -2, 2, -2, 2, 32481.0, 0.0, 0.0, -13870.0, 0.0, 0.0],
    [-2, 0, 0, 2, 0, -47722.0, 0.0, -18.0, 477.0, 0.0, -25.0],
    [2, 0, 2, 0, 2, -31046.0, -1.0, 131.0, 13238.0, -11.0, 59.0],
    [1, 0, 2, -2, 2, 28593.0, 0.0, -1.0, -12338.0, 10.0, -3.0],
    [-1, 0, 2, 0, 1, 20441.0, 21.0, 10.0, -10758.0, 0.0, -3.0],
    [2, 0, 0, 0, 0, 29243.0, 0.0, -74.0, -609.0, 0.0, 13.0],
    [0, 0, 2, 0, 0, 25887.0, 0.0, -66.0, -550.0, 0.0, 11.0],
    [0, 1, 0, 0, 1, -14053.0, -25.0, 79.0, 8551.0, -2.0, -45.0],
    [-1, 0, 0, 2, 1, 15164.0, 10.0, 11.0, -8001.0, 0.0, -1.0],
    [0, 2, 2, -2, 2, -15794.0, 72.0, -16.0, 6850.0, -42.0, -5.0],
    [0, 0, -2, 2, 0, 21783.0, 0.0, 13.0, -167.0, 0.0, 13.0],
    [1, 0, 0, -2, 1, -12873.0, -10.0, -37.0, 6953.0, 0.0, -14.0],
    [0, -1, 0, 0, 1, -12654.0, 11.0, 63.0, 6415.0, 0.0, 26.0],
    [-1, 0, 2, 2, 1, -10204.0, 0.0, 25.0, 5222.0, 0.0, 15.0],
    [0, 2, 0, 0, 0, 16707.0, -85.0, -10.0, 168.0, -1.0, 10.0],
    [1, 0, 2, 2, 2, -7691.0, 0.0, 44.0, 3268.0, 0.0, 19.0],
    [-2, 0, 2, 0, 0, -11024.0, 0.0, -14.0, 104.0, 0.0, 2.0],
    [0, 1, 2, 0, 2, 7566.0, -21.0, -11.0, -3250.0, 0.0, -5.0],
    [0, 0, 2, 2, 1, -6637.0, -11.0, 25.0, 3353.0, 0.0, 14.0],
    [0, -1, 2, 0, 2, -7141.0, 21.0, 8.0, 3070.0, 0.0, 4.0],
    [0, 0, 0, 2, 1, -6302.0, -11.0, 2.0, 3272.0, 0.0, 4.0],
    [1, 0, 2, -2, 1, 5800.0, 10.0, 2.0, -3045.0, 0.0, -1.0],
    [2, 0, 2, -2, 2, 6443.0, 0.0, -7.0, -2768.0, 0.0, -4.0],
    [-2, 0, 0, 2, 1, -5774.0, -11.0, -15.0, 3041.0, 0.0, -5.0],
    [2, 0, 2, 0, 1, -5350.0, 0.0, 21.0, 2695.0, 0.0, 12.0],
    [0, -1, 2, -2, 1, -4752.0, -11.0, -3.0, 2719.0, 0.0, -3.0],
    [0, 0, 0, -2, 1, -4940.0, -11.0, -21.0, 2720.0, 0.0, -9.0],
    [-1, -1, 0, 2, 0, 7350.0, 0.0, -8.0, -51.0, 0.0, 4.0],
    [2, 0, 0, -2, 1, 4065.0, 0.0, 6.0, -2206.0, 0.0, 1.0],
    [1, 0, 0, 2, 0, 6579.0, 0.0, -24.0, -199.0, 0.0, 2.0],
    [0, 1, 2, -2, 1, 3579.0, 0.0, 5.0, -1900.0, 0.0, 1.0],
    [1, -1, 0, 0, 0, 4725.0, 0.0, -6.0, -41.0, 0.0, 3.0],
    [-2, 0, 2, 0, 2, -3075.0, 0.0, -2.0, 1313.0, 0.0, -1.0],
    [3, 0, 2, 0, 2, -2904.0, 0.0, 15.0, 1233.0, 0.0, 7.0],
    [0, -1, 0, 2, 0, 4348.0, 0.0, -10.0, -81.0, 0.0, 2.0],
    [1, -1, 2, 0, 2, -2878.0, 0.0, 8.0, 1232.0, 0.0, 4.0],
    [0, 0, 0, 1, 0, -4230.0, 0.0, 5.0, -20.0, 0.0, -2.0],
    [-1, -1, 2, 2, 2, -2819.0, 0.0, 7.0, 1207.0, 0.0, 3.0],
    [-1, 0, 2, 0, 0, -4056.0, 0.0, 5.0, 40.0, 0.0, -2.0],
    [0, -1, 2, 2, 2, -2647.0, 0.0, 11.0, 1129.0, 0.0, 5.0],
    [-2, 0, 0, 0, 1, -2294.0, 0.0, -10.0, 1266.0, 0.0, -4.0],
    [1, 1, 2, 0, 2, 2481.0, 0.0, -7.0, -1062.0, 0.0, -3.0],
    [2, 0, 0, 0, 1, 2179.0, 0.0, -2.0, -1129.0, 0.0, -2.0],
    [-1, 1, 0, 1, 0, 3276.0, 0.0, 1.0, -9.0, 0.0, 0.0],
    [1, 1, 0, 0, 0, -3389.0, 0.0, 5.0, 35.0, 0.0, -2.0],
    [1, 0, 2, 0, 0, 3339.0, 0.0, -13.0, -107.0, 0.0, 1.0],
    [-1, 0, 2, -2, 1, -1987.0, 0.0, -6.0, 1073.0, 0.0, -2.0],
    [1, 0, 0, 0, 2, -1981.0, 0.0, 0.0, 854.0, 0.0, 0.0],
    [-1, 0, 0, 1, 0, 4026.0, 0.0, -353.0, -553.0, 0.0, -139.0],
    [0, 0, 2, 1, 2, 1660.0, 0.0, -5.0, -710.0, 0.0, -2.0],
    [-1, 0, 2, 4, 2, -1521.0, 0.0, 9.0, 647.0, 0.0, 4.0],
    [-1, 1, 0, 1, 1, 1314.0, 0.0, 0.0, -700.0, 0.0, 0.0],
    [0, -2, 2, -2, 1, -1283.0, 0.0, 0.0, 672.0, 0.0, 0.0],
    [1, 0, 2, 2, 1, -1331.0, 0.0, 8.0, 663.0, 0.0, 4.0],
    [-2, 0, 2, 2, 2, 1383.0, 0.0, -2.0, -594.0, 0.0, -2.0],
    [-1, 0, 0, 0, 2, 1405.0, 0.0, 4.0, -610.0, 0.0, 2.0],
    [1, 1, 2, -2, 2, 1290.0, 0.0, 0.0, -556.0, 0.0, 0.0],
])

# Fixed offsets in lieu of the IAU2000A planetary nutation terms
# [arcsec] (nut00b's dpplan/deplan).
_NUT_PLANETARY_BIAS_PSI = -0.135e-3
_NUT_PLANETARY_BIAS_EPS = 0.388e-3


def _fund_args_nut00b(T):
    """Fundamental arguments [rad] as prescribed for the IAU2000B
    series: LINEAR-only Delaunay expressions (nut00b truncates the
    IERS 2003 polynomials; using the quadratic forms here would move
    the series off the published model by ~10 uas at |T|~0.1)."""
    l = (485868.249036 + 1717915923.2178 * T) * ARCSEC_TO_RAD
    lp = (1287104.79305 + 129596581.0481 * T) * ARCSEC_TO_RAD
    F = (335779.526232 + 1739527262.8478 * T) * ARCSEC_TO_RAD
    D = (1072260.70369 + 1602961601.2090 * T) * ARCSEC_TO_RAD
    Om = (450160.398036 - 6962890.5431 * T) * ARCSEC_TO_RAD
    return l, lp, F, D, Om


def nutation(T):
    """(dpsi, deps) [rad], full IAU2000B (reference: erfa nut00b).

    Luni-solar series evaluated as one (N_epochs x 77) matrix product
    against the multiplier table plus the fixed planetary bias —
    ~1 mas of the full 2000A model, vs ~20 mas for the 13-term
    truncation this replaces (ERRORBUDGET.md)."""
    T = np.asarray(T, np.float64)
    scalar = T.ndim == 0
    Tv = np.atleast_1d(T)
    fund = np.stack(_fund_args_nut00b(Tv), axis=0)       # (5, N)
    arg = _NUT_TERMS[:, :5] @ fund                       # (77, N)
    s, c = np.sin(arg), np.cos(arg)
    ps, pst, pc = _NUT_TERMS[:, 5:6], _NUT_TERMS[:, 6:7], _NUT_TERMS[:, 7:8]
    ec, ect, es = _NUT_TERMS[:, 8:9], _NUT_TERMS[:, 9:10], _NUT_TERMS[:, 10:11]
    dpsi = np.sum((ps + pst * Tv) * s + pc * c, axis=0)
    deps = np.sum((ec + ect * Tv) * c + es * s, axis=0)
    scale = 1e-7 * ARCSEC_TO_RAD  # tables are in 0.1 uas
    dpsi = dpsi * scale + _NUT_PLANETARY_BIAS_PSI * ARCSEC_TO_RAD
    deps = deps * scale + _NUT_PLANETARY_BIAS_EPS * ARCSEC_TO_RAD
    if scalar:
        return float(dpsi[0]), float(deps[0])
    return dpsi, deps


def mean_obliquity(T):
    """Mean obliquity of the ecliptic [rad] (IAU 2006)."""
    eps = (84381.406 - 46.836769 * T - 0.0001831 * T**2 + 0.00200340 * T**3)
    return eps * ARCSEC_TO_RAD


def _rx(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([o, z, z], -1),
        np.stack([z, c, s], -1),
        np.stack([z, -s, c], -1),
    ], -2)


def _ry(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([c, z, -s], -1),
        np.stack([z, o, z], -1),
        np.stack([s, z, c], -1),
    ], -2)


def _rz(a):
    c, s = np.cos(a), np.sin(a)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([c, s, z], -1),
        np.stack([-s, c, z], -1),
        np.stack([z, z, o], -1),
    ], -2)


def precession_matrix(T):
    """Precession GCRS(J2000-ish)->mean-of-date, IAU1976 angles + frame bias.

    (reference: erfa pmat06 / bp06). zeta/z/theta polynomial form.
    """
    zeta = (2306.2181 * T + 0.30188 * T**2 + 0.017998 * T**3) * ARCSEC_TO_RAD
    z = (2306.2181 * T + 1.09468 * T**2 + 0.018203 * T**3) * ARCSEC_TO_RAD
    theta = (2004.3109 * T - 0.42665 * T**2 - 0.041833 * T**3) * ARCSEC_TO_RAD
    return _rz(-z) @ _ry(theta) @ _rz(-zeta)


# GCRS frame bias (ICRS vs mean J2000 equator/equinox), constant mas offsets
_BIAS = None


def _bias_matrix():
    global _BIAS
    if _BIAS is None:
        dpsi_b = -0.041775 * ARCSEC_TO_RAD
        deps_b = -0.0068192 * ARCSEC_TO_RAD
        dra0 = -0.0146 * ARCSEC_TO_RAD
        eps0 = 84381.406 * ARCSEC_TO_RAD
        _BIAS = (_rx(np.array(deps_b)) @ _ry(np.array(dpsi_b * np.sin(eps0)))
                 @ _rz(np.array(-dra0)))
    return _BIAS


def nutation_matrix(T):
    dpsi, deps = nutation(T)
    eps = mean_obliquity(T)
    return _rx(-(eps + deps)) @ _rz(-dpsi) @ _rx(eps)


def gast(ut1: Epochs, T_tt) -> np.ndarray:
    """Greenwich apparent sidereal time [rad] (reference: erfa gst06a)."""
    # GMST(IAU2006) = ERA + polynomial
    poly = (0.014506 + 4612.156534 * T_tt + 1.3915817 * T_tt**2
            - 0.00000044 * T_tt**3) * ARCSEC_TO_RAD
    dpsi, _ = nutation(T_tt)
    eps = mean_obliquity(T_tt)
    ee = dpsi * np.cos(eps)  # equation of the equinoxes (main term)
    return np.mod(era(ut1) + poly + ee, TWO_PI)


# default sentinel: "use the process-wide auto-discovered table".
# Distinct from None, which explicitly selects the zero-EOP tier for
# one call without touching global state.
AUTO_EOP = object()


def _earth_rotation_inputs(utc: Epochs, eop):
    """(tt, ut1, xp, yp) — the single home of the UTC->TT/UT1/EOP
    precompute shared by the numpy and native paths.

    eop=AUTO_EOP (the default everywhere) consults the process-wide
    auto-discovered table (earth/eop.py::get_eop_table) so dropping a
    finals2000A.all into the data dir upgrades every site->GCRS
    conversion transparently; eop=None forces UT1=UTC / zero polar
    motion for this call only."""
    from .eop import get_eop_table

    tt = ts.utc_to_tt(utc)
    if eop is AUTO_EOP:
        eop = get_eop_table()
    if eop is not None:
        dut1 = eop.ut1_minus_utc(utc)
        xp, yp = eop.polar_motion(utc)
    else:
        dut1 = np.zeros(len(utc))
        xp = yp = np.zeros(len(utc))
    ut1 = Epochs(utc.day, utc.sec + dut1, "ut1").normalized()
    return tt, ut1, xp, yp


def itrf_to_gcrs_matrix(utc: Epochs, eop=AUTO_EOP,
                        _inputs=None) -> np.ndarray:
    """Rotation matrices (n, 3, 3): r_GCRS = M @ r_ITRF.

    Chain: GCRS = B^T P^T N^T R3(-GAST) W^T r_ITRF
    (equinox-based; reference: erfa c2t06a equivalent).
    """
    tt, ut1, xp, yp = _inputs or _earth_rotation_inputs(utc, eop)
    T = _jc_tt(tt)
    theta = gast(ut1, T)
    # polar motion W = R1(yp) R2(xp) (s' neglected, <0.1 mas)
    W = _ry(xp) @ _rx(yp)
    c2t = W @ _rz(theta) @ nutation_matrix(T) @ precession_matrix(T) @ _bias_matrix()
    return np.swapaxes(c2t, -1, -2)  # transpose: ITRF->GCRS


def gcrs_posvel_from_itrf(itrf_xyz_m, utc: Epochs, eop=AUTO_EOP):
    """Observatory GCRS position [m] and velocity [m/s] at each epoch.

    (reference: src/pint/erfautils.py::gcrs_posvel_from_itrf)

    Dispatches to the C++ host kernel (pint_tpu/native) when built —
    same chain, same truncated series; the numpy path below is the
    always-available mirror.
    """
    from ..native import itrf_to_gcrs as _native

    r = np.asarray(itrf_xyz_m, dtype=np.float64)
    inputs = _earth_rotation_inputs(utc, eop)
    tt, ut1, xp, yp = inputs
    nat = _native(tt.day, tt.sec, ut1.day, ut1.sec, xp, yp, r)
    if nat is not None:
        return nat
    M = itrf_to_gcrs_matrix(utc, eop, _inputs=inputs)
    pos = (M @ r).reshape(len(utc), 3)
    # velocity: d/dt R3(-theta) only (PN terms ~1e5 x slower)
    omega = np.array([0.0, 0.0, OMEGA_EARTH])
    vel = np.cross(np.broadcast_to(omega, pos.shape), pos)
    return pos, vel
