"""CLI: ``python -m pint_tpu.analysis [paths...]``.

Exit status 0 when every finding is suppressed (each suppression is a
reviewed, justified exception), 1 when unsuppressed findings remain,
2 on usage errors. ``--format json`` emits the machine report bench.py
folds into its meta block.
"""

from __future__ import annotations

import argparse
import sys

from . import (LintConfig, all_rules, json_report, run, text_report,
               unsuppressed)


def _list_rules():
    lines = []
    for rule in all_rules():
        lines.append(f"{rule.id:24s} [{rule.family}] {rule.rationale}")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="pintlint",
        description="pint_tpu codebase-aware static analysis")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories to lint "
                             "(default: the pint_tpu package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in text "
                             "output")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)
    if args.list_rules:
        print(_list_rules())
        return 0
    paths = args.paths
    if not paths:
        import pint_tpu

        paths = [pint_tpu.__path__[0]]
    findings = run(paths, config=LintConfig.default())
    if args.format == "json":
        print(json_report(findings))
    else:
        print(text_report(findings,
                          show_suppressed=args.show_suppressed))
    return 1 if unsuppressed(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
