"""CLI: ``python -m pint_tpu.analysis [paths...]``.

Exit status 0 when every finding is suppressed (each suppression is a
reviewed, justified exception), 1 when unsuppressed findings remain,
2 on usage errors. ``--format json`` emits the machine report bench.py
folds into its meta block.

Two speeds:

- the default run includes the whole-program pass (ProjectIndex +
  lock-order-cycle / precision-flow / signature-incomplete /
  registry-drift) — the CI gate;
- ``--changed`` lints only files touched in the git diff (``--cached``
  for the staged set — the pre-commit hook in scripts/ uses this) and
  skips whole-program rules, keeping the inner edit loop fast.

``--lock-dag PATH`` writes the acquired-while-held lock-order graph as
JSON — the artifact tests/lockcheck.py cross-validates real execution
order against.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from . import (LintConfig, all_rules, json_report, run_project,
               text_report, unsuppressed)


def _list_rules():
    lines = []
    for rule in all_rules():
        tag = " (whole-program)" if rule.whole_program else ""
        lines.append(f"{rule.id:24s} [{rule.family}]{tag} "
                     f"{rule.rationale}")
    return "\n".join(lines)


def _changed_files(cached=False):
    """Python files touched in the git diff, absolute paths."""
    top = subprocess.run(
        ["git", "rev-parse", "--show-toplevel"],
        capture_output=True, text=True)
    if top.returncode != 0:
        raise SystemExit("pintlint: --changed requires a git checkout "
                         f"({top.stderr.strip()})")
    root = top.stdout.strip()
    cmd = ["git", "diff", "--name-only", "--diff-filter=ACMR"]
    cmd.append("--cached" if cached else "HEAD")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root)
    if out.returncode != 0:
        raise SystemExit(f"pintlint: git diff failed: "
                         f"{out.stderr.strip()}")
    files = []
    for line in out.stdout.splitlines():
        if not line.endswith(".py"):
            continue
        path = os.path.join(root, line)
        if os.path.exists(path):
            files.append(path)
    return files


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="pintlint",
        description="pint_tpu codebase-aware static analysis")
    parser.add_argument("paths", nargs="*", default=None,
                        help="files or directories to lint "
                             "(default: the pint_tpu package)")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in text "
                             "output")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--changed", action="store_true",
                        help="lint only .py files in the git diff "
                             "(per-file rules only — the whole-"
                             "program pass is skipped)")
    parser.add_argument("--cached", action="store_true",
                        help="with --changed: diff the staged set "
                             "(pre-commit mode)")
    parser.add_argument("--no-whole-program", action="store_true",
                        help="skip the ProjectIndex pass and every "
                             "whole-program rule")
    parser.add_argument("--lock-dag", metavar="PATH",
                        help="write the lock-order graph (JSON) here")
    args = parser.parse_args(argv)
    if args.list_rules:
        print(_list_rules())
        return 0
    whole_program = not args.no_whole_program
    paths = args.paths
    if args.changed:
        if paths:
            parser.error("--changed and explicit paths are exclusive")
        paths = _changed_files(cached=args.cached)
        whole_program = False
        if not paths:
            print("pintlint: no changed python files")
            return 0
    if not paths:
        import pint_tpu

        paths = [pint_tpu.__path__[0]]
    findings, project = run_project(paths, config=LintConfig.default(),
                                    whole_program=whole_program)
    if args.lock_dag:
        graph = project.lock_graph
        payload = (graph.as_dict() if graph is not None
                   else {"nodes": [], "edges": []})
        with open(args.lock_dag, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.format == "json":
        print(json_report(findings))
    else:
        print(text_report(findings,
                          show_suppressed=args.show_suppressed))
    return 1 if unsuppressed(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
