"""pintlint core: findings, suppression parsing, the rule registry,
and the file/project walker.

The runtime conventions this codebase depends on — NaN-aware
mixed-precision guards, the ExecutableCache zero-retrace contract,
lock discipline on shared serving state, fault-injection registry
coverage, synchronized timing regions — are invariants no generic
linter knows about. pintlint turns them into machine-checked rules:
each rule is a small AST pass registered here, findings carry a rule
id that per-line comments can suppress, and a project pass at the end
lets cross-file rules (the fault registry) see the whole tree.

Suppression syntax (see docs/lint_rules.md):

    x = risky()  # pintlint: disable=nan-guard
    # pintlint: disable=nan-guard          <- alone: covers next line
    # pintlint: disable-file=timing-no-block  <- whole file

Every suppression should carry a justification in the surrounding
comment; the CI gate counts suppressed findings so silent growth is
visible in bench telemetry.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

SUPPRESS_RE = re.compile(
    r"#\s*pintlint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\- ]+)")


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False

    def as_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "suppressed": self.suppressed}

    def __str__(self):
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


class Suppressions:
    """Per-file suppression map parsed from ``# pintlint:`` comments.

    A ``disable=`` comment suppresses its own line; when the comment is
    the only thing on its line it suppresses the NEXT line instead (so
    a long flagged expression can keep its own line short). ``all``
    matches every rule.
    """

    def __init__(self, source):
        self.line_rules = {}
        self.file_rules = set()
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = SUPPRESS_RE.search(text)
            if not m:
                continue
            kind, raw = m.group(1), m.group(2)
            rules = {r.strip() for r in raw.split(",") if r.strip()}
            if kind == "disable-file":
                self.file_rules |= rules
            elif text.lstrip().startswith("#"):
                self.line_rules.setdefault(lineno + 1, set()).update(rules)
            else:
                self.line_rules.setdefault(lineno, set()).update(rules)

    def is_suppressed(self, rule, line):
        for rules in (self.file_rules, self.line_rules.get(line, ())):
            if rule in rules or "all" in rules:
                return True
        return False


class FileContext:
    """One parsed source file plus its findings."""

    def __init__(self, path, source, config, rel=None):
        self.path = path
        self.rel = rel or path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppressions = Suppressions(source)
        self.config = config
        self.findings = []

    def report(self, rule_id, node, message):
        line = node if isinstance(node, int) else node.lineno
        self.findings.append(Finding(
            rule=rule_id, path=self.rel, line=line, message=message,
            suppressed=self.suppressions.is_suppressed(rule_id, line)))


class Project:
    """Whole-scan state for cross-file rules."""

    def __init__(self, config):
        self.config = config
        self.files = []          # FileContext per parsed file
        self.extra_findings = []  # parse failures etc.
        self.index = None        # ProjectIndex after the project pass
        self.lock_graph = None   # LockGraph from lock-order-cycle


class Rule:
    """Base class: subclasses set ``id``/``family``/``rationale`` and
    implement ``check_file`` (per file), ``finish`` (after every file
    was scanned — cross-file invariants), and/or ``check_project``
    (whole-program rules: runs with the cross-file ``ProjectIndex``
    after all files are parsed). Rules with ``whole_program = True``
    only run when the scan requests the project pass — the --changed
    inner loop skips them."""

    id = None
    family = None
    rationale = ""
    whole_program = False

    def check_file(self, ctx):
        pass

    def check_project(self, project, index):
        pass

    def finish(self, project):
        pass


RULES = {}


def register(cls):
    if cls.id in RULES:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    RULES[cls.id] = cls
    return cls


def all_rules():
    """Fresh rule instances, id-sorted (stable output order)."""
    return [RULES[rid]() for rid in sorted(RULES)]


def iter_py_files(paths):
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for root, dirs, names in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git",
                                          ".jax_cache"))
            for name in sorted(names):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def run(paths, config=None, rules=None, whole_program=True):
    """Lint ``paths`` (files or directory roots). Returns the full
    finding list — suppressed findings included, flagged — so callers
    can gate on unsuppressed ones while still counting the rest.
    ``whole_program=False`` skips the project-index pass and every
    whole-program rule (the fast inner-loop / --changed mode)."""
    findings, _ = run_project(paths, config=config, rules=rules,
                              whole_program=whole_program)
    return findings


def run_project(paths, config=None, rules=None, whole_program=True):
    """Like :func:`run` but also returns the ``Project`` — carrying
    the built ``ProjectIndex`` (``project.index``) and per-rule
    artifacts such as the lock-order graph (``project.lock_graph``)."""
    from .config import LintConfig

    config = config or LintConfig.default()
    rules = rules if rules is not None else all_rules()
    if not whole_program:
        rules = [r for r in rules if not r.whole_program]
    project = Project(config)
    base = os.path.commonpath([os.path.abspath(p) for p in paths]) \
        if paths else os.getcwd()
    if os.path.isfile(base):
        base = os.path.dirname(base)
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        try:
            ctx = FileContext(path, source, config,
                              rel=os.path.relpath(path, base))
        except SyntaxError as e:
            project.extra_findings.append(Finding(
                rule="parse-error", path=path, line=e.lineno or 1,
                message=f"file does not parse: {e.msg}"))
            continue
        for rule in rules:
            rule.check_file(ctx)
        project.files.append(ctx)
    if whole_program and any(r.whole_program for r in rules):
        from .project import build_index

        project.index = build_index(project)
        for rule in rules:
            if rule.whole_program:
                rule.check_project(project, project.index)
    for rule in rules:
        rule.finish(project)
    findings = list(project.extra_findings)
    for ctx in project.files:
        findings.extend(ctx.findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, project


def unsuppressed(findings):
    return [f for f in findings if not f.suppressed]


def counts_by_rule(findings):
    out = {}
    for f in findings:
        key = f.rule + (":suppressed" if f.suppressed else "")
        out[key] = out.get(key, 0) + 1
    return dict(sorted(out.items()))


# -- shared AST helpers used by several rule modules -------------------


def call_name(node):
    """Dotted name of a Call's callee: ``jax.jit`` -> "jax.jit",
    ``jit`` -> "jit"; None for computed callees."""
    return dotted_name(node.func)


def dotted_name(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr_root(node):
    """Peel subscripts/attributes down to a root ``self.X`` access:
    ``self._slots[k]`` -> "_slots"; None when the root is not a direct
    self attribute."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        node = node.value
    return None


def name_root(node):
    """Peel subscripts down to a plain Name: ``CACHE[k]`` -> "CACHE"."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "move_to_end", "pop", "popitem", "popleft", "remove",
    "setdefault", "update", "__setitem__", "__delitem__", "rotate",
})


def mentions(node, pattern):
    """True when any identifier inside ``node`` matches the compiled
    regex ``pattern`` (Name ids and Attribute attrs both count)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and pattern.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and pattern.search(sub.attr):
            return True
    return False
