"""pintlint pass 1: the whole-program ``ProjectIndex``.

Per-file AST rules see one module at a time; the interprocedural
rules (lock-order-cycle, precision-flow, signature-incomplete,
registry-drift) need the tree: which class a ``self.batcher`` attribute
holds, which function a ``from .batcher import pow2_bucket`` name binds
to, which locks a class owns, and who calls whom. This module builds
that index once per scan, from the already-parsed ``FileContext``
trees, with no imports executed — everything is derived syntactically,
so the index is safe to build on broken or heavyweight modules alike.

The index is intentionally a *may* analysis tuned for this codebase's
idioms rather than a sound points-to solver: attribute types come from
``self.x = ClassName(...)`` constructor assignments (including the
``x if x is not None else ClassName(...)`` injection idiom), local
variable types from ``v = ClassName(...)`` / ``v = self.attr``, and
calls resolve through imports, class MROs, and lexically enclosing
scopes. Unresolvable calls stay unresolved; the rules built on top
treat them conservatively.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .core import dotted_name

_LOCK_CTORS = frozenset({"Lock", "RLock"})


def _is_lock_ctor(node):
    """True for ``threading.Lock()`` / ``RLock()`` (any import style)."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if name is None:
        return False
    return name.split(".")[-1] in _LOCK_CTORS


def _condition_alias(node):
    """``threading.Condition(self._lock)`` -> "_lock"; else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None or name.split(".")[-1] != "Condition":
        return None
    if (node.args and isinstance(node.args[0], ast.Attribute)
            and isinstance(node.args[0].value, ast.Name)
            and node.args[0].value.id == "self"):
        return node.args[0].attr
    return None


def module_name_for(rel):
    """Dotted module name from a scan-relative path:
    ``serve/engine.py`` -> "serve.engine", ``obs/__init__.py`` ->
    "obs"."""
    rel = rel.replace(os.sep, "/").lstrip("./")
    if rel.endswith(".py"):
        rel = rel[:-3]
    parts = [p for p in rel.split("/") if p]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "__root__"


@dataclass
class FuncInfo:
    """One function or method definition."""

    qname: str                    # "module.Class.method" / "module.f"
    name: str
    node: object                  # ast.FunctionDef / AsyncFunctionDef
    module: "ModuleInfo"
    cls: "ClassInfo" = None       # owning class, when a method
    parent: "FuncInfo" = None     # lexically enclosing function
    nested: dict = field(default_factory=dict)   # name -> FuncInfo

    @property
    def ctx(self):
        return self.module.ctx


@dataclass
class ClassInfo:
    name: str
    qname: str
    node: object
    module: "ModuleInfo"
    base_names: list = field(default_factory=list)   # dotted strings
    methods: dict = field(default_factory=dict)      # name -> FuncInfo
    attr_types: dict = field(default_factory=dict)   # attr -> class NAME
    lock_attrs: set = field(default_factory=set)     # own Lock/RLock attrs
    cond_aliases: dict = field(default_factory=dict)  # cv attr -> lock attr

    def mro(self, index):
        """This class plus resolved base classes, nearest first.
        Cycles and unresolved bases are skipped silently."""
        out, seen, work = [], set(), [self]
        while work:
            cls = work.pop(0)
            if cls.qname in seen:
                continue
            seen.add(cls.qname)
            out.append(cls)
            for base in cls.base_names:
                resolved = index.resolve_class(cls.module, base)
                if resolved is not None:
                    work.append(resolved)
        return out

    def find_method(self, index, name):
        for cls in self.mro(index):
            if name in cls.methods:
                return cls.methods[name]
        return None

    def all_attr_types(self, index):
        out = {}
        for cls in reversed(self.mro(index)):
            out.update(cls.attr_types)
        return out

    def all_lock_attrs(self, index):
        out = {}                   # attr -> owning ClassInfo
        for cls in reversed(self.mro(index)):
            for attr in cls.lock_attrs:
                out[attr] = cls
        return out

    def all_cond_aliases(self, index):
        out = {}
        for cls in reversed(self.mro(index)):
            out.update(cls.cond_aliases)
        return out


@dataclass
class ModuleInfo:
    name: str
    ctx: object                    # FileContext
    imports: dict = field(default_factory=dict)   # local -> dotted target
    functions: dict = field(default_factory=dict)  # name -> FuncInfo
    classes: dict = field(default_factory=dict)    # name -> ClassInfo
    module_locks: set = field(default_factory=set)  # NAME = Lock()
    global_types: dict = field(default_factory=dict)  # NAME -> class


class ProjectIndex:
    """Cross-file symbol table + call graph over one lint scan."""

    def __init__(self, project):
        self.project = project
        self.modules = {}          # dotted name -> ModuleInfo
        self.functions = {}        # qname -> FuncInfo
        self.classes = {}          # qname -> ClassInfo
        self.classes_by_name = {}  # bare name -> [ClassInfo]
        self._call_cache = {}
        self._ret_cache = {}
        self._ret_inflight = set()
        self._locals_inflight = set()
        for ctx in project.files:
            self._index_module(ctx)
        # attr harvesting and the type-inference passes need the full
        # symbol table, so they run after every module is indexed
        for cls in self.classes.values():
            for method in cls.methods.values():
                self._harvest_attrs(cls, method.node)
        self._infer_global_types()
        self._infer_param_attr_types()

    # -- construction --------------------------------------------------

    def _index_module(self, ctx):
        mod = ModuleInfo(name=module_name_for(ctx.rel), ctx=ctx)
        self.modules[mod.name] = mod
        for node in ctx.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    mod.imports[local] = (alias.name if alias.asname
                                          else alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(mod.name, node)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    mod.imports[local] = (base + "." + alias.name
                                          if base else alias.name)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self._index_function(mod, None, None, node)
            elif isinstance(node, ast.ClassDef):
                self._index_class(mod, node)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Name)
                            and _is_lock_ctor(node.value)):
                        mod.module_locks.add(tgt.id)

    @staticmethod
    def _import_base(modname, node):
        if node.level == 0:
            return node.module or ""
        # relative: level 1 = this file's package, each extra level one
        # package up. A module file's package is its dirname.
        parts = modname.split(".")[:-1]
        up = node.level - 1
        parts = parts[:len(parts) - up] if up else parts
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts)

    def _index_function(self, mod, cls, parent, node):
        prefix = parent.qname if parent else (
            cls.qname if cls else mod.name)
        info = FuncInfo(qname=f"{prefix}.{node.name}", name=node.name,
                        node=node, module=mod, cls=cls, parent=parent)
        self.functions[info.qname] = info
        if parent is not None:
            parent.nested[node.name] = info
        elif cls is not None:
            cls.methods[node.name] = info
        else:
            mod.functions[node.name] = info
        for sub in ast.walk(node):
            if sub is node:
                continue
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._encloses(node, sub, stop_at_funcs=True):
                    self._index_function(mod, cls, info, sub)
        return info

    @staticmethod
    def _encloses(outer, target, stop_at_funcs=False):
        """True when ``target`` is a DIRECT nested def of ``outer``
        (not nested inside a deeper function)."""
        for sub in ast.iter_child_nodes(outer):
            stack = [sub]
            while stack:
                n = stack.pop()
                if n is target:
                    return True
                if (stop_at_funcs and n is not sub
                        and isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))):
                    continue
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)) and n is not sub:
                    continue
                stack.extend(ast.iter_child_nodes(n))
        return False

    def _index_class(self, mod, node):
        cls = ClassInfo(name=node.name,
                        qname=f"{mod.name}.{node.name}",
                        node=node, module=mod)
        cls.base_names = [dotted_name(b) for b in node.bases
                          if dotted_name(b)]
        mod.classes[node.name] = cls
        self.classes[cls.qname] = cls
        self.classes_by_name.setdefault(node.name, []).append(cls)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(mod, cls, None, item)

    def _harvest_attrs(self, cls, fn_node):
        for sub in ast.walk(fn_node):
            if not isinstance(sub, ast.Assign):
                continue
            for tgt in sub.targets:
                attr = None
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    attr = tgt.attr
                elif isinstance(tgt, ast.Subscript):
                    # self.X[k] = C(...): the container's element type
                    from .core import self_attr_root

                    attr = self_attr_root(tgt)
                if attr is None:
                    continue
                if isinstance(tgt, ast.Attribute):
                    if _is_lock_ctor(sub.value):
                        cls.lock_attrs.add(attr)
                        continue
                    alias = _condition_alias(sub.value)
                    if alias is not None:
                        cls.cond_aliases[attr] = alias
                        continue
                typ = self._ctor_class_name(cls.module, sub.value)
                if typ is not None:
                    cls.attr_types.setdefault(attr, typ)

    def _ctor_class_name(self, mod, value):
        """Bare class name when ``value`` constructs exactly one known
        class — handles ``C(...)``, ``x or C(...)``, ``x if x is not
        None else C(...)``, and container displays/comprehensions of a
        single class (``{p: Histogram() for p in ...}``)."""
        hits = set()
        for sub in ast.walk(value):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted_name(sub.func)
            if name is None:
                continue
            resolved = self.resolve_class(mod, name)
            if resolved is not None:
                hits.add(resolved.name)
        return hits.pop() if len(hits) == 1 else None

    # -- type inference passes -----------------------------------------

    def _infer_global_types(self):
        """Module-level singleton instances (``REGISTRY = Registry()``)
        get a type, so ``metricsreg.REGISTRY.counter(...)`` resolves."""
        for mod in self.modules.values():
            for node in mod.ctx.tree.body:
                if not isinstance(node, ast.Assign):
                    continue
                typ = self._ctor_class_name(mod, node.value)
                if typ is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        mod.global_types.setdefault(tgt.id, typ)

    def _infer_param_attr_types(self):
        """Propagate constructor-argument types into attribute types:
        ``ExecutableCache(cap, persistent=p)`` where ``p`` is a known
        ``PersistentExecutableCache`` gives ``self.persistent =
        persistent`` in __init__ a type. One pass, unique types only."""
        cand = {}
        for qname in sorted(self.functions):
            func = self.functions[qname]
            types = self.local_types(func)
            for call, callee in self.calls_of(func):
                if callee is None:
                    continue
                gargs = callee.node.args
                gparams = [a.arg for a in (list(gargs.posonlyargs)
                                           + list(gargs.args))]
                offset = 1 if gparams[:1] == ["self"] else 0
                pairs = []
                for i, arg in enumerate(call.args):
                    if i + offset < len(gparams):
                        pairs.append((gparams[i + offset], arg))
                for kw in call.keywords:
                    if kw.arg in gparams:
                        pairs.append((kw.arg, kw.value))
                for pname, arg in pairs:
                    typ = self._expr_class(func.module, arg, types,
                                           func)
                    if typ is not None:
                        cand.setdefault(
                            (callee.qname, pname), set()).add(typ)
        for cls in self.classes.values():
            for method in cls.methods.values():
                for sub in ast.walk(method.node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    if not isinstance(sub.value, ast.Name):
                        continue
                    key = (method.qname, sub.value.id)
                    types = cand.get(key)
                    if types is None or len(types) != 1:
                        continue
                    typ = next(iter(types))
                    for tgt in sub.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            cls.attr_types.setdefault(tgt.attr, typ)
        # argument types changed what attribute accesses resolve to —
        # drop call resolutions made with the poorer information
        self._call_cache.clear()

    def _expr_class(self, mod, expr, locals_map=None, func=None,
                    depth=0):
        """Bare class name of ``expr``'s value, or None. Follows
        constructor calls, typed locals/globals/attributes, method
        return types, container subscripts, and injection idioms."""
        if depth > 4:
            return None
        locals_map = locals_map or {}
        if isinstance(expr, ast.Name):
            if expr.id in locals_map:
                return locals_map[expr.id]
            return mod.global_types.get(expr.id)
        if isinstance(expr, ast.Subscript):
            # element of a typed container (attr_types harvested the
            # element class from the display/comprehension)
            return self._expr_class(mod, expr.value, locals_map, func,
                                    depth + 1)
        if isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and func is not None and func.cls is not None):
                return func.cls.all_attr_types(self).get(expr.attr)
            dotted = dotted_name(expr)
            if dotted is not None:
                parts = dotted.split(".")
                head = mod.imports.get(parts[0])
                if head is not None:
                    parts = head.split(".") + parts[1:]
                if len(parts) >= 2:
                    owner = self._lookup_module(".".join(parts[:-1]))
                    if owner is not None:
                        return owner.global_types.get(parts[-1])
            return None
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            if name is not None:
                found = self._resolve_dotted(mod, name)
                if isinstance(found, ClassInfo):
                    return found.name
                if isinstance(found, FuncInfo):
                    return self.ret_class(found)
                bare = self.resolve_class(mod, name) \
                    if "." not in name else None
                if bare is not None:
                    return bare.name
            if isinstance(expr.func, ast.Attribute):
                recv = self._expr_class(mod, expr.func.value,
                                        locals_map, func, depth + 1)
                if recv is not None:
                    cls = self.resolve_class(mod, recv)
                    if cls is not None:
                        method = cls.find_method(self, expr.func.attr)
                        if method is not None:
                            return self.ret_class(method)
            return None
        if isinstance(expr, (ast.IfExp, ast.BoolOp)):
            branches = (expr.values if isinstance(expr, ast.BoolOp)
                        else [expr.body, expr.orelse])
            hits = set()
            for b in branches:
                typ = self._expr_class(mod, b, locals_map, func,
                                       depth + 1)
                if typ is not None:
                    hits.add(typ)
            return hits.pop() if len(hits) == 1 else None
        return None

    def ret_class(self, func):
        """Bare class name ``func`` returns, when every classable
        return agrees (``Registry.counter`` -> "Counter")."""
        cached = self._ret_cache.get(func.qname, Ellipsis)
        if cached is not Ellipsis:
            return cached
        if func.qname in self._ret_inflight:
            return None
        self._ret_inflight.add(func.qname)
        try:
            types = self.local_types(func)
            hits = set()
            nested = {n.node for n in func.nested.values()}
            stack = list(ast.iter_child_nodes(func.node))
            while stack:
                n = stack.pop()
                if n in nested:
                    continue
                if isinstance(n, ast.Return) and n.value is not None:
                    typ = self._expr_class(func.module, n.value,
                                           types, func)
                    if typ is not None:
                        hits.add(typ)
                stack.extend(ast.iter_child_nodes(n))
            out = hits.pop() if len(hits) == 1 else None
        finally:
            self._ret_inflight.discard(func.qname)
        self._ret_cache[func.qname] = out
        return out

    # -- name resolution -----------------------------------------------

    def _lookup_module(self, dotted):
        if dotted in self.modules:
            return self.modules[dotted]
        # scans rooted below the package (rel "serve/engine.py" vs
        # absolute import "pint_tpu.serve.engine") meet on suffixes
        for name, mod in self.modules.items():
            if (dotted.endswith("." + name) or name.endswith("." + dotted)):
                return mod
        return None

    def _resolve_dotted(self, mod, dotted):
        """Resolve a dotted name used in ``mod`` to a FuncInfo /
        ClassInfo / ModuleInfo, following one import hop."""
        parts = dotted.split(".")
        head, rest = parts[0], parts[1:]
        target = mod.imports.get(head)
        if target is not None:
            dotted = ".".join([target] + rest)
            parts = dotted.split(".")
        else:
            own = mod.classes.get(head) or mod.functions.get(head)
            if own is not None:            # the module's own namespace
                if not rest:
                    return own
                if isinstance(own, ClassInfo) and len(rest) == 1:
                    return own.find_method(self, rest[0])
                return None
        # longest module prefix, then member lookup
        for cut in range(len(parts), 0, -1):
            owner = self._lookup_module(".".join(parts[:cut]))
            if owner is None:
                continue
            member = parts[cut:]
            if not member:
                return owner
            if len(member) == 1:
                return (owner.functions.get(member[0])
                        or owner.classes.get(member[0]))
            if len(member) == 2 and member[0] in owner.classes:
                return owner.classes[member[0]].find_method(
                    self, member[1])
            return None
        return None

    def resolve_class(self, mod, dotted):
        """ClassInfo for a (possibly dotted) class name used in
        ``mod``; falls back to the unique bare-name match."""
        found = self._resolve_dotted(mod, dotted)
        if isinstance(found, ClassInfo):
            return found
        bare = dotted.split(".")[-1]
        cands = self.classes_by_name.get(bare, ())
        return cands[0] if len(cands) == 1 else None

    # -- call graph ----------------------------------------------------

    def local_types(self, func):
        """{local var -> bare class name} from assignments inside
        ``func``: constructor calls, typed self attrs and globals,
        typed method returns, the injection idioms."""
        if func.qname in self._locals_inflight:
            return {}
        self._locals_inflight.add(func.qname)
        try:
            out = {}
            assigns = [n for n in ast.walk(func.node)
                       if isinstance(n, ast.Assign)]
            assigns.sort(key=lambda n: n.lineno)
            for sub in assigns:
                typ = self._expr_class(func.module, sub.value, out,
                                       func)
                if typ is None:
                    continue
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = typ
            # for-loop element types ride the container heuristic:
            # ``for b in self.batches:`` with batches -> PTABatch
            for sub in ast.walk(func.node):
                if not isinstance(sub, (ast.For, ast.AsyncFor)):
                    continue
                if not isinstance(sub.target, ast.Name):
                    continue
                it = sub.iter
                if (isinstance(it, ast.Call)
                        and isinstance(it.func, ast.Attribute)
                        and it.func.attr in ("values", "items")):
                    it = it.func.value
                typ = self._expr_class(func.module, it, out, func)
                if typ is not None:
                    out.setdefault(sub.target.id, typ)
            return out
        finally:
            self._locals_inflight.discard(func.qname)

    def resolve_call(self, func, call, local_types=None):
        """FuncInfo for ``call``'s callee as seen from inside
        ``func``; None when unresolvable (builtins, externals,
        dynamic dispatch)."""
        callee = call.func
        if isinstance(callee, ast.Name):
            name = callee.id
            cursor = func
            while cursor is not None:       # lexical scope first
                if name in cursor.nested:
                    return cursor.nested[name]
                cursor = cursor.parent
            found = self._resolve_dotted(func.module, name)
            if isinstance(found, FuncInfo):
                return found
            if isinstance(found, ClassInfo):
                return found.find_method(self, "__init__")
            return None
        if isinstance(callee, ast.Subscript):
            return None                     # program tables etc.
        if not isinstance(callee, ast.Attribute):
            return None
        owner, meth = callee.value, callee.attr
        if (isinstance(owner, ast.Name) and owner.id == "self"
                and func.cls is not None):
            return func.cls.find_method(self, meth)
        dotted = dotted_name(callee)
        if dotted is not None:
            found = self._resolve_dotted(func.module, dotted)
            if isinstance(found, FuncInfo):
                return found
            if isinstance(found, ClassInfo):
                return found.find_method(self, "__init__")
        # typed receiver: locals, self attrs, globals, subscripts,
        # chained method returns
        types = (local_types if local_types is not None
                 else self.local_types(func))
        recv = self._expr_class(func.module, owner, types, func)
        if recv is not None:
            cls = self.resolve_class(func.module, recv)
            if cls is not None:
                return cls.find_method(self, meth)
        return None

    def calls_of(self, func):
        """Cached [(ast.Call, FuncInfo-or-None)] for every call inside
        ``func`` (nested defs excluded — they have their own entry)."""
        hit = self._call_cache.get(func.qname)
        if hit is not None:
            return hit
        types = self.local_types(func)
        out = []
        skip = {n.node for n in func.nested.values()}
        stack = list(ast.iter_child_nodes(func.node))
        while stack:
            n = stack.pop()
            if n in skip:
                continue
            if isinstance(n, ast.Call):
                out.append((n, self.resolve_call(func, n, types)))
            stack.extend(ast.iter_child_nodes(n))
        out.reverse()
        self._call_cache[func.qname] = out
        return out


def build_index(project):
    return ProjectIndex(project)
