"""Observability rule family.

- timing-untraced: a raw wall-clock READ (``time.time()``,
  ``time.perf_counter()``, ``time.monotonic()``) in a module that is
  instrumented with the obs tracing layer (``pint_tpu.obs``).
  Instrumented modules must time through ``pint_tpu.obs.clock``
  (``obs_clock.now()`` / ``Stopwatch``) or a span: a raw read uses a
  clock the tracer does not know about, so the number never lands in
  exported timelines or flight-recorder dumps, and two "elapsed"
  figures in one report can come from different clocks.
  ``time.sleep`` is a delay, not a measurement, and injectable timer
  DEFAULTS (``clock=time.monotonic`` — a reference, not a call) stay
  legal. The obs package itself and tests (fake clocks on purpose)
  are allow-listed.
"""

from __future__ import annotations

import ast

from .core import Rule, call_name, register


@register
class TimingUntracedRule(Rule):
    id = "timing-untraced"
    family = "obs"
    rationale = ("raw clock reads in obs-instrumented modules bypass "
                 "the shared obs clock: invisible to span timelines "
                 "and flight dumps")

    def _applies(self, ctx):
        rel = "/" + ctx.rel.replace("\\", "/")
        markers = getattr(ctx.config, "obs_allowed_path_markers", ())
        if any(m in rel for m in markers):
            return False
        suffixes = getattr(ctx.config, "obs_instrumented_modules", ())
        return any(rel.endswith(s) for s in suffixes)

    def check_file(self, ctx):
        if not self._applies(ctx):
            return
        raw = getattr(ctx.config, "obs_raw_timer_calls", frozenset())
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in raw:
                ctx.report(
                    self.id, node,
                    f"raw {name}() in an obs-instrumented module: "
                    "read the clock through pint_tpu.obs.clock "
                    "(obs_clock.now) or wrap the region in an obs "
                    "span so the measurement lands in exported "
                    "timelines and flight dumps")
