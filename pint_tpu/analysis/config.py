"""Codebase-tuned registries the pintlint rules check against.

A generic linter cannot know which functions are f64-critical, which
classes are shared across threads, or which names are legal fault
points — those are THIS codebase's contracts. They live here, in one
reviewable place, so adding a shared class or a fault point is a
one-line registry edit and the rules pick it up everywhere.

Tests construct ``LintConfig`` directly with fixture registries; the
CLI and the CI gate use :meth:`LintConfig.default`, which binds the
registries below plus the live fault-point tuple from
``pint_tpu.resilience.faultinject``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

# Repo root (two levels above this package): registered surfaces like
# /bench.py and /benchmarks/ live outside the pint_tpu scan root, so
# the registry-drift staleness check also looks here.
_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# -- precision ---------------------------------------------------------

# Functions where introducing float32 (literals, dtype=, .astype) is a
# correctness bug: the whitening/normal-equation chain feeding the
# f64-critical residual solve. Keyed by path suffix; "*" marks a whole
# module. gls_gram and the batched mixed branches are deliberately NOT
# listed — their f32 is the sanctioned mixed-precision path, guarded at
# runtime by fitter.relres_failed.
F64_CRITICAL = {
    "pint_tpu/fitter.py": {
        "gls_whiten", "gls_normal", "gls_eigh_solve", "gls_eigh_refine",
        "column_norms", "stack_noise_bases", "relres_failed",
    },
    "pint_tpu/timescales.py": {"*"},
    "pint_tpu/residuals.py": {"*"},
    "pint_tpu/dd.py": {"*"},
}

# -- lock discipline ---------------------------------------------------

# Shared classes whose attributes may be mutated outside the owning
# thread: every mutation of a monitored attribute must sit inside
# ``with self._lock:`` (or live in a ``*_locked`` helper whose call
# sites the locked-helper-call rule checks). attrs=None monitors every
# self attribute except the exemptions.
LOCKED_CLASSES = {
    "ExecutableCache": {"lock": "_lock", "attrs": None},
    "MicroBatcher": {"lock": "_lock", "attrs": None},
    "HealthMonitor": {"lock": "_lock", "attrs": None},
    "CircuitBreaker": {"lock": "_lock", "attrs": None},
    # the async front door: submitter threads, the flusher worker,
    # and the watchdog all touch these
    "ServeTelemetry": {"lock": "_lock", "attrs": None},
    "IntakeQueue": {"lock": "_lock", "attrs": None},
    "AdmissionController": {"lock": "_lock", "attrs": None},
    # only the pipeline state shared with the prep worker pool; fit
    # results (diverged, fit_metrics, ...) are caller-thread-only
    "PTAFleet": {"lock": "_lock",
                 "attrs": {"batches", "_batch_futures", "_prep_pool"}},
    # the flusher work mutex serializes flush/idle generations against
    # drain() and close(); it guards execution phases, not attribute
    # state (attribute discipline on the front door lives in IntakeQueue
    # / AdmissionController above), so no attrs are monitored — the
    # entry exists for the lock-ORDER analysis, which needs to know the
    # mutex's identity to order it against the collaborator locks taken
    # underneath it.
    "AsyncServeEngine": {"lock": "_work_mutex", "attrs": set()},
    # observability: counters/ledgers written from serve worker threads
    # and read by exporters. Mutators hold self._lock; the exempt attrs
    # are injected collaborators (clock) handled globally.
    "Counter": {"lock": "_lock", "attrs": None},
    "Gauge": {"lock": "_lock", "attrs": None},
    "Histogram": {"lock": "_lock", "attrs": None},
    "Registry": {"lock": "_lock", "attrs": None},
    "ProgramLedger": {"lock": "_lock", "attrs": None},
    "Tracer": {"lock": "_lock", "attrs": None},
    "DriftBoard": {"lock": "_lock", "attrs": None},
    "LifecycleLedger": {"lock": "_lock", "attrs": None},
    "BurnRateMonitor": {"lock": "_lock", "attrs": None},
    "FitQualityLedger": {"lock": "_lock", "attrs": None},
    "FlightRecorder": {"lock": "_lock", "attrs": None},
    "RequestJournal": {"lock": "_lock", "attrs": None},
    # durable tiers reached from under their in-memory caches' locks:
    # ordering matters (ExecutableCache._lock -> Persistent..._lock).
    "PersistentExecutableCache": {"lock": "_lock", "attrs": None},
    "PackStore": {"lock": "_lock", "attrs": None},
    # streaming append lanes: serve worker threads append while
    # register/recover touch the same lane table. The refitter lock
    # covers only the lane registry and counters; each lane's math and
    # delta IO runs under the lane's OWN lock so independent lanes
    # append concurrently. Ordering is one-way — StreamingLane._lock
    # -> StreamingRefitter._lock (counter bumps inside an append /
    # escalation) and StreamingLane._lock -> DeltaStore._lock (the
    # durable-before-visible publish); nothing takes a lane lock while
    # holding the refitter lock. The lane lock is reached through the
    # registry dict (an untyped alias the static lock-order pass can't
    # follow), so the runtime recorder in tests/test_incremental.py
    # pins these edges; attrs=set() because lane fields are mutated
    # through that same alias (the documented static-model limit) —
    # tests/lockcheck.py instruments them at runtime instead. The
    # refitter monitors its registry + counters explicitly: `deltas`
    # is an init-time reference to the internally-locked DeltaStore
    # (calls into it are its own lock's business, not the refitter's).
    "StreamingRefitter": {"lock": "_lock",
                          "attrs": {"lanes", "appends", "escalated",
                                    "replayed"}},
    "StreamingLane": {"lock": "_lock", "attrs": set()},
    "DeltaStore": {"lock": "_lock", "attrs": None},
}

# Attributes never treated as shared state even under attrs=None:
# injected collaborators and configuration, written once in __init__.
LOCKED_CLASS_EXEMPT_ATTRS = frozenset({"_lock", "clock", "_sleep"})

# Module-level caches mutated from multiple threads (the fleet
# pipeline and concurrent prewarm both reach the per-process
# precision-probe cache): mutations must hold the paired module lock.
LOCKED_GLOBALS = {
    "_PRECISION_AUTO_CACHE": "_PRECISION_AUTO_LOCK",
}

# -- precision flow (whole-program) -----------------------------------

# Function-name patterns whose RESULTS are f32 at the source: Pallas
# TPU kernels compute in f32/bf16 tiles, so anything a *_pallas kernel
# returns is f32-tainted until an explicit astype(float64). The
# precision-flow rule seeds its taint from these (plus astype/float32
# literals) and tracks the value interprocedurally into F64_CRITICAL
# sinks.
F32_SOURCE_PATTERNS = (r"_pallas$",)

# -- signature completeness (whole-program) ---------------------------

# Classes whose jitted program tables are keyed by a shape signature:
# the registered method must fingerprint every attribute the traced
# closures read (and every self attr passed as a runtime argument at a
# self._fns[...] dispatch). "exempt" lists host-only metadata attrs
# that cannot affect compiled-program shape.
SIGNATURE_CLASSES = {
    # preps/_free_map/static/template are structure-determining, not
    # shape-determining: PTABatch.structure_key fingerprints them
    # (component set, free-param names, static scalar config), and every
    # path that shares a _fns table across instances composes
    # structure_key into its cache key alongside shape_signature
    # (serve engine slot_key, pta persistent cache_key). Folding them
    # into shape_signature would double-count and force spurious
    # retraces on same-structure batches.
    "PTABatch": {"signature": "shape_signature",
                 "exempt": {"preps", "_free_map", "static", "template"}},
    "ShapePlan": {"signature": "signature", "exempt": set()},
}

# Path suffix of THIS module: the registry-drift staleness half only
# runs when the registry file itself is in the scan (linting one file
# must not claim the whole registry is stale).
REGISTRY_ANCHOR_SUFFIX = "analysis/config.py"

# -- retrace / sync hazards -------------------------------------------

# Callables that trace their function argument: a function passed to
# any of these is device code, where host-sync calls (float, .item,
# np.asarray, time.*) either crash at trace time or silently bake a
# traced value into the executable.
TRACING_WRAPPERS = frozenset({
    "jit", "vmap", "pmap", "pjit", "shard_map", "grad", "jacfwd",
    "jacrev", "hessian", "checkpoint", "remat", "value_and_grad",
    "scan", "while_loop", "fori_loop", "cond", "custom_jvp",
    "custom_vjp",
})

# Host-sync callables forbidden inside traced functions.
HOST_SYNC_CALLS = frozenset({
    "float", "int", "bool", "np.asarray", "np.array", "numpy.asarray",
    "numpy.array", "np.float64", "np.float32", "jax.device_get",
    "device_get", "time.time", "time.perf_counter", "time.monotonic",
})

# Methods whose call on a traced value forces a device sync.
HOST_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})

# Modules (path substrings) where building a PTABatch without
# pad_toas= breaks the zero-recompile serving contract: every flush of
# a slot must present identical shapes to the executable cache.
SERVE_PAD_MODULES = ("pint_tpu/serve/",)

# -- bucket shapes -----------------------------------------------------

# Call names that pick a legacy pow2 bucket width directly.
BUCKET_CALLS = frozenset({"pow2_bucket"})

# Modules (path suffixes) allowed to call them: the canonical
# implementation (serve/batcher.py) and the shape planner's sanctioned
# wrapper (parallel/shapeplan.py::pow2_width). Everything else must
# route bucket-shape decisions through the planner so the padded-FLOP
# cost model stays in one place.
BUCKET_ALLOWED_MODULES = ("parallel/shapeplan.py", "serve/batcher.py")

# -- fault injection ---------------------------------------------------

# Call names whose first string argument must be a registered fault
# point.
FAULT_CALLS = frozenset({
    "fire", "inject", "faultinject.fire", "faultinject.inject",
    "FaultPoint", "faultinject.FaultPoint",
})

# Path suffix of the registry module; its POINTS tuple is the ground
# truth, and the unfired check only runs when this file is in the scan
# (linting one file must not claim the whole registry is unused).
FAULT_REGISTRY_SUFFIX = "resilience/faultinject.py"

# Path markers identifying test files. Device-level fault points
# (the registry's DEVICE_POINTS tuple) must be ARMED — inject()/
# FaultPoint() — from at least one test: a device failure mode that
# no test can trigger is chaos coverage on paper only. The check runs
# only when test files are in the scan, so linting the package alone
# stays quiet.
TEST_PATH_MARKERS = ("/tests/", "/test_")

# -- bench hygiene -----------------------------------------------------

# Calls that dispatch device work asynchronously: timing them without
# a block_until_ready (or an equivalent host pull) times the dispatch,
# not the compute. "_fns" matches self._fns[key](...) program-table
# dispatch; jit-wrapped local names are collected per file.
ASYNC_DISPATCH_SUBSCRIPTS = frozenset({"_fns"})

# Calls that synchronize: their presence inside a timing window makes
# the measurement honest.
SYNC_CALLS = frozenset({
    "block_until_ready", "jax.block_until_ready", "device_get",
    "jax.device_get", "np.asarray", "np.array", "float",
})

TIMER_CALLS = frozenset({
    "time.perf_counter", "time.monotonic", "time.time",
    "perf_counter", "monotonic", "self.clock", "clock",
    # the obs clock (pint_tpu.obs.clock) opens timing windows too —
    # instrumented modules import it as obs_clock
    "obs_clock.now", "obs_clock.walltime",
})

# -- observability -----------------------------------------------------

# Modules (normalized "/"-prefixed path suffixes) instrumented with
# the obs tracing layer (pint_tpu.obs): raw wall-clock READS there
# must go through pint_tpu.obs.clock (obs_clock.now / Stopwatch) or a
# span, so every timing number on the instrumented surface shares one
# clock and shows up in exported timelines and flight dumps.
# time.sleep is a delay, not a measurement, and stays legal; timer
# REFERENCES used as injectable defaults (clock=time.monotonic) are
# not calls and are never flagged.
OBS_INSTRUMENTED_MODULES = (
    "/fitter.py", "/parallel/pta.py", "/parallel/fleetmesh.py",
    "/serve/engine.py", "/serve/excache.py", "/serve/batcher.py",
    "/serve/metrics.py", "/serve/frontdoor.py", "/serve/admission.py",
    "/resilience/retry.py", "/bench.py",
    "/benchmarks/profile_harness.py", "/scripts/pint_serve_bench.py",
    "/gw/residuals.py", "/gw/correlate.py", "/gw/hd.py",
    "/gw/__main__.py",
)

# Raw timer call names timing-untraced flags in instrumented modules.
OBS_RAW_TIMER_CALLS = frozenset({
    "time.time", "time.perf_counter", "time.monotonic",
    "time.perf_counter_ns", "time.monotonic_ns",
    "perf_counter", "monotonic",
})

# Path markers never checked: the obs package IS the clock, and tests
# drive fake clocks on purpose.
OBS_ALLOWED_PATH_MARKERS = ("/obs/", "/tests/", "/test_")

# -- durable-artifact write discipline --------------------------------

# Modules (normalized "/"-prefixed path suffixes) that own
# crash-surviving artifacts: checkpoint snapshots, the write-ahead
# request journal, the persisted executable cache, the packed-TOA
# columnar store, flight-recorder dumps. Truncating open() there must
# go through pint_tpu.durable's atomic writers — a crash
# mid-`open(path, "w")` tears the previous good artifact, the exact
# loss these modules exist to prevent.
# pint_tpu/durable.py itself is NOT listed: its temp-file write IS
# the atomic implementation.
DURABLE_ARTIFACT_MODULES = (
    "/checkpoint.py", "/obs/recorder.py", "/serve/journal.py",
    "/serve/excache.py", "/store/packstore.py", "/store/deltas.py",
)

# -- kernel dispatch ---------------------------------------------------

# Path markers identifying dual-path kernel modules: exception
# handlers around a Pallas dispatch there must make the jnp fallback
# visible (kernels.fallback.note_pallas_fallback) instead of
# swallowing it — a fleet silently pinned to the reference path loses
# its MXU throughput with no signal anywhere.
KERNEL_DISPATCH_MODULES = ("/kernels/", "/gw/")

# -- budget coverage ---------------------------------------------------

# Modules (normalized "/"-prefixed path suffixes) whose measured_*/
# serve_* dict-literal keys must be registered in the budget file
# (pint_tpu/obs/budgets.json) so the bench regression gate sees every
# headline number from the round it first appears.
BUDGET_META_MODULES = ("/bench.py",)

# -- fit-quality signal coverage --------------------------------------

# Modules (normalized "/"-prefixed path suffixes) on the fit path
# where numerical quality signals are computed: any function there
# that evaluates a signal (a relres_failed verdict, a chi2_whitened
# assignment) must also feed the numerics observatory
# (pint_tpu.obs.fitquality) or carry an explicit suppression — a
# computed-then-dropped quality signal is telemetry the fleet
# dashboards silently never see.
QUALITY_SIGNAL_MODULES = (
    "/fitter.py", "/parallel/pta.py", "/parallel/toa_shard.py",
    "/serve/engine.py",
)

# Identifier pattern marking a quality-signal computation.
QUALITY_SIGNAL_PATTERN = r"relres_failed|chi2_whitened"

# Identifier pattern marking that the enclosing function routes the
# signal into the observatory (the fitquality ledger / the per-batch
# quality summary).
QUALITY_RECORD_PATTERN = (
    r"quality|FITQ|obs_fitq|record_fit_batch|note_fallback")

# -- serve request-state coverage -------------------------------------

# Modules (normalized "/"-prefixed path suffixes) that own the serve
# request state machine: any function there that assigns a request's
# terminal outcome (``res.status`` / ``res.reason``) must also emit a
# lifecycle transition (pint_tpu.obs.reqlife) or a telemetry record in
# the same function — a status set on a path the ledger never hears
# about breaks the exactly-one-terminal-state invariant silently.
SERVE_STATE_MODULES = ("/serve/engine.py", "/serve/frontdoor.py")

# Identifier pattern marking that the enclosing function records the
# outcome (a lifecycle transition, a telemetry record/counter, or one
# of the reject/fail helpers that do both).
SERVE_STATE_RECORD_PATTERN = (
    r"_lc|reqlife|lifecycle|telemetry|_reject|_fail")

# Names that mark a value as a NaN-signalling convergence diagnostic:
# comparing one of these with ``>`` (False under NaN) silently
# swallows a diverged fit. ADVICE.md round 5 found three variants of
# exactly this bug; fitter.relres_failed is the sanctioned guard.
NAN_DIAG_PATTERN = r"(?:^|_)rel_?res(?:id)?(?:_|$)|relres"


@dataclass
class LintConfig:
    f64_critical: dict = field(default_factory=dict)
    locked_classes: dict = field(default_factory=dict)
    locked_class_exempt_attrs: frozenset = LOCKED_CLASS_EXEMPT_ATTRS
    locked_globals: dict = field(default_factory=dict)
    serve_pad_modules: tuple = ()
    bucket_allowed_modules: tuple = ()
    fault_points: tuple = None  # None -> parse from the registry file
    device_fault_points: tuple = None  # None -> parse DEVICE_POINTS
    fault_registry_suffix: str = FAULT_REGISTRY_SUFFIX
    test_path_markers: tuple = TEST_PATH_MARKERS
    nan_diag_pattern: str = NAN_DIAG_PATTERN
    obs_instrumented_modules: tuple = ()
    obs_raw_timer_calls: frozenset = OBS_RAW_TIMER_CALLS
    obs_allowed_path_markers: tuple = OBS_ALLOWED_PATH_MARKERS
    durable_artifact_modules: tuple = ()
    kernel_dispatch_modules: tuple = ()
    budget_meta_modules: tuple = ()
    budgeted_meta_keys: frozenset = None  # None -> rule is inert
    quality_signal_modules: tuple = ()
    quality_signal_pattern: str = QUALITY_SIGNAL_PATTERN
    quality_record_pattern: str = QUALITY_RECORD_PATTERN
    serve_state_modules: tuple = ()
    serve_state_record_pattern: str = SERVE_STATE_RECORD_PATTERN
    # whole-program analyses (empty/falsy -> the rule is inert, so
    # fixture configs built for per-file rules stay quiet)
    f32_source_patterns: tuple = ()
    signature_classes: dict = field(default_factory=dict)
    registry_anchor_suffix: str = ""
    registry_tree_roots: tuple = ()

    @classmethod
    def default(cls):
        # The budget-file key set loads lazily and tolerantly: lint
        # must keep working when the optional data file is missing
        # (the meta-key rule goes inert rather than erroring).
        try:
            from ..obs import baseline

            budgeted = frozenset(baseline.registered_keys())
        except Exception:
            budgeted = None
        return cls(f64_critical=dict(F64_CRITICAL),
                   locked_classes=dict(LOCKED_CLASSES),
                   locked_globals=dict(LOCKED_GLOBALS),
                   serve_pad_modules=SERVE_PAD_MODULES,
                   bucket_allowed_modules=BUCKET_ALLOWED_MODULES,
                   obs_instrumented_modules=OBS_INSTRUMENTED_MODULES,
                   durable_artifact_modules=DURABLE_ARTIFACT_MODULES,
                   kernel_dispatch_modules=KERNEL_DISPATCH_MODULES,
                   budget_meta_modules=BUDGET_META_MODULES,
                   budgeted_meta_keys=budgeted,
                   quality_signal_modules=QUALITY_SIGNAL_MODULES,
                   serve_state_modules=SERVE_STATE_MODULES,
                   f32_source_patterns=F32_SOURCE_PATTERNS,
                   signature_classes=dict(SIGNATURE_CLASSES),
                   registry_anchor_suffix=REGISTRY_ANCHOR_SUFFIX,
                   registry_tree_roots=(_REPO_ROOT,))
