"""Budget-coverage rule family.

- meta-key-unbudgeted: a ``measured_*`` / ``serve_*`` / ``chaos_*``
  / ``cold_start_*`` / ``gw_*`` / ``incremental_*`` meta key
  defined as a dict-literal key in a budget-governed module (bench.py)
  that the machine-readable budget file
  (``pint_tpu/obs/budgets.json``) does not know about — neither a
  budget bound, a regression-gated key, nor a tracked key. Every
  headline number bench emits must be registered so the regression
  gate sees it from the round it first appears; an unregistered key
  is a metric that can silently regress forever. Fix: add the key to
  ``tracked`` (or give it a budget/regression entry) in budgets.json.

  Only dict-literal KEYS are inspected — ``report["serve_x"]`` reads
  of some other dict are not meta-key definitions. The rule is inert
  when the budget file cannot be loaded (``budgeted_meta_keys`` is
  None): lint must not fail because an optional data file is absent.
"""

from __future__ import annotations

import ast
import re

from .core import Rule, register

_META_KEY = re.compile(
    r"^(measured_|serve_|chaos_|cold_start_|gw_|incremental_)")


@register
class MetaKeyUnbudgetedRule(Rule):
    id = "meta-key-unbudgeted"
    family = "budget"
    rationale = ("a measured_*/serve_*/chaos_*/cold_start_*/gw_*/"
                 "incremental_* meta key "
                 "absent from pint_tpu/obs/budgets.json is invisible "
                 "to the bench regression gate and can regress "
                 "silently")

    def _applies(self, ctx):
        rel = "/" + ctx.rel.replace("\\", "/")
        suffixes = getattr(ctx.config, "budget_meta_modules", ())
        return any(rel.endswith(s) for s in suffixes)

    def check_file(self, ctx):
        if not self._applies(ctx):
            return
        budgeted = getattr(ctx.config, "budgeted_meta_keys", None)
        if budgeted is None:  # budget file unavailable -> inert
            return
        seen = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Dict):
                continue
            for key in node.keys:
                if (not isinstance(key, ast.Constant)
                        or not isinstance(key.value, str)):
                    continue
                name = key.value
                if (not _META_KEY.match(name) or name in budgeted
                        or name in seen):
                    continue
                seen.add(name)
                ctx.report(
                    self.id, key,
                    f"meta key {name!r} has no entry in "
                    "pint_tpu/obs/budgets.json: register it under "
                    "tracked (or give it a budget/regression bound) "
                    "so the bench regression gate can watch it")
