"""pintlint: codebase-aware static analysis for pint_tpu.

The repo's correctness conventions — NaN-aware mixed-precision
guards, the ExecutableCache zero-retrace contract, lock discipline on
shared serving state, fault-registry coverage, synchronized timing
regions — are enforced here as AST lint rules instead of reviewer
memory. ``python -m pint_tpu.analysis pint_tpu/`` (or
``pint_tpu/scripts/pintlint.py``) exits nonzero on any unsuppressed
finding; tests/test_pintlint.py gates the tree in CI.

Rule catalogue with bad/good examples: docs/lint_rules.md.
"""

from .config import LintConfig
from .core import (Finding, Rule, RULES, all_rules, counts_by_rule,
                   register, run, run_project, unsuppressed)
# importing the rule modules populates the registry
from . import (rules_bench, rules_bucket, rules_budget,  # noqa: F401
               rules_durable, rules_faults, rules_flow, rules_kernels,
               rules_locks, rules_lockorder, rules_obs,
               rules_precision, rules_quality, rules_registry,
               rules_retrace, rules_serve, rules_signature)
from .report import json_report, text_report

__all__ = [
    "Finding", "LintConfig", "Rule", "RULES", "all_rules",
    "counts_by_rule", "json_report", "register", "run", "run_project",
    "text_report", "unsuppressed",
]


def run_lint(paths, config=None):
    """Convenience wrapper: (findings, unsuppressed_findings)."""
    findings = run(paths, config=config)
    return findings, unsuppressed(findings)
