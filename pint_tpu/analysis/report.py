"""pintlint reporters: text for humans/CI logs, JSON for bench
telemetry and tooling."""

from __future__ import annotations

import json

from .core import counts_by_rule, unsuppressed


def text_report(findings, show_suppressed=False):
    lines = []
    shown = findings if show_suppressed else unsuppressed(findings)
    for f in shown:
        lines.append(str(f))
    live = unsuppressed(findings)
    n_sup = len(findings) - len(live)
    summary = (f"pintlint: {len(live)} finding(s), "
               f"{n_sup} suppressed")
    counts = counts_by_rule(findings)
    if counts:
        summary += " [" + ", ".join(f"{k}={v}"
                                    for k, v in counts.items()) + "]"
    lines.append(summary)
    return "\n".join(lines)


def json_report(findings):
    live = unsuppressed(findings)
    return json.dumps({
        "findings": [f.as_dict() for f in findings],
        "unsuppressed": len(live),
        "suppressed": len(findings) - len(live),
        "counts_by_rule": counts_by_rule(findings),
    }, indent=2, sort_keys=True)
