"""Fault-injection coverage rule family.

- fault-point-unknown: a fire()/inject()/FaultPoint() site naming a
  point that is not in the registry (the site would silently never
  fire — chaos coverage that tests nothing).
- fault-point-unfired: a registered point with no fire() site in the
  scanned tree (a failure mode the registry promises deterministic
  coverage for, with no code path that can exercise it).
"""

from __future__ import annotations

import ast

from .config import FAULT_CALLS
from .core import Rule, call_name, register


def _parse_points(tree, name="POINTS"):
    """A string-tuple assignment of a registry module, or None."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        vals = [el.value for el in node.value.elts
                                if isinstance(el, ast.Constant)]
                        return tuple(vals), node.lineno
    return None


def _find_registry(project, name="POINTS"):
    """(parsed tuple+lineno, FileContext) of the registry module in the
    scanned tree, or (None, None)."""
    suffix = project.config.fault_registry_suffix
    for ctx in project.files:
        path = ctx.path.replace("\\", "/")
        if path.endswith(suffix):
            parsed = _parse_points(ctx.tree, name)
            if parsed:
                return parsed, ctx
            break
    return None, None


def _point_sites(tree):
    """(name, node) for every call that names a fault point as its
    first string-literal argument."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name not in FAULT_CALLS:
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            yield name, node.args[0].value, node


@register
class FaultPointCoverageRule(Rule):
    """The faultinject registry's value is that every failure mode has
    a NAMED, armable point. A typo'd name at a fire() site is a dead
    injection point (FaultPoint() raises, but fire('typo') just never
    fires); a registered point nobody fires is a failure mode the
    chaos suite believes is covered but cannot actually trigger. Both
    directions are checked against the POINTS tuple parsed from the
    registry module in the scanned tree."""

    id = "fault-point-unknown"
    family = "faults"
    rationale = ("a fire()/inject() site naming an unregistered point "
                 "never fires; the chaos coverage is imaginary")

    def finish(self, project):
        cfg = project.config
        registry, registry_ctx = _find_registry(project)
        if cfg.fault_points is not None:
            points = set(cfg.fault_points)
        elif registry is not None:
            points = set(registry[0])
        else:
            return  # no registry in scope: nothing to check against
        fired = set()
        for ctx in project.files:
            for call, point, node in _point_sites(ctx.tree):
                # only real fire() sites count as coverage; inject()/
                # FaultPoint() arm a point but exercise nothing
                if call.rsplit(".", 1)[-1] == "fire":
                    fired.add(point)
                if point not in points:
                    ctx.report(
                        self.id, node,
                        f"{call}({point!r}): unregistered fault point "
                        f"(known: {', '.join(sorted(points))})")
        if registry_ctx is not None:
            unfired = points - fired
            for point in sorted(unfired):
                registry_ctx.report(
                    "fault-point-unfired", registry[1],
                    f"registered fault point '{point}' has no fire() "
                    f"site in the scanned tree: the failure mode it "
                    f"names cannot be exercised")


@register
class FaultPointUnfiredRule(Rule):
    """Registry side of the coverage check; findings are emitted by
    FaultPointCoverageRule.finish (one scan of the tree serves both
    directions), registered separately so the id can be listed and
    suppressed on its own."""

    id = "fault-point-unfired"
    family = "faults"
    rationale = ("a registered point with no fire() site is promised "
                 "chaos coverage that cannot be triggered")


@register
class FaultPointUntestedRule(Rule):
    """Device-level fault points (the registry's DEVICE_POINTS tuple:
    device_loss, collective_timeout, straggler_delay) model failures
    of a whole chip, not of one request — a fire() site alone proves
    the code CAN inject them, not that the recovery ladder (lane
    quarantine, work stealing, checkpoint resume) is ever driven. Each
    device point must be ARMED — inject()/FaultPoint() with the point
    as its first argument — from at least one test file. Runs only
    when both the registry and at least one test file are in the scan,
    so a package-only lint stays quiet."""

    id = "fault-point-untested"
    family = "faults"
    rationale = ("a device-level fault point no test arms means the "
                 "quarantine/steal/resume path it exists to exercise "
                 "is never driven in CI")

    def finish(self, project):
        cfg = project.config
        if cfg.device_fault_points is not None:
            device_points = tuple(cfg.device_fault_points)
            registry, registry_ctx = _find_registry(project)
        else:
            registry, registry_ctx = _find_registry(
                project, "DEVICE_POINTS")
            if registry is None:
                return
            device_points = registry[0]
        if registry_ctx is None:
            return
        markers = tuple(cfg.test_path_markers)

        def _is_test(path):
            # dir markers ("/tests/") match anywhere in the path;
            # file markers ("/test_") match the basename only — a
            # "test_*" substring in a parent directory (pytest tmp
            # dirs are named after the test) must not count
            p = "/" + path.replace("\\", "/")
            base = p.rsplit("/", 1)[-1]
            return any(m in p if m.endswith("/")
                       else base.startswith(m.lstrip("/"))
                       for m in markers)

        test_ctxs = [ctx for ctx in project.files
                     if _is_test(ctx.path)]
        if not test_ctxs:
            return  # package-only scan: nothing to prove
        armed = set()
        for ctx in test_ctxs:
            for call, point, _node in _point_sites(ctx.tree):
                # arming is inject()/FaultPoint(); a bare fire() in a
                # test exercises nothing unless a point is armed, and
                # fire() in test helpers is rare enough to ignore
                if call.rsplit(".", 1)[-1] in ("inject", "FaultPoint"):
                    armed.add(point)
        line = registry[1] if registry is not None else 1
        for point in sorted(set(device_points) - armed):
            registry_ctx.report(
                self.id, line,
                f"device-level fault point '{point}' is never armed "
                f"(inject()/FaultPoint()) by any test in the scanned "
                f"tree: its quarantine/steal/resume recovery path is "
                f"untested")
