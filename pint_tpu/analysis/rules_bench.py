"""Bench-hygiene rule family.

- timing-no-block: a perf-counter window that dispatches device work
  asynchronously (a ``self._fns[key](...)`` program-table call or a
  jit-wrapped local) without a synchronizing call before the elapsed
  time is computed. JAX dispatch is async: without block_until_ready
  (or a host pull) the window times the enqueue, not the compute —
  the classic way a bench reports a 100x phantom speedup.
"""

from __future__ import annotations

import ast

from .config import ASYNC_DISPATCH_SUBSCRIPTS, SYNC_CALLS, TIMER_CALLS
from .core import Rule, call_name, register
from .rules_retrace import TracedIndex


def _is_timer_call(node):
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    return name in TIMER_CALLS


def _subscript_root_attr(node):
    """'_fns' for a ``self._fns[key](...)`` style callee."""
    if isinstance(node, ast.Subscript) and \
            isinstance(node.value, ast.Attribute):
        return node.value.attr
    return None


@register
class TimingNoBlockRule(Rule):
    id = "timing-no-block"
    family = "bench"
    rationale = ("async dispatch inside a perf-counter window without "
                 "block_until_ready times the enqueue, not the work")

    def check_file(self, ctx):
        traced = TracedIndex(ctx.tree)
        for func in ast.walk(ctx.tree):
            if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_func(ctx, func, traced)

    def _check_func(self, ctx, func, traced):
        body = list(ast.walk(func))
        # timer starts: t = time.perf_counter() (several windows may
        # reuse one variable; pair each elapsed use with the closest
        # preceding start of the same name)
        starts = []
        for node in body:
            if isinstance(node, ast.Assign) and \
                    _is_timer_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        starts.append((t.id, node.lineno))
        if not starts:
            return
        ends = []
        for node in body:
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, ast.Sub) and \
                    isinstance(node.right, ast.Name) and \
                    node.right.id in {n for n, _ in starts}:
                ends.append((node.right.id, node.lineno))
        windows = []
        for name, end_line in sorted(ends, key=lambda p: p[1]):
            cands = [ln for n, ln in starts
                     if n == name and ln < end_line]
            if cands:
                windows.append((name, max(cands), end_line))
        for name, start_line, end_line in sorted(set(windows)):
            window = [n for n in body
                      if getattr(n, "lineno", None) is not None
                      and start_line <= n.lineno <= end_line]
            dispatch = None
            synced = False
            for node in window:
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                tail = cname.rsplit(".", 1)[-1] if cname else None
                if cname in SYNC_CALLS or tail in SYNC_CALLS:
                    synced = True
                if _subscript_root_attr(node.func) in \
                        ASYNC_DISPATCH_SUBSCRIPTS:
                    dispatch = dispatch or node
                elif traced.is_traced_name(cname, node):
                    dispatch = dispatch or node
            if dispatch is not None and not synced:
                ctx.report(
                    self.id, dispatch,
                    f"device dispatch inside the '{name}' timing "
                    f"window (lines {start_line}-{end_line}) with no "
                    f"block_until_ready/host pull before the elapsed "
                    f"computation: this times the async enqueue, not "
                    f"the compute")
