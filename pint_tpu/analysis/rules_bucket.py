"""Bucket-shape discipline rule family.

- bucket-hardcoded: direct pow2_bucket calls outside the shape
  planner / batcher keep bucket-shape decisions out of the planner's
  padded-FLOP cost model.
"""

from __future__ import annotations

import ast

from .config import BUCKET_CALLS
from .core import Rule, call_name, register


@register
class BucketHardcodedRule(Rule):
    """Every bucket-shape decision must route through
    parallel/shapeplan.py (plan_shapes / pow2_width / ladder_width)
    or the canonical serve/batcher.py implementation. A direct
    pow2_bucket call anywhere else hardcodes the legacy ladder,
    bypassing the planner's cost model and splitting the shape
    convention across modules — exactly the drift that made the pow2
    ladder's x1.37 padding invisible until the 670k bench measured
    it."""

    id = "bucket-hardcoded"
    family = "bucket"
    rationale = ("direct pow2_bucket calls outside shapeplan/batcher "
                 "hardcode the legacy ladder and bypass the shape "
                 "planner's cost model")

    def check_file(self, ctx):
        path = ctx.path.replace("\\", "/")
        if any(path.endswith(mod)
               for mod in ctx.config.bucket_allowed_modules):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1] if name else None
            if tail in BUCKET_CALLS:
                ctx.report(
                    self.id, node,
                    f"direct {tail}() call outside the shape planner "
                    "and batcher: route bucket widths through "
                    "parallel/shapeplan.py (plan_shapes / pow2_width "
                    "/ ladder_width) so shape decisions stay in the "
                    "cost model")
