"""Retrace / sync-hazard rule family.

- host-sync-in-jit: host-side conversions inside traced functions.
- static-unhashable: unhashable literals passed as jit static args.
- serve-unpadded-batch: PTABatch built in the serve path without
  pad_toas= (shape drift defeats the ExecutableCache).
"""

from __future__ import annotations

import ast

from .config import HOST_SYNC_CALLS, HOST_SYNC_METHODS, TRACING_WRAPPERS
from .core import Rule, call_name, dotted_name, register


def _tail(name):
    return name.rsplit(".", 1)[-1] if name else None


class TracedIndex:
    """Scope-aware index of locally-defined functions that end up
    traced: defined under a tracing decorator, or passed (possibly
    nested, e.g. ``jax.jit(jax.vmap(fit_one))``) to a tracing wrapper.

    Resolution is lexical, so an unrelated host-side closure that
    happens to share a name with a jitted function elsewhere in the
    file (fitter.py has three distinct ``chi2_of``) is not flagged."""

    _SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

    def __init__(self, tree):
        self._scope_of = {id(tree): None}
        self._defs = {}  # (id(scope), name) -> def node
        self._traced = set()  # id(def node)
        self._traced_bindings = set()  # (id(scope), name) of g = jit(f)
        self._index(tree, tree)
        self._mark(tree)

    def _index(self, node, scope):
        for child in ast.iter_child_nodes(node):
            self._scope_of[id(child)] = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs[(id(scope), child.name)] = child
                self._index(child, child)
            elif isinstance(child, ast.Lambda):
                self._index(child, child)
            else:
                self._index(child, scope)

    def _resolve(self, scope, name):
        while scope is not None:
            found = self._defs.get((id(scope), name))
            if found is not None:
                return found
            scope = self._scope_of.get(id(scope))
        return None

    def _harvest(self, node, scope):
        """Mark Name args of a tracing-wrapper call, recursing through
        nested wrapper/partial calls."""
        if isinstance(node, ast.Name):
            found = self._resolve(scope, node.id)
            if found is not None:
                self._traced.add(id(found))
        elif isinstance(node, ast.Call):
            if _tail(call_name(node)) in TRACING_WRAPPERS | {"partial"}:
                for arg in node.args:
                    self._harvest(arg, scope)

    def _mark(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                # g = jax.jit(step): calls through g dispatch device
                # work even though g itself is not a def
                if isinstance(node.value, ast.Call) and \
                        _tail(call_name(node.value)) in TRACING_WRAPPERS:
                    scope = self._scope_of.get(id(node))
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self._traced_bindings.add((id(scope), t.id))
            if isinstance(node, ast.Call):
                if _tail(call_name(node)) in TRACING_WRAPPERS:
                    scope = self._scope_of.get(id(node))
                    for arg in node.args:
                        self._harvest(arg, scope)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    name = (call_name(dec) if isinstance(dec, ast.Call)
                            else dotted_name(dec))
                    if _tail(name) in TRACING_WRAPPERS:
                        self._traced.add(id(node))
                    elif _tail(name) == "partial" and \
                            isinstance(dec, ast.Call):
                        heads = (dotted_name(a) for a in dec.args)
                        if any(_tail(h) in TRACING_WRAPPERS
                               for h in heads if h):
                            self._traced.add(id(node))

    def is_traced_def(self, func):
        return id(func) in self._traced

    def is_traced_name(self, name, at_node):
        """True when ``name`` called at ``at_node`` lexically resolves
        to a traced local function or a jit-result binding."""
        if not name or "." in name:
            return False
        scope = self._scope_of.get(id(at_node))
        probe = scope
        while probe is not None:
            if (id(probe), name) in self._traced_bindings:
                return True
            probe = self._scope_of.get(id(probe))
        found = self._resolve(scope, name)
        return found is not None and id(found) in self._traced

    def __bool__(self):
        return bool(self._traced) or bool(self._traced_bindings)


@register
class HostSyncInJitRule(Rule):
    """A ``float()`` / ``.item()`` / ``np.asarray`` / ``time.*`` call
    inside a jit-traced function either raises a concretization error
    at trace time or — worse — executes once at trace time and bakes a
    stale constant into every later run of the executable. Host
    conversions belong in the finalize half of the dispatch/finalize
    split (see PTABatch._pull)."""

    id = "host-sync-in-jit"
    family = "retrace"
    rationale = ("host conversions inside traced functions either "
                 "crash at trace time or freeze trace-time values "
                 "into the executable")

    def check_file(self, ctx):
        traced = TracedIndex(ctx.tree)
        if not traced:
            return
        seen = set()
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not traced.is_traced_def(func):
                continue
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                bad = None
                if name in HOST_SYNC_CALLS:
                    bad = name
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in HOST_SYNC_METHODS):
                    bad = f".{node.func.attr}()"
                if bad is None:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                ctx.report(
                    self.id, node,
                    f"host-sync call {bad} inside traced function "
                    f"'{func.name}'; move it to the finalize half of "
                    f"the dispatch/finalize split")


_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


@register
class StaticUnhashableRule(Rule):
    """jit static arguments key the trace cache by value, so they must
    be hashable — a list/dict/set static arg raises at dispatch, and a
    mutable one that WAS converted to tuple per call retraces whenever
    its identity-derived hash changes. Flags call sites passing
    unhashable literals to parameters declared static via
    static_argnames."""

    id = "static-unhashable"
    family = "retrace"
    rationale = ("unhashable values passed as jit static args fail at "
                 "dispatch or silently retrace per call")

    def check_file(self, ctx):
        static_names = {}  # wrapped function name -> set of static kwargs
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1] if name else None
            if tail not in ("jit", "pjit"):
                continue
            statics = set()
            for kw in node.keywords:
                if kw.arg == "static_argnames":
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and \
                                isinstance(sub.value, str):
                            statics.add(sub.value)
            if not statics:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    static_names.setdefault(arg.id, set()).update(statics)
        if not static_names:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node)
            if callee not in static_names:
                continue
            for kw in node.keywords:
                if kw.arg in static_names[callee] and \
                        isinstance(kw.value, _UNHASHABLE):
                    ctx.report(
                        self.id, kw.value,
                        f"unhashable literal passed to static arg "
                        f"'{kw.arg}' of jitted '{callee}'; static args "
                        f"key the trace cache and must be hashable "
                        f"(use a tuple)")


@register
class ServeUnpaddedBatchRule(Rule):
    """The serve path's zero-recompile contract requires every flush
    of a slot to present identical shapes: PTABatch built without
    ``pad_toas=`` pads to the batch's own max TOA count, so each new
    TOA-count mixture compiles a fresh executable and the
    ExecutableCache can never hit. Deliberate exceptions (the oversize
    spill path) must carry a justified suppression."""

    id = "serve-unpadded-batch"
    family = "retrace"
    rationale = ("an unpadded PTABatch in the serve path drifts the "
                 "shape signature and defeats the ExecutableCache")

    def check_file(self, ctx):
        path = ctx.path.replace("\\", "/")
        if not any(mod in path for mod in ctx.config.serve_pad_modules):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1] if name else None
            if tail != "PTABatch":
                continue
            if not any(kw.arg == "pad_toas" for kw in node.keywords):
                ctx.report(
                    self.id, node,
                    "PTABatch built in the serve path without "
                    "pad_toas=: shapes drift per flush and the "
                    "ExecutableCache zero-recompile contract breaks")
