"""precision-flow: interprocedural f32 taint into f64-critical sinks.

The per-file ``f32-in-f64`` rule flags float32 introduced LEXICALLY
inside a registered f64-critical function. It cannot see the chain the
ERRORBUDGET tiers actually worry about: a Pallas kernel or an
``astype(float32)`` in one module producing a value that flows through
helpers and call boundaries into the whitening/normal-equation chain
three files away. This rule closes that gap with a summary-based taint
analysis over the ProjectIndex:

- **sources**: ``.astype(float32)`` / ``jnp.float32(...)`` / ``dtype=
  ...float32`` constructors, and calls whose name matches the
  configured f32-source patterns (``*_pallas`` kernels — Pallas on TPU
  computes in f32/bf16 tiles);
- **propagation**: assignments, arithmetic, returns, and calls — each
  function gets a summary (tainted return? which params reach a
  critical sink?) iterated to a fixpoint over the call graph;
- **sanitizers**: ``.astype(float64)`` / ``np.float64(...)`` kill the
  taint (the value is f64 again — the 9 lost digits are gone, but that
  is f32-in-f64's lexical problem at the cast site, not a flow);
- **sinks**: calls into functions registered in ``F64_CRITICAL``.

Findings name the full source→sink chain, one per (function, source
site). Taint introduced lexically inside the critical function itself
is NOT re-reported — that is exactly f32-in-f64's finding, and the two
rules partition the space: lexical introduction vs cross-function
flow.
"""

from __future__ import annotations

import ast
import re

from .core import Rule, call_name, register

_CLEAN = (frozenset(), None)

# numpy/jnp constructors that accept dtype= and forward their input
_DTYPE_CTORS = frozenset({
    "asarray", "array", "zeros", "ones", "full", "empty", "arange",
    "linspace", "zeros_like", "ones_like", "full_like", "empty_like",
})


def _merge(a, b):
    return (a[0] | b[0], a[1] if a[1] is not None else b[1])


def _dtype_marker(expr):
    """"f32" / "f64" / None for a dtype-valued expression."""
    for sub in ast.walk(expr):
        text = None
        if isinstance(sub, ast.Attribute):
            text = sub.attr
        elif isinstance(sub, ast.Name):
            text = sub.id
        elif isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                          str):
            text = sub.value
        if text is None:
            continue
        if "float32" in text or text == "f32":
            return "f32"
        if "float64" in text or text == "f64":
            return "f64"
    return None


class _FuncState:
    __slots__ = ("func", "rel", "ret", "param_sinks", "calls",
                 "report", "reported", "ctx")

    def __init__(self, func, calls, report):
        self.func = func
        self.ctx = func.ctx
        self.rel = func.ctx.rel
        self.ret = _CLEAN
        self.param_sinks = {}
        self.calls = calls
        self.report = report
        self.reported = set()


@register
class PrecisionFlowRule(Rule):
    """An f32 value produced anywhere — a Pallas kernel, a cast in a
    prep helper — that reaches a registered f64-critical sink has
    already destroyed ~9 of the ~16 decimal digits the TOA residual
    contract requires, no matter how many f64 casts happen afterwards.
    The flow must be broken at the source or explicitly sanctioned."""

    id = "precision-flow"
    family = "precision"
    rationale = ("f32 value flowing across functions into an "
                 "f64-critical sink loses the precision the residual "
                 "contract requires; the full source->sink chain is "
                 "reported")
    whole_program = True

    def check_project(self, project, index):
        config = project.config
        if not config.f64_critical:
            return
        self.index = index
        self.src_re = re.compile("|".join(
            config.f32_source_patterns)) if config.f32_source_patterns \
            else None
        self.critical = self._critical_funcs(index, config)
        funcs = [index.functions[q] for q in sorted(index.functions)]
        call_maps = {
            f.qname: {id(c): g for c, g in index.calls_of(f)}
            for f in funcs
        }
        self.summaries = {}
        for _ in range(10):
            changed = False
            for f in funcs:
                st = _FuncState(f, call_maps[f.qname], report=False)
                self._analyze(st)
                new = (st.ret, tuple(sorted(st.param_sinks.items())))
                if self.summaries.get(f.qname) != new:
                    self.summaries[f.qname] = new
                    changed = True
            if not changed:
                break
        for f in funcs:
            st = _FuncState(f, call_maps[f.qname], report=True)
            self._analyze(st)

    @staticmethod
    def _critical_funcs(index, config):
        out = set()
        for qname, func in index.functions.items():
            for suffix, names in config.f64_critical.items():
                if not (func.ctx.path.endswith(suffix)
                        or func.ctx.rel.endswith(suffix)):
                    continue
                if "*" in names or func.name in names:
                    out.add(qname)
                break
        return out

    # -- driver ---------------------------------------------------------

    def _analyze(self, st):
        env = {}
        args = st.func.node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            env[a.arg] = (frozenset({a.arg}), None)
        if st.func.cls is not None:
            env.pop("self", None)
            env["self"] = _CLEAN
        self._block(st.func.node.body, env, st)
        st.ret = st.ret

    def _block(self, stmts, env, st):
        for s in stmts:
            self._stmt(s, env, st)

    def _stmt(self, s, env, st):
        if isinstance(s, ast.Assign):
            av = self._eval(s.value, env, st)
            for tgt in s.targets:
                self._bind(tgt, av, env)
        elif isinstance(s, ast.AnnAssign) and s.value is not None:
            self._bind(s.target, self._eval(s.value, env, st), env)
        elif isinstance(s, ast.AugAssign):
            av = self._eval(s.value, env, st)
            if isinstance(s.target, ast.Name):
                env[s.target.id] = _merge(
                    env.get(s.target.id, _CLEAN), av)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                st.ret = _merge(st.ret, self._eval(s.value, env, st))
        elif isinstance(s, ast.Expr):
            self._eval(s.value, env, st)
        elif isinstance(s, ast.If):
            self._eval(s.test, env, st)
            left, right = dict(env), dict(env)
            self._block(s.body, left, st)
            self._block(s.orelse, right, st)
            for k in set(left) | set(right):
                env[k] = _merge(left.get(k, _CLEAN),
                                right.get(k, _CLEAN))
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            av = self._eval(s.iter, env, st)
            self._bind(s.target, av, env)
            # twice: pick up loop-carried taint
            self._block(s.body, env, st)
            self._block(s.body, env, st)
            self._block(s.orelse, env, st)
        elif isinstance(s, ast.While):
            self._eval(s.test, env, st)
            self._block(s.body, env, st)
            self._block(s.body, env, st)
            self._block(s.orelse, env, st)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                av = self._eval(item.context_expr, env, st)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, av, env)
            self._block(s.body, env, st)
        elif isinstance(s, ast.Try):
            self._block(s.body, env, st)
            for h in s.handlers:
                self._block(h.body, env, st)
            self._block(s.orelse, env, st)
            self._block(s.finalbody, env, st)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            pass                   # nested defs have their own summary
        elif isinstance(s, (ast.Assert, ast.Raise)):
            pass
        # Pass/Break/Continue/Import/Global/Delete: nothing flows

    def _bind(self, tgt, av, env):
        if isinstance(tgt, ast.Name):
            env[tgt.id] = av
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._bind(elt, av, env)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, av, env)
        # attribute/subscript targets: not tracked

    # -- expressions ----------------------------------------------------

    def _eval(self, expr, env, st):
        if isinstance(expr, ast.Name):
            return env.get(expr.id, _CLEAN)
        if isinstance(expr, ast.Constant):
            return _CLEAN
        if isinstance(expr, ast.Call):
            return self._eval_call(expr, env, st)
        if isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return _CLEAN      # object state: not tracked
            return self._eval(expr.value, env, st)
        if isinstance(expr, ast.BinOp):
            return _merge(self._eval(expr.left, env, st),
                          self._eval(expr.right, env, st))
        if isinstance(expr, ast.BoolOp):
            out = _CLEAN
            for v in expr.values:
                out = _merge(out, self._eval(v, env, st))
            return out
        if isinstance(expr, ast.UnaryOp):
            return self._eval(expr.operand, env, st)
        if isinstance(expr, ast.Compare):
            for c in [expr.left] + expr.comparators:
                self._eval(c, env, st)
            return _CLEAN
        if isinstance(expr, ast.Subscript):
            return self._eval(expr.value, env, st)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = _CLEAN
            for elt in expr.elts:
                out = _merge(out, self._eval(elt, env, st))
            return out
        if isinstance(expr, ast.Dict):
            out = _CLEAN
            for v in expr.values:
                if v is not None:
                    out = _merge(out, self._eval(v, env, st))
            return out
        if isinstance(expr, ast.IfExp):
            self._eval(expr.test, env, st)
            return _merge(self._eval(expr.body, env, st),
                          self._eval(expr.orelse, env, st))
        if isinstance(expr, ast.Starred):
            return self._eval(expr.value, env, st)
        if isinstance(expr, ast.Await):
            return self._eval(expr.value, env, st)
        return _CLEAN

    def _taint(self, st, node, desc, base=_CLEAN):
        chain = (base[1] or ()) + ((st.rel, node.lineno, desc),)
        return (frozenset(), chain)

    def _eval_call(self, call, env, st):
        args_av = [self._eval(a, env, st) for a in call.args]
        kw_av = {kw.arg: self._eval(kw.value, env, st)
                 for kw in call.keywords if kw.arg is not None}
        name = call_name(call) or ""
        tail = name.rsplit(".", 1)[-1]
        recv = _CLEAN
        if isinstance(call.func, ast.Attribute):
            recv = self._eval(call.func.value, env, st)
            if not tail:
                # method call on a non-name receiver — e.g.
                # (M32.T @ M32).astype(f64) — call_name cannot build a
                # dotted name, but the method itself still decides
                # cast/sanitize semantics
                tail = call.func.attr

        # dtype casts: sanitize or taint
        if tail == "astype" and call.args:
            dt = _dtype_marker(call.args[0])
            if dt == "f64":
                return _CLEAN
            if dt == "f32":
                return self._taint(st, call, "astype(float32)", recv)
            return recv
        if tail in _DTYPE_CTORS:
            dt = None
            if "dtype" in kw_av or any(kw.arg == "dtype"
                                       for kw in call.keywords):
                for kw in call.keywords:
                    if kw.arg == "dtype":
                        dt = _dtype_marker(kw.value)
            if dt == "f64":
                return _CLEAN
            merged = recv
            for av in args_av:
                merged = _merge(merged, av)
            if dt == "f32":
                return self._taint(st, call, f"{tail}(dtype=float32)",
                                   merged)
            return merged
        if tail in ("float32", "bfloat16"):
            merged = _CLEAN
            for av in args_av:
                merged = _merge(merged, av)
            return self._taint(st, call, f"{name or tail}()", merged)
        if tail in ("float64", "double"):
            return _CLEAN

        callee = st.calls.get(id(call))
        if callee is not None:
            return self._resolved_call(call, callee, args_av, kw_av,
                                       st)
        # unresolved: configured f32 sources taint; everything else
        # passes its inputs through (jnp.dot and friends)
        merged = recv
        for av in args_av:
            merged = _merge(merged, av)
        for av in kw_av.values():
            merged = _merge(merged, av)
        if self.src_re is not None and tail \
                and self.src_re.search(tail):
            return self._taint(st, call, f"f32 source {name or tail}()",
                               merged)
        return merged

    def _resolved_call(self, call, callee, args_av, kw_av, st):
        gargs = callee.node.args
        gparams = [a.arg for a in (list(gargs.posonlyargs)
                                   + list(gargs.args))]
        offset = 1 if (callee.cls is not None
                       and isinstance(call.func, ast.Attribute)
                       and gparams[:1] == ["self"]) else 0
        pairs = []
        for i, av in enumerate(args_av):
            idx = i + offset
            if idx < len(gparams):
                pairs.append((gparams[idx], av))
        for kname, av in kw_av.items():
            if kname in gparams:
                pairs.append((kname, av))

        crit = callee.qname in self.critical
        summ = self.summaries.get(callee.qname)
        sinks = dict(summ[1]) if summ is not None else {}

        for pname, av in pairs:
            if av[1] is not None:      # tainted argument
                if crit:
                    self._report(st, call, av[1] + (
                        (st.rel, call.lineno,
                         f"passed to f64-critical {callee.name}()"),))
                elif pname in sinks:
                    self._report(st, call, av[1] + (
                        (st.rel, call.lineno,
                         f"passed to {callee.name}()"),) + sinks[pname])
            if av[0]:                  # caller params flow onward
                if crit:
                    for p in av[0]:
                        st.param_sinks.setdefault(p, (
                            (st.rel, call.lineno,
                             f"passed to f64-critical "
                             f"{callee.name}()"),))
                elif pname in sinks:
                    for p in av[0]:
                        st.param_sinks.setdefault(p, (
                            (st.rel, call.lineno,
                             f"passed to {callee.name}()"),)
                            + sinks[pname])

        result = _CLEAN
        if summ is not None:
            rparams, rchain = summ[0]
            if rchain is not None:
                result = (frozenset(), rchain + (
                    (st.rel, call.lineno,
                     f"returned by {callee.name}()"),))
            for pname, av in pairs:
                if pname in rparams:
                    if av[1] is not None:
                        result = (result[0] | av[0],
                                  result[1] if result[1] is not None
                                  else av[1] + ((st.rel, call.lineno,
                                                 f"through "
                                                 f"{callee.name}()"),))
                    else:
                        result = (result[0] | av[0], result[1])
        if (self.src_re is not None
                and self.src_re.search(callee.name)):
            result = self._taint(st, call,
                                 f"f32 source {callee.name}()", result)
        # a tainted value materializing inside a critical function is
        # itself a contamination, even with no further call
        if (result[1] is not None and st.func.qname in self.critical
                and not self._chain_starts_here(st, result[1])):
            self._report(st, call, result[1] + (
                (st.rel, call.lineno,
                 f"enters f64-critical {st.func.name}()"),))
        return result

    def _chain_starts_here(self, st, chain):
        rel, line, _ = chain[0]
        node = st.func.node
        end = getattr(node, "end_lineno", node.lineno)
        return rel == st.rel and node.lineno <= line <= end

    def _report(self, st, call, chain):
        if not st.report:
            return
        # lexical introduction inside a critical function is
        # f32-in-f64's finding; only cross-function flow is ours
        if (st.func.qname in self.critical
                and self._chain_starts_here(st, chain)):
            return
        key = chain[0]
        if key in st.reported:
            return
        st.reported.add(key)
        steps = " -> ".join(f"{rel}:{line} {desc}"
                            for rel, line, desc in chain)
        st.ctx.report(
            self.id, call.lineno,
            f"f32 value reaches an f64-critical sink: {steps}")
