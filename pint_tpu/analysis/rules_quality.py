"""Fit-quality signal coverage rule family.

- quality-signal-dropped: a function on the fit path (the
  ``quality_signal_modules`` registry: fitter.py, parallel/pta.py,
  parallel/toa_shard.py, serve/engine.py) computes a numerical
  quality signal — a ``relres_failed`` refinement verdict or a
  ``chi2_whitened`` assignment — without routing anything into the
  numerics observatory (``pint_tpu.obs.fitquality``): no ledger
  record, no fallback note, no per-batch quality summary. A
  computed-then-dropped signal is telemetry the drift sentinels and
  the ``fit_quality`` SLOs silently never see; the very fits that
  needed the f64 fallback are exactly the ones the observatory must
  know about. Fix: record through ``fitquality.record_fit_batch`` /
  ``FITQ.note_fallback`` (or the module's ``_record_*quality``
  helper), or suppress with a justification when the signal is a
  local probe diagnostic and not a production fit.

  Detection is per function: the SIGNAL must appear in the function's
  own body (nested defs are their own scope), while the RECORD
  pattern may appear anywhere inside it, so a recording closure
  counts. Functions whose own name matches the signal pattern (the
  ``relres_failed`` guard itself) are never flagged, and reads such
  as ``getattr(self, "chi2_whitened", None)`` are string constants,
  not computations, so they stay quiet.
"""

from __future__ import annotations

import ast
import re

from .core import Rule, call_name, mentions, register


def _own_nodes(fn):
    """The function's own statements — nested function/class bodies
    are separate scopes and are NOT descended into (they get their
    own check)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _assign_targets(node):
    if isinstance(node, ast.Assign):
        return node.targets
    if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        return [node.target]
    return []


@register
class QualitySignalDroppedRule(Rule):
    id = "quality-signal-dropped"
    family = "quality"
    rationale = ("a relres/chi2_whitened quality signal computed but "
                 "never recorded through pint_tpu.obs.fitquality is "
                 "invisible to the drift sentinels and fit_quality "
                 "SLOs")

    def _applies(self, ctx):
        rel = "/" + ctx.rel.replace("\\", "/")
        suffixes = getattr(ctx.config, "quality_signal_modules", ())
        return any(rel.endswith(s) for s in suffixes)

    def _signal_site(self, fn, sig):
        """First quality-signal computation in the function's own
        body: a call to a signal-named function, or an assignment to
        a signal-named target (self.chi2_whitened = ...)."""
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name and sig.search(name):
                    return node
            for target in _assign_targets(node):
                if isinstance(target, ast.Name) and sig.search(target.id):
                    return node
                if (isinstance(target, ast.Attribute)
                        and sig.search(target.attr)):
                    return node
        return None

    def check_file(self, ctx):
        if not self._applies(ctx):
            return
        sig = re.compile(getattr(ctx.config, "quality_signal_pattern",
                                 r"relres_failed|chi2_whitened"))
        rec = re.compile(getattr(
            ctx.config, "quality_record_pattern",
            r"quality|FITQ|obs_fitq|record_fit_batch|note_fallback"))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if sig.search(node.name):
                continue  # the guard/probe definition itself
            site = self._signal_site(node, sig)
            if site is None:
                continue
            if mentions(node, rec):
                continue
            ctx.report(
                self.id, site,
                f"{node.name}() computes a fit-quality signal but "
                "never records it: route it through "
                "pint_tpu.obs.fitquality (record_fit_batch / "
                "FITQ.note_fallback / the module's quality helper) "
                "or suppress with a justification")
