"""registry-drift: the lint registries must not rot.

pintlint's power comes from codebase-tuned registries
(analysis/config.py): LOCKED_CLASSES names the shared classes, the
module tuples name the instrumented / durable / kernel / serve-state
surfaces. Registries rot silently in both directions — a new class
grows an RLock and nobody registers it (its lock discipline is simply
never checked), or a file is renamed and its stale registry entry
matches nothing (the rule quietly stops running there). Both
directions are findings:

- a class that assigns ``self.X = threading.Lock()/RLock()`` but is
  not in ``LOCKED_CLASSES`` (checked whenever the scan's config has a
  non-empty registry — fixture configs with an empty one stay inert);
- registry entries in ``DURABLE_ARTIFACT_MODULES`` /
  ``KERNEL_DISPATCH_MODULES`` / ``SERVE_STATE_MODULES`` /
  ``OBS_INSTRUMENTED_MODULES`` matching no file, and
  ``LOCKED_CLASSES`` names with no class definition in the tree
  (checked only when the registry module itself is in the scan, so
  linting one file never claims the whole registry is stale). Paths
  are matched against the scan plus the configured tree roots — some
  registered surfaces (bench.py, benchmarks/) live outside the
  package scan root.
"""

from __future__ import annotations

import os

from .core import Rule, iter_py_files, register


@register
class RegistryDriftRule(Rule):
    """An unregistered lock-owning class gets no lock-discipline
    checking at all; a stale registry entry silently un-checks a
    surface that used to be covered. Either way the contract decays
    with no signal — this rule makes drift loud."""

    id = "registry-drift"
    family = "registry"
    rationale = ("lock-owning classes missing from LOCKED_CLASSES and "
                 "registry entries matching nothing make lint "
                 "coverage rot silently")
    whole_program = True

    def check_project(self, project, index):
        config = project.config
        if config.locked_classes:
            self._check_unregistered(project, index)
        anchor = self._find_anchor(project)
        if anchor is not None:
            self._check_stale(project, index, anchor)

    # -- unregistered lock owners ---------------------------------------

    def _check_unregistered(self, project, index):
        config = project.config
        for qname in sorted(index.classes):
            cls = index.classes[qname]
            if not cls.lock_attrs:
                continue
            if cls.name in config.locked_classes:
                continue
            if any(m in "/" + cls.module.ctx.rel.replace(os.sep, "/")
                   for m in config.test_path_markers):
                continue
            attrs = ", ".join(sorted(cls.lock_attrs))
            cls.module.ctx.report(
                self.id, cls.node.lineno,
                f"class {cls.name} owns a lock ({attrs}) but is not "
                f"registered in LOCKED_CLASSES — its lock discipline "
                f"and lock ordering are unchecked")

    # -- stale registry entries -----------------------------------------

    def _find_anchor(self, project):
        suffix = project.config.registry_anchor_suffix
        if not suffix:
            return None
        for ctx in project.files:
            if ctx.path.endswith(suffix) or ctx.rel.endswith(suffix):
                return ctx
        return None

    def _known_paths(self, project):
        paths = set()
        for ctx in project.files:
            paths.add("/" + ctx.rel.replace(os.sep, "/"))
            paths.add("/" + ctx.path.replace(os.sep, "/").lstrip("/"))
        for root in project.config.registry_tree_roots:
            if not os.path.isdir(root):
                continue
            for path in iter_py_files([root]):
                rel = os.path.relpath(path, root)
                paths.add("/" + rel.replace(os.sep, "/"))
        return paths

    def _check_stale(self, project, index, anchor):
        config = project.config
        paths = self._known_paths(project)
        registries = (
            ("DURABLE_ARTIFACT_MODULES",
             config.durable_artifact_modules, "suffix"),
            ("KERNEL_DISPATCH_MODULES",
             config.kernel_dispatch_modules, "marker"),
            ("SERVE_STATE_MODULES",
             config.serve_state_modules, "suffix"),
            ("OBS_INSTRUMENTED_MODULES",
             config.obs_instrumented_modules, "suffix"),
        )
        for reg_name, entries, kind in registries:
            for entry in entries:
                if kind == "suffix":
                    hit = any(p.endswith(entry) for p in paths)
                else:
                    hit = any(entry in p for p in paths)
                if not hit:
                    anchor.report(
                        self.id, 1,
                        f"stale registry entry: {reg_name} lists "
                        f"'{entry}' but no file in the tree matches "
                        f"it — the rules it scopes silently check "
                        f"nothing")
        for name in sorted(config.locked_classes):
            if name not in index.classes_by_name:
                anchor.report(
                    self.id, 1,
                    f"stale registry entry: LOCKED_CLASSES lists "
                    f"'{name}' but no class with that name is "
                    f"defined in the tree")
