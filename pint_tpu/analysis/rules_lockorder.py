"""Lock-order family: whole-program deadlock detection.

PR 16 made the serve path genuinely concurrent: submitter threads, the
flusher worker, and the watchdog all take locks — ``IntakeQueue._lock``
inside ``AsyncServeEngine._work_mutex``, ``Histogram._lock`` inside
``ServeTelemetry._lock``, the persistent tier inside
``ExecutableCache._lock``. Each class is individually disciplined
(rules_locks + tests/lockcheck), but nothing checked the SYSTEM: two
code paths acquiring the same pair of locks in opposite orders deadlock
under load, and no per-class rule can see it.

This rule builds the acquired-while-held graph over the whole scan:

- lock identities are class-level (``ServeTelemetry._lock``), so any
  two instances of the same class alias — conservative and exactly the
  granularity tests/lockcheck.py records at runtime;
- direct edges come from lexically nested ``with`` blocks (including
  multi-item ``with a, b:``);
- call-mediated edges resolve calls made under a held lock through the
  project call graph, transitively, with the full witness chain;
- ``*_locked`` helper methods are treated as holding their class lock
  for their whole body (the repo convention rules_locks enforces);
- ``threading.Condition(self._lock)`` aliases to the underlying lock.

A cycle in the graph is a ``lock-order-cycle`` finding naming the full
witness path. The acyclic graph is exported as a machine-readable
artifact (``python -m pint_tpu.analysis --lock-dag out.json``) and
cross-validated at runtime: tests/lockcheck.py records real acquisition
order during the async-serve stress test and asserts consistency.

Reentrant self-edges (RLock re-entry) are not recorded: they are the
sanctioned pattern, not an ordering constraint.
"""

from __future__ import annotations

import ast

from .core import Rule, register


def _with_items(func_node, nested_nodes):
    """Every (With/AsyncWith node, [context expr]) inside ``func_node``
    excluding nested function bodies."""
    out = []
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        n = stack.pop()
        if n in nested_nodes:
            continue
        if isinstance(n, (ast.With, ast.AsyncWith)):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    out.sort(key=lambda w: (w.lineno, w.col_offset))
    return out


class LockGraph:
    """Directed acquired-while-held graph with witness chains."""

    def __init__(self):
        self.nodes = set()
        self.edges = {}        # (held, acquired) -> witness [str, ...]
        self.sites = {}        # (held, acquired) -> (ctx, line)

    def add_node(self, lock):
        self.nodes.add(lock)

    def add_edge(self, held, acquired, witness, ctx, line):
        if held == acquired:
            return             # RLock re-entry, not an ordering edge
        self.nodes.add(held)
        self.nodes.add(acquired)
        key = (held, acquired)
        if key not in self.edges:
            self.edges[key] = list(witness)
            self.sites[key] = (ctx, line)

    def as_dict(self):
        return {
            "nodes": sorted(self.nodes),
            "edges": [
                {"held": held, "acquired": acquired,
                 "witness": self.edges[(held, acquired)]}
                for held, acquired in sorted(self.edges)
            ],
        }

    def cycles(self):
        """Strongly connected components with >1 node, as ordered node
        lists starting from the smallest lock name."""
        adj = {}
        for held, acquired in self.edges:
            adj.setdefault(held, set()).add(acquired)
        index, low, onstack = {}, {}, set()
        stack, sccs, counter = [], [], [0]

        def strongconnect(v):
            work = [(v, iter(sorted(adj.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            onstack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        onstack.add(w)
                        work.append((w, iter(sorted(adj.get(w, ())))))
                        advanced = True
                        break
                    if w in onstack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        onstack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(scc)

        for v in sorted(self.nodes):
            if v not in index:
                strongconnect(v)
        out = []
        for scc in sccs:
            members = set(scc)
            start = min(members)
            # shortest cycle through `start`: BFS within the SCC
            parent, dist = {start: None}, {start: 0}
            queue = [start]
            while queue:
                cur = queue.pop(0)
                for w in sorted(adj.get(cur, ())):
                    if w in members and w not in dist:
                        dist[w] = dist[cur] + 1
                        parent[w] = cur
                        queue.append(w)
            closers = [u for u in dist
                       if start in adj.get(u, ()) and u != start]
            if not closers:
                continue
            u = min(closers, key=lambda n: (dist[n], n))
            path = []
            while u is not None:
                path.append(u)
                u = parent[u]
            out.append(list(reversed(path)))
        return out


class _GraphBuilder:
    def __init__(self, project, index):
        self.project = project
        self.index = index
        self.config = project.config
        self.graph = LockGraph()
        self._acq_cache = {}
        self._acq_inflight = set()

    # -- lock identity -------------------------------------------------

    def _class_lock_owner(self, cls, attr):
        """(owner ClassInfo or None, canonical attr) for a ``self.X``
        lock access on ``cls``: resolves Condition aliases, own/
        inherited Lock attrs, and the LOCKED_CLASSES registry."""
        aliases = cls.all_cond_aliases(self.index)
        attr = aliases.get(attr, attr)
        owners = cls.all_lock_attrs(self.index)
        if attr in owners:
            return owners[attr], attr
        for mro_cls in cls.mro(self.index):
            spec = self.config.locked_classes.get(mro_cls.name)
            if spec and spec.get("lock") == attr:
                return mro_cls, attr
        return None, attr

    def _class_default_lock(self, cls):
        """The lock a ``*_locked`` helper implicitly holds."""
        spec = None
        for mro_cls in cls.mro(self.index):
            spec = self.config.locked_classes.get(mro_cls.name)
            if spec:
                break
        owners = cls.all_lock_attrs(self.index)
        if spec and spec.get("lock") in owners:
            attr = spec["lock"]
            return f"{owners[attr].name}.{attr}"
        if "_lock" in owners:
            return f"{owners['_lock'].name}._lock"
        if len(owners) == 1:
            attr, owner = next(iter(owners.items()))
            return f"{owner.name}.{attr}"
        return None

    def _lock_id(self, func, expr, local_types):
        """Lock identity of a with-item context expression, or None."""
        if isinstance(expr, ast.Attribute):
            attr, owner_expr = expr.attr, expr.value
            # with self._lock: / with self._cv:
            if (isinstance(owner_expr, ast.Name)
                    and owner_expr.id == "self"
                    and func.cls is not None):
                owner, attr = self._class_lock_owner(func.cls, attr)
                if owner is not None:
                    return f"{owner.name}.{attr}"
                return None
            # with <typed receiver>._lock: — q = self.intake, a local
            # constructed instance, a module singleton, c = reg.counter()
            typ = self.index._expr_class(func.module, owner_expr,
                                         local_types, func)
            if typ is not None:
                cls = self.index.resolve_class(func.module, typ)
                if cls is not None:
                    owner, attr = self._class_lock_owner(cls, attr)
                    if owner is not None:
                        return f"{owner.name}.{attr}"
            return None
        # with MODULE_LOCK:
        if isinstance(expr, ast.Name):
            if expr.id in func.module.module_locks:
                return f"{func.module.name}.{expr.id}"
        return None

    # -- per-function acquisition inventory -----------------------------

    def _acquisitions(self, func):
        """[(lock_id, with_node, item_index)] for direct with-block
        acquisitions in ``func``."""
        types = self.index.local_types(func)
        nested = {n.node for n in func.nested.values()}
        out = []
        for wnode in _with_items(func.node, nested):
            for i, item in enumerate(wnode.items):
                lock = self._lock_id(func, item.context_expr, types)
                if lock is not None:
                    out.append((lock, wnode, i))
        return out

    def _site(self, func, node):
        return f"{func.ctx.rel}:{node.lineno}"

    def acq_star(self, func):
        """{lock_id: witness chain} — every lock ``func`` may acquire
        during its execution, directly or through callees."""
        cached = self._acq_cache.get(func.qname)
        if cached is not None:
            return cached
        if func.qname in self._acq_inflight:
            return {}              # recursion: cut the cycle
        self._acq_inflight.add(func.qname)
        out = {}
        for lock, wnode, _ in self._acquisitions(func):
            out.setdefault(lock, (
                f"{self._site(func, wnode)}: {func.qname} "
                f"acquires {lock}",))
        for call, callee in self.index.calls_of(func):
            if callee is None:
                continue
            for lock, chain in self.acq_star(callee).items():
                out.setdefault(lock, (
                    f"{self._site(func, call)}: {func.qname} "
                    f"-> {callee.qname}",) + chain)
        self._acq_inflight.discard(func.qname)
        self._acq_cache[func.qname] = out
        return out

    # -- edge construction ----------------------------------------------

    @staticmethod
    def _inside(node, wnode):
        end = getattr(wnode, "end_lineno", wnode.lineno)
        nend = getattr(node, "end_lineno", node.lineno)
        return (node.lineno >= wnode.lineno and nend <= end
                and node is not wnode)

    def build(self):
        for qname in sorted(self.index.functions):
            self._edges_of(self.index.functions[qname])
        return self.graph

    def _edges_of(self, func):
        acqs = self._acquisitions(func)
        for lock, _, _ in acqs:
            self.graph.add_node(lock)
        calls = self.index.calls_of(func)
        for held, wnode, item_i in acqs:
            held_site = (f"{self._site(func, wnode)}: {func.qname} "
                         f"holds {held}")
            # nested with-blocks + later items of the same with
            for inner, iw, ii in acqs:
                if iw is wnode and ii > item_i:
                    self.graph.add_edge(
                        held, inner,
                        [held_site,
                         f"{self._site(func, iw)}: then acquires "
                         f"{inner} in the same with"],
                        func.ctx, wnode.lineno)
                elif iw is not wnode and self._inside(iw, wnode):
                    self.graph.add_edge(
                        held, inner,
                        [held_site,
                         f"{self._site(func, iw)}: acquires {inner} "
                         f"while held"],
                        func.ctx, wnode.lineno)
            # calls made while the lock is held
            for call, callee in calls:
                if callee is None or not self._inside(call, wnode):
                    continue
                for lock, chain in self.acq_star(callee).items():
                    self.graph.add_edge(
                        held, lock,
                        [held_site,
                         f"{self._site(func, call)}: calls "
                         f"{callee.qname}"] + list(chain),
                        func.ctx, wnode.lineno)
        # *_locked helpers hold their class lock for the whole body
        if (func.cls is not None and func.name.endswith("_locked")
                and func.parent is None):
            held = self._class_default_lock(func.cls)
            if held is not None:
                conv = (f"{self._site(func, func.node)}: {func.qname} "
                        f"holds {held} by *_locked convention")
                for lock, wnode, _ in acqs:
                    self.graph.add_edge(
                        held, lock,
                        [conv, f"{self._site(func, wnode)}: acquires "
                               f"{lock}"],
                        func.ctx, func.node.lineno)
                for call, callee in calls:
                    if callee is None:
                        continue
                    for lock, chain in self.acq_star(callee).items():
                        self.graph.add_edge(
                            held, lock,
                            [conv, f"{self._site(func, call)}: calls "
                                   f"{callee.qname}"] + list(chain),
                            func.ctx, func.node.lineno)


@register
class LockOrderRule(Rule):
    """Two threads acquiring the same pair of locks in opposite orders
    deadlock under load — the classic inversion no per-class rule can
    see. The whole-program acquired-while-held graph must be a DAG;
    every cycle is reported with its full witness path (the with-block
    or call chain realizing each edge). The acyclic graph doubles as
    the static contract tests/lockcheck.py checks real executions
    against."""

    id = "lock-order-cycle"
    family = "locks"
    rationale = ("opposite-order lock acquisition across threads "
                 "deadlocks; the acquired-while-held graph must stay "
                 "acyclic")
    whole_program = True

    def check_project(self, project, index):
        graph = _GraphBuilder(project, index).build()
        project.lock_graph = graph
        for cycle in graph.cycles():
            edges = list(zip(cycle, cycle[1:] + cycle[:1]))
            first = None
            lines = []
            for held, acquired in edges:
                witness = graph.edges.get((held, acquired))
                site = graph.sites.get((held, acquired))
                if witness is None:
                    continue
                if first is None:
                    first = site
                lines.append(f"[{held} -> {acquired}: "
                             + " | ".join(witness) + "]")
            if first is None:
                continue
            ctx, line = first
            ctx.report(
                self.id, line,
                "lock-order cycle "
                + " -> ".join(cycle + cycle[:1])
                + ": " + " ".join(lines))


def lock_order_graph(paths, config=None):
    """Run the whole-program pass over ``paths`` and return the
    acquired-while-held graph as a JSON-ready dict (the artifact the
    CLI's --lock-dag writes and the runtime cross-check consumes)."""
    from .core import run_project

    _, project = run_project(paths, config=config)
    graph = getattr(project, "lock_graph", None)
    return graph.as_dict() if graph is not None else {
        "nodes": [], "edges": []}
