"""Kernel-dispatch fallback-visibility rule family.

- kernel-silent-fallback: an exception handler around a Pallas kernel
  dispatch (in a ``kernels/`` module) that swallows the failure
  without routing through ``kernels.fallback.note_pallas_fallback``
  or re-raising. The dual-path kernels fall back to their jnp
  reference paths on any Pallas failure — which is *correct* but
  slow, so a fleet silently pinned to the fallback looks healthy in
  every fit-quality probe while quietly losing its MXU throughput.
  The seed fixture is the bare ``except Exception: pass`` that
  shipped in kernels/seggram.py's dispatcher: one mosaic version
  quirk away from an invisible ~10x GLS slowdown. Handlers must bump
  the ``kernels.pallas_fallbacks`` counter + flight note via
  ``note_pallas_fallback`` (or re-raise).
"""

from __future__ import annotations

import ast
import re

from .core import Rule, mentions, register

_PALLAS = re.compile(r"pallas", re.IGNORECASE)
_NOTE = re.compile(r"note_pallas_fallback")


@register
class KernelSilentFallbackRule(Rule):
    id = "kernel-silent-fallback"
    family = "kernels"
    rationale = ("a swallowed Pallas dispatch failure silently pins "
                 "the fleet to the slow jnp reference path; route "
                 "fallbacks through kernels.fallback."
                 "note_pallas_fallback so the degradation is counted, "
                 "flight-recorded, and logged")

    def _applies(self, ctx):
        rel = "/" + ctx.rel.replace("\\", "/")
        markers = getattr(ctx.config, "kernel_dispatch_modules", ())
        return any(m in rel for m in markers)

    @staticmethod
    def _silent(handler):
        """True when the handler neither re-raises nor routes through
        note_pallas_fallback — including the seed ``pass`` form."""
        if any(isinstance(sub, ast.Raise) for sub in ast.walk(handler)):
            return False
        return not mentions(handler, _NOTE)

    def check_file(self, ctx):
        if not self._applies(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            if not any(mentions(stmt, _PALLAS) for stmt in node.body):
                continue
            for handler in node.handlers:
                if self._silent(handler):
                    ctx.report(
                        self.id, handler,
                        "exception handler around a Pallas dispatch "
                        "swallows the failure silently: the jnp "
                        "fallback is correct but slow, and nothing "
                        "records the degradation. Call kernels."
                        "fallback.note_pallas_fallback(kernel, exc) "
                        "(counter + flight note + warn-once) or "
                        "re-raise")
