"""Durable-artifact write-discipline rule family.

- durable-write-unatomic: a truncating/creating ``open()`` (mode
  containing ``w`` or ``x``) in a module that owns crash-surviving
  artifacts — the checkpoint store, the request journal, the
  persisted executable cache, the flight recorder. A plain
  ``open(path, "w")`` truncates in place: a process killed between
  the truncate and the final flush leaves a torn file where the
  previous GOOD artifact used to be, which is precisely the data
  loss these modules exist to prevent. Durable modules must publish
  through ``pint_tpu.durable`` (``atomic_write_bytes`` /
  ``atomic_write_text`` / ``atomic_write_json``: temp file + fsync +
  rename) or append-only modes. Read modes (``r``, ``rb``) and
  in-place patch mode (``r+b`` — the fault injectors' byte-flippers)
  are not write-publishes and stay legal.
"""

from __future__ import annotations

import ast

from .core import Rule, call_name, register


def _open_mode(node):
    """The mode-string constant of an ``open()`` call, or None when
    the mode is absent (default "r") or not a literal we can judge."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return None
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


@register
class DurableWriteUnatomicRule(Rule):
    id = "durable-write-unatomic"
    family = "durable"
    rationale = ("a truncating open() in a durable-artifact module "
                 "can tear the previous good artifact on a crash; "
                 "publish through pint_tpu.durable atomic writes")

    def _applies(self, ctx):
        rel = "/" + ctx.rel.replace("\\", "/")
        suffixes = getattr(ctx.config, "durable_artifact_modules", ())
        return any(rel.endswith(s) for s in suffixes)

    def check_file(self, ctx):
        if not self._applies(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in ("open", "os.fdopen"):
                continue
            mode = _open_mode(node)
            if mode is None or not any(c in mode for c in "wx"):
                continue
            ctx.report(
                self.id, node,
                f"open(..., {mode!r}) in a durable-artifact module "
                "truncates in place: a crash mid-write tears the "
                "previous good copy. Publish through pint_tpu."
                "durable.atomic_write_bytes/text/json (temp + fsync "
                "+ rename) instead")
