"""Lock-discipline rule family.

- lock-discipline: mutations of registered shared state outside
  ``with self._lock:`` (classes) / ``with <LOCK>:`` (module globals).
- locked-helper-call: a ``*_locked`` helper invoked without the lock.

Model (documented limits, mirrored by tests/lockcheck.py at runtime):
the rule sees DIRECT mutations — ``self.x = ...``, ``self.x += ...``,
``self.x[k] = ...``, ``self.x.append(...)`` and friends. A mutation
through a local alias (``e = self._keys[k]; e["n"] += 1``) is invisible
statically, which is exactly why helpers that mutate through aliases
must follow the ``*_locked`` naming convention: the alias mutation is
then guarded at every call site, which IS checkable."""

from __future__ import annotations

import ast

from .core import (MUTATOR_METHODS, Rule, name_root, register,
                   self_attr_root)

_EXEMPT_METHODS = frozenset({"__init__", "__new__", "__repr__",
                             "__str__", "__len__"})


def _with_lock_spans(func, is_lock_expr):
    """(start, end) line spans of ``with <lock>:`` blocks in func."""
    spans = []
    for node in ast.walk(func):
        if isinstance(node, ast.With):
            for item in node.items:
                if is_lock_expr(item.context_expr):
                    spans.append((node.lineno, node.end_lineno))
                    break
    return spans


def _in_spans(line, spans):
    return any(a <= line <= b for a, b in spans)


def _self_lock_matcher(lock_attr):
    def match(expr):
        return (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and expr.attr == lock_attr)
    return match


def _name_lock_matcher(lock_name):
    def match(expr):
        return isinstance(expr, ast.Name) and expr.id == lock_name
    return match


def _iter_mutations(scope):
    """Yield (node, target_expr) for direct mutations in ``scope``:
    assignments, augmented assigns, deletes, and mutator-method calls.
    The target_expr is the mutated container/attribute expression."""
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for sub in _split_target(t):
                    yield node, sub
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node, ast.AnnAssign) and node.value is None:
                continue
            yield node, node.target
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                yield node, t
        elif isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in MUTATOR_METHODS):
                yield node, node.func.value


def _split_target(t):
    if isinstance(t, (ast.Tuple, ast.List)):
        for el in t.elts:
            yield from _split_target(el)
    else:
        yield t


def _thread_local_attrs(cls):
    """Attrs assigned ``threading.local()`` anywhere in the class:
    per-thread state is the canonical LOCK-FREE pattern, so mutations
    through such an attribute need no lock."""
    out = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not (isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "local"):
            continue
        for t in node.targets:
            attr = self_attr_root(t)
            if attr is not None:
                out.add(attr)
    return out


@register
class LockDisciplineRule(Rule):
    """The serve engine, the pipelined fleet executor, and concurrent
    prewarm all share these objects across threads; an unsynchronized
    ``self.hits += 1`` is a lost update and an unsynchronized
    OrderedDict mutation can corrupt the container. Every direct
    mutation of a registered class's monitored attributes (or of a
    registered module-level cache) must execute under its lock."""

    id = "lock-discipline"
    family = "locks"
    rationale = ("registered shared state mutated outside 'with "
                 "self._lock:' races the serve/fleet thread pools")

    def check_file(self, ctx):
        cfg = ctx.config
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name in cfg.locked_classes:
                self._check_class(ctx, node,
                                  cfg.locked_classes[node.name])
        if cfg.locked_globals:
            self._check_globals(ctx)

    def _check_class(self, ctx, cls, spec):
        lock_attr = spec.get("lock", "_lock")
        monitored = spec.get("attrs")
        is_lock = _self_lock_matcher(lock_attr)
        thread_local = _thread_local_attrs(cls)
        for func in cls.body:
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if func.name in _EXEMPT_METHODS or \
                    func.name.endswith("_locked"):
                continue
            spans = _with_lock_spans(func, is_lock)
            for node, target in _iter_mutations(func):
                attr = self_attr_root(target)
                if attr is None or attr == lock_attr:
                    continue
                if attr in ctx.config.locked_class_exempt_attrs:
                    continue
                if attr in thread_local:
                    continue
                if monitored is not None and attr not in monitored:
                    continue
                if not _in_spans(node.lineno, spans):
                    ctx.report(
                        self.id, node,
                        f"'{cls.name}.{func.name}' mutates shared "
                        f"attribute 'self.{attr}' outside 'with "
                        f"self.{lock_attr}:'")

    def _check_globals(self, ctx):
        cfg = ctx.config
        # only fire in files that actually define the registered global
        defined = set()
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and \
                            t.id in cfg.locked_globals:
                        defined.add(t.id)
        if not defined:
            return
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for node, target in _iter_mutations(func):
                root = name_root(target)
                if root not in defined:
                    continue
                lock_name = cfg.locked_globals[root]
                spans = _with_lock_spans(func,
                                         _name_lock_matcher(lock_name))
                if not _in_spans(node.lineno, spans):
                    ctx.report(
                        self.id, node,
                        f"module-level shared cache '{root}' mutated "
                        f"outside 'with {lock_name}:'")


@register
class LockedHelperCallRule(Rule):
    """``*_locked`` helpers mutate shared state through local aliases
    the static mutation scan cannot follow; the convention's other
    half is that every call site must already hold the lock. This rule
    checks that half: a ``self.<x>_locked(...)`` call outside ``with
    self._lock:`` (from a non-``_locked`` method) is a violation."""

    id = "locked-helper-call"
    family = "locks"
    rationale = ("a *_locked helper called without holding the lock "
                 "voids the convention that makes alias mutations safe")

    def check_file(self, ctx):
        cfg = ctx.config
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name in cfg.locked_classes:
                self._check_class(ctx, node,
                                  cfg.locked_classes[node.name])

    def _check_class(self, ctx, cls, spec):
        lock_attr = spec.get("lock", "_lock")
        is_lock = _self_lock_matcher(lock_attr)
        for func in cls.body:
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if func.name.endswith("_locked"):
                continue  # helpers may chain; call sites are guarded
            spans = _with_lock_spans(func, is_lock)
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                        and f.attr.endswith("_locked")
                        and not _in_spans(node.lineno, spans)):
                    ctx.report(
                        self.id, node,
                        f"'{cls.name}.{func.name}' calls "
                        f"'self.{f.attr}()' without holding "
                        f"'self.{lock_attr}'")
