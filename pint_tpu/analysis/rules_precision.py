"""Precision rule family.

- nan-guard: NaN-unsafe failure guards on convergence diagnostics.
- f32-in-f64: float32 introduced inside an f64-critical function.
"""

from __future__ import annotations

import ast
import re

from .core import Rule, call_name, mentions, register


@register
class NanGuardRule(Rule):
    """``max(relres) > tol`` is False when relres is NaN, so the very
    failure the diagnostic exists to signal (an f32 overflow / eigh
    NaN propagating through refinement) silently passes the guard.
    ADVICE.md round 5 found three live variants. The sanctioned forms
    are ``fitter.relres_failed(...)`` or ``not np.all(x <= tol)`` —
    NaN fails a ``<=`` comparison, so NaN means failure.
    Python's builtin ``max`` is equally unsafe: ``max(0.0, nan)`` is
    0.0 (comparison False keeps the first arg), so folding a
    diagnostic through ``max`` erases the NaN; ``np.maximum`` /
    ``jnp.maximum`` propagate it.
    """

    id = "nan-guard"
    family = "precision"
    rationale = ("'diag > tol' and builtin max() both treat NaN as "
                 "success; use relres_failed()/not np.all(diag <= tol)")

    def check_file(self, ctx):
        diag = re.compile(ctx.config.nan_diag_pattern)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Compare):
                for op, comparator in zip(node.ops, node.comparators):
                    if not isinstance(op, (ast.Gt, ast.GtE)):
                        continue
                    if mentions(node.left, diag):
                        ctx.report(self.id, node,
                                   "NaN-unsafe failure guard on a "
                                   "convergence diagnostic ('> tol' is "
                                   "False under NaN); use "
                                   "fitter.relres_failed() or "
                                   "'not np.all(x <= tol)'")
                        break
            elif isinstance(node, ast.Call):
                if (call_name(node) == "max" and len(node.args) >= 2
                        and any(mentions(a, diag) for a in node.args)):
                    ctx.report(self.id, node,
                               "builtin max() on a convergence "
                               "diagnostic returns the non-NaN "
                               "argument (max(0.0, nan) == 0.0); use "
                               "np.maximum, which propagates NaN")


_F32_MARKERS = ("float32", "f32")


@register
class F32InF64Rule(Rule):
    """The paper's contract is f64-critical residuals: the whitening /
    normal-equation chain must stay f64 end to end. The ONLY sanctioned
    f32 is the explicitly-guarded mixed-precision Gram (gls_gram and
    the batched equivalents), which is registry-excluded. Everywhere
    else in a registered f64-critical function, a float32 literal,
    ``dtype=jnp.float32``, or ``.astype(...32)`` silently costs ~9
    decimal digits on values (TOAs) that need ~16."""

    id = "f32-in-f64"
    family = "precision"
    rationale = ("float32 introduced inside a function registered as "
                 "f64-critical loses the precision the residual "
                 "contract requires")

    def _critical_names(self, ctx):
        for suffix, names in ctx.config.f64_critical.items():
            if ctx.path.endswith(suffix) or ctx.rel.endswith(suffix):
                return names
        return None

    def check_file(self, ctx):
        names = self._critical_names(ctx)
        if names is None:
            return
        whole_module = "*" in names
        seen = set()  # nested defs are walked twice; report once
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if not whole_module and func.name not in names:
                continue
            for node in ast.walk(func):
                hit = None
                if isinstance(node, ast.Attribute) and \
                        node.attr in _F32_MARKERS:
                    hit = node
                elif isinstance(node, ast.Constant) and \
                        node.value in _F32_MARKERS:
                    hit = node
                if hit is not None:
                    key = (hit.lineno, hit.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    ctx.report(
                        self.id, hit,
                        f"float32 introduced inside f64-critical "
                        f"function '{func.name}'; the residual chain "
                        f"requires f64 (mixed precision belongs in the "
                        f"guarded gls_gram path only)")
