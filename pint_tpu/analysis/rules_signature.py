"""signature-incomplete: cache-key completeness for traced programs.

The zero-retrace serving contract keys compiled programs on shape
signatures: ``PTABatch.shape_signature()`` fingerprints every array the
program table's jitted closures touch, ``ShapePlan.signature()`` hashes
the bucket geometry, and the ExecutableCache composes both. The
soundness requirement is COMPLETENESS: every shape-affecting attribute
a traced closure reads (or that is passed as a runtime argument at a
program-table dispatch) must be folded into the signature — an attr
read inside traced code that the key omits can change compiled-program
shape without changing the key, silently serving a stale executable or
retracing on every call.

This rule checks that statically, per class registered in
``SIGNATURE_CLASSES``:

- **signature set**: ``self.X`` reads inside the registered signature
  method, transitively through ``self.m()`` helper calls;
- **covered set**: the signature set, plus attrs appearing in the
  program-table KEY expression (``self._fns[key]`` — changing them
  changes the key, which is safe by construction), plus per-class
  exemptions for host-only metadata;
- **checked set**: ``self.X`` reads inside jit-traced closures defined
  in the class's methods (decorator, ``jax.jit(f)`` harvesting, or
  storage into ``self._fns[...]``), again transitive through self
  method calls — plus ``self.X`` runtime arguments at ``self._fns[...]
  (...)`` dispatch sites.

Anything in the checked set but not covered is a finding.
"""

from __future__ import annotations

import ast

from .core import Rule, register, self_attr_root
from .rules_retrace import TracedIndex


def _self_attr(node):
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@register
class SignatureCompletenessRule(Rule):
    """A shape-affecting attribute read inside traced code but absent
    from the program key can change the compiled program without
    changing the key: either a silently stale executable (wrong
    results) or a retrace on every call (the zero-recompile contract
    gone). The signature must fingerprint everything the trace
    reads."""

    id = "signature-incomplete"
    family = "retrace"
    rationale = ("attr read inside jit-traced code but missing from "
                 "the shape signature can change program shape "
                 "without changing the cache key")
    whole_program = True

    def check_project(self, project, index):
        config = project.config
        if not config.signature_classes:
            return
        for qname in sorted(index.classes):
            cls = index.classes[qname]
            spec = config.signature_classes.get(cls.name)
            if spec is None:
                continue
            self._check_class(index, cls, spec)

    def _check_class(self, index, cls, spec):
        sig_method = cls.find_method(index, spec["signature"])
        if sig_method is None:
            cls.module.ctx.report(
                self.id, cls.node.lineno,
                f"class {cls.name} is registered with signature "
                f"method '{spec['signature']}' but does not define "
                f"it")
            return
        exempt = set(spec.get("exempt", ())) | {"_fns"}
        sig_reads = self._transitive_self_reads(index, cls, sig_method)
        traced = TracedIndex(cls.module.ctx.tree)

        for method in self._all_methods(index, cls):
            key_attrs = self._key_attrs(method.node)
            covered = sig_reads | key_attrs | exempt
            for closure in method.nested.values():
                if not self._is_traced(traced, method, closure):
                    continue
                reads = self._transitive_self_reads(
                    index, cls, closure)
                for attr in sorted(reads - covered):
                    line = self._read_line(closure.node, attr)
                    closure.ctx.report(
                        self.id, line,
                        f"traced closure '{closure.name}' in "
                        f"{cls.name}.{method.name} reads self.{attr}, "
                        f"which is not folded into "
                        f"{cls.name}.{spec['signature']}() — a shape "
                        f"change through it will not change the "
                        f"cache key")
            for node, attrs in self._dispatch_args(method.node):
                for attr in sorted(attrs - covered):
                    method.ctx.report(
                        self.id, node.lineno,
                        f"self.{attr} is passed as a runtime argument "
                        f"at a program-table dispatch in "
                        f"{cls.name}.{method.name} but is not folded "
                        f"into {cls.name}.{spec['signature']}()")

    @staticmethod
    def _all_methods(index, cls):
        seen, out = set(), []
        for mro_cls in cls.mro(index):
            for name, method in sorted(mro_cls.methods.items()):
                if name not in seen:
                    seen.add(name)
                    out.append(method)
        return out

    def _is_traced(self, traced, method, closure):
        if traced.is_traced_def(closure.node):
            return True
        # stored into the program table: self._fns[key] = closure (or
        # a wrapper call mentioning it)
        for sub in ast.walk(method.node):
            if not isinstance(sub, ast.Assign):
                continue
            for tgt in sub.targets:
                if (isinstance(tgt, ast.Subscript)
                        and self_attr_root(tgt) == "_fns"):
                    for ref in ast.walk(sub.value):
                        if (isinstance(ref, ast.Name)
                                and ref.id == closure.name):
                            return True
        return False

    def _transitive_self_reads(self, index, cls, func):
        """self-attr READS in ``func``, following self.m() calls into
        other methods of the class (MRO-wide), memoized per class."""
        methods = {}
        for mro_cls in cls.mro(index):
            for name in mro_cls.methods:
                methods.setdefault(name, mro_cls.methods[name])
        reads, seen = set(), set()
        work = [func]
        while work:
            cur = work.pop()
            if cur.qname in seen:
                continue
            seen.add(cur.qname)
            for sub in ast.walk(cur.node):
                attr = _self_attr(sub)
                if attr is None:
                    continue
                if attr in methods:
                    callee = methods[attr]
                    if callee.qname not in seen:
                        work.append(callee)
                    continue
                if isinstance(sub.ctx, ast.Load):
                    reads.add(attr)
        return reads

    @staticmethod
    def _key_attrs(method_node):
        """self attrs participating in program-table keys: subscript
        expressions of ``self._fns[...]`` plus the local ``key = ...``
        assignments feeding them."""
        key_exprs, key_names = [], set()
        for sub in ast.walk(method_node):
            if (isinstance(sub, ast.Subscript)
                    and self_attr_root(sub.value) == "_fns"):
                key_exprs.append(sub.slice)
                if isinstance(sub.slice, ast.Name):
                    key_names.add(sub.slice.id)
        for sub in ast.walk(method_node):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Name)
                            and tgt.id in key_names):
                        key_exprs.append(sub.value)
        out = set()
        for expr in key_exprs:
            for sub in ast.walk(expr):
                attr = _self_attr(sub)
                if attr is not None:
                    out.add(attr)
        return out

    @staticmethod
    def _dispatch_args(method_node):
        """[(call node, {self attrs passed as runtime args})] for
        ``self._fns[...](...)`` dispatch sites."""
        out = []
        for sub in ast.walk(method_node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Subscript)
                    and self_attr_root(sub.func) == "_fns"):
                continue
            attrs = set()
            for arg in list(sub.args) + [kw.value
                                         for kw in sub.keywords]:
                for inner in ast.walk(arg):
                    attr = _self_attr(inner)
                    if attr is not None:
                        attrs.add(attr)
            out.append((sub, attrs))
        return out

    @staticmethod
    def _read_line(closure_node, attr):
        for sub in ast.walk(closure_node):
            if _self_attr(sub) == attr:
                return sub.lineno
        return closure_node.lineno
