"""Serve request-state coverage rule family.

- request-state-leak: a function in a serve-state module (the
  ``serve_state_modules`` registry: serve/engine.py) moves a request
  to a terminal outcome — an assignment to a result's ``.status`` or
  ``.reason`` — without telling anyone: no lifecycle transition
  (``_lc`` / ``reqlife``), no telemetry record or counter, no
  reject/fail helper that carries both. A status set in a code path
  the ledger never hears about is a request that exists in the
  caller's ServeResult but in NO observability surface: the lifecycle
  census under-counts, ``obs tail`` can't resolve it, and the
  terminal-state invariant ("every request ends in exactly one
  terminal state") rots silently the next time someone adds an early
  return. Fix: pair the assignment with a lifecycle transition or a
  telemetry record in the same function (the ``_reject`` / ``_fail``
  helpers do both), or suppress with a justification when the
  assignment is a non-terminal bookkeeping touch-up.

  Detection is per function: the STATUS assignment must appear in the
  function's own body (nested defs are their own scope), while the
  record pattern may appear anywhere inside it. Assignments to
  ``self.*`` are engine-internal state, not request outcomes, and
  stay quiet.
"""

from __future__ import annotations

import ast
import re

from .core import Rule, mentions, register
from .rules_quality import _assign_targets, _own_nodes


@register
class RequestStateLeakRule(Rule):
    id = "request-state-leak"
    family = "serve"
    rationale = ("a request status/reason assigned without a paired "
                 "lifecycle transition or telemetry record is a "
                 "terminal outcome no observability surface ever "
                 "sees")

    def _applies(self, ctx):
        rel = "/" + ctx.rel.replace("\\", "/")
        suffixes = getattr(ctx.config, "serve_state_modules", ())
        return any(rel.endswith(s) for s in suffixes)

    def _status_site(self, fn):
        """First request-outcome assignment in the function's own
        body: ``<non-self>.status = ...`` or ``<non-self>.reason =
        ...`` (self.* is engine state, not a request outcome)."""
        for node in _own_nodes(fn):
            for target in _assign_targets(node):
                if not isinstance(target, ast.Attribute) \
                        or target.attr not in ("status", "reason"):
                    continue
                recv = target.value
                if isinstance(recv, ast.Name) and recv.id == "self":
                    continue
                return node
        return None

    def check_file(self, ctx):
        if not self._applies(ctx):
            return
        rec = re.compile(getattr(
            ctx.config, "serve_state_record_pattern",
            r"_lc|reqlife|lifecycle|telemetry|_reject|_fail"))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            site = self._status_site(node)
            if site is None:
                continue
            if mentions(node, rec):
                continue
            ctx.report(
                self.id, site,
                f"{node.name}() assigns a request status/reason but "
                "never records the outcome: pair it with a lifecycle "
                "transition (self._lc / reqlife) or a telemetry "
                "record in the same function, or suppress with a "
                "justification")
