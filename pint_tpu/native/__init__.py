"""ctypes loader for the native host-kernel library.

Builds native/src/host_kernels.cpp on first use (g++ is in the build
image; no pybind11, so the C ABI + ctypes is the binding layer), and
degrades silently to the pure-numpy implementations when a compiler
is unavailable or PINT_TPU_NO_NATIVE is set. Every call site keeps
its numpy path; the native library is a performance mirror, verified
equal by tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB: ctypes.CDLL | None | bool = None  # False = tried and failed

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libpint_host.so")
_SRC = os.path.join(_HERE, "..", "..", "native", "src", "host_kernels.cpp")

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")


def _build() -> bool:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return False
    # compile to a temp path and atomically rename: an interrupted or
    # concurrent build must never leave a truncated .so that the
    # staleness check would treat as fresh
    tmp = f"{_SO}.build.{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o", tmp, src],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _setup(lib: ctypes.CDLL) -> bool:
    """Declare signatures and push the Python-side series tables
    (single source of truth) into the library: the full IAU2000B
    nutation table + planetary bias from erfa_lite, and the TDB-TT
    harmonic terms from timescales. Returns False when the library
    predates a required symbol — without the table push the .so would
    fall back to its built-in truncations and the native/numpy
    mirror-equality contract would break, so such a library must not
    be used."""
    try:
        lib.pt_tdb_minus_tt.argtypes = [ctypes.c_int64, _i64p, _f64p, _f64p]
        lib.pt_tdb_minus_tt.restype = None
        lib.pt_itrf_to_gcrs.argtypes = [
            ctypes.c_int64, _i64p, _f64p, _i64p,
            _f64p, _f64p, _f64p, _f64p, _f64p, _f64p]
        lib.pt_itrf_to_gcrs.restype = None
        lib.pt_cheby_posvel.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                        ctypes.c_int64, ctypes.c_int64,
                                        _f64p, _f64p, _f64p, _f64p]
        lib.pt_cheby_posvel.restype = None
        _u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        _i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.pt_parse_tim_t2.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, _i64p, _f64p, _f64p, _f64p,
            _i32p, _u8p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
            _u8p, ctypes.c_int64, _i64p, ctypes.POINTER(ctypes.c_int64)]
        lib.pt_parse_tim_t2.restype = ctypes.c_int64
        lib.pt_set_nut_table.argtypes = [ctypes.c_int64, _f64p,
                                         ctypes.c_double, ctypes.c_double]
        lib.pt_set_nut_table.restype = None
        lib.pt_set_tdb_terms.argtypes = [ctypes.c_int64, _f64p,
                                         ctypes.c_int64, _f64p, _f64p,
                                         ctypes.c_int64, ctypes.c_double,
                                         ctypes.c_double]
        lib.pt_set_tdb_terms.restype = None
    except AttributeError:
        return False
    from .. import timescales as _ts
    from ..earth import erfa_lite as _el

    nut = np.ascontiguousarray(_el._NUT_TERMS, np.float64)
    lib.pt_set_nut_table(nut.shape[0], nut,
                         _el._NUT_PLANETARY_BIAS_PSI,
                         _el._NUT_PLANETARY_BIAS_EPS)
    terms = np.ascontiguousarray(_ts._TDB_TERMS_ALL, np.float64)
    t_terms = np.ascontiguousarray(_ts._TDB_T_TERMS, np.float64)
    poly = np.ascontiguousarray(_ts._TDB_POLY, np.float64)
    lib.pt_set_tdb_terms(terms.shape[0], terms,
                         t_terms.shape[0], t_terms, poly,
                         _ts._N_T_TERMS_PUBLISHED,
                         _ts._TDB_T_CLAMP_LO, _ts._TDB_T_CLAMP_HI)
    return True


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, building it if needed; None if
    unavailable (callers then use their numpy paths)."""
    global _LIB
    if _LIB is False:
        return None
    if _LIB is not None:
        return _LIB
    if os.environ.get("PINT_TPU_NO_NATIVE"):
        _LIB = False
        return None
    stale = (not os.path.exists(_SO)
             or (os.path.exists(_SRC)
                 and os.path.getmtime(_SRC) > os.path.getmtime(_SO)))
    if stale and not _build():
        _LIB = False
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        _LIB = False
        return None
    if not _setup(lib):
        # symbols missing: a pre-table-injection .so slipped past the
        # mtime check (copied artifact, clock skew). One forced
        # rebuild from source, else the silent numpy fallback the
        # module docstring promises.
        lib = None
        if _build():
            try:
                lib = ctypes.CDLL(_SO)
            except OSError:
                lib = None
        if lib is None or not _setup(lib):
            _LIB = False
            return None
    _LIB = lib
    return lib


# ---- typed wrappers (None-safe callers check availability first) ----

def tdb_minus_tt(tt_day, tt_sec) -> np.ndarray | None:
    lib = get_lib()
    if lib is None:
        return None
    day = np.ascontiguousarray(tt_day, np.int64)
    sec = np.ascontiguousarray(tt_sec, np.float64)
    out = np.empty(day.shape, np.float64)
    lib.pt_tdb_minus_tt(day.size, day, sec, out)
    return out


def itrf_to_gcrs(tt_day, tt_sec, ut1_day, ut1_sec, xp, yp, itrf_xyz):
    lib = get_lib()
    if lib is None:
        return None
    n = len(tt_day)
    ttd = np.ascontiguousarray(tt_day, np.int64)
    tts = np.ascontiguousarray(tt_sec, np.float64)
    u1d = np.ascontiguousarray(ut1_day, np.int64)
    u1s = np.ascontiguousarray(ut1_sec, np.float64)
    xpa = np.ascontiguousarray(np.broadcast_to(xp, (n,)), np.float64)
    ypa = np.ascontiguousarray(np.broadcast_to(yp, (n,)), np.float64)
    itrf = np.ascontiguousarray(itrf_xyz, np.float64)
    pos = np.empty((n, 3), np.float64)
    vel = np.empty((n, 3), np.float64)
    lib.pt_itrf_to_gcrs(n, ttd, tts, u1d, u1s, xpa, ypa, itrf, pos, vel)
    return pos, vel


def cheby_posvel(et, rec, ncoef, data_type):
    lib = get_lib()
    if lib is None:
        return None
    et = np.ascontiguousarray(et, np.float64)
    rec = np.ascontiguousarray(rec, np.float64)
    n, rsize = rec.shape
    if ncoef > 32:
        return None  # C kernel stack buffer bound; numpy path handles it
    pos = np.empty((n, 3), np.float64)
    vel = np.empty((n, 3), np.float64)
    lib.pt_cheby_posvel(n, ncoef, data_type, rsize, et, rec, pos, vel)
    return pos, vel


def parse_tim_t2(data: bytes):
    """Fast-path parse of a FORMAT-1 tim buffer (native data loader;
    reference: src/pint/toa.py::read_toa_file hot loop).

    Returns ``(day, sec, freq, err, obs, flags_blob, flag_off, n_bad)``
    or ``None`` when unavailable or when the buffer needs the stateful
    Python parser (INCLUDE/TIME/EFAC/... commands, non-tempo2 lines).
    ``flags_blob``/``flag_off`` pack per-TOA flag dicts for lazy decode
    by ``pint_tpu.toa._decode_flags``.
    """
    lib = get_lib()
    if lib is None:
        return None
    nbytes = len(data)
    # the C++ parser splits on \n, \r\n, AND bare \r (python universal
    # newlines): capacity must count both terminators or bare-CR files
    # overrun the output arrays
    cap = data.count(b"\n") + data.count(b"\r") + 2
    day = np.empty(cap, np.int64)
    sec = np.empty(cap, np.float64)
    freq = np.empty(cap, np.float64)
    err = np.empty(cap, np.float64)
    obs_id = np.empty(cap, np.int32)
    obs_tab = np.empty(4096, np.uint8)
    flags_blob = np.empty(nbytes + 16 * cap + 64, np.uint8)
    flag_off = np.empty(cap + 1, np.int64)
    obs_tab_len = ctypes.c_int64(0)
    n_bad = ctypes.c_int64(0)
    n = lib.pt_parse_tim_t2(
        data, nbytes, day, sec, freq, err, obs_id, obs_tab,
        obs_tab.size, ctypes.byref(obs_tab_len), flags_blob,
        flags_blob.size, flag_off, ctypes.byref(n_bad))
    if n < 0:
        return None
    names = obs_tab[:obs_tab_len.value].tobytes().decode().split("\n")[:-1]
    obs = np.array(names, dtype=object)[obs_id[:n]] if n else \
        np.empty(0, dtype=object)
    # blob stays bytes: the offsets are byte positions, and non-ASCII
    # flag values must not shift later slices (_decode_flags decodes
    # each key/value individually)
    blob = flags_blob[:flag_off[n]].tobytes()
    return (day[:n].copy(), sec[:n].copy(), freq[:n].copy(),
            err[:n].copy(), obs, blob, flag_off[:n + 1].copy(),
            int(n_bad.value))
