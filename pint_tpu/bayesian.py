"""Bayesian timing interface: jit-compiled lnprior/lnlikelihood/
lnposterior + unit-cube prior transform.

(reference: src/pint/bayesian.py::BayesianTiming — vectorized
likelihoods for external samplers (emcee/dynesty/ultranest), optional
white-noise sampling, uniform default priors from uncertainties.)

Everything is a pure function of the free-parameter vector, built on
PreparedTiming, so one jit serves the sampler's whole ensemble via
vmap (see sampler.py).
"""

from __future__ import annotations

import math

import numpy as np

from .priors import Prior, UniformBoundedPrior


class BayesianTiming:
    """(reference: bayesian.py::BayesianTiming — same method surface:
    lnprior, lnlikelihood, lnposterior, prior_transform, nparams)."""

    def __init__(self, model, toas, use_pulse_numbers=False,
                 prior_info=None, sigma_range=10.0):
        self.model = model
        self.toas = toas
        self.prepared = model.prepare(toas)
        self.param_labels = list(model.free_params)
        self.nparams = len(self.param_labels)
        track = "use_pulse_numbers" if use_pulse_numbers else "nearest"
        self._resid_fn = self.prepared.residual_vector_fn(track_mode=track)
        self._x0 = np.asarray(self.prepared.vector_from_params())
        # priors: explicit prior_info dict > parameter .prior attribute >
        # uniform in value +/- sigma_range*uncertainty (reference default)
        self.priors: list[Prior] = []
        for i, pname in enumerate(self.param_labels):
            par = getattr(model, pname)
            if prior_info and pname in prior_info:
                info = prior_info[pname]
                if isinstance(info, Prior):
                    self.priors.append(info)
                else:
                    self.priors.append(UniformBoundedPrior(info["min"], info["max"]))
            elif getattr(par, "prior", None) is not None:
                self.priors.append(par.prior)
            elif par.uncertainty:
                half = sigma_range * par.uncertainty
                self.priors.append(
                    UniformBoundedPrior(self._x0[i] - half, self._x0[i] + half))
            else:
                raise ValueError(
                    f"no prior for {pname}: set par.prior, pass prior_info, "
                    "or fit first so uncertainties exist")
        self._lnlike_jit = None

    # ---- log densities ----

    def lnprior(self, x):
        import jax.numpy as jnp

        lp = 0.0
        for i, pr in enumerate(self.priors):
            lp = lp + pr.logpdf(x[i])
        return jnp.asarray(lp)

    def _lnlike_raw(self, x):
        import jax.numpy as jnp

        r = self._resid_fn(x)
        sigma = self.prepared.scaled_sigma_us(
            self.prepared.params_with_vector(x)) * 1e-6
        return (-0.5 * jnp.sum(jnp.square(r / sigma))
                - jnp.sum(jnp.log(sigma))
                - 0.5 * r.shape[0] * math.log(2 * math.pi))

    def lnlikelihood(self, x):
        import jax

        if self._lnlike_jit is None:
            self._lnlike_jit = jax.jit(self._lnlike_raw)
        return self._lnlike_jit(x)

    def lnposterior(self, x):
        """jit/vmap-safe: -inf prior short-circuits via where, not if."""
        import jax.numpy as jnp

        lp = self.lnprior(x)
        ll = self._lnlike_raw(x)
        return jnp.where(jnp.isfinite(lp), lp + ll, -jnp.inf)

    def prior_transform(self, u):
        """Unit cube -> parameter space for nested samplers
        (reference: bayesian.py::BayesianTiming.prior_transform)."""
        return np.array([pr.ppf(ui) for pr, ui in zip(self.priors, u)])

    # ---- conveniences ----

    def initial_position(self):
        return self._x0.copy()

    def scales(self):
        """Per-parameter walker-ball scales from uncertainties/priors."""
        out = []
        for i, pname in enumerate(self.param_labels):
            par = getattr(self.model, pname)
            if par.uncertainty:
                out.append(par.uncertainty)
            elif isinstance(self.priors[i], UniformBoundedPrior):
                out.append(0.01 * (self.priors[i].upper - self.priors[i].lower))
            else:
                out.append(max(abs(self._x0[i]) * 1e-6, 1e-12))
        return np.asarray(out)
