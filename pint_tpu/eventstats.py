"""Photon-phase periodicity statistics: H-test, Z^2_m, significances.

(reference: src/pint/eventstats.py — hm, hmw, z2m, z2mw, sf_hm,
sf_z2m, sig2sigma, h2sig.)

All statistics are pure jnp reductions over the photon-phase axis, so
they vmap/shard trivially over pulsars or energy bands — the TPU win
the reference's numpy loops can't have (SURVEY.md 3.5: 1e5-1e7 photon
phases is the natural device workload).
"""

from __future__ import annotations

import math

import numpy as np


def _jnp():
    import jax.numpy as jnp

    return jnp


import functools


@functools.lru_cache(maxsize=64)
def _stat_jit(m, weighted, stat):
    """One compiled program per (m, weighted, z2m|hm): the eager
    composition paid one dispatch round-trip PER OP, which behind a
    tunneled device (~10-90 ms each) dwarfed the kernel itself."""
    import jax
    import jax.numpy as jnp

    from .kernels import harmonic_sums

    def z_of(ph, w):
        c, s = harmonic_sums(ph, m, weights=w)
        if w is None:
            norm = ph.shape[-1] / 2.0
        else:
            norm = jnp.sum(w ** 2) / 2.0
        return jnp.cumsum((c ** 2 + s ** 2) / norm)

    if stat == "z2m":
        f = z_of
    else:
        def f(ph, w):
            k = jnp.arange(1, m + 1)
            return jnp.max(z_of(ph, w) - 4.0 * k + 4.0)

    if weighted:
        return jax.jit(f)
    return jax.jit(lambda ph: f(ph, None))


def z2m(phases, m=2):
    """Z^2_m test statistic for each harmonic count 1..m.

    Returns array [Z^2_1, ..., Z^2_m]
    (reference: eventstats.py::z2m). The harmonic sums go through the
    pallas streaming kernel on TPU at photon scale
    (pint_tpu/kernels/harmonics.py); small or CPU batches use the
    identical-math jnp path. One jitted program per (m,) — no
    per-op dispatch.
    """
    jnp = _jnp()
    return _stat_jit(int(m), False, "z2m")(jnp.asarray(phases))


def z2mw(phases, weights, m=2):
    """Weighted Z^2_m (reference: eventstats.py::z2mw)."""
    jnp = _jnp()
    return _stat_jit(int(m), True, "z2m")(jnp.asarray(phases),
                                          jnp.asarray(weights))


def hm(phases, m=20):
    """H-test statistic (de Jager, Raubenheimer & Swanepoel 1989):
    H = max_{1<=k<=m} (Z^2_k - 4k + 4)  (reference: eventstats.py::hm)."""
    jnp = _jnp()
    return _stat_jit(int(m), False, "hm")(jnp.asarray(phases))


def hmw(phases, weights, m=20):
    """Weighted H-test (reference: eventstats.py::hmw)."""
    jnp = _jnp()
    return _stat_jit(int(m), True, "hm")(jnp.asarray(phases),
                                         jnp.asarray(weights))


def sf_hm(h, logprob=False):
    """Survival function (false-alarm probability) of the H-test.

    de Jager & Busching 2010 calibration: sf = exp(-0.4 H)
    (reference: eventstats.py::sf_hm).
    """
    h = float(h)
    logsf = -0.4 * h
    return logsf if logprob else math.exp(max(logsf, -745.0))


def sf_z2m(z, m=2):
    """Survival function of Z^2_m: chi^2 with 2m dof
    (reference: eventstats.py::sf_z2m)."""
    from scipy.stats import chi2

    return float(chi2.sf(float(z), 2 * m))


def sig2sigma(sig, logprob=False):
    """One-sided survival probability -> Gaussian sigma
    (reference: eventstats.py::sig2sigma; e.g. 2.866e-7 -> 5.0).
    With logprob=True, sig is ln(prob) and the deep tail uses the
    asymptotic inversion sigma ~ sqrt(-2 ln p - ln(2 pi) - 2 ln sigma).
    """
    from scipy.stats import norm

    if logprob:
        logp = float(sig)
        if logp < -700.0:
            # fixed-point on the Gaussian tail expansion
            s = math.sqrt(-2.0 * logp)
            for _ in range(30):
                s = math.sqrt(-2.0 * (logp + math.log(s) + 0.5 * math.log(2 * math.pi)))
            return s
        sig = math.exp(logp)
    return float(norm.isf(sig))


def h2sig(h):
    """H-test statistic -> Gaussian sigma (reference: eventstats.py::h2sig).
    Routed through log-probability so huge H (bright pulsars, 1e6+
    photons) doesn't saturate at the f64 underflow floor."""
    return sig2sigma(sf_hm(h, logprob=True), logprob=True)


def hm_scan(phases_fn, f0_grid, m=20):
    """vmap an H-test over a frequency grid: phases_fn(f0) -> phases.

    TPU-native replacement for the reference's loop-over-trials in
    event searches; the whole scan is one device program.
    """
    import jax

    return jax.vmap(lambda f: hm(phases_fn(f), m=m))(np.asarray(f0_grid))
