"""Model conversion helpers: equatorial <-> ecliptic astrometry.

(reference: src/pint/modelutils.py::model_equatorial_to_ecliptic,
model_ecliptic_to_equatorial.)

The sky position and proper-motion vector are rotated by the chosen
obliquity; parameter uncertainties are propagated through the exact
Jacobian of the transform (numeric, central differences — matching the
reference's astropy-frame conversion including PM covariance rotation).
"""

from __future__ import annotations

import copy

import numpy as np

from .constants import ARCSEC_TO_RAD
from .models.astrometry import (AstrometryEcliptic, AstrometryEquatorial,
                                OBLIQUITY_ARCSEC)


def _eq_to_ecl_angles(ra, dec, eps):
    se, ce = np.sin(eps), np.cos(eps)
    x = np.cos(dec) * np.cos(ra)
    y = ce * np.cos(dec) * np.sin(ra) + se * np.sin(dec)
    z = -se * np.cos(dec) * np.sin(ra) + ce * np.sin(dec)
    return np.arctan2(y, x) % (2 * np.pi), np.arcsin(np.clip(z, -1, 1))


def _ecl_to_eq_angles(lon, lat, eps):
    se, ce = np.sin(eps), np.cos(eps)
    x = np.cos(lat) * np.cos(lon)
    y = ce * np.cos(lat) * np.sin(lon) - se * np.sin(lat)
    z = se * np.cos(lat) * np.sin(lon) + ce * np.sin(lat)
    return np.arctan2(y, x) % (2 * np.pi), np.arcsin(np.clip(z, -1, 1))


def _pm_jacobian(fwd, a, b, pma, pmb, eps):
    """Rotate (pm_a*cos b, pm_b) through the position transform by
    finite differences of the angle map."""
    h = 1e-8
    a2, b2 = fwd(a, b, eps)
    da_da, db_da = fwd(a + h / np.cos(b), b, eps)
    da_db, db_db = fwd(a, b + h, eps)

    def wrap(d):
        # difference of two angles that individually wrap at 2 pi: a
        # perturbation across the seam would otherwise read as ~2 pi
        return (d + np.pi) % (2 * np.pi) - np.pi

    # columns: unit steps along (a*cos b, b); rows: response in
    # (a2*cos b2, b2)
    J = np.array([
        [wrap(da_da - a2) * np.cos(b2) / h, wrap(da_db - a2) * np.cos(b2) / h],
        [wrap(db_da - b2) / h, wrap(db_db - b2) / h],
    ])
    pm = J @ np.array([pma, pmb])
    return pm[0], pm[1], J


def model_equatorial_to_ecliptic(model, ecl="IERS2010"):
    """(reference: modelutils.py::model_equatorial_to_ecliptic)"""
    old = model.components.get("AstrometryEquatorial")
    if old is None:
        raise ValueError("model has no AstrometryEquatorial component")
    eps = OBLIQUITY_ARCSEC.get(ecl.upper(), OBLIQUITY_ARCSEC["DEFAULT"]) * ARCSEC_TO_RAD
    out = copy.deepcopy(model)
    ra, dec = old.RAJ.value, old.DECJ.value
    lon, lat = _eq_to_ecl_angles(ra, dec, eps)
    pml, pmb, J = _pm_jacobian(_eq_to_ecl_angles, ra, dec,
                               old.PMRA.value or 0.0, old.PMDEC.value or 0.0,
                               eps)
    comp = AstrometryEcliptic()
    comp.ELONG.value = lon
    comp.ELAT.value = lat
    comp.PMELONG.value = pml
    comp.PMELAT.value = pmb
    comp.PX.value = old.PX.value
    comp.POSEPOCH.value = old.POSEPOCH.value
    comp.ECL.value = ecl.upper()
    for src, dst in (("RAJ", "ELONG"), ("DECJ", "ELAT"),
                     ("PMRA", "PMELONG"), ("PMDEC", "PMELAT"),
                     ("PX", "PX"), ("POSEPOCH", "POSEPOCH")):
        sp, dp = getattr(old, src), getattr(comp, dst)
        dp.frozen = sp.frozen
    # uncertainty propagation through the same Jacobian (angles and PM
    # rotate identically at linear order)
    if old.RAJ.uncertainty is not None or old.DECJ.uncertainty is not None:
        sa = (old.RAJ.uncertainty or 0.0) * np.cos(dec)
        sb = old.DECJ.uncertainty or 0.0
        ca = np.hypot(J[0, 0] * sa, J[0, 1] * sb)
        cb = np.hypot(J[1, 0] * sa, J[1, 1] * sb)
        comp.ELONG.uncertainty = ca / np.cos(lat)
        comp.ELAT.uncertainty = cb
    for su, du in (("PMRA", "PMELONG"), ("PMDEC", "PMELAT")):
        if getattr(old, su).uncertainty is not None:
            i = 0 if du == "PMELONG" else 1
            spm1 = getattr(old, "PMRA").uncertainty or 0.0
            spm2 = getattr(old, "PMDEC").uncertainty or 0.0
            getattr(comp, du).uncertainty = np.hypot(J[i, 0] * spm1,
                                                     J[i, 1] * spm2)
    comp.PX.uncertainty = old.PX.uncertainty
    out.remove_component("AstrometryEquatorial")
    out.add_component(comp)
    return out


def model_ecliptic_to_equatorial(model):
    """(reference: modelutils.py::model_ecliptic_to_equatorial)"""
    old = model.components.get("AstrometryEcliptic")
    if old is None:
        raise ValueError("model has no AstrometryEcliptic component")
    eps = old.obliquity_rad()
    out = copy.deepcopy(model)
    lon, lat = old.ELONG.value, old.ELAT.value
    ra, dec = _ecl_to_eq_angles(lon, lat, eps)
    pma, pmd, J = _pm_jacobian(_ecl_to_eq_angles, lon, lat,
                               old.PMELONG.value or 0.0,
                               old.PMELAT.value or 0.0, eps)
    comp = AstrometryEquatorial()
    comp.RAJ.value = ra
    comp.DECJ.value = dec
    comp.PMRA.value = pma
    comp.PMDEC.value = pmd
    comp.PX.value = old.PX.value
    comp.POSEPOCH.value = old.POSEPOCH.value
    for src, dst in (("ELONG", "RAJ"), ("ELAT", "DECJ"),
                     ("PMELONG", "PMRA"), ("PMELAT", "PMDEC"),
                     ("PX", "PX"), ("POSEPOCH", "POSEPOCH")):
        getattr(comp, dst).frozen = getattr(old, src).frozen
    if old.ELONG.uncertainty is not None or old.ELAT.uncertainty is not None:
        sa = (old.ELONG.uncertainty or 0.0) * np.cos(lat)
        sb = old.ELAT.uncertainty or 0.0
        comp.RAJ.uncertainty = np.hypot(J[0, 0] * sa, J[0, 1] * sb) / np.cos(dec)
        comp.DECJ.uncertainty = np.hypot(J[1, 0] * sa, J[1, 1] * sb)
    for i, du in ((0, "PMRA"), (1, "PMDEC")):
        s1 = old.PMELONG.uncertainty or 0.0
        s2 = old.PMELAT.uncertainty or 0.0
        if old.PMELONG.uncertainty is not None or old.PMELAT.uncertainty is not None:
            getattr(comp, du).uncertainty = np.hypot(J[i, 0] * s1, J[i, 1] * s2)
    comp.PX.uncertainty = old.PX.uncertainty
    out.remove_component("AstrometryEcliptic")
    out.add_component(comp)
    return out
