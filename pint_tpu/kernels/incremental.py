"""Incremental GLS: additive Gram deltas + rank-r factor updates.

Append-heavy TOA traffic (a handful of new arrival times per pulsar
per epoch) should not pay a full O(N K^2) repack-and-refit. The fused
augmented tile of kernels/fusedgls.py already states why it does not
have to: the GLS normal equations are ONE Gram of the whitened
augmented rows ``[X | r | winv]``, and a Gram is additive over rows.
Appending ``r_new`` rows therefore contributes

    dG = xw_new^T xw_new        ((K+2, K+2), rank <= r_new)

to the cached accumulator, so the refreshed normal matrix, RHS and
whitened residual power are pure sums::

    A' = A + dG[:K, :K]    b' = b + dG[:K, K]    rNr' = rNr + dG[K, K]

(the prior diagonal ``diag(q^2)`` of the GLS normal matrix is
row-count independent and rides along unchanged inside ``A``).

Dual path mirroring fusedgls/seggram:

- :func:`delta_gram_jnp` — bitwise-deterministic f64 jnp reference.
- :func:`delta_gram_pallas` — the f32 Pallas tile: appended rows are
  zero-padded to a sublane-aligned block (padding rows carry
  ``winv=0`` and whiten to nothing) and pushed through
  ``fused_block_gls_pallas`` as a single-block grid.
- :func:`delta_gram` dispatches; a failed Pallas dispatch falls back
  VISIBLY via kernels.fallback.note_pallas_fallback, never silently.

Parity is by CONSTRUCTION, not by tolerance: both the incremental
path and the from-scratch comparator accumulate their normal state
through the same sequential left fold over the same block partition
(:func:`fold_grams`), so after any append sequence the incremental
``(A, b, rNr)`` is *bitwise identical* to a from-scratch pass over
the concatenated rows — IEEE addition is deterministic and the two
paths perform literally the same sequence of additions. The shared
deterministic solve then maps identical state to identical
parameters, which is what lets the serve path promise "an
incremental lane never drifts from what a full refit would have
produced" (tests/test_incremental.py pins the bit-identity; the
bench's ``incremental_parity_max_rel`` <= 1e-15 budget is the
regression-gated witness).

On top of the delta sits the cached-factorization update.
:class:`IncrementalNormal` holds ``(A, b, rNr)`` plus a Cholesky
factor ``L`` of ``A``; :meth:`IncrementalNormal.append` refreshes
``L`` by a classical rank-r hyperbolic-rotation Cholesky update
(O(r K^2), no O(N) term), with a condition trigger — non-finite
entries or a collapsed diagonal ratio — that falls back to a full
refactor of the exact ``A'`` (counted in ``refactors``).
:meth:`IncrementalNormal.solve` solves the *exact* accumulated
normal equations through the updated factor plus iterative
refinement; if the refinement residual will not contract (factor too
stale/ill-conditioned) it falls back to the thresholded
``fitter.gls_eigh_solve`` — the same solver the from-scratch f64 fit
uses — so incremental parameters track a from-scratch fused refit to
the <=1e-15 f64 tier pinned in ERRORBUDGET.md.
"""

from __future__ import annotations

from .fallback import note_pallas_fallback
from .seggram import _tpu_backend

# TPU f32 tiles want the second-minor dimension in multiples of the
# sublane width; appended-row counts (typically <= 64) are padded up
# to this with winv=0 rows that whiten to zero.
_SUBLANE = 8

# refinement-residual acceptance for the factored solve: above this
# the cached factor is declared stale and the solve re-routes through
# the exact thresholded eigh (same guard philosophy as
# fitter.relres_failed on the mixed path).
_RELRES_TOL = 1e-12

# diagonal-collapse trigger for the rank-r factor update: if the
# updated factor's min/max diagonal ratio degrades below this
# fraction of the pre-update ratio, refactor from the exact A'.
_DIAG_DEGRADE = 1e-3


def pad_append_rows(X, r, winv, multiple=_SUBLANE):
    """Zero-pad appended rows up to ``multiple``. Padding rows carry
    ``winv=0`` so they whiten to zero and drop out of the Gram."""
    import jax.numpy as jnp

    X = jnp.asarray(X)
    r = jnp.asarray(r)
    winv = jnp.asarray(winv)
    n = X.shape[0]
    npad = (-n) % multiple
    if npad:
        X = jnp.pad(X, ((0, npad), (0, 0)))
        r = jnp.pad(r, (0, npad))
        winv = jnp.pad(winv, (0, npad))
    return X, r, winv


def delta_gram_jnp(X, r, winv):
    """f64 reference: (K+2, K+2) whitened Gram of the appended rows
    ``[X | r | winv]`` (same augmented layout as fusedgls)."""
    from .fusedgls import augment, fused_block_gls_jnp

    X, r, winv = pad_append_rows(X, r, winv)
    aug = augment(X, r, winv)
    return fused_block_gls_jnp(aug, aug.shape[0])[0]


def delta_gram_pallas(X, r, winv, interpret=False):
    """Pallas path: the padded appended rows as ONE fused-GLS block
    (f32 accumulate on the MXU), widened back to f64 for the additive
    update outside."""
    import jax.numpy as jnp

    from .fusedgls import augment, fused_block_gls_pallas

    X, r, winv = pad_append_rows(X, r, winv)
    aug = augment(X, r, winv)
    grams = fused_block_gls_pallas(aug, aug.shape[0],
                                   interpret=interpret)
    return grams[0].astype(jnp.float64)


def delta_gram_f32_jnp(X, r, winv):
    """f32 jnp emulation of the kernel numerics (mixed path on
    backends without Pallas), f64 widen outside — mirrors
    fusedgls.fused_segment_gls_f32_jnp."""
    import jax.numpy as jnp

    from .fusedgls import augment, fused_block_gls_jnp

    X, r, winv = pad_append_rows(X, r, winv)
    aug = augment(X, r, winv).astype(jnp.float32)
    return fused_block_gls_jnp(aug, aug.shape[0])[0].astype(jnp.float64)


def delta_gram(X, r, winv, precision="f64", interpret=False):
    """Dispatch the appended-rows Gram delta.

    ``precision="f64"`` always takes the jnp reference (the parity
    tier); ``"mixed"`` takes the Pallas tile on TPU (or anywhere
    under ``interpret=True``) and the f32 jnp emulation elsewhere.
    """
    if precision == "mixed":
        if _tpu_backend() or interpret:
            try:
                return delta_gram_pallas(X, r, winv,
                                         interpret=interpret)
            except Exception as exc:  # mosaic/version quirks
                note_pallas_fallback("incremental.delta_gram", exc)
        return delta_gram_f32_jnp(X, r, winv)
    return delta_gram_jnp(X, r, winv)


def _chol_update_impl(L, V):
    import jax
    import jax.numpy as jnp

    K = L.shape[0]
    idx = jnp.arange(K)

    def rank1(L, v):
        def body(j, carry):
            L, v = carry
            ljj = L[j, j]
            vj = v[j]
            rad = jnp.sqrt(ljj * ljj + vj * vj)
            c = rad / ljj
            s = vj / ljj
            below = idx > j
            col = L[:, j]
            newcol = jnp.where(below, (col + s * v) / c, col)
            newcol = newcol.at[j].set(rad)
            L = L.at[:, j].set(newcol)
            v = jnp.where(below, c * v - s * newcol, v)
            return L, v

        L, _ = jax.lax.fori_loop(0, K, body, (L, v))
        return L, None

    L, _ = jax.lax.scan(rank1, L, V.T)
    return L


# module-level jit handle: chol_update sits on the per-append hot
# path, and tracing through the scan-of-fori control flow costs
# ~100 ms per call — orders of magnitude more than the O(r K^2)
# update itself. A single cached jit (trace keyed on the stable
# module-level impl + shapes) makes repeat appends pay only the
# compiled kernel.
_chol_update_jit = None


def chol_update(L, V):
    """Rank-r Cholesky update: returns ``L'`` with
    ``L' L'^T = L L^T + V V^T`` via r sequential rank-1 updates
    (Givens-style, Golub & Van Loan sec. 12.5). ``L`` (K, K) lower
    triangular, ``V`` (K, r). O(r K^2); never touches the N rows."""
    global _chol_update_jit
    import jax
    import jax.numpy as jnp

    if _chol_update_jit is None:
        _chol_update_jit = jax.jit(_chol_update_impl)
    return _chol_update_jit(jnp.asarray(L), jnp.asarray(V))


def _chol_solve(L, b):
    """Two triangular solves through the cached factor."""
    import jax.scipy.linalg as jsl

    y = jsl.solve_triangular(L, b, lower=True)
    return jsl.solve_triangular(L.T, y, lower=False)


class IncrementalNormal:
    """Cached GLS normal state ``(A0, b, rNr, L)`` under row appends.

    ``A0`` is the accumulated design Gram WITHOUT the prior diagonal;
    ``q`` holds the prior weights and ``diag(q^2)`` is applied once,
    at factor/solve time. Keeping the prior out of the accumulator is
    what preserves bit-identity with the from-scratch fold: the
    incremental path then computes ``(fold(base) + d1 + d2) +
    diag(q^2)`` — the exact addition sequence the scratch path
    performs — instead of ``(fold(base) + diag(q^2)) + d1 + d2``.

    ``L`` is the lower Cholesky factor of the full normal matrix,
    refreshed per append by the rank-r update with a
    condition-triggered full refactor. The exact accumulators are
    always carried alongside the factor, so a refactor (or the eigh
    fallback in :meth:`solve`) never loses information — the factor
    is an accelerator, not the truth.
    """

    def __init__(self, A0, b, rNr, q=None):
        import jax.numpy as jnp

        self.A0 = jnp.asarray(A0, jnp.float64)
        self.b = jnp.asarray(b, jnp.float64)
        self.rNr = jnp.asarray(rNr, jnp.float64)
        k = self.A0.shape[0]
        if q is None:
            q = jnp.zeros(k, jnp.float64)
        self.q = jnp.asarray(q, jnp.float64)
        self.n_appended = 0
        self.appends = 0
        self.refactors = 0
        self.L = self._refactor()

    @property
    def A(self):
        """The full normal matrix (prior applied once, here)."""
        import jax.numpy as jnp

        return self.A0 + jnp.diag(self.q * self.q)

    def _refactor(self):
        import jax.numpy as jnp

        return jnp.linalg.cholesky(self.A)

    @staticmethod
    def _diag_ratio(L):
        import jax.numpy as jnp

        d = jnp.abs(jnp.diag(L))
        return float(jnp.min(d) / jnp.max(d))

    def append(self, X, r, winv, precision="f64", interpret=False):
        """Fold appended rows in: additive Gram delta on the exact
        accumulators, rank-r update on the factor. Returns the
        (K+2, K+2) Gram delta (callers reuse it for residual-delta
        consumers, e.g. the GW lattice)."""
        import jax.numpy as jnp

        k = self.A0.shape[0]
        G = delta_gram(X, r, winv, precision=precision,
                       interpret=interpret)
        self.A0 = self.A0 + G[:k, :k]
        self.b = self.b + G[:k, k]
        self.rNr = self.rNr + G[k, k]
        if self.L is None:
            # a previous append left no usable factor (eigh regime);
            # try a fresh factorization of the exact updated A before
            # giving up on the fast path again
            L = self._refactor()
            self.refactors += 1
            if not bool(jnp.all(jnp.isfinite(L))):
                L = None
            self.L = L
            self.n_appended += int(X.shape[0])
            self.appends += 1
            return G
        before = self._diag_ratio(self.L)
        # the factor update needs the whitened rows themselves, not
        # the Gram: dA = V V^T with V the (K, r) whitened design
        Xp, rp, wp = pad_append_rows(X, r, winv)
        V = (jnp.asarray(Xp, jnp.float64) * wp[:, None]).T
        L = chol_update(self.L, V)
        after = self._diag_ratio(L)
        degraded = (not jnp.all(jnp.isfinite(L))
                    or after < _DIAG_DEGRADE * before)
        if degraded:
            L = self._refactor()
            self.refactors += 1
            if not bool(jnp.all(jnp.isfinite(L))):
                # exact A' itself is not SPD-factorable — the eigh
                # fallback in solve() owns this regime
                L = None
        self.L = L
        self.n_appended += int(X.shape[0])
        self.appends += 1
        return G

    def solve(self, threshold=1e-12, refine=2):
        """Solve the accumulated normal equations.

        Fast path: triangular solves through the updated factor plus
        ``refine`` iterative-refinement sweeps against the exact
        ``A`` (each contracts the error by ~eps * kappa, recovering
        full f64 accuracy from the drifting factor). If the final
        relative residual exceeds the acceptance tol — stale or
        indefinite factor — fall back to ``fitter.gls_eigh_solve``
        on the exact accumulators, the identical solver the
        from-scratch f64 fit uses. Returns ``(dx, chi2, info)``.
        """
        import jax.numpy as jnp

        from ..fitter import gls_eigh_solve

        A = self.A
        dx = None
        relres = float("inf")
        if self.L is not None:
            dx = _chol_solve(self.L, self.b)
            for _ in range(refine):
                dx = dx + _chol_solve(self.L, self.b - A @ dx)
            bnorm = float(jnp.linalg.norm(self.b))
            resid = float(jnp.linalg.norm(self.b - A @ dx))
            relres = resid / bnorm if bnorm > 0 else resid
        solver = "chol_update"
        if dx is None or not bool(jnp.all(jnp.isfinite(dx))) \
                or not relres <= _RELRES_TOL:
            dx, _ = gls_eigh_solve(A, self.b, threshold=threshold)
            solver = "eigh_refresh"
        chi2 = float(self.rNr) - float(self.b @ dx)
        return dx, chi2, {"solver": solver, "relres": relres,
                          "refactors": self.refactors,
                          "appends": self.appends,
                          "n_appended": self.n_appended}


def block_grams(X, r, winv, block):
    """(nb, K+2, K+2) fused per-block Grams over rows padded (with
    winv=0) to a ``block`` multiple — the canonical partition both
    the incremental base state and the from-scratch comparator fold
    over, so their additions associate identically."""
    from .fusedgls import augment, fused_block_gls_jnp

    X, r, winv = pad_append_rows(X, r, winv, multiple=block)
    return fused_block_gls_jnp(augment(X, r, winv), block)


def fold_grams(grams):
    """Sequential LEFT fold of per-block Grams. This is the single
    accumulation-order authority for the bit-identity contract: a
    left fold over ``[base blocks..., d1, d2, ...]`` performs the
    exact addition sequence ``((fold(base) + d1) + d2) + ...`` that
    per-append delta application performs, so a tree-shaped
    ``jnp.sum`` (whose association depends on XLA's reduction
    schedule) must never replace it."""
    import jax

    def add(acc, g):
        return acc + g, None

    G, _ = jax.lax.scan(add, grams[0], grams[1:])
    return G


def scratch_normal(chunks, block):
    """From-scratch fused comparator over ``chunks`` — a list of
    ``(X, r, winv)`` row groups: the base tile first, then one chunk
    per append in arrival order. The base chunk streams through the
    fused tile at ``block`` granularity; each append chunk is its
    own sublane-padded block (exactly what :func:`delta_gram`
    computed at append time). Returns ``(A0, b0, rNr)`` WITHOUT the
    prior diagonal — callers add ``diag(q^2)`` themselves, matching
    the incremental path's base state."""
    import jax.numpy as jnp

    base = chunks[0]
    grams = [block_grams(*base, block=block)]
    for X, r, winv in chunks[1:]:
        X, r, winv = pad_append_rows(X, r, winv)
        grams.append(block_grams(X, r, winv, block=X.shape[0]))
    G = fold_grams(jnp.concatenate(grams, axis=0))
    k = base[0].shape[1]
    return G[:k, :k], G[:k, k], G[k, k]


def build_normal(X, r, winv, q, block=1024):
    """Build the cached :class:`IncrementalNormal` base state from a
    full row set: fused per-block Grams, left-folded, prior diagonal
    ``diag(q^2)`` added once. Appends then ride on
    :meth:`IncrementalNormal.append`."""
    G = fold_grams(block_grams(X, r, winv, block=block))
    k = X.shape[1]
    return IncrementalNormal(G[:k, :k], G[:k, k], G[k, k], q=q)


def scratch_refit(chunks, q, block=1024, threshold=1e-12, refine=2):
    """The full from-scratch refit the incremental path must be
    bit-identical to: :func:`scratch_normal` over all chunks, prior
    diagonal, the SAME deterministic solve. This is also what a
    drift-triggered lane escalation runs."""
    A0, b0, rNr = scratch_normal(chunks, block)
    state = IncrementalNormal(A0, b0, rNr, q=q)
    dx, chi2, info = state.solve(threshold=threshold, refine=refine)
    return dx, chi2, state, info
