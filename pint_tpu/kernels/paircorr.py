"""Pair-block cross-correlation products for the GW detection stage.

The Hellings–Downs optimal statistic (pint_tpu/gw/) needs, for every
pulsar pair (a, b), the weighted zero-lag cross products over a common
epoch lattice:

    num_ab = sum_m U_a[m] U_b[m]      with U = W * z (weighted resid)
    den_ab = sum_m W_a[m] W_b[m]      (pair weight / inverse variance)

Over a (B_a, M) x (B_b, M) block of pulsars both are plain matmuls —
``U_a @ U_b^T`` and ``W_a @ W_b^T`` — which is why the O(P^2) pair
sweep (~4.5M pairs at 3000 pulsars) is a dense batched-matmul workload
and the natural TPU fit. The streaming block accumulator lives in
gw/correlate.py; this module owns the per-block-pair compute.

Dual path mirroring kernels/seggram.py: a jnp reference (f64 — the
batched-vs-sequential <=1e-12 parity contract in tests/test_gw.py
rides on it) and a Pallas TPU kernel that tiles the A-side rows
through VMEM and feeds both products to the MXU in one grid step
(f32; acceptable where the pair statistic is later calibrated against
scrambled nulls rather than read at f64 precision). ``pair_products``
dispatches; non-TPU backends and f64 calls take the jnp path, and
Pallas failures are routed through kernels.fallback so a fleet never
silently pins to the reference path.
"""

from __future__ import annotations

import functools

_LANE = 128     # MXU/VPU lane width: the lattice axis pads to this
_SUBLANE = 8    # f32 sublane tile: pulsar-block rows pad to this


def pair_products_jnp(ua, wa, ub, wb):
    """Reference path: (B_a, M) x (B_b, M) -> two (B_a, B_b) products
    in the input dtype (f64 in the parity-pinned sweep)."""
    import jax.numpy as jnp

    ua, wa = jnp.asarray(ua), jnp.asarray(wa)
    ub, wb = jnp.asarray(ub), jnp.asarray(wb)
    return ua @ ub.T, wa @ wb.T


def _kernel(ua_ref, wa_ref, ub_ref, wb_ref, num_ref, den_ref):
    """One grid step: one A-side row tile against the whole B block —
    both pair products on the MXU."""
    import jax.numpy as jnp

    num_ref[:] = jnp.dot(ua_ref[:], ub_ref[:].T,
                         preferred_element_type=jnp.float32)
    den_ref[:] = jnp.dot(wa_ref[:], wb_ref[:].T,
                         preferred_element_type=jnp.float32)


def _pad2(x, rows, cols):
    import jax.numpy as jnp

    r, c = x.shape
    if r == rows and c == cols:
        return x
    return jnp.pad(x, ((0, rows - r), (0, cols - c)))


def pair_products_pallas(ua, wa, ub, wb, tile=128, interpret=False):
    """Pallas path: f32 pair products, lattice axis padded to the
    lane width, A-side rows streamed through VMEM in ``tile``-row
    grid steps. Returns two (B_a, B_b) f32 arrays."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    ua = jnp.asarray(ua, jnp.float32)
    wa = jnp.asarray(wa, jnp.float32)
    ub = jnp.asarray(ub, jnp.float32)
    wb = jnp.asarray(wb, jnp.float32)
    ba, m = ua.shape
    bb = ub.shape[0]
    mpad = -(-m // _LANE) * _LANE
    tile = max(_SUBLANE, min(tile, -(-ba // _SUBLANE) * _SUBLANE))
    apad = -(-ba // tile) * tile
    bpad = -(-bb // _LANE) * _LANE
    ua, wa = _pad2(ua, apad, mpad), _pad2(wa, apad, mpad)
    ub, wb = _pad2(ub, bpad, mpad), _pad2(wb, bpad, mpad)
    grid = (apad // tile,)
    num, den = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, mpad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, mpad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bpad, mpad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bpad, mpad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile, bpad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, bpad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((apad, bpad), jnp.float32),
            jax.ShapeDtypeStruct((apad, bpad), jnp.float32),
        ],
        interpret=interpret,
    )(ua, wa, ub, wb)
    return num[:ba, :bb], den[:ba, :bb]


@functools.lru_cache(maxsize=1)
def _tpu_backend():
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def pair_products(ua, wa, ub, wb, precision="f64", interpret=False):
    """Dispatch one pair-block's (num, den) products: the Pallas MXU
    kernel when f32 products are acceptable (``precision="mixed"``)
    on TPU — or anywhere under ``interpret=True``, which is how the
    CPU test tier exercises the exact kernel body — and the f64 jnp
    reference otherwise."""
    if precision == "mixed" and (_tpu_backend() or interpret):
        try:
            return pair_products_pallas(ua, wa, ub, wb,
                                        interpret=interpret)
        except Exception as exc:  # mosaic/version quirks
            from .fallback import note_pallas_fallback

            note_pallas_fallback("paircorr.pair_products", exc)
    return pair_products_jnp(ua, wa, ub, wb)
