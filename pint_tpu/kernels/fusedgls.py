"""Fused whiten -> Gram -> RHS segment kernel for the packed GLS fit.

The packed GLS normal equations (parallel/pta.py::_build_gls_packed)
used to make three separate reduction passes over each packed row:
the per-segment block Gram ``A0`` (kernels/seggram.py), the
right-hand side ``b0 = segment_sum(Mn * z)``, and the whitened
residual power ``rNr = segment_sum(z^2)``. This module fuses all
three into ONE streamed pass by augmenting the design tile with two
extra columns:

    aug = [ X | r | winv ]          (n, K + 2)

where X is the column-normalized design block, r the residual and
``winv = 1/sigma`` the per-TOA error weight. Each block tile is
whitened in-registers by its error column (``xw = aug * winv_col`` —
every column, including r, picks up the 1/sigma weight) and a single
Gram of the whitened tile is accumulated:

    G = xw^T xw = [[ Mn^T Mn,  Mn^T z,  . ],
                   [  z^T Mn,   z^T z,  . ],
                   [     .,        .,   . ]]

so ``A0 = G[:K, :K]``, ``b0 = G[:K, K]`` and ``rNr = G[K, K]`` fall
out of one product; the winv^2 row/column is garbage and sliced off.
The row data is read from HBM once instead of three times, and the
two extra columns are free on TPU (K pads to the 128 lane width
either way).

Dual path mirroring seggram/harmonics:

- :func:`fused_segment_gls_jnp` — the bitwise-deterministic f64 jnp
  reference (the CPU production path; same block factorization and
  reduction order every call).
- :func:`fused_block_gls_pallas` / :func:`fused_segment_gls_pallas`
  — the f32 Pallas TPU kernel: one (Q, K+2) tile HBM -> VMEM per
  grid step, whiten on the VPU, Gram + RHS on the MXU with f32
  accumulation. f32 RHS/rNr are *not* accurate enough for the 1e-15
  packed-vs-sequential contract, so the mixed-precision caller keeps
  the exact f64 RHS and hands A0 to fitter.seg_gls_eigh_refine as
  the preconditioner (ERRORBUDGET.md precision tiers).
- :func:`fused_segment_gls_f32_jnp` — f32 jnp emulation of the
  kernel numerics, the mixed-precision path on backends without
  Pallas (lets CI exercise the mixed packed fit on CPU).

``fused_segment_gls`` dispatches; a failed Pallas dispatch falls
back to the emulation VISIBLY via kernels.fallback (obs counter +
flight note + one log line), never silently.
"""

from __future__ import annotations

import functools

from .fallback import note_pallas_fallback
from .seggram import _LANE, _tpu_backend


def augment(X, r, winv):
    """Stack the fused tile ``[X | r | winv]`` (n, K+2)."""
    import jax.numpy as jnp

    return jnp.concatenate(
        [X, r[:, None], winv[:, None]], axis=1)


def fused_block_gls_jnp(aug, block):
    """(n, K+2) augmented rows -> (n/block, K+2, K+2) whitened
    per-block Grams; dtype follows ``aug`` (f64 reference)."""
    import jax.numpy as jnp

    aug = jnp.asarray(aug)
    n, ka = aug.shape
    xw = aug * aug[:, -1:]
    xb = xw.reshape(n // block, block, ka)
    return jnp.einsum("nbk,nbl->nkl", xb, xb)


def _slice_out(G, k):
    """(S, K+2, K+2) segment Grams -> (A0, b0, rNr)."""
    return G[:, :k, :k], G[:, :k, k], G[:, k, k]


def fused_segment_gls_jnp(X, r, winv, block_seg, n_seg, block):
    """Reference path: one fused pass in f64.

    X: (n, K) column-normalized design rows, n a multiple of
    ``block``; r/winv: (n,) residual and 1/sigma columns.
    block_seg: (n/block,) int segment id per block.
    Returns (A0 (n_seg, K, K), b0 (n_seg, K), rNr (n_seg,)).
    """
    import jax

    grams = fused_block_gls_jnp(augment(X, r, winv), block)
    G = jax.ops.segment_sum(grams, block_seg, num_segments=n_seg)
    return _slice_out(G, X.shape[1])


def _kernel(wcol, bk_ref, out_ref):
    """One grid step: whiten one (block, K+2) tile by its error
    column on the VPU, Gram + RHS on the MXU."""
    import jax.numpy as jnp

    x = bk_ref[:]
    w = x[:, wcol:wcol + 1]
    xw = x * w
    out_ref[:] = jnp.dot(xw.T, xw, preferred_element_type=jnp.float32)


def fused_block_gls_pallas(aug, block, interpret=False):
    """Pallas path: whitened per-block Grams in f32, columns padded
    to the lane width. Returns (n/block, K+2, K+2) f32; the segment
    reduction stays outside (cheap, f64-capable)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x = jnp.asarray(aug, jnp.float32)
    n, ka = x.shape
    nb = n // block
    kpad = -(-ka // _LANE) * _LANE
    if kpad != ka:
        # zero pad: padded columns whiten to zero and never reach the
        # sliced (ka, ka) output
        x = jnp.pad(x, ((0, 0), (0, kpad - ka)))
    out = pl.pallas_call(
        functools.partial(_kernel, ka - 1),
        grid=(nb,),
        in_specs=[pl.BlockSpec((block, kpad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((kpad, kpad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * kpad, kpad), jnp.float32),
        interpret=interpret,
    )(x)
    return out.reshape(nb, kpad, kpad)[:, :ka, :ka]


def fused_segment_gls_pallas(X, r, winv, block_seg, n_seg, block,
                             interpret=False):
    """Pallas fused pass + f64 segment reduction."""
    import jax
    import jax.numpy as jnp

    grams = fused_block_gls_pallas(augment(X, r, winv), block,
                                   interpret=interpret)
    G = jax.ops.segment_sum(grams.astype(jnp.float64), block_seg,
                            num_segments=n_seg)
    return _slice_out(G, X.shape[1])


def fused_segment_gls_f32_jnp(X, r, winv, block_seg, n_seg, block):
    """f32 jnp emulation of the kernel numerics: same whiten + block
    Gram in f32, f64 segment reduction. The mixed-precision packed
    fit runs this on backends without Pallas so the refinement path
    is exercised (and CI-testable) everywhere."""
    import jax
    import jax.numpy as jnp

    aug = augment(X, r, winv).astype(jnp.float32)
    grams = fused_block_gls_jnp(aug, block)
    G = jax.ops.segment_sum(grams.astype(jnp.float64), block_seg,
                            num_segments=n_seg)
    return _slice_out(G, X.shape[1])


def fused_segment_gls(X, r, winv, block_seg, n_seg, block,
                      precision="f64", interpret=False):
    """Dispatch the fused whiten+Gram+RHS pass.

    ``precision="f64"`` always takes the jnp reference (bitwise
    deterministic, the packed-vs-sequential contract). ``"mixed"``
    takes the Pallas kernel on TPU (or anywhere under
    ``interpret=True``) and the f32 jnp emulation elsewhere; the
    caller is responsible for recovering f64 accuracy by refinement
    (fitter.seg_gls_eigh_refine) and for using an exact f64 RHS.
    """
    if precision == "mixed":
        if _tpu_backend() or interpret:
            try:
                return fused_segment_gls_pallas(
                    X, r, winv, block_seg, n_seg, block,
                    interpret=interpret)
            except Exception as exc:  # mosaic/version quirks
                note_pallas_fallback("fusedgls.fused_segment_gls", exc)
        return fused_segment_gls_f32_jnp(X, r, winv, block_seg, n_seg,
                                         block)
    return fused_segment_gls_jnp(X, r, winv, block_seg, n_seg, block)
