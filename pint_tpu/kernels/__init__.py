"""Pallas TPU kernels for the hot ops, each with a jnp mirror.

(SURVEY.md 7.1 "Pallas: only if profiling shows need" — the photon
harmonic-sum reduction is the one op where streaming beats XLA's
materialize-then-reduce; everything else fuses fine.)
"""

from .fallback import note_pallas_fallback  # noqa: F401
from .fusedgls import (fused_segment_gls,  # noqa: F401
                       fused_segment_gls_jnp, fused_segment_gls_pallas)
from .harmonics import (harmonic_sums, harmonic_sums_jnp,  # noqa: F401
                        harmonic_sums_pallas)
from .paircorr import (pair_products, pair_products_jnp,  # noqa: F401
                       pair_products_pallas)
from .seggram import (segment_gram, segment_gram_jnp,  # noqa: F401
                      segment_gram_pallas)
