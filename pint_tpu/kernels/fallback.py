"""Visible accounting for Pallas-kernel fallbacks.

Every kernel in this package is dual-path: an f32 Pallas TPU kernel
and a deterministic jnp reference. When the Pallas dispatch fails
(mosaic/version quirks, a missing lowering on the running backend)
the dispatcher falls back to the jnp path — which is *correct* but
slow, and a fleet silently pinned to it would look healthy in every
fit-quality probe while quietly losing its MXU throughput. This
module makes the event observable three ways:

- the ``kernels.pallas_fallbacks`` counter in ``obs.REGISTRY``
  (scraped by the metrics exposition and the bench obs stage),
- a flight-recorder note carrying the kernel name and exception
  (so post-incident dumps name the kernel that degraded), and
- one ``logging`` warning per (kernel, exception type) — the first
  failure is loud, the per-batch repeat storm is not.

The pintlint ``kernel-silent-fallback`` rule enforces that kernel
dispatchers route through :func:`note_pallas_fallback` instead of a
bare ``except Exception: pass``.
"""

from __future__ import annotations

import logging
import threading

_LOG = logging.getLogger(__name__)
_LOCK = threading.Lock()
_warned_keys: set = set()

COUNTER_NAME = "kernels.pallas_fallbacks"


def note_pallas_fallback(kernel, exc):
    """Record one Pallas->jnp fallback for ``kernel`` caused by
    ``exc``: bump the obs counter, leave a flight-recorder note, and
    warn once per (kernel, exception type)."""
    reason = f"{type(exc).__name__}: {exc}"
    try:
        from ..obs import RECORDER, REGISTRY

        REGISTRY.counter(COUNTER_NAME).inc()
        RECORDER.note("pallas_fallback", kernel=str(kernel),
                      reason=reason[:300])
    except Exception:
        # observability must never take down the math path it watches
        _LOG.debug("pallas fallback accounting failed", exc_info=True)
    key = (str(kernel), type(exc).__name__)
    with _LOCK:
        first = key not in _warned_keys
        _warned_keys.add(key)
    if first:
        _LOG.warning(
            "Pallas kernel %r fell back to its jnp reference path: %s "
            "(further identical fallbacks counted in %s, not logged)",
            kernel, reason, COUNTER_NAME)


def reset_warned_for_tests():
    """Clear the warn-once memory (test isolation only)."""
    with _LOCK:
        _warned_keys.clear()
