"""Pallas TPU kernel: streaming harmonic sums over photon phases.

The H-test / Z^2_m hot loop (reference: src/pint/eventstats.py::hm/
z2m over 1e5-1e7 photon phases, SURVEY.md 3.5) needs, for harmonics
k = 1..m:

    C_k = sum_i w_i cos(2 pi k phi_i)      S_k = sum_i w_i sin(...)

The naive jnp expression materializes an (m, n) intermediate in HBM
(20x the photon array) before reducing; this kernel streams photon
blocks HBM -> VMEM once and accumulates all 2m sums on-chip, using the
Chebyshev recurrence cos(k t) = 2 cos t cos((k-1)t) - cos((k-2)t) so
each block pays two transcendentals instead of 2m.

Test statistics tolerate f32 phase precision (a phase error of 1e-6
turns perturbs H by ~1e-4); the final cross-lane reduction happens in
f64 on the host side of the call. Non-TPU backends and small batches
use the plain jnp path (identical math, f64) — the kernel is a
performance mirror, verified against it by tests/test_kernels.py.
"""

from __future__ import annotations

import functools

import numpy as np

_BLOCK_ROWS = 64  # photons per grid step = _BLOCK_ROWS * 128


def harmonic_sums_jnp(phases, m, weights=None):
    """Reference jnp path: (C[1..m], S[1..m]) in f64."""
    import jax.numpy as jnp

    ph = jnp.asarray(phases, jnp.float64) * (2.0 * jnp.pi)
    k = jnp.arange(1, m + 1, dtype=jnp.float64)[:, None]
    w = None if weights is None else jnp.asarray(weights, jnp.float64)
    ck = jnp.cos(k * ph[None, :])
    sk = jnp.sin(k * ph[None, :])
    if w is not None:
        ck = ck * w[None, :]
        sk = sk * w[None, :]
    return jnp.sum(ck, axis=-1), jnp.sum(sk, axis=-1)


def _kernel(m, ph_ref, w_ref, c_out, s_out, cacc, sacc):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        cacc[:] = jnp.zeros_like(cacc)
        sacc[:] = jnp.zeros_like(sacc)

    theta = ph_ref[:] * np.float32(2.0 * np.pi)
    w = w_ref[:]
    c1 = jnp.cos(theta)
    s1 = jnp.sin(theta)
    # Chebyshev three-term recurrence over harmonics; k loop unrolled
    # (m is a static python int), all VPU elementwise work
    ckm2 = jnp.ones_like(c1)   # cos(0 t)
    skm2 = jnp.zeros_like(s1)  # sin(0 t)
    ck, sk = c1, s1
    two_c1 = 2.0 * c1
    for k in range(1, m + 1):
        cacc[k - 1, :] += jnp.sum(w * ck, axis=0)
        sacc[k - 1, :] += jnp.sum(w * sk, axis=0)
        ck_next = two_c1 * ck - ckm2
        sk_next = two_c1 * sk - skm2
        ckm2, skm2 = ck, sk
        ck, sk = ck_next, sk_next

    @pl.when(step == pl.num_programs(0) - 1)
    def _emit():
        c_out[:] = cacc[:]
        s_out[:] = sacc[:]


def harmonic_sums_pallas(phases, m, weights=None, interpret=False):
    """Pallas path; returns (C[1..m], S[1..m]) as f64 jnp arrays."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block = _BLOCK_ROWS * 128
    ph = jnp.asarray(phases, jnp.float32).ravel()
    n = ph.shape[0]
    nblocks = max(1, -(-n // block))
    npad = nblocks * block - n
    # padded photons carry weight 0, so they vanish from every sum
    w = (jnp.ones(n, jnp.float32) if weights is None
         else jnp.asarray(weights, jnp.float32))
    ph = jnp.pad(ph, (0, npad))
    w = jnp.pad(w, (0, npad))
    ph2 = ph.reshape(nblocks * _BLOCK_ROWS, 128)
    w2 = w.reshape(nblocks * _BLOCK_ROWS, 128)

    m_pad = -(-m // 8) * 8  # sublane-aligned scratch/output

    c_part, s_part = pl.pallas_call(
        functools.partial(_kernel, m),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((m_pad, 128), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m_pad, 128), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((m_pad, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((m_pad, 128), jnp.float32),
            pltpu.VMEM((m_pad, 128), jnp.float32),
        ],
        interpret=interpret,
    )(ph2, w2)
    # cross-lane reduction in f64 (cheap: m x 128)
    c = jnp.sum(c_part[:m].astype(jnp.float64), axis=-1)
    s = jnp.sum(s_part[:m].astype(jnp.float64), axis=-1)
    return c, s


def _tpu_backend():
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def harmonic_sums(phases, m, weights=None):
    """Dispatch: pallas kernel on TPU for large photon batches, jnp
    elsewhere. Both return (C[1..m], S[1..m]) in f64."""
    import jax.numpy as jnp

    ph = jnp.asarray(phases)
    n = ph.size
    # 1-D only: the kernel ravels, so batched inputs must keep the
    # jnp path's per-axis semantics rather than silently co-adding
    if ph.ndim == 1 and n >= (1 << 16) and _tpu_backend():
        try:
            return harmonic_sums_pallas(phases, m, weights=weights)
        except Exception as exc:  # mosaic/version quirks
            from .fallback import note_pallas_fallback

            note_pallas_fallback("harmonics.harmonic_sums", exc)
    return harmonic_sums_jnp(phases, m, weights=weights)
