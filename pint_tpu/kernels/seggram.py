"""Segment-summed Gram matrices for packed ragged batches.

The shape-plan packed path (parallel/shapeplan.py, parallel/pta.py)
concatenates several pulsars into one padded row, each occupying a
contiguous quantum-aligned *segment* of blocks. The GLS normal matrix
must then be accumulated per segment:

    A_s = sum_{t in segment s} M[t]^T M[t]        (K x K per segment)

A naive per-TOA ``segment_sum`` of outer products materializes an
(n, K, K) intermediate — ~1 GB at the 670k scale. Because segments
are block-aligned, the sum factorizes: reshape rows into (n/Q, Q, K)
blocks, take one (Q, K)^T (Q, K) matmul per block (the same FLOPs as
the unsegmented Gram), and segment-sum the (n/Q, K, K) block Grams —
a ~Q-fold smaller intermediate.

Dual path mirroring kernels/harmonics.py: a jnp reference (f64, used
by the packed GLS fit — bitwise determinism matters there) and a
Pallas TPU kernel that streams blocks HBM -> VMEM and feeds the MXU
directly (f32; for mixed-precision Gram work on TPU where the fit
already tolerates f32 block products). ``segment_gram`` dispatches;
non-TPU backends and f64 calls always take the jnp path.
"""

from __future__ import annotations

import functools

import numpy as np

_LANE = 128  # MXU/VPU lane width: K tiles round up to this


def block_grams_jnp(x, block):
    """(n, K) rows -> (n/block, K, K) per-block Grams, f64."""
    import jax.numpy as jnp

    x = jnp.asarray(x)
    n, k = x.shape
    nb = n // block
    xb = x.reshape(nb, block, k)
    return jnp.einsum("nbk,nbl->nkl", xb, xb)


def segment_gram_jnp(x, block_seg, n_seg, block):
    """Reference path: per-segment Grams via block factorization.

    x: (n, K) rows, n a multiple of ``block``.
    block_seg: (n/block,) int segment id per block.
    Returns (n_seg, K, K) in x's dtype (f64 in the packed fit).
    """
    import jax

    grams = block_grams_jnp(x, block)
    return jax.ops.segment_sum(grams, block_seg, num_segments=n_seg)


def _kernel(bk_ref, out_ref):
    """One grid step: Gram of one (block, K) tile on the MXU."""
    import jax.numpy as jnp

    x = bk_ref[:]
    out_ref[:] = jnp.dot(x.T, x, preferred_element_type=jnp.float32)


def block_grams_pallas(x, block, interpret=False):
    """Pallas path: per-block Grams in f32, K padded to the lane
    width. Returns (n/block, K, K) f32; the segment reduction stays
    outside (cheap, f64-capable)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x = jnp.asarray(x, jnp.float32)
    n, k = x.shape
    nb = n // block
    kpad = -(-k // _LANE) * _LANE
    if kpad != k:
        x = jnp.pad(x, ((0, 0), (0, kpad - k)))
    out = pl.pallas_call(
        _kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec((block, kpad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((kpad, kpad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nb * kpad, kpad), jnp.float32),
        interpret=interpret,
    )(x)
    return out.reshape(nb, kpad, kpad)[:, :k, :k]


def segment_gram_pallas(x, block_seg, n_seg, block, interpret=False):
    """Pallas block Grams + f64 segment reduction (n/block x K x K,
    small next to the row data)."""
    import jax
    import jax.numpy as jnp

    grams = block_grams_pallas(x, block, interpret=interpret)
    return jax.ops.segment_sum(grams.astype(jnp.float64), block_seg,
                               num_segments=n_seg)


@functools.lru_cache(maxsize=1)
def _tpu_backend():
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def segment_gram(x, block_seg, n_seg, block, precision="f64"):
    """Dispatch: Pallas kernel on TPU when f32 block products are
    acceptable (``precision="mixed"``), jnp otherwise. The fused GLS
    path (kernels/fusedgls.py) owns the mixed packed fit; this entry
    still serves the ECORR downdate Grams and any direct callers,
    verified against the reference by tests/test_shapeplan.py."""
    if precision == "mixed" and _tpu_backend():
        try:
            return segment_gram_pallas(x, block_seg, n_seg, block)
        except Exception as exc:  # mosaic/version quirks
            from .fallback import note_pallas_fallback

            note_pallas_fallback("seggram.segment_gram", exc)
    return segment_gram_jnp(x, block_seg, n_seg, block)
