"""Derived astrophysical quantities from timing parameters.

(reference: src/pint/derived_quantities.py — mass_function,
companion_mass, pulsar_mass, pulsar_age, pulsar_B, pulsar_B_lightcyl,
pulsar_edot, omdot, gamma, pbdot, shklovskii_factor, dispersion_slope,
p_to_f / pferrs.)

No astropy here: arguments are plain floats/arrays in documented units
so every function is jax-transformable (the reference wraps the same
closed-form expressions in astropy Quantities).
"""

from __future__ import annotations

import math

from .constants import (
    AU_M,
    C_M_S,
    DMconst,
    MASYR_TO_RADS,
    PC_M,
    SECS_PER_DAY,
    SECS_PER_JULIAN_YEAR,
    TSUN_S,
)

_TWO_PI = 2.0 * math.pi
# moment of inertia 1e45 g cm^2 = 1e38 kg m^2 (reference convention)
_I_NS_SI = 1.0e38


def _sqrt(x):
    """sqrt that follows the argument's world: jnp for jax values
    (tracer-safe), np otherwise (negative -> nan, never complex)."""
    if type(x).__module__.startswith("jax"):
        import jax.numpy as jnp

        return jnp.sqrt(x)
    import numpy as np

    return np.sqrt(x)


def p_to_f(p, pd, pdd=None):
    """(P [s], Pdot) -> (F0 [Hz], F1); inverse of itself. Accepts
    scalars or array-likes (reference: derived_quantities.py::p_to_f)."""
    import numpy as np

    p = np.asarray(p, dtype=np.float64) if not np.isscalar(p) else p
    pd = np.asarray(pd, dtype=np.float64) if not np.isscalar(pd) else pd
    if pdd is not None and not np.isscalar(pdd):
        pdd = np.asarray(pdd, dtype=np.float64)
    f = 1.0 / p
    fd = -pd / p**2
    if pdd is None:
        return f, fd
    fdd = 2.0 * pd**2 / p**3 - pdd / p**2
    return f, fd, fdd


def pferrs(p, perr, pd=None, pderr=None):
    """Propagate (P, Pdot) uncertainties to (F0, F1)
    (reference: derived_quantities.py::pferrs)."""
    if pd is None:
        return 1.0 / p, perr / p**2
    f, fd = p_to_f(p, pd)
    ferr = perr / p**2
    fderr = _sqrt((4.0 * pd**2 * perr**2 / p**6) + pderr**2 / p**4)
    return f, ferr, fd, fderr


def mass_function(pb_days, a1_ls):
    """Binary mass function [Msun].

    f = 4 pi^2 x^3 / (T_sun Pb^2), x in ls, Pb in s
    (reference: derived_quantities.py::mass_funct).
    """
    pb_s = pb_days * SECS_PER_DAY
    return 4.0 * math.pi**2 * a1_ls**3 / (TSUN_S * pb_s**2)


# upstream spelling (reference: derived_quantities.py::mass_funct)
mass_funct = mass_function


def mass_funct2(mp, mc, sini):
    """Mass function from component masses [Msun]
    (reference: derived_quantities.py::mass_funct2)."""
    return (mc * sini) ** 3 / (mp + mc) ** 2


def companion_mass(pb_days, a1_ls, sini=1.0, mp=1.4, iters=64):
    """Solve the mass function for Mc [Msun] given Mp and sin(i).

    Newton iteration on (Mc sini)^3/(Mp+Mc)^2 = f(Pb, x)
    (reference: derived_quantities.py::companion_mass, which solves the
    same cubic via numpy roots; Newton from a guaranteed-left start is
    jit-friendly and converges monotonically).
    """
    f = mass_function(pb_days, a1_ls)
    mc = f + 1e-6  # start left of the root; Newton ascends monotonically
    for _ in range(iters):
        g = (mc * sini) ** 3 / (mp + mc) ** 2 - f
        dg = (3.0 * sini**3 * mc**2 * (mp + mc) - 2.0 * (mc * sini) ** 3) / (
            mp + mc
        ) ** 3
        mc = mc - g / dg
    return mc


def pulsar_mass(pb_days, a1_ls, mc, sini):
    """Mp [Msun] from the mass function given Mc and sin(i)
    (reference: derived_quantities.py::pulsar_mass)."""
    f = mass_function(pb_days, a1_ls)
    return _sqrt((mc * sini) ** 3 / f) - mc


def pulsar_age(f0, f1, n=3, fo=1e99):
    """Characteristic age [yr]; braking index n, original spin fo
    (reference: derived_quantities.py::pulsar_age)."""
    age_s = -f0 / ((n - 1.0) * f1) * (1.0 - (f0 / fo) ** (n - 1.0))
    return age_s / SECS_PER_JULIAN_YEAR


def pulsar_edot(f0, f1, I=_I_NS_SI):
    """Spin-down luminosity [W] (reference: derived_quantities.py::pulsar_edot).
    I in kg m^2 (default 1e38 = 1e45 g cm^2)."""
    return -4.0 * math.pi**2 * I * f0 * f1


def pulsar_B(f0, f1):
    """Surface dipole field [Gauss]: 3.2e19 sqrt(-F1/F0^3)
    (reference: derived_quantities.py::pulsar_B). _sqrt keeps
    spin-up (F1>0) as nan rather than a silent complex value while
    staying traceable under jax transforms."""
    return 3.2e19 * _sqrt(-f1 / f0**3)


def pulsar_B_lightcyl(f0, f1):
    """Field at the light cylinder [Gauss]
    (reference: derived_quantities.py::pulsar_B_lightcyl)."""
    p, pd = 1.0 / f0, -f1 / f0**2
    return 2.9e8 * p ** (-5.0 / 2.0) * _sqrt(pd)


def omdot(mp, mc, pb_days, e):
    """GR periastron advance [deg/yr]
    (reference: derived_quantities.py::omdot)."""
    pb_s = pb_days * SECS_PER_DAY
    rate = (
        3.0
        * (pb_s / _TWO_PI) ** (-5.0 / 3.0)
        * (TSUN_S * (mp + mc)) ** (2.0 / 3.0)
        / (1.0 - e**2)
    )  # rad/s
    return rate * SECS_PER_JULIAN_YEAR * 180.0 / math.pi


def gamma(mp, mc, pb_days, e):
    """GR time-dilation/grav-redshift amplitude gamma [s]
    (reference: derived_quantities.py::gamma)."""
    pb_s = pb_days * SECS_PER_DAY
    return (
        e
        * (pb_s / _TWO_PI) ** (1.0 / 3.0)
        * TSUN_S ** (2.0 / 3.0)
        * (mp + mc) ** (-4.0 / 3.0)
        * mc
        * (mp + 2.0 * mc)
    )


def pbdot(mp, mc, pb_days, e):
    """GR orbital decay Pbdot [s/s]
    (reference: derived_quantities.py::pbdot)."""
    pb_s = pb_days * SECS_PER_DAY
    fe = (1.0 + (73.0 / 24.0) * e**2 + (37.0 / 96.0) * e**4) * (1.0 - e**2) ** (
        -7.0 / 2.0
    )
    return (
        -192.0
        * math.pi
        / 5.0
        * (pb_s / _TWO_PI) ** (-5.0 / 3.0)
        * fe
        * TSUN_S ** (5.0 / 3.0)
        * mp
        * mc
        * (mp + mc) ** (-1.0 / 3.0)
    )


def sini_from_omdot(mp, mc, pb_days, e, a1_ls):
    """sin(i) implied by GR omdot masses via the mass function."""
    f = mass_function(pb_days, a1_ls)
    return (f * (mp + mc) ** 2) ** (1.0 / 3.0) / mc


def shklovskii_factor(pmtot_masyr, d_kpc):
    """Shklovskii apparent Pdot/P [1/s]: mu^2 d / c
    (reference: derived_quantities.py::shklovskii_factor)."""
    mu = pmtot_masyr * MASYR_TO_RADS  # rad/s
    d_m = d_kpc * 1000.0 * PC_M
    return mu**2 * d_m / C_M_S


def dispersion_slope(dm):
    """DM delay slope K*DM [s MHz^2]
    (reference: derived_quantities.py::dispersion_slope)."""
    return DMconst * dm


def pmtot(pmra_or_elong, pmdec_or_elat):
    """Total proper motion [mas/yr] (reference: utils.py::pmtot)."""
    return _sqrt(pmra_or_elong**2 + pmdec_or_elat**2)
