"""Logging setup with repeated-warning dedup.

(reference: src/pint/logging.py — loguru sink with a LogFilter that
suppresses repeats of known-noisy messages and a ``setup(level=...)``
entry point. loguru is not in this environment; the stdlib logging
module provides the same surface.)
"""

from __future__ import annotations

import logging
import sys

LOG_NAME = "pint_tpu"


class DedupFilter(logging.Filter):
    """Emit each distinct (level, message) once; drop repeats
    (reference: pint.logging.LogFilter)."""

    def __init__(self, max_repeats: int = 1):
        super().__init__()
        self.max_repeats = max_repeats
        self._seen: dict[tuple, int] = {}

    def filter(self, record: logging.LogRecord) -> bool:
        if record.levelno < logging.WARNING:
            return True
        key = (record.levelno, record.getMessage())
        n = self._seen.get(key, 0)
        self._seen[key] = n + 1
        return n < self.max_repeats


def setup(level="INFO", stream=None, dedup=True) -> logging.Logger:
    """Configure the package logger (reference: pint.logging.setup).

    Returns the logger; repeat calls reconfigure idempotently.
    """
    logger = logging.getLogger(LOG_NAME)
    logger.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    for h in list(logger.handlers):
        logger.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)-7s %(name)s: %(message)s", "%H:%M:%S"))
    if dedup:
        handler.addFilter(DedupFilter())
    logger.addHandler(handler)
    logger.propagate = False
    return logger


def get_logger(child: str | None = None) -> logging.Logger:
    name = LOG_NAME if child is None else f"{LOG_NAME}.{child}"
    return logging.getLogger(name)
