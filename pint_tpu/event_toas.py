"""Photon-event TOA loaders for X-ray/gamma-ray missions.

(reference: src/pint/event_toas.py — load_event_TOAs /
load_NICER_TOAs / load_RXTE_TOAs / load_XMM_TOAs / load_NuSTAR_TOAs /
load_Swift_TOAs; src/pint/fermi_toas.py — load_Fermi_TOAs with photon
weights.)

Event times are MET seconds since the mission MJDREF (TT), read from
the EVENTS binary table. Barycentered files (TIMESYS='TDB') map to the
'@' barycenter observatory; otherwise the TOAs are tagged with the
mission's satellite observatory, which must be registered first via
``get_satellite_observatory`` with an orbit file.

Per-photon TOAs are microsecond-precision and carry no uncertainty;
the downstream device pipeline phase-folds them in one vmapped pass
(the TPU win: 1e6-1e7 photons is a single batched phase() call).
"""

from __future__ import annotations

import numpy as np

from .toa import TOA, TOAs

# MJDREF fallbacks when the event header omits them (TT days).
# Values are the published mission epochs.
MISSION_MJDREF = {
    "nicer": 56658.000777592592592593,
    "nustar": 55197.00076601852,
    "rxte": 49353.000696574074,
    "swift": 51910.00074287037,
    "xmm": 50814.0,
    "fermi": 51910.00074287037,
    "ixpe": 57754.00080074074,
}


def _mjdref_days(header, mission=None) -> float:
    if "MJDREFI" in header:
        return float(header["MJDREFI"]) + float(header.get("MJDREFF", 0.0))
    if "MJDREF" in header:
        return float(header["MJDREF"])
    if mission and mission.lower() in MISSION_MJDREF:
        return MISSION_MJDREF[mission.lower()]
    raise KeyError("no MJDREF in event header and unknown mission")


def met_to_day_sec(met_s, mjdref_days):
    """MET seconds -> (int MJD day, float sec-of-day) without losing
    precision: the fractional MJDREF is carried in seconds."""
    met_s = np.asarray(met_s, dtype=np.float64)
    ref_day = int(np.floor(mjdref_days))
    ref_sec = (mjdref_days - ref_day) * 86400.0
    tot_sec = met_s + ref_sec
    dday = np.floor(tot_sec / 86400.0)
    sec = tot_sec - dday * 86400.0
    return (ref_day + dday.astype(np.int64)), sec


def load_event_TOAs(eventfile, mission, weights=None, weightcolumn=None,
                    minmjd=-np.inf, maxmjd=np.inf, extname="EVENTS",
                    errors_us=1.0, ephem="de440s", planets=False,
                    table=None):
    """FITS event list -> TOAs (reference: event_toas.py::load_event_TOAs).

    Returns a fully-populated TOAs object (clock/TDB/posvel computed
    downstream as usual). Weights (probability the photon is from the
    pulsar) land in per-TOA flags as ``-weight``. ``table`` supplies an
    already-read (header, cols) pair so callers that pre-scan columns
    (the Fermi CALC weight path) don't parse a multi-million-photon
    file twice.
    """
    from .io.fits import get_table

    header, cols = table if table is not None else get_table(eventfile,
                                                             extname)
    tcol = next(k for k in cols if k.upper() == "TIME")
    met = np.asarray(cols[tcol], np.float64)
    mjdref = _mjdref_days(header, mission)
    timesys = str(header.get("TIMESYS", "TT")).strip().upper()
    obs = "barycenter" if timesys == "TDB" else str(mission).lower()
    day, sec = met_to_day_sec(met, mjdref)
    mjd_f = day + sec / 86400.0
    keep = (mjd_f >= minmjd) & (mjd_f <= maxmjd)
    if weightcolumn is not None:
        wcol = next(k for k in cols if k.upper() == weightcolumn.upper())
        weights = np.asarray(cols[wcol], np.float64)
    if weights is not None:
        weights = np.asarray(weights, np.float64)[keep]
    # vectorized build: no per-photon Python objects (flags stay lazy)
    t = TOAs.from_arrays(day[keep], sec[keep], error_us=errors_us,
                         freq_mhz=np.inf, obs=obs, ephem=ephem,
                         planets=planets, weights=weights)
    t.filename = str(eventfile)
    return t


def _mission_loader(mission):
    def load(eventfile, **kw):
        kw.setdefault("mission", mission)
        m = kw.pop("mission")
        return load_event_TOAs(eventfile, m, **kw)
    load.__name__ = f"load_{mission.upper()}_TOAs"
    load.__doc__ = (f"Load {mission.upper()} photon events "
                    "(reference: event_toas.py::load_%s_TOAs)" % mission)
    return load


load_NICER_TOAs = _mission_loader("nicer")
load_RXTE_TOAs = _mission_loader("rxte")
load_XMM_TOAs = _mission_loader("xmm")
load_NuSTAR_TOAs = _mission_loader("nustar")
load_Swift_TOAs = _mission_loader("swift")
load_IXPE_TOAs = _mission_loader("ixpe")


def calc_lat_weights(energies_mev, angseps_deg, logeref=4.1,
                     logesig=0.5):
    """Heuristic Fermi-LAT photon weights from angular separation and
    energy (reference: fermi_toas.py::calc_lat_weights — Bruel's
    SearchPulsation convention): a King-profile radial factor
    fgeom = (1 + theta^2 / (2 gamma sigma^2))^(-gamma) with gamma = 2
    and an energy-dependent PSF scale, times a log-normal energy
    window centered on log10(E/MeV) = logeref. No spacecraft pointing
    history or IRF is used — these are aperture-photometry-grade
    weights; for likelihood-grade weights run gtsrcprob and pass its
    column.

    PSF scale: sigma(E) = sqrt(p0^2 (100 MeV/E)^(2 p1) + p2^2)/3 deg
    with (p0, p1, p2) = (5.445, 0.848, 0.084), the front-converting
    P7-era parameterization the reference convention uses.
    """
    e = np.asarray(energies_mev, np.float64)
    th = np.asarray(angseps_deg, np.float64)
    psfpar0, psfpar1, psfpar2, scalepsf = 5.445, 0.848, 0.084, 3.0
    gamma = 2.0
    sigma = np.sqrt(psfpar0**2 * (100.0 / e) ** (2 * psfpar1)
                    + psfpar2**2) / scalepsf
    fgeom = (1.0 + th**2 / (2.0 * gamma * sigma**2)) ** (-gamma)
    loge = np.log10(e)
    return fgeom * np.exp(-0.5 * ((loge - logeref) / logesig) ** 2)


def _angsep_deg(ra1, dec1, ra2, dec2):
    """Great-circle separation [deg] (Vincenty formula, stable at all
    separations), inputs in degrees; ra2/dec2 may be arrays."""
    l1, b1, l2, b2 = map(np.radians, (ra1, dec1, ra2, dec2))
    dl = l2 - l1
    num = np.hypot(np.cos(b2) * np.sin(dl),
                   np.cos(b1) * np.sin(b2)
                   - np.sin(b1) * np.cos(b2) * np.cos(dl))
    den = (np.sin(b1) * np.sin(b2)
           + np.cos(b1) * np.cos(b2) * np.cos(dl))
    return np.degrees(np.arctan2(num, den))


def load_Fermi_TOAs(ft1file, weightcolumn=None, targetcoord=None,
                    minmjd=-np.inf, maxmjd=np.inf, ephem="de440s",
                    planets=False, logeref=4.1, logesig=0.5):
    """Fermi-LAT FT1 photons (reference: fermi_toas.py::load_Fermi_TOAs).

    weightcolumn: name of a photon-weight column (e.g. from gtsrcprob),
    or "CALC" to compute heuristic PSF weights on the fly from the FT1
    RA/DEC/ENERGY columns and ``targetcoord`` (see calc_lat_weights).
    targetcoord: (ra_deg, dec_deg) of the pulsar, required for CALC.
    """
    if weightcolumn == "CALC":
        if targetcoord is None:
            raise ValueError("weightcolumn='CALC' needs targetcoord="
                             "(ra_deg, dec_deg)")
        from .io.fits import get_table

        table = get_table(ft1file, "EVENTS")
        cols = table[1]

        def col(name):
            return np.asarray(
                cols[next(k for k in cols if k.upper() == name)],
                np.float64)

        angsep = _angsep_deg(targetcoord[0], targetcoord[1],
                             col("RA"), col("DEC"))
        weights = calc_lat_weights(col("ENERGY"), angsep,
                                   logeref=logeref, logesig=logesig)
        return load_event_TOAs(ft1file, "fermi", weights=weights,
                               minmjd=minmjd, maxmjd=maxmjd, ephem=ephem,
                               planets=planets, table=table)
    return load_event_TOAs(ft1file, "fermi", weightcolumn=weightcolumn,
                           minmjd=minmjd, maxmjd=maxmjd, ephem=ephem,
                           planets=planets)


def get_event_weights(toas: TOAs) -> np.ndarray | None:
    """Per-photon weights (TOAs.weights column, with a fallback to
    per-TOA '-weight' flags for tim-file round-trips), or None."""
    if toas.weights is not None:
        return np.asarray(toas.weights, float)
    if not toas.has_flags():
        return None  # lazy flags: don't materialize 1e7 empty dicts
    w = [f.get("weight") for f in toas.flags]
    if any(x is None for x in w):
        return None
    return np.array([float(x) for x in w])
