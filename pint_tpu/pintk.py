"""Headless interactive-fitting state — the logic layer of the
reference's pintk GUI, without Tk.

(reference: src/pint/pintk/pulsar.py::Pulsar — the GUI-independent
wrapper that pintk's plk widget drives: fit/undo/reset, TOA
selection, per-selection jump add/remove, random-model spread. The Tk
widgets themselves are out of TPU scope (SURVEY.md section 2.3: GUI
exempted -> CLI parity); this class IS the tested surface, drivable
from scripts, notebooks, or any future frontend.)
"""

from __future__ import annotations

import copy

import numpy as np

from .fitter import auto_fitter
from .residuals import Residuals
from .simulation import calculate_random_models


class InteractivePulsar:
    """Mutable fit session over (model, TOAs) with undo history.

    (reference: pintk/pulsar.py::Pulsar)
    """

    def __init__(self, model, toas, fitter_factory=auto_fitter):
        self.toas = toas
        self.fitter_factory = fitter_factory
        self._history = [copy.deepcopy(model)]
        self.selected = np.zeros(len(toas), dtype=bool)
        self.fitted = False
        self.last_fit = None

    @property
    def model(self):
        return self._history[-1]

    @property
    def prefit_model(self):
        return self._history[0]

    # -- residuals --

    def resids_us(self, model=None) -> np.ndarray:
        r = Residuals(self.toas, model or self.model)
        return np.asarray(r.calc_time_resids()) * 1e6

    # -- selection (reference: plk click/drag selection) --

    def select(self, mask):
        self.selected = np.asarray(mask, dtype=bool).copy()

    def select_mjd_range(self, lo, hi):
        mjd = self.toas.get_mjds()
        self.selected = (mjd >= lo) & (mjd <= hi)

    def clear_selection(self):
        self.selected[:] = False

    # -- fitting with history (reference: Pulsar.fit / undo / reset) --

    def fit(self, **kw):
        model = copy.deepcopy(self.model)
        fitter = self.fitter_factory(self.toas, model)
        fitter.fit_toas(**kw)
        self._history.append(fitter.model)
        self.fitted = True
        self.last_fit = fitter
        return fitter

    def undo(self):
        if len(self._history) > 1:
            self._history.pop()
        self.fitted = len(self._history) > 1
        return self.model

    def reset(self):
        del self._history[1:]
        self.fitted = False
        self.last_fit = None

    # -- jumps on the current selection (reference: Pulsar.add_jump) --

    def add_jump_to_selection(self):
        """JUMP the selected TOAs via a per-TOA flag mask; returns the
        new jump parameter name."""
        if not self.selected.any():
            raise ValueError("no TOAs selected")
        model = self.model
        if "PhaseJump" not in model.components:
            from .models.jump import PhaseJump

            model.add_component(PhaseJump())
        comp = model.components["PhaseJump"]
        idx = (max(comp.jump_ids) + 1) if comp.jump_ids else 1
        flag_val = f"pintk_{idx}"
        for i in np.flatnonzero(self.selected):
            self.toas.flags[i]["jump"] = flag_val
        par = comp.add_jump(key="-jump", key_value=[flag_val], index=idx)
        return par.name

    def remove_jump(self, name):
        comp = self.model.components.get("PhaseJump")
        if comp is None or name not in comp.params:
            raise KeyError(name)
        idx = int(name[4:])
        par = getattr(comp, name)
        if par.key == "-jump":
            tag = par.key_value[0]
            for f in self.toas.flags:
                if f.get("jump") == tag:
                    del f["jump"]
        comp.remove_param(name)
        comp.jump_ids.remove(idx)

    # -- random-model spread (reference: Pulsar.random_models) --

    def random_models(self, n_models=30, seed=0):
        if self.last_fit is None:
            raise RuntimeError("fit first")
        return calculate_random_models(self.last_fit, self.toas,
                                       n_models=n_models, seed=seed)
