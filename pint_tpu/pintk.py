"""Headless interactive-fitting state — the logic layer of the
reference's pintk GUI, without Tk.

(reference: src/pint/pintk/pulsar.py::Pulsar — the GUI-independent
wrapper that pintk's plk widget drives: fit/undo/reset, TOA
selection, per-selection jump add/remove, random-model spread. The Tk
widgets themselves are out of TPU scope (SURVEY.md section 2.3: GUI
exempted -> CLI parity); this class IS the tested surface, drivable
from scripts, notebooks, or any future frontend.)
"""

from __future__ import annotations

import copy

import numpy as np

from .fitter import auto_fitter
from .residuals import Residuals
from .simulation import calculate_random_models


class InteractivePulsar:
    """Mutable fit session over (model, TOAs) with undo history.

    (reference: pintk/pulsar.py::Pulsar)
    """

    def __init__(self, model, toas, fitter_factory=auto_fitter):
        self.toas = toas
        self.fitter_factory = fitter_factory
        self._history = [copy.deepcopy(model)]
        # parallel to _history: whether each entry came from a fit
        # (par edits also grow history, so len(history)>1 != fitted)
        self._from_fit = [False]
        self.selected = np.zeros(len(toas), dtype=bool)
        self.fitted = False
        self.last_fit = None
        self._all_toas = None  # pre-deletion snapshot (restore_all_toas)

    @property
    def model(self):
        return self._history[-1]

    @property
    def prefit_model(self):
        return self._history[0]

    # -- residuals --

    def resids_us(self, model=None) -> np.ndarray:
        r = Residuals(self.toas, model or self.model)
        return np.asarray(r.calc_time_resids()) * 1e6

    def whitened_resids(self) -> np.ndarray:
        """Dimensionless whitened residuals of the last fit — with a
        GLS fit, the fitted noise realizations are subtracted first
        (reference: plk whitened plotting mode backed by
        Residuals.calc_whitened_resids)."""
        if self.last_fit is None:
            raise ValueError("no fit yet — run fit() first")
        return np.asarray(self.last_fit.resids.calc_whitened_resids())

    # -- selection (reference: plk click/drag selection) --

    def select(self, mask):
        self.selected = np.asarray(mask, dtype=bool).copy()

    def select_mjd_range(self, lo, hi):
        mjd = self.toas.get_mjds()
        self.selected = (mjd >= lo) & (mjd <= hi)

    def clear_selection(self):
        self.selected[:] = False

    # -- fitting with history (reference: Pulsar.fit / undo / reset) --

    def fit(self, **kw):
        model = copy.deepcopy(self.model)
        fitter = self.fitter_factory(self.toas, model)
        fitter.fit_toas(**kw)
        self._history.append(fitter.model)
        self._from_fit.append(True)
        self.fitted = True
        self.last_fit = fitter
        return fitter

    def undo(self):
        if len(self._history) > 1:
            self._history.pop()
            self._from_fit.pop()
        self.fitted = self._from_fit[-1]
        if not self.fitted:
            self.last_fit = None
        return self.model

    def reset(self):
        del self._history[1:]
        del self._from_fit[1:]
        self.fitted = False
        self.last_fit = None

    # -- jumps on the current selection (reference: Pulsar.add_jump) --

    def add_jump_to_selection(self):
        """JUMP the selected TOAs via a per-TOA flag mask; returns the
        new jump parameter name."""
        if not self.selected.any():
            raise ValueError("no TOAs selected")
        model = self.model
        if "PhaseJump" not in model.components:
            from .models.jump import PhaseJump

            model.add_component(PhaseJump())
        comp = model.components["PhaseJump"]
        idx = (max(comp.jump_ids) + 1) if comp.jump_ids else 1
        flag_val = f"pintk_{idx}"
        for i in np.flatnonzero(self.selected):
            self.toas.flags[i]["jump"] = flag_val
        par = comp.add_jump(key="-jump", key_value=[flag_val], index=idx)
        return par.name

    def remove_jump(self, name):
        comp = self.model.components.get("PhaseJump")
        if comp is None or name not in comp.params:
            raise KeyError(name)
        idx = int(name[4:])
        par = getattr(comp, name)
        if par.key == "-jump":
            tag = par.key_value[0]
            for f in self.toas.flags:
                if f.get("jump") == tag:
                    del f["jump"]
        comp.remove_param(name)
        comp.jump_ids.remove(idx)

    # -- TOA deletion (reference: plk delete/restore on selection) --

    def delete_selected(self):
        """Drop the selected TOAs from the working set (the full set is
        kept for restore, mirroring pintk's all_toas/selected_toas
        split: reference pintk/pulsar.py::Pulsar.delete_TOAs)."""
        if not self.selected.any():
            raise ValueError("no TOAs selected")
        if self._all_toas is None:
            self._all_toas = self.toas
        keep = ~self.selected
        self.toas = self.toas.mask(keep)
        self.selected = np.zeros(len(self.toas), dtype=bool)

    def restore_all_toas(self):
        """Undo every deletion (reference: Pulsar.reset_TOAs side)."""
        if self._all_toas is not None:
            self.toas = self._all_toas
            self._all_toas = None
        self.selected = np.zeros(len(self.toas), dtype=bool)

    # -- pulse numbers / phase wraps (reference: Pulsar.add_phase_wrap) --

    def compute_pulse_numbers(self):
        """Stamp model-predicted pulse numbers into the pn flags so
        residual tracking survives wraps/deletions (reference:
        TOAs.compute_pulse_numbers + pintk track mode)."""
        r = Residuals(self.toas, self.model, track_mode="nearest")
        frac, pulse_int = r.prepared.phase_frac_and_int(None)
        pn = np.asarray(pulse_int) + np.round(np.asarray(frac))
        for i, f in enumerate(self.toas.flags):
            f["pn"] = repr(int(pn[i]))
        return pn

    def add_phase_wrap(self, n_wraps: int):
        """Add +-N integer turns to the selected TOAs' pulse numbers
        (reference: pintk/pulsar.py::Pulsar.add_phase_wrap). Computes
        pulse numbers first unless every SELECTED TOA already carries
        one (delete/restore cycles can leave partial stamping)."""
        if not self.selected.any():
            raise ValueError("no TOAs selected")
        sel_idx = np.flatnonzero(self.selected)
        if not all("pn" in self.toas.flags[i] for i in sel_idx):
            self.compute_pulse_numbers()
        for i in sel_idx:
            f = self.toas.flags[i]
            f["pn"] = repr(int(float(f["pn"])) + int(n_wraps))

    # -- color modes (reference: pintk/colormodes.py, headless form) --

    COLOR_MODES = ("default", "obs", "freq", "error", "jump", "selected")

    def color_categories(self, mode="default"):
        """Per-TOA category labels for plotting frontends: the logic
        layer of pintk's colormodes (DefaultMode/ObservatoryMode/
        FreqMode/ErrorMode/JumpMode) without Tk or colors."""
        n = len(self.toas)
        if mode == "default":
            return np.array(["prefit" if not self.fitted else "postfit"] * n,
                            dtype=object)
        if mode == "obs":
            return self.toas.obs.astype(object)
        if mode == "freq":
            f = self.toas.freq_mhz
            bands = [(0.0, "<400"), (400.0, "400-700"), (700.0, "700-1000"),
                     (1000.0, "1000-1800"), (1800.0, "1800-3000"),
                     (3000.0, ">3000")]
            out = np.empty(n, dtype=object)
            for lo, name in bands:
                out[f >= lo] = name
            out[~np.isfinite(f)] = "inf"
            return out
        if mode == "error":
            med = np.median(self.toas.error_us)
            return np.where(self.toas.error_us > med, "above-median",
                            "below-median").astype(object)
        if mode == "jump":
            tags = self.toas.get_flag_value("jump", fill="")
            return np.array([t if t else "unjumped" for t in tags],
                            dtype=object)
        if mode == "selected":
            return np.where(self.selected, "selected",
                            "unselected").astype(object)
        raise ValueError(f"unknown color mode {mode!r}; "
                         f"choose from {self.COLOR_MODES}")

    # -- x-axis quantities (reference: pintk/plk.py xy-axis choices) --

    X_AXIS_CHOICES = ("mjd", "serial", "year", "day of year", "frequency",
                      "TOA error", "orbital phase")

    def xvals(self, mode="mjd"):
        """Per-TOA x-axis values for the residual plot, matching plk's
        x-axis dropdown. 'orbital phase' requires a binary model."""
        import datetime

        from .constants import DAYS_PER_JULIAN_YEAR, MJD_J2000

        t = self.toas
        if mode == "mjd":
            return t.get_mjds()
        if mode == "serial":
            return np.arange(len(t), dtype=float)
        if mode == "year":
            return 2000.0 + (t.get_mjds() - MJD_J2000) / DAYS_PER_JULIAN_YEAR
        if mode == "day of year":
            mjd0 = datetime.date(1858, 11, 17).toordinal()
            return np.array(
                [datetime.date.fromordinal(int(m) + mjd0).timetuple().tm_yday
                 + (m % 1.0) for m in t.get_mjds()])
        if mode == "frequency":
            f = np.asarray(t.freq_mhz)
            # infinite-frequency (barycentered) TOAs would break axis
            # autoscale; nan makes matplotlib skip them
            return np.where(np.isfinite(f), f, np.nan)
        if mode == "TOA error":
            return np.asarray(t.error_us)
        if mode == "orbital phase":
            return self.model.orbital_phase(t)
        raise ValueError(f"unknown x-axis mode {mode!r}; "
                         f"choose from {self.X_AXIS_CHOICES}")

    def x_axis_choices(self):
        """The modes valid for THIS model (orbital phase only for
        binaries)."""
        has_binary = any(c.category == "pulsar_system"
                         for c in self.model.delay_components())
        return tuple(m for m in self.X_AXIS_CHOICES
                     if has_binary or m != "orbital phase")

    # -- fit-parameter checkboxes (reference: plk fitbox) --

    def set_fit_params(self, names):
        """Free exactly these parameters (the plk fitbox behavior)."""
        self.model.free_params = list(names)

    # -- par/tim editing (reference: pintk/paredit.py + timedit.py) --

    def apply_parfile(self, par_text: str):
        """Replace the working model with an edited par file, keeping
        history (paredit's 'apply changes'). The previous fit no
        longer describes the working model, so last_fit is dropped
        (random_models must not spread around a stale covariance)."""
        from .models import get_model

        self._history.append(get_model(par_text))
        self._from_fit.append(False)
        self.fitted = False
        self.last_fit = None

    def write_par(self, path):
        with open(path, "w") as f:
            f.write(self.model.as_parfile())

    def write_tim(self, path):
        self.toas.write_TOA_file(path)

    # -- random-model spread (reference: Pulsar.random_models) --

    def random_models(self, n_models=30, seed=0):
        if self.last_fit is None:
            raise RuntimeError("fit first")
        return calculate_random_models(self.last_fit, self.toas,
                                       n_models=n_models, seed=seed)
