"""Fit-state checkpointing for long batch fits.

(reference analog: SURVEY.md section 5 — the reference's checkpoint is
the par file itself (TimingModel.as_parfile round-trips full state)
plus the TOA pickle cache. For TPU batch fits this module adds an
orbax-backed snapshot of the numeric fit state between outer
iterations, with a plain-npz fallback, so a preempted multi-hour PTA
run resumes instead of restarting.)
"""

from __future__ import annotations

import os

import numpy as np


class FitCheckpointer:
    """Save/restore (param-vector, iteration, chi2) snapshots.

    Uses orbax-checkpoint when importable (atomic, async-capable);
    falls back to numpy .npz with atomic rename otherwise. Either way
    the on-disk layout is a directory per tag.
    """

    def __init__(self, directory):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
        except ImportError:
            self._ocp = None

    def _path(self, tag):
        return os.path.join(self.directory, str(tag))

    def save(self, tag, state: dict):
        """state: dict of arrays/scalars (e.g. {"x": ..., "iter": i,
        "chi2": ...}). String-valued entries (parameter names) go to a
        JSON sidecar — orbax/tensorstore has no string dtype."""
        import json

        state = {k: np.asarray(v) for k, v in state.items()}
        meta = {k: np.asarray(v).tolist() for k, v in state.items()
                if np.asarray(v).dtype.kind in "US"}
        numeric = {k: v for k, v in state.items()
                   if np.asarray(v).dtype.kind not in "US"}
        meta_path = self._path(tag) + ".meta.json"
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, meta_path)
        if self._ocp is not None:
            import jax

            path = os.path.abspath(self._path(tag))
            ckptr = self._ocp.PyTreeCheckpointer()
            ckptr.save(path, jax.tree_util.tree_map(np.asarray, numeric),
                       force=True)
            return path
        path = self._path(tag) + ".npz"
        tmp = path + ".tmp.npz"
        np.savez(tmp, **numeric)
        os.replace(tmp, path)
        return path

    def restore(self, tag) -> dict | None:
        """Load a snapshot regardless of which backend WROTE it: save()
        picked the format at write time, so an .npz written where orbax
        was absent must still restore once orbax becomes importable
        (and vice versa) instead of silently restarting the fit."""
        import json

        out = None
        if self._ocp is not None:
            path = os.path.abspath(self._path(tag))
            if os.path.isdir(path):
                ckptr = self._ocp.PyTreeCheckpointer()
                try:
                    out = dict(ckptr.restore(path))
                except Exception:
                    out = None
        if out is None:
            path = self._path(tag) + ".npz"
            if os.path.exists(path):
                try:
                    with np.load(path) as z:
                        out = {k: z[k] for k in z.files}
                except OSError:
                    return None
        if out is None:
            return None
        meta_path = self._path(tag) + ".meta.json"
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    out.update({k: np.asarray(v)
                                for k, v in json.load(f).items()})
            except (OSError, json.JSONDecodeError):
                pass
        return out

    def latest_iteration(self, tag) -> int:
        state = self.restore(tag)
        return int(state["iter"]) if state is not None and "iter" in state else -1


def checkpointed_fit(fitter, directory, tag="fit", every=1, maxiter=20,
                     **fit_kw):
    """Run fitter.fit_toas with snapshots between outer iterations.

    Resumes from the saved parameter vector when a snapshot exists
    (per-pulsar failure isolation for batch runs lives in
    parallel/pta.py; this wrapper covers the single-pulsar fitters).
    Snapshots store parameter NAMES alongside values; on resume the
    values are matched by name, and a snapshot whose free-parameter
    set differs from the current model raises instead of silently
    mis-assigning. "iter" counts completed fit iterations.
    """
    ckpt = FitCheckpointer(directory)
    state = ckpt.restore(tag)
    chi2 = None
    if state is not None and "param_values" in state:
        names = [str(n) for n in np.asarray(state["param_names"])]
        current = list(fitter.model.free_params)
        if set(names) != set(current):
            raise ValueError(
                f"checkpoint {tag!r} was taken with free params {names}, "
                f"model has {current}; refusing positional restore")
        vals = dict(zip(names, np.asarray(state["param_values"], float)))
        for name in current:
            getattr(fitter.model, name).value = float(vals[name])
        chi2 = float(state["chi2"])
    done = max(ckpt.latest_iteration(tag), 0)
    while done < maxiter:
        n = min(every, maxiter - done)
        chi2 = fitter.fit_toas(maxiter=n, **fit_kw)
        done += n
        names = list(fitter.model.free_params)
        vals = np.array([getattr(fitter.model, p).value for p in names])
        ckpt.save(tag, {"param_values": vals,
                        "param_names": np.array(names),
                        "iter": done, "chi2": chi2})
    return chi2
