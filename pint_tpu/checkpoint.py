"""Fit-state checkpointing for long batch fits.

(reference analog: SURVEY.md section 5 — the reference's checkpoint is
the par file itself (TimingModel.as_parfile round-trips full state)
plus the TOA pickle cache. For TPU batch fits this module adds an
orbax-backed snapshot of the numeric fit state between outer
iterations, with a plain-npz fallback, so a preempted multi-hour PTA
run resumes instead of restarting.)

Integrity: every save records a CRC32 over the packed numeric arrays
(key + dtype + shape + raw bytes, keys sorted) EMBEDDED in the
snapshot itself (a ``__meta_json__`` uint8 array riding the saved
tree), and every save first rotates the existing snapshot to
``<tag>.prev``. Embedding makes a snapshot ONE artifact — one npz
file (written through durable.atomic_write_bytes) or one orbax
directory — so the rotation is a single ``os.replace`` and a process
kill can never leave ``.prev`` mixing a sidecar from one generation
with data from another. restore() verifies the checksum and, when
the latest snapshot is unreadable or fails verification, falls back
to the rotated previous one — a torn write at preemption time costs
one checkpoint interval, not the whole run. Snapshots written before
this scheme (sidecar ``.meta.json``, or no checksum record at all)
restore as before.
"""

from __future__ import annotations

import os
import shutil
import warnings
import zlib

import numpy as np

from .durable import atomic_write_bytes
from .resilience import faultinject

# reserved meta key carrying the snapshot checksum (never a state
# key: save() would have stringified it)
INTEGRITY_KEY = "__integrity__"
# reserved tree key carrying the JSON-encoded meta (string-valued
# state + the integrity record) as a uint8 array, so the whole
# snapshot — data AND checksum — is one atomic write unit
META_EMBED_KEY = "__meta_json__"


def _integrity_crc(numeric: dict) -> int:
    """CRC32 over the packed arrays, order-independent via sorted
    keys; dtype and shape are folded in so a reinterpreted buffer
    (same bytes, different view) fails verification too."""
    crc = 0
    for k in sorted(numeric):
        v = np.ascontiguousarray(np.asarray(numeric[k]))
        crc = zlib.crc32(str(k).encode(), crc)
        crc = zlib.crc32(str(v.dtype).encode(), crc)
        crc = zlib.crc32(repr(v.shape).encode(), crc)
        crc = zlib.crc32(v.tobytes(), crc)
    return int(crc)


class FitCheckpointer:
    """Save/restore (param-vector, iteration, chi2) snapshots.

    Uses orbax-checkpoint when importable (atomic, async-capable);
    falls back to numpy .npz with atomic rename otherwise. Either way
    the on-disk layout is a directory per tag.
    """

    def __init__(self, directory):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
        except ImportError:
            self._ocp = None

    def _path(self, tag):
        return os.path.join(self.directory, str(tag))

    def has_snapshot(self, tag) -> bool:
        """Any on-disk trace of ``tag`` (valid or not)?"""
        return (os.path.isdir(self._path(tag))
                or os.path.exists(self._path(tag) + ".npz")
                or os.path.exists(self._path(tag) + ".meta.json"))

    def _rotate(self, tag):
        """Move the current snapshot of ``tag`` (all backends' files)
        to ``<tag>.prev``, replacing any older .prev — the fallback
        restore() reaches for when the latest snapshot is damaged.

        The old .prev is cleared as a UNIT before anything moves: a
        kill mid-rotation must never leave .prev mixing generations
        (a stale legacy sidecar next to newer data would fail the CRC
        check and poison the fallback). New-style snapshots are a
        single artifact, so their rotation is one atomic
        ``os.replace``; the multi-file window only ever applies to
        legacy sidecar snapshots."""
        prev = f"{tag}.prev"
        for suffix in ("", ".npz", ".meta.json"):
            dst = self._path(prev) + suffix
            if os.path.isdir(dst):
                shutil.rmtree(dst)
            elif os.path.exists(dst):
                os.remove(dst)
        for suffix in ("", ".npz", ".meta.json"):
            src = self._path(tag) + suffix
            present = (os.path.isdir(src) if suffix == ""
                       else os.path.exists(src))
            if present:
                os.replace(src, self._path(prev) + suffix)

    def save(self, tag, state: dict):
        """state: dict of arrays/scalars (e.g. {"x": ..., "iter": i,
        "chi2": ...}). String-valued entries (parameter names) ride a
        JSON-encoded uint8 array inside the saved tree —
        orbax/tensorstore has no string dtype — alongside the CRC32
        of the numeric arrays, so the snapshot is one atomic unit
        rather than a data file plus a sidecar that can tear apart."""
        import json

        state = {k: np.asarray(v) for k, v in state.items()}
        meta = {k: np.asarray(v).tolist() for k, v in state.items()
                if np.asarray(v).dtype.kind in "US"}
        numeric = {k: v for k, v in state.items()
                   if np.asarray(v).dtype.kind not in "US"}
        meta[INTEGRITY_KEY] = _integrity_crc(numeric)
        tree = dict(numeric)
        tree[META_EMBED_KEY] = np.frombuffer(
            json.dumps(meta, sort_keys=True).encode(),
            dtype=np.uint8).copy()
        self._rotate(tag)
        if self._ocp is not None:
            import jax

            path = os.path.abspath(self._path(tag))
            ckptr = self._ocp.PyTreeCheckpointer()
            ckptr.save(path, jax.tree_util.tree_map(np.asarray, tree),
                       force=True)
        else:
            import io

            path = self._path(tag) + ".npz"
            buf = io.BytesIO()
            np.savez(buf, **tree)
            atomic_write_bytes(path, buf.getvalue())
        fault = faultinject.fire("checkpoint_corrupt", tag=str(tag))
        if fault:
            self._corrupt_snapshot(tag)
        return path

    def _corrupt_snapshot(self, tag):
        """checkpoint_corrupt fault effect: flip one byte mid-file in
        the snapshot just written, modeling a torn/bit-rotted write
        that the integrity check must catch."""
        npz = self._path(tag) + ".npz"
        if os.path.exists(npz):
            targets = [npz]
        else:
            # directory backend (orbax/ocdbt): metadata files shrug
            # off a flipped byte, so hit every sizable file — the data
            # chunks among them carry the array bytes the CRC covers
            targets = []
            for root, _, files in os.walk(self._path(tag)):
                targets += [p for p in
                            (os.path.join(root, f) for f in sorted(files))
                            if os.path.getsize(p) > 16]
        for path in targets:
            with open(path, "r+b") as f:
                data = f.read()
                pos = len(data) // 2
                f.seek(pos)
                f.write(bytes([data[pos] ^ 0xFF]))

    def _load_raw(self, tag):
        """(state-dict-with-meta-merged, recorded-crc-or-None), or
        (None, None) when nothing readable exists. A corrupted zip /
        tensorstore raises all sorts (BadZipFile, zlib.error,
        KeyError, ...) — any load failure means 'no snapshot here'."""
        import json

        out = None
        if self._ocp is not None:
            path = os.path.abspath(self._path(tag))
            if os.path.isdir(path):
                ckptr = self._ocp.PyTreeCheckpointer()
                try:
                    out = dict(ckptr.restore(path))
                except Exception:
                    out = None
        if out is None:
            path = self._path(tag) + ".npz"
            if os.path.exists(path):
                try:
                    with np.load(path) as z:
                        out = {k: z[k] for k in z.files}
                except Exception:
                    out = None
        if out is None:
            return None, None
        crc = None
        embedded = out.pop(META_EMBED_KEY, None)
        if embedded is not None:
            # new-style snapshot: meta + CRC ride inside the tree.
            # An unreadable embedded record means the snapshot is
            # damaged as a whole (it was written as one unit), so it
            # counts as 'no snapshot here', not 'restore unverified'.
            try:
                meta = json.loads(np.asarray(embedded, dtype=np.uint8)
                                  .tobytes().decode())
                crc = meta.pop(INTEGRITY_KEY, None)
                out.update({k: np.asarray(v) for k, v in meta.items()})
            except (ValueError, UnicodeDecodeError):
                return None, None
            return out, crc
        meta_path = self._path(tag) + ".meta.json"
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
                crc = meta.pop(INTEGRITY_KEY, None)
                out.update({k: np.asarray(v) for k, v in meta.items()})
            except (OSError, json.JSONDecodeError):
                pass
        return out, crc

    def _restore_verified(self, tag):
        out, crc = self._load_raw(tag)
        if out is None:
            return None
        if crc is not None:
            numeric = {k: v for k, v in out.items()
                       if np.asarray(v).dtype.kind not in "US"}
            if _integrity_crc(numeric) != int(crc):
                warnings.warn(
                    f"checkpoint {tag!r} failed its CRC32 integrity "
                    "check (torn or corrupted write); discarding it")
                return None
        # a pre-integrity snapshot (crc is None) restores unverified
        return out

    def restore(self, tag, fallback=True) -> dict | None:
        """Load a snapshot regardless of which backend WROTE it: save()
        picked the format at write time, so an .npz written where orbax
        was absent must still restore once orbax becomes importable
        (and vice versa) instead of silently restarting the fit.

        Verifies the CRC32 recorded at save time; an unreadable or
        corrupt snapshot falls back (fallback=True) to the rotated
        ``<tag>.prev`` — the most recent valid snapshot — and returns
        None only when nothing valid survives."""
        out = self._restore_verified(tag)
        if out is None and fallback:
            prev = f"{tag}.prev"
            if self.has_snapshot(prev):
                out = self._restore_verified(prev)
                if out is not None:
                    warnings.warn(
                        f"checkpoint {tag!r} was unreadable or corrupt; "
                        f"restored the previous snapshot {prev!r}")
        return out

    def latest_iteration(self, tag) -> int:
        state = self.restore(tag)
        return int(state["iter"]) if state is not None and "iter" in state else -1


# version stamp of the resilience-state checkpoint LAYOUT (the
# breaker/health dicts inside carry their own per-component versions,
# checked by the load_state_dict methods)
RESILIENCE_STATE_VERSION = 1


def save_resilience_state(directory, tag="resilience", breaker=None,
                          health=None):
    """Persist CircuitBreaker / HealthMonitor state through
    FitCheckpointer so a restarted process does not forget tripped
    breakers or a draining health standing (ISSUE 6 satellite).

    The JSON-encoded state rides as a uint8 byte array, NOT a sidecar
    string: the save path's CRC32 integrity record only covers
    numeric arrays, and breaker state is exactly the kind of small
    blob a torn write corrupts silently. Rotation to ``<tag>.prev``
    and corrupt-fallback come with FitCheckpointer for free.
    ``directory`` may be a path or an existing FitCheckpointer."""
    import json

    ckpt = (directory if isinstance(directory, FitCheckpointer)
            else FitCheckpointer(directory))
    state = {}
    if breaker is not None:
        state["breaker"] = breaker.state_dict()
    if health is not None:
        state["health"] = health.state_dict()
    blob = np.frombuffer(
        json.dumps(state, sort_keys=True).encode(), dtype=np.uint8)
    ckpt.save(tag, {"resilience_json": blob.copy(),
                    "resilience_version": RESILIENCE_STATE_VERSION})
    return ckpt


def restore_resilience_state(directory, tag="resilience", breaker=None,
                             health=None):
    """Load a save_resilience_state snapshot and apply it to the given
    breaker/health objects. Any mismatch — missing snapshot, foreign
    layout version, undecodable blob, or a per-component version the
    load_state_dict methods reject — warns and leaves the objects in
    their reset state rather than guessing. Returns the set of
    component names actually restored."""
    import json

    ckpt = (directory if isinstance(directory, FitCheckpointer)
            else FitCheckpointer(directory))
    state = ckpt.restore(tag)
    if state is None or "resilience_json" not in state:
        return set()
    version = int(np.asarray(state.get("resilience_version", -1)))
    if version != RESILIENCE_STATE_VERSION:
        warnings.warn(
            f"resilience checkpoint {tag!r} has layout version "
            f"{version}, this build writes {RESILIENCE_STATE_VERSION}; "
            "resetting breaker/health state")
        return set()
    try:
        blob = np.asarray(state["resilience_json"], dtype=np.uint8)
        decoded = json.loads(blob.tobytes().decode())
    except (ValueError, UnicodeDecodeError) as e:
        warnings.warn(f"resilience checkpoint {tag!r} is undecodable "
                      f"({type(e).__name__}: {e}); resetting state")
        return set()
    restored = set()
    if breaker is not None and "breaker" in decoded:
        if breaker.load_state_dict(decoded["breaker"]):
            restored.add("breaker")
    if health is not None and "health" in decoded:
        if health.load_state_dict(decoded["health"]):
            restored.add("health")
    return restored


def _warn_restart(tag, ckpt):
    """Shared 'nothing valid survives' report for the checkpointed_*
    drivers: on-disk snapshot(s) exist but none restored."""
    if ckpt.has_snapshot(tag) or ckpt.has_snapshot(f"{tag}.prev"):
        warnings.warn(
            f"checkpoint {tag!r}: no valid snapshot survives "
            "(all copies unreadable or corrupt); restarting the fit "
            "from scratch")


def checkpointed_fit(fitter, directory, tag="fit", every=1, maxiter=20,
                     **fit_kw):
    """Run fitter.fit_toas with snapshots between outer iterations.

    Resumes from the saved parameter vector when a snapshot exists
    (per-pulsar failure isolation for batch runs lives in
    parallel/pta.py; this wrapper covers the single-pulsar fitters).
    Snapshots store parameter NAMES alongside values; on resume the
    values are matched by name, and a snapshot whose free-parameter
    set differs from the current model raises instead of silently
    mis-assigning. "iter" counts completed fit iterations. A corrupt
    snapshot falls back to the previous one; when no valid snapshot
    survives the fit restarts cleanly from iteration 0 (with a
    warning)."""
    ckpt = FitCheckpointer(directory)
    state = ckpt.restore(tag)
    if state is None:
        _warn_restart(tag, ckpt)
    chi2 = None
    if state is not None and "param_values" in state:
        names = [str(n) for n in np.asarray(state["param_names"])]
        current = list(fitter.model.free_params)
        if set(names) != set(current):
            raise ValueError(
                f"checkpoint {tag!r} was taken with free params {names}, "
                f"model has {current}; refusing positional restore")
        vals = dict(zip(names, np.asarray(state["param_values"], float)))
        for name in current:
            getattr(fitter.model, name).value = float(vals[name])
        chi2 = float(state["chi2"])
    done = (int(state["iter"])
            if state is not None and "iter" in state else 0)
    while done < maxiter:
        n = min(every, maxiter - done)
        chi2 = fitter.fit_toas(maxiter=n, **fit_kw)
        done += n
        names = list(fitter.model.free_params)
        vals = np.array([getattr(fitter.model, p).value for p in names])
        ckpt.save(tag, {"param_values": vals,
                        "param_names": np.array(names),
                        "iter": done, "chi2": chi2})
    return chi2


def checkpointed_pta_fit(pta, directory, tag="pta", every=1, maxiter=4,
                         method="gls", **fit_kw):
    """Batched analogue of checkpointed_fit: snapshot the (n_psr, k)
    parameter vectors between fit chunks so an interrupted PTA refit
    resumes where it stopped (SURVEY 2.2 elasticity — per-pulsar
    divergence isolation already lives inside PTABatch; this adds the
    between-iterations snapshot). Returns (x, chi2, cov); cov is None
    when the snapshot already covered maxiter. Corrupt snapshots fall
    back to the previous one, then to a clean (warned) restart."""
    if method not in ("gls", "wls"):
        raise ValueError(f"method must be 'gls' or 'wls', got {method!r}")
    ckpt = FitCheckpointer(directory)
    names = [n for n, _, _ in pta.free_map()]
    state = ckpt.restore(tag)
    if state is None:
        _warn_restart(tag, ckpt)
    if state is not None and not all(
            k in state for k in ("param_names", "x", "chi2", "iter")):
        # partial/foreign snapshot (e.g. a single-pulsar checkpointed_fit
        # tag, or a damaged sidecar): restart cleanly rather than crash
        warnings.warn(f"checkpoint {tag!r} is not a PTA snapshot "
                      f"(keys {sorted(state)}); restarting the fit")
        state = None
    if state is not None:
        saved = [str(n) for n in np.asarray(state["param_names"])]
        if saved != names:
            raise ValueError(
                f"checkpoint {tag!r} was taken with params {saved}, "
                f"batch has {names}; refusing positional restore")
        pta.set_start_vector(np.asarray(state["x"], float))
    done = int(state["iter"]) if state is not None else 0
    fit = pta.gls_fit if method == "gls" else pta.wls_fit
    x = np.asarray(state["x"], float) if state is not None else None
    chi2 = np.asarray(state["chi2"], float) if state is not None else None
    cov = None
    while done < maxiter:
        n = min(every, maxiter - done)
        x, chi2, cov = fit(maxiter=n, **fit_kw)
        done += n
        pta.set_start_vector(x)
        ckpt.save(tag, {"x": np.asarray(x), "chi2": np.asarray(chi2),
                        "param_names": np.array(names), "iter": done})
    return x, chi2, cov
