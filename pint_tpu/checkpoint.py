"""Fit-state checkpointing for long batch fits.

(reference analog: SURVEY.md section 5 — the reference's checkpoint is
the par file itself (TimingModel.as_parfile round-trips full state)
plus the TOA pickle cache. For TPU batch fits this module adds an
orbax-backed snapshot of the numeric fit state between outer
iterations, with a plain-npz fallback, so a preempted multi-hour PTA
run resumes instead of restarting.)
"""

from __future__ import annotations

import os

import numpy as np


class FitCheckpointer:
    """Save/restore (param-vector, iteration, chi2) snapshots.

    Uses orbax-checkpoint when importable (atomic, async-capable);
    falls back to numpy .npz with atomic rename otherwise. Either way
    the on-disk layout is a directory per tag.
    """

    def __init__(self, directory):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        try:
            import orbax.checkpoint as ocp

            self._ocp = ocp
        except ImportError:
            self._ocp = None

    def _path(self, tag):
        return os.path.join(self.directory, str(tag))

    def save(self, tag, state: dict):
        """state: dict of arrays/scalars (e.g. {"x": ..., "iter": i,
        "chi2": ...}). String-valued entries (parameter names) go to a
        JSON sidecar — orbax/tensorstore has no string dtype."""
        import json

        state = {k: np.asarray(v) for k, v in state.items()}
        meta = {k: np.asarray(v).tolist() for k, v in state.items()
                if np.asarray(v).dtype.kind in "US"}
        numeric = {k: v for k, v in state.items()
                   if np.asarray(v).dtype.kind not in "US"}
        meta_path = self._path(tag) + ".meta.json"
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, meta_path)
        if self._ocp is not None:
            import jax

            path = os.path.abspath(self._path(tag))
            ckptr = self._ocp.PyTreeCheckpointer()
            ckptr.save(path, jax.tree_util.tree_map(np.asarray, numeric),
                       force=True)
            return path
        path = self._path(tag) + ".npz"
        tmp = path + ".tmp.npz"
        np.savez(tmp, **numeric)
        os.replace(tmp, path)
        return path

    def restore(self, tag) -> dict | None:
        """Load a snapshot regardless of which backend WROTE it: save()
        picked the format at write time, so an .npz written where orbax
        was absent must still restore once orbax becomes importable
        (and vice versa) instead of silently restarting the fit."""
        import json

        out = None
        if self._ocp is not None:
            path = os.path.abspath(self._path(tag))
            if os.path.isdir(path):
                ckptr = self._ocp.PyTreeCheckpointer()
                try:
                    out = dict(ckptr.restore(path))
                except Exception:
                    out = None
        if out is None:
            path = self._path(tag) + ".npz"
            if os.path.exists(path):
                try:
                    with np.load(path) as z:
                        out = {k: z[k] for k in z.files}
                except OSError:
                    return None
        if out is None:
            return None
        meta_path = self._path(tag) + ".meta.json"
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    out.update({k: np.asarray(v)
                                for k, v in json.load(f).items()})
            except (OSError, json.JSONDecodeError):
                pass
        return out

    def latest_iteration(self, tag) -> int:
        state = self.restore(tag)
        return int(state["iter"]) if state is not None and "iter" in state else -1


def checkpointed_fit(fitter, directory, tag="fit", every=1, maxiter=20,
                     **fit_kw):
    """Run fitter.fit_toas with snapshots between outer iterations.

    Resumes from the saved parameter vector when a snapshot exists
    (per-pulsar failure isolation for batch runs lives in
    parallel/pta.py; this wrapper covers the single-pulsar fitters).
    Snapshots store parameter NAMES alongside values; on resume the
    values are matched by name, and a snapshot whose free-parameter
    set differs from the current model raises instead of silently
    mis-assigning. "iter" counts completed fit iterations.
    """
    ckpt = FitCheckpointer(directory)
    state = ckpt.restore(tag)
    chi2 = None
    if state is not None and "param_values" in state:
        names = [str(n) for n in np.asarray(state["param_names"])]
        current = list(fitter.model.free_params)
        if set(names) != set(current):
            raise ValueError(
                f"checkpoint {tag!r} was taken with free params {names}, "
                f"model has {current}; refusing positional restore")
        vals = dict(zip(names, np.asarray(state["param_values"], float)))
        for name in current:
            getattr(fitter.model, name).value = float(vals[name])
        chi2 = float(state["chi2"])
    done = max(ckpt.latest_iteration(tag), 0)
    while done < maxiter:
        n = min(every, maxiter - done)
        chi2 = fitter.fit_toas(maxiter=n, **fit_kw)
        done += n
        names = list(fitter.model.free_params)
        vals = np.array([getattr(fitter.model, p).value for p in names])
        ckpt.save(tag, {"param_values": vals,
                        "param_names": np.array(names),
                        "iter": done, "chi2": chi2})
    return chi2


def checkpointed_pta_fit(pta, directory, tag="pta", every=1, maxiter=4,
                         method="gls", **fit_kw):
    """Batched analogue of checkpointed_fit: snapshot the (n_psr, k)
    parameter vectors between fit chunks so an interrupted PTA refit
    resumes where it stopped (SURVEY 2.2 elasticity — per-pulsar
    divergence isolation already lives inside PTABatch; this adds the
    between-iterations snapshot). Returns (x, chi2, cov); cov is None
    when the snapshot already covered maxiter."""
    if method not in ("gls", "wls"):
        raise ValueError(f"method must be 'gls' or 'wls', got {method!r}")
    ckpt = FitCheckpointer(directory)
    names = [n for n, _, _ in pta.free_map()]
    state = ckpt.restore(tag)
    if state is not None and not all(
            k in state for k in ("param_names", "x", "chi2", "iter")):
        # partial/foreign snapshot (e.g. a single-pulsar checkpointed_fit
        # tag, or a damaged sidecar): restart cleanly rather than crash
        import warnings

        warnings.warn(f"checkpoint {tag!r} is not a PTA snapshot "
                      f"(keys {sorted(state)}); restarting the fit")
        state = None
    if state is not None:
        saved = [str(n) for n in np.asarray(state["param_names"])]
        if saved != names:
            raise ValueError(
                f"checkpoint {tag!r} was taken with params {saved}, "
                f"batch has {names}; refusing positional restore")
        pta.set_start_vector(np.asarray(state["x"], float))
    done = int(state["iter"]) if state is not None else 0
    fit = pta.gls_fit if method == "gls" else pta.wls_fit
    x = np.asarray(state["x"], float) if state is not None else None
    chi2 = np.asarray(state["chi2"], float) if state is not None else None
    cov = None
    while done < maxiter:
        n = min(every, maxiter - done)
        x, chi2, cov = fit(maxiter=n, **fit_kw)
        done += n
        pta.set_start_vector(x)
        ckpt.save(tag, {"x": np.asarray(x), "chi2": np.asarray(chi2),
                        "param_names": np.array(names), "iter": done})
    return x, chi2, cov
