"""Columnar packed-TOA store: mmap'd post-barycentering arrays.

See :mod:`pint_tpu.store.packstore` for the format, keying, and
failure-handling contract. Public surface::

    from pint_tpu.store import PackStore, content_signature

    store = PackStore("cache/packstore")
    fleet = PTAFleet(models, toas_list, toa_bucket="plan", store=store)
"""

from .deltas import (  # noqa: F401
    DeltaStore,
    chain_signature,
    DELTA_MAGIC,
    DELTA_FORMAT_VERSION,
)
from .packstore import (  # noqa: F401
    PackStore,
    content_signature,
    store_identity,
    STORE_MAGIC,
    STORE_FORMAT_VERSION,
)

__all__ = [
    "PackStore", "content_signature", "store_identity",
    "STORE_MAGIC", "STORE_FORMAT_VERSION",
    "DeltaStore", "chain_signature", "DELTA_MAGIC",
    "DELTA_FORMAT_VERSION",
]
