"""Memory-mapped columnar store of post-barycentering packed arrays.

At the 670k-TOA fleet the astropy-side host chain (clock corrections,
TDB, posvels, design-matrix prep, segment packing) costs ~2.5 s per
bring-up while the refit itself runs in ~1.8 s — and the chain's
output is a pure function of its inputs: the par files, the raw TOA
columns, the ephemeris/clock configuration, and the shape-plan
geometry. This store persists that output once, as one CRC-framed
columnar file per fleet bucket, so warm refits and fresh processes
``mmap`` straight into :meth:`PTABatch.from_packed` and skip the host
chain entirely.

File format mirrors the persisted-executable cache's framing::

    PTPK | u32 manifest_len | u32 manifest_crc32 | manifest JSON
         | aligned column payloads ...

The JSON manifest carries the store identity (format version, jax
version, :data:`~pint_tpu.parallel.shapeplan.PACK_GEOMETRY_VERSION`),
the content signature the entry was written under, one descriptor per
array column (tree path, dtype, shape, offset, nbytes, crc32), and
the offset/crc of a pickled "meta" region holding the non-array
leaves of the pack state (static config, free_map, plan pack tables'
scalars). Columns are 64-byte aligned so the mmap'd views are
directly consumable by ``device_put``.

Keying is CONTENT, not filename convention: :func:`content_signature`
hashes the par files, the raw TOA columns (day/sec/freq/error/obs,
flags when present), the ephemeris + clock configuration, the
shape-plan signatures, and the bucketing options. Any divergence —
edited par file, new TOAs, different ephemeris, a jax or
pack-geometry version bump — lands on a different signature, and a
file whose embedded signature or identity disagrees with the request
is STALE: warn + delete + rebuild from live prep. A CRC mismatch
anywhere (bitrot, torn write that somehow bypassed the atomic
rename) is CORRUPT: same handling. A bad store entry can cost time,
never correctness.

Writes go through :func:`pint_tpu.durable.atomic_write_bytes` (this
module is registered in ``DURABLE_ARTIFACT_MODULES``, so pintlint's
``durable-write-unatomic`` flags any truncating open here), with the
``store_write`` process-kill fault point armed immediately before the
atomic publish — the kill-chaos harness proves a SIGKILL there leaves
no torn artifact.
"""

from __future__ import annotations

import hashlib
import json
import mmap
import os
import pickle
import struct
import threading
import warnings
import zlib

import numpy as np

from ..durable import atomic_write_bytes
from ..obs import trace as obs_trace
from ..resilience import faultinject

__all__ = [
    "PackStore", "content_signature", "store_identity",
    "STORE_MAGIC", "STORE_FORMAT_VERSION",
]

STORE_MAGIC = b"PTPK"
STORE_FORMAT_VERSION = 1
_STORE_HEADER = struct.Struct("<II")  # manifest length, manifest crc32
_ALIGN = 64  # column payload alignment inside the file

# sentinel key marking a numpy-column placeholder in the pickled meta
# tree; real pack-state dicts never contain it
_COL_KEY = "__ptpk_column__"


def store_identity():
    """Environment identity stamped into (and checked against) every
    entry: format version, jax version, and the packed-geometry
    version. The jax version guards ``device_put_staged`` layout
    assumptions; the geometry version guards the silent hazard where
    a ShapePlan's key stays stable while the layout it produces moves
    (the PR 11 quantum-ladder refinement did exactly that)."""
    import jax

    from ..parallel.shapeplan import PACK_GEOMETRY_VERSION

    return {"format": STORE_FORMAT_VERSION,
            "jax_version": jax.__version__,
            "pack_geometry": PACK_GEOMETRY_VERSION}


def _digest_toas(h, toas):
    """Fold one TOAs table's raw (pre-prep) content into ``h``: the
    columns the host chain consumes, plus the ephemeris/clock config
    that selects which chain runs. Never touches derived columns
    (tdb, posvels) — the whole point is to compute the key WITHOUT
    running prep."""
    h.update(np.ascontiguousarray(toas.day).tobytes())
    h.update(np.ascontiguousarray(toas.sec).tobytes())
    h.update(np.ascontiguousarray(toas.freq_mhz).tobytes())
    h.update(np.ascontiguousarray(toas.error_us).tobytes())
    h.update("|".join(str(o) for o in toas.obs).encode())
    if toas.weights is not None:
        h.update(np.ascontiguousarray(toas.weights).tobytes())
    h.update(repr((toas.ephem, toas.planets, toas.include_gps,
                   toas.include_bipm, toas.bipm_version,
                   toas.include_site_clock,
                   tuple(toas.commands))).encode())
    # flags feed maskParameter selection; hash the packed parser blob
    # when present (cheap), else only the non-empty dicts — photon-
    # scale flagless batches contribute nothing and stay O(1)
    raw = getattr(toas, "_flags_raw", None)
    if raw is not None:
        for part in raw:
            h.update(part if isinstance(part, (bytes, bytearray))
                     else repr(part).encode())
    else:
        flags = getattr(toas, "_flags", None)
        if flags is not None:
            for i, f in enumerate(flags):
                if f:
                    h.update(repr((i, sorted(f.items()))).encode())


def content_signature(models, toas_list, plans=None, **build_opts):
    """Hex signature over everything the packed arrays are a function
    of: store/jax/pack-geometry identity, every model's par-file
    serialization, every TOA table's raw columns and clock/ephemeris
    config, the shape-plan signatures, and the fleet bucketing
    options. Two fleets with equal signatures would build
    bit-identical pack states; anything else must miss.

    The environment identity (:func:`store_identity` — format, jax,
    pack-geometry versions) is deliberately NOT part of this hash:
    it is stamped into each entry's manifest and checked at load, so
    a jax or geometry bump finds the old entry at the same path and
    invalidates it VISIBLY (warn + delete + rebuild) instead of
    silently missing and leaving an orphan on disk."""
    h = hashlib.sha256()
    for m in models:
        h.update(m.as_parfile().encode())
        h.update(b"\x00")
    for t in toas_list:
        _digest_toas(h, t)
        h.update(b"\x00")
    if plans:
        for skey in sorted(plans, key=repr):
            h.update(repr(skey).encode())
            h.update(plans[skey].signature().encode())
    h.update(repr(sorted(build_opts.items())).encode())
    return "pack-" + h.hexdigest()[:40]


def _flatten_state(state):
    """Split a pack_state tree into (meta_tree, columns): numeric
    numpy leaves become indexed column placeholders, everything else
    stays in the (pickled) meta tree. Walks dicts/lists/tuples only —
    pack_state is built from exactly those."""
    columns = []

    def walk(node):
        if isinstance(node, np.ndarray) and node.dtype != object:
            columns.append(np.ascontiguousarray(node))
            return {_COL_KEY: len(columns) - 1}
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(state), columns


def _substitute(node, arrays):
    if isinstance(node, dict):
        if _COL_KEY in node and len(node) == 1:
            return arrays[node[_COL_KEY]]
        return {k: _substitute(v, arrays) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return type(node)(_substitute(v, arrays) for v in node)
    return node


def _align_up(n):
    return ((n + _ALIGN - 1) // _ALIGN) * _ALIGN


class PackStore:
    """Disk store of :meth:`PTABatch.pack_state` snapshots, one
    mmap'd columnar file per (content signature, bucket key).

    Thread-safe: the fleet's pipelined prep workers load/put
    concurrently, and the serve bring-up prewarm thread verifies
    entries while the engine constructs — every counter/staging
    access holds ``_lock``. The mmaps themselves are read-only and
    per-call, so verified views never race the prewarm."""

    def __init__(self, directory):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.RLock()
        self._prewarmed = {}  # path -> verified state tree
        self._prewarm_thread = None
        self._mmaps = []  # keep mapped buffers alive for loaded views
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.rebuilds = 0  # miss of any flavor -> caller ran live prep
        self.corrupt = 0
        self.stale = 0
        self.prewarm_hits = 0
        self.bytes_written = 0
        self.bytes_mapped = 0

    # -- keying -------------------------------------------------------

    def _path(self, signature, bucket_key):
        digest = hashlib.sha256(
            (signature + "|" + repr(bucket_key)).encode()
        ).hexdigest()[:32]
        return os.path.join(self.directory, digest + ".ptpk")

    # -- write path ---------------------------------------------------

    def put(self, signature, bucket_key, state):
        """Persist one bucket's pack_state atomically; returns the
        byte size written. The ``store_write`` kill site fires before
        the atomic publish, so a crash there leaves the previous
        entry (or nothing) — never a torn file."""
        meta_tree, columns = _flatten_state(state)
        meta_blob = pickle.dumps(meta_tree)
        descs = []
        # region offsets are relative to the start of the column area
        # (which itself starts aligned after the manifest); computed
        # in two passes because the manifest length shifts the base
        off = _align_up(len(meta_blob))
        for arr in columns:
            descs.append({"dtype": arr.dtype.str,
                          "shape": list(arr.shape),
                          "offset": off, "nbytes": arr.nbytes,
                          "crc32": zlib.crc32(arr.data)})
            off = _align_up(off + arr.nbytes)
        manifest = {
            "identity": store_identity(),
            "signature": signature,
            "bucket": repr(bucket_key),
            "meta": {"offset": 0, "nbytes": len(meta_blob),
                     "crc32": zlib.crc32(meta_blob)},
            "columns": descs,
        }
        mjson = json.dumps(manifest, sort_keys=True).encode()
        head = len(STORE_MAGIC) + _STORE_HEADER.size
        base = _align_up(head + len(mjson))
        parts = [STORE_MAGIC,
                 _STORE_HEADER.pack(len(mjson), zlib.crc32(mjson)),
                 mjson, b"\x00" * (base - head - len(mjson)),
                 meta_blob]
        pos = len(meta_blob)
        for arr, d in zip(columns, descs):
            parts.append(b"\x00" * (d["offset"] - pos))
            parts.append(arr.tobytes())
            pos = d["offset"] + d["nbytes"]
        blob = b"".join(parts)
        path = self._path(signature, bucket_key)
        with obs_trace.span("store.save", bucket=repr(bucket_key),
                            bytes=len(blob), columns=len(columns)):
            with self._lock:
                # die before the atomic publish: recovery sees the
                # previous good entry or a plain miss, never a tear
                faultinject.fire_kill("store_write",
                                      bucket=repr(bucket_key))
                atomic_write_bytes(path, blob)
                self.puts += 1
                self.bytes_written += len(blob)
        return len(blob)

    # -- read path ----------------------------------------------------

    def load(self, signature, bucket_key):
        """The verified pack_state for (signature, bucket_key), its
        array leaves read-only numpy views over a shared mmap — or
        None (counted as a rebuild) on miss/stale/corrupt, after
        which the caller runs live prep and normally :meth:`put`\\ s
        the result back."""
        path = self._path(signature, bucket_key)
        with obs_trace.span("store.load", bucket=repr(bucket_key)) as sp:
            self._join_prewarm()
            with self._lock:
                state = self._prewarmed.pop(path, None)
                if state is not None:
                    self.hits += 1
                    self.prewarm_hits += 1
                    sp.set(outcome="prewarm_hit")
                    return state
            state = self._load_verified(path, signature)
            with self._lock:
                if state is None:
                    self.misses += 1
                    self.rebuilds += 1
                    sp.set(outcome="miss")
                else:
                    self.hits += 1
                    sp.set(outcome="hit")
            return state

    def _load_verified(self, path, signature=None, pin=True):
        """mmap + full verification: magic, manifest CRC, identity,
        (optional) signature, meta CRC, every column CRC. Any failure
        warns, deletes the entry, and returns None. ``pin=False``
        (scan) skips the keep-alive bookkeeping — the mapping then
        lives only as long as the returned views."""
        try:
            size = os.path.getsize(path)
            fh = open(path, "rb")
        except OSError:
            return None
        try:
            head = len(STORE_MAGIC) + _STORE_HEADER.size
            if size < head:
                self._discard(path, "truncated header")
                return None
            mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        finally:
            fh.close()
        view = memoryview(mm)
        try:
            if bytes(view[:len(STORE_MAGIC)]) != STORE_MAGIC:
                self._discard(path, "bad magic")
                return None
            mlen, mcrc = _STORE_HEADER.unpack(
                view[len(STORE_MAGIC):head])
            if head + mlen > size:
                self._discard(path, "truncated manifest")
                return None
            mjson = view[head:head + mlen]
            if zlib.crc32(mjson) != mcrc:
                self._discard(path, "manifest CRC mismatch")
                return None
            try:
                manifest = json.loads(bytes(mjson))
            except ValueError as e:
                self._discard(path, f"undecodable manifest ({e!r})")
                return None
            ident, want = manifest.get("identity"), store_identity()
            if ident != want:
                self._stale(path, f"identity {ident} != {want}")
                return None
            if signature is not None and \
                    manifest.get("signature") != signature:
                self._stale(path, "content signature mismatch")
                return None
            base = _align_up(head + mlen)
            md = manifest["meta"]
            meta_raw = view[base + md["offset"]:
                            base + md["offset"] + md["nbytes"]]
            if len(meta_raw) != md["nbytes"] or \
                    zlib.crc32(meta_raw) != md["crc32"]:
                self._discard(path, "meta CRC mismatch")
                return None
            arrays = []
            for d in manifest["columns"]:
                lo = base + d["offset"]
                col = view[lo:lo + d["nbytes"]]
                if len(col) != d["nbytes"] or \
                        zlib.crc32(col) != d["crc32"]:
                    self._discard(
                        path, f"column {len(arrays)} CRC mismatch")
                    return None
                arrays.append(np.frombuffer(
                    col, dtype=np.dtype(d["dtype"])
                ).reshape(d["shape"]))
            try:
                meta_tree = pickle.loads(meta_raw)
            except Exception as e:
                self._discard(path, f"undecodable meta ({e!r})")
                return None
            state = _substitute(meta_tree, arrays)
        except BaseException:
            view.release()
            mm.close()
            raise
        if pin:
            with self._lock:
                # the views borrow the mapping; pin it for the process
                self._mmaps.append(mm)
                self.bytes_mapped += size
        return state

    # -- prewarm ------------------------------------------------------

    def prewarm(self, background=True):
        """Verify-and-stage every entry BEFORE the first load needs
        one: the per-column CRC pass is the expensive part of a hit
        (~0.1 s/GB), and on a background thread it overlaps the rest
        of bring-up (journal scan, executable rehydrate, intake) the
        same way ``PersistentExecutableCache.prewarm`` hides the XLA
        deserialize tax. ``load`` joins the worker before touching
        disk, so a half-finished prewarm is never raced. Returns the
        thread, or None when the directory is empty;
        ``background=False`` runs inline (tests)."""
        with self._lock:
            t = self._prewarm_thread
            if t is not None and t.is_alive():
                return t
            try:
                names = sorted(n for n in os.listdir(self.directory)
                               if n.endswith(".ptpk"))
            except OSError:
                names = []
            if not names:
                return None

        def work():
            with obs_trace.span("store.prewarm", entries=len(names)):
                for name in names:
                    path = os.path.join(self.directory, name)
                    with self._lock:
                        if path in self._prewarmed:
                            continue
                    state = self._load_verified(path)
                    if state is not None:
                        with self._lock:
                            self._prewarmed[path] = state

        if not background:
            work()
            return None
        t = threading.Thread(target=work, name="ptpk-prewarm",
                             daemon=True)
        with self._lock:
            self._prewarm_thread = t
        t.start()
        return t

    def _join_prewarm(self):
        # taken WITHOUT self._lock held: the worker needs the lock to
        # publish its entries
        t = self._prewarm_thread
        if t is not None and t.is_alive():
            t.join()

    # -- maintenance --------------------------------------------------

    def scan(self):
        """Classify every on-disk entry without staging it: returns
        {"entries", "valid", "corrupt_or_stale", "bytes"}. The
        kill-chaos recover leg asserts ``corrupt_or_stale == 0`` —
        a SIGKILL mid-write must never leave a torn artifact."""
        entries = valid = bad = nbytes = 0
        before = (self.corrupt, self.stale)
        try:
            names = [n for n in os.listdir(self.directory)
                     if n.endswith(".ptpk")]
        except OSError:
            names = []
        for name in names:
            path = os.path.join(self.directory, name)
            entries += 1
            try:
                nbytes += os.path.getsize(path)
            except OSError:
                pass
            if self._load_verified(path, pin=False) is not None:
                valid += 1
            else:
                bad += 1
        with self._lock:
            # scan is a health probe, not traffic: undo its effect on
            # the corruption counters so telemetry stays causal
            self.corrupt, self.stale = before
        return {"entries": entries, "valid": valid,
                "corrupt_or_stale": bad, "bytes": nbytes}

    def _stale(self, path, why):
        with self._lock:
            self.stale += 1
        warnings.warn(
            f"pack-store entry {os.path.basename(path)} is stale "
            f"({why}); deleting and rebuilding from live prep")
        self._remove(path)

    def _discard(self, path, why):
        with self._lock:
            self.corrupt += 1
        warnings.warn(
            f"pack-store entry {os.path.basename(path)} unusable "
            f"({why}); deleting and rebuilding from live prep")
        self._remove(path)

    @staticmethod
    def _remove(path):
        try:
            os.unlink(path)
        except OSError:
            pass

    def _damage(self, signature, bucket_key, offset=0):
        """Flip one column-area byte in place (fault-injection/test
        helper) — the bitrot the per-column CRCs exist to catch."""
        path = self._path(signature, bucket_key)
        size = os.path.getsize(path)
        head = len(STORE_MAGIC) + _STORE_HEADER.size
        with open(path, "r+b") as fh:
            mlen, _ = _STORE_HEADER.unpack(
                fh.read(head)[len(STORE_MAGIC):])
            pos = (_align_up(head + mlen) + offset) % max(size, 1)
            fh.seek(pos)
            byte = fh.read(1)
            fh.seek(pos)
            fh.write(bytes([byte[0] ^ 0xFF]))

    def counters(self):
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "puts": self.puts, "rebuilds": self.rebuilds,
                    "corrupt": self.corrupt, "stale": self.stale,
                    "prewarm_hits": self.prewarm_hits,
                    "bytes_written": self.bytes_written,
                    "bytes_mapped": self.bytes_mapped}
