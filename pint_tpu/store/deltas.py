"""Append-only delta segments chained beside the pack store.

The pack store's unit of persistence is a whole bucket — ~605 MB of
post-barycentering columns at the 670k fleet — which is exactly
wrong for append traffic: a handful of new TOAs per pulsar per epoch
must not rewrite the base entry. This module persists each
``append_toas`` batch as its own small columnar file (the whitened
design rows, residuals and error weights the incremental GLS delta
consumes — kernels/incremental.py), content-chained to the base::

    chain_0 = <base content signature>            (the pack entry)
    chain_i = sha256(chain_{i-1} | payload digest)[:40]

Every segment's manifest embeds its parent chain signature and its
own, so the on-disk lane state is a hash chain rooted at the base
entry. Verification walks the chain in sequence order: a segment
whose parent does not match the verified predecessor's chain
signature — a stale delta left over from a different base, a
reordered or deleted predecessor — invalidates VISIBLY (warn +
delete it and every successor) and the caller replays appends from
the journal or refits from scratch. A CRC failure anywhere is
CORRUPT: same handling. A bad delta can cost a refit, never
correctness.

File framing mirrors the pack store::

    PTPD | u32 manifest_len | u32 manifest_crc32 | manifest JSON
         | aligned column payloads ...

with the same environment identity stamp (format / jax /
PACK_GEOMETRY_VERSION — the v3 manifest revision is what marks a
base entry as chain-capable), checked at load so a geometry bump
invalidates old chains visibly instead of silently missing.

Writes are content-addressed and idempotent: a segment's path is a
function of (lane, sequence, chain signature), so replaying a
journaled ``append_toas`` request after a crash re-publishes the
byte-identical file instead of forking the chain — the exactly-once
story for appends. The ``append_delta_write`` process-kill site fires
immediately before each atomic publish; the kill-chaos harness
proves a SIGKILL there leaves the previous chain tip intact.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import warnings
import zlib

import numpy as np

from ..durable import atomic_write_bytes
from ..obs import trace as obs_trace
from ..resilience import faultinject
from .packstore import store_identity

__all__ = ["DeltaStore", "chain_signature", "DELTA_MAGIC",
           "DELTA_FORMAT_VERSION"]

DELTA_MAGIC = b"PTPD"
DELTA_FORMAT_VERSION = 1
_DELTA_HEADER = struct.Struct("<II")  # manifest length, manifest crc
_ALIGN = 64

# the arrays one append segment persists, in manifest order — the
# exact inputs kernels.incremental.delta_gram consumes
_COLS = ("X", "r", "winv")


def _payload_digest(arrays):
    h = hashlib.sha256()
    for name in _COLS:
        arr = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(arr.dtype.str.encode())
        h.update(repr(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def chain_signature(parent, arrays, rid=""):
    """The chain link for one append segment: hash of the verified
    predecessor's chain signature (the base content signature for the
    first segment), the journaled request id, and this segment's
    column payload. Folding ``rid`` in is what lets a journal replay
    of a persisted-but-uncommitted append be recognized at the chain
    tip while an INTENTIONAL duplicate payload (a different request
    appending identical TOAs) still forms a new link."""
    h = hashlib.sha256()
    h.update(parent.encode())
    h.update(str(rid).encode())
    h.update(_payload_digest(arrays).encode())
    return "delta-" + h.hexdigest()[:40]


def _align_up(n):
    return ((n + _ALIGN - 1) // _ALIGN) * _ALIGN


class DeltaStore:
    """Disk store of append-delta segments, one chained columnar file
    per ``append_toas`` batch.

    Thread-safe: serve lanes append concurrently with the bring-up
    prewarm thread verifying chains — every counter/staging access
    holds ``_lock``. Files are immutable after publish (content
    addressed), so verified reads never race a writer."""

    def __init__(self, directory):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.RLock()
        self._prewarmed = {}  # lane digest -> verified chain list
        self._prewarm_thread = None
        self.puts = 0
        self.replays = 0  # idempotent re-publish of an existing link
        self.loads = 0
        self.stale = 0
        self.corrupt = 0
        self.prewarm_hits = 0
        self.bytes_written = 0

    # -- keying -------------------------------------------------------

    @staticmethod
    def _lane_digest(lane):
        return hashlib.sha256(str(lane).encode()).hexdigest()[:24]

    def _path(self, lane, seq, chain):
        name = (f"{self._lane_digest(lane)}-{seq:06d}-"
                f"{chain.split('-', 1)[1][:16]}.ptpd")
        return os.path.join(self.directory, name)

    # -- write path ---------------------------------------------------

    def append(self, lane, parent, arrays, rid=""):
        """Persist one append batch atomically; returns
        ``(chain_sig, replayed)``. ``parent`` is the caller's view of
        the current chain tip (the base content signature for the
        first append); ``rid`` the journaled request id.

        Exactly-once: if the lane's newest persisted link was created
        by exactly this request (same rid + payload — a journal
        replay of an append that published its delta but died before
        commit), the publish is skipped and the existing tip is
        returned with ``replayed=True``, so replay can never fork the
        chain or double-apply a delta. The ``append_delta_write``
        kill site fires before the atomic publish, so a crash there
        leaves the previous tip."""
        paths = self._chain_paths(lane)
        last = tip = None
        while paths:
            entry = self._load_verified(paths[-1])
            if entry is not None:
                last, _ = entry
                tip = last["chain"]
                break
            # the newest persisted segment is unreadable: a segment
            # published after it could never verify (its on-disk
            # predecessor is broken), so load_chain would later delete
            # the COMMITTED new link silently — post-commit data loss.
            # Treat it as the broken chain it is: invalidate the
            # unreadable tip visibly and chain to the newest verified
            # predecessor instead.
            self._invalidate_from(paths, len(paths) - 1,
                                  "unreadable chain tip")
            paths.pop()
        seq = len(paths)
        if last is not None:
            if last["chain"] == chain_signature(
                    last["parent"], arrays, last.get("rid", "")) \
                    and last.get("rid", "") == str(rid):
                with self._lock:
                    self.replays += 1
                return last["chain"], True
            if parent != tip:
                # the caller's view of the chain has diverged from disk
                raise ValueError(
                    f"append parent {parent!r} is not the lane chain "
                    f"tip {tip!r}")
        chain = chain_signature(parent, arrays, rid)
        blob = self._encode(lane, seq, parent, chain, arrays, rid)
        path = self._path(lane, seq, chain)
        with obs_trace.span("store.delta_append", lane=str(lane),
                            seq=seq, bytes=len(blob)):
            with self._lock:
                # die before the atomic publish: recovery sees the
                # previous chain tip, never a torn delta
                faultinject.fire_kill("append_delta_write",
                                      lane=str(lane), seq=seq)
                atomic_write_bytes(path, blob)
                self.puts += 1
                self.bytes_written += len(blob)
        return chain, False

    def _encode(self, lane, seq, parent, chain, arrays, rid=""):
        cols = [np.ascontiguousarray(arrays[name]) for name in _COLS]
        descs = []
        off = 0
        for name, arr in zip(_COLS, cols):
            descs.append({"name": name, "dtype": arr.dtype.str,
                          "shape": list(arr.shape), "offset": off,
                          "nbytes": arr.nbytes,
                          "crc32": zlib.crc32(arr.data)})
            off = _align_up(off + arr.nbytes)
        manifest = {
            "identity": dict(store_identity(),
                             delta_format=DELTA_FORMAT_VERSION),
            "lane": str(lane), "seq": seq, "rid": str(rid),
            "parent": parent, "chain": chain,
            "columns": descs,
        }
        mjson = json.dumps(manifest, sort_keys=True).encode()
        head = len(DELTA_MAGIC) + _DELTA_HEADER.size
        base = _align_up(head + len(mjson))
        parts = [DELTA_MAGIC,
                 _DELTA_HEADER.pack(len(mjson), zlib.crc32(mjson)),
                 mjson, b"\x00" * (base - head - len(mjson))]
        pos = 0
        for arr, d in zip(cols, descs):
            parts.append(b"\x00" * (d["offset"] - pos))
            parts.append(arr.tobytes())
            pos = d["offset"] + d["nbytes"]
        return b"".join(parts)

    def reset_lane(self, lane):
        """Drop every persisted segment for ``lane`` — the escalation
        re-root. A full refit merges the appended rows into a NEW base
        (new content signature, new linearization), so the old chain —
        rooted at the surrendered base signature — can never verify
        against the rebuilt lane; left on disk it would wedge every
        subsequent append on the parent-divergence guard. Deletion is
        visible (the standard broken-chain warning), any prewarm
        staging for the lane is discarded, and the next append roots a
        fresh chain at the merged base's signature. Restart durability
        for the merged rows then rests on the caller re-registering the
        lane over its current dataset (the journal replays only
        uncommitted appends)."""
        paths = self._chain_paths(lane)
        if paths:
            self._invalidate_from(
                paths, 0, "lane escalated to a full refit; chain "
                "re-rooted at the merged base")
        digest = self._lane_digest(lane)
        with self._lock:
            for key in [k for k in self._prewarmed if k[0] == digest]:
                del self._prewarmed[key]

    # -- read path ----------------------------------------------------

    def _chain_paths(self, lane):
        prefix = self._lane_digest(lane) + "-"
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.startswith(prefix)
                           and n.endswith(".ptpd"))
        except OSError:
            names = []
        return [os.path.join(self.directory, n) for n in names]

    def load_chain(self, lane, base_signature):
        """The verified delta chain for ``lane`` rooted at
        ``base_signature``: a list of ``(chain_sig, {name: array})``
        in append order. Walks the on-disk segments in sequence
        order, re-deriving each chain signature from the verified
        predecessor; the first broken link (stale parent, identity
        or CRC failure) invalidates that segment AND every successor
        visibly, and the verified prefix is returned."""
        with self._lock:
            staged = self._prewarmed.pop(
                (self._lane_digest(lane), base_signature), None)
            if staged is not None:
                self.loads += 1
                self.prewarm_hits += 1
                return staged
        chain = self._load_chain_verified(lane, base_signature)
        with self._lock:
            self.loads += 1
        return chain

    def tip(self, lane, base_signature):
        """The lane's current chain tip signature (the base signature
        when no deltas are persisted)."""
        chain = self.load_chain(lane, base_signature)
        return chain[-1][0] if chain else base_signature

    def _load_chain_verified(self, lane, base_signature):
        out = []
        parent = base_signature
        paths = self._chain_paths(lane)
        for seq, path in enumerate(paths):
            entry = self._load_verified(path)
            if entry is None:
                self._invalidate_from(paths, seq, "unreadable segment")
                break
            manifest, arrays = entry
            if manifest["seq"] != seq or manifest["parent"] != parent:
                self._invalidate_from(
                    paths, seq,
                    f"segment {seq} parent {manifest['parent']!r} != "
                    f"verified tip {parent!r}")
                break
            want = chain_signature(parent, arrays,
                                   manifest.get("rid", ""))
            if manifest["chain"] != want:
                self._invalidate_from(
                    paths, seq,
                    f"segment {seq} chain signature mismatch")
                break
            out.append((manifest["chain"], arrays))
            parent = manifest["chain"]
        return out

    def _load_verified(self, path, count=True):
        """One segment: magic, manifest CRC, identity, column CRCs.
        Returns (manifest, {name: array}) or None (counted corrupt /
        stale unless ``count=False``; the chain walker owns
        deletion)."""
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError:
            return None
        head = len(DELTA_MAGIC) + _DELTA_HEADER.size
        if len(raw) < head or raw[:len(DELTA_MAGIC)] != DELTA_MAGIC:
            self._note_bad("corrupt", count)
            return None
        mlen, mcrc = _DELTA_HEADER.unpack(raw[len(DELTA_MAGIC):head])
        mjson = raw[head:head + mlen]
        if len(mjson) != mlen or zlib.crc32(mjson) != mcrc:
            self._note_bad("corrupt", count)
            return None
        try:
            manifest = json.loads(mjson)
        except ValueError:
            self._note_bad("corrupt", count)
            return None
        ident = dict(store_identity(),
                     delta_format=DELTA_FORMAT_VERSION)
        if manifest.get("identity") != ident:
            self._note_bad("stale", count)
            return None
        base = _align_up(head + mlen)
        arrays = {}
        for d in manifest["columns"]:
            lo = base + d["offset"]
            col = raw[lo:lo + d["nbytes"]]
            if len(col) != d["nbytes"] or \
                    zlib.crc32(col) != d["crc32"]:
                self._note_bad("corrupt", count)
                return None
            arrays[d["name"]] = np.frombuffer(
                col, dtype=np.dtype(d["dtype"])
            ).reshape(d["shape"])
        return manifest, arrays

    def _note_bad(self, kind, count=True):
        if not count:
            return
        with self._lock:
            if kind == "stale":
                self.stale += 1
            else:
                self.corrupt += 1

    def _invalidate_from(self, paths, seq, why):
        names = ", ".join(os.path.basename(p) for p in paths[seq:])
        warnings.warn(
            f"delta chain broken at segment {seq} ({why}); deleting "
            f"{names} — appends replay from the journal or the lane "
            f"refits from scratch")
        for path in paths[seq:]:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- prewarm ------------------------------------------------------

    def prewarm(self, lanes, background=True):
        """Verify-and-stage the delta chains for ``lanes`` — an
        iterable of ``(lane, base_signature)`` — alongside the pack
        store's base prewarm, so the first ``load_chain`` after
        bring-up consumes staged, already-CRC'd segments. Returns the
        worker thread (None when inline or nothing to stage)."""
        lanes = list(lanes)
        if not lanes:
            return None
        with self._lock:
            t = self._prewarm_thread
            if t is not None and t.is_alive():
                return t

        def work():
            with obs_trace.span("store.delta_prewarm",
                                lanes=len(lanes)):
                for lane, base in lanes:
                    key = (self._lane_digest(lane), base)
                    with self._lock:
                        if key in self._prewarmed:
                            continue
                    chain = self._load_chain_verified(lane, base)
                    with self._lock:
                        self._prewarmed[key] = chain

        if not background:
            work()
            return None
        t = threading.Thread(target=work, name="ptpd-prewarm",
                             daemon=True)
        with self._lock:
            self._prewarm_thread = t
        t.start()
        return t

    # -- maintenance --------------------------------------------------

    def scan(self):
        """Classify every on-disk segment without staging or deleting:
        returns {"segments", "valid", "corrupt_or_stale", "bytes"}.
        The kill-chaos recover leg asserts ``corrupt_or_stale == 0``
        — a SIGKILL mid-append must never leave a torn delta. Scan is
        a health probe, not traffic: it counts locally (count=False)
        and never touches the shared corrupt/stale counters, so
        increments from a concurrent load_chain/prewarm survive a
        scan running beside them."""
        segments = valid = bad = nbytes = 0
        try:
            names = [n for n in os.listdir(self.directory)
                     if n.endswith(".ptpd")]
        except OSError:
            names = []
        for name in names:
            path = os.path.join(self.directory, name)
            segments += 1
            try:
                nbytes += os.path.getsize(path)
            except OSError:
                pass
            if self._load_verified(path, count=False) is not None:
                valid += 1
            else:
                bad += 1
        return {"segments": segments, "valid": valid,
                "corrupt_or_stale": bad, "bytes": nbytes}

    def counters(self):
        with self._lock:
            return {"puts": self.puts, "replays": self.replays,
                    "loads": self.loads, "stale": self.stale,
                    "corrupt": self.corrupt,
                    "prewarm_hits": self.prewarm_hits,
                    "bytes_written": self.bytes_written}
