"""Plotting helpers for photon data and residuals (Agg-safe).

(reference: src/pint/plot_utils.py — phaseogram, phaseogram_binned,
plot_priors.)
"""

from __future__ import annotations

import numpy as np


def _plt():
    import matplotlib

    if matplotlib.get_backend().lower() not in ("agg",):
        try:
            matplotlib.use("Agg", force=False)
        except Exception:
            pass
    import matplotlib.pyplot as plt

    return plt


def phaseogram(mjds, phases, weights=None, bins=64, rotate=0.0, size=5,
               alpha=0.3, plotfile=None, title=None):
    """Photon phase vs time scatter with summed profile on top
    (reference: plot_utils.py::phaseogram). Phases are doubled to
    [0, 2) as is conventional."""
    plt = _plt()
    mjds = np.asarray(mjds, float)
    ph = (np.asarray(phases, float) + rotate) % 1.0
    fig, (ax0, ax1) = plt.subplots(
        2, 1, figsize=(6, 8), sharex=True,
        gridspec_kw={"height_ratios": [1, 3]})
    h, edges = np.histogram(ph, bins=bins, range=(0, 1), weights=weights)
    centers = 0.5 * (edges[:-1] + edges[1:])
    ax0.step(np.concatenate([centers, centers + 1.0]),
             np.concatenate([h, h]), where="mid")
    ax0.set_ylabel("Counts")
    if title:
        ax0.set_title(title)
    ph2 = np.concatenate([ph, ph + 1.0])
    t2 = np.concatenate([mjds, mjds])
    w2 = None if weights is None else np.concatenate([weights, weights])
    if w2 is None:
        ax1.scatter(ph2, t2, s=size, alpha=alpha)
    else:
        ax1.scatter(ph2, t2, s=size, alpha=alpha, c=w2, cmap="viridis")
    ax1.set_xlim(0, 2)
    ax1.set_xlabel("Pulse Phase")
    ax1.set_ylabel("MJD")
    fig.tight_layout()
    if plotfile:
        fig.savefig(plotfile, dpi=120)
        plt.close(fig)
        return plotfile
    return fig


def phaseogram_binned(mjds, phases, weights=None, bins=64, ntimebins=32,
                      plotfile=None, title=None):
    """2-D binned phaseogram (reference: plot_utils.py::phaseogram_binned)."""
    plt = _plt()
    mjds = np.asarray(mjds, float)
    ph = np.asarray(phases, float) % 1.0
    ph2 = np.concatenate([ph, ph + 1.0])
    t2 = np.concatenate([mjds, mjds])
    w2 = None if weights is None else np.concatenate([weights, weights])
    H, xe, ye = np.histogram2d(ph2, t2, bins=[2 * bins, ntimebins],
                               range=[[0, 2], [mjds.min(), mjds.max()]],
                               weights=w2)
    fig, ax = plt.subplots(figsize=(6, 6))
    ax.imshow(H.T, origin="lower", aspect="auto",
              extent=[0, 2, mjds.min(), mjds.max()], cmap="magma")
    ax.set_xlabel("Pulse Phase")
    ax.set_ylabel("MJD")
    if title:
        ax.set_title(title)
    fig.tight_layout()
    if plotfile:
        fig.savefig(plotfile, dpi=120)
        plt.close(fig)
        return plotfile
    return fig


def plot_residuals(fitter, plotfile=None, title=None):
    """Pre/post-style residual plot for a fitted model."""
    plt = _plt()
    toas = fitter.toas
    r_us = np.asarray(fitter.resids.time_resids) * 1e6
    mjd = toas.day + toas.sec / 86400.0
    fig, ax = plt.subplots(figsize=(8, 4.5))
    ax.errorbar(mjd, r_us, yerr=toas.error_us, fmt=".", ms=4)
    ax.axhline(0.0, color="0.6", lw=0.8)
    ax.set_xlabel("MJD")
    ax.set_ylabel("Residual (us)")
    if title:
        ax.set_title(title)
    fig.tight_layout()
    if plotfile:
        fig.savefig(plotfile, dpi=120)
        plt.close(fig)
        return plotfile
    return fig
