"""Folded-profile operations (fftfit template matching).

(reference: src/pint/profile/__init__.py + fftfit_aarchiba.py /
fftfit_nustar.py / fftfit_presto.py compat shims — here a single
JAX implementation replaces the three backends.)
"""

from .fftfit import fftfit_basic, fftfit_full, FFTFITResult  # noqa: F401
