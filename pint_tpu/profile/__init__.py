"""Folded-profile operations (fftfit template matching).

(reference: src/pint/profile/__init__.py + fftfit_aarchiba.py /
fftfit_nustar.py / fftfit_presto.py compat shims — here a single
JAX implementation replaces the three backends.)
"""

from .fftfit import (fftfit_basic, fftfit_cc, fftfit_full,  # noqa: F401
                     FFTFITResult)


def fftfit_full_aarchiba(template, profile, **kw):
    """Compat shim matching the reference's aarchiba backend surface
    (reference: profile/fftfit_aarchiba.py::fftfit_full)."""
    return fftfit_full(template, profile, **kw)


def fftfit_basic_aarchiba(template, profile, **kw):
    return fftfit_basic(template, profile, **kw)


def fftfit_full_nustar(template, profile, **kw):
    """nustar-backend shim: upstream returns (shift, eshift, snr, esnr);
    kept callable with the same positional meaning."""
    r = fftfit_full(template, profile, **kw)
    return r.shift, r.uncertainty, r.snr, 0.0


def fftfit_full_presto(template, profile, **kw):
    """presto-backend shim: upstream returns shift in BINS; convert."""
    import numpy as _np

    r = fftfit_full(template, profile, **kw)
    n = len(_np.asarray(profile))
    return r.shift * n, r.uncertainty * n


def fftfit_cprof(profile):
    """presto cprof equivalent: (c, amp, phase) harmonic decomposition
    of a profile (reference: profile/__init__.py::fftfit_cprof)."""
    import numpy as _np

    p = _np.asarray(profile, float)
    spec = _np.fft.rfft(p)
    return p.sum(), _np.abs(spec[1:]), _np.angle(spec[1:])

