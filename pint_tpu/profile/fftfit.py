"""fftfit: measure the phase shift between a folded profile and a
template by Fourier-domain matching (Taylor 1992).

(reference: src/pint/profile/fftfit_aarchiba.py::fftfit_full /
fftfit_basic — model: profile ~ offset + scale * template(phi - shift)
+ noise; solve for shift/scale/offset and their uncertainties.)

Device-side: FFTs and the shift objective are jnp; the 1-D maximize is
a dense grid + fixed Newton polish (no data-dependent control flow).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class FFTFITResult:
    shift: float
    uncertainty: float
    scale: float
    offset: float
    snr: float


def _spectra(template, profile):
    import jax.numpy as jnp

    t = jnp.asarray(template, jnp.float64)
    p = jnp.asarray(profile, jnp.float64)
    n = t.shape[0]
    T = jnp.fft.rfft(t)
    P = jnp.fft.rfft(p)
    return t, p, n, T, P


def fftfit_full(template, profile, ngrid=1024, newton_iters=6):
    """Full Taylor-method fit -> FFTFITResult.

    shift is the phase (in turns, in [-0.5, 0.5)) by which the template
    must be rotated to match the profile.
    """
    import jax.numpy as jnp

    t, p, n, T, P = _spectra(template, profile)
    k = jnp.arange(1, T.shape[0])
    Tk = T[1:]
    Pk = P[1:]
    amp = jnp.abs(Pk) * jnp.abs(Tk)
    dphi = jnp.angle(Pk) - jnp.angle(Tk)

    def corr(tau):
        return jnp.sum(amp * jnp.cos(dphi + 2 * jnp.pi * k * tau))

    def dcorr(tau):
        return jnp.sum(-2 * jnp.pi * k * amp * jnp.sin(dphi + 2 * jnp.pi * k * tau))

    def d2corr(tau):
        return jnp.sum(-(2 * jnp.pi * k) ** 2 * amp * jnp.cos(dphi + 2 * jnp.pi * k * tau))

    taus = jnp.linspace(-0.5, 0.5, ngrid, endpoint=False)
    vals = jnp.sum(
        amp[None, :] * jnp.cos(dphi[None, :] + 2 * jnp.pi * k[None, :] * taus[:, None]),
        axis=1)
    tau = taus[jnp.argmax(vals)]
    for _ in range(newton_iters):
        step = dcorr(tau) / d2corr(tau)
        # keep Newton inside the grid cell (d2<0 at a max)
        tau = tau - jnp.clip(step, -1.0 / ngrid, 1.0 / ngrid)
    # scale and offset (Taylor 1992 eqs.)
    b = corr(tau) / jnp.sum(jnp.abs(Tk) ** 2)
    off = (P[0].real - b * T[0].real) / n
    # noise from the residual power; shift uncertainty from curvature
    resid_pow = (jnp.sum(jnp.abs(Pk) ** 2) - 2 * b * corr(tau)
                 + b**2 * jnp.sum(jnp.abs(Tk) ** 2))
    nfreq = k.shape[0]
    sigma2 = jnp.maximum(resid_pow, 1e-300) / (2.0 * nfreq)
    var_tau = sigma2 / jnp.maximum(-b * d2corr(tau), 1e-300)
    snr = b * jnp.sqrt(jnp.sum(jnp.abs(Tk) ** 2) / jnp.maximum(sigma2, 1e-300))
    shift = float(tau)
    shift -= round(shift)  # wrap to [-0.5, 0.5)
    return FFTFITResult(shift=shift,
                        uncertainty=float(jnp.sqrt(var_tau)),
                        scale=float(b), offset=float(off), snr=float(snr))


def fftfit_basic(template, profile, **kw):
    """Shift only (reference: fftfit_basic)."""
    return fftfit_full(template, profile, **kw).shift


def fftfit_cc(template, profile, upsample=32):
    """Independent cross-correlation backend: zero-padded inverse FFT
    of P * conj(T) (upsampled correlation series) + parabolic peak
    interpolation. Matches fftfit_full's Taylor objective on the same
    grid, so the two backends cross-validate each other (the reference
    ships multiple fftfit backends for the same reason:
    src/pint/profile/fftfit_aarchiba.py / fftfit_nustar.py /
    fftfit_presto.py). Returns shift in turns in [-0.5, 0.5)."""
    import jax.numpy as jnp

    t, p, n, T, P = _spectra(template, profile)
    cross = P * jnp.conj(T)
    cross = cross.at[0].set(0.0)  # DC carries no shift information
    m = n * upsample
    corr = jnp.fft.irfft(cross, m)
    i = jnp.argmax(corr)
    # parabolic interpolation through the peak and its neighbors
    y0 = corr[(i - 1) % m]
    y1 = corr[i]
    y2 = corr[(i + 1) % m]
    denom = y0 - 2 * y1 + y2
    frac = jnp.where(jnp.abs(denom) > 1e-300,
                     0.5 * (y0 - y2) / denom, 0.0)
    tau = (i + frac) / m
    shift = float(tau)
    shift -= round(shift)
    return shift
