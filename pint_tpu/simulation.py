"""Simulation: zero-residual fake TOAs + randomized model draws.

(reference: src/pint/simulation.py — make_fake_toas_uniform /
make_fake_toas_fromMJDs / make_fake_toas_fromtim: iterate the
phase->time inversion until residuals vanish, then optionally add
Gaussian measurement noise and correlated noise realizations;
calculate_random_models.)
"""

from __future__ import annotations

import numpy as np

from .toa import TOA, TOAs
from .residuals import Residuals


def _iterate_zero_residuals(toas: TOAs, model, iterations=4):
    """Shift TOA times until model residuals are ~0 (sub-ns).

    (reference: simulation.py internal zero_residual iteration)
    """
    for _ in range(iterations):
        toas.apply_clock_corrections()
        toas.compute_TDBs()
        toas.compute_posvels()
        r = Residuals(toas, model, subtract_mean=False, track_mode="nearest")
        shift = np.asarray(r.calc_time_resids())
        toas.adjust_times(-shift)
    toas.apply_clock_corrections()
    toas.compute_TDBs()
    toas.compute_posvels()
    return toas


def _apply_noise(toas: TOAs, model, rng, white=True, correlated=False):
    """Add measurement-noise draws to TOA times in place and refresh
    the derived columns. Correlated draws realize each noise
    component's (basis, weights) pair — ECORR per-epoch offsets and
    power-law red-noise Fourier amplitudes — exactly as the GLS fit
    models them (reference: simulation.py add_correlated_noise)."""
    prepared = model.prepare(toas) if (white or correlated) else None
    delta_s = np.zeros(len(toas))
    if white:
        # draw at the MODEL-scaled uncertainty (EFAC/EQUAD applied to
        # mask-matched TOAs), so simulated data matches what the fitter
        # whitens with (reference: simulation.py uses
        # model.scaled_toa_uncertainty, not the raw tim errors)
        sigma_us = np.asarray(prepared.scaled_sigma_us())
        delta_s += rng.standard_normal(len(toas)) * sigma_us * 1e-6
    if correlated:
        for comp in model.components.values():
            bw = getattr(comp, "basis_weight", None)
            if bw is None:
                continue
            B, w_us2 = bw(prepared.params0, prepared.prep)
            B = np.asarray(B)
            w = np.asarray(w_us2)
            if B.size == 0:
                continue
            amps_us = rng.standard_normal(B.shape[1]) * np.sqrt(w)
            delta_s += (B @ amps_us) * 1e-6
    toas.adjust_times(delta_s)
    toas.apply_clock_corrections()
    toas.compute_TDBs()
    toas.compute_posvels()


def _check_wideband_args(model, dm_error_pccm3):
    """Fail fast (before the zero-residual iteration) on wideband
    requests the model/arguments cannot satisfy."""
    if "DispersionDM" not in model.components:
        raise ValueError(
            "wideband=True needs a dispersion model (DM in the par "
            "file) to predict per-TOA DM values")
    if not (dm_error_pccm3 > 0):
        raise ValueError(
            f"dm_error_pccm3 must be > 0 (got {dm_error_pccm3}); the "
            "wideband fit whitens DM residuals by this uncertainty")


def _add_wideband_dm(toas: TOAs, model, rng, dm_error_pccm3, add_noise):
    """Attach wideband DM measurements (-pp_dm/-pp_dme flags) equal to
    the model's DM prediction, optionally with Gaussian scatter at the
    stated DM uncertainty (reference: simulation.py wideband=True —
    fake TOAs carry pp_dm/pp_dme so WidebandTOAFitter has DM data)."""
    from .residuals import wideband_dm_model

    prepared = model.prepare(toas)
    dm_model = np.asarray(wideband_dm_model(model, prepared.params0,
                                            prepared.prep,
                                            batch=prepared.batch))
    dm_obs = dm_model.copy()
    if add_noise:
        dm_obs = dm_obs + rng.standard_normal(len(toas)) * dm_error_pccm3
    for fl, dv in zip(toas.flags, dm_obs):
        fl["pp_dm"] = repr(float(dv))
        fl["pp_dme"] = repr(float(dm_error_pccm3))


def make_fake_toas_uniform(startMJD, endMJD, ntoas, model, error_us=1.0,
                           freq_mhz=1400.0, obs="gbt", add_noise=False,
                           add_correlated_noise=False,
                           seed=None, iterations=4, flags=None,
                           wideband=False, dm_error_pccm3=1e-4,
                           fuzz_days=0.0) -> TOAs:
    """(reference: simulation.py::make_fake_toas_uniform — ``fuzz``
    jitters the nominally uniform epochs by up to +/-fuzz_days/2 so
    simulated cadences don't alias)."""
    mjds = np.linspace(startMJD, endMJD, ntoas)
    if fuzz_days:
        fuzz_rng = np.random.default_rng(None if seed is None else seed + 1)
        mjds = np.sort(mjds + fuzz_rng.uniform(-fuzz_days / 2, fuzz_days / 2,
                                               ntoas))
    return make_fake_toas_fromMJDs(mjds, model, error_us=error_us,
                                   freq_mhz=freq_mhz, obs=obs,
                                   add_noise=add_noise,
                                   add_correlated_noise=add_correlated_noise,
                                   seed=seed, iterations=iterations,
                                   flags=flags, wideband=wideband,
                                   dm_error_pccm3=dm_error_pccm3)


def make_fake_toas(*args, **kw) -> TOAs:
    """Alias for :func:`make_fake_toas_uniform`
    (reference: simulation.py historical make_fake_toas name)."""
    return make_fake_toas_uniform(*args, **kw)


def make_fake_toas_fromMJDs(mjds, model, error_us=1.0, freq_mhz=1400.0,
                            obs="gbt", add_noise=False,
                            add_correlated_noise=False, seed=None,
                            iterations=4, flags=None,
                            wideband=False, dm_error_pccm3=1e-4) -> TOAs:
    """(reference: simulation.py::make_fake_toas_fromMJDs)

    ``flags`` (dict) is applied to every TOA at creation, BEFORE any
    correlated-noise draw — mask-selected noise (EFAC/ECORR "-f L")
    only realizes on TOAs whose flags match at draw time.
    ``wideband=True`` attaches per-TOA DM measurements as
    -pp_dm/-pp_dme flags at the model's DM (scattered by
    ``dm_error_pccm3`` when ``add_noise``).
    """
    if wideband:
        _check_wideband_args(model, dm_error_pccm3)
    mjds = np.asarray(mjds, dtype=np.float64)
    freq = np.broadcast_to(np.asarray(freq_mhz, dtype=np.float64), mjds.shape)
    err = np.broadcast_to(np.asarray(error_us, dtype=np.float64), mjds.shape)
    base_flags = {"simulated": "1", **{k: str(v) for k, v in (flags or {}).items()}}
    toalist = [
        TOA(int(m), (m - int(m)) * 86400.0, error_us=float(e), freq_mhz=float(f),
            obs=obs, flags=dict(base_flags))
        for m, e, f in zip(mjds, err, freq)
    ]
    ephem = "de440s"
    if "EPHEM" in model.params and model.EPHEM.value:
        ephem = model.EPHEM.value.lower()
    planets = bool(model.PLANET_SHAPIRO.value) if "PLANET_SHAPIRO" in model.params else False
    toas = TOAs(toalist, ephem=ephem, planets=planets)
    _iterate_zero_residuals(toas, model, iterations=iterations)
    rng = np.random.default_rng(seed)
    if add_noise or add_correlated_noise:
        _apply_noise(toas, model, rng,
                     white=add_noise, correlated=add_correlated_noise)
    if wideband:
        _add_wideband_dm(toas, model, rng, dm_error_pccm3, add_noise)
    return toas


def make_fake_toas_fromtim(timfile, model, add_noise=False,
                           add_correlated_noise=False, seed=None,
                           wideband=False, dm_error_pccm3=1e-4) -> TOAs:
    """(reference: simulation.py::make_fake_toas_fromtim)"""
    from .toa import read_tim_file

    if wideband:
        _check_wideband_args(model, dm_error_pccm3)
    toalist, _ = read_tim_file(str(timfile))
    ephem = "de440s"
    if "EPHEM" in model.params and model.EPHEM.value:
        ephem = model.EPHEM.value.lower()
    planets = (bool(model.PLANET_SHAPIRO.value)
               if "PLANET_SHAPIRO" in model.params else False)
    toas = TOAs(toalist, ephem=ephem, planets=planets)
    _iterate_zero_residuals(toas, model)
    rng = np.random.default_rng(seed)
    if add_noise or add_correlated_noise:
        _apply_noise(toas, model, rng,
                     white=add_noise, correlated=add_correlated_noise)
    if wideband:
        _add_wideband_dm(toas, model, rng, dm_error_pccm3, add_noise)
    return toas


def calculate_random_models(fitter, toas, n_models=100, seed=None):
    """Sample models from the fit covariance; return residual spread [s].

    (reference: simulation.py::calculate_random_models)
    """
    rng = np.random.default_rng(seed)
    prepared = fitter.model.prepare(toas)
    x0 = np.asarray(prepared.vector_from_params())
    cov = fitter.parameter_covariance_matrix
    draws = rng.multivariate_normal(x0, cov, size=n_models)
    out = np.empty((n_models, len(toas)))
    r = Residuals(toas, fitter.model, prepared=prepared)
    for i, x in enumerate(draws):
        params = prepared.params_with_vector(x)
        out[i] = np.asarray(r.calc_time_resids(params))
    return out
