"""Packaged-data accessors.

(reference: src/pint/config.py — examplefile()/runtimefile() resolve
names inside the installed package's data directories.)
"""

from __future__ import annotations

import os

_DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def datadir() -> str:
    return _DATA


def examplefile(name: str) -> str:
    """Full path of a packaged example file (reference: pint.config.examplefile)."""
    path = os.path.join(_DATA, "examples", name)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no packaged example {name!r}")
    return path


def runtimefile(name: str) -> str:
    """Full path of a packaged runtime data file (observatories,
    leap seconds, clock chains; reference: pint.config.runtimefile)."""
    for sub in ("", "clock"):
        path = os.path.join(_DATA, sub, name)
        if os.path.exists(path):
            return path
    raise FileNotFoundError(f"no packaged runtime file {name!r}")
