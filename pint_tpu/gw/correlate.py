"""All-pairs cross-correlation sweep over the common epoch lattice.

For every unordered pulsar pair (a, b) the optimal statistic needs the
weighted zero-lag products

    num_ab = sum_m U_a[m] U_b[m]     U = W * z
    den_ab = sum_m W_a[m] W_b[m]

i.e. the pair correlation rho_ab = num/den and its inverse variance
den. Over a block of pulsars both are plain matmuls (see
kernels/paircorr.py), so the O(P^2) sweep — ~4.5M pairs at 3000
pulsars — is a dense batched-matmul workload.

:func:`correlation_sweep` streams the strict upper triangle in
(block x block) tiles through a caller-supplied fold, so the full
(P, P) pair matrix never materializes: the OS accumulator in gw/hd.py
only ever holds scalars, and peak memory is one (block, block) tile
regardless of P. Diagonal tiles have their a >= b entries zeroed in
BOTH products before the fold sees them, so any fold that weights by
num/den (every accumulation in hd.py does) needs no pair masking of
its own.

Each tile's products go through ``kernels.pair_products`` — the f64
jnp reference by default (the batched-vs-sequential <=1e-12 parity
contract in tests/test_gw.py), the Pallas MXU kernel under
``precision="mixed"`` on TPU — and the sweep self-attributes
flops/bytes through obs.costmodel for honest MFU/roofline numbers on
the ``gw.correlate`` span.
"""

from __future__ import annotations

import numpy as np

from ..obs import clock as obs_clock
from ..obs import costmodel, metricsreg
from ..obs import trace as obs_trace


def correlation_sweep(z, w, fold, block=256, precision="f64",
                      interpret=False):
    """Stream every unordered pulsar pair's (num, den) products
    through ``fold(a0, b0, num, den)`` in (block x block) tiles:
    ``num``/``den`` are host f64 arrays covering global pulsar rows
    ``a0:a0+num.shape[0]`` x cols ``b0:b0+num.shape[1]``, with
    invalid (a >= b) entries zeroed. Returns the sweep stats dict
    {n_psr, n_cells, n_pairs, n_blocks, wall_s, pairs_per_s, flops,
    mfu_pct, roofline_pct, bound}."""
    import jax.numpy as jnp

    from ..kernels import pair_products

    z = np.asarray(z, np.float64)
    w = np.asarray(w, np.float64)
    P, M = z.shape
    block = max(1, int(block))
    u = w * z
    n_pairs = P * (P - 1) // 2
    flops = 0
    bytes_accessed = 0
    n_blocks = 0
    with obs_trace.span("gw.correlate", n_psr=P, n_cells=M,
                        block=block, precision=precision) as sp:
        t0 = obs_clock.now()
        for a0 in range(0, P, block):
            a1 = min(a0 + block, P)
            ua = jnp.asarray(u[a0:a1])
            wa = jnp.asarray(w[a0:a1])
            for b0 in range(a0, P, block):
                b1 = min(b0 + block, P)
                num, den = pair_products(
                    ua, wa, jnp.asarray(u[b0:b1]),
                    jnp.asarray(w[b0:b1]), precision=precision,
                    interpret=interpret)
                num = np.asarray(num, np.float64)
                den = np.asarray(den, np.float64)
                if b0 == a0:
                    # diagonal tile: keep only a < b
                    ii = np.arange(a0, a1)
                    keep = ii[:, None] < ii[None, :]
                    num = np.where(keep, num, 0.0)
                    den = np.where(keep, den, 0.0)
                fold(a0, b0, num, den)
                ba, bb = a1 - a0, b1 - b0
                flops += 4 * ba * bb * M
                bytes_accessed += 8 * (2 * (ba + bb) * M
                                       + 2 * ba * bb)
                n_blocks += 1
        wall_s = obs_clock.now() - t0
        metricsreg.REGISTRY.counter("gw.pairs").inc(n_pairs)
        metricsreg.REGISTRY.counter("gw.pair_blocks").inc(n_blocks)
        stats = {"n_psr": P, "n_cells": M, "n_pairs": n_pairs,
                 "n_blocks": n_blocks, "wall_s": wall_s,
                 "pairs_per_s": (n_pairs / wall_s if wall_s > 0
                                 else None),
                 "flops": flops, "mfu_pct": None,
                 "roofline_pct": None, "bound": None}
        try:
            attr = costmodel.attribute(flops, bytes_accessed,
                                       wall_s=wall_s)
            stats["mfu_pct"] = attr["mfu_pct"]
            stats["roofline_pct"] = attr["roofline_pct"]
            stats["bound"] = attr["bound"]
        except Exception:
            pass  # attribution is telemetry, the sweep result is not
        sp.set(n_pairs=n_pairs, wall_s=round(wall_s, 6),
               pairs_per_s=stats["pairs_per_s"],
               mfu_pct=stats["mfu_pct"], bound=stats["bound"])
    return stats


def correlation_matrix(z, w, block=256, precision="f64",
                       interpret=False):
    """Materialize the full strict-upper-triangle (P, P) pair
    products — tests and small fleets only; real sweeps stay
    streaming. Returns (num, den, stats)."""
    P = np.asarray(z).shape[0]
    num = np.zeros((P, P))
    den = np.zeros((P, P))

    def fold(a0, b0, nb, db):
        num[a0:a0 + nb.shape[0], b0:b0 + nb.shape[1]] = nb
        den[a0:a0 + db.shape[0], b0:b0 + db.shape[1]] = db

    stats = correlation_sweep(z, w, fold, block=block,
                              precision=precision,
                              interpret=interpret)
    return num, den, stats
