"""Hellings–Downs overlap reduction + the frequentist optimal statistic.

An isotropic GW background imprints on every pulsar pair (a, b) an
expected correlation E[rho_ab] = A^2 * Gamma(xi_ab), where Gamma is
the Hellings–Downs curve of the pair's angular separation xi. With the
pair products from gw/correlate.py (num = rho * den, den = 1/sigma^2
per pair) the standard frequentist optimal statistic is the
inverse-variance-weighted template fit

    A^2_hat = sum_ab Gamma * num / sum_ab Gamma^2 * den
    sigma(A^2_hat) = (sum_ab Gamma^2 * den)^(-1/2)
    S/N = sum_ab Gamma * num / sqrt(sum_ab Gamma^2 * den)

accumulated as scalars inside the streaming pair-block sweep — no
(P, P) matrix. "monopole" (Gamma = 1, clock-like errors) and "dipole"
(Gamma = cos xi, ephemeris-like errors) alternatives use the same
machinery, so an HD detection can be checked against the boring
explanations on identical data.

Significance is calibrated empirically with seeded null draws
(:func:`scramble_null`): sky scrambles redraw every pulsar's position
isotropically (destroying the xi -> Gamma mapping while keeping the
residuals, including any common red signal, untouched), phase shifts
circularly slide each pulsar's lattice row (destroying inter-pulsar
alignment). Draw d uses ``np.random.default_rng([seed, d])`` — the
PR-12 reproducibility idiom — so null distributions are
bit-reproducible across processes and platforms.
"""

from __future__ import annotations

import numpy as np

from ..obs import fitquality as obs_fitq
from ..obs import metricsreg
from ..obs import trace as obs_trace
from .correlate import correlation_sweep


def hd_curve(cos_xi):
    """Hellings–Downs Gamma(xi) from cos(xi) (any shape):
    Gamma = 1.5 x ln x - x/4 + 1/2 with x = (1 - cos xi)/2.
    Coincident distinct pulsars (x -> 0) take the limit 1/2; 90 deg
    gives about -0.1449 and 180 deg gives 1/4."""
    c = np.clip(np.asarray(cos_xi, np.float64), -1.0, 1.0)
    x = 0.5 * (1.0 - c)
    with np.errstate(divide="ignore", invalid="ignore"):
        out = 1.5 * x * np.log(x) - 0.25 * x + 0.5
    return np.where(x > 0.0, out, 0.5)


_ORFS = {
    "hd": hd_curve,
    "monopole": lambda c: np.ones_like(np.asarray(c, np.float64)),
    "dipole": lambda c: np.clip(np.asarray(c, np.float64), -1.0, 1.0),
}


def _orf_fn(orf):
    try:
        return _ORFS[orf]
    except KeyError:
        raise ValueError(f"unknown orf {orf!r}; expected one of "
                         f"{sorted(_ORFS)}") from None


def optimal_statistic(lat, orf="hd", precision="f64", block=256,
                      interpret=False, z_limit=4.0):
    """Frequentist optimal statistic over a
    :class:`~pint_tpu.gw.residuals.GWLattice`: amplitude-squared
    estimate ``amp2`` (+ its ``sigma_amp2``), detection ``snr``, and
    per-pair coherence accounting (pairs whose normalized correlation
    ``num/sqrt(den)`` exceeds ``z_limit`` are counted incoherent and,
    when fit-quality probing is enabled, folded into the
    FitQualityLedger for the ``gw_coherence`` SLO). All accumulation
    happens inside the streaming pair sweep — scalars only."""
    pos = np.asarray(lat.pos, np.float64)
    fn = _orf_fn(orf)
    acc = {"s1": 0.0, "s2": 0.0, "n_eff": 0, "n_incoh": 0,
           "max_z": 0.0}

    def fold(a0, b0, num, den):
        ga = pos[a0:a0 + num.shape[0]]
        gb = pos[b0:b0 + num.shape[1]]
        G = fn(ga @ gb.T)
        acc["s1"] += float(np.sum(G * num))
        acc["s2"] += float(np.sum(G * G * den))
        ok = den > 0
        acc["n_eff"] += int(np.count_nonzero(ok))
        with np.errstate(invalid="ignore", divide="ignore"):
            zp = np.where(ok, num / np.sqrt(np.where(ok, den, 1.0)),
                          0.0)
        az = np.abs(zp)
        acc["max_z"] = max(acc["max_z"], float(az.max(initial=0.0)))
        acc["n_incoh"] += int(np.count_nonzero(az > z_limit))

    with obs_trace.span("gw.os", orf=orf, n_psr=lat.n_pulsars,
                        n_cells=lat.n_cells) as sp:
        stats = correlation_sweep(lat.z, lat.w, fold, block=block,
                                  precision=precision,
                                  interpret=interpret)
        s1, s2 = acc["s1"], acc["s2"]
        amp2 = s1 / s2 if s2 > 0 else None
        sigma_amp2 = float(1.0 / np.sqrt(s2)) if s2 > 0 else None
        snr = float(s1 / np.sqrt(s2)) if s2 > 0 else None
        metricsreg.REGISTRY.counter("gw.os_runs").inc()
        if obs_fitq.enabled():
            obs_fitq.FITQ.note_pair_coherence(
                acc["n_eff"], acc["n_incoh"], acc["max_z"])
        sp.set(amp2=amp2, snr=snr, n_pairs=acc["n_eff"],
               n_incoherent=acc["n_incoh"])
    return {"orf": orf, "amp2": amp2, "sigma_amp2": sigma_amp2,
            "snr": snr, "n_pairs": acc["n_eff"],
            "n_incoherent": acc["n_incoh"],
            "max_pair_snr": acc["max_z"], "sweep": stats}


def scramble_null(lat, n_draws=100, seed=0, mode="sky", orf="hd",
                  precision="f64", block=256, interpret=False,
                  snr_obs=None):
    """Empirical null distribution of the optimal-statistic S/N from
    ``n_draws`` seeded scrambles. mode="sky": redraw every pulsar
    position isotropically per draw — one pass over the (position-
    independent) pair products folds ALL draws at once, so the sweep
    cost does not scale with n_draws. mode="phase": circularly shift
    each pulsar's lattice row per draw (one sweep per draw).
    Draw d's generator is ``np.random.default_rng([seed, d])``; the
    returned ``snr_null`` array is bit-reproducible. p_value uses the
    standard (1 + exceedances) / (n_draws + 1) estimator against
    ``snr_obs`` (computed from the unscrambled lattice when not
    supplied)."""
    if mode not in ("sky", "phase"):
        raise ValueError(f"unknown scramble mode {mode!r}")
    if snr_obs is None:
        snr_obs = optimal_statistic(lat, orf=orf, precision=precision,
                                    block=block,
                                    interpret=interpret)["snr"]
    fn = _orf_fn(orf)
    P, M = lat.n_pulsars, lat.n_cells
    D = int(n_draws)
    s1 = np.zeros(D)
    s2 = np.zeros(D)
    with obs_trace.span("gw.scramble", mode=mode, n_draws=D,
                        seed=seed, orf=orf) as sp:
        if mode == "sky":
            vs = np.empty((D, P, 3))
            for d in range(D):
                rng = np.random.default_rng([seed, d])
                v = rng.standard_normal((P, 3))
                vs[d] = v / np.linalg.norm(v, axis=1, keepdims=True)

            def fold(a0, b0, num, den):
                va = vs[:, a0:a0 + num.shape[0]]
                vb = vs[:, b0:b0 + num.shape[1]]
                c = np.einsum("dak,dbk->dab", va, vb)
                G = fn(c)
                s1[...] += np.einsum("dab,ab->d", G, num)
                s2[...] += np.einsum("dab,ab->d", G * G, den)

            correlation_sweep(lat.z, lat.w, fold, block=block,
                              precision=precision,
                              interpret=interpret)
        else:
            pos = np.asarray(lat.pos, np.float64)
            z0 = np.asarray(lat.z, np.float64)
            w0 = np.asarray(lat.w, np.float64)
            for d in range(D):
                rng = np.random.default_rng([seed, d])
                shifts = (rng.integers(1, M, size=P) if M > 1
                          else np.zeros(P, np.int64))
                zd = np.empty_like(z0)
                wd = np.empty_like(w0)
                for p in range(P):
                    zd[p] = np.roll(z0[p], shifts[p])
                    wd[p] = np.roll(w0[p], shifts[p])

                def fold(a0, b0, num, den, d=d):
                    ga = pos[a0:a0 + num.shape[0]]
                    gb = pos[b0:b0 + num.shape[1]]
                    G = fn(ga @ gb.T)
                    s1[d] += float(np.sum(G * num))
                    s2[d] += float(np.sum(G * G * den))

                correlation_sweep(zd, wd, fold, block=block,
                                  precision=precision,
                                  interpret=interpret)
        with np.errstate(invalid="ignore", divide="ignore"):
            snr_null = np.where(s2 > 0, s1 / np.sqrt(np.where(
                s2 > 0, s2, 1.0)), 0.0)
        if snr_obs is None:
            p_value = None
        else:
            exceed = int(np.count_nonzero(
                np.abs(snr_null) >= abs(snr_obs)))
            p_value = (1.0 + exceed) / (D + 1.0)
        metricsreg.REGISTRY.counter("gw.scramble_draws").inc(D)
        sp.set(p_value=p_value, snr_obs=snr_obs)
    return {"mode": mode, "orf": orf, "n_draws": D, "seed": int(seed),
            "snr_null": snr_null, "snr_obs": snr_obs,
            "p_value": p_value}


def isotropic_positions(n, seed=0):
    """(n, 3) isotropic unit vectors — synthetic sky for benches and
    the injected fixture. The seed key [seed, 0, 1] is a distinct
    sub-stream from scramble_null's [seed, draw] draws: with a shared
    key, sky-scramble draw 0 would regenerate the TRUE sky and the
    null would contain the observed statistic by construction."""
    rng = np.random.default_rng([seed, 0, 1])
    v = rng.standard_normal((int(n), 3))
    return v / np.linalg.norm(v, axis=1, keepdims=True)


def inject_gwb(pos, n_cells, amplitude, seed=0, noise_sigma=1.0,
               n_modes=8):
    """Synthetic lattice with an HD-correlated signal of RMS
    ``amplitude`` injected over white noise — the amplitude-recovery
    fixture: ``optimal_statistic(...)["amp2"]`` estimates
    ``amplitude**2``.

    The inter-pulsar covariance is the HD matrix (unit diagonal plus
    a tiny jitter for the Cholesky); per-pulsar time series share
    ``n_modes`` random-phase unit-RMS sinusoids with HD-correlated
    mode amplitudes, so E[rho_ab] = amplitude^2 * Gamma_ab exactly as
    the OS assumes. Weights are the true inverse noise variance."""
    from .residuals import GWLattice

    pos = np.asarray(pos, np.float64)
    P = pos.shape[0]
    M = int(n_cells)
    # [seed, 0, 2]: decorrelated from both the scramble draws
    # ([seed, d]) and the synthetic sky ([seed, 0, 1])
    rng = np.random.default_rng([seed, 0, 2])
    C = hd_curve(pos @ pos.T)
    np.fill_diagonal(C, 1.0)
    C = C + 1e-6 * np.eye(P)
    L = np.linalg.cholesky(C)
    K = int(n_modes)
    t = (np.arange(M) + 0.5) / M
    phase = rng.uniform(0.0, 2.0 * np.pi, K)
    phi = np.sqrt(2.0) * np.cos(
        2.0 * np.pi * np.arange(1, K + 1)[:, None] * t[None, :]
        + phase[:, None])
    coef = (L @ rng.standard_normal((P, K))) / np.sqrt(K)
    signal = float(amplitude) * coef @ phi
    noise = float(noise_sigma) * rng.standard_normal((P, M))
    z = signal + noise
    w = np.full((P, M), 1.0 / float(noise_sigma) ** 2)
    labels = [f"SYN-{i:04d}" for i in range(P)]
    return GWLattice(labels, pos, z, w,
                     t_cells=np.arange(M, dtype=np.float64) + 0.5)
