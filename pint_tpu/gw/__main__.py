"""``python -m pint_tpu.gw`` — synthetic injected-GWB demo.

Builds a seeded isotropic sky, injects an HD-correlated background
into a white-noise lattice, runs the optimal statistic under all
three overlap-reduction templates, and (optionally) calibrates the
HD significance with scramble nulls. Everything is deterministic in
``--seed``, so the JSON output doubles as a quick cross-platform
reproducibility check of the whole gw/ pipeline."""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m pint_tpu.gw",
        description="synthetic injected-GWB optimal-statistic demo")
    ap.add_argument("--pulsars", type=int, default=68)
    ap.add_argument("--cells", type=int, default=256)
    ap.add_argument("--amplitude", type=float, default=0.5,
                    help="injected GWB RMS amplitude (recovered "
                    "as amp2 ~ amplitude^2)")
    ap.add_argument("--noise-sigma", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--scrambles", type=int, default=0,
                    help="sky-scramble null draws (0 = skip)")
    ap.add_argument("--scramble-mode", choices=("sky", "phase"),
                    default="sky")
    ap.add_argument("--block", type=int, default=256)
    ap.add_argument("--precision", choices=("f64", "mixed"),
                    default="f64")
    args = ap.parse_args(argv)

    from . import hd

    pos = hd.isotropic_positions(args.pulsars, seed=args.seed)
    lat = hd.inject_gwb(pos, args.cells, args.amplitude,
                        seed=args.seed, noise_sigma=args.noise_sigma)
    out = {"n_pulsars": args.pulsars, "n_cells": args.cells,
           "injected_amplitude": args.amplitude, "seed": args.seed}
    for orf in ("hd", "monopole", "dipole"):
        os_ = hd.optimal_statistic(lat, orf=orf, block=args.block,
                                   precision=args.precision)
        out[orf] = {"amp2": os_["amp2"], "snr": os_["snr"],
                    "sigma_amp2": os_["sigma_amp2"]}
        if orf == "hd":
            amp2 = os_["amp2"]
            out["recovered_amplitude"] = (
                float(np.sqrt(amp2)) if amp2 and amp2 > 0 else None)
            out["pairs_per_s"] = os_["sweep"]["pairs_per_s"]
            snr_obs = os_["snr"]
    if args.scrambles:
        null = hd.scramble_null(
            lat, n_draws=args.scrambles, seed=args.seed,
            mode=args.scramble_mode, block=args.block,
            precision=args.precision, snr_obs=snr_obs)
        out["null"] = {"mode": null["mode"],
                       "n_draws": null["n_draws"],
                       "p_value": null["p_value"],
                       "snr_null_max": float(
                           np.max(np.abs(null["snr_null"])))}
    json.dump(out, sys.stdout, indent=2, default=float)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
