"""Gravitational-wave detection over fleet fit outputs (ISSUE 15).

The PTA end product is evidence for a gravitational-wave background
in the *inter-pulsar correlations* of post-fit timing residuals: an
isotropic GWB imprints the Hellings–Downs curve Gamma(xi) on the
cross-correlation of every pulsar pair as a function of their angular
separation xi. Everything upstream — packed fleet fits, the whitened
fit-quality ledger, the columnar store — exists to feed this stage.

Pipeline (one pass, all host-orchestrated, device-heavy in the
middle):

1. :mod:`residuals` — assemble per-pulsar post-fit residual/sigma
   arrays from a :class:`~pint_tpu.parallel.pta.PTAFleet`'s fit
   results (``PTABatch.gw_arrays``), sky unit vectors from the timing
   models, and regrid everything onto a common epoch lattice.
2. :mod:`correlate` — the O(P^2) all-pairs cross-correlation sweep as
   tiled batched matmuls over the lattice (kernels/paircorr.py dual
   path), streamed through an upper-triangle pair-block accumulator
   so the 3000-pulsar pair matrix (~4.5M pairs) never materializes.
3. :mod:`hd` — the Hellings–Downs overlap-reduction curve and the
   frequentist optimal statistic (amplitude estimate A^2, S/N,
   per-pair weights), with significance calibrated by seeded
   sky-scramble / phase-shift null draws
   (``np.random.default_rng([seed, draw])``, the PR-12 idiom).

Entry points: ``PTAFleet.gw_stage()`` for fleets, ``python -m
pint_tpu.gw`` for a synthetic injected demo, and the bench.py gw
stage for the tracked ``gw_*`` meta keys. Obs surface: ``gw.correlate``
/ ``gw.os`` / ``gw.scramble`` spans, ``gw.*`` registry counters, and
roofline attribution on the pair-matmul sweep via obs.costmodel.
"""

from . import correlate, hd, residuals  # noqa: F401
from .correlate import correlation_matrix, correlation_sweep  # noqa: F401
from .hd import (hd_curve, inject_gwb, optimal_statistic,  # noqa: F401
                 scramble_null)
from .residuals import (GWInputs, assemble, regrid,  # noqa: F401
                        regrid_append, sky_positions)

__all__ = [
    "GWInputs", "assemble", "correlate", "correlation_matrix",
    "correlation_sweep", "hd", "hd_curve", "inject_gwb",
    "optimal_statistic", "regrid", "regrid_append", "residuals",
    "scramble_null", "sky_positions",
]
