"""GW input assembly: fleet fit outputs -> common-lattice arrays.

The detection statistic consumes three things per pulsar: post-fit
residual seconds, their per-TOA weights, and the sky unit vector.
:func:`assemble` pulls all three from a fitted
:class:`~pint_tpu.parallel.pta.PTAFleet` (``PTABatch.gw_arrays``
evaluates the overlaid fitted parameter vectors through the same
phase/sigma programs the fit used, for both regular and segment-packed
buckets), and :func:`regrid` bins every pulsar onto one shared epoch
lattice so the pair sweep becomes dense matmuls:

    W[p, m] = sum of 1/sigma^2 over pulsar p's TOAs in cell m
    z[p, m] = weighted mean residual of pulsar p in cell m

Cells a pulsar never observed carry W = 0 and drop out of every pair
product naturally (gw/correlate.py multiplies by W before summing),
so irregular cadences and disjoint observing spans need no masking
logic downstream.
"""

from __future__ import annotations

import numpy as np

from ..obs import trace as obs_trace


class GWInputs:
    """Per-pulsar GW inputs in original fleet order: ``labels`` (P),
    ``pos`` (P, 3) sky unit vectors, and ragged per-pulsar ``times``
    (MJD), ``resid`` (seconds), ``weights`` (1/s^2) lists."""

    def __init__(self, labels, pos, times, resid, weights):
        self.labels = list(labels)
        self.pos = np.asarray(pos, np.float64)
        self.times = [np.asarray(t, np.float64) for t in times]
        self.resid = [np.asarray(r, np.float64) for r in resid]
        self.weights = [np.asarray(w, np.float64) for w in weights]

    @property
    def n_pulsars(self):
        return len(self.labels)


class GWLattice:
    """Common-lattice arrays the pair sweep consumes: ``z`` (P, M)
    weighted-mean residual per cell, ``w`` (P, M) total weight per
    cell (0 = pulsar never observed the cell), ``pos`` (P, 3),
    ``t_cells`` (M,) cell-center MJDs."""

    def __init__(self, labels, pos, z, w, t_cells):
        self.labels = list(labels)
        self.pos = np.asarray(pos, np.float64)
        self.z = np.asarray(z, np.float64)
        self.w = np.asarray(w, np.float64)
        self.t_cells = np.asarray(t_cells, np.float64)

    @property
    def n_pulsars(self):
        return self.z.shape[0]

    @property
    def n_cells(self):
        return self.z.shape[1]


def _unit_vector_equatorial(ra, dec):
    cd = np.cos(dec)
    return np.array([cd * np.cos(ra), cd * np.sin(ra), np.sin(dec)])


def sky_positions(models):
    """(P, 3) ICRS unit vectors from the timing models' astrometry
    (host-side par values, not fitted params: the GW geometry needs
    ~arcminute accuracy, far below any timing-fit position update).
    Ecliptic models rotate to equatorial with the model's own
    obliquity convention, matching ``ssb_to_psb_xyz``."""
    from ..models.astrometry import (AstrometryEcliptic,
                                     AstrometryEquatorial)

    out = np.empty((len(models), 3), np.float64)
    for i, model in enumerate(models):
        comp = None
        for c in model.components.values():
            if isinstance(c, (AstrometryEquatorial, AstrometryEcliptic)):
                comp = c
                break
        if comp is None:
            raise ValueError(
                f"model {i} has no astrometry component; GW "
                "correlations need sky positions (pass positions= "
                "explicitly to assemble/gw_stage)")
        if isinstance(comp, AstrometryEquatorial):
            out[i] = _unit_vector_equatorial(model.RAJ.value,
                                             model.DECJ.value)
        else:
            lon, lat = model.ELONG.value, model.ELAT.value
            cb = np.cos(lat)
            x, y, z = cb * np.cos(lon), cb * np.sin(lon), np.sin(lat)
            eps = comp.obliquity_rad()
            ce, se = np.cos(eps), np.sin(eps)
            out[i] = [x, ce * y - se * z, se * y + ce * z]
    return out


def assemble(fleet, xs, positions=None):
    """Per-pulsar GW inputs from a fitted fleet: evaluate each
    bucket's post-fit residuals/sigmas at the fitted vectors ``xs``
    (the ``fleet.fit()`` per-pulsar list) and collect sky positions.
    ``positions`` (P, 3) overrides the model astrometry — required
    for store-rebuilt fleets whose template models carry no real
    coordinates."""
    n = fleet.n
    labels = [None] * n
    times = [None] * n
    resid = [None] * n
    weights = [None] * n
    pos = (np.asarray(positions, np.float64)
           if positions is not None else np.empty((n, 3)))
    if pos.shape != (n, 3):
        raise ValueError(f"positions shape {pos.shape} != ({n}, 3)")
    with obs_trace.span("gw.assemble", n_psr=n,
                        n_buckets=len(fleet.group_indices)):
        for key, idxs in fleet.group_indices.items():
            batch = fleet._resolve(key)
            xb = np.stack([np.asarray(xs[i], np.float64)
                           for i in idxs])
            arrays = batch.gw_arrays(xb)
            blabels = batch._pulsar_labels()
            if positions is None:
                pos[idxs] = sky_positions(batch.models)
            mask = arrays["mask"]
            sig_s = arrays["sigma_us"] * 1e-6
            for j, i in enumerate(idxs):
                m = mask[j]
                labels[i] = blabels[j]
                times[i] = arrays["mjd"][j][m]
                resid[i] = arrays["resid"][j][m]
                weights[i] = 1.0 / np.square(sig_s[j][m])
    return GWInputs(labels, pos, times, resid, weights)


def regrid(inputs, lattice_days=30.0, t0=None, t1=None):
    """Bin every pulsar onto one shared epoch lattice of
    ``lattice_days``-wide cells spanning the fleet's joint observing
    window. Weighted mean per cell: the zero-lag pair products then
    compare simultaneous residuals without per-pair interpolation."""
    if t0 is None:
        t0 = min(float(t[0]) for t in inputs.times if t.size)
    if t1 is None:
        t1 = max(float(t[-1]) for t in inputs.times if t.size)
    dt = float(lattice_days)
    n_cells = max(1, int(np.floor((t1 - t0) / dt)) + 1)
    P = inputs.n_pulsars
    w = np.zeros((P, n_cells))
    u = np.zeros((P, n_cells))
    for p in range(P):
        t, r, wt = inputs.times[p], inputs.resid[p], inputs.weights[p]
        cells = np.floor((t - t0) / dt).astype(np.int64)
        ok = (cells >= 0) & (cells < n_cells)
        np.add.at(w[p], cells[ok], wt[ok])
        np.add.at(u[p], cells[ok], wt[ok] * r[ok])
    with np.errstate(invalid="ignore"):
        z = np.where(w > 0, u / np.where(w > 0, w, 1.0), 0.0)
    t_cells = t0 + dt * (np.arange(n_cells) + 0.5)
    lat = GWLattice(inputs.labels, inputs.pos, z, w, t_cells)
    # raw weighted-residual accumulators, kept beside the derived z:
    # regrid_append updates (w, u) additively and re-derives z, which
    # is what makes an appended lattice bitwise-identical to a full
    # regrid of the concatenated inputs (z = u/w would not survive a
    # round-trip through z*w)
    lat.u = u
    return lat


def regrid_append(lattice, label, times, resid, weights):
    """Fold one pulsar's appended TOAs into an existing lattice —
    the streaming-refit consumer: an ``append_toas`` request's
    residual delta updates ONE row of the (P, M) lattice in O(r)
    instead of re-running :func:`assemble` + :func:`regrid` over all
    P pulsars' full row sets.

    Exact additive update: ``w' = w + dw``, ``u' = u + du`` with the
    per-cell ``np.add.at`` accumulation order identical to a full
    regrid of base-then-appended concatenated inputs, so the returned
    lattice's (w, u, z) are bitwise what :func:`regrid` would produce
    from scratch (tests/test_incremental.py pins this). Appended
    epochs past the current window GROW the lattice to the right
    (new cells start at zero weight for every other pulsar); epochs
    before the window raise — TOA streams append forward in time.

    Returns a NEW GWLattice (the input is not mutated: pair-sweep
    consumers may still hold it)."""
    if label not in lattice.labels:
        raise KeyError(f"unknown lattice pulsar {label!r}")
    p = lattice.labels.index(label)
    t = np.asarray(times, np.float64)
    r = np.asarray(resid, np.float64)
    wt = np.asarray(weights, np.float64)
    if lattice.t_cells.size > 1:
        dt = float(lattice.t_cells[1] - lattice.t_cells[0])
    else:
        raise ValueError("cannot infer cell width from a single-cell "
                         "lattice; re-run regrid")
    t0 = float(lattice.t_cells[0]) - dt / 2
    cells = np.floor((t - t0) / dt).astype(np.int64)
    if t.size and cells.min() < 0:
        raise ValueError("appended TOAs precede the lattice window; "
                         "streams append forward in time")
    n_cells = max(lattice.n_cells,
                  (int(cells.max()) + 1) if t.size else 0)
    P = lattice.n_pulsars
    w = np.zeros((P, n_cells))
    u = np.zeros((P, n_cells))
    w[:, :lattice.n_cells] = lattice.w
    u[:, :lattice.n_cells] = getattr(
        lattice, "u", lattice.z * lattice.w)
    np.add.at(w[p], cells, wt)
    np.add.at(u[p], cells, wt * r)
    with np.errstate(invalid="ignore"):
        z = np.where(w > 0, u / np.where(w > 0, w, 1.0), 0.0)
    t_cells = t0 + dt * (np.arange(n_cells) + 0.5)
    out = GWLattice(lattice.labels, lattice.pos, z, w, t_cells)
    out.u = u
    return out
