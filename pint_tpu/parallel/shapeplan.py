"""Cost-model-driven shape planner for ragged PTA batches.

The pow2 bucket ladder burns ~37% of Gram/GLS FLOPs on padding at the
670k-TOA fleet scale (BENCH measured_670k_padding_ratio 1.366) and
cold-compiles one program per bucket. This module plans shapes the way
LLM serving stacks plan sequence packing:

- **Segment packing**: several small pulsars share one padded row.
  Each pulsar occupies a contiguous, quantum-aligned *segment* of the
  row; the GLS math stays per-pulsar via segment-summed Grams and
  per-segment eigh solves (parallel/pta.py packed path,
  kernels/seggram.py).
- **Ladder optimization**: an exhaustive search over candidate width
  ladders minimizes padded area subject to a compile budget (number of
  distinct compiled programs), instead of blindly doubling.

A :class:`ShapePlan` is pure host-side geometry — which pulsar goes in
which row of which bucket, at which offset — plus a stable
``signature()`` used by the serve layer's executable-cache keys. The
planner never touches device arrays.

``pow2_width`` wraps serve/batcher.py's ``pow2_bucket`` so that every
bucket-shape decision in the package routes through this module or the
batcher (enforced by the pintlint ``bucket-hardcoded`` rule).
"""

from __future__ import annotations

import hashlib
import itertools
from dataclasses import dataclass, field

__all__ = [
    "Segment", "PlanRow", "PlanBucket", "ShapePlan",
    "align_up", "ladder_width", "plan_shapes", "pow2_width",
    "PACK_GEOMETRY_VERSION",
]

DEFAULT_QUANTUM = 256
DEFAULT_MAX_PACK = 8
DEFAULT_COMPILE_BUDGET = 4
# Version of the PACKED-BATCH GEOMETRY itself: bump whenever the
# layout a ShapePlan (or the pow2/split bucketer) produces for the
# SAME inputs changes — segment alignment rules, renumbering, dummy
# padding conventions, pack_state field layout. A plan key can stay
# stable while the geometry under it moves (the PR 11 quantum-ladder
# refinement did exactly that, forcing bench.py's pack-cache v1->v2
# bump); any on-disk cache of packed arrays (store/, bench
# .bench_cache) must fold this into its content signature so a
# geometry change invalidates cleanly instead of rebuilding batches
# from stale layouts. v3: the append-friendly store manifest revision
# (ISSUE 20) — base entries may now carry delta column segments
# chained beside them (store/deltas.py), so pre-delta entries written
# under v2 must invalidate visibly rather than be silently reused as
# if they were chain bases.
PACK_GEOMETRY_VERSION = 3
# below this, vector lanes go idle and per-program overhead dominates
DEFAULT_MIN_WIDTH = 1024
# candidate-pool size for the ladder search: subsets of <= budget
# widths from <= _POOL candidates keeps the search a few thousand
# ladders even with the quantum ladder multiplying it out
_POOL = 20

# finer segment quanta the planner may refine to when the caller's
# quantum is coarser: every entry is a multiple of 32, so the packed
# path's gcd-derived block size and the 32-aligned ECORR epoch
# quantum stay compatible (parallel/pta.py::stack_packed)
_QUANTUM_LADDER = (128, 96, 64, 32)
# relative cost penalty for finer quanta, x(1 + _QUANTUM_PENALTY/q):
# the block-factorized Gram stores + segment-sums one (K, K) block
# per q rows next to the 2 K^2 multiply-adds per row, an overhead
# share of ~1/(2q); doubled to 1/q to also cover the intermediate's
# memory traffic. A finer quantum must buy its padding back first.
_QUANTUM_PENALTY = 1.0


def pow2_width(n, floor=256):
    """Smallest power-of-two >= n (the legacy ladder). Canonical
    implementation lives in serve/batcher.py; planner and batcher are
    the only modules allowed to call it directly."""
    from ..serve.batcher import pow2_bucket

    return pow2_bucket(n, floor)


def align_up(n, quantum):
    """Round ``n`` up to a multiple of ``quantum`` (minimum one)."""
    n = max(1, int(n))
    q = int(quantum)
    return ((n + q - 1) // q) * q


def ladder_width(n, widths, floor=256):
    """Smallest ladder width >= n; pow2 fallback above the ladder."""
    for w in sorted(widths):
        if w >= n:
            return int(w)
    return pow2_width(n, floor)


@dataclass(frozen=True)
class Segment:
    """One pulsar's quantum-aligned span inside a packed row."""

    index: int   # pulsar position in the planner's input order
    n_toas: int  # real TOA count
    width: int   # aligned segment width (>= n_toas)


@dataclass(frozen=True)
class PlanRow:
    """One padded row: an ordered tuple of segments. The final
    segment absorbs the row tail when the packer widens it to the
    bucket width, so tail padding stays attached to a real pulsar."""

    segments: tuple

    @property
    def used(self):
        return sum(s.width for s in self.segments)

    @property
    def n_toas(self):
        return sum(s.n_toas for s in self.segments)


@dataclass(frozen=True)
class PlanBucket:
    """All rows that share one compiled program shape (width)."""

    width: int
    rows: tuple

    @property
    def n_slots(self):
        return max(len(r.segments) for r in self.rows)

    @property
    def padded_area(self):
        return self.width * len(self.rows)

    @property
    def real_area(self):
        return sum(r.n_toas for r in self.rows)

    def indices(self):
        """Pulsar indices in row-major, slot order."""
        return [s.index for r in self.rows for s in r.segments]

    def renumbered(self):
        """Copy with segment indices replaced by their position in
        ``indices()`` order — the order a packer (stack_packed)
        receives the bucket's pulsars."""
        pos = 0
        rows = []
        for r in self.rows:
            segs = []
            for s in r.segments:
                segs.append(Segment(pos, s.n_toas, s.width))
                pos += 1
            rows.append(PlanRow(tuple(segs)))
        return PlanBucket(self.width, tuple(rows))


@dataclass(frozen=True)
class ShapePlan:
    """The planner's output: buckets plus the knobs that produced
    them. Immutable; ``signature()`` is stable across processes."""

    buckets: tuple
    counts: tuple
    quantum: int = DEFAULT_QUANTUM
    max_pack: int = DEFAULT_MAX_PACK
    compile_budget: int = DEFAULT_COMPILE_BUDGET
    _sig: str = field(default="", compare=False)

    @property
    def n_programs(self):
        return len(self.buckets)

    @property
    def widths(self):
        return tuple(sorted({b.width for b in self.buckets}))

    @property
    def padded_area(self):
        return sum(b.padded_area for b in self.buckets)

    @property
    def real_area(self):
        return sum(b.real_area for b in self.buckets)

    @property
    def padding_ratio(self):
        real = self.real_area
        return float(self.padded_area) / real if real else 1.0

    def indices(self):
        """Every pulsar index, bucket-major (must cover the input
        exactly once — property-tested)."""
        return [i for b in self.buckets for i in b.indices()]

    def width_for(self, n):
        """Serve-side slot width for a single request of ``n`` TOAs:
        smallest planned width that fits, pow2 above the ladder."""
        return ladder_width(n, self.widths)

    def signature(self):
        """Stable short hash of the full geometry, for executable
        cache keys and bench metadata."""
        if self._sig:
            return self._sig
        h = hashlib.blake2s(digest_size=8)
        h.update(repr((self.quantum, self.max_pack,
                       self.compile_budget)).encode())
        for b in self.buckets:
            h.update(repr((b.width,
                           tuple(tuple((s.index, s.width)
                                       for s in r.segments)
                                 for r in b.rows))).encode())
        sig = "plan-" + h.hexdigest()
        object.__setattr__(self, "_sig", sig)
        return sig


def _ffd_pack(segs, width, max_pack):
    """First-fit-decreasing bin packing of segments into rows of
    ``width`` with at most ``max_pack`` segments per row. ``segs`` is
    a list of (seg_width, index, n_toas), pre-sorted descending."""
    rows = []      # list of [remaining, [Segment, ...]]
    for sw, idx, n in segs:
        placed = False
        for row in rows:
            if row[0] >= sw and len(row[1]) < max_pack:
                row[1].append(Segment(idx, n, sw))
                row[0] -= sw
                placed = True
                break
        if not placed:
            rows.append([width - sw, [Segment(idx, n, sw)]])
    return [PlanRow(tuple(r[1])) for r in rows]


# relative cost of one extra evaluation slot per row: the packed path
# evaluates phase + the parameter jacobian once per slot over the
# whole row — cheap next to the K^2-per-TOA Gram but not free. With
# the x-independent slot work hoisted out of the iteration loop
# (parallel/pta.py packed hoist) the residual marginal cost is the
# per-iteration phase/jacobian alone; 0.08 is its measured share.
_SLOT_COST = 0.08
# the planner's padding target: among ladders at or under this ratio
# the slot-overhead cost decides; a ladder over it only wins when no
# compliant ladder exists. 1.05 is the fused-pipeline acceptance
# bound at the 670k fleet scale (ERRORBUDGET.md padded-FLOP budget).
DEFAULT_PADDING_TARGET = 1.05


def _evaluate_ladder(widths, segs_desc, max_pack):
    """Pack every pulsar under a fixed ladder; returns
    (cost, padded_area, buckets). Each pulsar joins the smallest
    ladder width that fits its aligned segment, then FFD packs within
    the width class. Cost = padded area inflated by the per-slot
    evaluation overhead."""
    widths = sorted(widths)
    classes = {w: [] for w in widths}
    for sw, idx, n in segs_desc:
        for w in widths:
            if w >= sw:
                classes[w].append((sw, idx, n))
                break
        else:  # pragma: no cover - ladders always include the max seg
            classes[widths[-1]].append((widths[-1], idx, n))
    buckets = []
    area = 0
    cost = 0.0
    for w in widths:
        if not classes[w]:
            continue
        rows = _ffd_pack(classes[w], w, max_pack)
        bucket = PlanBucket(w, tuple(rows))
        buckets.append(bucket)
        area += w * len(rows)
        cost += w * len(rows) * (1.0 + _SLOT_COST * (bucket.n_slots - 1))
    return cost, area, tuple(buckets)


def _candidate_widths(seg_widths, quantum, min_width):
    """<= _POOL candidate widths: quantiles of the aligned segment
    distribution plus power-of-two-ish pack targets, always including
    the max (every ladder must fit the largest pulsar)."""
    distinct = sorted({max(w, min_width) for w in seg_widths})
    top = distinct[-1]
    pool = {top, min_width}
    # quantile sample of the distribution
    if len(distinct) > 1:
        for k in range(1, _POOL - 2):
            pool.add(distinct[(k * (len(distinct) - 1)) // (_POOL - 2)])
    # pack targets: multiples of the median give small pulsars rows
    # they can genuinely share
    med = distinct[len(distinct) // 2]
    for mult in (2, 3, 4):
        cand = align_up(min(mult * med, top), quantum)
        pool.add(cand)
    pool = sorted(pool)
    if len(pool) > _POOL:
        # keep endpoints, thin the middle
        keep = {pool[0], pool[-1]}
        for k in range(1, _POOL - 1):
            keep.add(pool[(k * (len(pool) - 1)) // (_POOL - 1)])
        pool = sorted(keep)
    return pool


def plan_shapes(counts, quantum=DEFAULT_QUANTUM, max_pack=DEFAULT_MAX_PACK,
                compile_budget=DEFAULT_COMPILE_BUDGET,
                min_width=DEFAULT_MIN_WIDTH,
                padding_target=DEFAULT_PADDING_TARGET):
    """Plan a packed bucket layout for ``counts`` TOA counts.

    Exhaustive search over ladders of <= ``compile_budget`` widths
    drawn from a small candidate pool; each ladder is scored by its
    FFD-packed padded area plus a per-slot evaluation overhead, with
    ``padding_target`` as a soft ceiling: ladders padding worse than
    the target lose to any compliant ladder regardless of slot count.

    ``quantum`` is the COARSEST alignment the caller accepts: the
    search also tries the finer entries of ``_QUANTUM_LADDER`` below
    it (each cost-penalized by x(1 + _QUANTUM_PENALTY/q) for its
    block-bookkeeping overhead) and keeps the overall winner — the
    compile budget is unchanged, only the segment alignment inside
    the same number of programs gets finer. Explicitly fine quanta
    (e.g. test fixtures at 16) see a single-entry ladder and behave
    exactly as before. Deterministic for fixed inputs.
    """
    counts = [int(c) for c in counts]
    if not counts or min(counts) < 1:
        raise ValueError("counts must be a non-empty list of positive ints")
    if compile_budget < 1:
        raise ValueError("compile_budget must be >= 1")
    max_pack = max(1, int(max_pack))
    best = None  # ((over_target, cost, n_widths, n_rows), buckets)
    for q in [int(quantum)] + [m for m in _QUANTUM_LADDER
                               if m < int(quantum)]:
        cand = _plan_for_quantum(counts, q, max_pack, compile_budget,
                                 min_width, padding_target)
        if best is None or cand[0] < best[0]:
            best = cand
    return ShapePlan(buckets=best[1], counts=tuple(counts),
                     quantum=int(quantum), max_pack=max_pack,
                     compile_budget=int(compile_budget))


def _plan_for_quantum(counts, quantum, max_pack, compile_budget,
                      min_width, padding_target):
    """One quantum's ladder search: ((over, cost, n_widths, n_rows),
    buckets) for the best ladder at this alignment, with the cost
    already carrying the finer-quantum penalty so plan_shapes can
    compare candidates across quanta directly."""
    segs = sorted(
        ((max(align_up(n, quantum), 1), i, n)
         for i, n in enumerate(counts)),
        key=lambda t: (-t[0], t[1]))
    seg_widths = [s[0] for s in segs]
    pool = _candidate_widths(seg_widths, quantum, min_width)
    top = max(max(seg_widths), min_width)
    rest = [w for w in pool if w != top]
    real = sum(counts)
    penalty = 1.0 + _QUANTUM_PENALTY / quantum
    best = None
    for k in range(0, min(compile_budget, len(rest) + 1)):
        for combo in itertools.combinations(rest, k):
            cost, area, buckets = _evaluate_ladder(
                combo + (top,), segs, max_pack)
            n_rows = sum(len(b.rows) for b in buckets)
            over = area > padding_target * real
            key = (over, cost * penalty, len(buckets), n_rows)
            if best is None or key < best[0]:
                best = (key, buckets)
    return best
