"""Multi-host (DCN) initialization for PTA fleets.

The reference has no distributed backend at all (SURVEY.md section
2.2: no NCCL/MPI/Gloo anywhere); the TPU-native equivalent is jax's
built-in runtime: collectives ride ICI inside a slice and DCN across
slices/hosts, with no framework-level transport code. What this module
owns is the small amount of glue a pulsar-timing fleet needs:

- ``initialize_distributed``: one-call `jax.distributed.initialize`
  wrapper with env-var defaults (JAX_COORDINATOR_ADDRESS etc.), safe
  to call in single-process runs (num_processes=1) — which is exactly
  how the unit test exercises the real code path without a cluster.
- ``process_pulsar_slice``: which pulsars THIS process should load and
  pack. Host data (tim files) are process-local in a fleet; each host
  packs its shard and the global mesh assembles the batch.
- ``global_pulsar_mesh``: a 1-D 'pulsar' mesh over every device of
  every process (jax.devices() is global after initialization).

Recipe (documented in docs/tutorial_pta.md): initialize on every
process, slice the pulsar list with process_pulsar_slice, build the
local PTABatch arrays, and use
``jax.make_array_from_process_local_data`` with a
``NamedSharding(global_pulsar_mesh(), P('pulsar'))`` to assemble the
fleet-wide batch; PTABatch's jitted fit programs then run unchanged —
XLA inserts the (tiny) cross-host collectives.
"""

from __future__ import annotations

import os


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None, local_device_ids=None):
    """Initialize the jax distributed runtime (DCN); idempotent.
    Returns (process_id, num_processes).

    Arguments left as None fall back to the JAX_* env vars when set
    and otherwise stay None, so jax's built-in cluster auto-detection
    (TPU pod metadata, SLURM, ...) keeps working — substituting
    single-process defaults here would silently split a real fleet
    into standalone hosts."""
    import jax

    if jax.distributed.is_initialized():
        return jax.process_index(), jax.process_count()
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id,
        local_device_ids=local_device_ids)
    return jax.process_index(), jax.process_count()


def process_pulsar_slice(n_pulsars, process_id=None, num_processes=None):
    """Contiguous slice of pulsar indices THIS process loads/packs.

    Contiguous (not strided) so each host's shard maps onto a
    contiguous block of the 'pulsar' mesh axis — the layout
    jax.make_array_from_process_local_data expects."""
    import jax

    pid = jax.process_index() if process_id is None else process_id
    nproc = jax.process_count() if num_processes is None else num_processes
    per = -(-n_pulsars // nproc)  # ceil
    lo = min(pid * per, n_pulsars)
    hi = min(lo + per, n_pulsars)
    return slice(lo, hi)


def global_pulsar_mesh():
    """1-D 'pulsar' mesh over every device of every process
    (jax.devices() is global after initialization) — the same mesh
    mesh.py::make_mesh builds; aliased here for the fleet recipe."""
    from .mesh import make_mesh

    return make_mesh()
