"""Multi-host (DCN) initialization for PTA fleets.

The reference has no distributed backend at all (SURVEY.md section
2.2: no NCCL/MPI/Gloo anywhere); the TPU-native equivalent is jax's
built-in runtime: collectives ride ICI inside a slice and DCN across
slices/hosts, with no framework-level transport code. What this module
owns is the small amount of glue a pulsar-timing fleet needs:

- ``initialize_distributed``: one-call `jax.distributed.initialize`
  wrapper with env-var defaults (JAX_COORDINATOR_ADDRESS etc.), safe
  to call in single-process runs (num_processes=1) — which is exactly
  how the unit test exercises the real code path without a cluster.
- ``process_pulsar_slice``: which pulsars THIS process should load and
  pack. Host data (tim files) are process-local in a fleet; each host
  packs its shard and the global mesh assembles the batch.
- ``global_pulsar_mesh``: a 1-D 'pulsar' mesh over every device of
  every process (jax.devices() is global after initialization).

Recipe (documented in docs/tutorial_pta.md): initialize on every
process, slice the pulsar list with process_pulsar_slice, build the
local PTABatch arrays, and use
``jax.make_array_from_process_local_data`` with a
``NamedSharding(global_pulsar_mesh(), P('pulsar'))`` to assemble the
fleet-wide batch; PTABatch's jitted fit programs then run unchanged —
XLA inserts the (tiny) cross-host collectives.
"""

from __future__ import annotations

import os


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None, local_device_ids=None,
                           timeout_s=None):
    """Initialize the jax distributed runtime (DCN); idempotent.
    Returns (process_id, num_processes).

    Arguments left as None fall back to the JAX_* env vars when set
    and otherwise stay None, so jax's built-in cluster auto-detection
    (TPU pod metadata, SLURM, ...) keeps working — substituting
    single-process defaults here would silently split a real fleet
    into standalone hosts.

    timeout_s (or the JAX_COORDINATOR_TIMEOUT_S env var): bound the
    coordinator handshake. An unreachable/mistyped coordinator address
    otherwise hangs this call for jax's own multi-minute default with
    no indication of what it is waiting for; with a timeout the
    failure is a TimeoutError naming the coordinator address, this
    process's id, and the elapsed wait. The watchdog thread is a
    daemon, so a worker stuck inside the native barrier cannot keep
    the interpreter alive after the error surfaces."""
    import inspect
    import threading
    import time

    import jax

    # not every jax build exposes is_initialized (the 0.4.x graft
    # doesn't); fall back to the runtime state object it wraps, which
    # 0.4.37 keeps only at jax._src.distributed.global_state (the
    # public module re-exports neither name)
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is None:
        def is_init():
            state = getattr(jax.distributed, "global_state", None)
            if state is None:
                try:
                    from jax._src import distributed as _dist_src
                    state = getattr(_dist_src, "global_state", None)
                except ImportError:
                    state = None
            return getattr(state, "client", None) is not None
    if is_init():
        return jax.process_index(), jax.process_count()
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if timeout_s is None and "JAX_COORDINATOR_TIMEOUT_S" in os.environ:
        timeout_s = float(os.environ["JAX_COORDINATOR_TIMEOUT_S"])

    init_kw = dict(coordinator_address=coordinator_address,
                   num_processes=num_processes, process_id=process_id,
                   local_device_ids=local_device_ids)
    # newer jax exposes the handshake bound directly; pass it through
    # WITH a grace margin past our watchdog — the native client
    # LOG(FATAL)s the whole process when ITS deadline fires, so ours
    # must fire first to surface a catchable TimeoutError (the native
    # bound then stops an abandoned worker from waiting forever)
    if timeout_s is not None:
        try:
            sig = inspect.signature(jax.distributed.initialize)
            if "initialization_timeout" in sig.parameters:
                init_kw["initialization_timeout"] = int(timeout_s) + 30
        except (TypeError, ValueError):
            pass

    def _initialize():
        # fleetmesh's work-steal path re-runs initialize_distributed
        # when it re-shards buckets after a device loss; on jax builds
        # where the is_init() fallback chain above cannot see the
        # runtime state (the attribute moved between 0.4.x releases),
        # the native client raises instead of no-oping. Treat exactly
        # that "already initialized" RuntimeError as success — every
        # other error still propagates.
        try:
            jax.distributed.initialize(**init_kw)
        except RuntimeError as e:
            if "already initialized" not in str(e).lower():
                raise

    if timeout_s is None:
        _initialize()
        return jax.process_index(), jax.process_count()

    outcome = {}

    def _worker():
        try:
            _initialize()
            outcome["ok"] = True
        except Exception as e:  # surfaced in the caller below
            outcome["error"] = e

    t0 = time.monotonic()
    worker = threading.Thread(target=_worker, daemon=True,
                              name="pint-tpu-dist-init")
    worker.start()
    worker.join(timeout_s)
    elapsed = time.monotonic() - t0
    if worker.is_alive():
        raise TimeoutError(
            f"jax.distributed.initialize did not complete within "
            f"{timeout_s:.1f}s (waited {elapsed:.1f}s): coordinator "
            f"{coordinator_address!r} unreachable or not every process "
            f"joined (this process_id={process_id}, "
            f"num_processes={num_processes}). Check the coordinator "
            "address/port and that all processes launched; raise "
            "JAX_COORDINATOR_TIMEOUT_S if the cluster is just slow.")
    if "error" in outcome:
        raise outcome["error"]
    return jax.process_index(), jax.process_count()


def process_pulsar_slice(n_pulsars, process_id=None, num_processes=None):
    """Contiguous slice of pulsar indices THIS process loads/packs.

    Contiguous (not strided) so each host's shard maps onto a
    contiguous block of the 'pulsar' mesh axis — the layout
    jax.make_array_from_process_local_data expects."""
    import jax

    pid = jax.process_index() if process_id is None else process_id
    nproc = jax.process_count() if num_processes is None else num_processes
    per = -(-n_pulsars // nproc)  # ceil
    lo = min(pid * per, n_pulsars)
    hi = min(lo + per, n_pulsars)
    return slice(lo, hi)


def global_pulsar_mesh():
    """1-D 'pulsar' mesh over every device of every process
    (jax.devices() is global after initialization) — the same mesh
    mesh.py::make_mesh builds; aliased here for the fleet recipe."""
    from .mesh import make_mesh

    return make_mesh()


def assemble_global_batch(local_pta, mesh=None):
    """Assemble the fleet-global PTABatch from this process's slice.

    Every process builds a PTABatch for ITS pulsars (the contiguous
    ``process_pulsar_slice`` block, in global order) and calls this;
    the local params/prep/batch pytrees become global jax.Arrays
    sharded over the 'pulsar' mesh axis via
    ``jax.make_array_from_process_local_data``. The jitted fit
    programs then run unchanged as one SPMD program across all hosts —
    XLA inserts the (tiny) DCN collectives, exactly the recipe this
    module's docstring describes, now as tested library code.

    Requirements: identical model structure everywhere (as within any
    PTABatch) and identical padded array shapes across processes — pad
    ragged fleets to a common fleet-wide max TOA count before packing.

    Returns the same PTABatch object, mutated in place.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh if mesh is not None else global_pulsar_mesh()
    # ECORR-marginalization eligibility selects which program gets
    # compiled, so every process must decide it identically: check the
    # local slice, AND across processes.
    ok_local = bool("ecorr_eidx" in local_pta.prep
                    and local_pta.prep["ecorr_owner"].shape[-1] > 0)
    if jax.process_count() > 1:
        import zlib

        from jax.experimental import multihost_utils

        n_local = len(local_pta.models)
        has_dense = "ecorr_U" in local_pta.prep
        counts = np.asarray(multihost_utils.process_allgather(
            np.array([n_local, int(ok_local), int(has_dense)])))
        if not (counts[:, 0] == n_local).all():
            raise ValueError(
                "assemble_global_batch needs the same pulsar count on "
                f"every process (even 'pulsar'-axis shards); got "
                f"{counts[:, 0].tolist()} — pad the fleet to a multiple "
                "of process_count()")
        # every process must trace the SAME program over the global
        # arrays: if any slice packed the dense ECORR basis
        # (overlapping masks), sparse slices densify to match — the
        # cross-process analog of stack_prepared's within-process rule
        if counts[:, 2].any() and "ecorr_eidx" in local_pta.prep:
            from ..models.noise import EcorrNoise

            local_pta.prep = dict(local_pta.prep)
            local_pta.prep["ecorr_U"] = EcorrNoise.dense_U(local_pta.prep)
            del local_pta.prep["ecorr_eidx"]
        local_pta._ecorr_marg_ok = bool(counts[:, 1].all())
        # differing padded shapes (TOA max, epoch/basis counts) would
        # surface as a collective mismatch hang deep in XLA — compare a
        # shape signature up front and fail loud instead
        sig_src = repr(sorted(
            [(k, tuple(np.shape(v))) for k, v in local_pta.prep.items()]
            + [(k, tuple(np.shape(v)))
               for k, v in local_pta.params.items()]))
        sig = zlib.crc32(sig_src.encode())
        sigs = np.asarray(multihost_utils.process_allgather(
            np.array([sig], dtype=np.int64)))
        if not (sigs == sig).all():
            raise ValueError(
                "assemble_global_batch: packed array shapes differ "
                "across processes (ragged TOA/epoch/basis maxima) — "
                "pad every process's pack to common fleet-wide maxima")
        # n_toas must describe the GLOBAL fleet (time_residuals masks,
        # metrics); self.models stays local — slice-only labels
        local_pta._pulsar_offset = jax.process_index() * n_local
        local_pta.n_toas = np.concatenate(np.asarray(
            multihost_utils.process_allgather(
                np.asarray(local_pta.n_toas))))
    else:
        local_pta._ecorr_marg_ok = ok_local

    sh = NamedSharding(mesh, P("pulsar"))

    def to_global(x):
        return jax.make_array_from_process_local_data(sh, np.asarray(x))

    local_pta.params, local_pta.prep, local_pta.batch = \
        jax.tree_util.tree_map(
            to_global,
            (local_pta.params, local_pta.prep, local_pta.batch))
    local_pta.mesh = mesh
    local_pta._x0_cache = None
    local_pta._fns = {}
    return local_pta
