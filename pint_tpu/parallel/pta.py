"""PTA batch fitting: vmap over pulsars, pjit over a device mesh.

This is the BASELINE.json north-star path (no reference counterpart —
the reference fits pulsars one at a time in a Python loop): stack many
pulsars' prepared models into one pytree, vmap the whole WLS/GLS
iteration, and shard the pulsar axis across TPU chips with
jax.sharding. A full PTA refit is then ONE jitted program.

Requirements: all pulsars share the same model *structure* (component
set, F order, mask/basis counts — pad counts to the max). TOA counts
are padded to the batch max with sigma=1e30 sentinels so padded rows
vanish from every whitened reduction.
"""

from __future__ import annotations

import threading

import numpy as np

from ..models.timing_model import PreparedTiming
from ..obs import clock as obs_clock
from ..obs import fitquality as obs_fitq
from ..obs import trace as obs_trace

_EXCLUDE_KEYS = ("T_ld", "pepoch_day", "pepoch_sec")
_STATIC_KEYS = ("orb_mode_fb", "planet_shapiro", "obliquity",
                "tropo_on", "ifunc_mode")


def _is_static(key, value):
    """Control-flow config (bools/strs/known keys) must stay Python
    scalars — stacking them into traced arrays breaks `if` branches
    inside the jitted phase functions."""
    return key in _STATIC_KEYS or isinstance(value, (bool, str))
_PAD_SIGMA = 1e30


def _toa_dim_pad(arr, n_toa, n_max):
    """Pad only dimensions equal to this pulsar's own TOA count.

    Non-TOA axes (Taylor orders, mask counts, basis columns) must NOT
    be touched here — ragged counts there are padded with zeros later
    by _pad_to across the batch.
    """
    a = np.asarray(arr)
    if n_toa == n_max:
        return a
    if a.ndim == 1 and a.shape[0] == n_toa:
        a = np.concatenate([a, np.repeat(a[-1:], n_max - n_toa, axis=0)])
    elif a.ndim == 2:
        if a.shape[1] == n_toa:  # (k, n_toa) masks
            a = np.concatenate(
                [a, np.zeros((a.shape[0], n_max - n_toa))], axis=1)
        elif a.shape[0] == n_toa:  # (n_toa, k) bases
            a = np.concatenate(
                [a, np.zeros((n_max - n_toa, a.shape[1]))], axis=0)
    return a


def _pad_single(prepared, n_pad):
    """Pad one pulsar's (batch, prep arrays) TOA dims to n_pad rows so
    the axis divides evenly across shards. Padded rows get the
    _PAD_SIGMA sentinel (vanish from every whitened reduction); basis
    rows pad with zeros."""
    import numpy as np

    import jax.numpy as jnp

    from ..toa import TOABatch

    n = prepared.batch.n_toas
    static, arrays = {}, {}
    for k, v in prepared.prep.items():
        if k in ("T_ld", "pepoch_day", "pepoch_sec"):
            continue
        if _is_static(k, v):
            static[k] = v
        elif k == "ecorr_eidx":
            arrays[k] = jnp.asarray(np.concatenate(
                [np.asarray(v), np.full(n_pad - n, -1, dtype=np.int32)]))
        else:
            arrays[k] = jnp.asarray(_toa_dim_pad(v, n, n_pad))
    fields = {}
    for name in TOABatch._fields:
        a = np.asarray(getattr(prepared.batch, name))
        if n_pad != n:
            if name == "error_us":
                a = np.concatenate([a, np.full(n_pad - n, _PAD_SIGMA)])
            elif a.ndim >= 1 and a.shape[0] == n:
                a = np.concatenate(
                    [a, np.repeat(a[-1:], n_pad - n, axis=0)], axis=0)
            elif a.ndim == 3 and a.shape[1] == n:  # planet (np, n, 3)
                a = np.concatenate(
                    [a, np.repeat(a[:, -1:], n_pad - n, axis=1)], axis=1)
        fields[name] = jnp.asarray(a)
    return TOABatch(**fields), arrays, static


def _pad_to(a, shape):
    out = np.zeros(shape, dtype=np.asarray(a).dtype)
    sl = tuple(slice(0, s) for s in np.asarray(a).shape)
    out[sl] = np.asarray(a)
    return out


def stack_prepared(preps: list[PreparedTiming], pad_toas=None):
    """Stack same-structure PreparedTimings into batched pytrees.

    ``pad_toas`` forces the padded TOA axis to exactly that length
    (must be >= the batch max count). The offline path pads to the
    batch's own max; the serve path pads to the pow2 bucket BOUNDARY
    so every flush of a bucket presents identical shapes to jax.jit
    and the executable cache gets a dispatch hit instead of a retrace.

    Returns (params_stack, prep_stack, batch_stack, static, n_toas).
    """
    import jax.numpy as jnp

    n_max = max(p.batch.n_toas for p in preps)
    if pad_toas is not None:
        if int(pad_toas) < n_max:
            raise ValueError(f"pad_toas={pad_toas} is below the batch "
                             f"max TOA count {n_max}")
        n_max = int(pad_toas)
    n_toas = np.array([p.batch.n_toas for p in preps])

    # ECORR representation must be uniform across the batch: pulsars
    # with overlapping masks pack the dense U, disjoint ones pack the
    # O(n) epoch index (models/noise.py::EcorrNoise.pack). A mixed
    # batch densifies the sparse ones (rare: overlap means hand-built
    # overlapping mask ranges).
    if (any("ecorr_U" in p.prep for p in preps)
            and any("ecorr_eidx" in p.prep for p in preps)):
        from ..models.noise import EcorrNoise

        for p in preps:
            if "ecorr_eidx" in p.prep:
                p.prep["ecorr_U"] = EcorrNoise.dense_U(p.prep)
                del p.prep["ecorr_eidx"]

    # --- params: same keys; vector lengths padded to max
    keys = preps[0].params0.keys()
    params_stack = {}
    for k in keys:
        arrs = [np.atleast_1d(np.asarray(p.params0[k])) for p in preps]
        klen = max(a.shape[0] for a in arrs)
        params_stack[k] = jnp.asarray(
            np.stack([_pad_to(a, (klen,)) if a.ndim else a for a in arrs]))
        if np.asarray(preps[0].params0[k]).ndim == 0:
            params_stack[k] = params_stack[k][:, 0]

    # --- prep: pad TOA dims and ragged mask/basis counts
    static = {}
    prep_stack = {}
    for k in preps[0].prep:
        if k in _EXCLUDE_KEYS:
            continue
        vals = [p.prep[k] for p in preps]
        if _is_static(k, vals[0]):
            assert all(np.all(v == vals[0]) for v in vals), \
                f"prep[{k}] must be uniform across the PTA batch"
            static[k] = vals[0]
            continue
        if k == "ecorr_eidx":
            # epoch indices: padded TOA rows must be OUTSIDE every
            # epoch (-1), not joined to the last real epoch
            arrs = [np.concatenate(
                [np.asarray(v),
                 np.full(n_max - p.batch.n_toas, -1, dtype=np.int32)])
                for v, p in zip(vals, preps)]
            prep_stack[k] = jnp.asarray(np.stack(arrs))
            continue
        arrs = [np.asarray(_toa_dim_pad(v, p.batch.n_toas, n_max))
                for v, p in zip(vals, preps)]
        shape = tuple(max(a.shape[i] for a in arrs) for i in range(arrs[0].ndim))
        # ecorr_owner indexes ECORR params; pad with -1 so padded basis
        # columns get zero weight (see EcorrNoise.basis_weight), not
        # pulsar-0's ECORR prior
        fill = -1 if k == "ecorr_owner" else 0
        prep_stack[k] = jnp.asarray(np.stack(
            [_pad_to(a, shape) if fill == 0 else
             np.concatenate([a, np.full(shape[0] - a.shape[0], fill,
                                        dtype=a.dtype)])
             for a in arrs]))

    # --- batch: pad TOA axis; sentinel sigma on padded rows
    from ..toa import TOABatch

    fields = {}
    for name in TOABatch._fields:
        arrs = []
        for p in preps:
            a = np.asarray(getattr(p.batch, name))
            n = p.batch.n_toas
            if name == "error_us":
                a = np.concatenate([a, np.full(n_max - n, _PAD_SIGMA)])
            elif a.ndim >= 1 and a.shape[-1] == n and name != "planet_pos_ls":
                pad = n_max - n
                a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0) \
                    if a.ndim == 1 else a
            if name == "obs_pos_ls" or name == "obs_vel_ls" or name == "obs_sun_ls":
                if a.shape[0] != n_max:
                    a = np.concatenate(
                        [a, np.repeat(a[-1:], n_max - a.shape[0], axis=0)], axis=0)
            if name == "planet_pos_ls":
                if a.shape[0] and a.shape[1] != n_max:
                    a = np.concatenate(
                        [a, np.repeat(a[:, -1:], n_max - a.shape[1], axis=1)], axis=1)
            if name in ("tdb_day", "tdb_sec", "freq_mhz", "pulse_number") \
                    and a.shape[0] != n_max:
                a = np.concatenate([a, np.repeat(a[-1:], n_max - a.shape[0])])
            arrs.append(a)
        shape = tuple(max(x.shape[i] for x in arrs) for i in range(arrs[0].ndim)) \
            if arrs[0].ndim else ()
        fields[name] = jnp.asarray(np.stack([_pad_to(a, shape) for a in arrs]))
    batch_stack = TOABatch(**fields)
    return params_stack, prep_stack, batch_stack, static, n_toas


def stack_packed(preps: list[PreparedTiming], bucket, e_quantum=32):
    """Pack same-structure PreparedTimings into the segment-packed
    layout of a shapeplan :class:`~.shapeplan.PlanBucket` — several
    small pulsars share one padded row instead of each paying for a
    full bucket width (the padded-FLOP fix the planner exists for).

    Layout (R rows, W = bucket.width columns, S = bucket.n_slots):

    - TOA-dim leaves (batch fields, per-TOA prep arrays, (k, n) masks,
      (n, k) bases) are COMBINED: each row concatenates its members'
      padded segments, so packed memory matches the unpacked stack —
      there is no S-fold copy. The packed GLS path evaluates each slot
      over the whole row and masks to its own segment afterwards.
    - params and non-TOA prep leaves are SLOT-STACKED (R, S, ...);
      rows with fewer members repeat slot 0 (dummy slots own no
      blocks, so their garbage fits are finite and dropped at the
      result gather).
    - prep["_pack_block_slot"] (R, W/Q) int32 maps each Q-sized block
      of TOA rows to its owning slot (Q = gcd of all segment widths —
      segments are quantum-aligned, so any common divisor works and
      the gcd gives the cheapest segment sums).
    - With sparse ECORR, prep["ecorr_eidx"] is renumbered to row-
      global epoch ids (members offset by e_quantum-aligned spans),
      prep["ecorr_owner"] becomes the per-slot (NE,) global owner
      vector (-1 off-slot), and prep["_pack_eblock_slot"] (R, NE/Qe)
      keys the epoch blocks by slot.

    Returns (params, prep, batch, static, n_toas, pack); ``pack`` is
    the host-side layout descriptor (row_of/slot_of gather indices,
    block quanta, slot-stacked key list) the packed GLS path and
    result gather consume.
    """
    import math

    import jax.numpy as jnp

    from ..toa import TOABatch

    W = int(bucket.width)
    rows = bucket.rows
    R = len(rows)
    S = max(len(r.segments) for r in rows)
    Q = math.gcd(W, *[s.width for r in rows for s in r.segments])

    # effective member pad widths: the last member absorbs the row
    # tail, so tail padding stays ordinary sentinel rows of a real
    # pulsar (exactly the sequential path's padding semantics)
    layout = []  # per row: [[prep_index, pad_width], ...]
    for r in rows:
        segs = [[s.index, s.width] for s in r.segments]
        segs[-1][1] += W - r.used
        layout.append(segs)
    n_psr = len(preps)
    if sorted(i for r in layout for i, _ in r) != list(range(n_psr)):
        raise ValueError("plan bucket must cover the prepared pulsars "
                         "exactly once (indices 0..n-1)")

    # uniform ECORR representation across the bucket (see
    # stack_prepared: a mixed bucket densifies the sparse members)
    if (any("ecorr_U" in p.prep for p in preps)
            and any("ecorr_eidx" in p.prep for p in preps)):
        from ..models.noise import EcorrNoise

        for p in preps:
            if "ecorr_eidx" in p.prep:
                p.prep["ecorr_U"] = EcorrNoise.dense_U(p.prep)
                del p.prep["ecorr_eidx"]
    sparse_ecorr = "ecorr_eidx" in preps[0].prep

    padded = {}  # prep index -> (TOABatch, arrays, static)
    for r in layout:
        for i, w in r:
            padded[i] = _pad_single(preps[i], w)

    # classify prep keys once (member 0): an axis equal to the
    # member's own pad width marks a combined (TOA-dim) leaf, same
    # rule as _toa_dim_pad; everything else is slot-stacked
    i0, w0 = layout[0][0]
    combined_keys, slot_keys = set(), set()
    for k, v in padded[i0][1].items():
        if k in ("ecorr_eidx", "ecorr_owner"):
            continue  # placed specially below
        a = np.asarray(v)
        if ((a.ndim == 1 and a.shape[0] == w0)
                or (a.ndim == 2 and w0 in a.shape)):
            combined_keys.add(k)
        else:
            slot_keys.add(k)

    # 2-D combined leaves: which axis is the TOA axis, and the
    # bucket-wide max of the other (ragged mask/basis counts pad with
    # zeros exactly like stack_prepared)
    info2d = {}
    for k in combined_keys:
        a0 = np.asarray(padded[i0][1][k])
        if a0.ndim == 2:
            taxis = 0 if a0.shape[0] == w0 else 1
            kax = 1 - taxis
            kmax = max(np.asarray(padded[i][1][k]).shape[kax]
                       for r in layout for i, _ in r)
            info2d[k] = (taxis, kax, kmax)
    slot_shapes = {}
    for k in slot_keys:
        shapes = [np.asarray(padded[i][1][k]).shape
                  for r in layout for i, _ in r]
        slot_shapes[k] = tuple(max(s[d] for s in shapes)
                               for d in range(len(shapes[0])))

    # row-global epoch numbering: each member's epochs occupy an
    # e_quantum-aligned span so the per-slot epoch Gram can reduce by
    # block (pad epochs have owner -1 -> zero Sherman-Morrison weight)
    NE = 0
    epoch_info = {}
    if sparse_ecorr:
        for r in layout:
            eoff = 0
            for i, _ in r:
                k_i = int(np.asarray(preps[i].prep["ecorr_owner"]).shape[0])
                espan = -(-k_i // int(e_quantum)) * int(e_quantum)
                epoch_info[i] = (eoff, k_i, espan)
                eoff += espan
            NE = max(NE, eoff)

    static = dict(padded[i0][2])
    prep_rows, batch_rows = [], []
    for r in layout:
        comb = {}
        for k in combined_keys:
            parts = [np.asarray(padded[i][1][k]) for i, _ in r]
            if parts[0].ndim == 1:
                comb[k] = np.concatenate(parts)
            else:
                taxis, kax, kmax = info2d[k]
                shaped = []
                for a in parts:
                    tgt = list(a.shape)
                    tgt[kax] = kmax
                    shaped.append(_pad_to(a, tuple(tgt)))
                comb[k] = np.concatenate(shaped, axis=taxis)
        for k in slot_keys:
            vals = [_pad_to(padded[i][1][k], slot_shapes[k])
                    for i, _ in r]
            vals += [vals[0]] * (S - len(vals))
            comb[k] = np.stack(vals)
        if sparse_ecorr:
            eparts, owners = [], []
            for i, _ in r:
                eoff, k_i, _ = epoch_info[i]
                e = np.asarray(padded[i][1]["ecorr_eidx"])
                eparts.append(np.where(e >= 0, e + eoff, -1)
                              .astype(np.int32))
                ow = np.full(NE, -1, dtype=np.int64)
                ow[eoff:eoff + k_i] = np.asarray(
                    preps[i].prep["ecorr_owner"])
                owners.append(ow)
            owners += [np.full(NE, -1, dtype=np.int64)] * (S - len(owners))
            comb["ecorr_eidx"] = np.concatenate(eparts)
            comb["ecorr_owner"] = np.stack(owners)
            ebs = np.zeros(NE // int(e_quantum), dtype=np.int32)
            for s_i, (i, _) in enumerate(r):
                eoff, _, espan = epoch_info[i]
                ebs[eoff // int(e_quantum):
                    (eoff + espan) // int(e_quantum)] = s_i
            comb["_pack_eblock_slot"] = ebs
        elif "ecorr_owner" in preps[i0].prep:
            # dense-U bucket: owner stays local per slot (columns are
            # shared across slots; each slot's rows carry its own U)
            kU = max(np.asarray(p.prep["ecorr_owner"]).shape[0]
                     for p in preps)
            owners = []
            for i, _ in r:
                ow = np.full(kU, -1, dtype=np.int64)
                o = np.asarray(preps[i].prep["ecorr_owner"])
                ow[:o.shape[0]] = o
                owners.append(ow)
            owners += [np.full(kU, -1, dtype=np.int64)] * (S - len(owners))
            comb["ecorr_owner"] = np.stack(owners)
        bs = np.zeros(W // Q, dtype=np.int32)
        off = 0
        for s_i, (i, w) in enumerate(r):
            bs[off // Q:(off + w) // Q] = s_i
            off += w
        comb["_pack_block_slot"] = bs
        prep_rows.append(comb)

        fields = {}
        for name in TOABatch._fields:
            parts = [np.asarray(getattr(padded[i][0], name))
                     for i, _ in r]
            if parts[0].ndim == 3:  # planet (n_planets, n, 3)
                fields[name] = np.concatenate(parts, axis=1)
            else:
                fields[name] = np.concatenate(parts, axis=0)
        batch_rows.append(fields)

    slot_param_keys = set(slot_keys)
    if "ecorr_owner" in preps[i0].prep:
        slot_param_keys.add("ecorr_owner")
    prep_stack = {k: jnp.asarray(np.stack([pr[k] for pr in prep_rows]))
                  for k in prep_rows[0]}
    batch_stack = TOABatch(**{
        name: jnp.asarray(np.stack([br[name] for br in batch_rows]))
        for name in TOABatch._fields})

    keys = preps[0].params0.keys()
    params_stack = {}
    for k in keys:
        arrs = [np.atleast_1d(np.asarray(p.params0[k])) for p in preps]
        klen = max(a.shape[0] for a in arrs)
        rows_np = []
        for r in layout:
            vals = [_pad_to(arrs[i], (klen,)) for i, _ in r]
            vals += [vals[0]] * (S - len(vals))
            rows_np.append(np.stack(vals))
        out = np.stack(rows_np)  # (R, S, klen)
        if np.asarray(preps[0].params0[k]).ndim == 0:
            out = out[:, :, 0]
        params_stack[k] = jnp.asarray(out)

    row_of = np.zeros(n_psr, dtype=np.int64)
    slot_of = np.zeros(n_psr, dtype=np.int64)
    for rr, r in enumerate(layout):
        for s_i, (i, _) in enumerate(r):
            row_of[i] = rr
            slot_of[i] = s_i
    n_toas = np.array([p.batch.n_toas for p in preps])
    pack = {"width": W, "quantum": Q, "e_quantum": int(e_quantum),
            "n_rows": R, "n_slots": S, "n_epochs": int(NE),
            "row_of": row_of, "slot_of": slot_of,
            "slot_keys": sorted(slot_param_keys)}
    return params_stack, prep_stack, batch_stack, static, n_toas, pack


def pure_phase_fn(template_model, static):
    """(params, batch, prep) -> continuous phase; pure, closure-free over
    data so it vmaps over pulsars and shard_maps over the TOA axis."""
    delay_comps = template_model.delay_components()
    phase_comps = template_model.phase_components()

    def phase(params, batch, prep):
        import jax.numpy as jnp

        full_prep = {**prep, **static}
        d = jnp.zeros_like(batch.tdb_sec)
        for c in delay_comps:
            d = d + c.delay(params, batch, full_prep, d)
        ph = jnp.zeros_like(d)
        for c in phase_comps:
            ph = ph + c.phase(params, batch, full_prep, d)
        return ph

    return phase


def pure_sigma_fn(template_model, static):
    comps = [c for c in template_model.components.values()
             if getattr(c, "scale_sigma", None) is not None]

    def sigma_us(params, batch, prep):
        s = batch.error_us
        for c in comps:
            s = c.scale_sigma(params, batch, {**prep, **static}, s)
        return s

    return sigma_us


# precision="auto" verdicts, keyed on (structure, shapes, fit options);
# process-wide so every PTABatch with the same bucket structure reuses
# one timed probe instead of re-racing mixed vs f64. The fleet's
# pipelined executor and concurrent prewarm reach this from worker
# threads, so access holds _PRECISION_AUTO_LOCK (probes themselves run
# outside the lock; racing probes converge via setdefault).
_PRECISION_AUTO_CACHE = {}
_PRECISION_AUTO_LOCK = threading.RLock()


class PTABatch:
    """Batched multi-pulsar fitting (the reference's per-pulsar Python
    loop becomes one vmapped, mesh-sharded program).

    All models must share component structure; see stack_prepared.
    """

    def __init__(self, models, toas_list, mesh=None, pad_toas=None,
                 plan=None):
        """``plan`` (a shapeplan PlanBucket whose segment indices cover
        models/toas_list exactly once) switches to the segment-packed
        layout: several pulsars share one padded row, the GLS math
        runs per-segment (stack_packed / _build_gls_packed), and
        results gather back to per-pulsar order. Packed batches are
        GLS-only and f64-only; no mesh sharding."""
        from ..models.timing_model import _cpu_staging, device_put_staged

        self.models = models
        self.toas_list = toas_list
        self.pad_toas = pad_toas
        self._pack = None
        if plan is not None and mesh is not None:
            raise ValueError("packed plan batches do not support a "
                             "device mesh")
        if plan is not None and pad_toas is not None:
            raise ValueError("pad_toas and plan are mutually exclusive")
        # stage per-pulsar packing + stacking on the CPU backend, then
        # one batched transfer of the stacked trees (behind a tunnel,
        # per-array transfers dominate the pack otherwise)
        with _cpu_staging():
            self.preps = [m.prepare(t) for m, t in zip(models, toas_list)]
            if plan is not None:
                (self.params, self.prep, self.batch, self.static,
                 self.n_toas, self._pack) = stack_packed(self.preps, plan)
            else:
                (self.params, self.prep, self.batch, self.static,
                 self.n_toas) = stack_prepared(self.preps,
                                               pad_toas=pad_toas)
        self.params, self.prep, self.batch = device_put_staged(
            (self.params, self.prep, self.batch))
        self.template = models[0]
        self.mesh = mesh
        if mesh is not None:
            from .mesh import shard_batch

            n_max = int(self.batch.tdb_sec.shape[1])
            self.params = shard_batch(self.params, mesh)
            self.prep = shard_batch(self.prep, mesh, n_toa=n_max)
            self.batch = shard_batch(self.batch, mesh, n_toa=n_max)
        self._fns = {}
        self._costs = {}  # program key -> executable cost record
        self._ecorr_marg_ok = None  # lazy host check, cached (gls_fit)
        self.quality = None  # fitquality bucket summary when enabled

    # -- single-pulsar kernel (closed over static config only) --

    def _phase_fn(self):
        return pure_phase_fn(self.template, self.static)

    def _sigma_fn(self):
        return pure_sigma_fn(self.template, self.static)

    def _resid_fn(self):
        phase = self._phase_fn()
        sigma_fn = self._sigma_fn()

        def resid_seconds(params, batch, prep):
            import jax.numpy as jnp

            ph = phase(params, batch, prep)
            frac = ph - jnp.floor(ph + 0.5)
            sig = sigma_fn(params, batch, prep)
            w = 1.0 / jnp.square(sig)
            frac = frac - jnp.sum(frac * w) / jnp.sum(w)
            return frac / params["F"][0], sig

        return resid_seconds

    @property
    def n_pulsars(self):
        """Batch size from the packed arrays themselves — in a
        multi-process fleet (assemble_global_batch) this is the GLOBAL
        pulsar count while self.models holds only the local slice."""
        import jax

        if getattr(self, "_pack", None):
            # packed layout: leading axis is rows, not pulsars
            return int(len(self._pack["row_of"]))
        return int(jax.tree_util.tree_leaves(self.params)[0].shape[0])

    def free_map(self):
        """Free-parameter layout of the template (uniform across batch)."""
        if getattr(self, "_free_map", None) is not None:
            return self._free_map
        return self.preps[0].free_param_map()

    def pack_state(self):
        """Host-side numpy snapshot of the packed batch. Together with
        ``from_packed`` this lets a caller cache the expensive host
        pack (TOA prep + stacking) across processes — the bench's
        full-scale stage rebuilds a 670k-TOA fleet from disk in
        seconds instead of minutes.

        The whole (params, prep, batch) tree comes back in ONE batched
        device_get (the per-leaf np.asarray loop this replaced
        serialized a device round-trip per array — the bulk of the
        0.62 s pack_s line in BENCH_r05), and the snapshot is cached
        per instance: params/prep/batch are immutable for the life of
        the batch (the same invariant _x0 relies on), so a refit
        reuses the staged host buffers instead of re-pulling."""
        import jax

        if getattr(self, "_pack_state_cache", None) is not None:
            return self._pack_state_cache
        from ..toa import TOABatch

        fields = {f: getattr(self.batch, f) for f in TOABatch._fields}
        params, prep, fields = jax.device_get(
            (self.params, self.prep, fields))
        self._pack_state_cache = {
            "params": params, "prep": prep, "batch": fields,
            "static": dict(self.static),
            "n_toas": np.asarray(self.n_toas),
            "free_map": list(self.free_map())}
        if getattr(self, "_pack", None):
            self._pack_state_cache["pack"] = dict(self._pack)
        return self._pack_state_cache

    @classmethod
    def from_packed(cls, template_model, state, mesh=None):
        """Rebuild a PTABatch from ``pack_state()`` output, skipping
        host TOA prep entirely. template_model provides the component
        structure (it must match the one that produced the state).

        The numpy state goes to the device in ONE batched device_put
        (device_put_staged(include_numpy=True)) — no intermediate
        per-leaf jnp.asarray host copies."""
        from ..models.timing_model import device_put_staged
        from ..toa import TOABatch

        self = cls.__new__(cls)
        n_psr = int(len(state["n_toas"]))
        self.models = [template_model] * n_psr  # divergence labels only
        self.toas_list = None
        self.preps = None
        self._pack = dict(state["pack"]) if "pack" in state else None
        if self._pack is not None and mesh is not None:
            raise ValueError("packed plan batches do not support a "
                             "device mesh")
        self._free_map = [tuple(x) for x in state["free_map"]]
        self.params, self.prep, self.batch = device_put_staged(
            (dict(state["params"]), dict(state["prep"]),
             TOABatch(**state["batch"])), include_numpy=True)
        self.static = dict(state["static"])
        self.n_toas = np.asarray(state["n_toas"])
        self.template = template_model
        self.mesh = mesh
        if mesh is not None:
            from .mesh import shard_batch

            n_max = int(self.batch.tdb_sec.shape[1])
            self.params = shard_batch(self.params, mesh)
            self.prep = shard_batch(self.prep, mesh, n_toa=n_max)
            self.batch = shard_batch(self.batch, mesh, n_toa=n_max)
        self._fns = {}
        self._costs = {}
        self._ecorr_marg_ok = None
        return self

    def set_start_vector(self, x):
        """Override the starting parameter vectors for the next fit —
        the checkpoint-resume hook (shape (n_psr, n_free), same layout
        as the fit results)."""
        import jax.numpy as jnp

        x = jnp.asarray(x)
        k = len(self.free_map())
        if x.shape != (self.n_pulsars, k):
            raise ValueError(
                f"start vector shape {x.shape} != "
                f"({self.n_pulsars}, {k})")
        if getattr(self, "_pack", None):
            # scatter per-pulsar rows into the (rows, slots, k) packed
            # start tensor; dummy slots keep their slot-0 defaults
            import jax

            self._x0_cache = None
            base = np.array(jax.device_get(self._x0()), np.float64)
            base[self._pack["row_of"], self._pack["slot_of"]] = \
                np.asarray(x, np.float64)
            self._x0_cache = jnp.asarray(base)
            return
        self._x0_cache = x

    def _overlay(self, params, x):
        out = dict(params)
        for i, (_, key, idx) in enumerate(self.free_map()):
            v = out[key]
            if v.ndim == 0 or idx is None:
                out[key] = x[i]
            else:
                out = {**out, key: v.at[idx].set(x[i])}
        return out

    def _x0(self):
        import jax.numpy as jnp
        import jax

        # params are immutable for the life of the batch; behind a
        # tunneled device each dispatch costs ~10 ms, so cache
        if getattr(self, "_x0_cache", None) is not None:
            return self._x0_cache

        def pull_one(params):
            vals = []
            for (_, key, idx) in self.free_map():
                v = params[key]
                vals.append(v if (v.ndim == 0 or idx is None) else v[idx])
            return jnp.stack(vals)

        if getattr(self, "_pack", None):
            # packed layout: params are (rows, slots, ...) -> (R, S, k)
            self._x0_cache = jax.vmap(jax.vmap(pull_one))(self.params)
        else:
            self._x0_cache = jax.vmap(pull_one)(self.params)
        return self._x0_cache

    def _pull(self, tree):
        """Device->host pull that also works on multi-process global
        arrays (assemble_global_batch fleets): non-addressable leaves
        are first replicated across the mesh — the all-gather IS the
        fleet's DCN collective — then materialized as numpy."""
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
        if any(isinstance(l, jax.Array) and not l.is_fully_addressable
               for l in leaves):
            from jax.sharding import NamedSharding, PartitionSpec as P

            if "pull_rep" not in self._fns:  # one compiled gather, reused
                rep = NamedSharding(self.mesh, P())
                self._fns["pull_rep"] = jax.jit(lambda t: t,
                                                out_shardings=rep)
            tree = self._fns["pull_rep"](tree)
            # after replication every leaf is fully addressable: one
            # batched device_get instead of a per-leaf np.asarray loop
        return jax.device_get(tree)

    def _maybe_inject_divergence(self, chi2, method):
        """resilience hook: the ``solver_diverge`` fault point poisons
        the requested lanes' chi2 with NaN right where a real solver
        blow-up would surface (before _isolate_diverged), so the
        quarantine/serve paths downstream see the genuine article.
        No-op (one falsy check) when nothing is armed."""
        from ..resilience import faultinject

        fault = faultinject.fire("solver_diverge", method=method)
        if not fault:
            return chi2
        chi2 = np.array(chi2, np.float64)
        n = len(chi2)
        for lane in fault.get("lanes", [0]):
            chi2[int(lane) % n] = np.nan
        return chi2

    def _pulsar_labels(self):
        """Per-pulsar display labels in original batch order (same
        convention as _isolate_diverged's warning: PSR name when the
        model has one, global index otherwise)."""
        off = getattr(self, "_pulsar_offset", 0)
        return [getattr(m, "PSR", None) and m.PSR.value or f"#{off + i}"
                for i, m in enumerate(self.models)]

    def _record_quality(self, method, handle, x, chi2, covn,
                        relres=None):
        """Fit-quality probes over the finalize's already-pulled host
        arrays (no device interaction — the fit stays bitwise
        identical; tests/test_fitquality.py pins it). dof is the
        design-matrix count: TOAs minus free params minus the offset
        column; noise amplitudes are marginalized, not subtracted."""
        n_free = int(np.asarray(x).shape[1])
        # distributed fleets hold only a local model slice of a global
        # result; probe just the rows this process owns
        off = getattr(self, "_pulsar_offset", 0)
        labels = self._pulsar_labels()
        sl = slice(off, off + len(labels))
        n_toas = np.asarray(self.n_toas, np.float64).reshape(-1)
        dof = n_toas[sl] - (n_free + 1)
        self.quality = obs_fitq.record_fit_batch(
            labels, np.asarray(chi2)[sl], dof,
            covn=np.asarray(covn)[sl],
            relres=None if relres is None else np.asarray(relres)[sl],
            method=method, precision=handle.get("precision", "f64"),
            maxiter=handle["maxiter"],
            fell_back=self.__dict__.pop("_fitq_fell_back", False),
            diverged=[i - off for i in self.diverged
                      if 0 <= i - off < len(labels)],
            source="pta." + method)

    def _isolate_diverged(self, x0, x, chi2):
        """Per-pulsar fault isolation (SURVEY section 5 "failure
        detection"): a diverged lane (non-finite chi2 or params) must
        not poison the batch result. vmap lanes are independent, so
        divergence cannot corrupt *other* pulsars mid-fit; here we
        restore the diverged pulsars' starting vectors, record which
        they were, and continue — the reference analog is the Downhill
        fitters keeping the best-so-far ModelState on a failed step.

        Returns (x_clean, chi2); the diverged pulsar indices are
        reported via self.diverged.
        """
        import warnings

        x = np.array(x, np.float64)  # copy: jax buffers are read-only
        chi2 = np.asarray(chi2, np.float64)
        bad = ~np.isfinite(chi2) | ~np.isfinite(x).all(axis=1)
        self.diverged = np.flatnonzero(bad)
        if bad.any():
            # self.models holds only this process's slice in a
            # distributed fleet (indices offset by _pulsar_offset);
            # out-of-slice pulsars are labeled by global index
            off = getattr(self, "_pulsar_offset", 0)
            names = [getattr(m, "PSR", None) and m.PSR.value or f"#{off + i}"
                     for i, m in enumerate(self.models)]
            labels = [names[i - off] if 0 <= i - off < len(names)
                      else f"#{i}" for i in self.diverged]
            warnings.warn(
                f"PTA batch: {bad.sum()}/{len(bad)} pulsars diverged "
                f"({labels}); their parameter "
                "vectors were restored to the pre-fit values")
            x[bad] = np.asarray(self._pull(x0), np.float64)[bad]
        return x, chi2

    def _build_wls(self, maxiter=3, threshold=1e-12):
        """(cache key, per-pulsar fit_one) for the WLS program —
        shared by :meth:`wls_fit` and :meth:`aot_compile`."""
        import jax
        import jax.numpy as jnp

        from ..fitter import _warn_degraded_once

        if getattr(self, "_pack", None):
            raise RuntimeError(
                "WLS is not supported on packed plan batches; the "
                "planner gives WLS structures singleton rows "
                "(PTABatch(..., pad_toas=width)) instead")
        _warn_degraded_once()
        resid_fn = self._resid_fn()

        def one_step(x, params, batch, prep):
            p = self._overlay(params, x)
            r, sig = resid_fn(p, batch, prep)
            sigma_s = sig * 1e-6

            def phase_of(xv):
                pp = self._overlay(params, xv)
                ph = self._phase_fn()(pp, batch, prep)
                return ph

            M = jax.jacfwd(phase_of)(x) / p["F"][0]
            M = jnp.concatenate([jnp.ones((M.shape[0], 1)), M], axis=1)
            Mw = M / sigma_s[:, None]
            rw = r / sigma_s
            # exponent-safe normalization + normalized-space covariance
            # (TPU f64 has f32-like exponent range; see fitter.column_norms)
            from ..fitter import column_norms

            norm = column_norms(Mw)
            Mn = Mw / norm
            U, s, Vt = jnp.linalg.svd(Mn, full_matrices=False)
            sinv = jnp.where(s > threshold * jnp.max(s), 1.0 / s, 0.0)
            dx = (Vt.T @ (sinv * (U.T @ rw))) / norm
            covn = Vt.T @ jnp.diag(sinv**2) @ Vt
            chi2 = jnp.sum(jnp.square(rw - Mw @ dx))
            return x - dx[1:], chi2, (covn[1:, 1:], norm[1:])

        def fit_one(x0, params, batch, prep):
            x = x0
            for _ in range(maxiter):
                x, chi2, cov = one_step(x, params, batch, prep)
            return x, chi2, cov

        return ("wls", maxiter, threshold), fit_one

    def _dispatch_wls(self, maxiter=3, threshold=1e-12):
        """Dispatch the WLS program WITHOUT pulling results: jax async
        dispatch queues the device work and returns immediately, so a
        fleet can dispatch every bucket before any bucket's blocking
        host pull (PTAFleet.fit(pipeline=True)). Returns a handle for
        :meth:`_finalize_wls`; wls_fit == finalize(dispatch)."""
        import jax

        key, fit_one = self._build_wls(maxiter, threshold)
        t0 = obs_clock.now()
        warm = key in self._fns
        if not warm:
            self._fns[key] = jax.jit(jax.vmap(fit_one))
        x0 = self._x0()
        out = self._fns[key](x0, self.params, self.batch, self.prep)
        return {"method": "wls", "t0": t0, "warm": warm, "x0": x0,
                "maxiter": maxiter, "out": out}

    def _finalize_wls(self, handle):
        """Blocking half of the WLS fit: pull the dispatched results,
        run divergence isolation, record metrics."""
        x, chi2, (covn, norm) = handle["out"]
        # ONE batched device->host pull (device_get overlaps the
        # per-array copies): behind a tunneled device each separate
        # np.asarray sync costs ~90 ms of round-trip latency.
        # Physical-unit covariance then forms on host in IEEE f64:
        # variances like var(F1)~1e-38 leave the TPU emulated-f64
        # exponent range.
        x, chi2, covn, norm = self._pull((x, chi2, covn, norm))
        cov = covn / (norm[:, :, None] * norm[:, None, :])
        chi2 = self._maybe_inject_divergence(chi2, "wls")
        x, chi2 = self._isolate_diverged(handle["x0"], x, chi2)
        self._record_metrics("wls", handle["t0"], handle["maxiter"],
                             warm=handle["warm"])
        if obs_fitq.enabled():
            self._record_quality("wls", handle, x, chi2, covn)
        else:
            self.quality = None
        return x, chi2, cov

    def wls_fit(self, maxiter=3, threshold=1e-12):
        """Vmapped, mesh-sharded multi-pulsar WLS fit.

        Returns (x_fit (n_psr, n_free), chi2 (n_psr,), cov (n_psr, k, k)).
        Diverged pulsars (non-finite results) are reported via
        self.diverged and returned with their starting vectors.
        """
        return self._finalize_wls(self._dispatch_wls(maxiter, threshold))

    def _record_metrics(self, method, t0, maxiter, warm):
        """Per-fit metrics surface (SURVEY section 5): wall time
        (compile included when warm=False), batch shape, device
        memory."""
        import jax

        from ..fitter import device_memory_stats

        self.metrics = {
            "method": method,
            "backend": jax.default_backend(),
            "fit_wall_s": round(obs_clock.now() - t0, 4),
            "includes_compile": not warm,
            "maxiter": maxiter,
            "n_pulsars": self.n_pulsars,
            "n_toas_total": int(sum(self.n_toas)),
            "device_bytes_in_use": device_memory_stats(),
        }

    def _noise_bw_fn(self, exclude_ecorr=False):
        """Pure (params, prep) -> (B, w_us2) stacking every noise
        component's basis/weight pair; None if the batch has no
        correlated-noise components. Padded basis columns are zero with
        zero weight (red-noise raggedness) or zero with a real prior
        (ECORR raggedness) — both give exactly zero amplitude in the
        augmented solve below. With exclude_ecorr=True the ECORR
        component is skipped (gls_fit marginalizes it analytically).
        """
        comps = [c for c in self.template.components.values()
                 if getattr(c, "basis_weight", None) is not None
                 and not (exclude_ecorr
                          and type(c).__name__ == "EcorrNoise")]
        if not comps:
            return None
        static = self.static

        def noise_bw(params, prep):
            import jax.numpy as jnp

            full = {**prep, **static}
            Bs, ws = [], []
            for c in comps:
                B, w = c.basis_weight(params, full)
                if B.shape[1]:
                    Bs.append(B)
                    ws.append(w)
            if not Bs:
                return None
            return jnp.concatenate(Bs, axis=1), jnp.concatenate(ws)

        return noise_bw

    def _build_gls(self, maxiter=2, threshold=1e-12, ecorr_mode="auto",
                   precision="f64", fused=None):
        """(cache key, per-pulsar fit_one) for the GLS program — the
        single home of the program construction, shared by
        :meth:`gls_fit` (JIT path) and :meth:`aot_compile` (explicit
        lower/compile path with trace-vs-XLA timing).

        Two equivalent solves (Woodbury identities), chosen by
        ``ecorr_mode``:

        - ``"auto"`` (default): ECORR epochs are marginalized
          ANALYTICALLY — the quantization basis U has disjoint 0/1
          columns, so N' = N + U W U^T inverts by per-epoch
          Sherman-Morrison using segment sums; only the parameter and
          red-noise Fourier columns enter the dense eigh. The dense
          system shrinks from ~(params + epochs + harmonics) to
          ~(params + harmonics) columns — at NANOGrav scale that is
          ~314 -> ~64, an order of magnitude fewer normal-equation
          FLOPs.
        - ``"dense"``: every basis column (ECORR U + red F) is appended
          to the design matrix with prior weights and the full system
          is solved by one batched eigh — the same math as
          fitter.py::GLSFitter, vmapped. (Kept as the cross-check path;
          tests assert both give identical fits.)

        Zero-padded basis columns/epochs from ragged per-pulsar counts
        carry zero weight, so they drop out of either path exactly.

        Returns (x_fit, chi2_whitened, cov) like wls_fit; diverged
        pulsars reported via self.diverged.
        """
        import jax
        import jax.numpy as jnp

        from ..fitter import (_warn_degraded_once, check_precision,
                              gls_eigh_refine, gls_eigh_solve,
                              gls_fused_normal, gls_gram, gls_whiten,
                              stack_noise_bases)

        if getattr(self, "_pack", None):
            return self._build_gls_packed(
                maxiter, threshold, ecorr_mode, precision,
                fused=(True if fused is None else bool(fused)))
        _warn_degraded_once()

        if ecorr_mode not in ("auto", "dense"):
            raise ValueError(
                f"ecorr_mode must be 'auto' or 'dense', got {ecorr_mode!r}")
        check_precision(precision)
        resid_fn = self._resid_fn()
        phase_fn = self._phase_fn()
        noise_bw = self._noise_bw_fn()
        has_ecorr = "EcorrNoise" in self.template.components
        marginalize = has_ecorr and ecorr_mode == "auto"
        if marginalize:
            # Sherman-Morrison needs DISJOINT epoch columns: true within
            # one ECORR mask by construction, but overlapping masks
            # (e.g. a flag mask plus an mjd-range mask) put a TOA in two
            # epochs. Zero epochs (all singletons) has nothing to
            # marginalize. Both fall back to the exact dense path.
            # Disjointness is now explicit in the packed representation
            # (models/noise.py::EcorrNoise.pack): the sparse epoch
            # index exists iff the epochs are disjoint; overlapping
            # masks pack the dense U instead. Cached: prep is immutable
            # for the life of the batch.
            if self._ecorr_marg_ok is None:
                self._ecorr_marg_ok = bool(
                    "ecorr_eidx" in self.prep
                    and self.prep["ecorr_owner"].shape[-1] > 0)
            marginalize = self._ecorr_marg_ok
        noise_bw_nf = (self._noise_bw_fn(exclude_ecorr=True)
                       if marginalize else None)
        ecorr_comp = (self.template.components.get("EcorrNoise")
                      if marginalize else None)
        # HOIST the x-independent dense blocks out of the Gauss-Newton
        # iteration: with every noise/white-noise parameter frozen (the
        # universal case — LS fits can't constrain them anyway), the
        # whitened noise-basis columns Bn, their Gram Bn^T Bn (~88% of
        # the normal-equation FLOPs at 60-of-64 columns), the epoch
        # sums, and the Sherman-Morrison weights are all constants of
        # the fit; only the tiny parameter block changes per iteration.
        # A free noise parameter disables the hoist (falls back to the
        # full per-iteration recompute).
        free_names = {n for n, _, _ in self.free_map()}
        noise_param_names = set()
        for c in self.template.components.values():
            # duck-typed like pure_sigma_fn / _noise_bw_fn: any
            # component that can scale sigma or contribute a basis
            # feeds the hoisted constants
            if (getattr(c, "basis_weight", None) is not None
                    or getattr(c, "scale_sigma", None) is not None):
                noise_param_names.update(c.params)
        # (mixed precision composes: the hoisted constant Gram runs in
        # f32 and the per-iteration solve is refined against exact f64
        # matvecs through the factored blocks)
        hoist = (marginalize
                 and not (free_names & noise_param_names))

        def design(x, params, batch, prep, p):
            def phase_of(xv):
                return phase_fn(self._overlay(params, xv), batch, prep)

            M = jax.jacfwd(phase_of)(x) / p["F"][0]
            return jnp.concatenate([jnp.ones((M.shape[0], 1)), M], axis=1)

        def one_step_dense(x, params, batch, prep):
            p = self._overlay(params, x)
            r, sig = resid_fn(p, batch, prep)
            sigma_s = sig * 1e-6
            M = design(x, params, batch, prep, p)
            # shared GLS machinery (fitter.stack_noise_bases /
            # gls_normal / gls_eigh_solve): prior-folded normalization
            # keeps the relative eigenvalue cut meaningful, sqrt-form
            # priors stay inside the TPU f64 exponent range, and the
            # zero-weight padded columns (zero basis + zero prior)
            # surface as exactly-zero eigenvalues -> dropped
            bw = (noise_bw(p, prep) if noise_bw is not None
                  else None) or (None, None)
            Mfull, sqrt_phi_inv, nparam = stack_noise_bases(M, bw)
            Mn, norm, q = gls_whiten(Mfull, sigma_s, sqrt_phi_inv)
            z = r / sigma_s
            # fused normal assembly: A, b and |z|^2 from ONE augmented
            # Gram [Mn | z] — one pass over the whitened design
            # instead of three (fitter.gls_fused_normal)
            A, b, rNr = gls_fused_normal(Mn, z, q, precision)
            if precision == "mixed":
                dxn, covn, relres = gls_eigh_refine(
                    A, b, lambda v: Mn.T @ (Mn @ v) + (q * q) * v,
                    threshold)
            else:
                dxn, covn = gls_eigh_solve(A, b, threshold)
                relres = jnp.zeros(())
            dx_all = dxn / norm
            # whitened marginalized chi2: r^T C^-1 r = |rw|^2 - b.dxn
            chi2 = rNr - b @ dxn
            return (x - dx_all[1:nparam], chi2,
                    (covn[1:nparam, 1:nparam], norm[1:nparam], relres))

        def one_step_marg(x, params, batch, prep):
            # ECORR epochs eliminated by per-epoch Sherman-Morrison:
            # N'^-1 = N^-1 - sum_j c_j (N^-1 u_j)(N^-1 u_j)^T with
            # c_j = w_j/(1 + w_j s_j), s_j = u_j^T N^-1 u_j, u_j the
            # 0/1 indicator of epoch j (disjoint by construction of
            # the quantization). All epoch reductions are segment sums.
            p = self._overlay(params, x)
            r, sig = resid_fn(p, batch, prep)
            sigma_s = sig * 1e-6
            M = design(x, params, batch, prep, p)
            bw = (noise_bw_nf(p, prep) if noise_bw_nf is not None
                  else None) or (None, None)
            Mfull, sqrt_phi_inv, nparam = stack_noise_bases(M, bw)
            # sparse quantization: the (n_toa, k) dense U never
            # materializes anywhere on this path — epoch membership is
            # one int per TOA and every epoch reduction is a segment
            # sum, which is what lets a 30k-TOA NANOGrav-scale pulsar
            # fit in HBM (dense U would be ~0.25 GB/pulsar)
            eidx, w_us2 = ecorr_comp.epoch_index_weight(
                p, {**prep, **self.static})
            k = w_us2.shape[0]
            # per-TOA epoch id; rows outside every epoch (-1 / padded)
            # go to bucket k
            e_idx = jnp.where((eidx >= 0) & (eidx < k), eidx, k)
            # everything below lives in WHITENED, COLUMN-NORMALIZED
            # space (fitter.gls_whiten — the one home of the prior-
            # folded convention): raw whitened column products overflow
            # the TPU-emulated f64 exponent range (F1 column ~1e19)
            Mn, norm, q = gls_whiten(Mfull, sigma_s, sqrt_phi_inv)
            z = r / sigma_s
            a = 1.0 / sigma_s
            # fused: A0 (+ prior diag), b0 and |z|^2 from one
            # augmented Gram (see one_step_dense / gls_fused_normal)
            A0q, b0, rNr = gls_fused_normal(Mn, z, q, precision)
            s = jax.ops.segment_sum(a * a, e_idx, num_segments=k + 1)[:k]
            G = jax.ops.segment_sum(Mn * a[:, None], e_idx,
                                    num_segments=k + 1)[:k]
            t = jax.ops.segment_sum(z * a, e_idx, num_segments=k + 1)[:k]
            w_s2 = w_us2 * 1e-12
            c = w_s2 / (1.0 + w_s2 * s)  # w=0 (padding) -> c=0 exactly
            # sqrt(c)-scaled epoch matrix: the Sherman-Morrison
            # downdate becomes a symmetric PSD Gram, so the mixed-
            # precision path can run BOTH big products in f32
            Gc = jnp.sqrt(c)[:, None] * G
            bn = b0 - G.T @ (c * t)
            rCr = rNr - jnp.sum(c * jnp.square(t))
            if precision == "mixed":
                Gc32 = Gc.astype(jnp.float32)
                An = A0q - (Gc32.T @ Gc32).astype(jnp.float64)
                dxn, covn, relres = gls_eigh_refine(
                    An, bn,
                    lambda v: (Mn.T @ (Mn @ v) - Gc.T @ (Gc @ v)
                               + (q * q) * v),
                    threshold)
            else:
                An = A0q - Gc.T @ Gc
                dxn, covn = gls_eigh_solve(An, bn, threshold)
                relres = jnp.zeros(())
            dx_all = dxn / norm
            chi2 = rCr - bn @ dxn
            return (x - dx_all[1:nparam], chi2,
                    (covn[1:nparam, 1:nparam], norm[1:nparam], relres))

        def precompute_marg(params, batch, prep):
            """x-independent pieces of one_step_marg (see the hoist
            comment above): whitened noise basis, its Gram, epoch sums,
            Sherman-Morrison weights. Evaluated at the packed params —
            valid because the hoist guard proved none of these read a
            free parameter."""
            _, sig = resid_fn(params, batch, prep)
            sigma_s = sig * 1e-6
            a = 1.0 / sigma_s
            bw = (noise_bw_nf(params, prep) if noise_bw_nf is not None
                  else None) or (None, None)
            # single-home conventions: stack_noise_bases owns the
            # us^2 -> prior-sqrt formula, gls_whiten the prior-folded
            # whitening/normalization (a zero-column params block makes
            # them operate on the basis alone)
            B, spi_B, _ = stack_noise_bases(
                jnp.zeros((sigma_s.shape[0], 0)), bw)
            Bn, normB, qB = gls_whiten(B, sigma_s, spi_B)
            # the one remaining big Gram: f32 (MXU) under "mixed", with
            # the per-iteration refinement recovering f64 accuracy
            FtF = gls_gram(Bn, jnp.zeros_like(qB), precision)
            eidx, w_ec = ecorr_comp.epoch_index_weight(
                params, {**prep, **self.static})
            k = w_ec.shape[0]
            e_idx = jnp.where((eidx >= 0) & (eidx < k), eidx, k)
            s = jax.ops.segment_sum(a * a, e_idx, num_segments=k + 1)[:k]
            GB = jax.ops.segment_sum(Bn * a[:, None], e_idx,
                                     num_segments=k + 1)[:k]
            w_s2 = w_ec * 1e-12
            c = w_s2 / (1.0 + w_s2 * s)
            sc = jnp.sqrt(c)
            GcB = sc[:, None] * GB
            return dict(sigma_s=sigma_s, a=a, Bn=Bn, qB=qB, normB=normB,
                        FtF=FtF, e_idx=e_idx, c=c, sc=sc, GcB=GcB,
                        GcBtGcB=GcB.T @ GcB, k=k)

        def one_step_marg_hoisted(x, params, batch, prep, pre):
            # identical math to one_step_marg with the constant blocks
            # read from ``pre`` — only the (1 + n_free)-column parameter
            # block is recomputed per iteration
            p = self._overlay(params, x)
            r, _ = resid_fn(p, batch, prep)
            sigma_s, a, k = pre["sigma_s"], pre["a"], pre["k"]
            M = design(x, params, batch, prep, p)
            nparam = M.shape[1]
            Mn_p, normM, _ = gls_whiten(M, sigma_s, jnp.zeros(nparam))
            z = r / sigma_s
            # fused parameter-block assembly: augmenting the small
            # per-iteration block with z folds Bn^T z into the SAME
            # pass over the big constant basis as the cross Gram, and
            # the tiny aug Gram yields Mn_p^T Mn_p, Mn_p^T z and
            # |z|^2 together (the kernels/fusedgls.py identity)
            aug_p = jnp.concatenate([Mn_p, z[:, None]], axis=1)
            GpB = aug_p.T @ pre["Bn"]
            Gpp = aug_p.T @ aug_p
            b0 = jnp.concatenate([Gpp[:nparam, nparam], GpB[nparam]])
            rNr = Gpp[nparam, nparam]
            G_p = jax.ops.segment_sum(Mn_p * a[:, None], pre["e_idx"],
                                      num_segments=k + 1)[:k]
            Gc_p = pre["sc"][:, None] * G_p
            t = jax.ops.segment_sum(z * a, pre["e_idx"],
                                    num_segments=k + 1)[:k]
            ApB = GpB[:nparam]
            A0 = jnp.block([[Gpp[:nparam, :nparam], ApB],
                            [ApB.T, pre["FtF"]]])
            GcX = Gc_p.T @ pre["GcB"]
            Gct = jnp.block([[Gc_p.T @ Gc_p, GcX],
                             [GcX.T, pre["GcBtGcB"]]])
            q = jnp.concatenate([jnp.zeros(nparam), pre["qB"]])
            norm = jnp.concatenate([normM, pre["normB"]])
            sct = pre["sc"] * t
            bn = b0 - jnp.concatenate([Gc_p.T @ sct, pre["GcB"].T @ sct])
            rCr = rNr - jnp.sum(pre["c"] * jnp.square(t))
            An = A0 - Gct + jnp.diag(q * q)
            if precision == "mixed":
                # exact f64 operator through the factored blocks: every
                # product is O(n k) or O(epochs k) — the f64 Gram never
                # forms, yet refinement converges to f64 accuracy
                def matvec(v):
                    vp, vB = v[:nparam], v[nparam:]
                    u = Mn_p @ vp + pre["Bn"] @ vB
                    A0v = jnp.concatenate([Mn_p.T @ u, pre["Bn"].T @ u])
                    gv = Gc_p @ vp + pre["GcB"] @ vB
                    Gcv = jnp.concatenate([Gc_p.T @ gv,
                                           pre["GcB"].T @ gv])
                    return A0v - Gcv + (q * q) * v

                dxn, covn, relres = gls_eigh_refine(An, bn, matvec,
                                                    threshold)
            else:
                dxn, covn = gls_eigh_solve(An, bn, threshold)
                relres = jnp.zeros(())
            dx_all = dxn / norm
            chi2 = rCr - bn @ dxn
            return (x - dx_all[1:nparam], chi2,
                    (covn[1:nparam, 1:nparam], norm[1:nparam], relres))

        one_step = one_step_marg if marginalize else one_step_dense

        def fit_one(x0, params, batch, prep):
            x = x0
            # track the WORST refinement residual over the Gauss-Newton
            # iterations: an early-iteration non-contraction corrupts x
            # even if the final (off-optimum) solve happens to converge
            worst = jnp.zeros(())
            pre = precompute_marg(params, batch, prep) if hoist else None
            for _ in range(maxiter):
                if hoist:
                    x, chi2, (covn, norm, relres) = one_step_marg_hoisted(
                        x, params, batch, prep, pre)
                else:
                    x, chi2, (covn, norm, relres) = one_step(
                        x, params, batch, prep)
                worst = jnp.maximum(worst, relres)
            return x, chi2, (covn, norm, worst)

        return (("gls", maxiter, threshold, marginalize, precision, hoist),
                fit_one)

    def _build_gls_packed(self, maxiter=2, threshold=1e-12,
                          ecorr_mode="auto", precision="f64",
                          fused=True):
        """(cache key, per-ROW fit_one) for the segment-packed GLS
        program — the shapeplan layout where several pulsars share one
        padded row (stack_packed).

        Same math as one_step_dense / one_step_marg in the SAME
        operation order, with every whole-row reduction replaced by
        its per-segment form: fitter.seg_gls_whiten for the whitened
        column normalization, block-factorized segment Grams for the
        normal matrices, and segment sums keyed by the per-TOA owner
        for the b/chi2/epoch reductions. Each slot evaluates
        phase/design/noise with ITS params over the whole row
        (foreign-row outputs are masked out before any reduction);
        the slot loop accumulates the combined arrays in place so
        peak memory stays at one row, not n_slots rows.

        ``fused=True`` (the default) assembles the per-segment normal
        matrix, right-hand side and whitened residual power in ONE
        streamed pass over the packed row (kernels/fusedgls.py:
        whiten -> Gram -> RHS fused — the Pallas TPU kernel under
        precision="mixed", the f64 jnp mirror otherwise) and — when
        no noise parameter is free — HOISTS the x-independent slot
        work (sigma, the noise basis + prior, ECORR weights) out of
        the Gauss-Newton iteration, so each iteration re-evaluates
        only the phase and the parameter jacobian per slot.
        ``fused=False`` keeps the classic three-pass f64 program as
        the equivalence reference (tests/test_shapeplan.py).

        ``precision="mixed"`` (fused only) runs the fused pass in f32
        (the MXU path on TPU) and recovers f64 accuracy with
        fitter.seg_gls_eigh_refine: the right-hand sides stay exact
        f64 segment sums and the refinement matvec applies the exact
        f64 normal operator through segment-masked O(n k) products —
        the f32 kernel output is only the eigh preconditioner.
        """
        import jax
        import jax.numpy as jnp

        from ..fitter import (_warn_degraded_once, check_precision,
                              gls_eigh_solve, seg_gls_eigh_refine,
                              seg_gls_norm, seg_gls_whiten,
                              stack_noise_bases)
        from ..kernels.fusedgls import fused_segment_gls
        from ..kernels.seggram import segment_gram

        _warn_degraded_once()
        if ecorr_mode not in ("auto", "dense"):
            raise ValueError(
                f"ecorr_mode must be 'auto' or 'dense', got {ecorr_mode!r}")
        check_precision(precision)
        if precision != "f64" and not fused:
            raise ValueError(
                "packed plan batches are f64-only on the classic "
                "(fused=False) path; precision='mixed' needs the "
                "fused kernel program (fused=True)")
        phase_fn = self._phase_fn()
        sigma_fn = self._sigma_fn()
        has_ecorr = "EcorrNoise" in self.template.components
        marginalize = has_ecorr and ecorr_mode == "auto"
        if marginalize:
            if self._ecorr_marg_ok is None:
                self._ecorr_marg_ok = bool(
                    "ecorr_eidx" in self.prep
                    and self.prep["ecorr_owner"].shape[-1] > 0)
            marginalize = self._ecorr_marg_ok
        noise_bw = (self._noise_bw_fn(exclude_ecorr=True) if marginalize
                    else self._noise_bw_fn())
        ecorr_comp = (self.template.components.get("EcorrNoise")
                      if marginalize else None)
        # packed hoist guard — mirrors the unpacked one (_build_gls):
        # with every noise parameter frozen, sigma, the noise
        # basis/prior and the ECORR weights never read the fit vector,
        # so they are bitwise iteration constants. Kept off the
        # classic path so fused=False stays the unchanged reference.
        free_names = {n for n, _, _ in self.free_map()}
        noise_param_names = set()
        for c in self.template.components.values():
            if (getattr(c, "basis_weight", None) is not None
                    or getattr(c, "scale_sigma", None) is not None):
                noise_param_names.update(c.params)
        hoist = fused and not (free_names & noise_param_names)
        pack = self._pack
        S = int(pack["n_slots"])
        Q = int(pack["quantum"])
        Qe = int(pack["e_quantum"])
        slot_keys = frozenset(pack["slot_keys"])

        def fit_one(x0, params, batch, prep):
            # one packed ROW: x0 (S, k); params slot-stacked (S, ...);
            # prep mixes combined row leaves with slot-stacked leaves
            shared = {k: v for k, v in prep.items()
                      if k not in slot_keys
                      and not k.startswith("_pack_")}
            block_slot = prep["_pack_block_slot"]
            W = batch.tdb_sec.shape[0]
            owner = jnp.repeat(block_slot, Q, total_repeat_length=W)

            def slot_env(s):
                ps = jax.tree_util.tree_map(lambda v: v[s], params)
                full = dict(shared)
                for k in slot_keys:
                    full[k] = prep[k][s]
                return ps, full

            def combine_noise(x):
                # combined-over-slots sigma and noise-basis columns,
                # (S, ...) prior sqrts, row-global ECORR weights.
                # x-independent under the hoist guard (evaluated once
                # per fit); recomputed per iteration otherwise.
                spis = []
                w_ec = None
                sig = B = None
                for s in range(S):
                    ps, full = slot_env(s)
                    p = self._overlay(ps, x[s])
                    sig_s = sigma_fn(p, batch, full)
                    bw = (noise_bw(p, full) if noise_bw is not None
                          else None) or (None, None)
                    # zero-width params block: stack_noise_bases on
                    # the basis alone (one home of the prior formula)
                    B_s, spiB_s, _ = stack_noise_bases(
                        jnp.zeros((W, 0)), bw)
                    if s == 0:
                        sig, B = sig_s, B_s
                    else:
                        m = owner == s
                        sig = jnp.where(m, sig_s, sig)
                        B = jnp.where(m[:, None], B_s, B)
                    spis.append(spiB_s)
                    if marginalize:
                        _, wec_s = ecorr_comp.epoch_index_weight(
                            p, {**full, **self.static})
                        # disjoint global epoch spans: summing the
                        # per-slot weight vectors assembles the row's
                        w_ec = wec_s if w_ec is None else w_ec + wec_s
                return sig, B, jnp.stack(spis), w_ec

            def combine_design(x):
                # the per-iteration slot work: phase + the
                # (1 + n_free)-column parameter jacobian
                f0s = []
                ph = M = None
                for s in range(S):
                    ps, full = slot_env(s)
                    p = self._overlay(ps, x[s])
                    ph_s = phase_fn(p, batch, full)

                    def phase_of(xv, ps=ps, full=full):
                        return phase_fn(self._overlay(ps, xv),
                                        batch, full)

                    M_s = jax.jacfwd(phase_of)(x[s]) / p["F"][0]
                    if s == 0:
                        ph, M = ph_s, M_s
                    else:
                        m = owner == s
                        ph = jnp.where(m, ph_s, ph)
                        M = jnp.where(m[:, None], M_s, M)
                    f0s.append(p["F"][0])
                M = jnp.concatenate([jnp.ones((W, 1)), M], axis=1)
                return ph, M, jnp.stack(f0s)

            def one_step(x, noise):
                sig, B, spiB, w_ec = noise
                ph, M, F0 = combine_design(x)
                nparam = M.shape[1]
                Mfull = (jnp.concatenate([M, B], axis=1)
                         if B.shape[1] else M)
                spi = jnp.concatenate(
                    [jnp.zeros((S, nparam)), spiB], axis=1)
                # per-segment weighted phase mean — the packed analog
                # of _resid_fn's whole-row mean subtraction
                frac = ph - jnp.floor(ph + 0.5)
                wts = 1.0 / jnp.square(sig)
                num = jax.ops.segment_sum(frac * wts, owner,
                                          num_segments=S)
                den = jax.ops.segment_sum(wts, owner, num_segments=S)
                frac = frac - (num / den)[owner]
                r = frac / F0[owner]
                sigma_s = sig * 1e-6
                if fused:
                    winv = 1.0 / sigma_s
                    norm, q = seg_gls_norm(Mfull, sigma_s, spi,
                                           owner, S)
                    # pre-normalized raw design: the kernel whitens by
                    # the winv column in-tile, so P * winv == Mn up to
                    # one rounding (the packed-vs-sequential 1e-15
                    # param contract holds — tests/test_shapeplan.py)
                    P = Mfull / norm[owner]
                    A0, b0, rNr = fused_segment_gls(
                        P, r, winv, block_slot, S, Q,
                        precision=precision)
                    Mn = P * winv[:, None]
                    z = r * winv
                    if precision == "mixed":
                        # the f32 kernel Gram is only the refinement
                        # preconditioner; the RHS must stay exact f64
                        # or the refinement fixed point inherits its
                        # error (kernels/fusedgls.py docstring)
                        b0 = jax.ops.segment_sum(
                            Mn * z[:, None], owner, num_segments=S)
                        rNr = jax.ops.segment_sum(
                            z * z, owner, num_segments=S)
                else:
                    Mn, norm, q = seg_gls_whiten(Mfull, sigma_s, spi,
                                                 owner, S)
                    z = r / sigma_s
                    b0 = jax.ops.segment_sum(Mn * z[:, None], owner,
                                             num_segments=S)
                    rNr = jax.ops.segment_sum(z * z, owner,
                                              num_segments=S)
                    A0 = segment_gram(Mn, block_slot, S, Q,
                                      precision=precision)
                eowner = Gc = None
                if marginalize:
                    a = 1.0 / sigma_s
                    NE = w_ec.shape[0]
                    eidx = prep["ecorr_eidx"]  # row-global epoch ids
                    e_idx = jnp.where((eidx >= 0) & (eidx < NE),
                                      eidx, NE)
                    s_e = jax.ops.segment_sum(
                        a * a, e_idx, num_segments=NE + 1)[:NE]
                    G = jax.ops.segment_sum(
                        Mn * a[:, None], e_idx, num_segments=NE + 1)[:NE]
                    t_e = jax.ops.segment_sum(
                        z * a, e_idx, num_segments=NE + 1)[:NE]
                    w_s2 = w_ec * 1e-12
                    c = w_s2 / (1.0 + w_s2 * s_e)  # w=0 (pad) -> c=0
                    Gc = jnp.sqrt(c)[:, None] * G
                    eblock_slot = prep["_pack_eblock_slot"]
                    eowner = jnp.repeat(eblock_slot, Qe,
                                        total_repeat_length=NE)
                    D = segment_gram(Gc, eblock_slot, S, Qe,
                                     precision=precision)
                    bn = b0 - jax.ops.segment_sum(
                        (c * t_e)[:, None] * G, eowner, num_segments=S)
                    rCr = rNr - jax.ops.segment_sum(
                        c * jnp.square(t_e), eowner, num_segments=S)
                    An = A0 - D + jax.vmap(jnp.diag)(q * q)
                else:
                    An = A0 + jax.vmap(jnp.diag)(q * q)
                    bn = b0
                    rCr = rNr
                if precision == "mixed":
                    def matvec(v):
                        # exact f64 normal operator for all segments
                        # at once via owner-masked O(n k) products —
                        # the f64 Grams never form (the segment analog
                        # of one_step_marg_hoisted's factored matvec)
                        u = jnp.sum(Mn * v[owner], axis=1)
                        Av = jax.ops.segment_sum(
                            Mn * u[:, None], owner, num_segments=S)
                        Av = Av + (q * q) * v
                        if marginalize:
                            gv = jnp.sum(Gc * v[eowner], axis=1)
                            Av = Av - jax.ops.segment_sum(
                                Gc * gv[:, None], eowner,
                                num_segments=S)
                        return Av

                    dxn, covn, relres = seg_gls_eigh_refine(
                        An, bn, matvec, threshold)
                else:
                    dxn, covn = jax.vmap(
                        lambda Ai, bi: gls_eigh_solve(Ai, bi,
                                                      threshold))(
                            An, bn)
                    relres = jnp.zeros(S)
                dx_all = dxn / norm
                chi2 = rCr - jnp.sum(bn * dxn, axis=1)
                return (x - dx_all[:, 1:nparam], chi2,
                        (covn[:, 1:nparam, 1:nparam],
                         norm[:, 1:nparam], relres))

            x = x0
            # worst refinement residual over iterations, like the
            # unpacked fit_one (zeros throughout on the f64 paths)
            worst = jnp.zeros(S)
            noise = combine_noise(x0) if hoist else None
            for _ in range(maxiter):
                x, chi2, (covn, norm, relres) = one_step(
                    x, noise if hoist else combine_noise(x))
                worst = jnp.maximum(worst, relres)
            return x, chi2, (covn, norm, worst)

        return (("gls", maxiter, threshold, marginalize, precision,
                 "packed-fused" if fused else "packed", hoist),
                fit_one)

    @staticmethod
    def _precision_verdict(timings, mixed_failed):
        """Pure decision rule behind precision="auto": f64 wins when
        the mixed probe's refinement diagnostic failed (a mode that
        would immediately fall back is never faster) or when the
        timed warm run says f64 is at least as fast. Ties go to f64 —
        equal speed buys nothing for the precision risk. Mixed has to
        EARN its slot with a strictly faster measured run; on CPU it
        never does (gls_mixed_speedup 0.768, BASELINE.md r5: the f32
        Gram vectorizes no wider than f64 on AVX while the refinement
        pass doubles the passes), which is exactly why the verdict is
        measured rather than assumed from the platform."""
        if mixed_failed:
            return "f64"
        return ("f64" if timings["f64"] <= timings["mixed"]
                else "mixed")

    def _resolve_precision(self, precision, maxiter=2, threshold=1e-12,
                           ecorr_mode="auto", fused=None):
        """Resolve precision="auto" to the MEASURED winner of "f64" vs
        "mixed" for this bucket structure (gls_mixed_speedup = 0.768
        on CPU made mixed a regression where it runs today, so the
        choice must be timed, not assumed). Both programs are compiled
        and warmed, one warm run each is timed, and the faster mode
        wins — unless the mixed run's refinement diagnostic failed, in
        which case f64 wins outright (a mode that would immediately
        fall back is never faster). The verdict is cached per process
        keyed on (structure, shapes, fit options); the compiled
        programs stay in self._fns so the probe work is not wasted.
        Explicit "f64"/"mixed" pass through untouched."""
        import jax

        from ..fitter import check_precision, relres_failed

        check_precision(precision, allow_auto=True)
        if precision != "auto":
            return precision
        if getattr(self, "_pack", None) and fused is not None \
                and not fused:
            # the classic packed program is f64-only: auto resolves
            # without a probe (mixed needs the fused kernel path)
            return "f64"
        cache_key = (self.structure_key(self.template),
                     self.shape_signature(), maxiter, threshold,
                     ecorr_mode, fused)
        with _PRECISION_AUTO_LOCK:
            choice = _PRECISION_AUTO_CACHE.get(cache_key)
        if choice is not None:
            return choice
        args = (self._x0(), self.params, self.batch, self.prep)
        timings = {}
        mixed_failed = False
        for mode in ("f64", "mixed"):
            key, fit_one = self._build_gls(maxiter, threshold,
                                           ecorr_mode, mode,
                                           fused=fused)
            if key not in self._fns:
                self._fns[key] = jax.jit(jax.vmap(fit_one))
            out = self._fns[key](*args)  # compile + warm-up
            jax.block_until_ready(out)
            if mode == "mixed":
                relres = jax.device_get(out[2][2])
                # probe diagnostic, not a production fit: these warm-up
                # fits pick a precision mode and are re-run (and then
                # recorded) by the real dispatch; ledgering them would
                # double-count every auto-resolved bucket
                # pintlint: disable=quality-signal-dropped
                mixed_failed = relres_failed(relres)
            t0 = obs_clock.now()
            jax.block_until_ready(self._fns[key](*args))
            timings[mode] = obs_clock.now() - t0
        choice = self._precision_verdict(timings, mixed_failed)
        with _PRECISION_AUTO_LOCK:
            choice = _PRECISION_AUTO_CACHE.setdefault(cache_key, choice)
        self.precision_auto = {"choice": choice,
                               "f64_s": round(timings["f64"], 4),
                               "mixed_s": round(timings["mixed"], 4),
                               "mixed_relres_failed": mixed_failed}
        return choice

    def _dispatch_gls(self, maxiter=2, threshold=1e-12, ecorr_mode="auto",
                      precision="f64", fused=None):
        """Dispatch the GLS program WITHOUT pulling results (see
        _dispatch_wls); gls_fit == finalize(dispatch). Resolves
        precision="auto" to the measured per-structure winner first."""
        import jax

        precision = self._resolve_precision(precision, maxiter,
                                            threshold, ecorr_mode,
                                            fused=fused)
        key, fit_one = self._build_gls(maxiter, threshold, ecorr_mode,
                                       precision, fused=fused)
        t0 = obs_clock.now()
        warm = key in self._fns
        if not warm:
            self._fns[key] = jax.jit(jax.vmap(fit_one))
        x0 = self._x0()
        out = self._fns[key](x0, self.params, self.batch, self.prep)
        return {"method": "gls", "t0": t0, "warm": warm, "x0": x0,
                "maxiter": maxiter, "threshold": threshold,
                "ecorr_mode": ecorr_mode, "precision": precision,
                "fused": fused, "out": out}

    def _finalize_gls(self, handle):
        """Blocking half of the GLS fit: pull, mixed-precision
        fallback check, divergence isolation, metrics."""
        x, chi2, (covn, norm, relres) = handle["out"]
        # one batched pull; see _finalize_wls
        x, chi2, covn, norm, relres = self._pull(
            (x, chi2, covn, norm, relres))
        x0 = handle["x0"]
        if getattr(self, "_pack", None):
            # gather packed (rows, slots, ...) results back to
            # per-pulsar original order BEFORE fault injection and
            # divergence isolation, so lane indices / restored start
            # vectors keep their sequential-path semantics
            ro, so = self._pack["row_of"], self._pack["slot_of"]
            x, chi2 = x[ro, so], chi2[ro, so]
            covn, norm = covn[ro, so], norm[ro, so]
            relres = relres[ro, so]
            x0 = self._pull(x0)[ro, so]
        handle = {**handle, "x0": x0}
        from ..fitter import relres_failed

        if handle["precision"] == "mixed" and relres_failed(relres):
            # the f32 preconditioner failed to contract for >= 1 pulsar
            # (kept spectrum wider than ~1e7, or NaN from an f32
            # overflow): redo the batch in f64 — correctness is
            # non-negotiable, the speedup opt-in
            import warnings

            warnings.warn(
                f"mixed-precision GLS refinement did not converge "
                f"(max rel resid {float(np.max(relres)):.2e}); "
                "refitting in f64")
            if obs_fitq.enabled():
                # count the fallback at the decision; flag the f64
                # re-run's probes so the ledger shows both
                obs_fitq.FITQ.note_fallback(self._pulsar_labels())
                self._fitq_fell_back = True
            return self.gls_fit(maxiter=handle["maxiter"],
                                threshold=handle["threshold"],
                                ecorr_mode=handle["ecorr_mode"],
                                precision="f64",
                                fused=handle.get("fused"))
        cov = covn / (norm[:, :, None] * norm[:, None, :])
        chi2 = self._maybe_inject_divergence(chi2, "gls")
        x, chi2 = self._isolate_diverged(handle["x0"], x, chi2)
        self._record_metrics("gls", handle["t0"], handle["maxiter"],
                             warm=handle["warm"])
        if obs_fitq.enabled():
            self._record_quality("gls", handle, x, chi2, covn,
                                 relres=relres)
        else:
            self.quality = None
        return x, chi2, cov

    def gls_fit(self, maxiter=2, threshold=1e-12, ecorr_mode="auto",
                precision="f64", fused=None):
        """Vmapped, mesh-sharded multi-pulsar GLS fit — the
        BASELINE.json north-star path (NANOGrav-15yr-style refit with
        EFAC/EQUAD/ECORR/red-noise) as ONE jitted program. See
        :meth:`_build_gls` for the two ECORR solve modes and the
        whitening/normalization conventions.

        ``precision="mixed"`` runs the FLOP-dominant Gram products in
        f32 (MXU-native on TPU, where f64 matmuls are software-
        emulated) and recovers f64 parameter accuracy by iterative
        refinement with exact f64 residuals (fitter.gls_eigh_refine).
        A per-pulsar convergence diagnostic guards the mode: if any
        pulsar's refinement failed to contract the whole batch is
        automatically refit in f64 with a warning.
        ``precision="auto"`` times one warm mixed vs f64 run for this
        bucket structure (cached per process) and uses the winner —
        see :meth:`_resolve_precision`.

        ``fused`` selects the packed fused-kernel program (default
        True on packed plan batches; ignored elsewhere) — see
        :meth:`_build_gls_packed`.

        Returns (x_fit, chi2_whitened, cov) like wls_fit; diverged
        pulsars reported via self.diverged.
        """
        return self._finalize_gls(self._dispatch_gls(
            maxiter, threshold, ecorr_mode, precision, fused=fused))

    def _build_method(self, method, maxiter, threshold, ecorr_mode,
                      precision, fused=None):
        """Shared method dispatch for program_key/aot_lower: returns
        (cache_key, fit_one) with the per-method maxiter default
        applied (gls: 2, wls: 3)."""
        if method == "gls":
            maxiter = 2 if maxiter is None else maxiter
            return self._build_gls(maxiter, threshold, ecorr_mode,
                                   precision, fused=fused)
        if method == "wls":
            if precision != "f64":
                raise ValueError(
                    "precision applies to the GLS path only; WLS has "
                    "no mixed-precision mode")
            maxiter = 3 if maxiter is None else maxiter
            return self._build_wls(maxiter, threshold)
        raise ValueError(f"aot_compile: unknown method {method!r}")

    def program_key(self, method="gls", maxiter=None, threshold=1e-12,
                    ecorr_mode="auto", precision="f64", fused=None):
        """The _fns cache key the given fit options compile to — lets
        a fleet/serve scheduler test ``key in batch._fns`` (is this
        program already warm?) without building or tracing anything.
        Fused packed programs key as "packed-fused", so executable
        caches (serve/engine.py) never alias them with classic-path
        builds."""
        return self._build_method(method, maxiter, threshold, ecorr_mode,
                                  precision, fused=fused)[0]

    def aot_lower(self, method="gls", maxiter=None, threshold=1e-12,
                  ecorr_mode="auto", precision="f64", fused=None):
        """Trace (lower) one vmapped fit program WITHOUT compiling it.

        Tracing is GIL-bound Python work, so a pipelined executor runs
        this serially on the caller thread and farms only the XLA
        backend compile (:meth:`_aot_backend_compile`, which releases
        the GIL) out to a thread pool — concurrent tracing would just
        timeshare the interpreter and inflate every per-bucket trace
        measurement.

        Returns {key, method, lowered, trace_s}; feed the whole dict
        to _aot_backend_compile to finish and install the executable.
        """
        from .. import fitter

        key, fit_one = self._build_method(method, maxiter, threshold,
                                          ecorr_mode, precision,
                                          fused=fused)
        import jax

        low = fitter.aot_lower(jax.jit(jax.vmap(fit_one)), self._x0(),
                               self.params, self.batch, self.prep)
        return {"key": key, "method": method, "lowered": low["lowered"],
                "trace_s": low["trace_s"]}

    def _aot_backend_compile(self, low):
        """XLA backend compile of an :meth:`aot_lower` handle; thread-
        safe (pure XLA, releases the GIL) so a fleet can run many
        buckets' compiles concurrently. Installs the executable in the
        fit cache, records the executable's cost model in ``_costs``
        (keyed like ``_fns``) for execute-time roofline attribution,
        and returns the aot_compile info dict."""
        from .. import fitter

        info = fitter.aot_backend_compile(low["lowered"],
                                          label=str(low["key"]))
        self._fns[low["key"]] = info.pop("compiled")
        self._costs[low["key"]] = {
            "flops": info.get("flops"),
            "bytes_accessed": info.get("bytes_accessed"),
            "memory": info.get("memory")}
        return {"method": low["method"], "trace_s": low["trace_s"],
                **info}

    def aot_compile(self, method="gls", maxiter=None, threshold=1e-12,
                    ecorr_mode="auto", precision="f64", fused=None):
        """Ahead-of-time compile one vmapped fit program, splitting
        Python/JAX *trace* time from XLA *backend compile* time and
        recording the compiled executable's own cost model.

        The split answers "is the 100 s+ relay compile tracing or
        XLA?" (the two need opposite fixes: tracing cost is this
        package's graph size, backend cost is XLA/relay-side), and
        the cost model gives an honest FLOP count for MFU accounting
        instead of a hand-derived estimate (SURVEY section 5
        tracing/profiling; the hand model lives in BASELINE.md as the
        cross-check).

        Returns {trace_s, backend_compile_s, flops, bytes_accessed}
        (cost fields None when the backend doesn't report them). The
        executable is installed in the fit cache, so the next
        wls_fit/gls_fit call with the same options runs warm. For the
        concurrent multi-bucket path see :func:`fleet_aot_compile`,
        which splits this into aot_lower + _aot_backend_compile.
        """
        return self._aot_backend_compile(self.aot_lower(
            method, maxiter, threshold, ecorr_mode, precision,
            fused=fused))

    @staticmethod
    def structure_key(model):
        """Hashable model-structure signature: component set, free
        parameters, AND the par values that become static (Python
        scalar) prep config — those must be uniform within a batch
        (stack_prepared asserts it), so they are part of the bucket
        key. Pulsars sharing a key can be stacked into one vmapped
        batch."""
        comps = tuple(sorted(model.components))
        free = tuple(sorted(model.free_params))
        static_cfg = []
        for pname in ("PLANET_SHAPIRO", "ECL", "CORRECT_TROPOSPHERE",
                      "SIFUNC"):
            if pname in model.params:
                static_cfg.append((pname, getattr(model, pname).value))
        # FB-mode vs PB-mode orbits produce different static orb_mode_fb
        if "FB0" in model.params:
            static_cfg.append(("FB0?", getattr(model, "FB0").value
                               is not None))
        return (comps, free, tuple(static_cfg))

    def time_residuals(self):
        """(n_psr, n_toa_max) residual seconds + validity mask. The
        jitted program is cached in self._fns like the fit programs,
        so repeated calls (and serve-layer executable-cache sharing)
        dispatch warm."""
        import jax

        if getattr(self, "_pack", None):
            raise RuntimeError("time_residuals is not supported on "
                               "packed plan batches (serve lanes use "
                               "regular ladder-width batches)")
        key = ("resid",)
        if key not in self._fns:
            resid_fn = self._resid_fn()

            def one(params, batch, prep):
                r, sig = resid_fn(params, batch, prep)
                return r

            self._fns[key] = jax.jit(jax.vmap(one))
        r = self._fns[key](self.params, self.batch, self.prep)
        mask = np.arange(r.shape[1])[None, :] < self.n_toas[:, None]
        return r, mask

    def phases(self):
        """(n_psr, n_toa_max) continuous pulse phase + validity mask —
        the phase-predict surface of the serve engine (polyco-style
        evaluation at the request's TOAs, computed exactly instead of
        through a polynomial expansion). Cached in self._fns like
        time_residuals."""
        import jax

        if getattr(self, "_pack", None):
            raise RuntimeError("phases is not supported on packed "
                               "plan batches (serve lanes use regular "
                               "ladder-width batches)")
        key = ("phase",)
        if key not in self._fns:
            self._fns[key] = jax.jit(jax.vmap(self._phase_fn()))
        ph = self._fns[key](self.params, self.batch, self.prep)
        mask = np.arange(ph.shape[1])[None, :] < self.n_toas[:, None]
        return ph, mask

    def _gw_eval_packed(self, x):
        """Packed-plan half of :meth:`gw_arrays`: scatter the
        per-pulsar fitted vectors into the (rows, slots, k) packed
        layout, then evaluate residual seconds + sigma per ROW with
        the same slot-merge machinery the packed fit uses (slot_env /
        owner-masked jnp.where merges / per-segment weighted phase
        mean). Dummy slots produce NaN rows that no real pulsar's
        span ever indexes. Returns device (R, W) arrays."""
        import jax
        import jax.numpy as jnp

        pack = self._pack
        S = int(pack["n_slots"])
        Q = int(pack["quantum"])
        slot_keys = frozenset(pack["slot_keys"])
        key = ("gw_resid_packed",)
        if key not in self._fns:
            phase_fn = self._phase_fn()
            sigma_fn = self._sigma_fn()

            def row_eval(xrow, params, batch, prep):
                shared = {k: v for k, v in prep.items()
                          if k not in slot_keys
                          and not k.startswith("_pack_")}
                block_slot = prep["_pack_block_slot"]
                W = batch.tdb_sec.shape[0]
                owner = jnp.repeat(block_slot, Q,
                                   total_repeat_length=W)
                ph = sig = None
                f0s = []
                for s in range(S):
                    ps = jax.tree_util.tree_map(lambda v: v[s],
                                                params)
                    full = dict(shared)
                    for k2 in slot_keys:
                        full[k2] = prep[k2][s]
                    p = self._overlay(ps, xrow[s])
                    ph_s = phase_fn(p, batch, full)
                    sig_s = sigma_fn(p, batch, full)
                    if s == 0:
                        ph, sig = ph_s, sig_s
                    else:
                        m = owner == s
                        ph = jnp.where(m, ph_s, ph)
                        sig = jnp.where(m, sig_s, sig)
                    f0s.append(p["F"][0])
                F0 = jnp.stack(f0s)
                # per-segment weighted phase mean — same convention
                # as the packed fit's one_step
                frac = ph - jnp.floor(ph + 0.5)
                wts = 1.0 / jnp.square(sig)
                num = jax.ops.segment_sum(frac * wts, owner,
                                          num_segments=S)
                den = jax.ops.segment_sum(wts, owner, num_segments=S)
                frac = frac - (num / den)[owner]
                return frac / F0[owner], sig

            self._fns[key] = jax.jit(jax.vmap(row_eval))
        base = np.array(jax.device_get(self._x0()), np.float64)
        base[np.asarray(pack["row_of"]),
             np.asarray(pack["slot_of"])] = np.asarray(
                 jax.device_get(x), np.float64)
        return self._fns[key](jnp.asarray(base), self.params,
                              self.batch, self.prep)

    def gw_arrays(self, x):
        """Post-fit per-pulsar arrays for the GW detection stage
        (pint_tpu/gw/): residual seconds evaluated at the FITTED
        parameter vectors ``x`` (n_psr, n_free), per-TOA sigma (us),
        TDB MJDs, and the validity mask — all (n_psr, n_toa_max) host
        numpy in original pulsar order. Unlike :meth:`time_residuals`
        (initial params, regular layout only) this overlays the fit
        result into the phase/sigma programs and also walks
        segment-packed plan batches, gathering each pulsar's
        contiguous span back out of its packed row. The jitted
        programs are cached in ``self._fns`` like the fit programs."""
        import jax
        import jax.numpy as jnp

        x = jnp.asarray(x)
        n_toas = np.asarray(self.n_toas).reshape(-1)
        if getattr(self, "_pack", None):
            r, sig = self._gw_eval_packed(x)
            pack = self._pack
            Q = int(pack["quantum"])
            r, sig, bs, day, sec = self._pull(
                (r, sig, self.prep["_pack_block_slot"],
                 self.batch.tdb_day, self.batch.tdb_sec))
            r = np.asarray(r, np.float64)
            sig = np.asarray(sig, np.float64)
            bs = np.asarray(bs)
            mjd = (np.asarray(day, np.float64)
                   + np.asarray(sec, np.float64) / 86400.0)
            P = len(n_toas)
            n_max = int(n_toas.max())
            out_r = np.zeros((P, n_max))
            out_s = np.ones((P, n_max))
            out_t = np.zeros((P, n_max))
            mask = np.arange(n_max)[None, :] < n_toas[:, None]
            row_of = np.asarray(pack["row_of"])
            slot_of = np.asarray(pack["slot_of"])
            for i in range(P):
                r0, s0 = int(row_of[i]), int(slot_of[i])
                # segments are contiguous Q-quantum spans in the row
                start = int(np.flatnonzero(bs[r0] == s0)[0]) * Q
                n = int(n_toas[i])
                sl = slice(start, start + n)
                out_r[i, :n] = r[r0, sl]
                out_s[i, :n] = sig[r0, sl]
                out_t[i, :n] = mjd[r0, sl]
            return {"resid": out_r, "sigma_us": out_s, "mjd": out_t,
                    "mask": mask}
        key = ("gw_resid",)
        if key not in self._fns:
            resid_fn = self._resid_fn()

            def one(xv, params, batch, prep):
                return resid_fn(self._overlay(params, xv), batch,
                                prep)

            self._fns[key] = jax.jit(jax.vmap(one))
        r, sig = self._fns[key](x, self.params, self.batch, self.prep)
        r, sig, day, sec = self._pull(
            (r, sig, self.batch.tdb_day, self.batch.tdb_sec))
        mjd = (np.asarray(day, np.float64)
               + np.asarray(sec, np.float64) / 86400.0)
        mask = np.arange(r.shape[1])[None, :] < n_toas[:, None]
        return {"resid": np.asarray(r, np.float64),
                "sigma_us": np.asarray(sig, np.float64),
                "mjd": mjd, "mask": mask}

    def shape_signature(self):
        """Hashable fingerprint of every traced array's (shape, dtype)
        across (params, prep, batch). Two PTABatches with equal
        structure_key AND equal shape_signature dispatch the same
        compiled executables when they share a ``_fns`` table — the
        serve-layer cache keys on both, so residual shape variance the
        structure key cannot see (e.g. ECORR epoch counts, param
        vector lengths) becomes a visible cache miss instead of a
        silent retrace."""
        import jax

        leaves = jax.tree_util.tree_leaves(
            (self.params, self.prep, self.batch))
        return tuple((tuple(getattr(leaf, "shape", np.shape(leaf))),
                      str(getattr(leaf, "dtype", type(leaf).__name__)))
                     for leaf in leaves)


def fleet_aot_compile(jobs, max_workers=None):
    """Compile many bucket programs with the trace/XLA split the GIL
    dictates: all traces run serially on the caller thread (tracing is
    pure Python; concurrent tracing only timeshares the interpreter),
    then every XLA backend compile — which releases the GIL — runs in
    a thread pool. With the persistent compilation cache enabled
    (PINT_TPU_COMPILE_CACHE / jax_compilation_cache_dir) hits resolve
    inside the pool too, so a warm cache collapses the whole phase.

    jobs: list of (batch, kwargs) where kwargs are aot_compile-style
    options including "method". Returns (infos, wall_s): infos in job
    order, each the aot_compile info dict; wall_s the total elapsed
    including the serial trace phase — compare against
    sum(trace_s + backend_compile_s) for the concurrency win.
    """
    import os
    from concurrent.futures import ThreadPoolExecutor

    t0 = obs_clock.now()
    with obs_trace.span("fleet.compile", phase="trace", n_jobs=len(jobs)):
        lowered = [batch.aot_lower(**kw) for batch, kw in jobs]
    if not lowered:
        return [], 0.0
    tid = obs_trace.current_trace_id()

    def _compile_one(pair):
        # pool thread: join the caller's trace explicitly (span stacks
        # are thread-local, so the parent link cannot be implicit)
        batch, low = pair
        with obs_trace.span("fleet.compile", trace_id=tid, phase="xla",
                            bucket=low["key"][0]) as sp:
            info = batch._aot_backend_compile(low)
            sp.set(flops=info.get("flops"),
                   bytes_accessed=info.get("bytes_accessed"),
                   intensity_flops_per_byte=info.get(
                       "intensity_flops_per_byte"),
                   roofline_ceiling_flops=info.get(
                       "roofline_ceiling_flops"),
                   bound=info.get("bound"))
            return info

    workers = max_workers or min(len(lowered), os.cpu_count() or 1)
    with ThreadPoolExecutor(max_workers=workers) as pool:
        infos = list(pool.map(_compile_one,
                              zip([b for b, _ in jobs], lowered)))
    return infos, obs_clock.now() - t0


def fleet_pipeline_metrics(fleet, method="auto", maxiter=3, repeats=2,
                           max_workers=None, **kw):
    """Measured pipeline report for one fleet — the shared
    instrumentation surface behind bench.py's fleet-pipeline stage,
    profile_harness --workload fleet_pipeline, and the serve bench:

    - fleet_compile_serial_s / fleet_compile_concurrent_s: the
      serial-equivalent sum(trace_s + backend_compile_s) of every cold
      program vs the wall clock of compiling them through
      fleet_aot_compile (trace serial, XLA concurrent). None when
      every program was already warm (nothing left to compile).
    - fleet_fit_sequential_s / fleet_fit_pipelined_s: best-of-repeats
      WARM fit wall through each executor path (min, not mean — CPU
      bench rounds alias host load into means).
    - fleet_pipeline_overlap_pct: 100 * (1 - pipelined/sequential),
      the fraction of the sequential wall the pipelined executor
      recovers by dispatch-all + overlapped host finalize.
    - fleet_pipeline_bitwise: pipelined results identical to
      sequential (np.array_equal on every x/chi2/cov).
    """
    infos, concurrent_s = fleet.precompile(method=method,
                                           maxiter=maxiter,
                                           max_workers=max_workers)
    if infos:
        serial_s = sum(i["trace_s"] + i["backend_compile_s"]
                       for i in infos)
    else:
        serial_s = concurrent_s = None
    # one warm pass per path (also the bitwise reference)
    xs_s, chi_s, cov_s = fleet.fit(method=method, maxiter=maxiter,
                                   pipeline=False, **kw)
    xs_p, chi_p, cov_p = fleet.fit(method=method, maxiter=maxiter,
                                   pipeline=True, **kw)
    bitwise = bool(
        np.array_equal(chi_s, chi_p)
        and all(np.array_equal(a, b) for a, b in zip(xs_s, xs_p))
        and all(np.array_equal(a, b) for a, b in zip(cov_s, cov_p)))
    seq_s = pipe_s = float("inf")
    for _ in range(max(1, repeats)):
        t0 = obs_clock.now()
        fleet.fit(method=method, maxiter=maxiter, pipeline=False, **kw)
        seq_s = min(seq_s, obs_clock.now() - t0)
        t0 = obs_clock.now()
        fleet.fit(method=method, maxiter=maxiter, pipeline=True, **kw)
        pipe_s = min(pipe_s, obs_clock.now() - t0)
    return {
        "fleet_compile_serial_s": (round(serial_s, 3)
                                   if serial_s is not None else None),
        "fleet_compile_concurrent_s": (round(concurrent_s, 3)
                                       if concurrent_s is not None
                                       else None),
        "fleet_fit_sequential_s": round(seq_s, 4),
        "fleet_fit_pipelined_s": round(pipe_s, 4),
        "fleet_pipeline_overlap_pct": round(
            100.0 * (1.0 - pipe_s / seq_s), 2) if seq_s > 0 else 0.0,
        "fleet_pipeline_bitwise": bitwise,
        "fleet_buckets": len(fleet.group_indices),
    }


class PTAFleet:
    """Mixed-structure PTA fitting: bucket pulsars by model structure,
    one PTABatch per bucket, fit buckets sequentially or — with
    ``pipeline=True`` — through the pipelined executor that overlaps
    host prep, compilation, and device compute across buckets (each
    bucket is one vmapped mesh-sharded program either way).

    Real PTA datasets mix isolated pulsars, different binary models and
    noise configurations; PTABatch requires uniform structure
    (SURVEY.md section 7.3 item 4 — "bucketing TOA counts / component
    sets to limit recompiles"). The reference fits pulsars one at a
    time in Python (no counterpart); this keeps the per-bucket batching
    win while accepting arbitrary mixtures.
    """

    @staticmethod
    def optimal_split_bounds(counts, k):
        """Upper bounds (inclusive pad targets) of the <=k contiguous
        segments over sorted TOA counts that MINIMIZE total padded
        area sum(len(segment) * max(segment)) — O(n^2 k) dynamic
        program, exact. Where pow2 bucketing fixes the bucket edges a
        priori, this picks the k-1 thresholds the actual count
        distribution wants: on the NANOGrav-15yr-like bench raggedness
        k=2 already cuts the one-program padding x3.05 to x1.61, and
        k=3 reaches x1.38 (~= pow2's x1.37 with half the compiled
        programs — compile count is wedge exposure on the tunneled
        TPU, BASELINE.md)."""
        c = np.sort(np.asarray(counts, dtype=np.int64))
        n = len(c)
        if n == 0:
            return []
        k = min(int(k), n)  # segments beyond n are provably useless
        inf = float("inf")
        cost = np.full((n + 1, k + 1), inf)
        cost[0, 0] = 0.0
        back = np.zeros((n + 1, k + 1), dtype=np.int64)
        for i in range(1, n + 1):
            for j in range(1, k + 1):
                for p in range(i):
                    v = cost[p, j - 1] + (i - p) * c[i - 1]
                    if v < cost[i, j]:
                        cost[i, j] = v
                        back[i, j] = p
        j = int(np.argmin(cost[n, 1:])) + 1
        bounds = []
        i = n
        while j > 0:
            bounds.append(int(c[i - 1]))
            i = int(back[i, j])
            j -= 1
        return sorted(bounds)

    @classmethod
    def plan_groups(cls, models, toas_list, toa_bucket=None,
                    bucket_floor=256, plan_compile_budget=None,
                    plan_max_pack=None, plan_quantum=None,
                    plan_min_width=None):
        """Bucket assignment WITHOUT building any PTABatch: returns
        (groups, build_kwargs, plans) where groups maps bucket key ->
        pulsar indices, build_kwargs maps bucket key -> the PTABatch
        constructor kwargs (plan= / pad_toas=) that bucket needs, and
        plans maps structure key -> ShapePlan (toa_bucket="plan"
        only). Shared by __init__ and by fleetmesh.FleetMesh, whose
        DeviceLanes defer per-lane batch construction until a bucket
        is actually dispatched to (or stolen by) a device."""
        split_k = None
        if isinstance(toa_bucket, str) and toa_bucket.startswith("split"):
            try:
                split_k = int(toa_bucket[5:])
            except ValueError:
                split_k = 0
            if split_k < 1:
                raise ValueError(f"toa_bucket {toa_bucket!r}: 'split<k>' "
                                 f"needs a positive integer k")
        elif toa_bucket not in (None, "pow2", "plan"):
            raise ValueError(f"toa_bucket must be None, 'pow2', 'plan', "
                             f"or 'split<k>', got {toa_bucket!r}")
        split_bounds = {}
        if split_k is not None:
            by_struct = {}
            for m, t in zip(models, toas_list):
                by_struct.setdefault(PTABatch.structure_key(m),
                                     []).append(len(t))
            split_bounds = {sk: cls.optimal_split_bounds(cs, split_k)
                            for sk, cs in by_struct.items()}
        plans = {}
        build_kwargs = {}
        if toa_bucket == "plan":
            from . import shapeplan

            plan_kw = {}
            if plan_compile_budget is not None:
                plan_kw["compile_budget"] = int(plan_compile_budget)
            if plan_quantum is not None:
                plan_kw["quantum"] = int(plan_quantum)
            if plan_min_width is not None:
                plan_kw["min_width"] = int(plan_min_width)
            max_pack = (int(plan_max_pack) if plan_max_pack is not None
                        else shapeplan.DEFAULT_MAX_PACK)
            by_struct = {}
            for i, (m, t) in enumerate(zip(models, toas_list)):
                by_struct.setdefault(PTABatch.structure_key(m),
                                     []).append(i)
            groups = {}
            for skey, idxs in by_struct.items():
                tmpl = models[idxs[0]]
                # packing needs the per-segment GLS math; structures
                # with no correlated-noise basis take the WLS route,
                # so they get singleton planned-width rows instead
                packable = any(
                    getattr(c, "basis_weight", None) is not None
                    for c in tmpl.components.values())
                plan = shapeplan.plan_shapes(
                    [len(toas_list[i]) for i in idxs],
                    max_pack=max_pack if packable else 1, **plan_kw)
                plans[skey] = plan
                for bucket in plan.buckets:
                    key = (skey, ("plan", bucket.width))
                    groups[key] = [idxs[j] for j in bucket.indices()]
                    if packable and any(len(r.segments) > 1
                                        for r in bucket.rows):
                        build_kwargs[key] = {"plan": bucket.renumbered()}
                    else:
                        build_kwargs[key] = {"pad_toas": bucket.width}
        else:
            groups = {}
            for i, (m, t) in enumerate(zip(models, toas_list)):
                key = PTABatch.structure_key(m)
                if toa_bucket == "pow2":
                    # canonical pow2 convention shared with serve slot
                    # keys, routed through the shape planner's wrapper
                    from .shapeplan import pow2_width

                    key = (key, pow2_width(len(t), bucket_floor))
                elif split_k is not None:
                    for b in split_bounds[key]:
                        if len(t) <= b:
                            break
                    key = (key, b)
                groups.setdefault(key, []).append(i)
        return groups, build_kwargs, plans

    def __init__(self, models, toas_list, mesh=None, toa_bucket=None,
                 bucket_floor=256, pipeline=False,
                 plan_compile_budget=None, plan_max_pack=None,
                 plan_quantum=None, plan_min_width=None, store=None):
        """toa_bucket=None: group by model structure only (each batch
        pads to its own max TOA count). toa_bucket="pow2": additionally
        bucket pulsars by next-power-of-two TOA count (>= bucket_floor,
        the same serve/batcher.py pow2_bucket convention the online
        engine keys its slots on, so fleet buckets and serve slots
        cannot desynchronize) — on ragged real datasets (NANOGrav spans
        10^2..10^4.5 TOAs/pulsar) structure-only grouping pads EVERY
        pulsar to the fleet max, a ~3x FLOP and memory tax; pow2
        bucketing caps padding waste at 2x per pulsar while keeping
        the compiled-program count at O(log(max/min)).
        toa_bucket="split<k>" (e.g. "split2"): at most k buckets per
        model structure with thresholds chosen by the exact
        minimum-padded-area dynamic program (optimal_split_bounds) —
        fewest programs for a given padding budget, the right trade
        where each extra compile is wedge exposure on a tunneled
        device (SURVEY.md section 7.3 item 4).

        toa_bucket="plan": shape-planned buckets (shapeplan.plan_shapes
        per structure): small pulsars pack several-per-row into
        segment-packed PTABatches (GLS-capable structures) or
        singleton planned-width rows (WLS structures), with the width
        ladder chosen to minimize padded FLOPs under a compile budget
        (plan_compile_budget, default 4). On the 670k bench workload
        the planner lands at padding <= 1.10 with <= 4 programs where
        pow2 pays 1.46 over 6. Knobs: plan_compile_budget,
        plan_max_pack (max pulsars per row), plan_quantum (segment
        alignment).

        pipeline=True defers PTABatch construction to a worker pool:
        buckets pack concurrently with each other and with whatever
        the caller does next (compile, earlier buckets' fits), and
        fit() defaults to the pipelined executor. Results are bitwise
        identical to pipeline=False — only scheduling changes.

        store (a ``pint_tpu.store.PackStore``) short-circuits the
        host prep: each bucket first consults the store under the
        fleet's content signature and, on a verified hit, rebuilds
        via PTABatch.from_packed straight from the mmap'd columns —
        the astropy chain never runs. Misses (cold store, stale
        signature, corrupt entry) fall back to live prep and write
        the fresh pack state back, so the NEXT bring-up hits. Both
        the inline and pipelined build paths take the same detour;
        results are bit-identical either way (the store round-trips
        pack_state exactly)."""
        self.buckets = {}
        self.order = []  # (bucket_key, index_within_bucket) per pulsar
        groups, build_kwargs, self.plans = self.plan_groups(
            models, toas_list, toa_bucket=toa_bucket,
            bucket_floor=bucket_floor,
            plan_compile_budget=plan_compile_budget,
            plan_max_pack=plan_max_pack, plan_quantum=plan_quantum,
            plan_min_width=plan_min_width)
        self.group_indices = groups
        self.pipeline = bool(pipeline)
        self._lock = threading.RLock()
        self.batches = {}
        self._batch_futures = {}
        self._prep_pool = None
        self.store = store
        self._store_sig = None
        if store is not None:
            from ..store import content_signature

            # one signature for the whole fleet: the par files, raw
            # TOA columns, clock/ephemeris config, plan geometry, and
            # bucketing options — computed WITHOUT running prep
            self._store_sig = content_signature(
                models, toas_list, plans=self.plans,
                toa_bucket=toa_bucket, bucket_floor=bucket_floor,
                plan_compile_budget=plan_compile_budget,
                plan_max_pack=plan_max_pack, plan_quantum=plan_quantum,
                plan_min_width=plan_min_width)
        sig = self._store_sig

        def _make(key, ms, ts, bkw):
            """Store-first bucket build: mmap hit -> from_packed,
            else live prep (+ write-back). Shared by both paths."""
            if store is not None:
                st = store.load(sig, key)
                if st is not None and not ("pack" in st
                                           and mesh is not None):
                    # packed plan batches reject a device mesh in
                    # from_packed; that combination rebuilds live
                    return PTABatch.from_packed(ms[0], st, mesh=mesh)
                b = PTABatch(ms, ts, mesh=mesh, **bkw)
                store.put(sig, key, b.pack_state())
                return b
            return PTABatch(ms, ts, mesh=mesh, **bkw)

        if self.pipeline and len(groups) > 1:
            import os
            from concurrent.futures import ThreadPoolExecutor

            tid = obs_trace.current_trace_id()

            def _build(key, ms, ts, bkw):
                # pool thread: join the constructor's trace explicitly
                # (span stacks are thread-local)
                with obs_trace.span("fleet.host_prep", trace_id=tid,
                                    bucket=key, n=len(ms)):
                    return _make(key, ms, ts, bkw)

            self._prep_pool = ThreadPoolExecutor(
                max_workers=min(len(groups), os.cpu_count() or 1))
            for key, idxs in groups.items():
                self._batch_futures[key] = self._prep_pool.submit(
                    _build, key, [models[i] for i in idxs],
                    [toas_list[i] for i in idxs],
                    build_kwargs.get(key, {}))
        else:
            for key, idxs in groups.items():
                with obs_trace.span("fleet.host_prep", bucket=key,
                                    n=len(idxs)):
                    self.batches[key] = _make(
                        key, [models[i] for i in idxs],
                        [toas_list[i] for i in idxs],
                        build_kwargs.get(key, {}))
        self.n = len(models)
        real = sum(len(t) for t in toas_list)
        if toa_bucket == "plan":
            # the plan IS the padded geometry (packed rows included)
            padded = sum(p.padded_area for p in self.plans.values())
        else:
            # analytic padded area (PTABatch pads to the bucket max, so
            # len(bucket) * max(counts) == the packed array area) — no
            # need to force deferred batches just to read a shape
            padded = sum(
                len(idxs) * max(len(toas_list[i]) for i in idxs)
                for idxs in groups.values())
        self.padding_ratio = padded / max(real, 1)

    def _resolve(self, key):
        """The bucket's PTABatch, blocking on its deferred pack if
        pipeline=True and it has not landed yet. Concurrent compile and
        the pipelined executor both resolve buckets from worker
        threads; the pop/insert pair must be atomic or a racing thread
        pops a missing future."""
        with self._lock:
            batch = self.batches.get(key)
            if batch is None:
                # fleet.pack = the blocking wait for this bucket's
                # deferred pack to land (the pack work itself is the
                # worker's fleet.host_prep span)
                with obs_trace.span("fleet.pack", bucket=key):
                    batch = self._batch_futures.pop(key).result()
                self.batches[key] = batch
                if not self._batch_futures and self._prep_pool is not None:
                    self._prep_pool.shutdown(wait=False)
                    self._prep_pool = None
            return batch

    @classmethod
    def from_batches(cls, batches):
        """Wrap already-built PTABatches (e.g. bench.py's pickled
        full-scale pack cache) as a fleet so they can ride the
        pipelined executor / concurrent compile without re-packing.
        Pulsar order is the concatenation of the batches' rows."""
        fleet = cls.__new__(cls)
        fleet.buckets = {}
        fleet.order = []
        fleet.pipeline = False
        fleet._lock = threading.RLock()
        fleet._batch_futures = {}
        fleet._prep_pool = None
        fleet.store = None
        fleet._store_sig = None
        fleet.batches = dict(enumerate(batches))
        start = 0
        fleet.group_indices = {}
        for k, b in fleet.batches.items():
            n = b.n_pulsars
            fleet.group_indices[k] = list(range(start, start + n))
            start += n
        fleet.n = start
        real = sum(int(n) for b in batches for n in b.n_toas)
        padded = sum(int(b.batch.tdb_sec.shape[0]
                         * b.batch.tdb_sec.shape[1]) for b in batches)
        fleet.padding_ratio = padded / max(real, 1)
        return fleet

    def _use_gls(self, batch, method):
        return (method == "gls"
                or (method == "auto"
                    and batch._noise_bw_fn() is not None))

    @staticmethod
    def _scatter(xs, chi2s, covs, idxs, x, chi2, cov):
        """Scatter one bucket's stacked results to per-pulsar slots —
        one host conversion per array, then row indexing (the old
        per-pulsar np.asarray(x)[j] re-converted the whole stack for
        every row)."""
        x, chi2, cov = np.asarray(x), np.asarray(chi2), np.asarray(cov)
        for j, i in enumerate(idxs):
            xs[i] = x[j]
            chi2s[i] = chi2[j]
            covs[i] = cov[j]

    def fit(self, method="auto", maxiter=3, pipeline=None,
            max_workers=None, **kw):
        """Fit every bucket; returns per-pulsar lists (x, chi2, cov)
        in the original pulsar order. method: "wls", "gls", or "auto"
        (gls when the bucket has correlated-noise components).

        pipeline=True (default: the fleet's own pipeline flag) runs
        the pipelined executor: cold bucket programs are traced
        serially then XLA-compiled concurrently in a thread pool
        (max_workers), every bucket's program is DISPATCHED before any
        result is pulled (JAX async dispatch queues the device work,
        so per-bucket wall time becomes max-of-buckets instead of
        sum), and host-side finalize of earlier buckets overlaps
        device execution of later ones. Finalization runs in the same
        bucket order as the sequential path, so results — including
        fault-injection schedules and mixed-precision fallbacks — are
        bitwise identical; only per-bucket fit_wall_s metrics change
        meaning (they include queue wait in pipeline mode).
        """
        if pipeline is None:
            pipeline = self.pipeline
        with obs_trace.span("fleet.fit", n_psr=self.n,
                            n_buckets=len(self.group_indices),
                            method=method, pipeline=bool(pipeline)):
            if not pipeline:
                return self._fit_sequential(method, maxiter, **kw)
            return self._fit_pipelined(method, maxiter, max_workers,
                                       **kw)

    @staticmethod
    def _annotate_execute(sp, batch, use_gls, maxiter, bkw, wall_s,
                          pkey=None):
        """Best-effort roofline attribution of one bucket's execute
        span: look up the program's compile-time cost record in
        ``batch._costs`` and attach mfu_pct / roofline ceiling /
        bound. Called only when tracing is enabled; never raises —
        attribution is telemetry, the fit result is not."""
        try:
            from ..obs import costmodel

            if pkey is None:
                if use_gls:
                    pkey = batch.program_key(
                        "gls", maxiter, bkw.get("threshold", 1e-12),
                        bkw.get("ecorr_mode", "auto"),
                        bkw.get("precision", "f64"))
                else:
                    pkey = batch.program_key(
                        "wls", maxiter, bkw.get("threshold", 1e-12))
            cost = getattr(batch, "_costs", {}).get(pkey)
            if not cost:
                return
            attr = costmodel.attribute(cost.get("flops"),
                                       cost.get("bytes_accessed"),
                                       wall_s=wall_s)
            sp.set(wall_s=round(wall_s, 6),
                   program=str(pkey),
                   flops=attr["flops"],
                   intensity_flops_per_byte=attr[
                       "intensity_flops_per_byte"],
                   roofline_ceiling_flops=attr["roofline_ceiling_flops"],
                   roofline_pct=attr["roofline_pct"],
                   mfu_pct=attr["mfu_pct"],
                   bound=attr["bound"])
        except Exception:
            pass

    def _fit_sequential(self, method, maxiter, **kw):
        xs = [None] * self.n
        chi2s = np.zeros(self.n)
        covs = [None] * self.n
        self.diverged = []
        self.fit_metrics = {}
        self.fit_quality = {}
        for key, idxs in self.group_indices.items():
            batch = self._resolve(key)
            use_gls = self._use_gls(batch, method)
            fit = batch.gls_fit if use_gls else batch.wls_fit
            with obs_trace.span("fleet.execute", bucket=key,
                                n=len(idxs)) as sp:
                traced = obs_trace.enabled()
                t0 = obs_clock.now() if traced else None
                x, chi2, cov = fit(maxiter=maxiter, **kw)
                if traced:
                    self._annotate_execute(sp, batch, use_gls, maxiter,
                                           kw, obs_clock.now() - t0)
                if traced and batch.quality:
                    sp.set(**batch.quality)
            self._scatter(xs, chi2s, covs, idxs, x, chi2, cov)
            self.diverged.extend(idxs[j] for j in batch.diverged)
            self.fit_metrics[key] = batch.metrics
            if batch.quality:
                self.fit_quality[key] = batch.quality
        return xs, chi2s, covs

    def _fit_pipelined(self, method, maxiter, max_workers, **kw):
        import os
        from concurrent.futures import ThreadPoolExecutor

        from ..resilience import faultinject

        xs = [None] * self.n
        chi2s = np.zeros(self.n)
        covs = [None] * self.n
        # 1) plan: resolve batches (in bucket order, so later deferred
        # packs overlap earlier planning) and pin down each bucket's
        # program, resolving precision="auto" now — the probe both
        # fits and times, and the verdict decides which program to
        # compile
        plan = []
        for key, idxs in self.group_indices.items():
            batch = self._resolve(key)
            use_gls = self._use_gls(batch, method)
            bkw = dict(kw)
            allowed = ({"threshold", "ecorr_mode", "precision", "fused"}
                       if use_gls else {"threshold"})
            extra = set(bkw) - allowed
            if extra:
                # same TypeError the sequential path's wls_fit/gls_fit
                # call would raise
                raise TypeError(
                    f"{'gls' if use_gls else 'wls'}_fit() got unexpected "
                    f"keyword arguments {sorted(extra)}")
            if use_gls and bkw.get("precision") == "auto":
                bkw["precision"] = batch._resolve_precision(
                    bkw["precision"], maxiter,
                    bkw.get("threshold", 1e-12),
                    bkw.get("ecorr_mode", "auto"),
                    fused=bkw.get("fused"))
            if use_gls:
                pkey = batch.program_key(
                    "gls", maxiter, bkw.get("threshold", 1e-12),
                    bkw.get("ecorr_mode", "auto"),
                    bkw.get("precision", "f64"),
                    fused=bkw.get("fused"))
            else:
                pkey = batch.program_key(
                    "wls", maxiter, bkw.get("threshold", 1e-12))
            plan.append((key, idxs, batch, use_gls, bkw, pkey))
        # 2) trace cold programs serially (GIL), compile concurrently
        cold = [(key, batch, use_gls, bkw)
                for key, idxs, batch, use_gls, bkw, pkey in plan
                if pkey not in batch._fns]
        self.compile_infos = {}
        compile_futs = {}
        pool = None
        if cold:
            lowered = []
            with obs_trace.span("fleet.compile", phase="trace",
                                n_jobs=len(cold)):
                for key, batch, use_gls, bkw in cold:
                    lkw = {"method": "gls" if use_gls else "wls",
                           "maxiter": maxiter,
                           "threshold": bkw.get("threshold", 1e-12)}
                    if use_gls:
                        lkw["ecorr_mode"] = bkw.get("ecorr_mode",
                                                    "auto")
                        lkw["precision"] = bkw.get("precision", "f64")
                        lkw["fused"] = bkw.get("fused")
                    lowered.append((key, batch,
                                    batch.aot_lower(**lkw)))
            tid = obs_trace.current_trace_id()

            def _compile_one(key, batch, low):
                # pool thread: join the fit's trace explicitly
                with obs_trace.span("fleet.compile", trace_id=tid,
                                    phase="xla", bucket=key) as csp:
                    info = batch._aot_backend_compile(low)
                    csp.set(flops=info.get("flops"),
                            bytes_accessed=info.get("bytes_accessed"),
                            roofline_ceiling_flops=info.get(
                                "roofline_ceiling_flops"),
                            bound=info.get("bound"))
                    return info

            pool = ThreadPoolExecutor(
                max_workers=max_workers
                or min(len(cold), os.cpu_count() or 1))
            compile_futs = {
                key: pool.submit(_compile_one, key, batch, low)
                for key, batch, low in lowered}
        try:
            # 3) dispatch every bucket before pulling anything (JAX
            # async dispatch queues the device work); a bucket waits
            # only for its OWN compile
            handles = []
            for bi, (key, idxs, batch, use_gls, bkw, pkey) in \
                    enumerate(plan):
                fut = compile_futs.get(key)
                if fut is not None:
                    self.compile_infos[key] = fut.result()
                # device-level chaos: a straggling device delays THIS
                # bucket's dispatch without failing it — downstream
                # buckets still dispatch, finalize order is unchanged,
                # so results stay bitwise-equal to sequential. The
                # payload's "lane" (when set) pins which bucket index
                # straggles; fire() ctx must not shadow it.
                fault = faultinject.fire("straggler_delay", bucket=bi)
                if fault and int(fault.get("lane", bi)) == bi:
                    import time as _time

                    _time.sleep(float(fault.get("delay_s", 0.0)))
                with obs_trace.span("fleet.dispatch", bucket=bi,
                                    n=len(idxs)):
                    if use_gls:
                        h = batch._dispatch_gls(
                            maxiter, bkw.get("threshold", 1e-12),
                            bkw.get("ecorr_mode", "auto"),
                            bkw.get("precision", "f64"),
                            fused=bkw.get("fused"))
                    else:
                        h = batch._dispatch_wls(
                            maxiter, bkw.get("threshold", 1e-12))
                handles.append((key, idxs, batch, use_gls, h, pkey))
            # 4) finalize in the SAME bucket order as the sequential
            # path — the host unpack of bucket i overlaps device
            # execution of buckets i+1.. still queued, and the
            # fault-injection fire() sequence matches sequential
            # exactly (bitwise guarantee)
            self.diverged = []
            self.fit_metrics = {}
            self.fit_quality = {}
            for key, idxs, batch, use_gls, h, pkey in handles:
                fin = (batch._finalize_gls if use_gls
                       else batch._finalize_wls)
                with obs_trace.span("fleet.execute", bucket=key,
                                    n=len(idxs)) as sp:
                    traced = obs_trace.enabled()
                    t0 = obs_clock.now() if traced else None
                    x, chi2, cov = fin(h)
                    if traced:
                        # wall includes queue wait (pipeline mode) —
                        # the attributed MFU is a lower bound here
                        self._annotate_execute(sp, batch, use_gls,
                                               maxiter, {},
                                               obs_clock.now() - t0,
                                               pkey=pkey)
                    if traced and batch.quality:
                        sp.set(**batch.quality)
                self._scatter(xs, chi2s, covs, idxs, x, chi2, cov)
                self.diverged.extend(idxs[j] for j in batch.diverged)
                self.fit_metrics[key] = batch.metrics
                if batch.quality:
                    self.fit_quality[key] = batch.quality
        finally:
            if pool is not None:
                pool.shutdown(wait=False)
        return xs, chi2s, covs

    def precompile(self, method="auto", maxiter=3, max_workers=None,
                   threshold=1e-12, ecorr_mode="auto", precision="f64"):
        """Concurrently AOT-compile every bucket's fit program that is
        not already warm (see fleet_aot_compile for the trace/XLA
        split). precision="auto" compiles BOTH gls modes per bucket so
        the runtime probe dispatches warm either way. Returns
        (infos, wall_s); infos also land in self.compile_infos."""
        jobs = []
        for key in self.group_indices:
            batch = self._resolve(key)
            use_gls = self._use_gls(batch, method)
            if use_gls:
                modes = (("f64", "mixed") if precision == "auto"
                         else (precision,))
                for mode in modes:
                    kwargs = {"method": "gls", "maxiter": maxiter,
                              "threshold": threshold,
                              "ecorr_mode": ecorr_mode,
                              "precision": mode}
                    if batch.program_key(**kwargs) not in batch._fns:
                        jobs.append((batch, kwargs))
            else:
                kwargs = {"method": "wls", "maxiter": maxiter,
                          "threshold": threshold}
                if batch.program_key(**kwargs) not in batch._fns:
                    jobs.append((batch, kwargs))
        infos, wall_s = fleet_aot_compile(jobs, max_workers=max_workers)
        self.compile_infos = dict(enumerate(infos))
        return infos, wall_s

    def free_maps(self):
        """Per-pulsar free-parameter maps in original order."""
        out = [None] * self.n
        for key, idxs in self.group_indices.items():
            fmap = self.batches[key].free_map()
            for i in idxs:
                out[i] = fmap
        return out

    def gw_stage(self, xs=None, method="auto", maxiter=3,
                 lattice_days=30.0, orf="hd", n_scrambles=0,
                 scramble_mode="sky", seed=0, precision="f64",
                 block=256, positions=None, interpret=False,
                 lattice=None, **kw):
        """End-to-end GW detection stage over this fleet (the
        pint_tpu/gw/ pipeline): fit every bucket (skipped when the
        fitted per-pulsar vectors ``xs`` are supplied), assemble
        post-fit residual/weight arrays and sky positions, regrid
        onto a common ``lattice_days`` epoch lattice, and run the
        Hellings–Downs optimal statistic over all pulsar pairs.
        ``n_scrambles > 0`` additionally calibrates significance with
        that many seeded ``scramble_mode`` null draws ("sky" or
        "phase"). ``positions`` (n, 3) overrides model astrometry —
        required for store-rebuilt fleets whose template models carry
        no real coordinates. Returns the optimal-statistic dict
        (amp2 / snr / pair sweep stats) plus lattice shape and, when
        scrambling, the ``null`` block with its p-value.

        ``lattice`` short-circuits the fit/assemble/regrid front half
        with a caller-held GWLattice — the streaming-refit consumer:
        ``append_toas`` traffic keeps a lattice current through
        ``gw.regrid_append`` (one O(r) row update per append, bitwise
        what a full regrid of the final dataset would build) and the
        pair sweep runs directly on it instead of re-fitting the
        fleet and re-binning every pulsar."""
        from .. import gw

        with obs_trace.span("gw.stage", n_psr=self.n, orf=orf,
                            n_scrambles=n_scrambles,
                            incremental=lattice is not None):
            if lattice is not None:
                lat = lattice
            else:
                if xs is None:
                    xs, _, _ = self.fit(method=method,
                                        maxiter=maxiter, **kw)
                inputs = gw.assemble(self, xs, positions=positions)
                lat = gw.regrid(inputs, lattice_days=lattice_days)
            out = gw.optimal_statistic(lat, orf=orf,
                                       precision=precision,
                                       block=block,
                                       interpret=interpret)
            out["n_pulsars"] = lat.n_pulsars
            out["n_cells"] = lat.n_cells
            if n_scrambles:
                out["null"] = gw.scramble_null(
                    lat, n_draws=n_scrambles, seed=seed,
                    mode=scramble_mode, orf=orf, precision=precision,
                    block=block, interpret=interpret,
                    snr_obs=out["snr"])
        return out
