"""PTA batch fitting: vmap over pulsars, pjit over a device mesh.

This is the BASELINE.json north-star path (no reference counterpart —
the reference fits pulsars one at a time in a Python loop): stack many
pulsars' prepared models into one pytree, vmap the whole WLS/GLS
iteration, and shard the pulsar axis across TPU chips with
jax.sharding. A full PTA refit is then ONE jitted program.

Requirements: all pulsars share the same model *structure* (component
set, F order, mask/basis counts — pad counts to the max). TOA counts
are padded to the batch max with sigma=1e30 sentinels so padded rows
vanish from every whitened reduction.
"""

from __future__ import annotations

import numpy as np

from ..models.timing_model import PreparedTiming

_EXCLUDE_KEYS = ("T_ld", "pepoch_day", "pepoch_sec")
_STATIC_KEYS = ("orb_mode_fb", "planet_shapiro", "obliquity",
                "tropo_on", "ifunc_mode")


def _is_static(key, value):
    """Control-flow config (bools/strs/known keys) must stay Python
    scalars — stacking them into traced arrays breaks `if` branches
    inside the jitted phase functions."""
    return key in _STATIC_KEYS or isinstance(value, (bool, str))
_PAD_SIGMA = 1e30


def _toa_dim_pad(arr, n_toa, n_max):
    """Pad only dimensions equal to this pulsar's own TOA count.

    Non-TOA axes (Taylor orders, mask counts, basis columns) must NOT
    be touched here — ragged counts there are padded with zeros later
    by _pad_to across the batch.
    """
    a = np.asarray(arr)
    if n_toa == n_max:
        return a
    if a.ndim == 1 and a.shape[0] == n_toa:
        a = np.concatenate([a, np.repeat(a[-1:], n_max - n_toa, axis=0)])
    elif a.ndim == 2:
        if a.shape[1] == n_toa:  # (k, n_toa) masks
            a = np.concatenate(
                [a, np.zeros((a.shape[0], n_max - n_toa))], axis=1)
        elif a.shape[0] == n_toa:  # (n_toa, k) bases
            a = np.concatenate(
                [a, np.zeros((n_max - n_toa, a.shape[1]))], axis=0)
    return a


def _pad_to(a, shape):
    out = np.zeros(shape, dtype=np.asarray(a).dtype)
    sl = tuple(slice(0, s) for s in np.asarray(a).shape)
    out[sl] = np.asarray(a)
    return out


def stack_prepared(preps: list[PreparedTiming]):
    """Stack same-structure PreparedTimings into batched pytrees.

    Returns (params_stack, prep_stack, batch_stack, static, n_toas).
    """
    import jax.numpy as jnp

    n_max = max(p.batch.n_toas for p in preps)
    n_toas = np.array([p.batch.n_toas for p in preps])

    # --- params: same keys; vector lengths padded to max
    keys = preps[0].params0.keys()
    params_stack = {}
    for k in keys:
        arrs = [np.atleast_1d(np.asarray(p.params0[k])) for p in preps]
        klen = max(a.shape[0] for a in arrs)
        params_stack[k] = jnp.asarray(
            np.stack([_pad_to(a, (klen,)) if a.ndim else a for a in arrs]))
        if np.asarray(preps[0].params0[k]).ndim == 0:
            params_stack[k] = params_stack[k][:, 0]

    # --- prep: pad TOA dims and ragged mask/basis counts
    static = {}
    prep_stack = {}
    for k in preps[0].prep:
        if k in _EXCLUDE_KEYS:
            continue
        vals = [p.prep[k] for p in preps]
        if _is_static(k, vals[0]):
            assert all(np.all(v == vals[0]) for v in vals), \
                f"prep[{k}] must be uniform across the PTA batch"
            static[k] = vals[0]
            continue
        arrs = [np.asarray(_toa_dim_pad(v, p.batch.n_toas, n_max))
                for v, p in zip(vals, preps)]
        shape = tuple(max(a.shape[i] for a in arrs) for i in range(arrs[0].ndim))
        prep_stack[k] = jnp.asarray(np.stack([_pad_to(a, shape) for a in arrs]))

    # --- batch: pad TOA axis; sentinel sigma on padded rows
    from ..toa import TOABatch

    fields = {}
    for name in TOABatch._fields:
        arrs = []
        for p in preps:
            a = np.asarray(getattr(p.batch, name))
            n = p.batch.n_toas
            if name == "error_us":
                a = np.concatenate([a, np.full(n_max - n, _PAD_SIGMA)])
            elif a.ndim >= 1 and a.shape[-1] == n and name != "planet_pos_ls":
                pad = n_max - n
                a = np.concatenate([a, np.repeat(a[-1:], pad, axis=0)], axis=0) \
                    if a.ndim == 1 else a
            if name == "obs_pos_ls" or name == "obs_vel_ls" or name == "obs_sun_ls":
                if a.shape[0] != n_max:
                    a = np.concatenate(
                        [a, np.repeat(a[-1:], n_max - a.shape[0], axis=0)], axis=0)
            if name == "planet_pos_ls":
                if a.shape[0] and a.shape[1] != n_max:
                    a = np.concatenate(
                        [a, np.repeat(a[:, -1:], n_max - a.shape[1], axis=1)], axis=1)
            if name in ("tdb_day", "tdb_sec", "freq_mhz", "pulse_number") \
                    and a.shape[0] != n_max:
                a = np.concatenate([a, np.repeat(a[-1:], n_max - a.shape[0])])
            arrs.append(a)
        shape = tuple(max(x.shape[i] for x in arrs) for i in range(arrs[0].ndim)) \
            if arrs[0].ndim else ()
        fields[name] = jnp.asarray(np.stack([_pad_to(a, shape) for a in arrs]))
    batch_stack = TOABatch(**fields)
    return params_stack, prep_stack, batch_stack, static, n_toas


def pure_phase_fn(template_model, static):
    """(params, batch, prep) -> continuous phase; pure, closure-free over
    data so it vmaps over pulsars and shard_maps over the TOA axis."""
    delay_comps = template_model.delay_components()
    phase_comps = template_model.phase_components()

    def phase(params, batch, prep):
        import jax.numpy as jnp

        full_prep = {**prep, **static}
        d = jnp.zeros_like(batch.tdb_sec)
        for c in delay_comps:
            d = d + c.delay(params, batch, full_prep, d)
        ph = jnp.zeros_like(d)
        for c in phase_comps:
            ph = ph + c.phase(params, batch, full_prep, d)
        return ph

    return phase


def pure_sigma_fn(template_model, static):
    comps = [c for c in template_model.components.values()
             if getattr(c, "scale_sigma", None) is not None]

    def sigma_us(params, batch, prep):
        s = batch.error_us
        for c in comps:
            s = c.scale_sigma(params, batch, {**prep, **static}, s)
        return s

    return sigma_us


class PTABatch:
    """Batched multi-pulsar fitting (the reference's per-pulsar Python
    loop becomes one vmapped, mesh-sharded program).

    All models must share component structure; see stack_prepared.
    """

    def __init__(self, models, toas_list, mesh=None):
        from ..models.timing_model import _cpu_staging, device_put_staged

        self.models = models
        self.toas_list = toas_list
        # stage per-pulsar packing + stacking on the CPU backend, then
        # one batched transfer of the stacked trees (behind a tunnel,
        # per-array transfers dominate the pack otherwise)
        with _cpu_staging():
            self.preps = [m.prepare(t) for m, t in zip(models, toas_list)]
            (self.params, self.prep, self.batch, self.static,
             self.n_toas) = stack_prepared(self.preps)
        self.params, self.prep, self.batch = device_put_staged(
            (self.params, self.prep, self.batch))
        self.template = models[0]
        self.mesh = mesh
        if mesh is not None:
            from .mesh import shard_batch

            self.params = shard_batch(self.params, mesh)
            self.prep = shard_batch(self.prep, mesh)
            self.batch = shard_batch(self.batch, mesh)
        self._fns = {}

    # -- single-pulsar kernel (closed over static config only) --

    def _phase_fn(self):
        return pure_phase_fn(self.template, self.static)

    def _sigma_fn(self):
        return pure_sigma_fn(self.template, self.static)

    def _resid_fn(self):
        phase = self._phase_fn()
        sigma_fn = self._sigma_fn()

        def resid_seconds(params, batch, prep):
            import jax.numpy as jnp

            ph = phase(params, batch, prep)
            frac = ph - jnp.floor(ph + 0.5)
            sig = sigma_fn(params, batch, prep)
            w = 1.0 / jnp.square(sig)
            frac = frac - jnp.sum(frac * w) / jnp.sum(w)
            return frac / params["F"][0], sig

        return resid_seconds

    def free_map(self):
        """Free-parameter layout of the template (uniform across batch)."""
        return self.preps[0].free_param_map()

    def _overlay(self, params, x):
        out = dict(params)
        for i, (_, key, idx) in enumerate(self.free_map()):
            v = out[key]
            if v.ndim == 0 or idx is None:
                out[key] = x[i]
            else:
                out = {**out, key: v.at[idx].set(x[i])}
        return out

    def _x0(self):
        import jax.numpy as jnp
        import jax

        def pull_one(params):
            vals = []
            for (_, key, idx) in self.free_map():
                v = params[key]
                vals.append(v if (v.ndim == 0 or idx is None) else v[idx])
            return jnp.stack(vals)

        return jax.vmap(pull_one)(self.params)

    def wls_fit(self, maxiter=3, threshold=1e-12):
        """Vmapped, mesh-sharded multi-pulsar WLS fit.

        Returns (x_fit (n_psr, n_free), chi2 (n_psr,), cov (n_psr, k, k)).
        """
        import jax
        import jax.numpy as jnp

        resid_fn = self._resid_fn()

        def one_step(x, params, batch, prep):
            p = self._overlay(params, x)
            r, sig = resid_fn(p, batch, prep)
            sigma_s = sig * 1e-6

            def phase_of(xv):
                pp = self._overlay(params, xv)
                ph = self._phase_fn()(pp, batch, prep)
                return ph

            M = jax.jacfwd(phase_of)(x) / p["F"][0]
            M = jnp.concatenate([jnp.ones((M.shape[0], 1)), M], axis=1)
            Mw = M / sigma_s[:, None]
            rw = r / sigma_s
            # exponent-safe normalization + normalized-space covariance
            # (TPU f64 has f32-like exponent range; see fitter.column_norms)
            from ..fitter import column_norms

            norm = column_norms(Mw)
            Mn = Mw / norm
            U, s, Vt = jnp.linalg.svd(Mn, full_matrices=False)
            sinv = jnp.where(s > threshold * jnp.max(s), 1.0 / s, 0.0)
            dx = (Vt.T @ (sinv * (U.T @ rw))) / norm
            covn = Vt.T @ jnp.diag(sinv**2) @ Vt
            chi2 = jnp.sum(jnp.square(rw - Mw @ dx))
            return x - dx[1:], chi2, (covn[1:, 1:], norm[1:])

        def fit_one(x0, params, batch, prep):
            x = x0
            for _ in range(maxiter):
                x, chi2, cov = one_step(x, params, batch, prep)
            return x, chi2, cov

        key = ("wls", maxiter, threshold)
        if key not in self._fns:
            self._fns[key] = jax.jit(jax.vmap(fit_one))
        x, chi2, (covn, norm) = self._fns[key](self._x0(), self.params,
                                               self.batch, self.prep)
        # physical-unit covariance on host in IEEE f64: variances like
        # var(F1)~1e-38 leave the TPU emulated-f64 exponent range
        covn = np.asarray(covn, np.float64)
        norm = np.asarray(norm, np.float64)
        cov = covn / (norm[:, :, None] * norm[:, None, :])
        return x, chi2, cov

    def time_residuals(self):
        """(n_psr, n_toa_max) residual seconds + validity mask."""
        import jax
        import jax.numpy as jnp

        resid_fn = self._resid_fn()

        def one(params, batch, prep):
            r, sig = resid_fn(params, batch, prep)
            return r

        r = jax.jit(jax.vmap(one))(self.params, self.batch, self.prep)
        mask = np.arange(r.shape[1])[None, :] < self.n_toas[:, None]
        return r, mask
