"""Distributed failure domains: the shape-planned fleet sharded over
a pulsar-axis device mesh, with per-device health and recovery.

Everything in ``pint_tpu/resilience`` (breakers, health gating,
quarantine) was built single-device; here the multi-device path
becomes a first-class failure domain. The design choice that makes it
work: each device is wrapped in a :class:`DeviceLane` owning its OWN
``HealthMonitor`` and ``CircuitBreaker``, and every shape-plan bucket
is dispatched to exactly one lane (the lane's single-device 'pulsar'
mesh — see ``mesh.lane_meshes``). A bucket program therefore touches
one chip, so a lost/hung/straggling chip poisons that lane's buckets
and nothing else — where a fleet-spanning shard_map program would die
whole. The cross-device coupling a PTA fit actually needs is zero
(per-pulsar fits are embarrassingly parallel; the TOA-axis psum path
lives in ``toa_shard`` and gets the same watchdog via ``run_watched``).

Failure handling, in order of escalation:

- ``straggler_delay`` (injected) / a genuinely slow lane: the bucket
  is late, the lane's flush watchdog notes the breach, nothing fails.
- ``collective_timeout`` / a hung device pull: ``run_watched`` bounds
  every blocking result pull with a daemon-thread watchdog, so a hung
  psum/all_gather surfaces as a catchable :class:`CollectiveTimeout`
  instead of wedging the fleet; the lane's breaker records the
  failure and the bucket retries (a tripped breaker quarantines the
  lane).
- ``device_loss`` / :class:`DeviceLost`: the lane is quarantined
  immediately (a lost chip does not come back mid-fit), its pending
  buckets are re-sharded onto the surviving lanes in deterministic
  order (canonical bucket order round-robined over surviving lane
  indices — a pure function of the completed set and the survivor
  set, so two runs with the same fault schedule steal identically),
  and the failed bucket re-runs on a survivor.
- a bucket that fails on a HEALTHY lane (poisoned pulsar, persistent
  solver fault): bisected down to singletons exactly like the serve
  engine's lane-quarantine path — the pathological pulsars are
  quarantined with NaN results, their co-bucketed neighbors complete.

Progress is checkpointable per bucket (``checkpoint.FitCheckpointer``
CRC + rotation): a fleet fit interrupted mid-bucket resumes from the
last completed bucket and finishes with bit-identical final
parameters — completed buckets restore bitwise from the snapshot and
the remaining buckets run the same programs in the same order.

Multi-device dryrun on CPU:
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (tests/conftest
sets N=8).
"""

from __future__ import annotations

import threading
import time
import warnings
import zlib

import numpy as np

from ..obs import trace as obs_trace
from ..obs.recorder import RECORDER as _flight
from ..resilience import faultinject
from ..resilience.faultinject import FaultInjected
from ..resilience.health import HealthMonitor
from ..resilience.retry import CircuitBreaker


class DeviceLost(RuntimeError):
    """A device in the fleet mesh died (injected via the
    ``device_loss`` fault point, or raised by a caller that detected a
    real chip loss). Never retryable on the SAME lane — the handling
    is quarantine + work stealing, not backoff."""


class CollectiveTimeout(TimeoutError):
    """A cross-device collective / device result pull exceeded the
    watchdog bound. TimeoutError subclass so retry.is_retryable treats
    it as transient — the bucket retries on a (possibly different)
    lane while the breaker counts the lane's strikes."""


def run_watched(fn, timeout_s, what="collective"):
    """Run ``fn()`` under a collective watchdog: a hung native
    psum/all_gather (or any wedged device pull) cannot be interrupted
    from Python, so the call runs in a daemon worker thread and the
    caller bounds the join. On timeout a catchable
    :class:`CollectiveTimeout` is raised naming the site; the
    abandoned worker cannot keep the interpreter alive (daemon), the
    same shape as ``initialize_distributed``'s handshake watchdog."""
    if timeout_s is None:
        return fn()
    out = {}

    def _worker():
        try:
            out["value"] = fn()
        except Exception as e:  # surfaced in the caller below
            out["error"] = e

    worker = threading.Thread(target=_worker, daemon=True,
                              name="pint-tpu-collective-watchdog")
    worker.start()
    worker.join(timeout_s)
    if worker.is_alive():
        raise CollectiveTimeout(
            f"{what} did not complete within {timeout_s:.1f}s "
            "(hung collective or wedged device); the lane's breaker "
            "records this and the bucket is re-dispatched")
    if "error" in out:
        raise out["error"]
    return out["value"]


class DeviceLane:
    """One device of the fleet mesh as an independent failure domain:
    the device, its single-device 'pulsar' mesh, and its OWN
    HealthMonitor + CircuitBreaker (keyed by ``self.key``). The fleet
    quarantines a lane — and steals its pending buckets — when the
    breaker trips or health reaches draining, mirroring what the
    serve engine does to a poisoned in-batch lane."""

    def __init__(self, index, device, clock=time.monotonic,
                 breaker=None, health=None, breaker_threshold=2,
                 breaker_cooldown_s=30.0):
        self.index = int(index)
        self.device = device
        self.key = ("lane", self.index)
        self.clock = clock
        self.breaker = breaker or CircuitBreaker(
            threshold=breaker_threshold, cooldown_s=breaker_cooldown_s,
            clock=clock)
        self.health = health or HealthMonitor(clock=clock)
        self.lost = False
        self.completed = []  # canonical bucket order-indices
        self.stolen = 0  # buckets this lane took over from dead lanes
        self._mesh = None

    @property
    def mesh(self):
        """Single-device 1-D 'pulsar' Mesh, built on first use (packed
        plan buckets run under jax.default_device instead — PTABatch
        rejects plan+mesh — so many lanes never need one)."""
        if self._mesh is None:
            import numpy as _np
            from jax.sharding import Mesh

            self._mesh = Mesh(_np.array([self.device]),
                              axis_names=("pulsar",))
        return self._mesh

    def alive(self):
        return (not self.lost
                and self.breaker.state(self.key) != "open"
                and self.health.state != "draining")

    def quarantine(self):
        """Mark the lane dead and force its breaker open; idempotent.
        Returns True when this call newly quarantined it."""
        was = self.lost
        self.lost = True
        tripped = self.breaker.trip(self.key)
        self.health.note_breakers(self.breaker.open_count(), tripped)
        return not was

    def snapshot(self):
        return {"index": self.index, "device": str(self.device),
                "lost": bool(self.lost), "alive": self.alive(),
                "completed_buckets": list(self.completed),
                "stolen": int(self.stolen),
                "health": self.health.snapshot(),
                "breaker": self.breaker.snapshot()}


class FleetMesh:
    """Shape-planned fleet fitting across a device mesh of
    :class:`DeviceLane` failure domains (module docstring has the
    failure-handling contract).

    Buckets come from ``PTAFleet.plan_groups`` (same grouping as
    PTAFleet — structure key x toa_bucket policy, including "plan"
    packed buckets) and are assigned to lanes deterministically:
    canonical bucket order (sorted by repr) round-robined over lane
    indices. Per-lane PTABatch construction is deferred until a
    bucket is actually dispatched, so stealing a bucket just rebuilds
    it on the surviving lane's device.

    clock/sleep are injectable (tests drive fault delays with a fake
    clock); collective_timeout_s=None disables the watchdog.
    """

    def __init__(self, models, toas_list, devices=None, toa_bucket=None,
                 bucket_floor=256, clock=time.monotonic,
                 sleep=time.sleep, breaker_threshold=2,
                 breaker_cooldown_s=30.0, collective_timeout_s=60.0,
                 bisect_depth=4, **plan_kw):
        from .pta import PTAFleet

        groups, build_kwargs, plans = PTAFleet.plan_groups(
            models, toas_list, toa_bucket=toa_bucket,
            bucket_floor=bucket_floor, **plan_kw)
        self.models = models
        self.toas_list = toas_list
        self.group_indices = groups
        self.build_kwargs = build_kwargs
        self.plans = plans
        self.n = len(models)
        if devices is None:
            import jax

            devices = jax.devices()
        if not devices:
            raise ValueError("FleetMesh needs at least one device")
        self.clock = clock
        self._sleep = sleep
        self.collective_timeout_s = collective_timeout_s
        self.bisect_depth = int(bisect_depth)
        self.lanes = [
            DeviceLane(i, d, clock=clock,
                       breaker_threshold=breaker_threshold,
                       breaker_cooldown_s=breaker_cooldown_s)
            for i, d in enumerate(devices)]
        # canonical bucket order: sorted by repr so assignment — and
        # every re-shard after a lane loss — is a pure function of the
        # (bucket set, survivor set), never of dict iteration order
        self.bucket_order = sorted(groups, key=repr)
        self.assignment = {key: i % len(self.lanes)
                           for i, key in enumerate(self.bucket_order)}
        self._built = {}  # (order_idx, lane_idx) -> PTABatch
        self.reassignments = []  # (bucket_repr, from_lane, to_lane)
        self.stolen = 0
        self.diverged = []
        self.quarantined = []  # pulsar indices bisected out
        self.fit_quality = {}  # bucket repr -> fitquality summary

    # -- lane selection / work stealing -----------------------------

    def _survivors(self):
        return [ln for ln in self.lanes if ln.alive()]

    def _steal_from(self, lane, completed):
        """Re-shard ``lane``'s pending buckets onto surviving lanes:
        pending buckets in canonical order, round-robined over
        surviving lane indices in ascending order — deterministic and
        bitwise-reproducible (the reassignment is pure bookkeeping;
        the stolen bucket's program re-runs identically on the new
        device)."""
        survivors = self._survivors()
        if not survivors:
            return
        with obs_trace.span("mesh.steal", from_lane=lane.index):
            pending = [k for k in self.bucket_order
                       if k not in completed
                       and self.assignment[k] == lane.index]
            tid = obs_trace.current_trace_id()
            for j, key in enumerate(pending):
                to = survivors[j % len(survivors)]
                self.reassignments.append((repr(key), lane.index,
                                           to.index))
                self.assignment[key] = to.index
                to.stolen += 1
                self.stolen += 1
                _flight.note("work_steal", bucket=repr(key),
                             from_lane=lane.index, to_lane=to.index,
                             trace=tid)

    def _lane_for(self, key, completed):
        """The bucket's assigned lane, stealing first when the owner
        is dead. Returns None when no lane survives."""
        lane = self.lanes[self.assignment[key]]
        if lane.alive():
            return lane
        self._steal_from(lane, completed)
        lane = self.lanes[self.assignment[key]]
        return lane if lane.alive() else None

    # -- bucket execution -------------------------------------------

    def _use_gls(self, batch, method):
        return (method == "gls"
                or (method == "auto"
                    and batch._noise_bw_fn() is not None))

    def _split_kw(self, use_gls, kw):
        allowed = ({"threshold", "ecorr_mode", "precision"}
                   if use_gls else {"threshold"})
        extra = set(kw) - allowed
        if extra:
            raise TypeError(
                f"{'gls' if use_gls else 'wls'}_fit() got unexpected "
                f"keyword arguments {sorted(extra)}")
        return {k: v for k, v in kw.items() if k in allowed}

    def _batch_for(self, oi, key, lane):
        """The bucket's PTABatch committed to ``lane``'s device
        (rebuilt per lane: executables are device-committed, and a
        stolen bucket must not drag arrays off a dead chip)."""
        import jax

        from .pta import PTABatch

        cached = self._built.get((oi, lane.index))
        if cached is not None:
            return cached
        idxs = self.group_indices[key]
        bkw = self.build_kwargs.get(key, {})
        # packed plan buckets reject an explicit mesh; default_device
        # commits their arrays (and everything else's) to the lane
        with jax.default_device(lane.device):
            batch = PTABatch([self.models[i] for i in idxs],
                             [self.toas_list[i] for i in idxs], **bkw)
        self._built[(oi, lane.index)] = batch
        return batch

    def _watched(self, fn, lane, what):
        """Collective watchdog around one blocking device pull, with
        the ``collective_timeout`` fault point simulating the hang
        deterministically: an injected hang >= the watchdog bound
        times out (the fleet pays the full watchdog wait, as it would
        for a real hang); a shorter one is just a late collective."""
        fault = faultinject.fire("collective_timeout", site=what)
        timeout = self.collective_timeout_s
        if fault and int(fault.get("lane", lane.index)) == lane.index:
            hang = float(fault.get("hang_s", (timeout or 0.0) + 1.0))
            if timeout is not None and hang >= timeout:
                self._sleep(timeout)
                raise CollectiveTimeout(
                    f"{what} hung past the {timeout:.1f}s watchdog "
                    f"(injected hang {hang:.1f}s on lane {lane.index})")
            self._sleep(hang)
        return run_watched(fn, timeout, what=what)

    def _run_bucket(self, lane, oi, key, method, maxiter, **kw):
        """One bucket fit on one lane. Raises DeviceLost /
        CollectiveTimeout for device-level failures (handled by the
        caller via quarantine + stealing); other exceptions mean the
        bucket itself is bad (bisected by the caller)."""
        with obs_trace.span("mesh.bucket", bucket=oi, lane=lane.index,
                            method=method) as sp:
            out = self._run_bucket_traced(lane, oi, key, method,
                                          maxiter, **kw)
            q = self.fit_quality.get(repr(key))
            if q:
                sp.set(**q)
            return out

    def _run_bucket_traced(self, lane, oi, key, method, maxiter, **kw):
        t0 = self.clock()
        fault = faultinject.fire("straggler_delay", bucket=oi)
        if fault and int(fault.get("lane", lane.index)) == lane.index:
            delay = float(fault.get("delay_s", 0.0))
            self._sleep(delay)
            lane.health.note_flush(delay)
        fault = faultinject.fire("device_loss", bucket=oi)
        if fault and int(fault.get("lane", lane.index)) == lane.index:
            raise DeviceLost(
                f"injected device loss on lane {lane.index} "
                f"(device {lane.device}, bucket {oi})")
        import jax

        batch = self._batch_for(oi, key, lane)
        use_gls = self._use_gls(batch, method)
        bkw = self._split_kw(use_gls, kw)
        fit = batch.gls_fit if use_gls else batch.wls_fit

        def pull():
            with jax.default_device(lane.device):
                x, chi2, cov = fit(maxiter=maxiter, **bkw)
            return np.asarray(x), np.asarray(chi2), np.asarray(cov)

        x, chi2, cov = self._watched(
            pull, lane, what=f"bucket {oi} fit on lane {lane.index}")
        idxs = self.group_indices[key]
        self.diverged.extend(idxs[j] for j in batch.diverged)
        if batch.quality:
            # per-segment probes were already extracted from the one
            # packed pull above — no extra device round-trip
            self.fit_quality[repr(key)] = batch.quality
        lane.health.note_flush(self.clock() - t0)
        lane.health.note_request("ok")
        lane.breaker.record_success(lane.key)
        lane.completed.append(oi)
        return x, chi2, cov

    def _lane_failed(self, lane, exc, completed):
        """Bookkeeping for a device-level lane failure: DeviceLost
        quarantines immediately (a lost chip stays lost); a
        CollectiveTimeout is a breaker strike that quarantines once
        the threshold trips. Either way the dead lane's pending
        buckets are re-sharded."""
        lane.health.note_request("error")
        if isinstance(exc, DeviceLost):
            lane.quarantine()
        else:
            tripped = lane.breaker.record_failure(lane.key)
            lane.health.note_breakers(lane.breaker.open_count(), tripped)
            if tripped:
                lane.lost = True
        n_before = len(self.reassignments)
        if not lane.alive():
            self._steal_from(lane, completed)
            # lane census into the metrics registry: the SLO burn-rate
            # monitor and Prometheus scrapes watch lane losses by name
            from ..obs import metricsreg

            metricsreg.REGISTRY.counter("mesh.lanes_lost").inc()
            metricsreg.REGISTRY.gauge("mesh.alive_lanes").set(
                sum(1 for ln in self.lanes if ln.alive()))
            # post-mortem artifact: which lane died, which fault point
            # killed it, and where its pending buckets went
            _flight.dump(
                "device_lost" if isinstance(exc, DeviceLost)
                else "collective_timeout",
                source="fleetmesh", lane=lane.index,
                fault_point=("device_loss" if isinstance(exc, DeviceLost)
                             else "collective_timeout"),
                error=str(exc),
                resharded=[list(r)
                           for r in self.reassignments[n_before:]],
                trace=obs_trace.current_trace_id())

    def _fit_bucket_isolated(self, lane, oi, key, idxs, method, maxiter,
                             depth, **kw):
        """Bisection fallback for a bucket that fails on a HEALTHY
        lane: split the bucket's pulsars until the pathological ones
        are singletons, quarantine those (NaN results), fit the rest —
        the fleet twin of the serve engine's _execute bisect. Returns
        {pulsar_index: (x, chi2, cov)} rows."""
        import jax

        from .pta import PTABatch

        sub_kw = dict(self.build_kwargs.get(key, {}))
        if "plan" in sub_kw:
            # a subset cannot reuse the packed plan; pad singleton
            # rows to the plan width so shapes stay bucketed
            sub_kw = {"pad_toas": sub_kw["plan"].width}
        try:
            with jax.default_device(lane.device):
                batch = PTABatch([self.models[i] for i in idxs],
                                 [self.toas_list[i] for i in idxs],
                                 **sub_kw)
            use_gls = self._use_gls(batch, method)
            bkw = self._split_kw(use_gls, kw)
            fit = batch.gls_fit if use_gls else batch.wls_fit

            def pull():
                with jax.default_device(lane.device):
                    x, chi2, cov = fit(maxiter=maxiter, **bkw)
                return (np.asarray(x), np.asarray(chi2),
                        np.asarray(cov))

            x, chi2, cov = self._watched(
                pull, lane,
                what=f"bucket {oi} bisect fit on lane {lane.index}")
        except (DeviceLost, CollectiveTimeout):
            raise  # device-level: the resilient driver handles it
        except Exception:
            if len(idxs) == 1 or depth >= self.bisect_depth:
                self.quarantined.extend(idxs)
                return {i: None for i in idxs}
            mid = len(idxs) // 2
            out = self._fit_bucket_isolated(
                lane, oi, key, idxs[:mid], method, maxiter,
                depth + 1, **kw)
            out.update(self._fit_bucket_isolated(
                lane, oi, key, idxs[mid:], method, maxiter,
                depth + 1, **kw))
            return out
        self.diverged.extend(idxs[j] for j in batch.diverged)
        return {i: (x[j], chi2[j], cov[j]) for j, i in enumerate(idxs)}

    def _fit_bucket_resilient(self, oi, key, method, maxiter,
                              completed, **kw):
        """Drive one bucket to completion through lane failures:
        device-level errors quarantine/strike the lane and retry on a
        survivor (work stealing); a bucket that then fails on a
        healthy lane is bisected. Bounded by the total breaker budget
        so an unrecoverable fleet raises instead of spinning."""
        max_attempts = len(self.lanes) * max(
            2, self.lanes[0].breaker.threshold)
        last = None
        for _ in range(max_attempts):
            lane = self._lane_for(key, completed)
            if lane is None:
                raise last or DeviceLost(
                    f"no surviving lanes for bucket {oi} "
                    f"({len(self.lanes)} quarantined)")
            try:
                return self._run_bucket(lane, oi, key, method,
                                        maxiter, **kw)
            except (DeviceLost, CollectiveTimeout) as e:
                last = e
                self._lane_failed(lane, e, completed)
                continue
            except FaultInjected as e:
                if e.retryable:
                    last = e
                    lane.health.note_request("error")
                    continue
                # persistent bucket-level fault on a healthy lane:
                # isolate the pathological pulsars
                idxs = self.group_indices[key]
                rows = self._fit_bucket_isolated(
                    lane, oi, key, list(idxs), method, maxiter, 0,
                    **kw)
                lane.completed.append(oi)
                return self._assemble_rows(key, rows)
        raise last or RuntimeError(
            f"bucket {oi} failed after {max_attempts} attempts")

    def _assemble_rows(self, key, rows):
        """Stack per-pulsar bisect rows back into bucket-shaped
        (x, chi2, cov) arrays; quarantined pulsars carry NaNs."""
        idxs = self.group_indices[key]
        good = next((v for v in rows.values() if v is not None), None)
        k = (good[0].shape[-1] if good is not None
             else len(self.models[idxs[0]].free_params))
        x = np.full((len(idxs), k), np.nan)
        chi2 = np.full(len(idxs), np.nan)
        cov = np.full((len(idxs), k, k), np.nan)
        for j, i in enumerate(idxs):
            if rows.get(i) is not None:
                x[j], chi2[j], cov[j] = rows[i]
        return x, chi2, cov

    # -- checkpointed fleet fit -------------------------------------

    def _fleet_signature(self, method, maxiter):
        """CRC pinning a progress snapshot to THIS fleet + fit config;
        a foreign snapshot (different buckets, pulsar count, or fit
        settings) warns and restarts instead of mis-scattering rows."""
        src = repr((self.n, [repr(k) for k in self.bucket_order],
                    {repr(k): list(v)
                     for k, v in self.group_indices.items()},
                    str(method), int(maxiter)))
        return zlib.crc32(src.encode())

    def fit(self, method="auto", maxiter=3, checkpoint_dir=None,
            tag="fleetmesh", **kw):
        """Fit every bucket across the lanes; returns per-pulsar
        (xs, chi2s, covs) in original pulsar order like PTAFleet.fit.

        checkpoint_dir: persist per-bucket progress through
        FitCheckpointer (CRC + <tag>.prev rotation) after every
        completed bucket; an interrupted fit re-run with the same
        directory resumes from the last completed bucket and its
        final parameters are bit-identical to an uninterrupted run
        (completed buckets restore bitwise from the snapshot, the
        rest re-run the same programs in the same canonical order).
        """
        with obs_trace.span("mesh.fit", n_psr=self.n,
                            n_buckets=len(self.bucket_order),
                            n_lanes=len(self.lanes), method=method):
            return self._fit_traced(method, maxiter, checkpoint_dir,
                                    tag, **kw)

    def _fit_traced(self, method, maxiter, checkpoint_dir, tag, **kw):
        xs = [None] * self.n
        chi2s = np.zeros(self.n)
        covs = [None] * self.n
        self.diverged = []
        self.quarantined = []
        self.fit_quality = {}
        ckpt = None
        state = {}
        completed = {}
        sig = self._fleet_signature(method, maxiter)
        if checkpoint_dir is not None:
            from ..checkpoint import FitCheckpointer

            ckpt = FitCheckpointer(checkpoint_dir)
            saved = ckpt.restore(tag)
            if saved is not None:
                if int(np.asarray(saved.get("sig", -1))) != sig:
                    warnings.warn(
                        f"fleet checkpoint {tag!r} was taken for a "
                        "different fleet/fit configuration; "
                        "restarting from scratch")
                else:
                    for oi in np.asarray(saved.get("done", []),
                                         dtype=int):
                        oi = int(oi)
                        completed[self.bucket_order[oi]] = oi
                        state[f"b{oi}_x"] = saved[f"b{oi}_x"]
                        state[f"b{oi}_chi2"] = saved[f"b{oi}_chi2"]
                        state[f"b{oi}_cov"] = saved[f"b{oi}_cov"]
                    # a resume IS a recovery event: leave the ring's
                    # recent history in a dump before it scrolls away
                    _flight.dump(
                        "checkpoint_restart", source="fleetmesh",
                        tag=tag,
                        restored_buckets=sorted(completed.values()),
                        trace=obs_trace.current_trace_id())
        for oi, key in enumerate(self.bucket_order):
            idxs = self.group_indices[key]
            if key in completed:
                self._scatter(xs, chi2s, covs, idxs,
                              state[f"b{oi}_x"], state[f"b{oi}_chi2"],
                              state[f"b{oi}_cov"])
                continue
            x, chi2, cov = self._fit_bucket_resilient(
                oi, key, method, maxiter, completed, **kw)
            self._scatter(xs, chi2s, covs, idxs, x, chi2, cov)
            completed[key] = oi
            if ckpt is not None:
                state[f"b{oi}_x"] = np.asarray(x)
                state[f"b{oi}_chi2"] = np.asarray(chi2)
                state[f"b{oi}_cov"] = np.asarray(cov)
                ckpt.save(tag, {
                    "sig": sig,
                    "done": np.asarray(sorted(completed.values()),
                                       dtype=np.int64), **state})
        return xs, chi2s, covs

    @staticmethod
    def _scatter(xs, chi2s, covs, idxs, x, chi2, cov):
        x, chi2, cov = np.asarray(x), np.asarray(chi2), np.asarray(cov)
        for j, i in enumerate(idxs):
            xs[i] = x[j]
            chi2s[i] = chi2[j]
            covs[i] = cov[j]

    # -- export ------------------------------------------------------

    def snapshot(self):
        """JSON-safe fleet state: per-lane health/breaker blocks plus
        the work-stealing ledger — the multi-device analog of
        ServeEngine.snapshot()."""
        return {
            "n_lanes": len(self.lanes),
            "alive_lanes": sum(1 for ln in self.lanes if ln.alive()),
            "lost_lanes": [ln.index for ln in self.lanes if ln.lost],
            "stolen_buckets": int(self.stolen),
            "reassignments": [list(r) for r in self.reassignments],
            "quarantined_pulsars": list(self.quarantined),
            "fit_quality": {k: dict(v)
                            for k, v in self.fit_quality.items()},
            "lanes": [ln.snapshot() for ln in self.lanes],
        }
