from .pta import PTABatch, PTAFleet, stack_prepared  # noqa: F401
from .mesh import make_mesh, make_mesh2d, shard_batch  # noqa: F401
from .distributed import (initialize_distributed,  # noqa: F401
                          process_pulsar_slice, global_pulsar_mesh)
