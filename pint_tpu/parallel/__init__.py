from .pta import PTABatch, PTAFleet, stack_prepared  # noqa: F401
from .mesh import make_mesh, shard_batch  # noqa: F401
