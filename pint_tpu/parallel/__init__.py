from .pta import PTABatch, PTAFleet, stack_prepared  # noqa: F401
from .mesh import make_mesh, make_mesh2d, shard_batch  # noqa: F401
