from .pta import (PTABatch, PTAFleet, fleet_aot_compile,  # noqa: F401
                  fleet_pipeline_metrics, stack_prepared)
from .shapeplan import (PlanBucket, PlanRow, Segment,  # noqa: F401
                        ShapePlan, plan_shapes, pow2_width)
from .mesh import (make_mesh, make_mesh2d, shard_batch,  # noqa: F401
                   lane_meshes)
from .distributed import (initialize_distributed,  # noqa: F401
                          process_pulsar_slice, global_pulsar_mesh)
from .fleetmesh import (FleetMesh, DeviceLane, DeviceLost,  # noqa: F401
                        CollectiveTimeout, run_watched)
