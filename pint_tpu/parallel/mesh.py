"""Device-mesh helpers for PTA-scale fits.

The reference has no distributed execution (SURVEY.md section 2.2);
this layer is the TPU-native design: a (pulsar, toa) mesh where
per-pulsar fits ride the 'pulsar' axis (pure data parallelism, zero
collectives inside a fit) and the TOA axis of very long single-pulsar
datasets can be sharded with psum-reductions for the few cross-TOA
couplings (weighted mean, normal-equation accumulation). Collectives
ride ICI within a slice; DCN multi-slice is out of scope for one host.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_pulsar_shards=None, devices=None) -> Mesh:
    """1-D 'pulsar' mesh over available devices."""
    devices = devices if devices is not None else jax.devices()
    n = n_pulsar_shards or len(devices)
    return Mesh(np.array(devices[:n]), axis_names=("pulsar",))


def lane_meshes(devices=None):
    """One single-device 1-D 'pulsar' Mesh PER device, in device
    order — the per-device failure domains fleetmesh.DeviceLane wraps.
    A bucket fit placed on one of these meshes touches exactly one
    chip, so losing that chip poisons one lane's buckets and nothing
    else (contrast make_mesh, where every bucket spans all devices and
    one lost chip kills every in-flight program)."""
    devices = devices if devices is not None else jax.devices()
    return [Mesh(np.array([d]), axis_names=("pulsar",)) for d in devices]


def make_mesh2d(n_pulsar_shards, n_toa_shards, devices=None) -> Mesh:
    """2-D ('pulsar', 'toa') mesh: pulsar data parallelism combined
    with TOA-axis (sequence) sharding inside each pulsar shard. The
    per-TOA physics is pointwise, so GSPMD only inserts collectives
    for the few cross-TOA reductions (mean subtraction, normal
    equations) — these ride ICI (SURVEY.md section 2.2)."""
    devices = devices if devices is not None else jax.devices()
    n = n_pulsar_shards * n_toa_shards
    if len(devices) < n:
        raise ValueError(f"mesh {n_pulsar_shards}x{n_toa_shards} needs "
                         f"{n} devices, have {len(devices)}")
    grid = np.array(devices[:n]).reshape(n_pulsar_shards, n_toa_shards)
    return Mesh(grid, axis_names=("pulsar", "toa"))


def shard_batch(tree, mesh: Mesh, n_toa=None):
    """Place a stacked per-pulsar pytree with the pulsar axis sharded.

    On a 2-D ('pulsar', 'toa') mesh, leaves whose SECOND axis is the
    (padded) TOA axis — length ``n_toa`` divisible by the toa mesh
    size — are sharded along it too; everything else stays replicated
    across the toa axis (correct, just not memory-split)."""
    toa_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("toa")

    def put(x):
        spec = P("pulsar")
        if (toa_size and n_toa and getattr(x, "ndim", 0) >= 2
                and x.shape[1] == n_toa and n_toa % toa_size == 0):
            spec = P("pulsar", "toa")
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, tree)


def replicate(tree, mesh: Mesh):
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
