"""Device-mesh helpers for PTA-scale fits.

The reference has no distributed execution (SURVEY.md section 2.2);
this layer is the TPU-native design: a (pulsar, toa) mesh where
per-pulsar fits ride the 'pulsar' axis (pure data parallelism, zero
collectives inside a fit) and the TOA axis of very long single-pulsar
datasets can be sharded with psum-reductions for the few cross-TOA
couplings (weighted mean, normal-equation accumulation). Collectives
ride ICI within a slice; DCN multi-slice is out of scope for one host.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_pulsar_shards=None, devices=None) -> Mesh:
    """1-D 'pulsar' mesh over available devices."""
    devices = devices if devices is not None else jax.devices()
    n = n_pulsar_shards or len(devices)
    return Mesh(np.array(devices[:n]), axis_names=("pulsar",))


def shard_batch(tree, mesh: Mesh):
    """Place a stacked per-pulsar pytree with the pulsar axis sharded."""
    sharding = NamedSharding(mesh, P("pulsar"))

    def put(x):
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(put, tree)


def replicate(tree, mesh: Mesh):
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
