"""TOA-axis sharding: the sequence-parallel analog for pulsar timing.

SURVEY.md section 5 ("long-context"): the reference's long axis is the
TOA/photon axis (up to ~1e7 photons) processed in one address space.
Here the axis is sharded across the device mesh with jax.shard_map —
delays/phases are pointwise per TOA (zero communication); the only
cross-TOA couplings are the weighted mean (one psum) and
normal-equation accumulation M^T W M (psum of per-shard partials).
Ring attention/Ulysses-style machinery is explicitly unnecessary —
there is no all-to-all coupling along the axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def sharded_residuals(template_model, static, mesh: Mesh, params, batch, prep,
                      axis="toa"):
    """Residual seconds with the TOA axis sharded over ``mesh``.

    params are replicated; batch/prep arrays are sharded on their TOA
    dimension. Returns a sharded residual array.
    """
    from .pta import pure_phase_fn, pure_sigma_fn

    phase = pure_phase_fn(template_model, static)
    sigma_fn = pure_sigma_fn(template_model, static)

    def local(params, batch, prep):
        ph = phase(params, batch, prep)
        frac = ph - jnp.floor(ph + 0.5)
        sig = sigma_fn(params, batch, prep)
        w = 1.0 / jnp.square(sig)
        # weighted mean needs the global sums: one psum each
        sw = jax.lax.psum(jnp.sum(frac * w), axis)
        tw = jax.lax.psum(jnp.sum(w), axis)
        frac = frac - sw / tw
        return frac / params["F"][0]

    def spec_for(x):
        # shard the leading/TOA dimension where present
        if getattr(x, "ndim", 0) == 0:
            return P()
        return P(axis) if x.shape[0] != 3 else P()

    batch_specs = jax.tree_util.tree_map(
        lambda x: P(axis) if getattr(x, "ndim", 0) >= 1 and x.shape[0] > 3 else P(),
        batch)
    prep_specs = jax.tree_util.tree_map(
        lambda x: (P(axis) if getattr(x, "ndim", 0) >= 1
                   and x.shape[-1] == batch.tdb_sec.shape[0] else P()), prep)
    # masks (k, n_toa) shard on dim 1
    prep_specs = {
        k: (P(None, axis) if getattr(prep[k], "ndim", 0) == 2
            and prep[k].shape[1] == batch.tdb_sec.shape[0] else v)
        for k, v in prep_specs.items()
    }
    fn = jax.shard_map(
        local, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  batch_specs, prep_specs),
        out_specs=P(axis))
    return fn(params, batch, prep)


def sharded_chi2(template_model, static, mesh, params, batch, prep, axis="toa"):
    """Whitened chi2 with TOA-sharded reduction (psum)."""
    r = sharded_residuals(template_model, static, mesh, params, batch, prep, axis)
    from .pta import pure_sigma_fn

    sig = pure_sigma_fn(template_model, static)(params, batch, prep) * 1e-6
    return jnp.sum(jnp.square(r / sig))
