"""TOA-axis sharding: the sequence-parallel analog for pulsar timing.

SURVEY.md section 5 ("long-context"): the reference's long axis is the
TOA/photon axis (up to ~1e7 photons) processed in one address space.
Here the axis is sharded across the device mesh with jax.shard_map —
delays/phases are pointwise per TOA (zero communication); the only
cross-TOA couplings are the weighted mean (one psum) and
normal-equation accumulation M^T W M (psum of per-shard partials).
Ring attention/Ulysses-style machinery is explicitly unnecessary —
there is no all-to-all coupling along the axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def sharded_residuals(template_model, static, mesh: Mesh, params, batch, prep,
                      axis="toa"):
    """Residual seconds with the TOA axis sharded over ``mesh``.

    params are replicated; batch/prep arrays are sharded on their TOA
    dimension. Returns a sharded residual array.
    """
    from .pta import pure_phase_fn, pure_sigma_fn

    phase = pure_phase_fn(template_model, static)
    sigma_fn = pure_sigma_fn(template_model, static)

    def local(params, batch, prep):
        ph = phase(params, batch, prep)
        frac = ph - jnp.floor(ph + 0.5)
        sig = sigma_fn(params, batch, prep)
        w = 1.0 / jnp.square(sig)
        # weighted mean needs the global sums: one psum each
        sw = jax.lax.psum(jnp.sum(frac * w), axis)
        tw = jax.lax.psum(jnp.sum(w), axis)
        frac = frac - sw / tw
        return frac / params["F"][0]

    n_toa = batch.tdb_sec.shape[0]

    def data_spec(x):
        """Shard whichever dimension carries the TOA axis; replicate
        everything else. Handles (n,), (n, 3), (k, n) masks/bases, and
        (n_planets, n, 3) planet tensors by shape, not position."""
        nd = getattr(x, "ndim", 0)
        if nd == 0:
            return P()
        dims = [None] * nd
        for i, s in enumerate(x.shape):
            if s == n_toa:
                dims[i] = axis
                break
        return P(*dims)

    batch_specs = jax.tree_util.tree_map(data_spec, batch)
    prep_specs = jax.tree_util.tree_map(data_spec, prep)
    # inputs may be committed to a single device by the staged batched
    # transfer (PreparedTiming); re-place them onto the mesh sharding
    # so shard_map accepts them
    from jax.sharding import NamedSharding

    def place(tree, specs):
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s))
            if isinstance(x, jax.Array) else x, tree, specs)

    params = place(params, jax.tree_util.tree_map(lambda _: P(), params))
    batch = place(batch, batch_specs)
    prep = place(prep, prep_specs)
    fn = jax.shard_map(
        local, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  batch_specs, prep_specs),
        out_specs=P(axis))
    return fn(params, batch, prep)


def sharded_chi2(template_model, static, mesh, params, batch, prep, axis="toa"):
    """Whitened chi2 with TOA-sharded reduction (psum)."""
    import numpy as np

    r = sharded_residuals(template_model, static, mesh, params, batch, prep, axis)
    from .pta import pure_sigma_fn

    # sigma is evaluated on the original (single-device) inputs; pull
    # both to host for the scalar reduction rather than mixing array
    # placements inside one jitted expression
    sig = np.asarray(pure_sigma_fn(template_model, static)(params, batch, prep)) * 1e-6
    return float(np.sum(np.square(np.asarray(r) / sig)))
