"""TOA-axis sharding: the sequence-parallel analog for pulsar timing.

SURVEY.md section 5 ("long-context"): the reference's long axis is the
TOA/photon axis (up to ~1e7 photons) processed in one address space.
Here the axis is sharded across the device mesh with jax.shard_map —
delays/phases are pointwise per TOA (zero communication); the only
cross-TOA couplings are the weighted mean (one psum) and
normal-equation accumulation M^T W M (psum of per-shard partials).
Ring attention/Ulysses-style machinery is explicitly unnecessary —
there is no all-to-all coupling along the axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # jax 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map


def _data_spec(x, n_rows, axis):
    """PartitionSpec sharding whichever dimension carries the TOA axis
    (detected by length == n_rows); everything else replicated. Single
    home for the by-shape heuristic used by every sharded entry point."""
    nd = getattr(x, "ndim", 0)
    if nd == 0:
        return P()
    dims = [None] * nd
    for i, s in enumerate(x.shape):
        if s == n_rows:
            dims[i] = axis
            break
    return P(*dims)


def _place(mesh, tree, specs):
    """Re-place committed single-device arrays onto the mesh shardings
    so shard_map accepts them."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s))
        if isinstance(x, jax.Array) else x, tree, specs)


def _watched_get(arrays, watchdog_s, what):
    """jax.device_get bounded by the collective watchdog (None: plain
    blocking get). The get is where a hung psum/all_gather actually
    wedges the caller — dispatch is async — so this is the one site
    that needs the bound."""
    if watchdog_s is None:
        return jax.device_get(arrays)
    from .fleetmesh import run_watched

    return run_watched(lambda: jax.device_get(arrays), watchdog_s,
                       what=what)


def sharded_residuals(template_model, static, mesh: Mesh, params, batch, prep,
                      axis="toa"):
    """Residual seconds with the TOA axis sharded over ``mesh``.

    params are replicated; batch/prep arrays are sharded on their TOA
    dimension. Returns a sharded residual array.
    """
    from .pta import pure_phase_fn, pure_sigma_fn

    phase = pure_phase_fn(template_model, static)
    sigma_fn = pure_sigma_fn(template_model, static)

    def local(params, batch, prep):
        ph = phase(params, batch, prep)
        frac = ph - jnp.floor(ph + 0.5)
        sig = sigma_fn(params, batch, prep)
        w = 1.0 / jnp.square(sig)
        # weighted mean needs the global sums: one psum each
        sw = jax.lax.psum(jnp.sum(frac * w), axis)
        tw = jax.lax.psum(jnp.sum(w), axis)
        frac = frac - sw / tw
        return frac / params["F"][0]

    n_toa = batch.tdb_sec.shape[0]
    batch_specs = jax.tree_util.tree_map(
        lambda a: _data_spec(a, n_toa, axis), batch)
    prep_specs = jax.tree_util.tree_map(
        lambda a: _data_spec(a, n_toa, axis), prep)
    params = _place(mesh, params,
                    jax.tree_util.tree_map(lambda _: P(), params))
    batch = _place(mesh, batch, batch_specs)
    prep = _place(mesh, prep, prep_specs)
    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  batch_specs, prep_specs),
        out_specs=P(axis))
    return fn(params, batch, prep)


def sharded_chi2(template_model, static, mesh, params, batch, prep, axis="toa"):
    """Whitened chi2 with TOA-sharded reduction (psum)."""
    import numpy as np

    r = sharded_residuals(template_model, static, mesh, params, batch, prep, axis)
    from .pta import pure_sigma_fn

    # sigma is evaluated on the original (single-device) inputs; pull
    # both to host for the scalar reduction rather than mixing array
    # placements inside one jitted expression
    sig = np.asarray(pure_sigma_fn(template_model, static)(params, batch, prep)) * 1e-6
    return float(np.sum(np.square(np.asarray(r) / sig)))


def sharded_gls_fit(model, toas, mesh: Mesh, maxiter=2, threshold=1e-12,
                    axis="toa", precision="f64", compile_timings=None,
                    watchdog_s=None):
    """Single-pulsar GLS fit with the TOA axis sharded over ``mesh`` —
    the sequence-parallel path for a pulsar whose TOA/photon count
    outgrows one chip (SURVEY section 5 "long-context").

    ``compile_timings``: optional dict; when given, every sharded step
    program is AOT-compiled through fitter.aot_lower /
    aot_backend_compile and the per-program
    {trace_s, backend_compile_s} splits are recorded into it — the
    same instrumentation surface PTABatch.aot_compile exposes, so
    bench/profile tooling can attribute sharded-path compile cost.

    Per shard: local residuals + local jacfwd design block + local
    noise-basis rows; cross-shard coupling is the weighted mean (psum),
    the exponent-safe column norms (pmax + psum), and the
    normal-equation partials A = psum(Mn_loc^T Mn_loc),
    b = psum(Mn_loc^T z_loc) — the tiny (k x k) prior-folded eigh solve
    then runs replicated. ECORR epochs may straddle shard boundaries
    here: the epochs enter as explicit basis COLUMNS (Woodbury), whose
    psum accumulation is exact regardless of row placement — only the
    batched path's analytic Sherman-Morrison marginalization needs
    epoch locality.

    ``precision="mixed"`` forms each shard's Gram block in f32 (the
    MXU-native path) and recovers f64 accuracy by iterative refinement
    whose exact-residual matvec is two O(n_local k) products plus one
    psum per step — the distributed twin of PTABatch's mixed mode,
    with the same non-contraction fallback to f64.

    ``watchdog_s``: bound the blocking device pull of the fit results
    with ``fleetmesh.run_watched`` — THIS is the call a hung psum /
    all_gather wedges (dispatch is async; the hang surfaces at the
    pull), so with a bound it raises a catchable
    ``fleetmesh.CollectiveTimeout`` instead of blocking forever.

    Returns (x, whitened_chi2, cov) as numpy, matching
    fitter.GLSFitter on the same data (pinned by test_parallel.py).
    """
    import numpy as np

    from ..fitter import (_reject_free_dmjump, check_precision,
                          cov_from_normalized, gls_eigh_refine,
                          gls_eigh_solve)

    check_precision(precision)
    from .pta import _pad_single, pure_phase_fn, pure_sigma_fn

    _reject_free_dmjump(model)
    n_dev = mesh.devices.size
    prepared = model.prepare(toas)
    n = prepared.batch.n_toas
    n_pad = ((n + n_dev - 1) // n_dev) * n_dev
    batch, arrays, static = _pad_single(prepared, n_pad)
    phase = pure_phase_fn(model, static)
    sigma_fn = pure_sigma_fn(model, static)
    noise_comps = [c for c in model.components.values()
                   if getattr(c, "basis_weight", None) is not None]
    free = prepared.free_param_map()
    nparam = len(free) + 1  # + offset column
    x0 = jnp.asarray(prepared.vector_from_params())
    # hoist guard, analogous to PTABatch._build_gls: with every noise /
    # sigma-scaling parameter frozen, the whitened basis columns, their
    # psum'd Gram (the bulk of the normal-equation FLOPs), the norms,
    # and sigma itself are constants of the fit — precompute them in
    # ONE sharded pass and rebuild only the parameter block per
    # Gauss-Newton iteration. INTENTIONAL divergence from the batched
    # path: there hoist composes with precision="mixed"; here mixed
    # keeps the unhoisted step (composing them needs the refinement
    # matvec factored across shards — deferred until it can be
    # validated on real multi-chip hardware)
    free_names = {n for n, _, _ in free}
    noise_param_names = set()
    for c in model.components.values():
        if (getattr(c, "basis_weight", None) is not None
                or getattr(c, "scale_sigma", None) is not None):
            noise_param_names.update(c.params)
    hoist = (precision == "f64" and bool(noise_comps)
             and not (free_names & noise_param_names))

    batch_specs = jax.tree_util.tree_map(
        lambda a: _data_spec(a, n_pad, axis), batch)
    prep_specs = jax.tree_util.tree_map(
        lambda a: _data_spec(a, n_pad, axis), arrays)
    batch = _place(mesh, batch, batch_specs)
    arrays = _place(mesh, arrays, prep_specs)

    def _global_colnorms(Mw):
        # exponent-safe global column norms (see fitter.column_norms):
        # peak-scale via pmax, then a psum'd sum of squares
        amax = jax.lax.pmax(jnp.max(jnp.abs(Mw), axis=0), axis)
        amax = jnp.where(amax == 0, 1.0, amax)
        ss = jax.lax.psum(jnp.sum(jnp.square(Mw / amax), axis=0), axis)
        return amax * jnp.where(ss == 0, 1.0, jnp.sqrt(ss))

    def local(x, batch, prep):
        def resid_of(xv):
            p = prepared.params_with_vector(xv)
            ph = phase(p, batch, prep)
            frac = ph - jnp.floor(ph + 0.5)
            sig = sigma_fn(p, batch, prep) * 1e-6
            w = 1.0 / jnp.square(sig)
            sw = jax.lax.psum(jnp.sum(frac * w), axis)
            tw = jax.lax.psum(jnp.sum(w), axis)
            return (frac - sw / tw) / p["F"][0], sig

        r, sig = resid_of(x)
        M = jax.jacfwd(lambda xv: resid_of(xv)[0])(x)
        M = jnp.concatenate([jnp.ones((M.shape[0], 1)), M], axis=1)
        p = prepared.params_with_vector(x)
        full = {**prep, **static}
        sqrt_phi_inv = jnp.zeros(nparam)
        for c in noise_comps:
            B, w_us2 = c.basis_weight(p, full)
            if B.shape[1]:
                M = jnp.concatenate([M, B], axis=1)
                spi = jnp.where(
                    w_us2 > 0,
                    1.0 / (jnp.sqrt(jnp.where(w_us2 > 0, w_us2, 1.0))
                           * 1e-6), 0.0)
                sqrt_phi_inv = jnp.concatenate([sqrt_phi_inv, spi])
        Mw = M / sig[:, None]
        norm = jnp.hypot(_global_colnorms(Mw), sqrt_phi_inv)
        Mn = Mw / norm
        q = sqrt_phi_inv / norm
        z = r / sig
        b = jax.lax.psum(Mn.T @ z, axis)
        rw2 = jax.lax.psum(jnp.sum(jnp.square(z)), axis)
        if precision == "mixed":
            # per-shard Gram in f32 (the compute win), accumulated in
            # f64 so the psum adds no further rounding
            M32 = Mn.astype(jnp.float32)
            A = (jax.lax.psum((M32.T @ M32).astype(jnp.float64), axis)
                 + jnp.diag(q * q))

            def matvec(v):
                return jax.lax.psum(Mn.T @ (Mn @ v), axis) + (q * q) * v

            dxn, covn, relres = gls_eigh_refine(A, b, matvec, threshold)
        else:
            A = jax.lax.psum(Mn.T @ Mn, axis) + jnp.diag(q * q)
            dxn, covn = gls_eigh_solve(A, b, threshold)
            relres = jnp.zeros(())
        chi2 = rw2 - b @ dxn
        dx = dxn / norm
        return (x - dx[1:nparam], chi2, covn[1:nparam, 1:nparam],
                norm[1:nparam], relres)

    def pre_local(batch, prep):
        """One sharded pass for the x-independent pieces (hoist)."""
        p = prepared.params_with_vector(x0)
        sig = sigma_fn(p, batch, prep) * 1e-6
        full = {**prep, **static}
        from ..fitter import stack_noise_bases

        Bs, ws = [], []
        for c in noise_comps:
            Bc, w_us2 = c.basis_weight(p, full)
            if Bc.shape[1]:
                Bs.append(Bc)
                ws.append(w_us2)
        bw = ((jnp.concatenate(Bs, axis=1), jnp.concatenate(ws))
              if Bs else None)
        # single home of the us^2 -> prior-sqrt convention
        B, spi, _ = stack_noise_bases(
            jnp.zeros((sig.shape[0], 0)), bw or (None, None))
        normB = jnp.hypot(_global_colnorms(B / sig[:, None]), spi)
        Bn = (B / sig[:, None]) / normB
        qB = spi / normB
        FtF = jax.lax.psum(Bn.T @ Bn, axis)
        return Bn, sig, FtF, normB, qB

    def local_hoisted(x, batch, prep, Bn, sig, FtF, normB, qB):
        # identical math to ``local`` with the basis block constant
        def resid_of(xv):
            p = prepared.params_with_vector(xv)
            ph = phase(p, batch, prep)
            frac = ph - jnp.floor(ph + 0.5)
            w = 1.0 / jnp.square(sig)
            sw = jax.lax.psum(jnp.sum(frac * w), axis)
            tw = jax.lax.psum(jnp.sum(w), axis)
            return (frac - sw / tw) / p["F"][0]

        r = resid_of(x)
        M = jax.jacfwd(resid_of)(x)
        M = jnp.concatenate([jnp.ones((M.shape[0], 1)), M], axis=1)
        Mw = M / sig[:, None]
        normM = _global_colnorms(Mw)
        Mn_p = Mw / normM
        z = r / sig
        b = jnp.concatenate([jax.lax.psum(Mn_p.T @ z, axis),
                             jax.lax.psum(Bn.T @ z, axis)])
        rw2 = jax.lax.psum(jnp.sum(jnp.square(z)), axis)
        App = jax.lax.psum(Mn_p.T @ Mn_p, axis)
        ApB = jax.lax.psum(Mn_p.T @ Bn, axis)
        q = jnp.concatenate([jnp.zeros(nparam), qB])
        A = jnp.block([[App, ApB], [ApB.T, FtF]]) + jnp.diag(q * q)
        dxn, covn = gls_eigh_solve(A, b, threshold)
        chi2 = rw2 - b @ dxn
        norm = jnp.concatenate([normM, normB])
        dx = dxn / norm
        return (x - dx[1:nparam], chi2, covn[1:nparam, 1:nparam],
                norm[1:nparam], jnp.zeros(()))

    def _maybe_aot(name, fn, *args):
        # AOT-compile one sharded program when the caller wants the
        # trace/XLA timing split; otherwise leave the lazy jit
        if compile_timings is None:
            return fn
        from ..fitter import aot_backend_compile, aot_lower

        low = aot_lower(fn, *args)
        info = aot_backend_compile(low["lowered"])
        compile_timings[name] = {
            "trace_s": low["trace_s"],
            "backend_compile_s": info["backend_compile_s"]}
        return info["compiled"]

    step = jax.jit(_shard_map(
        local, mesh=mesh,
        in_specs=(P(), batch_specs, prep_specs),
        out_specs=(P(), P(), P(), P(), P())))

    # x must live replicated on the SAME mesh as the sharded data
    x = jax.device_put(x0, NamedSharding(mesh, P()))
    relres_hist = []
    if hoist:
        pre_step = jax.jit(_shard_map(
            pre_local, mesh=mesh, in_specs=(batch_specs, prep_specs),
            out_specs=(P(axis), P(axis), P(), P(), P())))
        pre_step = _maybe_aot("pre_step", pre_step, batch, arrays)
        pre = pre_step(batch, arrays)
        step_h = jax.jit(_shard_map(
            local_hoisted, mesh=mesh,
            in_specs=(P(), batch_specs, prep_specs,
                      P(axis), P(axis), P(), P(), P()),
            out_specs=(P(), P(), P(), P(), P())))
        step_h = _maybe_aot("step_h", step_h, x, batch, arrays, *pre)
        for _ in range(maxiter):
            x, chi2, covn, norm, relres = step_h(x, batch, arrays, *pre)
        x, chi2, covn, norm = _watched_get(
            (x, chi2, covn, norm), watchdog_s, "sharded_gls_fit hoisted")
        cov = cov_from_normalized(covn, norm)
        return x, float(chi2), cov
    step = _maybe_aot("step", step, x, batch, arrays)
    for _ in range(maxiter):
        x, chi2, covn, norm, relres = step(x, batch, arrays)
        # every iteration's residual is checked: an early
        # non-contraction corrupts x even when the final off-optimum
        # solve happens to converge (a Python max() would also swallow
        # a NaN — fitter.relres_failed is the nan-aware guard)
        relres_hist.append(float(relres))
    x, chi2, covn, norm = _watched_get(
        (x, chi2, covn, norm), watchdog_s, "sharded_gls_fit")
    from ..fitter import relres_failed

    # single-pulsar sharded path: no per-pulsar label exists here (the
    # caller owns the model identity) so the fitquality ledger hook
    # lives in the callers; the verdict still drives the f64 refit
    # pintlint: disable=quality-signal-dropped
    if precision == "mixed" and relres_failed(relres_hist):
        import warnings

        warnings.warn(
            f"mixed-precision sharded GLS refinement did not converge "
            f"(worst rel resid {np.max(relres_hist):.2e}); "
            "refitting in f64")
        return sharded_gls_fit(model, toas, mesh, maxiter=maxiter,
                               threshold=threshold, axis=axis,
                               precision="f64",
                               compile_timings=compile_timings)
    cov = cov_from_normalized(covn, norm)
    return x, float(chi2), cov
