"""pint_trace: the observability CLI (``python -m pint_tpu.obs``).

Subcommands:

- ``fleet``   — run a traced N-pulsar fleet refit and export the span
  timeline as Chrome trace-event JSON (open in ui.perfetto.dev). The
  default settings reproduce the ISSUE 7 acceptance artifact: a
  68-pulsar traced refit whose span tree covers host prep, pack,
  compile, and execute per bucket.
- ``convert`` — turn a flight-recorder dump (or a raw span-list JSON)
  into a Chrome trace-event file.
- ``prom``    — render a metrics snapshot JSON (or the dump's embedded
  metrics block) as Prometheus text exposition format.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_fleet(args):
    import numpy as np

    from .. import obs
    from ..parallel import PTAFleet
    from ..scripts.pint_serve_bench import build_serve_fleet

    # 9 structure x size combos -> per_combo ~ n_psr / 9
    per_combo = max(1, -(-args.n_psr // 9))
    models, toas_list = build_serve_fleet(
        sizes=tuple(args.sizes), per_combo=per_combo, seed=args.seed)
    models, toas_list = models[:args.n_psr], toas_list[:args.n_psr]
    # trace from construction on so the timeline covers the whole
    # cold path — host prep, pack, compile — not just the refit
    obs.enable(capacity=args.capacity,
               jax_annotations=args.jax_annotations)
    obs.reset()
    print(f"[pint_trace] fleet of {len(models)} pulsars; traced cold "
          "fit (host prep + pack + compile + execute) ...",
          file=sys.stderr)
    fleet = PTAFleet(models, toas_list, bucket_floor=args.bucket_floor,
                     pipeline=not args.no_pipeline)
    fleet.fit(method=args.method, maxiter=args.maxiter)
    print("[pint_trace] traced warm refit ...", file=sys.stderr)
    xs, chi2, meta = fleet.fit(method=args.method, maxiter=args.maxiter)
    obs.disable()

    spans = obs.spans()
    out = obs.write_chrome_trace(args.out, spans)
    phases = sorted({s["name"] for s in spans})
    print(json.dumps({
        "pulsars": len(models),
        "buckets": len(fleet.batches),
        "chi2_total": float(np.sum([np.sum(c) for c in chi2])),
        "spans": len(spans),
        "phases": phases,
        "trace_out": out,
    }, indent=1))
    return 0


def _cmd_convert(args):
    from . import export

    with open(args.dump) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "events" in doc:      # flight dump
        spans = export.flight_spans(doc)
    elif isinstance(doc, dict) and "traceEvents" in doc:
        print("input is already a Chrome trace", file=sys.stderr)
        return 1
    else:                                              # raw span list
        spans = doc
    out = export.write_chrome_trace(args.out, spans)
    print(json.dumps({"spans": len(spans), "trace_out": out}))
    return 0


def _cmd_prom(args):
    from . import metricsreg

    with open(args.snapshot) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "metrics" in doc:     # flight dump
        doc = doc["metrics"]
    sys.stdout.write(metricsreg.prometheus_text(doc))
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m pint_tpu.obs",
        description="pint_trace: traced fleet timelines, flight-dump "
                    "conversion, Prometheus rendering")
    sub = p.add_subparsers(dest="cmd", required=True)

    f = sub.add_parser("fleet", help="traced fleet refit -> Chrome "
                                     "trace JSON")
    f.add_argument("--n-psr", type=int, default=68)
    f.add_argument("--sizes", type=int, nargs="+",
                   default=[48, 96, 180])
    f.add_argument("--seed", type=int, default=0)
    f.add_argument("--method", default="gls",
                   choices=("wls", "gls"))
    f.add_argument("--maxiter", type=int, default=2)
    f.add_argument("--bucket-floor", type=int, default=64)
    f.add_argument("--no-pipeline", action="store_true",
                   help="sequential fit (fewer phases in the trace)")
    f.add_argument("--capacity", type=int, default=65536)
    f.add_argument("--jax-annotations", action="store_true",
                   help="also emit jax.profiler TraceAnnotations")
    f.add_argument("--out", default="pint_fleet_trace.json")
    f.set_defaults(fn=_cmd_fleet)

    c = sub.add_parser("convert", help="flight dump / span list -> "
                                       "Chrome trace JSON")
    c.add_argument("dump")
    c.add_argument("--out", default="pint_trace.json")
    c.set_defaults(fn=_cmd_convert)

    m = sub.add_parser("prom", help="metrics snapshot -> Prometheus "
                                    "text format")
    m.add_argument("snapshot")
    m.set_defaults(fn=_cmd_prom)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
