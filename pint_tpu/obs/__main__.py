"""pint_trace: the observability CLI (``python -m pint_tpu.obs``).

Subcommands:

- ``fleet``   — run a traced N-pulsar fleet refit and export the span
  timeline as Chrome trace-event JSON (open in ui.perfetto.dev). The
  default settings reproduce the ISSUE 7 acceptance artifact: a
  68-pulsar traced refit whose span tree covers host prep, pack,
  compile, and execute per bucket.
- ``convert`` — turn a flight-recorder dump (or a raw span-list JSON)
  into a Chrome trace-event file.
- ``prom``    — render a metrics snapshot JSON (or the dump's embedded
  metrics block) as Prometheus text exposition format.
- ``regress`` — the perf-observatory gate: check the latest
  BENCH_r0*.json round against the machine-readable budgets and the
  robust median+MAD regression tolerances (exit 1 on any violation —
  this is the CI hook, and bench.py runs the same check as its
  ``regress_*`` meta stage).
- ``slo``     — replay serve snapshot JSON files through the
  dual-window burn-rate monitor and report per-SLO burn / alert state
  (exit 1 when any SLO is alerting at the end of the replay).
- ``fitq``    — the numerics observatory: check a fit-quality ledger /
  engine snapshot JSON against the probe limits, or (with no file)
  run a probed fleet refit and report the live ledger (exit 1 on any
  probe violation).
- ``doctor``  — one CI entry point: regress + (optional) slo replay +
  (optional) fitq snapshot check; exit non-zero on ANY violation.
- ``tail``    — "why was this request slow" in one command: resolve a
  p99 tail-latency exemplar from a serve run (a ``--tail-out``
  artifact of pint_serve_bench, or a live mini serve stream when no
  file is given) to its request-lifecycle record — tenant, state
  timeline, queue-wait vs execute split, and the flush trace id.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_fleet(args):
    import numpy as np

    from .. import obs
    from ..parallel import PTAFleet
    from ..scripts.pint_serve_bench import build_serve_fleet

    # 9 structure x size combos -> per_combo ~ n_psr / 9
    per_combo = max(1, -(-args.n_psr // 9))
    models, toas_list = build_serve_fleet(
        sizes=tuple(args.sizes), per_combo=per_combo, seed=args.seed)
    models, toas_list = models[:args.n_psr], toas_list[:args.n_psr]
    # trace from construction on so the timeline covers the whole
    # cold path — host prep, pack, compile — not just the refit
    obs.enable(capacity=args.capacity,
               jax_annotations=args.jax_annotations)
    obs.reset()
    print(f"[pint_trace] fleet of {len(models)} pulsars; traced cold "
          "fit (host prep + pack + compile + execute) ...",
          file=sys.stderr)
    fleet = PTAFleet(models, toas_list, bucket_floor=args.bucket_floor,
                     pipeline=not args.no_pipeline)
    fleet.fit(method=args.method, maxiter=args.maxiter)
    print("[pint_trace] traced warm refit ...", file=sys.stderr)
    xs, chi2, meta = fleet.fit(method=args.method, maxiter=args.maxiter)
    obs.disable()

    spans = obs.spans()
    out = obs.write_chrome_trace(args.out, spans)
    phases = sorted({s["name"] for s in spans})
    print(json.dumps({
        "pulsars": len(models),
        "buckets": len(fleet.batches),
        "chi2_total": float(np.sum([np.sum(c) for c in chi2])),
        "spans": len(spans),
        "phases": phases,
        "trace_out": out,
    }, indent=1))
    return 0


def _cmd_convert(args):
    from . import export

    with open(args.dump) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "events" in doc:      # flight dump
        spans = export.flight_spans(doc)
    elif isinstance(doc, dict) and "traceEvents" in doc:
        print("input is already a Chrome trace", file=sys.stderr)
        return 1
    else:                                              # raw span list
        spans = doc
    out = export.write_chrome_trace(args.out, spans)
    print(json.dumps({"spans": len(spans), "trace_out": out}))
    return 0


def _cmd_prom(args):
    from . import metricsreg

    with open(args.snapshot) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "metrics" in doc:     # flight dump
        doc = doc["metrics"]
    sys.stdout.write(metricsreg.prometheus_text(doc))
    return 0


def _cmd_regress(args):
    from . import baseline

    report = baseline.run_regress(root=args.root,
                                  budgets_path=args.budgets)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print("regress: %s over %d rounds (latest %s)"
              % ("OK" if report["ok"] else "FAIL",
                 report["n_rounds"], report.get("latest")))
        for v in report.get("budget_violations", []):
            print("  BUDGET  %s" % v["detail"], file=sys.stderr)
        for r in report.get("regressions", []):
            print("  REGRESS %s" % r["detail"], file=sys.stderr)
        if report.get("error"):
            print("  ERROR   %s" % report["error"], file=sys.stderr)
        checked = report.get("checked", [])
        skipped = report.get("skipped", {})
        print("  checked: %s" % (", ".join(checked) or "(none)"))
        if skipped:
            print("  skipped: %s"
                  % ", ".join("%s [%s]" % kv
                              for kv in sorted(skipped.items())))
    return 0 if report["ok"] else 1


def _cmd_slo(args):
    from . import slo

    mon = slo.BurnRateMonitor(
        specs=slo.serve_slos(latency_limit_s=args.latency_limit))
    for i, path in enumerate(args.snapshots):
        with open(path) as fh:
            doc = json.load(fh)
        if isinstance(doc, dict) and "snapshot" in doc:
            doc = doc["snapshot"]
        t = doc.get("walltime") if isinstance(doc, dict) else None
        mon.ingest(doc, t=t if t is not None else float(i * args.step))
    out = {"slos": mon.snapshot(), "alerting": mon.alerting()}
    print(json.dumps(out, indent=1))
    return 1 if out["alerting"] else 0


def _cmd_fitq(args):
    from . import fitquality

    if args.snapshot:
        with open(args.snapshot) as fh:
            snap = json.load(fh)
    else:
        # no snapshot: run a probed fleet refit and report the live
        # ledger (the fitq twin of the `fleet` demo)
        from ..parallel import PTAFleet
        from ..scripts.pint_serve_bench import build_serve_fleet

        per_combo = max(1, -(-args.n_psr // 9))
        models, toas_list = build_serve_fleet(
            sizes=tuple(args.sizes), per_combo=per_combo,
            seed=args.seed)
        models, toas_list = models[:args.n_psr], toas_list[:args.n_psr]
        print(f"[pint_trace] probed fleet refit of {len(models)} "
              "pulsars ...", file=sys.stderr)
        fitquality.reset()
        fitquality.enable()
        try:
            fleet = PTAFleet(models, toas_list,
                             bucket_floor=args.bucket_floor)
            fleet.fit(method=args.method, maxiter=args.maxiter)
        finally:
            fitquality.disable()
        snap = fitquality.FITQ.snapshot()
    report = fitquality.check_report(
        snap, chi2_z_limit=args.chi2_z_limit,
        condition_limit=args.condition_limit)
    ledger = {k: v for k, v in fitquality._fq(snap).items()
              if k != "pulsars"}
    print(json.dumps({"report": report, "ledger": ledger}, indent=1,
                     default=float))
    return 0 if report["ok"] else 1


def _cmd_doctor(args):
    from . import baseline, fitquality, slo

    failures = []
    sections = {}
    regress = baseline.run_regress(root=args.root,
                                   budgets_path=args.budgets)
    sections["regress"] = regress
    if not regress["ok"]:
        failures.append("regress")
    if args.slo_snapshots:
        mon = slo.BurnRateMonitor(specs=slo.serve_slos())
        for i, path in enumerate(args.slo_snapshots):
            with open(path) as fh:
                doc = json.load(fh)
            if isinstance(doc, dict) and "snapshot" in doc:
                doc = doc["snapshot"]
            t = doc.get("walltime") if isinstance(doc, dict) else None
            mon.ingest(doc, t=t if t is not None else float(i * 60.0))
        alerting = mon.alerting()
        sections["slo"] = {"ok": not alerting, "alerting": alerting}
        if alerting:
            failures.append("slo")
    if args.fitq_snapshot:
        with open(args.fitq_snapshot) as fh:
            doc = json.load(fh)
        fitq = fitquality.check_report(doc)
        sections["fitq"] = fitq
        if not fitq["ok"]:
            failures.append("fitq")
    out = {"ok": not failures, "failures": failures,
           "sections": sections}
    if args.json:
        print(json.dumps(out, indent=1, default=float))
    else:
        print("doctor: %s" % ("OK" if out["ok"] else
                              "FAIL (%s)" % ", ".join(failures)))
        for name, sect in sections.items():
            ok = sect.get("ok", True)
            print("  %-8s %s" % (name, "ok" if ok else "FAIL"))
            for v in sect.get("violations", []):
                print("    FITQ    %s" % json.dumps(v),
                      file=sys.stderr)
            for v in sect.get("budget_violations", []):
                print("    BUDGET  %s" % v["detail"], file=sys.stderr)
            for r in sect.get("regressions", []):
                print("    REGRESS %s" % r["detail"], file=sys.stderr)
            for a in sect.get("alerting", []) or []:
                print("    SLO     %s alerting" % a, file=sys.stderr)
    return 0 if out["ok"] else 1


def _cmd_tail(args):
    from . import reqlife

    if args.artifact:
        with open(args.artifact) as fh:
            artifact = json.load(fh)
    else:
        # no artifact: run a small live serve stream and resolve its
        # own tail (the reqlife twin of the `fitq` live mode)
        from ..scripts.pint_serve_bench import run_serve_stream

        print("[pint_trace] live serve stream of %d requests ..."
              % args.n_requests, file=sys.stderr)
        rep = run_serve_stream(n_requests=args.n_requests,
                               sizes=tuple(args.sizes),
                               bucket_floor=args.bucket_floor,
                               seed=args.seed, compare_offline=False,
                               measure_overhead=False)
        artifact = rep["tail_artifact"]
    if args.trace:
        # resolve a specific trace id instead of the p99 exemplar
        recs = [r for r in artifact.get("lifecycle", [])
                if r.get("trace") == args.trace]
        if not recs:
            print(json.dumps({"resolved": False,
                              "reason": "trace_not_in_ledger",
                              "trace": args.trace}, indent=1))
            return 1
        split = reqlife.phase_split(recs[0])
        out = {"resolved": True, "trace": args.trace,
               "request_id": recs[0].get("request_id"),
               "tenant": recs[0].get("tenant"),
               "states": [s["state"] for s in recs[0]["states"]],
               "queue_wait_s": split["queue_wait_s"],
               "execute_s": split["execute_s"],
               "per_state_s": split["per_state_s"],
               "flush_trace": (recs[0].get("attrs") or {})
               .get("flush_trace"),
               "record": recs[0]}
    else:
        out = reqlife.resolve_tail(artifact)
    print(json.dumps(out, indent=1, default=float))
    return 0 if out.get("resolved") else 1


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m pint_tpu.obs",
        description="pint_trace: traced fleet timelines, flight-dump "
                    "conversion, Prometheus rendering")
    sub = p.add_subparsers(dest="cmd", required=True)

    f = sub.add_parser("fleet", help="traced fleet refit -> Chrome "
                                     "trace JSON")
    f.add_argument("--n-psr", type=int, default=68)
    f.add_argument("--sizes", type=int, nargs="+",
                   default=[48, 96, 180])
    f.add_argument("--seed", type=int, default=0)
    f.add_argument("--method", default="gls",
                   choices=("wls", "gls"))
    f.add_argument("--maxiter", type=int, default=2)
    f.add_argument("--bucket-floor", type=int, default=64)
    f.add_argument("--no-pipeline", action="store_true",
                   help="sequential fit (fewer phases in the trace)")
    f.add_argument("--capacity", type=int, default=65536)
    f.add_argument("--jax-annotations", action="store_true",
                   help="also emit jax.profiler TraceAnnotations")
    f.add_argument("--out", default="pint_fleet_trace.json")
    f.set_defaults(fn=_cmd_fleet)

    c = sub.add_parser("convert", help="flight dump / span list -> "
                                       "Chrome trace JSON")
    c.add_argument("dump")
    c.add_argument("--out", default="pint_trace.json")
    c.set_defaults(fn=_cmd_convert)

    m = sub.add_parser("prom", help="metrics snapshot -> Prometheus "
                                    "text format")
    m.add_argument("snapshot")
    m.set_defaults(fn=_cmd_prom)

    r = sub.add_parser("regress", help="bench-trajectory budget + "
                                       "regression gate (CI exit code)")
    r.add_argument("--root", default=None,
                   help="directory holding BENCH_r*.json "
                        "(default: cwd, else the repo root)")
    r.add_argument("--budgets", default=None,
                   help="budget spec path (default: the packaged "
                        "pint_tpu/obs/budgets.json)")
    r.add_argument("--json", action="store_true",
                   help="emit the full machine-readable report")
    r.set_defaults(fn=_cmd_regress)

    s = sub.add_parser("slo", help="replay serve snapshots through "
                                   "the burn-rate monitor")
    s.add_argument("snapshots", nargs="+",
                   help="serve snapshot JSON files, in time order")
    s.add_argument("--latency-limit", type=float, default=0.25,
                   help="p99 latency SLO limit in seconds")
    s.add_argument("--step", type=float, default=60.0,
                   help="assumed seconds between snapshots lacking a "
                        "walltime field")
    s.set_defaults(fn=_cmd_slo)

    q = sub.add_parser("fitq", help="fit-quality probe report / gate "
                                    "(numerics observatory)")
    q.add_argument("snapshot", nargs="?", default=None,
                   help="ledger or engine snapshot JSON; omitted -> "
                        "run a probed fleet refit")
    q.add_argument("--n-psr", type=int, default=27)
    q.add_argument("--sizes", type=int, nargs="+", default=[48])
    q.add_argument("--seed", type=int, default=0)
    q.add_argument("--method", default="gls", choices=("wls", "gls"))
    q.add_argument("--maxiter", type=int, default=2)
    q.add_argument("--bucket-floor", type=int, default=64)
    q.add_argument("--chi2-z-limit", type=float, default=6.0)
    q.add_argument("--condition-limit", type=float, default=1e12)
    q.set_defaults(fn=_cmd_fitq)

    d = sub.add_parser("doctor", help="regress + slo + fitq in one "
                                      "CI gate (exit !=0 on any "
                                      "violation)")
    d.add_argument("--root", default=None,
                   help="directory holding BENCH_r*.json")
    d.add_argument("--budgets", default=None,
                   help="budget spec path (default packaged)")
    d.add_argument("--slo-snapshots", nargs="*", default=None,
                   help="serve snapshot JSONs to replay through the "
                        "burn-rate monitor")
    d.add_argument("--fitq-snapshot", default=None,
                   help="fit-quality ledger / engine snapshot JSON "
                        "to gate")
    d.add_argument("--json", action="store_true",
                   help="emit the machine-readable report")
    d.set_defaults(fn=_cmd_doctor)

    t = sub.add_parser("tail", help="resolve a p99 tail exemplar to "
                                    "its request-lifecycle record")
    t.add_argument("artifact", nargs="?", default=None,
                   help="tail artifact JSON (pint_serve_bench "
                        "--tail-out); omitted -> run a live mini "
                        "serve stream")
    t.add_argument("--trace", default=None,
                   help="resolve this trace id instead of the p99 "
                        "exemplar")
    t.add_argument("--n-requests", type=int, default=48)
    t.add_argument("--sizes", type=int, nargs="+", default=[48])
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--bucket-floor", type=int, default=64)
    t.set_defaults(fn=_cmd_tail)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
