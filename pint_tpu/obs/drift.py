"""Drift sentinels: EWMA/CUSUM change detection over fit quality.

A fleet that refits the same pulsars continuously (the serve path)
produces per-pulsar time series — fitted parameters, uncertainties,
reduced chi2 — that should be *boring*. This module watches them:
an :class:`EWMA` tracks the running baseline (mean + variance) of
each series and a :class:`CUSUM` accumulates standardized deviations
so both sudden steps (a big one-shot z) and slow simmer (many small
same-signed z's) trip an alarm. Each alarm names the pulsar, the
probe, the baseline it drifted from, and the observed value; it
increments the fit-quality ledger's ``drift_alarms`` counter (the
``fitq_drift`` SLO numerator) and dumps a ``reason="fit_anomaly"``
flight record for the post-mortem.

Checkpoint semantics (pinned by tests/test_fitquality.py): a
:class:`DriftBoard` survives serve ``state_dict`` /
``load_state_dict`` round-trips by serializing the EWMA baselines
but deliberately NOT the CUSUM accumulators — a restart re-anchors
detection at the learned baselines with zeroed accumulators, so a
restore mid-simmer never replays half-accumulated evidence into a
spurious alarm storm. Detection of a *real* persisting drift simply
re-accumulates within ``~h/k`` rounds.
"""

from __future__ import annotations

import math
import threading

from . import recorder as obs_recorder


class EWMA:
    """Exponentially-weighted running mean/variance of one series.

    ``update(x)`` returns ``(z, ready)``: the standardized deviation
    of ``x`` against the *pre-update* baseline (None until ``min_n``
    warmup observations), then folds ``x`` in. The sigma carries a
    relative floor so a bitwise-constant series (successive refits of
    identical data) doesn't collapse to zero variance and alarm on
    the first ulp of float noise."""

    def __init__(self, alpha=0.2, min_n=8, rel_floor=1e-9):
        self.alpha = float(alpha)
        self.min_n = int(min_n)
        self.rel_floor = float(rel_floor)
        self.mean = None
        self.var = 0.0
        self.n = 0

    def sigma(self):
        if self.mean is None:
            return None
        return (math.sqrt(max(self.var, 0.0))
                + self.rel_floor * (abs(self.mean) + 1e-300))

    def update(self, x):
        x = float(x)
        z = None
        if self.n >= self.min_n:
            z = (x - self.mean) / self.sigma()
        if self.mean is None:
            self.mean = x
        else:
            delta = x - self.mean
            # West-style EW moments: variance first (it uses the old
            # mean's delta), then the mean
            self.var = (1.0 - self.alpha) * (self.var
                                             + self.alpha * delta * delta)
            self.mean += self.alpha * delta
        self.n += 1
        return z, z is not None


class CUSUM:
    """Two-sided standardized CUSUM: ``S+ = max(0, S+ + z - k)``,
    ``S- = max(0, S- - z - k)``; fires when either exceeds ``h``.
    ``k`` is the per-step drift allowance (in sigmas), ``h`` the
    accumulated-evidence threshold."""

    def __init__(self, k=0.5, h=6.0):
        self.k = float(k)
        self.h = float(h)
        self.pos = 0.0
        self.neg = 0.0

    def update(self, z):
        self.pos = max(0.0, self.pos + z - self.k)
        self.neg = max(0.0, self.neg - z - self.k)
        return self.pos > self.h or self.neg > self.h

    def reset(self):
        self.pos = 0.0
        self.neg = 0.0


class DriftSentinel:
    """One watched series: EWMA baseline + CUSUM accumulator + an
    immediate trip on a single huge step (``|z| >= z_trip``). On
    alarm the CUSUM resets (one alarm per episode, not one per
    round) while the EWMA keeps adapting toward the new level."""

    KIND = "DriftSentinel"
    VERSION = 1

    def __init__(self, alpha=0.2, min_n=8, k=0.5, h=6.0, z_trip=8.0):
        self.ewma = EWMA(alpha=alpha, min_n=min_n)
        self.cusum = CUSUM(k=k, h=h)
        self.z_trip = float(z_trip)
        self.alarms = 0

    def observe(self, x):
        """Feed one observation; returns an alarm dict or None."""
        baseline = self.ewma.mean
        z, ready = self.ewma.update(x)
        if not ready:
            return None
        fired = self.cusum.update(z) or abs(z) >= self.z_trip
        if not fired:
            return None
        self.alarms += 1
        alarm = {"baseline": baseline, "observed": float(x),
                 "z": round(z, 3), "cusum_pos": round(self.cusum.pos, 3),
                 "cusum_neg": round(self.cusum.neg, 3),
                 "n": self.ewma.n}
        self.cusum.reset()
        return alarm

    def state_dict(self):
        """Versioned state. The CUSUM accumulators are deliberately
        absent: restore re-anchors at the learned baseline with zero
        accumulated evidence (no post-restart alarm storm)."""
        return {"kind": self.KIND, "version": self.VERSION,
                "alpha": self.ewma.alpha, "min_n": self.ewma.min_n,
                "rel_floor": self.ewma.rel_floor,
                "mean": self.ewma.mean, "var": self.ewma.var,
                "n": self.ewma.n, "k": self.cusum.k, "h": self.cusum.h,
                "z_trip": self.z_trip, "alarms": self.alarms}

    def load_state_dict(self, state):
        if (state.get("kind") != self.KIND
                or state.get("version") != self.VERSION):
            raise ValueError(
                "not a %s v%d state: %r" % (
                    self.KIND, self.VERSION,
                    {k: state.get(k) for k in ("kind", "version")}))
        self.ewma = EWMA(alpha=state["alpha"], min_n=state["min_n"],
                         rel_floor=state.get("rel_floor", 1e-9))
        self.ewma.mean = state["mean"]
        self.ewma.var = float(state["var"])
        self.ewma.n = int(state["n"])
        self.cusum = CUSUM(k=state["k"], h=state["h"])
        self.z_trip = float(state["z_trip"])
        self.alarms = int(state.get("alarms", 0))


class DriftBoard:
    """Per-(pulsar, probe) drift sentinels over successive refits.

    ``observe(pulsar, values)`` feeds a dict of probe -> value for
    one refit and returns the alarms it raised; every alarm lands in
    the fit-quality ledger (``drift_alarms``) and — when a flight
    dump dir is configured — a ``fit_anomaly`` dump naming pulsar,
    probe, baseline, and observed value. Thread-safe; series count is
    capped so an unbounded pulsar stream cannot grow host memory
    without bound."""

    KIND = "DriftBoard"
    VERSION = 1

    def __init__(self, alpha=0.2, min_n=8, k=0.5, h=6.0, z_trip=8.0,
                 max_series=8192, ledger=None, recorder=None):
        self._kw = {"alpha": alpha, "min_n": min_n, "k": k, "h": h,
                    "z_trip": z_trip}
        self.max_series = int(max_series)
        self.ledger = ledger
        self.recorder = recorder
        self._lock = threading.Lock()
        self._sentinels = {}
        self.dropped_series = 0
        self.alarms = 0

    def _ledger(self):
        if self.ledger is not None:
            return self.ledger
        from . import fitquality

        return fitquality.FITQ

    def _recorder(self):
        return (obs_recorder.RECORDER if self.recorder is None
                else self.recorder)

    def observe(self, pulsar, values, **context):
        """One refit's probe values for one pulsar; returns the list
        of alarm dicts raised (usually empty). Non-finite / missing
        values are skipped — a diverged lane is the divergence
        probe's business, not a drift observation."""
        pulsar = str(pulsar)
        alarms = []
        with self._lock:
            for probe in sorted(values):
                val = values[probe]
                if val is None:
                    continue
                val = float(val)
                if not math.isfinite(val):
                    continue
                key = (pulsar, probe)
                sent = self._sentinels.get(key)
                if sent is None:
                    if len(self._sentinels) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    sent = DriftSentinel(**self._kw)
                    self._sentinels[key] = sent
                alarm = sent.observe(val)
                if alarm is not None:
                    alarm.update(pulsar=pulsar, probe=probe)
                    alarms.append(alarm)
                    self.alarms += 1
        for alarm in alarms:
            self._ledger().note_drift_alarm(alarm["pulsar"],
                                            alarm["probe"])
            self._recorder().dump("fit_anomaly", source="drift",
                                  **alarm, **context)
        return alarms

    def snapshot(self):
        with self._lock:
            return {"series": len(self._sentinels),
                    "alarms": self.alarms,
                    "dropped_series": self.dropped_series}

    def state_dict(self):
        """Versioned, JSON-safe state: every sentinel's EWMA baseline
        (keys flattened to "pulsar\\x1fprobe") — CUSUM evidence is
        intentionally not carried (see module docstring)."""
        with self._lock:
            return {"kind": self.KIND, "version": self.VERSION,
                    "kw": dict(self._kw),
                    "max_series": self.max_series,
                    "alarms": self.alarms,
                    "dropped_series": self.dropped_series,
                    "sentinels": {
                        "\x1f".join(key): s.state_dict()
                        for key, s in self._sentinels.items()}}

    def load_state_dict(self, state):
        if (state.get("kind") != self.KIND
                or state.get("version") != self.VERSION):
            raise ValueError(
                "not a %s v%d state: %r" % (
                    self.KIND, self.VERSION,
                    {k: state.get(k) for k in ("kind", "version")}))
        with self._lock:
            self._kw = dict(state.get("kw", self._kw))
            self.max_series = int(state.get("max_series",
                                            self.max_series))
            self.alarms = int(state.get("alarms", 0))
            self.dropped_series = int(state.get("dropped_series", 0))
            self._sentinels = {}
            for flat, sd in (state.get("sentinels") or {}).items():
                pulsar, _, probe = flat.partition("\x1f")
                sent = DriftSentinel(**self._kw)
                sent.load_state_dict(sd)
                self._sentinels[(pulsar, probe)] = sent


def fit_drift_values(x, sigma, reduced_chi2, names=None,
                     max_params=16):
    """The standard probe dict a serve refit feeds the board: fitted
    parameter values, their uncertainties, and the reduced chi2 —
    keyed ``param.<name>`` / ``sigma.<name>`` (index-keyed when no
    names are given), capped at ``max_params`` so a huge timing
    model doesn't explode the series count."""
    values = {"reduced_chi2": reduced_chi2}
    if x is not None:
        for j, xv in enumerate(list(x)[:max_params]):
            tag = (names[j] if names is not None and j < len(names)
                   else str(j))
            values["param.%s" % tag] = xv
            if sigma is not None and j < len(sigma):
                values["sigma.%s" % tag] = sigma[j]
    return values
