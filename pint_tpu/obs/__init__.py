"""pint_tpu.obs: unified tracing spans, metrics, and flight recorder.

One observability surface for the whole stack (ISSUE 7): the fleet
pipeline, mesh lanes, serve flush path, AOT compile split, and
retry/bisect ladder all emit :func:`span`\\ s; counters and latency
histograms aggregate in :data:`metricsreg.REGISTRY`; the
:data:`recorder.RECORDER` flight recorder keeps a bounded ring of
recent spans + fault firings and dumps it to JSON on DeviceLost /
CollectiveTimeout / breaker-trip / checkpoint-restart.

Quick start::

    from pint_tpu import obs

    obs.enable()                       # spans on (off by default)
    xs, chi2, meta = fleet.fit()
    obs.write_chrome_trace("fleet.json")   # -> ui.perfetto.dev

Tracing is off by default and a disabled ``span(...)`` call is one
attribute check — the instrumented hot paths cost effectively nothing
until tracing is enabled, and enabling it never touches device code
(traced fits stay bitwise identical; tests/test_obs.py pins both).

CLI: ``python -m pint_tpu.obs`` (traced fleet demo, flight-dump ->
Perfetto conversion, Prometheus rendering).
"""

from . import clock  # noqa: F401
from .trace import (  # noqa: F401
    NOOP_SPAN,
    TRACER,
    Span,
    Tracer,
    current_trace_id,
    disable,
    enable,
    enabled,
    reset,
    span,
    spans,
)
from .metricsreg import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    percentile,
    prometheus_text,
    summary,
)
from .recorder import RECORDER, FlightRecorder, configure  # noqa: F401
from .export import (  # noqa: F401
    chrome_trace,
    flight_spans,
    write_chrome_trace,
)

__all__ = [
    "NOOP_SPAN", "RECORDER", "REGISTRY", "TRACER", "Counter",
    "FlightRecorder", "Gauge", "Histogram", "Registry", "Span",
    "Tracer", "chrome_trace", "clock", "configure",
    "current_trace_id", "disable", "enable", "enabled",
    "flight_spans", "percentile", "prometheus_text", "reset", "span",
    "spans", "summary", "write_chrome_trace",
]
