"""pint_tpu.obs: unified tracing spans, metrics, and flight recorder.

One observability surface for the whole stack (ISSUE 7): the fleet
pipeline, mesh lanes, serve flush path, AOT compile split, and
retry/bisect ladder all emit :func:`span`\\ s; counters and latency
histograms aggregate in :data:`metricsreg.REGISTRY`; the
:data:`recorder.RECORDER` flight recorder keeps a bounded ring of
recent spans + fault firings and dumps it to JSON on DeviceLost /
CollectiveTimeout / breaker-trip / checkpoint-restart.

Quick start::

    from pint_tpu import obs

    obs.enable()                       # spans on (off by default)
    xs, chi2, meta = fleet.fit()
    obs.write_chrome_trace("fleet.json")   # -> ui.perfetto.dev

Tracing is off by default and a disabled ``span(...)`` call is one
attribute check — the instrumented hot paths cost effectively nothing
until tracing is enabled, and enabling it never touches device code
(traced fits stay bitwise identical; tests/test_obs.py pins both).

On top of the raw telemetry sits the perf observatory (ISSUE 8):
:mod:`costmodel` captures per-executable XLA cost/memory analysis at
the AOT compile split and attributes roofline MFU per program,
:mod:`baseline` gates the BENCH_r0*.json trajectory against the
machine-readable ``budgets.json``, and :mod:`slo` runs dual-window
burn-rate alerts over serve telemetry.

The request-lifecycle observatory (ISSUE 12) joins the two planes:
:mod:`reqlife` tracks every serve request through its state machine
(submitted -> queued -> packed -> executing -> delivered | shed |
rejected | error) keyed by the same trace ids the ``serve.*`` spans
carry, tail-latency exemplars on the serve histograms point back into
that ledger, and per-tenant accounting rides the registry's label
families behind a hard cardinality cap.

CLI: ``python -m pint_tpu.obs`` (traced fleet demo, flight-dump ->
Perfetto conversion, Prometheus rendering, the ``regress`` perf gate,
offline ``slo`` replay, and ``tail`` p99-exemplar resolution).
"""

from . import baseline  # noqa: F401
from . import clock  # noqa: F401
from . import costmodel  # noqa: F401
from . import drift  # noqa: F401
from . import fitquality  # noqa: F401
from . import reqlife  # noqa: F401
from . import slo  # noqa: F401
from .trace import (  # noqa: F401
    NOOP_SPAN,
    TRACER,
    Span,
    Tracer,
    current_trace_id,
    disable,
    enable,
    enabled,
    reset,
    span,
    spans,
)
from .metricsreg import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    percentile,
    prometheus_text,
    summary,
)
from .recorder import RECORDER, FlightRecorder, configure  # noqa: F401
from .costmodel import (  # noqa: F401
    LEDGER,
    ProgramLedger,
    attribute,
    device_spec,
    executable_cost,
    mfu_pct,
)
from .slo import (  # noqa: F401
    BurnRateMonitor,
    SLOSpec,
    serve_slos,
    tenant_slos,
)
from .reqlife import (  # noqa: F401
    REQLIFE,
    LifecycleLedger,
    phase_split,
    resolve_tail,
    tail_artifact,
)
from .drift import CUSUM, EWMA, DriftBoard, DriftSentinel  # noqa: F401
from .fitquality import (  # noqa: F401
    FITQ,
    FitQualityLedger,
    fit_quality_slos,
)
from .export import (  # noqa: F401
    chrome_trace,
    flight_spans,
    reqlife_spans,
    write_chrome_trace,
)

__all__ = [
    "BurnRateMonitor", "CUSUM", "Counter", "DriftBoard",
    "DriftSentinel", "EWMA", "FITQ", "FitQualityLedger",
    "FlightRecorder", "Gauge", "Histogram", "LEDGER",
    "LifecycleLedger", "NOOP_SPAN", "ProgramLedger", "RECORDER",
    "REGISTRY", "REQLIFE", "Registry", "SLOSpec", "Span", "TRACER",
    "Tracer", "attribute", "baseline", "chrome_trace", "clock",
    "configure", "costmodel", "current_trace_id", "device_spec",
    "disable", "drift", "enable", "enabled", "executable_cost",
    "fit_quality_slos", "fitquality", "flight_spans", "mfu_pct",
    "percentile", "phase_split", "prometheus_text", "reqlife",
    "reqlife_spans", "reset", "resolve_tail", "serve_slos", "slo",
    "span", "spans", "summary", "tail_artifact", "tenant_slos",
    "write_chrome_trace",
]
