"""Per-request lifecycle ledger: the request plane of the observatory.

Every ``TimingRequest`` gets a trace id minted at ``ServeEngine.submit``
that rides the batcher slot into the flush span. The ledger records the
full state machine

    submitted -> queued -> packed -> executing ->
        delivered | shed(queue_full/deadline) |
        rejected(circuit_open/...) | error

with per-transition timestamps on the obs clock, so queue-wait vs
service-time decomposition exists PER REQUEST — joinable to the
``serve.*`` spans (via the flush trace id recorded on delivery) and to
the flight recorder's dumps. Recovery replays append two extra states:
``replayed_committed`` (journal returned the committed result, terminal)
and ``re_executed`` (uncommitted intake re-submitted live, non-terminal
— the normal machine then runs it to a terminal state).

The ledger is bounded (FIFO eviction at ``capacity``) and thread-safe;
evicting a record that never reached a terminal state increments
``lost_records``, which obs/budgets.json pins at 0 — bounded memory
must never silently drop in-flight accounting. All bookkeeping is
host-side dict work: instrumented serve runs stay bitwise identical to
uninstrumented ones (tests/test_reqlife.py digest-asserts this).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from . import clock as obs_clock
from . import trace as obs_trace

TERMINAL_STATES = frozenset({
    "delivered", "shed", "rejected", "error", "replayed_committed",
})

#: States a healthy request passes through, in order (docs + tail
#: resolution use this to compute the queue-wait vs execute split).
HAPPY_PATH = ("submitted", "queued", "packed", "executing", "delivered")


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return int(default)


class LifecycleLedger:
    """Bounded, thread-safe per-request state-machine recorder.

    One record per request id::

        {"request_id", "tenant", "kind", "trace",
         "states": [{"state", "t", "reason"?}, ...],
         "state": <latest>, "terminal": bool, "attrs": {...}}

    ``attrs`` carries delivery-time joins (flush_trace, queue_wait_s,
    execute_s, slot bucket). Timestamps default to the obs clock but
    callers holding a deterministic test clock pass ``t=`` explicitly.
    """

    TERMINAL_STATES = TERMINAL_STATES

    def __init__(self, capacity=None, clock=None):
        self._lock = threading.Lock()
        self._capacity = max(1, int(
            capacity if capacity is not None
            else _env_int("PINT_TPU_REQLIFE_CAP", 8192)))
        self._records = OrderedDict()  # request_id -> record dict
        self._by_trace = {}  # trace id -> request_id
        self._counters = {"submitted": 0, "terminal": 0,
                          "lost_records": 0, "double_terminal": 0,
                          "unknown_request": 0}
        self.clock = clock if clock is not None else obs_clock.now

    @property
    def capacity(self):
        return self._capacity

    def submitted(self, request_id, tenant="anon", kind=None, t=None):
        """Open (or re-anchor, on recovery re-submit) a record; returns
        the request's trace id. Trace ids come from the obs tracer's
        counter so they join the span namespace even when tracing is
        disabled."""
        t = self.clock() if t is None else t
        with self._lock:
            rec = self._records.get(request_id)
            if rec is not None:
                # recovery re-submit: same id rides back through
                # submit(); keep the trace, re-open the machine
                self._records.move_to_end(request_id)
                rec["states"].append({"state": "submitted", "t": t})
                rec["state"] = "submitted"
                rec["terminal"] = False
                return rec["trace"]
            trace = obs_trace.TRACER.new_trace_id()
            rec = {"request_id": request_id,
                   "tenant": str(tenant) if tenant else "anon",
                   "kind": kind, "trace": trace,
                   "states": [{"state": "submitted", "t": t}],
                   "state": "submitted", "terminal": False,
                   "attrs": {}}
            self._records[request_id] = rec
            self._by_trace[trace] = request_id
            self._counters["submitted"] += 1
            self._evict_locked()
            return trace

    def transition(self, request_id, state, t=None, reason=None,
                   **attrs):
        """Append one state transition; returns the trace id (None for
        an unknown request — evicted or never submitted). A second
        terminal transition is refused and counted (exactly-one-
        terminal-state is an acceptance criterion, not a hope)."""
        t = self.clock() if t is None else t
        with self._lock:
            rec = self._records.get(request_id)
            if rec is None:
                self._counters["unknown_request"] += 1
                return None
            if rec["terminal"] and state in TERMINAL_STATES:
                self._counters["double_terminal"] += 1
                return rec["trace"]
            entry = {"state": state, "t": t}
            if reason is not None:
                entry["reason"] = reason
            rec["states"].append(entry)
            rec["state"] = state
            if state in TERMINAL_STATES:
                rec["terminal"] = True
                self._counters["terminal"] += 1
            if attrs:
                rec["attrs"].update(attrs)
            return rec["trace"]

    def _evict_locked(self):
        while len(self._records) > self._capacity:
            _, old = self._records.popitem(last=False)
            self._by_trace.pop(old["trace"], None)
            if not old["terminal"]:
                self._counters["lost_records"] += 1

    def record(self, request_id):
        """JSON-safe copy of one record (None if unknown)."""
        with self._lock:
            rec = self._records.get(request_id)
            return _copy_record(rec) if rec is not None else None

    def by_trace(self, trace):
        """Resolve a trace id back to its record (None if unknown)."""
        with self._lock:
            rid = self._by_trace.get(trace)
            if rid is None:
                return None
            return _copy_record(self._records[rid])

    def trace_of(self, request_id):
        with self._lock:
            rec = self._records.get(request_id)
            return rec["trace"] if rec is not None else None

    def nonterminal_ids(self):
        """Request ids still in a non-terminal state — must be empty
        after drain/recovery (kill-chaos asserts this)."""
        with self._lock:
            return [rid for rid, rec in self._records.items()
                    if not rec["terminal"]]

    def snapshot(self, tenant_cap=None):
        """Aggregate census: counts by state and by tenant (behind the
        same hard cardinality cap the metrics registry enforces — the
        tail folds into ``other``), plus the loss/double-terminal
        counters the budgets gate."""
        cap = max(1, int(tenant_cap if tenant_cap is not None
                         else _env_int("PINT_TPU_TENANT_CAP", 32)))
        with self._lock:
            by_state = {}
            by_tenant = {}
            non_terminal = 0
            for rec in self._records.values():
                by_state[rec["state"]] = by_state.get(rec["state"], 0) + 1
                by_tenant[rec["tenant"]] = by_tenant.get(
                    rec["tenant"], 0) + 1
                if not rec["terminal"]:
                    non_terminal += 1
            counters = dict(self._counters)
            resident = len(self._records)
        if len(by_tenant) > cap:
            kept = sorted(by_tenant.items(),
                          key=lambda kv: (-kv[1], kv[0]))[:cap]
            other = sum(by_tenant.values()) - sum(v for _, v in kept)
            by_tenant = dict(kept)
            by_tenant["other"] = by_tenant.get("other", 0) + other
        return {"records": counters["submitted"],
                "resident": resident,
                "capacity": self._capacity,
                "non_terminal": non_terminal,
                "lost_records": counters["lost_records"],
                "double_terminal": counters["double_terminal"],
                "unknown_request": counters["unknown_request"],
                "terminal": counters["terminal"],
                "by_state": dict(sorted(by_state.items())),
                "by_tenant": dict(sorted(by_tenant.items()))}

    def export(self):
        """All resident records, JSON-safe (the ``--tail-out`` artifact
        and the chrome-trace converter consume this)."""
        with self._lock:
            return [_copy_record(rec)
                    for rec in self._records.values()]

    def __len__(self):
        with self._lock:
            return len(self._records)

    def reset(self):
        with self._lock:
            self._records = OrderedDict()
            self._by_trace = {}
            self._counters = {"submitted": 0, "terminal": 0,
                              "lost_records": 0, "double_terminal": 0,
                              "unknown_request": 0}


def _copy_record(rec):
    out = dict(rec)
    out["states"] = [dict(s) for s in rec["states"]]
    out["attrs"] = dict(rec["attrs"])
    return out


def phase_split(record):
    """Queue-wait vs service-time decomposition from one record's
    transition timestamps: time between consecutive states, plus the
    two headline aggregates (queue_wait_s = submitted -> executing,
    execute_s = executing -> terminal)."""
    states = record.get("states") or []
    per_state = {}
    t_sub = t_exec = t_term = None
    for prev, nxt in zip(states, states[1:]):
        key = prev["state"]
        per_state[key] = per_state.get(key, 0.0) \
            + (nxt["t"] - prev["t"])
    for s in states:
        if s["state"] == "submitted" and t_sub is None:
            t_sub = s["t"]
        if s["state"] == "executing":
            t_exec = s["t"]
        if s["state"] in TERMINAL_STATES:
            t_term = s["t"]
    queue_wait = (t_exec - t_sub) if (t_sub is not None
                                      and t_exec is not None) else None
    execute = (t_term - t_exec) if (t_exec is not None
                                    and t_term is not None) else None
    return {"per_state_s": per_state, "queue_wait_s": queue_wait,
            "execute_s": execute}


def tail_artifact(telemetry_snapshot, ledger):
    """Bundle everything ``resolve_tail`` needs into one JSON-safe
    dict: the serve snapshot's p99 + exemplars and the ledger's
    records. pint_serve_bench writes this via ``--tail-out``."""
    total = telemetry_snapshot.get("total_s") or {}
    return {"p99_s": total.get("p99"),
            "exemplars": telemetry_snapshot.get("exemplars") or [],
            "tenants": telemetry_snapshot.get("tenants") or {},
            "lifecycle": ledger.export()}


def resolve_tail(artifact):
    """Answer "why was this request slow" from a tail artifact: pick
    the exemplar nearest ABOVE the p99 (falling back to the max-latency
    exemplar), join it to its lifecycle record by trace/request id, and
    return the record with its queue-wait vs execute split and the
    flush trace id the delivery rode in on."""
    exemplars = sorted(artifact.get("exemplars") or [],
                       key=lambda e: e.get("value") or 0.0)
    if not exemplars:
        return {"resolved": False, "reason": "no_exemplars"}
    p99 = artifact.get("p99_s")
    pick = exemplars[-1]
    if p99 is not None:
        above = [e for e in exemplars if (e.get("value") or 0.0) >= p99]
        if above:
            pick = above[0]
    records = artifact.get("lifecycle") or []
    by_id = {r.get("request_id"): r for r in records}
    by_tr = {r.get("trace"): r for r in records}
    rec = by_id.get(pick.get("request_id")) or by_tr.get(pick.get("trace"))
    if rec is None:
        return {"resolved": False, "reason": "exemplar_not_in_ledger",
                "exemplar": pick}
    split = phase_split(rec)
    return {"resolved": True,
            "exemplar": pick,
            "p99_s": p99,
            "trace": rec.get("trace"),
            "request_id": rec.get("request_id"),
            "tenant": rec.get("tenant"),
            "states": [s["state"] for s in rec.get("states") or []],
            "queue_wait_s": split["queue_wait_s"],
            "execute_s": split["execute_s"],
            "per_state_s": split["per_state_s"],
            "flush_trace": (rec.get("attrs") or {}).get("flush_trace"),
            "record": rec}


#: Process-wide ledger the serve engine records into by default
#: (costmodel already owns the name LEDGER in the obs namespace).
REQLIFE = LifecycleLedger()
